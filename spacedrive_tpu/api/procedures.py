"""Every API procedure, namespace by namespace.

Parity target: the reference's rspc procedure inventory (SURVEY.md §2.1
"rspc API"; names enumerated from /root/reference/core/src/api/*.rs —
`keys.` is commented out there and therefore omitted here; `p2p.` mounts
from the p2p module when it lands). Net-new additions beyond the
reference: `search.duplicates` / `search.nearDuplicates` /
`jobs.nearDupDetector` exposing the device dedup analytics.
"""

from __future__ import annotations

import asyncio
import os
import time
import uuid as uuidlib
from typing import Any, Dict, List, Optional

from .. import backups as backups_mod
from .. import tasks
from .. import telemetry
from .. import tracing
from ..jobs.report import JobStatus
from ..library import Library
from ..locations import manager as loc_manager
from ..locations.file_path_helper import materialized_like
from ..locations.non_indexed import walk_ephemeral
from ..locations.paths import IsolatedPath
from ..locations.rules import IndexerRule, RuleKind, RulePerKind
from ..media.exif import extract_media_data
from ..store.db import uuid_bytes
from ..volume import get_volumes
from .router import Router, RpcError
from .serialization import file_path_display, row_to_dict, rows_to_dicts

BUILD_VERSION = "0.1.0"


def _json_safe(v: Any) -> Any:
    """Make an arbitrary extraction structure JSON-encodable: hex bytes
    at any depth, recurse containers, stringify anything else non-JSON
    (e.g. EXIF IFDRational)."""
    if v is None or isinstance(v, (str, int, float, bool)):
        return v
    if isinstance(v, (bytes, bytearray)):
        return bytes(v).hex()
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple, set)):
        return [_json_safe(x) for x in v]
    return str(v)


def register_all(router: Router) -> None:
    _core(router)
    _fleet(router)
    _incidents(router)
    _libraries(router)
    _volumes(router)
    _tags(router)
    _labels(router)
    _spaces(router)
    _albums(router)
    _categories(router)
    _locations(router)
    _files(router)
    _jobs(router)
    _search(router)
    _sync(router)
    _preferences(router)
    _notifications(router)
    _nodes(router)
    _auth(router)
    _backups(router)
    _p2p(router)
    _keys(router)
    _invalidation(router)


# -- unscoped core (api/mod.rs buildInfo/nodeState/toggleFeatureFlag) ------

def _core(r: Router) -> None:
    @r.query("buildInfo")
    def build_info(node, _input):
        return {"version": BUILD_VERSION, "commit": "unknown"}

    @r.query("nodeState")
    def node_state(node, _input):
        return {
            "id": node.config.id.hex(),
            "name": node.config.name,
            "data_path": node.data_dir,
            "features": node.config.features,
        }

    @r.mutation("toggleFeatureFlag")
    def toggle_feature(node, input):
        return node.config.toggle_feature(str(input["feature"]))

    @r.query("node.metrics")
    def node_metrics(node, _input):
        """The node-wide telemetry registry as one JSON-safe snapshot —
        the rspc face of GET /metrics (same counters, same instant)."""
        return telemetry.snapshot()

    @r.query("node.spans")
    def node_spans(node, input):
        """Recent finished spans from the tracing ring buffer, newest
        last; optional {limit, trace} filters."""
        input = input or {}
        return tracing.recent_spans(
            limit=int(input.get("limit", 100)),
            trace_id=input.get("trace"))

    @r.query("node.trace.export")
    async def node_trace_export(node, input):
        """The flight-recorder export: span ring + pipeline timeline
        as one schema-valid Chrome-trace/Perfetto JSON document
        (spacedrive_tpu/flight.py). Open it in chrome://tracing or
        ui.perfetto.dev; `python -m tools.trace_export --url ...`
        pulls and validates it from a live node. Built off-loop: a
        full ring is thousands of events to copy/sort, and the export
        is pulled exactly when the node is busy."""
        from .. import flight

        del input
        return await asyncio.to_thread(flight.chrome_trace,
                                       node_name=node.config.name)

    @r.query("node.health")
    def node_health(node, _input):
        """The health observatory's latest snapshot (spacedrive_tpu/
        health.py): per-subsystem ok|degraded|saturated states with
        bottleneck attribution — the top-k declared resources driving
        each non-ok state, evidence series inline. Served from the
        periodic sampler's cache; computes a fresh sample when the
        sampler hasn't run within ~2 intervals (loop-less embedders,
        sync tests)."""
        return node.health.snapshot()

    @r.subscription("node.health")
    def node_health_sub(node, _input, emit):
        """Push every HealthSnapshot the sampler emits (plus one
        immediately, so subscribers paint without waiting an
        interval). The ws pump coalesces these newest-wins — a
        stalled operator top only ever misses stale states."""
        def on_event(e):
            if e.get("type") == "HealthSnapshot":
                emit(e)
        unsub = node.events.subscribe(on_event)
        # AFTER subscribing, same ordering contract as node.telemetry.
        node.health.emit_snapshot()
        return unsub

    @r.subscription("node.telemetry")
    def node_telemetry(node, _input, emit):
        """Relay the TelemetryReporter's periodic TelemetrySnapshot
        events (plus one immediately, so subscribers paint without
        waiting an interval)."""
        def on_event(e):
            if e.get("type") == "TelemetrySnapshot":
                emit(e)
        unsub = node.events.subscribe(on_event)
        # AFTER subscribing: emit fans out synchronously to the current
        # subscriber list, so the other order would skip this client.
        node.telemetry_reporter.emit_snapshot()
        return unsub


# -- obs. + fleet. (fleet observatory, spacedrive_tpu/fleet.py) -------------

def _fleet(r: Router) -> None:
    """The observability-federation surfaces. The obs.* queries are
    the rspc face of the p2p obs protocol (one serve_obs dispatch for
    every transport — p2p tunnels, HTTP fleets, loopback tests); the
    fleet.* queries serve the merged view the poller maintains."""

    def _serve(node, header):
        from ..p2p.obs import serve_obs

        return asyncio.to_thread(serve_obs, node, header)

    @r.query("obs.metrics")
    async def obs_metrics(node, _input):
        """This node's telemetry snapshot in the obs envelope (node
        identity + sampled-at wall clock) — what a fleet poller over
        HTTP consumes; same payload the p2p obs.metrics handler
        serves."""
        return await _serve(node, {"t": "obs.metrics"})

    @r.query("obs.health")
    async def obs_health(node, _input):
        """This node's HealthSnapshot in the obs envelope — the fleet
        poller's per-round pull."""
        return await _serve(node, {"t": "obs.health"})

    @r.query("obs.trace")
    async def obs_trace(node, input):
        """This node's span-ring + flight-timeline slice, filterable
        by {trace} and capped by {limit} — the raw material of
        distributed trace assembly."""
        input = input or {}
        header: Dict[str, Any] = {"t": "obs.trace"}
        if input.get("trace"):
            header["trace"] = str(input["trace"])
        if input.get("limit") is not None:
            header["limit"] = input["limit"]
        return await _serve(node, header)

    @r.query("obs.incidents")
    async def obs_incidents(node, input):
        """This node's incident bundle HEADERS in the obs envelope,
        newest-first, capped by {limit} — what the fleet poller
        digests into per-row incident columns. Full bundles never
        ride this route; incidents.get serves them locally."""
        header: Dict[str, Any] = {"t": "obs.incidents"}
        if (input or {}).get("limit") is not None:
            header["limit"] = input["limit"]
        return await _serve(node, header)

    @r.query("fleet.health")
    async def fleet_health(node, _input):
        """The merged fleet health view (fleet.py): one row per node
        — the local one plus every polled peer — with states and
        attribution re-keyed per (node, subsystem), unreachable/stale
        peers degraded with last-seen evidence. Served from the
        poller's cache; polls fresh when stale (loop-less embedders,
        no-poller tests)."""
        return await node.fleet.snapshot()

    @r.query("fleet.metrics")
    async def fleet_metrics(node, _input):
        """Per-node cumulative metrics snapshots (local registry +
        every reachable peer's obs.metrics, fetched on demand)."""
        return await node.fleet.metrics()

    @r.query("fleet.trace.export")
    async def fleet_trace_export(node, input):
        """Distributed trace assembly: every paired peer's spans +
        timeline for {trace}, merged with the local slice into one
        validated Chrome-trace document with per-node pid lanes and
        skew-aligned clocks."""
        input = input or {}
        trace = input.get("trace")
        if not trace:
            raise RpcError("BAD_REQUEST",
                           "fleet.trace.export needs {trace: <hex id>}")
        return await node.fleet.assemble_trace(str(trace))

    @r.subscription("fleet.health")
    async def fleet_health_sub(node, _input, emit):
        """Push every FleetHealthSnapshot the poller publishes (plus
        one immediately so subscribers paint without waiting a poll
        round). The ws pump coalesces these newest-wins, same as
        node.health."""
        def on_event(e):
            if e.get("type") == "FleetHealthSnapshot":
                emit(e)
        unsub = node.events.subscribe(on_event)
        # AFTER subscribing (the EventBus fans out synchronously to
        # the current list); built fresh if the poller has no view.
        view = await node.fleet.snapshot()
        emit({"type": "FleetHealthSnapshot", "ts": view["ts"],
              "fleet": view})
        return unsub


# -- incidents. (incident observatory, spacedrive_tpu/incidents.py) ---------

def _incidents(r: Router) -> None:
    """The postmortem-triage surface: list bundle headers, pull one
    full bundle, acknowledge it (drains the sd_incident_open backlog),
    and stream new incidents as they freeze. All four degrade cleanly
    when SDTPU_INCIDENTS=off (empty list / NOT_FOUND / stream of
    nothing)."""

    def _obs(node):
        from .. import incidents

        return getattr(node, "incidents", None) or incidents.current()

    @r.query("incidents.list")
    def incidents_list(node, input):
        """Bundle headers newest-first, optional {limit}."""
        obs = _obs(node)
        if obs is None:
            return []
        return obs.list(limit=int((input or {}).get("limit", 0)))

    @r.query("incidents.get")
    def incidents_get(node, input):
        """One full evidence bundle by {id} (disk-authoritative)."""
        obs = _obs(node)
        bundle = obs.get(str((input or {}).get("id", ""))) \
            if obs is not None else None
        if bundle is None:
            raise RpcError("NOT_FOUND", "no such incident bundle")
        return bundle

    @r.mutation("incidents.ack")
    def incidents_ack(node, input):
        """Mark a bundle triaged: {id} → {acked: bool}."""
        obs = _obs(node)
        acked = obs.ack(str((input or {}).get("id", ""))) \
            if obs is not None else False
        return {"acked": acked}

    @r.subscription("incidents")
    def incidents_sub(node, _input, emit):
        """Push each Incident event (the new bundle's header) as the
        observatory freezes it — the operator-console live feed. No
        initial emit: incidents.list is the paint-in query, and an
        empty store should paint empty."""
        def on_event(e):
            if e.get("type") == "Incident":
                emit(e)
        return node.events.subscribe(on_event)


# -- library. (api/libraries.rs) -------------------------------------------

def _libraries(r: Router) -> None:
    def _lib_info(lib: Library) -> Dict[str, Any]:
        return {"uuid": str(lib.id), "config": lib.config.to_json()}

    @r.query("library.list")
    def lib_list(node, _input):
        return [_lib_info(lib) for lib in node.libraries.list()]

    @r.mutation("library.create", invalidates=["library.list"])
    def lib_create(node, input):
        lib = node.create_library(str(input["name"]))
        return _lib_info(lib)

    @r.mutation("library.edit", invalidates=["library.list"])
    def lib_edit(node, input):
        lib = node.libraries.edit(
            uuidlib.UUID(str(input["id"])),
            name=input.get("name"), description=input.get("description"))
        return _lib_info(lib)

    @r.mutation("library.delete", invalidates=["library.list"])
    def lib_delete(node, input):
        node.libraries.delete(uuidlib.UUID(str(input["id"])))
        return None

    @r.query("library.statistics", library=True)
    def lib_statistics(node, library, _input):
        return library.statistics()


# -- volumes. --------------------------------------------------------------

def _volumes(r: Router) -> None:
    @r.query("volumes.list")
    def volumes_list(node, _input):
        return get_volumes()


# -- tags. (api/tags.rs) ---------------------------------------------------

def _tags(r: Router) -> None:
    @r.query("tags.list", library=True)
    def tags_list(node, library, _input):
        return rows_to_dicts(library.db.run("api.tag.all"))

    @r.query("tags.get", library=True)
    def tags_get(node, library, input):
        row = library.db.run("api.tag.by_id", (int(input["id"]),))
        return row_to_dict(row) if row else None

    @r.query("tags.getForObject", library=True)
    def tags_for_object(node, library, input):
        return rows_to_dicts(library.db.run(
            "api.tag.for_object", (int(input["object_id"]),)))

    @r.query("tags.getWithObjects", library=True)
    def tags_with_objects(node, library, input):
        tags = rows_to_dicts(library.db.run("api.tag.all"))
        for t in tags:
            t["object_ids"] = [
                row["object_id"] for row in library.db.run(
                    "api.tag.object_ids", (t["id"],))
            ]
        return tags

    @r.mutation("tags.create", library=True, invalidates=["tags.list"])
    def tags_create(node, library, input):
        pub_id = uuid_bytes()
        sync = library.sync
        values = {"name": str(input["name"]),
                  "color": input.get("color"),
                  "date_created": int(time.time())}
        with sync.write_ops(
                sync.shared_create("tag", pub_id, values)) as conn:
            tag_id = library.db.insert(
                "tag", {"pub_id": pub_id, **values}, conn=conn)
        return {"id": tag_id, "pub_id": pub_id.hex(), **values}

    @r.mutation("tags.update", library=True, invalidates=["tags.list"])
    def tags_update(node, library, input):
        tag = library.db.run("api.tag.by_id", (int(input["id"]),))
        if tag is None:
            raise RpcError("NOT_FOUND", "no such tag")
        sync = library.sync
        values = {k: input[k] for k in ("name", "color") if k in input}
        ops = [sync.shared_update("tag", tag["pub_id"], k, v)
               for k, v in values.items()]
        with sync.write_ops(ops) as conn:
            library.db.update("tag", tag["id"], values, conn=conn)
        return None

    @r.mutation("tags.delete", library=True, invalidates=["tags.list"])
    def tags_delete(node, library, input):
        tag = library.db.run("api.tag.by_id", (int(input["id"]),))
        if tag is None:
            return None
        sync = library.sync
        # relation deletes FIRST (earlier HLC stamps): a peer holding
        # assignments must clear them before the row delete or its
        # FK constraint rejects the op forever (sync divergence).
        assigned = library.db.run("api.tag.assigned_objects",
                                  (tag["id"],))
        ops = [sync.relation_delete("tag_on_object", r["opub"],
                                    tag["pub_id"]) for r in assigned]
        ops.append(sync.shared_delete("tag", tag["pub_id"]))
        with sync.write_ops(ops) as conn:
            library.db.run("api.tag.clear_assignments", (tag["id"],),
                           conn=conn)
            library.db.delete("tag", tag["id"], conn=conn)
        return None

    @r.mutation("tags.assign", library=True,
                invalidates=["tags.getForObject"])
    def tags_assign(node, library, input):
        tag = library.db.run("api.tag.by_id", (int(input["tag_id"]),))
        obj = library.db.run("api.object.by_id",
                             (int(input["object_id"]),))
        if tag is None or obj is None:
            raise RpcError("NOT_FOUND", "tag or object missing")
        sync = library.sync
        if input.get("unassign"):
            ops = [sync.relation_delete(
                "tag_on_object", obj["pub_id"], tag["pub_id"])]
            with sync.write_ops(ops) as conn:
                library.db.run("api.tag.unassign",
                               (tag["id"], obj["id"]), conn=conn)
        else:
            ops = sync.relation_create(
                "tag_on_object", obj["pub_id"], tag["pub_id"])
            with sync.write_ops(ops) as conn:
                library.db.run("api.tag.assign",
                               (tag["id"], obj["id"]), conn=conn)
        return None


# -- labels. (schema.prisma:362-385 Label/LabelOnObject — the model the
#    reference ships without an API; CRUD + assignment mirror tags.) -------

def _labels(r: Router) -> None:
    @r.query("labels.list", library=True)
    def labels_list(node, library, _input):
        return rows_to_dicts(library.db.run(
            "api.label.list_with_counts"))

    @r.query("labels.getForObject", library=True)
    def labels_for_object(node, library, input):
        return rows_to_dicts(library.db.run(
            "api.label.for_object", (int(input["object_id"]),)))

    @r.mutation("labels.create", library=True, invalidates=["labels.list"])
    def labels_create(node, library, input):
        pub_id = uuid_bytes()
        sync = library.sync
        values = {"name": str(input["name"]),
                  "date_created": int(time.time())}
        with sync.write_ops(
                sync.shared_create("label", pub_id, values)) as conn:
            label_id = library.db.insert(
                "label", {"pub_id": pub_id, **values}, conn=conn)
        return {"id": label_id, "pub_id": pub_id.hex(), **values}

    @r.mutation("labels.assign", library=True,
                invalidates=["labels.list", "labels.getForObject"])
    def labels_assign(node, library, input):
        lb = library.db.run("api.label.by_id",
                            (int(input["label_id"]),))
        obj = library.db.run("api.object.by_id",
                             (int(input["object_id"]),))
        if lb is None or obj is None:
            raise RpcError("NOT_FOUND", "label or object missing")
        sync = library.sync
        if input.get("unassign"):
            ops = [sync.relation_delete(
                "label_on_object", obj["pub_id"], lb["pub_id"])]
            with sync.write_ops(ops) as conn:
                library.db.run("api.label.unassign",
                               (lb["id"], obj["id"]), conn=conn)
        else:
            ops = sync.relation_create(
                "label_on_object", obj["pub_id"], lb["pub_id"],
                {"date_created": int(time.time())})
            with sync.write_ops(ops) as conn:
                library.db.run(
                    "api.label.assign",
                    (lb["id"], obj["id"], int(time.time())), conn=conn)
        return None

    @r.mutation("labels.delete", library=True, invalidates=["labels.list"])
    def labels_delete(node, library, input):
        lb = library.db.run("api.label.by_id", (int(input["id"]),))
        if lb is None:
            return None
        sync = library.sync
        # relation deletes first — see tags_delete (FK-safe op order)
        assigned = library.db.run("api.label.assigned_objects",
                                  (lb["id"],))
        ops = [sync.relation_delete("label_on_object", r["opub"],
                                    lb["pub_id"]) for r in assigned]
        ops.append(sync.shared_delete("label", lb["pub_id"]))
        with sync.write_ops(ops) as conn:
            library.db.run("api.label.clear_assignments", (lb["id"],),
                           conn=conn)
            library.db.delete("label", lb["id"], conn=conn)
        return None


# -- spaces / albums (net-new API over schema.prisma:389-411/448-477's
# models — the reference registers the tables but ships NO api/ui for
# them; both stay LOCAL sync mode, matching its unannotated models) ----

def _grouping(r: Router, kind: str, rel: str, fk: str,
              extra_fields: tuple,
              rel_has_date_created: bool = False) -> None:
    """Shared CRUD for the two object-grouping models (space/album):
    identical shape, different table names and editable columns."""
    list_key = f"{kind}s.list"
    get_key = f"{kind}s.get"

    @r.query(f"{kind}s.list", library=True)
    def g_list(node, library, _input):
        return rows_to_dicts(library.db.query(
            f"SELECT g.*, COUNT(r.{fk}) AS object_count "
            f"FROM {kind} g LEFT JOIN {rel} r ON r.{fk} = g.id "
            f"GROUP BY g.id"))

    @r.query(f"{kind}s.get", library=True)
    def g_get(node, library, input):
        # f-strings bind the declared api.grouping.* shapes
        row = library.db.query_one(
            f"SELECT * FROM {kind} WHERE id = ?", (int(input["id"]),))
        if row is None:
            raise RpcError("NOT_FOUND", f"no such {kind}")
        out = row_to_dict(row)
        out["object_ids"] = [x["object_id"] for x in library.db.query(
            f"SELECT object_id FROM {rel} WHERE {fk} = ?", (row["id"],))]
        return out

    @r.mutation(f"{kind}s.create", library=True, invalidates=[list_key])
    def g_create(node, library, input):
        values = {"name": str(input["name"]),
                  "date_created": int(time.time()),
                  "date_modified": int(time.time())}
        for f in extra_fields:
            if f in input:
                values[f] = input[f]
        gid = library.db.insert(kind, {"pub_id": uuid_bytes(), **values})
        return {"id": gid, **values}

    @r.mutation(f"{kind}s.update", library=True,
                invalidates=[list_key, get_key])
    def g_update(node, library, input):
        gid = int(input["id"])
        if library.db.query_one(
                f"SELECT 1 FROM {kind} WHERE id = ?", (gid,)) is None:
            raise RpcError("NOT_FOUND", f"no such {kind}")
        values = {k: input[k] for k in ("name",) + extra_fields
                  if k in input}
        values["date_modified"] = int(time.time())
        library.db.update(kind, gid, values)
        return None

    @r.mutation(f"{kind}s.delete", library=True,
                invalidates=[list_key, get_key])
    def g_delete(node, library, input):
        with library.db.write_tx() as conn:
            conn.execute(f"DELETE FROM {rel} WHERE {fk} = ?",
                         (int(input["id"]),))
            conn.execute(f"DELETE FROM {kind} WHERE id = ?",
                         (int(input["id"]),))
        return None

    @r.mutation(f"{kind}s.addObjects", library=True,
                invalidates=[list_key, f"{kind}s.get"])
    def g_add(node, library, input):
        gid = int(input["id"])
        if library.db.query_one(
                f"SELECT 1 FROM {kind} WHERE id = ?", (gid,)) is None:
            raise RpcError("NOT_FOUND", f"no such {kind}")
        now = int(time.time())
        with library.db.write_tx() as conn:
            for oid in input["object_ids"]:
                # skip stale ids (object deleted between the caller's
                # list and this add): INSERT OR IGNORE does NOT
                # suppress FK violations, and one would roll back the
                # whole batch with a raw IntegrityError
                if library.db.run("api.object.exists", (int(oid),),
                                  conn=conn) is None:
                    continue
                if rel_has_date_created:
                    conn.execute(
                        f"INSERT OR IGNORE INTO {rel} ({fk}, object_id, "
                        f"date_created) VALUES (?, ?, ?)",
                        (gid, int(oid), now))
                else:
                    conn.execute(
                        f"INSERT OR IGNORE INTO {rel} ({fk}, object_id) "
                        f"VALUES (?, ?)", (gid, int(oid)))
        return None

    @r.mutation(f"{kind}s.removeObjects", library=True,
                invalidates=[list_key, f"{kind}s.get"])
    def g_remove(node, library, input):
        with library.db.write_tx() as conn:
            for oid in input["object_ids"]:
                conn.execute(
                    f"DELETE FROM {rel} WHERE {fk} = ? AND object_id = ?",
                    (int(input["id"]), int(oid)))
        return None


def _spaces(r: Router) -> None:
    _grouping(r, "space", "object_in_space", "space_id",
              ("description",))


def _albums(r: Router) -> None:
    _grouping(r, "album", "object_in_album", "album_id",
              ("is_hidden",), rel_has_date_created=True)


# -- categories. (api/categories.rs: object-kind counts) -------------------

def _categories(r: Router) -> None:
    @r.query("categories.list", library=True)
    def categories_list(node, library, _input):
        from ..files import ObjectKind
        counts = {int(k): 0 for k in ObjectKind}
        for row in library.db.run("api.object.kind_counts"):
            if row["kind"] is not None:
                counts[int(row["kind"])] = row["n"]
        return {ObjectKind(k).name.title().replace("_", ""): n
                for k, n in counts.items()}


# -- locations. (api/locations.rs incl. indexer_rules sub-router) ----------

def _locations(r: Router) -> None:
    @r.query("locations.list", library=True)
    def locations_list(node, library, _input):
        return rows_to_dicts(library.db.run("location.all"))

    @r.query("locations.get", library=True)
    def locations_get(node, library, input):
        row = library.db.run("location.by_id",
                             (int(input["location_id"]),))
        return row_to_dict(row) if row else None

    @r.query("locations.getWithRules", library=True)
    def locations_get_with_rules(node, library, input):
        row = library.db.run("location.by_id",
                             (int(input["location_id"]),))
        if row is None:
            return None
        out = row_to_dict(row)
        out["indexer_rules"] = rows_to_dicts(library.db.run(
            "location.rules_for", (row["id"],)))
        return out

    @r.mutation("locations.create", library=True,
                invalidates=["locations.list"])
    async def locations_create(node, library, input):
        try:
            loc_id = await asyncio.to_thread(
                loc_manager.create_location,
                library, str(input["path"]),
                indexer_rule_ids=input.get("indexer_rules_ids", []),
                name=input.get("name"))
        except loc_manager.LocationError as e:
            raise RpcError("BAD_REQUEST", str(e))
        if input.get("dry_run"):
            return loc_id
        await loc_manager.scan_location(node.jobs, library, loc_id)
        return loc_id

    @r.mutation("locations.update", library=True,
                invalidates=["locations.list"])
    def locations_update(node, library, input):
        loc = library.db.run("location.by_id", (int(input["id"]),))
        if loc is None:
            raise RpcError("NOT_FOUND", "no such location")
        sync = library.sync
        values = {k: input[k] for k in ("name", "hidden") if k in input}
        ops = [sync.shared_update("location", loc["pub_id"], k, v)
               for k, v in values.items()]
        with sync.write_ops(ops) as conn:
            library.db.update("location", loc["id"], values, conn=conn)
        # rule re-attachment
        if "indexer_rules_ids" in input:
            with library.db.write_tx() as conn:
                library.db.run("location.detach_rules", (loc["id"],),
                               conn=conn)
                library.db.run_many(
                    "location.attach_rule",
                    [(loc["id"], int(rid))
                     for rid in input["indexer_rules_ids"]], conn=conn)
        return None

    @r.mutation("locations.delete", library=True,
                invalidates=["locations.list"])
    def locations_delete(node, library, input):
        loc_manager.delete_location(library, int(input["location_id"]))
        return None

    @r.mutation("locations.relink", library=True,
                invalidates=["locations.list"])
    def locations_relink(node, library, input):
        loc_manager.relink_location(
            library, int(input["location_id"]), str(input["path"]))
        return None

    @r.mutation("locations.addLibrary", library=True,
                invalidates=["locations.list"])
    async def locations_add_library(node, library, input):
        # Same as create, addressed at an explicit library (locations.rs).
        return await locations_create(node, library, input)

    @r.mutation("locations.fullRescan", library=True)
    async def locations_full_rescan(node, library, input):
        await loc_manager.scan_location(
            node.jobs, library, int(input["location_id"]))
        return None

    @r.mutation("locations.quickRescan", library=True)
    async def locations_quick_rescan(node, library, input):
        from ..locations.shallow import light_scan_location
        return await asyncio.to_thread(
            light_scan_location, library, int(input["location_id"]),
            input.get("sub_path") or None)

    @r.mutation("locations.subPathRescan", library=True)
    async def locations_sub_path_rescan(node, library, input):
        await loc_manager.scan_location_sub_path(
            node.jobs, library, int(input["location_id"]),
            str(input.get("sub_path", "")))
        return None

    @r.query("locations.online", library=True)
    def locations_online(node, library, _input):
        out = []
        for row in library.db.run("location.id_paths"):
            if row["path"] and os.path.isdir(row["path"]):
                out.append(row["id"])
        return out

    @r.mutation("locations.createDirectory", library=True)
    def locations_create_directory(node, library, input):
        loc = library.db.run("location.path_by_id",
                             (int(input["location_id"]),))
        if loc is None:
            raise RpcError("NOT_FOUND", "no such location")
        target = os.path.join(
            loc["path"], str(input["sub_path"]).strip("/"))
        os.makedirs(target, exist_ok=False)
        return None

    # indexer_rules sub-router (locations.rs mounts it under
    # locations.indexer_rules.*)
    @r.query("locations.indexer_rules.list", library=True)
    def rules_list(node, library, _input):
        return rows_to_dicts(library.db.run("location.rule.all"))

    @r.query("locations.indexer_rules.get", library=True)
    def rules_get(node, library, input):
        row = library.db.run("location.rule.by_id",
                             (int(input["id"]),))
        return row_to_dict(row) if row else None

    @r.query("locations.indexer_rules.listForLocation", library=True)
    def rules_for_location(node, library, input):
        return rows_to_dicts(library.db.run(
            "location.rules_for", (int(input["location_id"]),)))

    @r.mutation("locations.indexer_rules.create", library=True,
                invalidates=["locations.indexer_rules.list"])
    def rules_create(node, library, input):
        rule = IndexerRule(
            name=str(input["name"]),
            rules=[RulePerKind(RuleKind(int(k)), tuple(params))
                   for k, params in input["rules"]],
        )
        rid = library.db.insert("indexer_rule", {
            "pub_id": uuid_bytes(),
            "name": rule.name,
            "default_rule": int(bool(input.get("default", False))),
            "rules_per_kind": rule.serialize_rules(),
            "date_created": int(time.time()),
            "date_modified": int(time.time()),
        })
        return rid

    @r.mutation("locations.indexer_rules.delete", library=True,
                invalidates=["locations.indexer_rules.list"])
    def rules_delete(node, library, input):
        row = library.db.run("location.rule.default_flag",
                             (int(input["id"]),))
        if row is None:
            return None
        if row["default_rule"]:
            raise RpcError("BAD_REQUEST", "cannot delete a system rule")
        library.db.delete("indexer_rule", int(input["id"]))
        return None


# -- files. (api/files.rs) -------------------------------------------------

def _file_path_row(library, file_path_id: int):
    row = library.db.run("api.file_path.by_id", (file_path_id,))
    if row is None:
        raise RpcError("NOT_FOUND", f"file_path {file_path_id} not found")
    return row


def _object_row(library, object_id: int):
    row = library.db.run("api.object.by_id", (object_id,))
    if row is None:
        raise RpcError("NOT_FOUND", f"object {object_id} not found")
    return row


def _files(r: Router) -> None:
    @r.query("files.get", library=True)
    def files_get(node, library, input):
        obj = library.db.run("api.object.by_id", (int(input["id"]),))
        if obj is None:
            return None
        out = row_to_dict(obj)
        out["file_paths"] = rows_to_dicts(library.db.run(
            "api.file_path.for_object", (obj["id"],)))
        md = library.db.run("api.media_data.for_object", (obj["id"],))
        out["media_data"] = row_to_dict(md) if md else None
        return out

    @r.query("files.getPath", library=True)
    def files_get_path(node, library, input):
        row = _file_path_row(library, int(input["id"]))
        loc = library.db.run("location.path_by_id",
                             (row["location_id"],))
        if loc is None or not loc["path"]:
            return None
        iso = IsolatedPath.from_db_row(
            row["location_id"], bool(row["is_dir"]),
            row["materialized_path"], row["name"] or "",
            row["extension"] or "")
        return iso.join_on(loc["path"])

    @r.query("files.getMediaData", library=True)
    def files_get_media_data(node, library, input):
        md = library.db.run("api.media_data.for_object",
                            (int(input["id"]),))
        return row_to_dict(md) if md else None

    @r.query("files.getEphemeralMediaData")
    def files_get_ephemeral_media_data(node, input):
        md = extract_media_data(str(input["path"]))
        # EXIF extraction carries raw byte blobs (maker notes,
        # thumbnails) and rationals nested at ANY depth (IFD sub-dicts,
        # rational arrays) — sanitize recursively at the protocol
        # boundary instead of blowing up JSON encoding.
        return _json_safe(md)

    @r.mutation("files.setNote", library=True, invalidates=["search.objects"])
    def files_set_note(node, library, input):
        obj = _object_row(library, int(input["id"]))
        sync = library.sync
        note = input.get("note")
        with sync.write_ops([sync.shared_update(
                "object", obj["pub_id"], "note", note)]) as conn:
            library.db.update("object", obj["id"], {"note": note}, conn=conn)
        return None

    @r.mutation("files.setFavorite", library=True,
                invalidates=["search.objects"])
    def files_set_favorite(node, library, input):
        obj = _object_row(library, int(input["id"]))
        sync = library.sync
        fav = int(bool(input.get("favorite")))
        with sync.write_ops([sync.shared_update(
                "object", obj["pub_id"], "favorite", fav)]) as conn:
            library.db.update("object", obj["id"], {"favorite": fav},
                              conn=conn)
        return None

    def _set_access_time(library, ids, value):
        # date_accessed is a SYNCED object field: the write and its
        # per-object LWW update ops land in one tx (sdlint crdt-parity
        # — the bare UPDATE this used to do never reached peers).
        ids = [int(oid) for oid in ids]
        if not ids:
            return
        sync = library.sync
        ph = ",".join("?" for _ in ids)
        # binds the declared api.object.pubs_by_ids shape
        rows = library.db.query(
            f"SELECT id, pub_id FROM object WHERE id IN ({ph})", ids)
        ops = [sync.shared_update("object", r["pub_id"], "date_accessed",
                                  value) for r in rows]
        with sync.write_ops(ops) as conn:
            library.db.run_many(
                "api.object.set_access_time",
                [(value, r["id"]) for r in rows], conn=conn)

    @r.mutation("files.updateAccessTime", library=True)
    async def files_update_access_time(node, library, input):
        # A multi-select can carry thousands of ids — the SELECT + op
        # minting + write tx must not run on the event loop.
        await asyncio.to_thread(
            _set_access_time, library, input["ids"], int(time.time()))
        return None

    @r.mutation("files.removeAccessTime", library=True)
    async def files_remove_access_time(node, library, input):
        await asyncio.to_thread(
            _set_access_time, library, input["ids"], None)
        return None

    @r.mutation("files.renameFile", library=True,
                invalidates=["search.paths"])
    def files_rename(node, library, input):
        row = _file_path_row(library, int(input["file_path_id"]))
        loc = library.db.run("location.by_id", (row["location_id"],))
        iso = IsolatedPath.from_db_row(
            row["location_id"], bool(row["is_dir"]),
            row["materialized_path"], row["name"] or "",
            row["extension"] or "")
        old_full = iso.join_on(loc["path"])
        new_name = str(input["new_name"])
        if "/" in new_name or "\x00" in new_name:
            raise RpcError("BAD_REQUEST", "invalid file name")
        new_full = os.path.join(os.path.dirname(old_full), new_name)
        if os.path.exists(new_full):
            raise RpcError("BAD_REQUEST", "target name already exists")
        # User-file RENAME requested over RPC (the row follows the
        # user's file), not an artifact commit.
        # sdlint: ok[io-durability]
        os.rename(old_full, new_full)
        if row["is_dir"]:
            name, ext = new_name, ""
        else:
            dot = new_name.rfind(".")
            name, ext = (new_name, "") if dot <= 0 else \
                (new_name[:dot], new_name[dot + 1:])
        sync = library.sync
        ops = [sync.shared_update("file_path", row["pub_id"], "name", name),
               sync.shared_update("file_path", row["pub_id"], "extension",
                                  ext)]
        with sync.write_ops(ops) as conn:
            library.db.update("file_path", row["id"],
                              {"name": name, "extension": ext}, conn=conn)
            if row["is_dir"]:
                # descendants' materialized_path prefix changes too
                old_mat = f"{row['materialized_path']}{row['name']}/"
                new_mat = f"{row['materialized_path']}{name}/"
                library.db.run(
                    "api.file_path.rename_descendants",
                    (old_mat, new_mat, row["location_id"],
                     old_mat.replace("\\", "\\\\").replace("%", r"\%")
                     .replace("_", r"\_") + "%"), conn=conn)
        return None

    @r.mutation("files.createFolder", library=True,
                invalidates=["search.paths"])
    def files_create_folder(node, library, input):
        loc = library.db.run("location.by_id",
                             (int(input["location_id"]),))
        if loc is None:
            raise RpcError("NOT_FOUND", "no such location")
        target = os.path.join(loc["path"],
                              str(input["sub_path"]).strip("/"),
                              str(input["name"]))
        os.makedirs(target, exist_ok=False)
        from ..locations.shallow import light_scan_location
        light_scan_location(library, loc["id"],
                            str(input["sub_path"]).strip("/") or None)
        return target

    @r.mutation("files.createEphemeralFolder")
    def files_create_ephemeral_folder(node, input):
        target = os.path.join(str(input["path"]), str(input["name"]))
        os.makedirs(target, exist_ok=False)
        return target

    async def _spawn_fs_job(node, library, job):
        return (await node.jobs.ingest(library, job)).hex()

    @r.mutation("files.deleteFiles", library=True,
                invalidates=["search.paths"])
    async def files_delete(node, library, input):
        from ..objects.fs_ops import FileDeleterJob
        return await _spawn_fs_job(node, library, FileDeleterJob(
            location_id=int(input["location_id"]),
            file_path_ids=[int(i) for i in input["file_path_ids"]]))

    @r.mutation("files.eraseFiles", library=True,
                invalidates=["search.paths"])
    async def files_erase(node, library, input):
        from ..objects.fs_ops import FileEraserJob
        return await _spawn_fs_job(node, library, FileEraserJob(
            location_id=int(input["location_id"]),
            file_path_ids=[int(i) for i in input["file_path_ids"]],
            passes=int(input.get("passes", 1))))

    @r.mutation("files.copyFiles", library=True,
                invalidates=["search.paths"])
    async def files_copy(node, library, input):
        from ..objects.fs_ops import FileCopierJob
        return await _spawn_fs_job(node, library, FileCopierJob(
            location_id=int(input["source_location_id"]),
            file_path_ids=[int(i) for i in input["sources_file_path_ids"]],
            target_location_id=int(input["target_location_id"]),
            target_relative_directory=str(
                input.get("target_location_relative_directory_path", ""))))

    @r.mutation("files.cutFiles", library=True,
                invalidates=["search.paths"])
    async def files_cut(node, library, input):
        from ..objects.fs_ops import FileCutterJob
        return await _spawn_fs_job(node, library, FileCutterJob(
            location_id=int(input["source_location_id"]),
            file_path_ids=[int(i) for i in input["sources_file_path_ids"]],
            target_location_id=int(input["target_location_id"]),
            target_relative_directory=str(
                input.get("target_location_relative_directory_path", ""))))

    @r.mutation("files.duplicateFiles", library=True,
                invalidates=["search.paths"])
    async def files_duplicate(node, library, input):
        from ..objects.fs_ops import FileCopierJob
        return await _spawn_fs_job(node, library, FileCopierJob(
            location_id=int(input["location_id"]),
            file_path_ids=[int(i) for i in input["file_path_ids"]],
            target_location_id=int(input["location_id"]),
            target_relative_directory=str(
                input.get("target_relative_directory", ""))))

    @r.mutation("files.encryptFiles", library=True,
                invalidates=["search.paths"])
    async def files_encrypt(node, library, input):
        from ..objects.crypto_ops import FileEncryptorJob
        return await _spawn_fs_job(node, library, FileEncryptorJob(
            location_id=int(input["location_id"]),
            file_path_ids=[int(i) for i in input["file_path_ids"]],
            password=str(input["password"]),
            algorithm=str(input.get("algorithm", "XChaCha20Poly1305")),
            hashing_algorithm=str(
                input.get("hashing_algorithm", "Argon2id")),
            params=str(input.get("params", "Standard")),
            with_metadata=bool(input.get("with_metadata", True)),
            erase_original=bool(input.get("erase_original", False))))

    @r.mutation("files.decryptFiles", library=True,
                invalidates=["search.paths"])
    async def files_decrypt(node, library, input):
        from ..objects.crypto_ops import FileDecryptorJob
        return await _spawn_fs_job(node, library, FileDecryptorJob(
            location_id=int(input["location_id"]),
            file_path_ids=[int(i) for i in input["file_path_ids"]],
            password=str(input["password"]),
            output_path=input.get("output_path")))

    @r.query("files.getConvertableImageExtensions")
    def files_convertable(node, _input):
        return ["png", "jpeg", "jpg", "webp", "bmp", "gif", "tiff"]

    @r.mutation("files.convertImage", library=True)
    def files_convert_image(node, library, input):
        row = _file_path_row(library, int(input["file_path_id"]))
        loc = library.db.run("location.path_by_id",
                             (row["location_id"],))
        iso = IsolatedPath.from_db_row(
            row["location_id"], bool(row["is_dir"]),
            row["materialized_path"], row["name"] or "",
            row["extension"] or "")
        src = iso.join_on(loc["path"])
        to_ext = str(input["to_extension"]).lower()
        if to_ext not in ("png", "jpeg", "jpg", "webp", "bmp", "gif",
                          "tiff"):
            raise RpcError("BAD_REQUEST", f"unsupported target {to_ext}")
        from PIL import Image
        dst = os.path.splitext(src)[0] + "." + to_ext
        if os.path.exists(dst):
            from ..objects.fs_ops import find_available_filename_for_duplicate
            dst = find_available_filename_for_duplicate(dst)
        with Image.open(src) as im:
            fmt = {"jpg": "JPEG"}.get(to_ext, to_ext.upper())
            im.convert("RGB" if fmt == "JPEG" else im.mode).save(dst, fmt)
        return dst


# -- jobs. (api/jobs.rs) ---------------------------------------------------

def _jobs(r: Router) -> None:
    @r.query("jobs.reports", library=True)
    def jobs_reports(node, library, _input):
        rows = library.db.run("api.job.reports")
        return rows_to_dicts(rows)

    @r.query("jobs.isActive", library=True)
    def jobs_is_active(node, library, _input):
        return bool(node.jobs.running)

    @r.subscription("jobs.progress")
    def jobs_progress(node, _input, emit):
        def on_event(e):
            if e.get("type") in ("JobProgress", "JobUpdate"):
                emit(e)
        return node.events.subscribe(on_event)

    @r.subscription("jobs.newThumbnail")
    def jobs_new_thumbnail(node, _input, emit):
        def on_event(e):
            if e.get("type") == "NewThumbnail":
                emit(e)
        return node.events.subscribe(on_event)

    @r.mutation("jobs.pause", library=True, invalidates=["jobs.reports"])
    def jobs_pause(node, library, input):
        node.jobs.pause(bytes.fromhex(str(input["id"])))
        return None

    @r.mutation("jobs.resume", library=True, invalidates=["jobs.reports"])
    async def jobs_resume(node, library, input):
        await node.jobs.resume(library, bytes.fromhex(str(input["id"])))
        return None

    @r.mutation("jobs.cancel", library=True, invalidates=["jobs.reports"])
    def jobs_cancel(node, library, input):
        node.jobs.cancel(bytes.fromhex(str(input["id"])))
        return None

    @r.mutation("jobs.clear", library=True, invalidates=["jobs.reports"])
    def jobs_clear(node, library, input):
        with library.db.write_tx() as conn:
            library.db.run(
                "api.job.clear",
                (bytes.fromhex(str(input["id"])), int(JobStatus.RUNNING),
                 int(JobStatus.PAUSED), int(JobStatus.QUEUED)),
                conn=conn)
        return None

    @r.mutation("jobs.clearAll", library=True, invalidates=["jobs.reports"])
    def jobs_clear_all(node, library, _input):
        with library.db.write_tx() as conn:
            library.db.run(
                "api.job.clear_all",
                (int(JobStatus.RUNNING), int(JobStatus.PAUSED),
                 int(JobStatus.QUEUED)), conn=conn)
        return None

    @r.mutation("jobs.generateThumbsForLocation", library=True)
    async def jobs_gen_thumbs(node, library, input):
        from ..media.processor import MediaProcessorJob
        jid = await node.jobs.ingest(library, MediaProcessorJob(
            location_id=int(input["id"]),
            sub_path=input.get("path") or None))
        return jid.hex()

    @r.mutation("jobs.objectValidator", library=True)
    async def jobs_object_validator(node, library, input):
        from ..objects.validator import ObjectValidatorJob
        jid = await node.jobs.ingest(library, ObjectValidatorJob(
            location_id=int(input["id"]),
            sub_path=input.get("path") or None,
            mode=str(input.get("mode", "fill"))))
        return jid.hex()

    @r.mutation("jobs.identifyUniqueFiles", library=True)
    async def jobs_identify(node, library, input):
        from ..objects.identifier import FileIdentifierJob
        jid = await node.jobs.ingest(library, FileIdentifierJob(
            location_id=int(input["id"]),
            sub_path=input.get("path") or None))
        return jid.hex()

    @r.mutation("jobs.nearDupDetector", library=True)
    async def jobs_near_dup(node, library, input):
        from ..objects.dedup import NearDupDetectorJob
        jid = await node.jobs.ingest(library, NearDupDetectorJob(
            location_id=int(input["id"]),
            threshold=int(input.get("threshold", 10))))
        return jid.hex()


# -- search. (api/search.rs:364-750) ---------------------------------------

def _search_paths_where(input) -> tuple:
    where, params = "1=1", []
    f = input.get("filter") or {}
    if "location_id" in f:
        where += " AND fp.location_id = ?"
        params.append(int(f["location_id"]))
    if f.get("search"):
        where += " AND fp.name LIKE ?"
        params.append(f"%{f['search']}%")
    if "is_dir" in f:
        where += " AND fp.is_dir = ?"
        params.append(int(bool(f["is_dir"])))
    if f.get("extension"):
        where += " AND LOWER(fp.extension) = ?"
        params.append(str(f["extension"]).lower())
    if f.get("materialized_path"):
        where += " AND fp.materialized_path = ?"
        params.append(f["materialized_path"])
    if f.get("object_kind"):
        ph = ",".join("?" for _ in f["object_kind"])
        where += (f" AND fp.object_id IN "
                  f"(SELECT id FROM object WHERE kind IN ({ph}))")
        params.extend(int(k) for k in f["object_kind"])
    if f.get("tags"):
        ph = ",".join("?" for _ in f["tags"])
        where += (f" AND fp.object_id IN (SELECT object_id FROM "
                  f"tag_on_object WHERE tag_id IN ({ph}))")
        params.extend(int(t) for t in f["tags"])
    # Server-side favorite/extension-set filters: the virtualized
    # explorer windows the result by absolute index, so EVERY filter
    # must narrow the SQL — a client-side filter would leave holes in
    # the windows and shift indices.
    if f.get("favorite") is not None:
        where += (" AND fp.object_id IN "
                  "(SELECT id FROM object WHERE favorite = ?)")
        params.append(int(bool(f["favorite"])))
    if f.get("album_id"):
        where += (" AND fp.object_id IN (SELECT object_id FROM "
                  "object_in_album WHERE album_id = ?)")
        params.append(int(f["album_id"]))
    if f.get("space_id"):
        where += (" AND fp.object_id IN (SELECT object_id FROM "
                  "object_in_space WHERE space_id = ?)")
        params.append(int(f["space_id"]))
    if f.get("extensions"):
        ph = ",".join("?" for _ in f["extensions"])
        where += f" AND LOWER(fp.extension) IN ({ph})"
        params.extend(str(e).lower() for e in f["extensions"])
    return where, params


def _search(r: Router) -> None:
    @r.query("search.paths", library=True)
    def search_paths(node, library, input):
        """Two access modes (the reference's Explorer queries through
        @tanstack/react-virtual windows — interface/app/$libraryId/
        Explorer): keyset `cursor` pagination for sequential readers,
        and absolute `skip` windows + server-side `order` for the
        virtualized explorer, which addresses rows by scroll index."""
        input = input or {}
        where, params = _search_paths_where(input)
        take = min(int(input.get("take", 100)), 500)
        order = input.get("order") or {}
        ocol = {"id": "fp.id", "name": "fp.name COLLATE NOCASE",
                "kind": "fp.extension COLLATE NOCASE",
                "size": "fp.size_in_bytes",
                "modified": "fp.date_modified",
                }.get(str(order.get("field", "id")), "fp.id")
        odir = "DESC" if order.get("desc") else "ASC"
        if "skip" in input:
            skip = max(0, int(input["skip"]))
            rows = library.db.query(
                f"SELECT fp.* FROM file_path fp WHERE {where} "
                f"ORDER BY {ocol} {odir}, fp.id LIMIT ? OFFSET ?",
                params + [take, skip])
            items = rows_to_dicts(rows)
            for it in items:
                it["thumbnail_key"] = it.get("cas_id")
            return {"items": items, "skip": skip}
        cursor = int(input.get("cursor", 0))
        rows = library.db.query(
            f"SELECT fp.* FROM file_path fp WHERE {where} AND fp.id > ? "
            f"ORDER BY fp.id LIMIT ?", params + [cursor, take])
        items = rows_to_dicts(rows)
        for it in items:
            it["thumbnail_key"] = it.get("cas_id")
        return {
            "items": items,
            "cursor": items[-1]["id"] if len(items) == take else None,
        }

    @r.query("search.pathsCount", library=True)
    def search_paths_count(node, library, input):
        where, params = _search_paths_where(input or {})
        return library.db.query_one(
            f"SELECT COUNT(*) AS n FROM file_path fp WHERE {where}",
            params)["n"]

    def _objects_where(input) -> tuple:
        where, params = "1=1", []
        f = (input or {}).get("filter") or {}
        if f.get("favorite") is not None:
            where += " AND o.favorite = ?"
            params.append(int(bool(f["favorite"])))
        if f.get("hidden") is not None:
            where += " AND o.hidden = ?"
            params.append(int(bool(f["hidden"])))
        if f.get("kind"):
            ph = ",".join("?" for _ in f["kind"])
            where += f" AND o.kind IN ({ph})"
            params.extend(int(k) for k in f["kind"])
        if f.get("tags"):
            ph = ",".join("?" for _ in f["tags"])
            where += (f" AND o.id IN (SELECT object_id FROM tag_on_object "
                      f"WHERE tag_id IN ({ph}))")
            params.extend(int(t) for t in f["tags"])
        return where, params

    @r.query("search.objects", library=True)
    def search_objects(node, library, input):
        """Same two access modes as search.paths: keyset `cursor`
        pagination, or absolute `skip` windows + server-side `order`
        for virtualized object views."""
        input = input or {}
        where, params = _objects_where(input)
        take = min(int(input.get("take", 100)), 500)

        def _attach_fps(items):
            # ONE query per page, not per object: the windowed mode is
            # hit on every scroll of a virtualized view
            if not items:
                return items
            ph = ",".join("?" for _ in items)
            by_obj: Dict[int, list] = {it["id"]: [] for it in items}
            # binds the declared api.search.paths_for_objects shape
            for fp in library.db.query(
                    f"SELECT * FROM file_path WHERE object_id IN ({ph})",
                    [it["id"] for it in items]):
                by_obj[fp["object_id"]].append(row_to_dict(fp))
            for it in items:
                it["file_paths"] = by_obj[it["id"]]
            return items

        if "skip" in input:
            order = input.get("order") or {}
            ocol = {"id": "o.id", "kind": "o.kind",
                    "date_created": "o.date_created",
                    "date_accessed": "o.date_accessed",
                    }.get(str(order.get("field", "id")), "o.id")
            odir = "DESC" if order.get("desc") else "ASC"
            skip = max(0, int(input["skip"]))
            rows = library.db.query(
                f"SELECT o.* FROM object o WHERE {where} "
                f"ORDER BY {ocol} {odir}, o.id LIMIT ? OFFSET ?",
                params + [take, skip])
            return {"items": _attach_fps(rows_to_dicts(rows)),
                    "skip": skip}
        cursor = int(input.get("cursor", 0))
        rows = library.db.query(
            f"SELECT o.* FROM object o WHERE {where} AND o.id > ? "
            f"ORDER BY o.id LIMIT ?", params + [cursor, take])
        items = _attach_fps(rows_to_dicts(rows))
        return {
            "items": items,
            "cursor": items[-1]["id"] if len(items) == take else None,
        }

    @r.query("search.objectsCount", library=True)
    def search_objects_count(node, library, input):
        where, params = _objects_where(input or {})
        return library.db.query_one(
            f"SELECT COUNT(*) AS n FROM object o WHERE {where}", params)["n"]

    @r.query("search.ephemeralPaths")
    async def search_ephemeral(node, input):
        path = str(input["path"])
        if not os.path.isdir(path):
            raise RpcError("BAD_REQUEST", f"{path} is not a directory")
        want_thumbs = bool(input.get("with_thumbnails"))
        # CAS hashing is file I/O — never on the event loop.
        entries = await asyncio.to_thread(
            walk_ephemeral, path,
            with_hidden_files=bool(input.get("with_hidden_files")),
            compute_cas_ids=want_thumbs)
        if want_thumbs and node.thumbnailer.is_running():
            # Fire-and-forget ephemeral batch (non_indexed.rs spawns the
            # same way); NewThumbnail events announce completions.
            batch = [(e["cas_id"], e["path"])
                     for e in entries if e.get("cas_id")]
            if batch:
                await node.thumbnailer.new_ephemeral_batch(batch)
        return entries

    # Net-new: device dedup analytics surfaces.
    @r.query("search.duplicates", library=True)
    def search_duplicates(node, library, input):
        from ..objects.dedup import exact_duplicate_groups
        return exact_duplicate_groups(
            library, location_id=(input or {}).get("location_id"))

    @r.query("search.nearDuplicates", library=True)
    def search_near_duplicates(node, library, input):
        from ..objects.dedup import near_duplicates
        return near_duplicates(
            library,
            max_distance=int((input or {}).get("max_distance", 10)))


# -- sync. (api/sync.rs) ---------------------------------------------------

def _sync(r: Router) -> None:
    @r.query("sync.messages", library=True)
    def sync_messages(node, library, _input):
        from ..sync.manager import GetOpsArgs
        ops = library.sync.get_ops(GetOpsArgs(clocks=[], count=1000))
        return [
            {"instance": op.instance.hex(), "timestamp": op.timestamp,
             "kind": op.typ.kind,
             "model": getattr(op.typ, "model",
                              getattr(op.typ, "relation", None))}
            for op in ops
        ]

    @r.subscription("sync.newMessage", library=True)
    def sync_new_message(node, library, _input, emit):
        def cb():
            emit({"type": "SyncMessageCreated"})
        library.sync.on_created(cb)
        return lambda: library.sync._on_created.remove(cb)


# -- preferences. (api/preferences.rs; KV per library) ---------------------

def _preferences(r: Router) -> None:
    import msgpack

    @r.query("preferences.get", library=True)
    def preferences_get(node, library, _input):
        out = {}
        for row in library.db.run("api.preference.all"):
            out[row["key"]] = msgpack.unpackb(row["value"], raw=False) \
                if row["value"] else None
        return out

    @r.mutation("preferences.update", library=True,
                invalidates=["preferences.get"])
    def preferences_update(node, library, input):
        with library.db.write_tx() as conn:
            for k, v in (input.get("values") or {}).items():
                if v is None:
                    library.db.run("api.preference.delete", (str(k),),
                                   conn=conn)
                else:
                    library.db.upsert(
                        "preference", {"key": str(k)},
                        {"value": msgpack.packb(v, use_bin_type=True)},
                        conn=conn)
        return None


# -- notifications. (api/notifications.rs) ---------------------------------

def _notifications(r: Router) -> None:
    @r.query("notifications.get")
    def notifications_get(node, _input):
        out = []
        for lib in node.libraries.list():
            for row in lib.db.run("api.notification.recent"):
                d = row_to_dict(row)
                d["library_id"] = str(lib.id)
                out.append(d)
        return out

    @r.mutation("notifications.dismiss", library=True,
                invalidates=["notifications.get"])
    def notifications_dismiss(node, library, input):
        with library.db.write_tx() as conn:
            library.db.run("api.notification.dismiss",
                           (int(input["id"]),), conn=conn)
        return None

    @r.mutation("notifications.dismissAll",
                invalidates=["notifications.get"])
    def notifications_dismiss_all(node, _input):
        for lib in node.libraries.list():
            # one tx per LIBRARY — each library is its own database
            lib.db.run_tx("api.notification.dismiss_all")  # sdlint: ok[tx-shape]
        return None

    @r.subscription("notifications.listen")
    def notifications_listen(node, _input, emit):
        def on_event(e):
            if e.get("type") == "Notification":
                emit(e)
        return node.events.subscribe(on_event)

    @r.mutation("notifications.test")
    def notifications_test(node, _input):
        node.events.emit({"type": "Notification",
                          "data": {"kind": "test", "message": "test"}})
        return None

    @r.mutation("notifications.testLibrary", library=True)
    def notifications_test_library(node, library, _input):
        import msgpack
        library.db.insert("notification", {
            "data": msgpack.packb({"kind": "test"}, use_bin_type=True),
        })
        node.events.emit({"type": "Notification",
                          "data": {"kind": "test",
                                   "library_id": str(library.id)}})
        return None


# -- nodes. (api/nodes.rs) -------------------------------------------------

def _nodes(r: Router) -> None:
    @r.mutation("nodes.edit", invalidates=["nodeState"])
    def nodes_edit(node, input):
        if input.get("name"):
            node.config.raw["name"] = str(input["name"])
            node.config.save()
        return None

    @r.query("nodes.listLocations", library=True)
    def nodes_list_locations(node, library, input):
        return rows_to_dicts(library.db.run("location.all"))


# -- auth. (api/auth.rs — the RFC 8628 device flow state machine) ----------

def _auth(r: Router) -> None:
    from .. import auth as auth_mod

    @r.query("auth.me")
    def auth_me(node, _input):
        # api/auth.rs:148-174: stored token → issuer lookup → {id,email}
        token = auth_mod.stored_token(node)
        if token is None:
            raise RpcError("UNAUTHORIZED", "No auth token")
        user = auth_mod.issuer_for(node).me(token.to_header())
        if user is None:
            raise RpcError("UNAUTHORIZED", "token no longer valid")
        return {"id": user["id"], "email": user["email"]}

    @r.mutation("auth.logout", invalidates=["auth.me"])
    def auth_logout(node, _input):
        # api/auth.rs:133-147: clear the persisted token
        token = auth_mod.stored_token(node)
        if token is not None:
            auth_mod.issuer_for(node).revoke(token.access_token)
        auth_mod.store_token(node, None)
        return None

    @r.subscription("auth.loginSession")
    def auth_login(node, _input, emit):
        """api/auth.rs:36-131: Start{user_code, urls} → poll the
        device-code grant → persist token → Complete; pending keeps
        polling, denial/expiry → Error. `poll_interval` input shortens
        the reference's 5 s loop for tests/offline issuers."""
        issuer = auth_mod.issuer_for(node)
        client_id = node.config.id.hex()
        interval = 5.0
        if isinstance(_input, dict) and _input.get("poll_interval"):
            interval = float(_input["poll_interval"])
        try:
            dev = issuer.device_code(client_id)
        except Exception:
            emit({"state": "Error"})
            return lambda: None
        emit({"state": "Start",
              "user_code": dev["user_code"],
              "verification_url": dev["verification_url"],
              "verification_url_complete": dev["verification_uri_complete"]})

        async def poll():
            try:
                while True:
                    await asyncio.sleep(interval)
                    status, body = issuer.access_token(
                        auth_mod.DEVICE_CODE_URN, dev["device_code"],
                        client_id)
                    if status == 200:
                        await asyncio.to_thread(
                            auth_mod.store_token,
                            node, auth_mod.OAuthToken.from_raw(body))
                        node.events.invalidate_query(None, "auth.me")
                        emit({"state": "Complete"})
                        return
                    if body.get("error") == "authorization_pending":
                        continue
                    emit({"state": "Error"})
                    return
            except asyncio.CancelledError:
                raise
            except Exception:
                # An HTTP-adapter issuer can raise (network) or return
                # a malformed token body — the subscriber must get a
                # terminal Error, never a silent hang (api/auth.rs
                # breaks Response::Error on every failure arm).
                emit({"state": "Error"})

        # Supervised: the returned cancel-handle leaked this task
        # whenever the subscriber disconnected before the first emit —
        # node.shutdown's reap now sweeps an un-cancelled poll
        # (tests/test_shutdown_leaks.py asserts none survive close()).
        task = tasks.spawn("auth-poll", poll(),
                           owner=f"{node.task_owner}/api")
        return task.cancel


# -- backups. (api/backups.rs) ---------------------------------------------

def _backups(r: Router) -> None:
    @r.query("backups.getAll")
    def backups_get_all(node, _input):
        return backups_mod.list_backups(node)

    @r.mutation("backups.backup", library=True,
                invalidates=["backups.getAll"])
    async def backups_backup(node, library, _input):
        return await asyncio.to_thread(backups_mod.do_backup, node, library)

    @r.mutation("backups.restore", invalidates=["backups.getAll",
                                                "library.list"])
    async def backups_restore(node, input):
        return await asyncio.to_thread(
            backups_mod.restore_backup, node, str(input["backup_id"]))

    @r.mutation("backups.delete", invalidates=["backups.getAll"])
    def backups_delete(node, input):
        return backups_mod.delete_backup(node, str(input["backup_id"]))


# -- invalidation. (api/utils/invalidate.rs) -------------------------------

# -- keys. (the key-manager surface; the reference's keys router exists
#    but ships disabled alongside its commented-out crypto jobs — here
#    the crypto subsystem works, so the surface is live) --------------------

def _keys(r: Router) -> None:
    def _km(node):
        km = getattr(node, "_key_manager", None)
        if km is None:
            from ..crypto.keymanager import KeyManager

            km = KeyManager(os.path.join(node.data_dir, "keys.json"))
            node._key_manager = km
        return km

    @r.query("keys.isUnlocked")
    def keys_is_unlocked(node, _input):
        return _km(node).is_unlocked

    @r.query("keys.isSetup")
    def keys_is_setup(node, _input):
        return _km(node)._verification is not None

    @r.mutation("keys.setup",
                invalidates=["keys.list", "keys.isUnlocked",
                             "keys.isSetup"])
    def keys_setup(node, input):
        from ..crypto.primitives import Protected

        km = _km(node)
        km.initialize(Protected(str(input["password"]).encode()))
        km.automount()
        return None

    @r.mutation("keys.unlock",
                invalidates=["keys.list", "keys.isUnlocked"])
    def keys_unlock(node, input):
        from ..crypto.primitives import Protected

        km = _km(node)
        km.unlock(Protected(str(input["password"]).encode()))
        km.automount()  # automount-flagged keys come back on unlock
        return None

    @r.mutation("keys.lock",
                invalidates=["keys.list", "keys.isUnlocked"])
    def keys_lock(node, _input):
        _km(node).lock()
        return None

    @r.query("keys.list")
    def keys_list(node, _input):
        return _km(node).list_keys()

    @r.mutation("keys.add", invalidates=["keys.list"])
    def keys_add(node, input):
        from ..crypto.primitives import Protected

        # ValueError/KeyError → BAD_REQUEST is the router's job.
        return _km(node).add_key(
            Protected(str(input["key"]).encode()),
            automount=bool(input.get("automount")))

    @r.mutation("keys.mount", invalidates=["keys.list"])
    def keys_mount(node, input):
        uuid_s = str(input["uuid"])
        try:
            _km(node).mount(uuid_s)
        except KeyError:
            raise RpcError("NOT_FOUND", "no such key")
        return None

    @r.mutation("keys.unmount", invalidates=["keys.list"])
    def keys_unmount(node, input):
        _km(node).unmount(str(input["uuid"]))
        return None

    @r.mutation("keys.delete", invalidates=["keys.list"])
    def keys_delete(node, input):
        _km(node).delete_key(str(input["uuid"]))
        return None


# -- p2p. (api/p2p.rs: events, state, spacedrop, acceptSpacedrop,
#    cancelSpacedrop, pair) --------------------------------------------------

def _p2p(r: Router) -> None:
    def _mgr(node):
        if node.p2p is None:
            raise RpcError("BAD_REQUEST", "p2p is not started on this node")
        return node.p2p

    @r.query("p2p.state")
    def p2p_state(node, _input):
        if node.p2p is None:
            return {"enabled": False, "peers": []}
        disc = node.p2p.discovery
        peers = []
        if disc is not None:
            for peer in disc.peers.values():
                peers.append({
                    "identity": peer.identity.to_bytes().hex(),
                    "addr": peer.addr, "port": peer.port,
                    "metadata": peer.metadata,
                })
        return {
            "enabled": True,
            "identity": node.p2p.identity.to_remote_identity()
                        .to_bytes().hex(),
            "port": node.p2p.port,
            "peers": peers,
        }

    @r.subscription("p2p.events")
    def p2p_events(node, _input, emit):
        def on_event(e):
            if str(e.get("type", "")).startswith(("Spacedrop", "P2P",
                                                  "Discovered")):
                emit(e)
        return node.events.subscribe(on_event)

    @r.mutation("p2p.spacedrop")
    async def p2p_spacedrop(node, input):
        mgr = _mgr(node)
        return await mgr.spacedrop(
            str(input["addr"]), int(input["port"]),
            str(input["file_path"]))

    @r.mutation("p2p.acceptSpacedrop")
    def p2p_accept_spacedrop(node, input):
        mgr = _mgr(node)
        drop_id = str(input["id"])
        # rspc signature: Some(path) accepts, None rejects
        # (api/p2p.rs acceptSpacedrop).
        path = input.get("path")
        if path:
            return mgr.accept_spacedrop(drop_id, str(path))
        return mgr.reject_spacedrop(drop_id)

    @r.mutation("p2p.cancelSpacedrop")
    def p2p_cancel_spacedrop(node, input):
        _mgr(node).cancel_spacedrop(str(input["id"]))
        return None

    @r.mutation("p2p.pair", library=True)
    async def p2p_pair(node, library, input):
        mgr = _mgr(node)
        return await mgr.pair(str(input["addr"]), int(input["port"]),
                              library)

    @r.mutation("p2p.debugPing")
    async def p2p_debug_ping(node, input):
        mgr = _mgr(node)
        return await mgr.ping(str(input["addr"]), int(input["port"]))


def _invalidation(r: Router) -> None:
    @r.subscription("invalidation.listen")
    def invalidation_listen(node, _input, emit):
        def on_event(e):
            if e.get("type") == "InvalidateOperation":
                emit(e)
        return node.events.subscribe(on_event)
