let reqId = 0, pending = {}, subs = {}, subSpecs = [];
const wsProto = location.protocol === "https:" ? "wss" : "ws";
let ws = null, reconnectDelay = 500;
// wsReady always has a live resolver: awaiting rpc() calls parked
// during a reconnect wake on the SAME promise the next onopen resolves.
let wsReadyResolve = null;
let wsReady = new Promise(r => wsReadyResolve = r);

function connect() {
  ws = new WebSocket(`${wsProto}://${location.host}/rspc`);
  ws.onopen = () => {
    reconnectDelay = 500;
    // standing subscriptions survive reconnects (the standalone-client
    // contract: the UI must keep working across server restarts)
    for (const s of subSpecs) {
      const id = ++reqId; subs[id] = s.cb;
      ws.send(JSON.stringify({id, type: "subscription",
                              path: s.path, input: s.input}));
    }
    wsReadyResolve();
  };
  ws.onmessage = (m) => {
    const f = JSON.parse(m.data);
    if (f.type === "response" && pending[f.id]) {
      pending[f.id].resolve(f.result); delete pending[f.id];
    } else if (f.type === "error" && pending[f.id]) {
      pending[f.id].reject(new Error(f.message)); delete pending[f.id];
    } else if (f.type === "event" && subs[f.id]) {
      subs[f.id](f.data);
    }
  };
  ws.onclose = () => {
    for (const id in pending) {
      pending[id].reject(new Error("connection lost")); delete pending[id];
    }
    subs = {};
    // Park wsReady on a fresh promise NOW (resolver saved for the next
    // onopen): rpc() calls made during the backoff window suspend here
    // instead of sending into the closed socket.
    wsReady = new Promise(r => wsReadyResolve = r);
    toast(`reconnecting in ${Math.round(reconnectDelay / 1000)}s…`);
    setTimeout(connect, reconnectDelay);
    reconnectDelay = Math.min(reconnectDelay * 2, 15000);
  };
}
connect();
async function rpc(type, path, input) {
  await wsReady;
  const id = ++reqId;
  ws.send(JSON.stringify({id, type, path, input}));
  return new Promise((resolve, reject) => pending[id] = {resolve, reject});
}
const q = (p, i) => rpc("query", p, i);
const mut = (p, i) => rpc("mutation", p, i);
function sub(path, input, cb) {
  subSpecs.push({path, input, cb});
  if (ws && ws.readyState === 1) {  // otherwise onopen replays subSpecs
    const id = ++reqId;
    subs[id] = cb;
    ws.send(JSON.stringify({id, type: "subscription", path, input}));
  }
}
function toast(msg) {
  const t = document.getElementById("toast");
  t.textContent = msg; t.style.display = "block";
  clearTimeout(t._h); t._h = setTimeout(() => t.style.display = "none", 3000);
}
const esc = (s) => String(s ?? "").replace(/[&<>"']/g,
  c => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));
const fmtBytes = (n) => {
  n = Number(n) || 0;
  for (const u of ["B","KiB","MiB","GiB","TiB"]) {
    if (n < 1024 || u === "TiB") return n.toFixed(u==="B"?0:1)+" "+u;
    n /= 1024;
  }
};

let lib = null, loc = null, curPath = "/", view = "explorer";
let selected = null, tagFilter = null, favOnly = false, allTags = [];
let viewMode = "grid";         // grid | list | media (explorer modes)
let sortKey = null, sortDir = 1;  // list-view column sort
let selection = new Set();     // multi-select: file_path ids
let lastRows = [];             // rows rendered by the last browse()
let lastClickId = null;        // shift-range anchor
let clipboard = null;          // {op: "copy"|"cut", ids, locId}
let settingsLoc = null;        // location id open in per-location settings

const TABS = [["explorer","Explorer"],["browse","Browse"],
              ["dups","Duplicates"],
              ["neardups","Near-dups"],["jobs","Jobs"],["p2p","P2P"],
              ["settings","Settings"]];
function renderTabs() {
  const el = document.getElementById("tabs"); el.innerHTML = "";
  for (const [id, label] of TABS) {
    const d = document.createElement("div");
    d.className = "tab" + (view === id ? " sel" : "");
    d.textContent = label;
    d.onclick = () => { view = id; renderTabs(); render(); };
    el.appendChild(d);
  }
}

// ---- Onboarding (create library → add location, the reference's
// interface/app/onboarding flow) ---------------------------------------
function showOnboarding() {
  if (document.getElementById("onboard")) return;
  const o = document.createElement("div");
  o.id = "onboard";
  o.innerHTML = `<div class="card">
    <h1>Welcome to spacedrive-tpu</h1>
    <p class="muted">A library is your private database of every file
      it indexes. Create one, then point it at a folder.</p>
    <h3>1 · Create your library</h3>
    <p><input id="oblib" placeholder="library name" value="My Library"
              style="width:100%"/></p>
    <h3>2 · Add a first location</h3>
    <p><input id="obloc" placeholder="/path/to/files (optional)"
              style="width:100%"/></p>
    <p style="text-align:right"><button id="obgo">Create</button></p>
    <div id="oberr" class="muted"></div>
  </div>`;
  document.body.appendChild(o);
  document.getElementById("obgo").onclick = async () => {
    const name = document.getElementById("oblib").value.trim();
    if (!name) return;
    try {
      const l = await mut("library.create", {name});
      lib = l.uuid;
      const path = document.getElementById("obloc").value.trim();
      if (path) {
        loc = await mut("locations.create", {library_id: lib, path});
        toast("indexing started");
      }
      o.remove(); loadAll();
    } catch (err) {
      document.getElementById("oberr").textContent = String(err);
    }
  };
}

async function loadLibs() {
  const libs = await q("library.list");
  if (!libs.length) showOnboarding();
  const el = document.getElementById("libs"); el.innerHTML = "";
  for (const l of libs) {
    const d = document.createElement("div");
    d.className = "item" + (lib === l.uuid ? " sel" : "");
    d.textContent = l.config ? l.config.name : l.name;
    d.onclick = () => { lib = l.uuid; loadAll(); };
    d.oncontextmenu = async (e) => {
      e.preventDefault();
      if (confirm(`delete library "${d.textContent}"?`)) {
        await mut("library.delete", {id: l.uuid});
        if (lib === l.uuid) lib = null;
        loadLibs();
      }
    };
    el.appendChild(d);
  }
  if (!lib && libs.length) { lib = libs[0].uuid; loadAll(); }
}
function loadAll() { loadLibs(); loadLocs(); loadTags(); loadStats(); render(); }

async function loadLocs() {
  if (!lib) return;
  const locs = await q("locations.list", {library_id: lib});
  const el = document.getElementById("locs"); el.innerHTML = "";
  for (const l of locs) {
    const d = document.createElement("div");
    d.className = "item" + (loc === l.id ? " sel" : "");
    d.textContent = l.name || l.path;
    const gear = document.createElement("span");
    gear.className = "gear"; gear.textContent = "⚙";
    gear.title = "location settings";
    gear.onclick = (e) => {
      e.stopPropagation();
      settingsLoc = l.id; view = "locsettings"; renderTabs(); render();
    };
    d.prepend(gear);
    d.title = "click: open · right-click: rescan · shift-click: delete";
    d.oncontextmenu = async (e) => {
      e.preventDefault();
      await mut("locations.fullRescan", {library_id: lib, location_id: l.id});
      toast("rescan started");
    };
    d.onclick = async (e) => {
      if (e.shiftKey) {
        if (confirm(`remove location ${d.textContent}?`)) {
          await mut("locations.delete", {library_id: lib, id: l.id});
          if (loc === l.id) loc = null;
          loadLocs();
        }
        return;
      }
      loc = l.id; curPath = "/"; view = "explorer";
      renderTabs(); render(); loadLocs();
    };
    el.appendChild(d);
  }
}

async function loadTags() {
  if (!lib) return;
  allTags = await q("tags.list", {library_id: lib});
  const el = document.getElementById("tags"); el.innerHTML = "";
  for (const t of allTags) {
    const d = document.createElement("span");
    d.className = "tagchip" + (tagFilter === t.id ? " on" : "");
    d.textContent = t.name;
    if (t.color) d.style.borderLeft = `4px solid ${esc(t.color)}`;
    d.onclick = () => {
      tagFilter = tagFilter === t.id ? null : t.id; loadTags(); render();
    };
    d.oncontextmenu = async (e) => {
      e.preventDefault();
      if (confirm(`delete tag "${t.name}"?`)) {
        await mut("tags.delete", {library_id: lib, id: t.id});
        if (tagFilter === t.id) tagFilter = null;
        loadTags();
      }
    };
    el.appendChild(d);
  }
}

async function loadStats() {
  if (!lib) return;
  const s = await q("library.statistics", {library_id: lib});
  document.getElementById("stats").innerHTML =
    `<div class="kv">paths: <b>${s.total_paths ?? s.file_paths ?? "?"}</b></div>` +
    `<div class="kv">objects: <b>${s.total_objects ?? s.objects ?? "?"}</b></div>` +
    `<div class="kv">bytes: <b>${fmtBytes(s.total_bytes_used ?? s.total_bytes ?? 0)}</b></div>`;
}

function render() {
  document.getElementById("inspector").style.display = "none";
  hideCtx();
  ({explorer: browse, browse: renderEphemeral, dups: renderDups,
    neardups: renderNearDups,
    jobs: renderJobs, p2p: renderP2P, settings: renderSettings,
    locsettings: renderLocSettings}[view])();
}

// ---- Ephemeral browsing (non-indexed paths, non_indexed.rs) ----------
let ephPath = "/";
async function renderEphemeral() {
  const main = document.getElementById("main");
  main.innerHTML = `
    <h1>Browse (not indexed)</h1>
    <p><input id="ephpath" value="${esc(ephPath)}" style="width:60%"/>
       <button id="ephgo">go</button>
       <span class="muted">any directory on this node — nothing is
       written to the library</span></p>
    <div id="grid"></div>`;
  const go = async () => {
    ephPath = document.getElementById("ephpath").value.trim() || "/";
    let entries;
    try {
      entries = await q("search.ephemeralPaths",
                        {path: ephPath, with_thumbnails: true});
    } catch (e) { toast(String(e)); return; }
    const grid = document.getElementById("grid");
    grid.innerHTML = "";
    if (ephPath !== "/") {
      grid.appendChild(cell({name: "..", is_dir: 1}, () => {
        ephPath = ephPath.replace(/\/[^/]+\/?$/, "") || "/";
        document.getElementById("ephpath").value = ephPath;
        go();
      }));
    }
    for (const e of entries) {
      const r = {name: e.name, extension: e.extension,
                 is_dir: e.is_dir, cas_id: e.cas_id, id: -1};
      grid.appendChild(cell(r, () => {
        if (e.is_dir) {
          ephPath = e.path;
          document.getElementById("ephpath").value = ephPath;
          go();
        }
      }));
    }
  };
  document.getElementById("ephgo").onclick = go;
  document.getElementById("ephpath").onkeydown =
    (e) => { if (e.key === "Enter") go(); };
  go();
}

// ---- Explorer --------------------------------------------------------
async function browse() {
  const main = document.getElementById("main");
  if (!lib || loc == null) { main.innerHTML =
    "<div class='muted'>create a library and add a location</div>"; return; }
  const searchText = document.getElementById("search").value.trim();
  const filter = {location_id: loc};
  if (searchText) filter.search = searchText;
  else filter.materialized_path = curPath;
  if (tagFilter != null) filter.tags = [tagFilter];
  const [rows, count] = await Promise.all([
    q("search.paths", {library_id: lib, take: 400, filter}),
    q("search.pathsCount", {library_id: lib, filter}),
  ]);
  main.innerHTML =
    `<div class="muted" style="margin-bottom:10px">location ${loc} · ` +
    `${searchText ? `search "${esc(searchText)}"` : esc(curPath)} · ` +
    `${count} paths</div><div id="grid"></div>`;
  const grid = document.getElementById("grid");
  if (!searchText && curPath !== "/") {
    grid.appendChild(cell({name: "..", is_dir: 1}, () => {
      curPath = curPath.replace(/[^/]+\/$/, ""); browse();
    }));
  }
  let items = rows.items || rows;
  if (favOnly) {
    const favs = await q("search.objects",
      {library_id: lib, take: 500, filter: {favorite: true}});
    const favIds = new Set((favs.items || []).map(o => o.id));
    items = items.filter(r => favIds.has(r.object_id));
  }
  if (viewMode === "media") {
    const mediaExt = new Set(["png","jpg","jpeg","gif","webp","bmp","tiff",
      "tif","heic","heif","avif","svg","svgz","pdf","avi","mp4","mkv",
      "mov","webm"]);
    items = items.filter(r => !r.is_dir
      && mediaExt.has((r.extension || "").toLowerCase()));
    grid.className = "media";
  } else grid.className = "";
  lastRows = sortItems(items);
  if (viewMode === "list") {
    main.removeChild(grid);
    main.appendChild(buildListTable(!searchText && curPath !== "/"));
  } else {
    items = lastRows;
    for (const r of items) grid.appendChild(cell(r, null));
  }
}

function sortItems(items) {
  if (viewMode !== "list" || !sortKey) return items;
  const keyf = {name: r => (r.name || "").toLowerCase(),
                kind: r => r.is_dir ? "" : (r.extension || ""),
                size: r => r.size_in_bytes || 0,
                modified: r => r.date_modified || 0}[sortKey];
  return [...items].sort((a, b) => {
    const ka = keyf(a), kb = keyf(b);
    return (ka < kb ? -1 : ka > kb ? 1 : 0) * sortDir;
  });
}

function buildListTable(showUp) {
  // Header clicks re-sort lastRows CLIENT-SIDE and swap the table in
  // place — no refetch (same repaint-in-place rule as selection).
  const tbl = document.createElement("table");
  const hdr = document.createElement("tr");
  hdr.innerHTML = "<th></th>";
  for (const k of ["name", "kind", "size", "modified"]) {
    const th = document.createElement("th");
    th.style.cursor = "pointer";
    th.textContent = k + (sortKey === k
      ? (sortDir > 0 ? " ↑" : " ↓") : "");
    th.onclick = () => {
      sortDir = sortKey === k ? -sortDir : 1;
      sortKey = k;
      lastRows = sortItems(lastRows);
      tbl.replaceWith(buildListTable(showUp));
    };
    hdr.appendChild(th);
  }
  tbl.appendChild(hdr);
  if (showUp) {
    const up = document.createElement("tr");
    up.className = "row";
    up.innerHTML = "<td>📁</td><td>..</td><td></td><td></td><td></td>";
    up.onclick = () => { curPath = curPath.replace(/[^/]+\/$/, "");
                         browse(); };
    tbl.appendChild(up);
  }
  for (const r of lastRows) tbl.appendChild(listRow(r));
  return tbl;
}

function openEntry(r) {
  if (r.is_dir) {
    curPath = r.materialized_path + r.name + "/";
    document.getElementById("search").value = ""; clearSel(); browse();
  } else inspect(r);
}

// ---- multi-select + context menu -------------------------------------
function clearSel() { selection.clear(); lastClickId = null; }
function updateSelClasses() {
  // selection changes repaint in place — no refetch, no DOM rebuild
  document.querySelectorAll("[data-fpid]").forEach(el =>
    el.classList.toggle("sel", selection.has(+el.dataset.fpid)));
}
function entryClick(r, e) {
  if (e.shiftKey && lastClickId != null) {
    const ids = lastRows.map(x => x.id);
    const a = ids.indexOf(lastClickId), b = ids.indexOf(r.id);
    if (a >= 0 && b >= 0) {
      for (let k = Math.min(a, b); k <= Math.max(a, b); k++)
        selection.add(ids[k]);
    }
    updateSelClasses();
  } else if (e.ctrlKey || e.metaKey) {
    selection.has(r.id) ? selection.delete(r.id) : selection.add(r.id);
    lastClickId = r.id;
    updateSelClasses();
  } else {
    selection.clear(); selection.add(r.id); lastClickId = r.id;
    updateSelClasses();
    openEntry(r);
  }
}
function selRows() {
  const rows = lastRows.filter(r => selection.has(r.id) && !r.is_dir);
  return rows.length ? rows : [];
}
function hideCtx() {
  const m = document.getElementById("ctxmenu");
  if (m) m.style.display = "none";
}
document.addEventListener("click", hideCtx);
document.addEventListener("keydown", (e) => {
  if (e.key === "Escape") { clearSel(); hideCtx(); updateSelClasses(); }
});
function showCtx(r, e) {
  e.preventDefault();
  if (!selection.has(r.id)) {
    selection.clear(); selection.add(r.id); lastClickId = r.id;
    updateSelClasses();
  }
  const m = document.getElementById("ctxmenu");
  const rows = selRows();
  const n = rows.length;
  // Directory-only selection: file operations have nothing to act on,
  // so offer navigation alone instead of "(0)" no-op actions.
  const items = n === 0 ? [["Open", () => openEntry(r)]] : [
    ["Open / inspect", () => openEntry(r)],
    ["sep"],
    [`Copy (${n})`, () => { clipboard = {op: "copy",
       ids: rows.map(x => x.id), locId: loc}; pasteBtn(); }],
    [`Cut (${n})`, () => { clipboard = {op: "cut",
       ids: rows.map(x => x.id), locId: loc}; pasteBtn(); }],
    [`Duplicate (${n})`, async () => {
       await mut("files.duplicateFiles", {library_id: lib,
         location_id: loc, file_path_ids: rows.map(x => x.id)});
       toast("duplicating…"); }],
    ["sep"],
    [`★ Favorite (${n})`, async () => {
       for (const x of rows) if (x.object_id != null)
         await mut("files.setFavorite",
                   {library_id: lib, id: x.object_id, favorite: true});
       toast("favorited"); }],
    [`Tag… (${n})`, async () => {
       const nm = prompt("tag name" + (allTags.length
         ? ` (existing: ${allTags.map(t => t.name).join(", ")})` : ""));
       if (!nm) return;
       let t = allTags.find(x => x.name === nm);
       if (!t) t = await mut("tags.create",
                             {library_id: lib, name: nm, color: null});
       for (const x of rows) if (x.object_id != null)
         await mut("tags.assign", {library_id: lib, tag_id: t.id,
                                   object_id: x.object_id});
       toast(`tagged ${n}`); loadTags(); }],
    [`Validate (${n})`, async () => {
       await mut("jobs.objectValidator",
                 {library_id: lib, id: loc, mode: "fill"});
       toast("validator started"); }],
    ["sep"],
    [`Delete (${n})`, async () => {
       if (!confirm(`delete ${n} file(s)?`)) return;
       await mut("files.deleteFiles", {library_id: lib, location_id: loc,
         file_path_ids: rows.map(x => x.id)});
       toast("deleting…"); clearSel();
       setTimeout(browse, 400); }],
    [`Erase securely (${n})`, async () => {
       if (!confirm(`overwrite + delete ${n} file(s)? irreversible`))
         return;
       await mut("files.eraseFiles", {library_id: lib, location_id: loc,
         file_path_ids: rows.map(x => x.id), passes: 1});
       toast("erasing…"); clearSel();
       setTimeout(browse, 600); }],
    ["sep"],
    [`Encrypt… (${n})`, async () => {
       const pw = prompt("encryption password"); if (!pw) return;
       await mut("files.encryptFiles", {library_id: lib,
         location_id: loc, file_path_ids: rows.map(x => x.id),
         password: pw});
       toast("encrypting…"); setTimeout(browse, 600); }],
    [`Decrypt… (${n})`, async () => {
       const pw = prompt("decryption password"); if (!pw) return;
       await mut("files.decryptFiles", {library_id: lib,
         location_id: loc, file_path_ids: rows.map(x => x.id),
         password: pw});
       toast("decrypting…"); setTimeout(browse, 600); }],
  ];
  m.innerHTML = "";
  for (const [label, fn] of items) {
    if (label === "sep") {
      const s = document.createElement("div"); s.className = "sep";
      m.appendChild(s); continue;
    }
    const d = document.createElement("div");
    d.className = "mi"; d.textContent = label;
    d.onclick = (ev) => { ev.stopPropagation(); hideCtx(); fn(); };
    m.appendChild(d);
  }
  m.style.left = Math.min(e.clientX, innerWidth - 180) + "px";
  m.style.top = Math.min(e.clientY, innerHeight - items.length * 28) + "px";
  m.style.display = "block";
}
function pasteBtn() {
  const b = document.getElementById("pastebtn");
  b.style.display = clipboard ? "" : "none";
  if (clipboard) b.textContent =
    `paste ${clipboard.ids.length} (${clipboard.op})`;
}
async function doPaste() {
  if (!clipboard || loc == null) return;
  const rel = curPath === "/" ? "" : curPath.slice(1);
  const input = {library_id: lib, source_location_id: clipboard.locId,
    sources_file_path_ids: clipboard.ids, target_location_id: loc,
    target_location_relative_directory_path: rel};
  await mut(clipboard.op === "cut" ? "files.cutFiles" : "files.copyFiles",
            input);
  toast(clipboard.op === "cut" ? "moving…" : "copying…");
  if (clipboard.op === "cut") clipboard = null;
  pasteBtn();
  setTimeout(browse, 500);
}

// ---- drag & drop: drag files onto a folder to move them --------------
function wireDnD(el, r) {
  if (!r.is_dir) {
    el.draggable = true;
    el.ondragstart = (e) => {
      if (!selection.has(r.id)) {
        selection.clear(); selection.add(r.id); updateSelClasses();
      }
      e.dataTransfer.setData("text/sdtpu-ids",
        JSON.stringify(selRows().map(x => x.id)));
      e.dataTransfer.effectAllowed = "move";
    };
  } else {
    el.ondragover = (e) => { e.preventDefault(); el.style.outline =
      "2px dashed #3b82f6"; };
    el.ondragleave = () => { el.style.outline = ""; };
    el.ondrop = async (e) => {
      e.preventDefault(); el.style.outline = "";
      let ids;
      try { ids = JSON.parse(e.dataTransfer.getData("text/sdtpu-ids")); }
      catch { return; }
      if (!ids || !ids.length) return;
      const rel = (r.materialized_path + r.name + "/").replace(/^\//, "");
      await mut("files.cutFiles", {library_id: lib,
        source_location_id: loc, sources_file_path_ids: ids,
        target_location_id: loc,
        target_location_relative_directory_path: rel});
      toast(`moving ${ids.length} into ${r.name}/`);
      clearSel();
      setTimeout(browse, 500);
    };
  }
}

function listRow(r) {
  const tr = document.createElement("tr");
  tr.className = "row" + (selection.has(r.id) ? " sel" : "");
  const kindName = r.is_dir ? "folder" : (r.extension || "file");
  const size = r.is_dir ? "" : fmtBytes(r.size_in_bytes || 0);
  const dm = r.date_modified
    ? new Date(r.date_modified * 1000).toISOString().slice(0, 16)
        .replace("T", " ") : "";
  tr.dataset.fpid = r.id;
  tr.innerHTML = `<td>${r.is_dir ? "📁" : "🗎"}</td>` +
    `<td>${esc(r.name)}${r.extension ? "." + esc(r.extension) : ""}</td>` +
    `<td>${esc(kindName)}</td><td>${size}</td><td>${dm}</td>`;
  tr.onclick = (e) => entryClick(r, e);
  tr.ondblclick = () => openEntry(r);
  tr.oncontextmenu = (e) => showCtx(r, e);
  wireDnD(tr, r);
  return tr;
}
function cell(r, onclick) {
  const c = document.createElement("div"); c.className = "cell";
  if (!onclick) c.dataset.fpid = r.id;
  if (selection.has(r.id) || (selected && selected.id === r.id))
    c.className += " sel";
  const t = document.createElement("div"); t.className = "thumb";
  if (r.cas_id) {
    const img = document.createElement("img");
    img.src = `/spacedrive/thumbnail/${r.cas_id}.webp`;
    img.onerror = () => { img.remove(); t.textContent = "🗎"; };
    t.appendChild(img);
  } else t.textContent = r.is_dir ? "📁" : "🗎";
  const n = document.createElement("div"); n.className = "nm";
  n.textContent = r.name + (r.extension ? "." + r.extension : "");
  c.appendChild(t); c.appendChild(n);
  if (onclick) c.onclick = onclick;       // the ".." up-cell
  else {
    c.onclick = (e) => entryClick(r, e);
    c.ondblclick = () => openEntry(r);
    c.oncontextmenu = (e) => showCtx(r, e);
    wireDnD(c, r);
  }
  return c;
}

// ---- Per-location settings (indexer-rule editor, rescans) ------------
const RULE_KINDS = [[0, "accept glob"], [1, "reject glob"],
  [2, "accept if children"], [3, "reject if children"]];
async function renderLocSettings() {
  const main = document.getElementById("main");
  if (!lib || settingsLoc == null) {
    main.innerHTML = "<div class='muted'>no location selected</div>"; return;
  }
  const [l, allRules] = await Promise.all([
    q("locations.getWithRules",
      {library_id: lib, location_id: settingsLoc}),
    q("locations.indexer_rules.list", {library_id: lib}),
  ]);
  if (!l) { main.innerHTML = "<div class='muted'>gone</div>"; return; }
  const attached = new Set((l.indexer_rules || []).map(r => r.id));
  main.innerHTML = `
    <h1>Location settings — ${esc(l.name || l.path)}</h1>
    <div class="kv">path: <b>${esc(l.path)}</b></div>
    <div class="kv">id: <b>${l.id}</b> · hidden: <b>${l.hidden ? "yes"
      : "no"}</b></div>
    <p>
      <input id="lsname" value="${esc(l.name || "")}"
             placeholder="display name"/>
      <button id="lsrename">rename</button>
      <button id="lshide" class="ghost">${l.hidden ? "unhide" : "hide"}
      </button>
    </p>
    <p>
      <button id="lsfull">full rescan</button>
      <button id="lsquick" class="ghost">quick rescan</button>
      <button id="lsdelete" class="danger">remove location</button>
    </p>
    <h2>Indexer rules</h2>
    <div class="muted">checked rules apply when this location is
      indexed</div>
    <div id="lsrules"></div>
    <h3>New rule</h3>
    <p>
      <input id="nrname" placeholder="rule name" style="width:130px"/>
      <select id="nrkind">${RULE_KINDS.map(([v, t]) =>
        `<option value="${v}">${t}</option>`).join("")}</select>
      <input id="nrglob" placeholder="glob, e.g. **/*.tmp"
             style="width:160px"/>
      <button id="nradd">add rule</button>
    </p>`;
  const rulesEl = document.getElementById("lsrules");
  for (const r of allRules) {
    const d = document.createElement("div"); d.className = "kv";
    const cb = document.createElement("input");
    cb.type = "checkbox"; cb.checked = attached.has(r.id);
    cb.onchange = async () => {
      const ids = new Set(attached);
      cb.checked ? ids.add(r.id) : ids.delete(r.id);
      await mut("locations.update", {library_id: lib, id: l.id,
        indexer_rules_ids: [...ids]});
      renderLocSettings();
    };
    d.appendChild(cb);
    d.append(` ${r.name} `);
    if (r.default_rule) {
      const s = document.createElement("span");
      s.className = "muted"; s.textContent = "(system)";
      d.appendChild(s);
    } else {
      const del = document.createElement("button");
      del.className = "danger"; del.textContent = "×";
      del.onclick = async () => {
        await mut("locations.indexer_rules.delete",
                  {library_id: lib, id: r.id});
        renderLocSettings();
      };
      d.appendChild(del);
    }
    rulesEl.appendChild(d);
  }
  document.getElementById("lsrename").onclick = async () => {
    await mut("locations.update", {library_id: lib, id: l.id,
      name: document.getElementById("lsname").value});
    loadLocs(); renderLocSettings();
  };
  document.getElementById("lshide").onclick = async () => {
    await mut("locations.update", {library_id: lib, id: l.id,
      hidden: l.hidden ? 0 : 1});
    renderLocSettings();
  };
  document.getElementById("lsfull").onclick = async () => {
    await mut("locations.fullRescan",
              {library_id: lib, location_id: l.id});
    toast("full rescan started");
  };
  document.getElementById("lsquick").onclick = async () => {
    await mut("locations.quickRescan",
              {library_id: lib, location_id: l.id, sub_path: "/"});
    toast("quick rescan started");
  };
  document.getElementById("lsdelete").onclick = async () => {
    if (!confirm("remove this location from the library?")) return;
    await mut("locations.delete", {library_id: lib, id: l.id});
    if (loc === l.id) loc = null;
    settingsLoc = null; view = "explorer"; renderTabs();
    loadLocs(); render();
  };
  document.getElementById("nradd").onclick = async () => {
    const name = document.getElementById("nrname").value.trim();
    const glob = document.getElementById("nrglob").value.trim();
    const kind = parseInt(document.getElementById("nrkind").value);
    if (!name || !glob) { toast("name + glob required"); return; }
    await mut("locations.indexer_rules.create", {library_id: lib,
      name, rules: [[kind, [glob]]]});
    renderLocSettings();
  };
}

// ---- Inspector (file detail panel) -----------------------------------
async function inspect(r) {
  selected = r;
  const el = document.getElementById("inspector");
  el.style.display = "block";
  const name = r.name + (r.extension ? "." + r.extension : "");
  const size = r.size_in_bytes_bytes ? parseInt(r.size_in_bytes_bytes, 16) ||
               r.size_in_bytes : r.size_in_bytes;
  let html = `<h3>${esc(name)}</h3>` +
    `<div class="kv">size: <b>${fmtBytes(size)}</b></div>` +
    `<div class="kv">cas_id: <b>${esc(r.cas_id || "—")}</b></div>` +
    `<div class="kv">object: <b>${r.object_id ?? "—"}</b></div>` +
    `<div class="kv">path: <b>${esc(r.materialized_path)}</b></div>`;
  let obj = null;
  if (r.object_id != null) {
    obj = await q("files.get", {library_id: lib, id: r.object_id});
    if (obj) {
      html += `<div class="kv">kind: <b>${obj.kind}</b></div>` +
        `<div class="kv">note: <b>${esc(obj.note || "—")}</b></div>`;
    }
  }
  html += `<div id="itags"></div><div id="iexif"></div>
    <div style="margin-top:8px">
      <button id="ifav" class="ghost">${obj && obj.favorite ? "★" : "☆"} favorite</button>
      <button id="irename" class="ghost">rename</button>
      <button id="inote" class="ghost">note</button>
      <button id="idup" class="ghost">duplicate</button>
      <button id="idel" class="danger">delete</button>
    </div>`;
  el.innerHTML = html;
  if (r.object_id != null) {
    const mine = await q("tags.getForObject",
      {library_id: lib, object_id: r.object_id});
    const mineIds = new Set(mine.map(t => t.id));
    const tl = document.getElementById("itags");
    tl.innerHTML = "<h3>tags</h3>";
    for (const t of allTags) {
      const chip = document.createElement("span");
      chip.className = "tagchip" + (mineIds.has(t.id) ? " on" : "");
      chip.textContent = t.name;
      chip.onclick = async () => {
        await mut("tags.assign", {library_id: lib, tag_id: t.id,
          object_id: r.object_id, unassign: mineIds.has(t.id)});
        inspect(r);
      };
      tl.appendChild(chip);
    }
    const md = await q("files.getMediaData", {library_id: lib,
                                              id: r.object_id});
    if (md) {
      if (md.stream_data) {
        // audio/video container metadata rides as JSON
        try { Object.assign(md, JSON.parse(md.stream_data)); } catch {}
        delete md.stream_data;
      }
      const ex = document.getElementById("iexif");
      ex.innerHTML = "<h3>media data</h3>" +
        Object.entries(md).filter(([k, v]) => v != null && k !== "phash" &&
                                  k !== "object_id" && k !== "id")
          .map(([k, v]) => `<div class="kv">${esc(k)}: <b>${esc(v)}</b></div>`)
          .join("");
    }
  }
  document.getElementById("ifav").onclick = async () => {
    if (r.object_id == null) return toast("not identified yet");
    await mut("files.setFavorite", {library_id: lib, id: r.object_id,
      favorite: !(obj && obj.favorite)});
    inspect(r);
  };
  document.getElementById("irename").onclick = async () => {
    const nn = prompt("new name", name); if (!nn || nn === name) return;
    try {
      await mut("files.renameFile", {library_id: lib, file_path_id: r.id,
        new_name: nn});
      toast("renamed"); browse();
    } catch (e) { toast(e.message); }
  };
  document.getElementById("inote").onclick = async () => {
    if (r.object_id == null) return toast("not identified yet");
    const note = prompt("note", obj && obj.note || "");
    if (note === null) return;
    await mut("files.setNote", {library_id: lib, id: r.object_id, note});
    inspect(r);
  };
  document.getElementById("idup").onclick = async () => {
    await mut("files.duplicateFiles", {library_id: lib, location_id: loc,
      file_path_ids: [r.id]});
    toast("duplicating…");
  };
  document.getElementById("idel").onclick = async () => {
    if (!confirm(`delete ${name}?`)) return;
    await mut("files.deleteFiles", {library_id: lib, location_id: loc,
      file_path_ids: [r.id]});
    el.style.display = "none"; selected = null;
  };
}

// ---- Duplicates ------------------------------------------------------
async function renderDups() {
  const main = document.getElementById("main");
  if (!lib) return;
  const groups = await q("search.duplicates",
    {library_id: lib, location_id: loc});
  const total = groups.reduce((a, g) => a + (g.reclaimable_bytes || 0), 0);
  main.innerHTML = `<h3>Exact duplicates (by CAS ID)</h3>
    <div class="muted">${groups.length} groups · ` +
    `${fmtBytes(total)} reclaimable</div>
    <table><tr><th>cas_id</th><th>copies</th><th>total</th>
    <th>paths</th></tr>` +
    groups.map(g => `<tr><td>${esc(g.cas_id)}</td><td>${g.count}</td>
      <td>${fmtBytes(g.total_bytes)}</td>
      <td class="muted">${g.paths.map(esc).join("<br>")}</td></tr>`).join("")
    + "</table>";
}

// ---- Near-duplicates (device-backed analytics) -----------------------
async function renderNearDups() {
  const main = document.getElementById("main");
  if (!lib) return;
  const pairs = await q("search.nearDuplicates",
    {library_id: lib, max_distance: 10});
  main.innerHTML = `<h3>Near-duplicate images (pHash Hamming ≤ 10)</h3>
    <div style="margin:6px 0">
      <button id="rundet">run detector on location ${loc ?? "—"}</button>
      <span class="muted">batched DCT pHash + tiled Hamming all-pairs on
      the device; LSH bucketing past 100k images</span></div>
    <table><tr><th>distance</th><th>a</th><th>b</th></tr>` +
    pairs.map(p => `<tr><td>${p.distance}</td>
      <td class="muted">${p.paths_a.map(esc).join("<br>")}</td>
      <td class="muted">${p.paths_b.map(esc).join("<br>")}</td></tr>`)
      .join("") + "</table>";
  document.getElementById("rundet").onclick = async () => {
    if (loc == null) return toast("select a location first");
    await mut("jobs.nearDupDetector", {library_id: lib, id: loc});
    toast("near-dup detector started");
  };
}

// ---- Jobs console ----------------------------------------------------
const JSTATUS = {0:"queued",1:"running",2:"completed",3:"cancelled",
                 4:"failed",5:"paused",6:"completed+errors"};
async function renderJobs() {
  const main = document.getElementById("main");
  if (!lib) return;
  const reports = await q("jobs.reports", {library_id: lib});
  main.innerHTML = `<h3>Jobs</h3>
    <div style="margin:6px 0">
      <button id="jid">identify</button>
      <button id="jval">validate</button>
      <button id="jverify" class="ghost">verify (bit-rot)</button>
      <button id="jthumb" class="ghost">thumbnails</button>
      <button id="jclear" class="ghost">clear finished</button>
    </div>
    <table><tr><th>name</th><th>status</th><th>progress</th><th>created</th>
    <th></th></tr>` +
    reports.map(j => {
      const pct = j.task_count ?
        Math.round(100 * (j.completed_task_count || 0) / j.task_count) : 0;
      const running = j.status === 1, paused = j.status === 5;
      return `<tr><td>${esc(j.name)}</td><td>${JSTATUS[j.status] ?? j.status}</td>
        <td>${pct}% (${j.completed_task_count || 0}/${j.task_count || 0})</td>
        <td class="muted">${new Date((j.date_created||0)*1000)
          .toLocaleTimeString()}</td>
        <td>${running ? `<button class="ghost" onclick="jobCtl('pause','${j.id}')">⏸</button>` : ""}
            ${paused ? `<button class="ghost" onclick="jobCtl('resume','${j.id}')">▶</button>` : ""}
            ${(running || paused) ? `<button class="danger" onclick="jobCtl('cancel','${j.id}')">✕</button>` : ""}
        </td></tr>`;
    }).join("") + "</table>";
  const need = () => loc == null ? (toast("select a location"), false) : true;
  document.getElementById("jid").onclick = async () =>
    need() && (await mut("jobs.identifyUniqueFiles", {library_id: lib, id: loc}),
               renderJobs());
  document.getElementById("jval").onclick = async () =>
    need() && (await mut("jobs.objectValidator", {library_id: lib, id: loc}),
               renderJobs());
  document.getElementById("jverify").onclick = async () =>
    need() && (await mut("jobs.objectValidator",
                         {library_id: lib, id: loc, mode: "verify"}),
               renderJobs());
  document.getElementById("jthumb").onclick = async () =>
    need() && (await mut("jobs.generateThumbsForLocation",
                         {library_id: lib, id: loc}), renderJobs());
  document.getElementById("jclear").onclick = async () => {
    await mut("jobs.clearAll", {library_id: lib}); renderJobs();
  };
}
window.jobCtl = async (op, id) => {
  await mut("jobs." + op, {library_id: lib, id});
  renderJobs();
};

// ---- P2P -------------------------------------------------------------
async function renderP2P() {
  const main = document.getElementById("main");
  const st = await q("p2p.state");
  if (!st.enabled) {
    main.innerHTML = "<div class='muted'>p2p is not started</div>"; return;
  }
  main.innerHTML = `<h3>P2P</h3>
    <div class="kv">identity: <b>${esc(st.identity.slice(0, 24))}…</b>
      · port <b>${st.port}</b></div>
    <h3>Peers</h3>
    <table><tr><th>identity</th><th>addr</th><th></th></tr>` +
    st.peers.map(p => {
      // Beacon payloads are peer-controlled: port must never reach
      // innerHTML/onclick as a string (stored-XSS vector).
      const port = Number(p.port) || 0;
      return `<tr>
      <td class="muted">${esc(p.identity.slice(0, 24))}…</td>
      <td>${esc(p.addr)}:${port}</td>
      <td><button class="ghost" onclick="p2pPing('${esc(p.addr)}',${port})">ping</button>
          <button class="ghost" onclick="p2pPair('${esc(p.addr)}',${port})">pair</button>
          <button onclick="p2pDrop('${esc(p.addr)}',${port})">spacedrop</button>
      </td></tr>`;}).join("") + `</table>
    <div class="muted" style="margin-top:8px">spacedrop sends an absolute
    file path from this node; pairing joins the current library.</div>`;
}
window.p2pPing = async (addr, port) => {
  try { await mut("p2p.debugPing", {addr, port}); toast("pong"); }
  catch (e) { toast(e.message); }
};
window.p2pPair = async (addr, port) => {
  try {
    await mut("p2p.pair", {library_id: lib, addr, port});
    toast("paired");
  } catch (e) { toast(e.message); }
};
window.p2pDrop = async (addr, port) => {
  const file_path = prompt("absolute path of file to send");
  if (!file_path) return;
  try {
    await mut("p2p.spacedrop", {addr, port, file_path});
    toast("spacedrop sent");
  } catch (e) { toast(e.message); }
};

// ---- Settings --------------------------------------------------------
async function renderSettings() {
  const main = document.getElementById("main");
  if (!lib) return;
  const [stats, cats, vols, keysSetup, backups, prefs] = await Promise.all([
    q("library.statistics", {library_id: lib}),
    q("categories.list", {library_id: lib}),
    q("volumes.list"),
    q("keys.isSetup", {library_id: lib}),
    q("backups.getAll"),
    q("preferences.get", {library_id: lib}),
  ]);
  const catRows = Object.entries(cats).filter(([, n]) => n > 0)
    .map(([k, n]) => `<tr><td>${esc(k)}</td><td>${n}</td></tr>`).join("");
  main.innerHTML = `<h3>Statistics</h3>` +
    Object.entries(stats).map(([k, v]) =>
      `<div class="kv">${esc(k)}: <b>${esc(v)}</b></div>`).join("") +
    `<h3>Categories</h3><table>${catRows}</table>
    <h3>Volumes</h3><table>` +
    vols.map(v => `<tr><td>${esc(v.name || v.mount_point)}</td>
      <td>${fmtBytes(v.available_capacity)} free of
          ${fmtBytes(v.total_capacity)}</td></tr>`).join("") + `</table>
    <h3>Key manager</h3><div id="keys"></div>
    <h3>Backups</h3>
    <div><button id="dobackup">backup library now</button></div>
    <table>` + (backups.backups || backups).map(b =>
      `<tr><td>${esc(b.id || b.path || JSON.stringify(b)).slice(0, 60)}</td>
       <td class="muted">${esc(b.timestamp || b.date || "")}</td>
       <td><button class="ghost brestore" data-bid="${esc(b.id)}">restore
       </button><button class="danger bdelete" data-bid="${esc(b.id)}">×
       </button></td></tr>`)
      .join("") + `</table>
    <h3>Preferences</h3>
    <div class="kv">stored keys: <b>${Object.keys(prefs || {}).length}</b>
      <button id="setpref" class="ghost">set pref</button></div>
    <h3>Notifications</h3>
    <button id="notifytest" class="ghost">send test notification</button>`;

  const keysEl = document.getElementById("keys");
  if (!keysSetup) {
    keysEl.innerHTML = `<button id="ksetup">set up key manager</button>`;
    document.getElementById("ksetup").onclick = async () => {
      const pw = prompt("master password"); if (!pw) return;
      await mut("keys.setup", {library_id: lib, password: pw});
      renderSettings();
    };
  } else {
    const unlocked = await q("keys.isUnlocked", {library_id: lib});
    if (!unlocked) {
      keysEl.innerHTML = `<button id="kunlock">unlock</button>`;
      document.getElementById("kunlock").onclick = async () => {
        const pw = prompt("master password"); if (!pw) return;
        try {
          await mut("keys.unlock", {library_id: lib, password: pw});
          renderSettings();
        } catch (e) { toast(e.message); }
      };
    } else {
      const keys = await q("keys.list", {library_id: lib});
      keysEl.innerHTML = keys.map(k =>
        `<div class="kv">${esc(k.uuid || k.id)} ` +
        `${k.mounted ? "(mounted)" : ""}</div>`).join("") +
        `<button id="kadd" class="ghost">add key</button>
         <button id="klock" class="ghost">lock</button>`;
      document.getElementById("kadd").onclick = async () => {
        const pw = prompt("new key password"); if (!pw) return;
        await mut("keys.add", {library_id: lib, password: pw});
        renderSettings();
      };
      document.getElementById("klock").onclick = async () => {
        await mut("keys.lock", {library_id: lib}); renderSettings();
      };
    }
  }
  document.getElementById("dobackup").onclick = async () => {
    await mut("backups.backup", {library_id: lib});
    toast("backup written"); renderSettings();
  };
  document.querySelectorAll(".brestore").forEach(b => b.onclick =
    async () => {
      if (!confirm("restore this backup over the current library?"))
        return;
      await mut("backups.restore", {backup_id: b.dataset.bid});
      toast("backup restored"); loadAll();
    });
  document.querySelectorAll(".bdelete").forEach(b => b.onclick =
    async () => {
      await mut("backups.delete", {backup_id: b.dataset.bid});
      renderSettings();
    });
  document.getElementById("setpref").onclick = async () => {
    const k = prompt("preference key"); if (!k) return;
    const v = prompt("value");
    await mut("preferences.update", {library_id: lib, values: {[k]: v}});
    renderSettings();
  };
  document.getElementById("notifytest").onclick = () =>
    mut("notifications.test");
}

// ---- chrome wiring ---------------------------------------------------
document.getElementById("newlib").onclick = async () => {
  const name = prompt("library name"); if (!name) return;
  await mut("library.create", {name}); lib = null; loadLibs();
};
document.getElementById("newloc").onclick = async () => {
  const path = prompt("absolute path to index"); if (!path || !lib) return;
  await mut("locations.create", {library_id: lib, path});
  loadLocs();
};
document.getElementById("newtag").onclick = async () => {
  const name = prompt("tag name"); if (!name || !lib) return;
  const color = prompt("color (css, optional)") || null;
  await mut("tags.create", {library_id: lib, name, color});
  loadTags();
};
document.getElementById("search").oninput = (() => {
  let h; return () => { clearTimeout(h); h = setTimeout(() => {
    if (view !== "explorer") { view = "explorer"; renderTabs(); }
    browse();
  }, 250); };
})();
document.getElementById("favbtn").onclick = () => {
  favOnly = !favOnly;
  document.getElementById("favbtn").className = favOnly ? "" : "ghost";
  if (view === "explorer") browse();
};
function setViewMode(m) {
  viewMode = m;
  for (const [id, mm] of [["vgrid","grid"],["vlist","list"],
                          ["vmedia","media"]])
    document.getElementById(id).className =
      "viewbtn" + (viewMode === mm ? " on" : "");
  if (view === "explorer") browse();
}
document.getElementById("vgrid").onclick = () => setViewMode("grid");
document.getElementById("vlist").onclick = () => setViewMode("list");
document.getElementById("vmedia").onclick = () => setViewMode("media");
document.getElementById("pastebtn").onclick = doPaste;
document.getElementById("newfolder").onclick = async () => {
  if (view !== "explorer") { toast("open the explorer first"); return; }
  if (loc == null) { toast("select a location"); return; }
  const name = prompt("folder name"); if (!name) return;
  await mut("files.createFolder", {library_id: lib, location_id: loc,
    sub_path: curPath, name});
  setTimeout(() => { if (view === "explorer") browse(); }, 300);
};
setViewMode("grid");

sub("jobs.progress", null, (e) => {
  const el = document.getElementById("joblist");
  let row = document.getElementById("job-" + e.id);
  if (!row) {
    row = document.createElement("div"); row.className = "job";
    row.id = "job-" + e.id;
    row.innerHTML = `<span></span><div class="bar"><div></div></div>`;
    el.prepend(row);
  }
  row.querySelector("span").textContent =
    `${e.name || "job"} — ${e.message || ""}`;
  const pct = e.task_count ? (100 * (e.completed_task_count || 0) /
                              e.task_count) : 0;
  row.querySelector(".bar > div").style.width = pct + "%";
  if (e.task_count && e.completed_task_count >= e.task_count)
    setTimeout(() => row.remove(), 4000);
});
sub("invalidation.listen", null, (e) => {
  if (e.key === "search.paths" && view === "explorer") browse();
  if (e.key === "library.list") loadLibs();
  if (e.key === "tags.list") loadTags();
  if (e.key === "jobs.reports" && view === "jobs") renderJobs();
});
sub("notifications.listen", null, (e) => {
  toast(`🔔 ${e.title || ""} ${e.content || e.message || ""}`);
});
sub("p2p.events", null, async (e) => {
  if (e.type === "SpacedropRequest") {
    // The peer-supplied name is untrusted: suggest only its basename,
    // never a path ("../../etc/x" must not prefill the save prompt).
    const safe = (e.name || "spacedrop.bin")
      .split(/[\\/]/).pop().replace(/^\.+/, "") || "spacedrop.bin";
    const ok = confirm(
      `Spacedrop: accept "${safe}" (${e.size} bytes) from ${e.peer}?`);
    // Cancelling/clearing the prompt falls back to the safe name in the
    // current directory — an accepted drop is never silently rejected.
    const path = ok ? (prompt("save as", safe) || safe) : null;
    await mut("p2p.acceptSpacedrop", {id: e.id, path});
  }
});
renderTabs();
loadLibs();
