let reqId = 0, pending = {}, subs = {}, subSpecs = [];
const wsProto = location.protocol === "https:" ? "wss" : "ws";
let ws = null, reconnectDelay = 500;
// wsReady always has a live resolver: awaiting rpc() calls parked
// during a reconnect wake on the SAME promise the next onopen resolves.
let wsReadyResolve = null;
let wsReady = new Promise(r => wsReadyResolve = r);

function connect() {
  ws = new WebSocket(`${wsProto}://${location.host}/rspc`);
  ws.onopen = () => {
    reconnectDelay = 500;
    // standing subscriptions survive reconnects (the standalone-client
    // contract: the UI must keep working across server restarts)
    for (const s of subSpecs) {
      const id = ++reqId; subs[id] = s.cb;
      ws.send(JSON.stringify({id, type: "subscription",
                              path: s.path, input: s.input}));
    }
    wsReadyResolve();
  };
  ws.onmessage = (m) => {
    const f = JSON.parse(m.data);
    if (f.type === "response" && pending[f.id]) {
      pending[f.id].resolve(f.result); delete pending[f.id];
    } else if (f.type === "error" && pending[f.id]) {
      pending[f.id].reject(new Error(f.message)); delete pending[f.id];
    } else if (f.type === "event" && subs[f.id]) {
      subs[f.id](f.data);
    }
  };
  ws.onclose = () => {
    for (const id in pending) {
      pending[id].reject(new Error("connection lost")); delete pending[id];
    }
    subs = {};
    // Park wsReady on a fresh promise NOW (resolver saved for the next
    // onopen): rpc() calls made during the backoff window suspend here
    // instead of sending into the closed socket.
    wsReady = new Promise(r => wsReadyResolve = r);
    toast(`reconnecting in ${Math.round(reconnectDelay / 1000)}s…`);
    setTimeout(connect, reconnectDelay);
    reconnectDelay = Math.min(reconnectDelay * 2, 15000);
  };
}
connect();
async function rpc(type, path, input) {
  await wsReady;
  const id = ++reqId;
  ws.send(JSON.stringify({id, type, path, input}));
  return new Promise((resolve, reject) => pending[id] = {resolve, reject});
}
const q = (p, i) => rpc("query", p, i);
const mut = (p, i) => rpc("mutation", p, i);
function sub(path, input, cb) {
  subSpecs.push({path, input, cb});
  if (ws && ws.readyState === 1) {  // otherwise onopen replays subSpecs
    const id = ++reqId;
    subs[id] = cb;
    ws.send(JSON.stringify({id, type: "subscription", path, input}));
  }
}
function subOnce(path, input, cb) {
  // NOT replayed on reconnect (device-code flows must not silently
  // restart server-side); caller's cb returns true to stop the stream.
  if (!ws || ws.readyState !== 1) { toast("not connected"); return; }
  const id = ++reqId;
  subs[id] = (data) => {
    if (cb(data)) {
      delete subs[id];
      ws.send(JSON.stringify({id, type: "subscriptionStop"}));
    }
  };
  ws.send(JSON.stringify({id, type: "subscription", path, input}));
}
function toast(msg) {
  const t = document.getElementById("toast");
  t.textContent = msg; t.style.display = "block";
  clearTimeout(t._h); t._h = setTimeout(() => t.style.display = "none", 3000);
}
const esc = (s) => String(s ?? "").replace(/[&<>"']/g,
  c => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));
const fmtBytes = (n) => {
  n = Number(n) || 0;
  for (const u of ["B","KiB","MiB","GiB","TiB"]) {
    if (n < 1024 || u === "TiB") return n.toFixed(u==="B"?0:1)+" "+u;
    n /= 1024;
  }
};

let lib = null, loc = null, curPath = "/", view = "explorer";
let selected = null, tagFilter = null, favOnly = false, allTags = [];
let albumFilter = null, spaceFilter = null;  // object-grouping filters
let kindFilter = null;         // ObjectKind filter from the overview
let viewMode = "grid";         // grid | list | media (explorer modes)
let sortKey = null, sortDir = 1;  // list-view column sort
let selection = new Set();     // multi-select: file_path ids
let lastRows = [];             // rows rendered by the last browse()
let lastClickId = null;        // shift-range anchor
let clipboard = null;          // {op: "copy"|"cut", ids, locId}
let settingsLoc = null;        // location id open in per-location settings
let syncSubLib = null;         // library whose sync stream we watch

const TABS = [["overview","Overview"],
              ["explorer","Explorer"],["browse","Browse"],
              ["dups","Duplicates"],
              ["neardups","Near-dups"],["jobs","Jobs"],["p2p","P2P"],
              ["settings","Settings"]];
function renderTabs() {
  const el = document.getElementById("tabs"); el.innerHTML = "";
  for (const [id, label] of TABS) {
    const d = document.createElement("div");
    d.className = "tab" + (view === id ? " sel" : "");
    d.textContent = label;
    d.onclick = () => { view = id; renderTabs(); render(); };
    el.appendChild(d);
  }
}

// ---- Onboarding (create library → add location, the reference's
// interface/app/onboarding flow) ---------------------------------------
function showOnboarding() {
  if (document.getElementById("onboard")) return;
  const o = document.createElement("div");
  o.id = "onboard";
  o.innerHTML = `<div class="card">
    <h1>Welcome to spacedrive-tpu</h1>
    <p class="muted">A library is your private database of every file
      it indexes. Create one, then point it at a folder.</p>
    <h3>1 · Create your library</h3>
    <p><input id="oblib" placeholder="library name" value="My Library"
              style="width:100%"/></p>
    <h3>2 · Add a first location</h3>
    <p><input id="obloc" placeholder="/path/to/files (optional)"
              style="width:100%"/></p>
    <p style="text-align:right"><button id="obgo">Create</button></p>
    <div id="oberr" class="muted"></div>
  </div>`;
  document.body.appendChild(o);
  document.getElementById("obgo").onclick = async () => {
    const name = document.getElementById("oblib").value.trim();
    if (!name) return;
    try {
      const l = await mut("library.create", {name});
      lib = l.uuid;
      const path = document.getElementById("obloc").value.trim();
      if (path) {
        loc = await mut("locations.create", {library_id: lib, path});
        toast("indexing started");
      }
      o.remove(); loadAll();
    } catch (err) {
      document.getElementById("oberr").textContent = String(err);
    }
  };
}

async function loadLibs() {
  const libs = await q("library.list");
  if (!libs.length) showOnboarding();
  const el = document.getElementById("libs"); el.innerHTML = "";
  for (const l of libs) {
    const d = document.createElement("div");
    d.className = "item" + (lib === l.uuid ? " sel" : "");
    d.textContent = l.config ? l.config.name : l.name;
    d.onclick = () => { lib = l.uuid; loadAll(); };
    d.oncontextmenu = async (e) => {
      e.preventDefault();
      if (confirm(`delete library "${d.textContent}"?`)) {
        await mut("library.delete", {id: l.uuid});
        if (lib === l.uuid) lib = null;
        loadLibs();
      }
    };
    el.appendChild(d);
  }
  if (!lib && libs.length) { lib = libs[0].uuid; loadAll(); }
}
function loadAll() {
  loadLibs(); loadLocs(); loadTags(); loadGroupings();
  loadSaved(); loadStats(); render();
}

// ---- albums / spaces (object groupings over the reference's
// schema.prisma:389-411/448-477 models — it ships no UI for them) --
async function loadGroupings() {
  if (!lib) return;
  for (const kind of ["album", "space"]) {
    const rows = await q(`${kind}s.list`, {library_id: lib});
    const el = document.getElementById(kind + "s");
    el.innerHTML = "";
    for (const g of rows) {
      const d = document.createElement("span");
      const active = (kind === "album" ? albumFilter : spaceFilter)
        === g.id;
      d.className = "tagchip" + (active ? " on" : "");
      d.textContent = g.name +
        (g.object_count ? ` (${g.object_count})` : "");
      d.title = "click: filter · dblclick: rename · " +
        "right-click: delete";
      d.onclick = () => {
        if (kind === "album") {
          albumFilter = albumFilter === g.id ? null : g.id;
        } else {
          spaceFilter = spaceFilter === g.id ? null : g.id;
        }
        loadGroupings(); render();
      };
      d.ondblclick = async () => {
        const name = prompt(`${kind} name`, g.name);
        if (!name) return;
        await mut(`${kind}s.update`,
                  {library_id: lib, id: g.id, name});
        loadGroupings();
      };
      d.oncontextmenu = async (e) => {
        e.preventDefault();
        if (confirm(`delete ${kind} \"${g.name}\"?`)) {
          await mut(`${kind}s.delete`, {library_id: lib, id: g.id});
          if (kind === "album" && albumFilter === g.id)
            albumFilter = null;
          if (kind === "space" && spaceFilter === g.id)
            spaceFilter = null;
          loadGroupings(); render();
        }
      };
      el.appendChild(d);
    }
  }
}

// ---- saved searches (stored in library preferences, the reference's
// interface persists view state the same way) --------------------------
async function getSavedSearches() {
  const prefs = await q("preferences.get", {library_id: lib});
  try { return JSON.parse(prefs.saved_searches || "{}"); }
  catch (e) { return {}; }
}
function putSavedSearches(saved) {
  return mut("preferences.update", {library_id: lib,
    values: {saved_searches: JSON.stringify(saved)}});
}
async function loadSaved() {
  if (!lib) return;
  const saved = await getSavedSearches();
  const el = document.getElementById("saved"); el.innerHTML = "";
  for (const [name, spec] of Object.entries(saved)) {
    const d = document.createElement("div");
    d.className = "item"; d.textContent = "🔖 " + name;
    d.title = "click: run · right-click: delete";
    d.onclick = () => {
      document.getElementById("search").value = spec.q || "";
      tagFilter = spec.tag ?? null;
      kindFilter = spec.kind ?? null;
      if (spec.loc != null) loc = spec.loc;
      view = "explorer"; renderTabs(); render();
    };
    d.oncontextmenu = async (e) => {
      e.preventDefault();
      delete saved[name];
      await putSavedSearches(saved);
      loadSaved();
    };
    el.appendChild(d);
  }
}
document.getElementById("savesearch").onclick = async () => {
  if (!lib) return;
  const name = prompt("name this search"); if (!name) return;
  const saved = await getSavedSearches();
  saved[name] = {q: document.getElementById("search").value.trim(),
                 tag: tagFilter, kind: kindFilter, loc};
  await putSavedSearches(saved);
  loadSaved();
};

async function loadLocs() {
  if (!lib) return;
  const locs = await q("locations.list", {library_id: lib});
  const el = document.getElementById("locs"); el.innerHTML = "";
  for (const l of locs) {
    const d = document.createElement("div");
    d.className = "item" + (loc === l.id ? " sel" : "");
    d.textContent = l.name || l.path;
    const gear = document.createElement("span");
    gear.className = "gear"; gear.textContent = "⚙";
    gear.title = "location settings";
    gear.onclick = (e) => {
      e.stopPropagation();
      settingsLoc = l.id; view = "locsettings"; renderTabs(); render();
    };
    d.prepend(gear);
    d.title = "click: open · right-click: rescan · shift-click: delete";
    d.oncontextmenu = async (e) => {
      e.preventDefault();
      await mut("locations.fullRescan", {library_id: lib, location_id: l.id});
      toast("rescan started");
    };
    d.onclick = async (e) => {
      if (e.shiftKey) {
        if (confirm(`remove location ${d.textContent}?`)) {
          await mut("locations.delete", {library_id: lib, id: l.id});
          if (loc === l.id) loc = null;
          loadLocs();
        }
        return;
      }
      kindFilter = null;
      loc = l.id; curPath = "/"; view = "explorer";
      renderTabs(); render(); loadLocs();
    };
    el.appendChild(d);
  }
}

async function loadTags() {
  if (!lib) return;
  const withObjects = await q("tags.getWithObjects", {library_id: lib});
  allTags = withObjects;
  const el = document.getElementById("tags"); el.innerHTML = "";
  for (const t of allTags) {
    const d = document.createElement("span");
    d.className = "tagchip" + (tagFilter === t.id ? " on" : "");
    const nObj = (t.object_ids || []).length;
    d.textContent = t.name + (nObj ? ` (${nObj})` : "");
    d.title = "click: filter · dblclick: edit · right-click: delete";
    if (t.color) d.style.borderLeft = `4px solid ${esc(t.color)}`;
    d.onclick = () => {
      tagFilter = tagFilter === t.id ? null : t.id; loadTags(); render();
    };
    d.ondblclick = async () => {
      const cur = await q("tags.get", {library_id: lib, id: t.id});
      const name = prompt("tag name", cur.name); if (!name) return;
      const color = prompt("color (css)", cur.color || "") || null;
      await mut("tags.update", {library_id: lib, id: t.id, name, color});
      loadTags();
    };
    d.oncontextmenu = async (e) => {
      e.preventDefault();
      if (confirm(`delete tag "${t.name}"?`)) {
        await mut("tags.delete", {library_id: lib, id: t.id});
        if (tagFilter === t.id) tagFilter = null;
        loadTags();
      }
    };
    el.appendChild(d);
  }
}

async function loadStats() {
  if (!lib) return;
  const s = await q("library.statistics", {library_id: lib});
  document.getElementById("stats").innerHTML =
    `<div class="kv">paths: <b>${s.total_paths ?? s.file_paths ?? "?"}</b></div>` +
    `<div class="kv">objects: <b>${s.total_objects ?? s.objects ?? "?"}</b></div>` +
    `<div class="kv">bytes: <b>${fmtBytes(s.total_bytes_used ?? s.total_bytes ?? 0)}</b></div>`;
}

function render() {
  document.getElementById("inspector").style.display = "none";
  hideCtx(); closePreview();
  if (view !== "explorer") { vg = null; cursorIdx = null; }
  ({overview: renderOverview,
    explorer: browse, browse: renderEphemeral, dups: renderDups,
    neardups: renderNearDups,
    jobs: renderJobs, p2p: renderP2P, settings: renderSettings,
    locsettings: renderLocSettings}[view])();
}

// ---- Overview landing page (interface/app/$libraryId/overview:
// categories + statistics + recents + node card) -----------------------
const KIND_NAMES = {0:"Unknown",1:"Document",2:"Folder",3:"Text",
  4:"Package",5:"Image",6:"Audio",7:"Video",8:"Archive",9:"Executable",
  10:"Alias",11:"Encrypted",12:"Key",13:"Link",14:"WebPage",15:"Widget",
  16:"Album",17:"Book",18:"Code",19:"Database",20:"Font",21:"Mesh",
  22:"Config",23:"Dotfile",24:"Screenshot",25:"Label"};
async function renderOverview() {
  const main = document.getElementById("main");
  if (!lib) { main.innerHTML =
    "<div class='muted'>create a library first</div>"; return; }
  const [stats, cats, locs, online, info, nstate, active, nlocs,
         nObjects] = await Promise.all([
    q("library.statistics", {library_id: lib}),
    q("categories.list", {library_id: lib}),
    q("locations.list", {library_id: lib}),
    q("locations.online", {library_id: lib}),
    q("buildInfo"),
    q("nodeState"),
    q("jobs.isActive", {library_id: lib}),
    q("nodes.listLocations", {library_id: lib}),
    q("search.objectsCount", {library_id: lib, filter: {}}),
  ]);
  const onlineSet = new Set(online);
  const tiles = [
    ["Objects", nObjects],
    ["Unique bytes", fmtBytes(+stats.total_unique_bytes || 0)],
    ["Total bytes", fmtBytes(+stats.total_bytes_used || 0)],
    ["Capacity", fmtBytes(+stats.total_bytes_capacity || 0)],
    ["Locations", nlocs.length],
    ["Jobs", active ? "running" : "idle"],
  ];
  const catCells = Object.entries(cats)
    .filter(([, n]) => n > 0)
    .sort((a, b) => b[1] - a[1])
    .map(([k, n]) => `<div class="cat" data-kind="${esc(k)}">
       <b>${n}</b> ${esc(KIND_NAMES[k] ?? k)}</div>`).join("");
  main.innerHTML = `<h1>Overview</h1>
    <div id="tiles">` + tiles.map(([k, v]) =>
      `<div class="tile"><div class="muted">${esc(k)}</div>
       <b>${esc(v)}</b></div>`).join("") + `</div>
    <h2>Categories</h2>
    <div id="cats">${catCells ||
      "<span class='muted'>nothing indexed yet</span>"}</div>
    <h2>Locations</h2>
    <div id="ovlocs">` + locs.map(l => `
      <div class="item ovloc" data-lid="${l.id}">
        ${onlineSet.has(l.id) ? "🟢" : "⚫"} ${esc(l.name || l.path)}
        <span class="muted">${esc(l.path || "")}</span></div>`).join("") +
    `</div>
    <h2>This node</h2>
    <div class="kv">name: <b>${esc(nstate.name)}</b>
      · version <b>${esc(info.version)}</b></div>
    <div class="kv">data: <b>${esc(nstate.data_path)}</b></div>`;
  document.querySelectorAll(".ovloc").forEach(el => el.onclick = () => {
    loc = +el.dataset.lid; curPath = "/"; kindFilter = null;
    view = "explorer"; renderTabs(); render();
  });
  document.querySelectorAll(".cat").forEach(el => el.onclick = () => {
    view = "explorer"; renderTabs();
    kindFilter = +el.dataset.kind; render();
  });
}

// ---- Ephemeral browsing (non-indexed paths, non_indexed.rs) ----------
let ephPath = "/";
async function renderEphemeral() {
  const main = document.getElementById("main");
  main.innerHTML = `
    <h1>Browse (not indexed)</h1>
    <p><input id="ephpath" value="${esc(ephPath)}" style="width:60%"/>
       <button id="ephgo">go</button>
       <button id="ephmkdir" class="ghost">+ folder</button>
       <span class="muted">any directory on this node — nothing is
       written to the library</span></p>
    <div id="ephmeta" class="muted"></div>
    <div id="grid"></div>`;
  const go = async () => {
    ephPath = document.getElementById("ephpath").value.trim() || "/";
    let entries;
    try {
      entries = await q("search.ephemeralPaths",
                        {path: ephPath, with_thumbnails: true});
    } catch (e) { toast(String(e)); return; }
    const grid = document.getElementById("grid");
    grid.innerHTML = "";
    if (ephPath !== "/") {
      grid.appendChild(cell({name: "..", is_dir: 1}, () => {
        ephPath = ephPath.replace(/\/[^/]+\/?$/, "") || "/";
        document.getElementById("ephpath").value = ephPath;
        go();
      }));
    }
    for (const e of entries) {
      const r = {name: e.name, extension: e.extension,
                 is_dir: e.is_dir, cas_id: e.cas_id, id: -1};
      grid.appendChild(cell(r, async () => {
        if (e.is_dir) {
          ephPath = e.path;
          document.getElementById("ephpath").value = ephPath;
          go();
        } else {
          let md = null;
          try {
            md = await q("files.getEphemeralMediaData", {path: e.path});
          } catch (err) { /* unreadable */ }
          document.getElementById("ephmeta").textContent =
            `${e.name}: ` + (md ? Object.entries(md)
              .map(([k, v]) => `${k}=${v}`).join(" · ")
              : "no media metadata");
        }
      }));
    }
  };
  document.getElementById("ephgo").onclick = go;
  document.getElementById("ephmkdir").onclick = async () => {
    const name = prompt("folder name"); if (!name) return;
    await mut("files.createEphemeralFolder", {path: ephPath, name});
    go();
  };
  document.getElementById("ephpath").onkeydown =
    (e) => { if (e.key === "Enter") go(); };
  go();
}

// ---- Explorer --------------------------------------------------------
// ---- virtualized explorer --------------------------------------------
// The engine browses 1M-file libraries; the old renderer fetched a hard
// take:400 and built a DOM node per row. Now the result set is WINDOWED
// (search.paths skip/take — the server orders and filters, so absolute
// indices are stable) and only the viewport ± overscan rows exist in
// the DOM, the same shape as the reference Explorer's
// @tanstack/react-virtual grids (interface/app/$libraryId/Explorer/).
const VWIN = 200;        // rows per fetched window (≤ server take cap)
// Browsers clamp element heights (~17.9M px in Firefox); above this
// the spacer stays capped and scrollTop maps into virtual row space
// by ratio, so a 1M-row list (26M px) stays fully reachable.
const VG_MAX_SPACER = 12_000_000;
const MEDIA_EXTS = ["png","jpg","jpeg","gif","webp","bmp","tiff",
  "tif","heic","heif","avif","svg","svgz","pdf","avi","mp4","mkv",
  "mov","webm"];
let vg = null;           // virtual-grid state for the current browse
let vgResizeObs = null;  // one observer, re-pointed per browse
let cursorIdx = null;    // keyboard cursor as an ABSOLUTE index
let lastClickIdx = null; // shift-range anchor as an ABSOLUTE index

function vgDims() {
  if (viewMode === "list") return {cellW: 0, cellH: 26, listMode: true};
  if (viewMode === "media")
    return {cellW: 188, cellH: 178, listMode: false};
  return {cellW: 116, cellH: 126, listMode: false};
}
function vgCols() {
  if (!vg) return 1;
  const {cellW, listMode} = vgDims();
  if (listMode) return 1;
  return Math.max(1, Math.floor((vg.wrap.clientWidth - 8) / cellW));
}

async function browse() {
  const main = document.getElementById("main");
  vg = null; cursorIdx = null;
  if (!lib || (loc == null && kindFilter == null
               && albumFilter == null && spaceFilter == null)) {
    main.innerHTML =
      "<div class='muted'>create a library and add a location</div>";
    return;
  }
  const searchText = document.getElementById("search").value.trim();
  // kind drill-down from the overview is LIBRARY-wide (matching the
  // tile's count); normal browsing scopes to the selected location.
  // album/space/kind drill-downs are LIBRARY-wide; normal browsing
  // scopes to the selected location + current folder
  const libraryWide = kindFilter != null || albumFilter != null
    || spaceFilter != null;
  const filter = kindFilter != null ? {object_kind: [kindFilter]}
    : (libraryWide ? {} : {location_id: loc});
  if (albumFilter != null) filter.album_id = albumFilter;
  if (spaceFilter != null) filter.space_id = spaceFilter;
  if (searchText) filter.search = searchText;
  else if (!libraryWide) filter.materialized_path = curPath;
  if (tagFilter != null) filter.tags = [tagFilter];
  // Every narrowing is SERVER-side: client-side filtering would leave
  // holes in the windows and shift absolute indices.
  if (favOnly) filter.favorite = true;
  if (viewMode === "media") filter.extensions = MEDIA_EXTS;
  const order = (viewMode === "list" && sortKey)
    ? {field: sortKey, desc: sortDir < 0} : null;
  const count = await q("search.pathsCount", {library_id: lib, filter});
  const kindChip = kindFilter == null ? "" :
    ` · <span class="tagchip on" id="kindchip">kind: ` +
    `${esc(KIND_NAMES[kindFilter] ?? kindFilter)} ✕</span>`;
  const showUp = !searchText && !libraryWide && curPath !== "/";
  const upBtn = showUp
    ? `<span class="tagchip" id="upbtn">⬆ ..</span> · ` : "";
  main.innerHTML =
    `<div class="muted" style="margin-bottom:10px">${upBtn}` +
    `location ${loc} · ` +
    `${searchText ? `search "${esc(searchText)}"` : esc(curPath)} · ` +
    `${count} paths${kindChip}</div>` +
    (viewMode === "list" ? listHeaderHtml() : "") +
    `<div id="gridwrap"><div id="grid" class="virt` +
    `${viewMode === "media" ? " media" : ""}` +
    `${viewMode === "list" ? " vlist" : ""}"></div></div>`;
  const chip = document.getElementById("kindchip");
  if (chip) chip.onclick = () => { kindFilter = null; browse(); };
  const up = document.getElementById("upbtn");
  if (up) up.onclick = () => {
    curPath = curPath.replace(/[^/]+\/$/, ""); clearSel(); browse();
  };
  if (viewMode === "list") wireListHeader();
  lastRows = new Array(count);  // sparse: windows fill as they load
  vg = {count, filter, order,
        wrap: document.getElementById("gridwrap"),
        grid: document.getElementById("grid"),
        fetched: new Set(), inflight: new Map(), pool: new Map()};
  vg.wrap.onscroll = () => vgUpdate();
  // Re-layout when the scroller's width changes without a scroll
  // (inspector open/close, window resize) — vgUpdate detects the new
  // column count and rebuilds the pool.
  if (window.ResizeObserver) {
    if (!vgResizeObs) vgResizeObs = new ResizeObserver(() => vgUpdate());
    vgResizeObs.disconnect();
    vgResizeObs.observe(vg.wrap);
  }
  vgUpdate();
}

function listHeaderHtml() {
  const lbl = (k) => k + (sortKey === k
    ? (sortDir > 0 ? " ↑" : " ↓") : "");
  return `<div id="listhdr"><span></span>` +
    ["name", "kind", "size", "modified"].map(k =>
      `<span class="lh" data-k="${k}">${lbl(k)}</span>`).join("") +
    `</div>`;
}
function wireListHeader() {
  document.querySelectorAll("#listhdr .lh").forEach(el => {
    el.onclick = () => {   // server-side re-sort, windows refetch
      const k = el.dataset.k;
      sortDir = sortKey === k ? -sortDir : 1;
      sortKey = k;
      browse();
    };
  });
}

function vgUpdate() {
  if (!vg || !vg.wrap.isConnected) return;
  const {cellW, cellH, listMode} = vgDims();
  const cols = vgCols();
  if (vg.renderedCols !== undefined && vg.renderedCols !== cols) {
    // Column count changed (inspector opened, window resized): pooled
    // cells hold absolute positions computed with the OLD count —
    // drop them all so this pass re-lays out at the new geometry.
    for (const el of vg.pool.values()) el.remove();
    vg.pool.clear();
  }
  vg.renderedCols = cols;
  const rows = Math.ceil(vg.count / cols);
  const fullH = Math.max(rows * cellH, 1);
  const spacerH = Math.min(fullH, VG_MAX_SPACER);
  vg.grid.style.height = spacerH + "px";
  const view = vg.wrap.clientHeight;
  const scale = (fullH > spacerH && spacerH > view)
    ? (fullH - view) / (spacerH - view) : 1;
  const vTop = vg.wrap.scrollTop * scale;  // virtual pixel offset
  const base = vg.wrap.scrollTop - vTop;   // virtual→spacer shift
  vg.scale = scale;
  const y0 = vTop, y1 = vTop + view;
  const r0 = Math.max(0, Math.floor(y0 / cellH) - 3);
  const r1 = Math.min(Math.max(rows - 1, 0), Math.ceil(y1 / cellH) + 3);
  const i0 = r0 * cols;
  const i1 = Math.min(vg.count - 1, (r1 + 1) * cols - 1);
  for (let w = Math.floor(i0 / VWIN); w <= Math.floor(i1 / VWIN); w++)
    vgFetch(w);
  for (const [idx, el] of [...vg.pool]) {
    if (idx < i0 || idx > i1) { el.remove(); vg.pool.delete(idx); }
    else if (scale !== 1) {   // compressed spacer: tops shift per scroll
      el.style.top = (base + Math.floor(idx / cols) * cellH) + "px";
    }
  }
  for (let i = i0; i <= i1; i++) {
    if (vg.pool.has(i)) continue;
    const r = lastRows[i];
    if (!r) continue;    // window in flight; vgFetch re-renders
    const el = listMode ? listRow(r) : cell(r, null);
    el.style.position = "absolute";
    el.style.top = (base + Math.floor(i / cols) * cellH) + "px";
    if (listMode) {
      el.style.left = "0"; el.style.right = "0";
    } else {
      el.style.left = ((i % cols) * cellW) + "px";
    }
    el.dataset.idx = i;
    vg.grid.appendChild(el);
    vg.pool.set(i, el);
  }
}

function vgFetch(w) {
  if (!vg || vg.fetched.has(w)) return Promise.resolve();
  if (vg.inflight.has(w)) return vg.inflight.get(w);
  const mine = vg;
  const p = q("search.paths", {
    library_id: lib, skip: w * VWIN, take: VWIN, filter: mine.filter,
    ...(mine.order ? {order: mine.order} : {}),
  }).then(res => {
    if (vg !== mine) return;    // navigated away mid-flight
    (res.items || []).forEach((it, j) => { lastRows[w * VWIN + j] = it; });
    mine.fetched.add(w);
    mine.inflight.delete(w);
    vgUpdate();
  }).catch(() => {
    // transient failure (server restart, network blip): clear the
    // inflight marker and retry shortly — otherwise the very first
    // viewport stays blank forever with no scroll to re-trigger it
    mine.inflight.delete(w);
    setTimeout(() => { if (vg === mine) vgUpdate(); }, 1000);
  });
  mine.inflight.set(w, p);
  return p;
}

// Scroll an absolute index into view, fetch its window, select it.
async function selectIndex(i) {
  if (!vg || !vg.count) return;
  i = Math.max(0, Math.min(vg.count - 1, i));
  cursorIdx = i;
  const {cellH} = vgDims();
  const cols = vgCols();
  const scale = vg.scale || 1;
  const top = Math.floor(i / cols) * cellH;  // virtual px
  const vTop = vg.wrap.scrollTop * scale;
  if (top < vTop) vg.wrap.scrollTop = top / scale;
  else if (top + cellH > vTop + vg.wrap.clientHeight)
    vg.wrap.scrollTop =
      (top + cellH - vg.wrap.clientHeight) / scale;
  await vgFetch(Math.floor(i / VWIN));
  const r = lastRows[i];
  if (!r) return;
  selection.clear(); selection.add(r.id); lastClickId = r.id;
  vgUpdate(); updateSelClasses();
  if (previewRow) openPreview(r);
}

function openEntry(r) {
  if (r.is_dir) {
    curPath = r.materialized_path + r.name + "/";
    document.getElementById("search").value = ""; clearSel(); browse();
  } else inspect(r);
}

// ---- multi-select + context menu -------------------------------------
function clearSel() {
  selection.clear(); lastClickId = null; lastClickIdx = null;
}
function updateSelClasses() {
  // selection changes repaint in place — no refetch, no DOM rebuild
  document.querySelectorAll("[data-fpid]").forEach(el =>
    el.classList.toggle("sel", selection.has(+el.dataset.fpid)));
}
function entryClick(r, e) {
  // absolute keyboard cursor: the rendered cell carries its index
  // (dataset.idx, set by vgUpdate) — O(1) vs an O(count) indexOf over
  // the sparse array at 1M rows
  const el = e && e.currentTarget;
  const idx = (el && el.dataset && el.dataset.idx !== undefined)
    ? +el.dataset.idx : null;
  cursorIdx = idx;
  if (e.shiftKey && lastClickIdx != null && idx != null) {
    // range select between the two ANCHOR INDICES — O(range), no
    // O(count) scan of the sparse array (holes stay unselected)
    for (let k = Math.min(lastClickIdx, idx);
         k <= Math.max(lastClickIdx, idx); k++)
      if (lastRows[k]) selection.add(lastRows[k].id);
    updateSelClasses();
  } else if (e.ctrlKey || e.metaKey) {
    selection.has(r.id) ? selection.delete(r.id) : selection.add(r.id);
    lastClickId = r.id; lastClickIdx = idx;
    updateSelClasses();
  } else {
    selection.clear(); selection.add(r.id); lastClickId = r.id;
    lastClickIdx = idx;
    updateSelClasses();
    openEntry(r);
  }
}
function selRows() {
  const rows = lastRows.filter(r => selection.has(r.id) && !r.is_dir);
  return rows.length ? rows : [];
}
function hideCtx() {
  const m = document.getElementById("ctxmenu");
  if (m) m.style.display = "none";
}
document.addEventListener("click", hideCtx);

// ---- quick preview overlay (the reference's space-bar QuickPreview,
// interface/app/$libraryId/Explorer/QuickPreview) ----------------------
let previewRow = null;
const IMG_EXT = new Set(["png","jpg","jpeg","gif","webp","bmp","svg"]);
function closePreview() {
  const p = document.getElementById("preview");
  if (p) p.style.display = "none";
  previewRow = null;
}
async function openPreview(r) {
  if (!r || r.is_dir) return;
  previewRow = r;
  const p = document.getElementById("preview");
  const ext = (r.extension || "").toLowerCase();
  const src = IMG_EXT.has(ext)
    ? `/spacedrive/file/${lib}/${loc}/${r.id}`
    : (r.cas_id ? `/spacedrive/thumbnail/${r.cas_id}.webp` : null);
  let pathLine = "";
  try {
    const full = await q("files.getPath", {library_id: lib, id: r.id});
    if (full) pathLine = `<div class="kv pvpath">${esc(full)}</div>`;
  } catch (e) { /* ephemeral rows have no id */ }
  p.innerHTML = `<div id="pvbody">
    <div id="pvmedia">${src
      ? `<img src="${src}" onerror="this.replaceWith('🗎')"/>` : "🗎"}</div>
    <div id="pvmeta">
      <h1>${esc(r.name)}${r.extension ? "." + esc(r.extension) : ""}</h1>
      <div class="kv">size: <b>${fmtBytes(r.size_in_bytes || 0)}</b></div>
      <div class="kv">modified: <b>${r.date_modified
        ? new Date(r.date_modified * 1000).toISOString() : "?"}</b></div>
      <div class="kv">cas: <b>${esc(r.cas_id || "—")}</b></div>
      ${pathLine}
      <div class="muted">space/esc close · ←/→ navigate</div>
    </div></div>`;
  p.style.display = "flex";
  p.onclick = (e) => { if (e.target === p) closePreview(); };
  if (r.object_id != null)
    mut("files.updateAccessTime",
        {library_id: lib, ids: [r.object_id]}).catch(() => {});
}
function previewStep(delta) {
  const files = lastRows.filter(x => !x.is_dir);
  if (!files.length || !previewRow) return;
  const i = files.findIndex(x => x.id === previewRow.id);
  const next = files[(i + delta + files.length) % files.length];
  selection.clear(); selection.add(next.id); lastClickId = next.id;
  updateSelClasses();
  openPreview(next);
}

// ---- keyboard model: arrows/enter/del/space in grid and list ---------
function gridColumns() {
  if (vg) return vgCols();
  const g = document.getElementById("grid");
  if (!g || viewMode === "list") return 1;
  const cols = getComputedStyle(g).gridTemplateColumns.split(" ").length;
  return Math.max(1, cols);
}
function moveCursor(delta) {
  // Absolute-index navigation over the virtual window: the target row
  // may not be fetched yet — selectIndex scrolls there, fetches its
  // window, then selects.
  if (!vg || !vg.count) return;
  if (cursorIdx == null) { selectIndex(delta > 0 ? 0 : vg.count - 1); return; }
  selectIndex(cursorIdx + delta);
}
document.addEventListener("keydown", (e) => {
  if (e.key === "Escape") {
    closePreview(); clearSel(); hideCtx(); updateSelClasses(); return;
  }
  const tag = (document.activeElement || {}).tagName;
  if (tag === "INPUT" || tag === "TEXTAREA" || view !== "explorer") return;
  if (e.key === " ") {
    e.preventDefault();
    if (previewRow) { closePreview(); return; }
    const r = lastRows.find(x => x && selection.has(x.id) && !x.is_dir);
    if (r) openPreview(r);
  } else if (e.key === "ArrowRight") {
    e.preventDefault();
    previewRow ? previewStep(1) : moveCursor(1);
  } else if (e.key === "ArrowLeft") {
    e.preventDefault();
    previewRow ? previewStep(-1) : moveCursor(-1);
  } else if (e.key === "ArrowDown") {
    e.preventDefault(); moveCursor(gridColumns());
  } else if (e.key === "ArrowUp") {
    e.preventDefault(); moveCursor(-gridColumns());
  } else if (e.key === "Enter") {
    const r = lastRows.find(x => x && selection.has(x.id));
    if (r) openEntry(r);
  } else if (e.key === "Delete") {
    const rows = selRows();
    if (!rows.length || !confirm(`delete ${rows.length} file(s)?`)) return;
    mut("files.deleteFiles", {library_id: lib, location_id: loc,
      file_path_ids: rows.map(x => x.id)}).then(() => {
        toast("deleting…"); clearSel(); setTimeout(browse, 400);
      });
  } else if ((e.ctrlKey || e.metaKey) && e.key.toLowerCase() === "a") {
    e.preventDefault();
    lastRows.forEach(r => selection.add(r.id));  // loaded windows only
    updateSelClasses();
  }
});
function showCtx(r, e) {
  e.preventDefault();
  if (!selection.has(r.id)) {
    selection.clear(); selection.add(r.id); lastClickId = r.id;
    updateSelClasses();
  }
  const m = document.getElementById("ctxmenu");
  const rows = selRows();
  const n = rows.length;
  // Directory-only selection: file operations have nothing to act on,
  // so offer navigation alone instead of "(0)" no-op actions.
  const items = n === 0 ? [
    ["Open", () => openEntry(r)],
    ["Rescan this folder", async () => {
       await mut("locations.subPathRescan", {library_id: lib,
         location_id: loc, sub_path: curPath});
       toast("rescanning…"); }],
  ] : [
    ["Open / inspect", () => openEntry(r)],
    ["Preview (space)", () => { const f = selRows()[0];
       if (f) openPreview(f); }],
    ["Copy path", async () => {
       const full = await q("files.getPath", {library_id: lib, id: r.id});
       if (full && navigator.clipboard)
         navigator.clipboard.writeText(full).catch(() => {});
       toast(full || "no path"); }],
    ["sep"],
    [`Copy (${n})`, () => { clipboard = {op: "copy",
       ids: rows.map(x => x.id), locId: loc}; pasteBtn(); }],
    [`Cut (${n})`, () => { clipboard = {op: "cut",
       ids: rows.map(x => x.id), locId: loc}; pasteBtn(); }],
    [`Duplicate (${n})`, async () => {
       await mut("files.duplicateFiles", {library_id: lib,
         location_id: loc, file_path_ids: rows.map(x => x.id)});
       toast("duplicating…"); }],
    ["sep"],
    [`★ Favorite (${n})`, async () => {
       for (const x of rows) if (x.object_id != null)
         await mut("files.setFavorite",
                   {library_id: lib, id: x.object_id, favorite: true});
       toast("favorited"); }],
    [`Tag… (${n})`, async () => {
       const nm = prompt("tag name" + (allTags.length
         ? ` (existing: ${allTags.map(t => t.name).join(", ")})` : ""));
       if (!nm) return;
       let t = allTags.find(x => x.name === nm);
       if (!t) t = await mut("tags.create",
                             {library_id: lib, name: nm, color: null});
       for (const x of rows) if (x.object_id != null)
         await mut("tags.assign", {library_id: lib, tag_id: t.id,
                                   object_id: x.object_id});
       toast(`tagged ${n}`); loadTags(); }],
    [`Add to album… (${n})`, async () => {
       const albums = await q("albums.list", {library_id: lib});
       const nm = prompt("album name" + (albums.length
         ? ` (existing: ${albums.map(a => a.name).join(", ")})` : ""));
       if (!nm) return;
       let a2 = albums.find(x => x.name === nm);
       if (!a2) a2 = await mut("albums.create",
                               {library_id: lib, name: nm});
       const ids = rows.map(x => x.object_id).filter(v => v != null);
       await mut("albums.addObjects",
                 {library_id: lib, id: a2.id, object_ids: ids});
       toast(`added ${ids.length} to ${nm}`); loadGroupings(); }],
    [`Add to space… (${n})`, async () => {
       const sps = await q("spaces.list", {library_id: lib});
       const nm = prompt("space name" + (sps.length
         ? ` (existing: ${sps.map(s => s.name).join(", ")})` : ""));
       if (!nm) return;
       let sp = sps.find(x => x.name === nm);
       if (!sp) sp = await mut("spaces.create",
                               {library_id: lib, name: nm});
       const ids = rows.map(x => x.object_id).filter(v => v != null);
       await mut("spaces.addObjects",
                 {library_id: lib, id: sp.id, object_ids: ids});
       toast(`added ${ids.length} to ${nm}`); loadGroupings(); }],
    [`Validate (${n})`, async () => {
       await mut("jobs.objectValidator",
                 {library_id: lib, id: loc, mode: "fill"});
       toast("validator started"); }],
    ["Convert image…", async () => {
       const exts = await q("files.getConvertableImageExtensions");
       const to = prompt(`convert to (${exts.join(", ")})`);
       if (!to || !exts.includes(to.toLowerCase())) return;
       for (const x of selRows())
         await mut("files.convertImage", {library_id: lib,
           file_path_id: x.id, to_extension: to.toLowerCase()});
       toast("converted"); setTimeout(browse, 400); }],
    [`Clear access time (${n})`, async () => {
       const ids = selRows().map(x => x.object_id).filter(v => v != null);
       if (ids.length)
         await mut("files.removeAccessTime", {library_id: lib, ids});
       toast("cleared"); }],
    ["sep"],
    [`Delete (${n})`, async () => {
       if (!confirm(`delete ${n} file(s)?`)) return;
       await mut("files.deleteFiles", {library_id: lib, location_id: loc,
         file_path_ids: rows.map(x => x.id)});
       toast("deleting…"); clearSel();
       setTimeout(browse, 400); }],
    [`Erase securely (${n})`, async () => {
       if (!confirm(`overwrite + delete ${n} file(s)? irreversible`))
         return;
       await mut("files.eraseFiles", {library_id: lib, location_id: loc,
         file_path_ids: rows.map(x => x.id), passes: 1});
       toast("erasing…"); clearSel();
       setTimeout(browse, 600); }],
    ["sep"],
    [`Encrypt… (${n})`, async () => {
       const pw = prompt("encryption password"); if (!pw) return;
       await mut("files.encryptFiles", {library_id: lib,
         location_id: loc, file_path_ids: rows.map(x => x.id),
         password: pw});
       toast("encrypting…"); setTimeout(browse, 600); }],
    [`Decrypt… (${n})`, async () => {
       const pw = prompt("decryption password"); if (!pw) return;
       await mut("files.decryptFiles", {library_id: lib,
         location_id: loc, file_path_ids: rows.map(x => x.id),
         password: pw});
       toast("decrypting…"); setTimeout(browse, 600); }],
  ];
  m.innerHTML = "";
  for (const [label, fn] of items) {
    if (label === "sep") {
      const s = document.createElement("div"); s.className = "sep";
      m.appendChild(s); continue;
    }
    const d = document.createElement("div");
    d.className = "mi"; d.textContent = label;
    d.onclick = (ev) => { ev.stopPropagation(); hideCtx(); fn(); };
    m.appendChild(d);
  }
  m.style.left = Math.min(e.clientX, innerWidth - 180) + "px";
  m.style.top = Math.min(e.clientY, innerHeight - items.length * 28) + "px";
  m.style.display = "block";
}
function pasteBtn() {
  const b = document.getElementById("pastebtn");
  b.style.display = clipboard ? "" : "none";
  if (clipboard) b.textContent =
    `paste ${clipboard.ids.length} (${clipboard.op})`;
}
async function doPaste() {
  if (!clipboard || loc == null) return;
  const rel = curPath === "/" ? "" : curPath.slice(1);
  const input = {library_id: lib, source_location_id: clipboard.locId,
    sources_file_path_ids: clipboard.ids, target_location_id: loc,
    target_location_relative_directory_path: rel};
  await mut(clipboard.op === "cut" ? "files.cutFiles" : "files.copyFiles",
            input);
  toast(clipboard.op === "cut" ? "moving…" : "copying…");
  if (clipboard.op === "cut") clipboard = null;
  pasteBtn();
  setTimeout(browse, 500);
}

// ---- drag & drop: drag files onto a folder to move them --------------
function wireDnD(el, r) {
  if (!r.is_dir) {
    el.draggable = true;
    el.ondragstart = (e) => {
      if (!selection.has(r.id)) {
        selection.clear(); selection.add(r.id); updateSelClasses();
      }
      e.dataTransfer.setData("text/sdtpu-ids",
        JSON.stringify(selRows().map(x => x.id)));
      e.dataTransfer.effectAllowed = "move";
    };
  } else {
    el.ondragover = (e) => { e.preventDefault(); el.style.outline =
      "2px dashed #3b82f6"; };
    el.ondragleave = () => { el.style.outline = ""; };
    el.ondrop = async (e) => {
      e.preventDefault(); el.style.outline = "";
      let ids;
      try { ids = JSON.parse(e.dataTransfer.getData("text/sdtpu-ids")); }
      catch { return; }
      if (!ids || !ids.length) return;
      const rel = (r.materialized_path + r.name + "/").replace(/^\//, "");
      await mut("files.cutFiles", {library_id: lib,
        source_location_id: loc, sources_file_path_ids: ids,
        target_location_id: loc,
        target_location_relative_directory_path: rel});
      toast(`moving ${ids.length} into ${r.name}/`);
      clearSel();
      setTimeout(browse, 500);
    };
  }
}

function listRow(r) {
  // div-based (not <tr>) so the virtual renderer can absolutely
  // position each row inside the windowed scroller.
  const tr = document.createElement("div");
  tr.className = "lrow" + (selection.has(r.id) ? " sel" : "");
  const kindName = r.is_dir ? "folder" : (r.extension || "file");
  const size = r.is_dir ? "" : fmtBytes(r.size_in_bytes || 0);
  const dm = r.date_modified
    ? new Date(r.date_modified * 1000).toISOString().slice(0, 16)
        .replace("T", " ") : "";
  tr.dataset.fpid = r.id;
  tr.innerHTML = `<span>${r.is_dir ? "📁" : "🗎"}</span>` +
    `<span>${esc(r.name)}${r.extension ? "." + esc(r.extension) : ""}` +
    `</span><span>${esc(kindName)}</span><span>${size}</span>` +
    `<span>${dm}</span>`;
  tr.onclick = (e) => entryClick(r, e);
  tr.ondblclick = () => openEntry(r);
  tr.oncontextmenu = (e) => showCtx(r, e);
  wireDnD(tr, r);
  return tr;
}
function cell(r, onclick) {
  const c = document.createElement("div"); c.className = "cell";
  if (!onclick) c.dataset.fpid = r.id;
  if (selection.has(r.id) || (selected && selected.id === r.id))
    c.className += " sel";
  const t = document.createElement("div"); t.className = "thumb";
  if (r.cas_id) {
    const img = document.createElement("img");
    img.src = `/spacedrive/thumbnail/${r.cas_id}.webp`;
    img.onerror = () => { img.remove(); t.textContent = "🗎"; };
    t.appendChild(img);
  } else t.textContent = r.is_dir ? "📁" : "🗎";
  const n = document.createElement("div"); n.className = "nm";
  n.textContent = r.name + (r.extension ? "." + r.extension : "");
  c.appendChild(t); c.appendChild(n);
  if (onclick) c.onclick = onclick;       // the ".." up-cell
  else {
    c.onclick = (e) => entryClick(r, e);
    c.ondblclick = () => openEntry(r);
    c.oncontextmenu = (e) => showCtx(r, e);
    wireDnD(c, r);
  }
  return c;
}

// ---- Per-location settings (indexer-rule editor, rescans) ------------
const RULE_KINDS = [[0, "accept glob"], [1, "reject glob"],
  [2, "accept if children"], [3, "reject if children"]];
async function renderLocSettings() {
  const main = document.getElementById("main");
  if (!lib || settingsLoc == null) {
    main.innerHTML = "<div class='muted'>no location selected</div>"; return;
  }
  const [l, allRules, attachedRules, online] = await Promise.all([
    q("locations.get", {library_id: lib, location_id: settingsLoc}),
    q("locations.indexer_rules.list", {library_id: lib}),
    q("locations.indexer_rules.listForLocation",
      {library_id: lib, location_id: settingsLoc}),
    q("locations.online", {library_id: lib}),
  ]);
  if (!l) { main.innerHTML = "<div class='muted'>gone</div>"; return; }
  const isOnline = online.includes(l.id);
  const attached = new Set((attachedRules || []).map(r => r.id));
  main.innerHTML = `
    <h1>Location settings — ${esc(l.name || l.path)}</h1>
    <div class="kv">path: <b>${esc(l.path)}</b>
      ${isOnline ? "🟢 online" : "⚫ offline"}
      ${isOnline ? "" :
        '<button id="lsrelink" class="ghost">relink…</button>'}</div>
    <div class="kv">id: <b>${l.id}</b> · hidden: <b>${l.hidden ? "yes"
      : "no"}</b> · indexed <b>${esc(String(l.date_created || "?"))}
      </b></div>
    <div class="kv"><button id="lsaddlib" class="ghost">
      add to another library…</button></div>
    <p>
      <input id="lsname" value="${esc(l.name || "")}"
             placeholder="display name"/>
      <button id="lsrename">rename</button>
      <button id="lshide" class="ghost">${l.hidden ? "unhide" : "hide"}
      </button>
    </p>
    <p>
      <button id="lsfull">full rescan</button>
      <button id="lsquick" class="ghost">quick rescan</button>
      <button id="lsmkdir" class="ghost">create subdirectory…</button>
      <button id="lsdelete" class="danger">remove location</button>
    </p>
    <h2>Indexer rules</h2>
    <div class="muted">checked rules apply when this location is
      indexed</div>
    <div id="lsrules"></div>
    <h3>New rule</h3>
    <p>
      <input id="nrname" placeholder="rule name" style="width:130px"/>
      <select id="nrkind">${RULE_KINDS.map(([v, t]) =>
        `<option value="${v}">${t}</option>`).join("")}</select>
      <input id="nrglob" placeholder="glob, e.g. **/*.tmp"
             style="width:160px"/>
      <button id="nradd">add rule</button>
    </p>`;
  document.getElementById("lsmkdir").onclick = async () => {
    const sp = prompt("subdirectory path (relative to the location)");
    if (!sp) return;
    try {
      await mut("locations.createDirectory",
                {library_id: lib, location_id: l.id, sub_path: sp});
      toast("created");
    } catch (e) { toast(e.message); }
  };
  const relinkBtn = document.getElementById("lsrelink");
  if (relinkBtn) relinkBtn.onclick = async () => {
    const path = prompt("new absolute path for this location");
    if (!path) return;
    await mut("locations.relink",
              {library_id: lib, location_id: l.id, path});
    toast("relinked"); renderLocSettings();
  };
  document.getElementById("lsaddlib").onclick = async () => {
    const target = prompt("target library id (uuid)");
    if (!target) return;
    try {
      await mut("locations.addLibrary",
                {library_id: target, path: l.path});
      toast("added to library");
    } catch (e) { toast(e.message); }
  };
  const rulesEl = document.getElementById("lsrules");
  for (const r of allRules) {
    const d = document.createElement("div"); d.className = "kv";
    const cb = document.createElement("input");
    cb.type = "checkbox"; cb.checked = attached.has(r.id);
    cb.onchange = async () => {
      const ids = new Set(attached);
      cb.checked ? ids.add(r.id) : ids.delete(r.id);
      await mut("locations.update", {library_id: lib, id: l.id,
        indexer_rules_ids: [...ids]});
      renderLocSettings();
    };
    d.appendChild(cb);
    const nm = document.createElement("span");
    nm.textContent = ` ${r.name} `;
    nm.style.cursor = "pointer";
    nm.title = "click for rule details";
    nm.onclick = async () => {
      const full = await q("locations.indexer_rules.get",
                           {library_id: lib, id: r.id});
      toast(`${full.name}: ${full.rules_per_kind ? "rules blob "
        + full.rules_per_kind.length + " B" : "no params"}`);
    };
    d.appendChild(nm);
    if (r.default_rule) {
      const s = document.createElement("span");
      s.className = "muted"; s.textContent = "(system)";
      d.appendChild(s);
    } else {
      const del = document.createElement("button");
      del.className = "danger"; del.textContent = "×";
      del.onclick = async () => {
        await mut("locations.indexer_rules.delete",
                  {library_id: lib, id: r.id});
        renderLocSettings();
      };
      d.appendChild(del);
    }
    rulesEl.appendChild(d);
  }
  document.getElementById("lsrename").onclick = async () => {
    await mut("locations.update", {library_id: lib, id: l.id,
      name: document.getElementById("lsname").value});
    loadLocs(); renderLocSettings();
  };
  document.getElementById("lshide").onclick = async () => {
    await mut("locations.update", {library_id: lib, id: l.id,
      hidden: l.hidden ? 0 : 1});
    renderLocSettings();
  };
  document.getElementById("lsfull").onclick = async () => {
    await mut("locations.fullRescan",
              {library_id: lib, location_id: l.id});
    toast("full rescan started");
  };
  document.getElementById("lsquick").onclick = async () => {
    await mut("locations.quickRescan",
              {library_id: lib, location_id: l.id, sub_path: "/"});
    toast("quick rescan started");
  };
  document.getElementById("lsdelete").onclick = async () => {
    if (!confirm("remove this location from the library?")) return;
    await mut("locations.delete", {library_id: lib, id: l.id});
    if (loc === l.id) loc = null;
    settingsLoc = null; view = "explorer"; renderTabs();
    loadLocs(); render();
  };
  document.getElementById("nradd").onclick = async () => {
    const name = document.getElementById("nrname").value.trim();
    const glob = document.getElementById("nrglob").value.trim();
    const kind = parseInt(document.getElementById("nrkind").value);
    if (!name || !glob) { toast("name + glob required"); return; }
    await mut("locations.indexer_rules.create", {library_id: lib,
      name, rules: [[kind, [glob]]]});
    renderLocSettings();
  };
}

// ---- Inspector (file detail panel) -----------------------------------
async function inspect(r) {
  selected = r;
  const el = document.getElementById("inspector");
  el.style.display = "block";
  const name = r.name + (r.extension ? "." + r.extension : "");
  const size = r.size_in_bytes_bytes ? parseInt(r.size_in_bytes_bytes, 16) ||
               r.size_in_bytes : r.size_in_bytes;
  let html = `<h3>${esc(name)}</h3>` +
    `<div class="kv">size: <b>${fmtBytes(size)}</b></div>` +
    `<div class="kv">cas_id: <b>${esc(r.cas_id || "—")}</b></div>` +
    `<div class="kv">object: <b>${r.object_id ?? "—"}</b></div>` +
    `<div class="kv">path: <b>${esc(r.materialized_path)}</b></div>`;
  let obj = null;
  if (r.object_id != null) {
    obj = await q("files.get", {library_id: lib, id: r.object_id});
    if (obj) {
      html += `<div class="kv">kind: <b>${obj.kind}</b></div>` +
        `<div class="kv">note: <b>${esc(obj.note || "—")}</b></div>`;
    }
  }
  html += `<div id="itags"></div><div id="ilabels"></div>
    <div id="iexif"></div>
    <div style="margin-top:8px">
      <button id="ifav" class="ghost">${obj && obj.favorite ? "★" : "☆"} favorite</button>
      <button id="irename" class="ghost">rename</button>
      <button id="inote" class="ghost">note</button>
      <button id="idup" class="ghost">duplicate</button>
      <button id="idel" class="danger">delete</button>
    </div>`;
  el.innerHTML = html;
  if (r.object_id != null) {
    const renderChips = (el, title, items, mineIds, onToggle, onCtx) => {
      el.innerHTML = `<h3>${title}</h3>`;
      for (const it of items) {
        const chip = document.createElement("span");
        chip.className = "tagchip" + (mineIds.has(it.id) ? " on" : "");
        chip.textContent = it.name;
        chip.onclick = () => onToggle(it, mineIds.has(it.id));
        if (onCtx) chip.oncontextmenu = (ev) => {
          ev.preventDefault(); onCtx(it);
        };
        el.appendChild(chip);
      }
      return el;
    };
    const mine = await q("tags.getForObject",
      {library_id: lib, object_id: r.object_id});
    renderChips(document.getElementById("itags"), "tags", allTags,
      new Set(mine.map(t => t.id)), async (t, has) => {
        await mut("tags.assign", {library_id: lib, tag_id: t.id,
          object_id: r.object_id, unassign: has});
        inspect(r);
      });
    // labels (net-new surface over the schema's Label model)
    const [allLabels, mineL] = await Promise.all([
      q("labels.list", {library_id: lib}),
      q("labels.getForObject", {library_id: lib,
                                object_id: r.object_id}),
    ]);
    const ll = renderChips(document.getElementById("ilabels"), "labels",
      allLabels, new Set(mineL.map(x => x.id)), async (lbl, has) => {
        await mut("labels.assign", {library_id: lib, label_id: lbl.id,
          object_id: r.object_id, unassign: has});
        inspect(r);
      }, async (lbl) => {
        if (confirm(`delete label "${lbl.name}" everywhere?`)) {
          await mut("labels.delete", {library_id: lib, id: lbl.id});
          inspect(r);
        }
      });
    const addl = document.createElement("span");
    addl.className = "tagchip"; addl.textContent = "+ label";
    addl.onclick = async () => {
      const nm = prompt("label name"); if (!nm) return;
      const lbl = await mut("labels.create", {library_id: lib, name: nm});
      await mut("labels.assign", {library_id: lib, label_id: lbl.id,
        object_id: r.object_id});
      inspect(r);
    };
    ll.appendChild(addl);
    const md = await q("files.getMediaData", {library_id: lib,
                                              id: r.object_id});
    if (md) {
      if (md.stream_data) {
        // audio/video container metadata rides as JSON
        try { Object.assign(md, JSON.parse(md.stream_data)); } catch {}
        delete md.stream_data;
      }
      const ex = document.getElementById("iexif");
      ex.innerHTML = "<h3>media data</h3>" +
        Object.entries(md).filter(([k, v]) => v != null && k !== "phash" &&
                                  k !== "object_id" && k !== "id")
          .map(([k, v]) => `<div class="kv">${esc(k)}: <b>${esc(v)}</b></div>`)
          .join("");
    }
  }
  document.getElementById("ifav").onclick = async () => {
    if (r.object_id == null) return toast("not identified yet");
    await mut("files.setFavorite", {library_id: lib, id: r.object_id,
      favorite: !(obj && obj.favorite)});
    inspect(r);
  };
  document.getElementById("irename").onclick = async () => {
    const nn = prompt("new name", name); if (!nn || nn === name) return;
    try {
      await mut("files.renameFile", {library_id: lib, file_path_id: r.id,
        new_name: nn});
      toast("renamed"); browse();
    } catch (e) { toast(e.message); }
  };
  document.getElementById("inote").onclick = async () => {
    if (r.object_id == null) return toast("not identified yet");
    const note = prompt("note", obj && obj.note || "");
    if (note === null) return;
    await mut("files.setNote", {library_id: lib, id: r.object_id, note});
    inspect(r);
  };
  document.getElementById("idup").onclick = async () => {
    await mut("files.duplicateFiles", {library_id: lib, location_id: loc,
      file_path_ids: [r.id]});
    toast("duplicating…");
  };
  document.getElementById("idel").onclick = async () => {
    if (!confirm(`delete ${name}?`)) return;
    await mut("files.deleteFiles", {library_id: lib, location_id: loc,
      file_path_ids: [r.id]});
    el.style.display = "none"; selected = null;
  };
}

// ---- Duplicates ------------------------------------------------------
async function renderDups() {
  const main = document.getElementById("main");
  if (!lib) return;
  const groups = await q("search.duplicates",
    {library_id: lib, location_id: loc});
  const total = groups.reduce((a, g) => a + (g.reclaimable_bytes || 0), 0);
  main.innerHTML = `<h3>Exact duplicates (by CAS ID)</h3>
    <div class="muted">${groups.length} groups · ` +
    `${fmtBytes(total)} reclaimable</div>
    <table><tr><th>cas_id</th><th>copies</th><th>total</th>
    <th>paths</th></tr>` +
    groups.map(g => `<tr><td>${esc(g.cas_id)}</td><td>${g.count}</td>
      <td>${fmtBytes(g.total_bytes)}</td>
      <td class="muted">${g.paths.map(esc).join("<br>")}</td></tr>`).join("")
    + "</table>";
}

// ---- Near-duplicates (device-backed analytics) -----------------------
async function renderNearDups() {
  const main = document.getElementById("main");
  if (!lib) return;
  const pairs = await q("search.nearDuplicates",
    {library_id: lib, max_distance: 10});
  main.innerHTML = `<h3>Near-duplicate images (pHash Hamming ≤ 10)</h3>
    <div style="margin:6px 0">
      <button id="rundet">run detector on location ${loc ?? "—"}</button>
      <span class="muted">batched DCT pHash + tiled Hamming all-pairs on
      the device; LSH bucketing past 100k images</span></div>
    <table><tr><th>distance</th><th>a</th><th>b</th></tr>` +
    pairs.map(p => `<tr><td>${p.distance}</td>
      <td class="muted">${p.paths_a.map(esc).join("<br>")}</td>
      <td class="muted">${p.paths_b.map(esc).join("<br>")}</td></tr>`)
      .join("") + "</table>";
  document.getElementById("rundet").onclick = async () => {
    if (loc == null) return toast("select a location first");
    await mut("jobs.nearDupDetector", {library_id: lib, id: loc});
    toast("near-dup detector started");
  };
}

// ---- Jobs console ----------------------------------------------------
const JSTATUS = {0:"queued",1:"running",2:"completed",3:"cancelled",
                 4:"failed",5:"paused",6:"completed+errors"};
async function renderJobs() {
  const main = document.getElementById("main");
  if (!lib) return;
  const reports = await q("jobs.reports", {library_id: lib});
  main.innerHTML = `<h3>Jobs</h3>
    <div style="margin:6px 0">
      <button id="jid">identify</button>
      <button id="jval">validate</button>
      <button id="jverify" class="ghost">verify (bit-rot)</button>
      <button id="jthumb" class="ghost">thumbnails</button>
      <button id="jclear" class="ghost">clear finished</button>
    </div>
    <table><tr><th>name</th><th>status</th><th>progress</th><th>created</th>
    <th></th></tr>` +
    reports.map(j => {
      const pct = j.task_count ?
        Math.round(100 * (j.completed_task_count || 0) / j.task_count) : 0;
      const running = j.status === 1, paused = j.status === 5;
      return `<tr><td>${esc(j.name)}</td><td>${JSTATUS[j.status] ?? j.status}</td>
        <td>${pct}% (${j.completed_task_count || 0}/${j.task_count || 0})</td>
        <td class="muted">${new Date((j.date_created||0)*1000)
          .toLocaleTimeString()}</td>
        <td>${running ? `<button class="ghost" onclick="jobCtl('pause','${j.id}')">⏸</button>` : ""}
            ${paused ? `<button class="ghost" onclick="jobCtl('resume','${j.id}')">▶</button>` : ""}
            ${(running || paused) ? `<button class="danger" onclick="jobCtl('cancel','${j.id}')">✕</button>` : ""}
        </td></tr>`;
    }).join("") + "</table>";
  const need = () => loc == null ? (toast("select a location"), false) : true;
  document.getElementById("jid").onclick = async () =>
    need() && (await mut("jobs.identifyUniqueFiles", {library_id: lib, id: loc}),
               renderJobs());
  document.getElementById("jval").onclick = async () =>
    need() && (await mut("jobs.objectValidator", {library_id: lib, id: loc}),
               renderJobs());
  document.getElementById("jverify").onclick = async () =>
    need() && (await mut("jobs.objectValidator",
                         {library_id: lib, id: loc, mode: "verify"}),
               renderJobs());
  document.getElementById("jthumb").onclick = async () =>
    need() && (await mut("jobs.generateThumbsForLocation",
                         {library_id: lib, id: loc}), renderJobs());
  document.getElementById("jclear").onclick = async () => {
    await mut("jobs.clearAll", {library_id: lib}); renderJobs();
  };
}
window.jobCtl = async (op, id) => {
  await mut("jobs." + op, {library_id: lib, id});
  renderJobs();
};

// ---- P2P -------------------------------------------------------------
async function renderP2P() {
  const main = document.getElementById("main");
  const st = await q("p2p.state");
  if (!st.enabled) {
    main.innerHTML = "<div class='muted'>p2p is not started</div>"; return;
  }
  main.innerHTML = `<h3>P2P</h3>
    <div class="kv">identity: <b>${esc(st.identity.slice(0, 24))}…</b>
      · port <b>${st.port}</b></div>
    <h3>Peers</h3>
    <table><tr><th>identity</th><th>addr</th><th></th></tr>` +
    st.peers.map(p => {
      // Beacon payloads are peer-controlled: port must never reach
      // innerHTML/onclick as a string (stored-XSS vector).
      const port = Number(p.port) || 0;
      return `<tr>
      <td class="muted">${esc(p.identity.slice(0, 24))}…</td>
      <td>${esc(p.addr)}:${port}</td>
      <td><button class="ghost" onclick="p2pPing('${esc(p.addr)}',${port})">ping</button>
          <button class="ghost" onclick="p2pPair('${esc(p.addr)}',${port})">pair</button>
          <button onclick="p2pDrop('${esc(p.addr)}',${port})">spacedrop</button>
      </td></tr>`;}).join("") + `</table>
    <div class="muted" style="margin-top:8px">spacedrop sends an absolute
    file path from this node; pairing joins the current library.</div>`;
}
window.p2pPing = async (addr, port) => {
  try { await mut("p2p.debugPing", {addr, port}); toast("pong"); }
  catch (e) { toast(e.message); }
};
window.p2pPair = async (addr, port) => {
  try {
    await mut("p2p.pair", {library_id: lib, addr, port});
    toast("paired");
  } catch (e) { toast(e.message); }
};
window.p2pDrop = async (addr, port) => {
  const file_path = prompt("absolute path of file to send");
  if (!file_path) return;
  try {
    await mut("p2p.spacedrop", {addr, port, file_path});
    toast("spacedrop sent");
  } catch (e) { toast(e.message); }
};

// ---- Settings --------------------------------------------------------
async function renderSettings() {
  const main = document.getElementById("main");
  if (!lib) return;
  const [stats, cats, vols, keysSetup, backups, prefs, nstate, info,
         notifs, syncOps] = await Promise.all([
    q("library.statistics", {library_id: lib}),
    q("categories.list", {library_id: lib}),
    q("volumes.list"),
    q("keys.isSetup", {library_id: lib}),
    q("backups.getAll"),
    q("preferences.get", {library_id: lib}),
    q("nodeState"),
    q("buildInfo"),
    q("notifications.get"),
    q("sync.messages", {library_id: lib}),
  ]);
  let account;
  try { account = await q("auth.me"); } catch (e) { account = null; }
  const catRows = Object.entries(cats).filter(([, n]) => n > 0)
    .map(([k, n]) => `<tr><td>${esc(k)}</td><td>${n}</td></tr>`).join("");
  main.innerHTML = `<h3>Account</h3><div id="account">` + (account
    ? `<div class="kv">signed in: <b>${esc(account.email)}</b>
       (${esc(account.id)})</div>
       <button id="logoutbtn" class="ghost">log out</button>`
    : `<button id="loginbtn">log in (device flow)</button>
       <span id="logincode" class="muted"></span>`) + `</div>
    <h3>This node</h3>
    <div class="kv">name: <b>${esc(nstate.name)}</b>
      <button id="renamenode" class="ghost">rename</button>
      · v${esc(info.version)}</div>
    <div class="kv">features: <b>${esc(nstate.features.join(", ") ||
      "none")}</b>
      <button id="togglep2pfiles" class="ghost">toggle filesOverP2P
      </button></div>
    <h3>Library</h3>
    <div class="kv"><button id="renamelib" class="ghost">rename library
      </button></div>
    <h3>Statistics</h3>` +
    Object.entries(stats).map(([k, v]) =>
      `<div class="kv">${esc(k)}: <b>${esc(v)}</b></div>`).join("") +
    `<h3>Categories</h3><table>${catRows}</table>
    <h3>Sync</h3>
    <div class="kv">op log: <b>${syncOps.length}</b> ops (latest page)
      <span id="synclive" class="muted"></span></div>
    <h3>Notifications</h3>
    <button id="notifytest" class="ghost">test (node)</button>
    <button id="notifytestlib" class="ghost">test (library)</button>
    <button id="dismissall" class="ghost">dismiss all</button>
    <table>` + notifs.slice(0, 8).map(nn =>
      `<tr><td>${esc(nn.kind || nn.title || "notification")}</td>
       <td class="muted">${nn.read ? "read" : "unread"}</td>
       <td><button class="ghost ndismiss" data-nid="${nn.id}"
            data-nlib="${esc(nn.library_id || lib)}">dismiss
       </button></td></tr>`).join("") + `</table>
    <h3>Volumes</h3><table>` +
    vols.map(v => `<tr><td>${esc(v.name || v.mount_point)}</td>
      <td>${fmtBytes(v.available_capacity)} free of
          ${fmtBytes(v.total_capacity)}</td></tr>`).join("") + `</table>
    <h3>Key manager</h3><div id="keys"></div>
    <h3>Backups</h3>
    <div><button id="dobackup">backup library now</button></div>
    <table>` + (backups.backups || backups).map(b =>
      `<tr><td>${esc(b.id || b.path || JSON.stringify(b)).slice(0, 60)}</td>
       <td class="muted">${esc(b.timestamp || b.date || "")}</td>
       <td><button class="ghost brestore" data-bid="${esc(b.id)}">restore
       </button><button class="danger bdelete" data-bid="${esc(b.id)}">×
       </button></td></tr>`)
      .join("") + `</table>
    <h3>Preferences</h3>
    <div class="kv">stored keys: <b>${Object.keys(prefs || {}).length}</b>
      <button id="setpref" class="ghost">set pref</button></div>`;

  // account card wiring (the RFC 8628 device flow, api/auth.rs)
  const loginBtn = document.getElementById("loginbtn");
  if (loginBtn) loginBtn.onclick = () => {
    subOnce("auth.loginSession", {poll_interval: 0.3}, (ev) => {
      const codeEl = document.getElementById("logincode");
      if (ev.state === "Start") {
        if (codeEl) codeEl.textContent =
          ` enter code ${ev.user_code} at ${ev.verification_url}`;
        return false;              // keep listening
      }
      if (ev.state === "Complete") { toast("signed in"); renderSettings(); }
      else toast("login failed");
      return true;                 // terminal: stop the stream
    });
  };
  const logoutBtn = document.getElementById("logoutbtn");
  if (logoutBtn) logoutBtn.onclick = async () => {
    await mut("auth.logout"); renderSettings();
  };
  document.getElementById("renamenode").onclick = async () => {
    const name = prompt("node name"); if (!name) return;
    await mut("nodes.edit", {name}); renderSettings();
  };
  document.getElementById("togglep2pfiles").onclick = async () => {
    await mut("toggleFeatureFlag", {feature: "filesOverP2P"});
    renderSettings();
  };
  document.getElementById("renamelib").onclick = async () => {
    const name = prompt("library name"); if (!name) return;
    await mut("library.edit", {id: lib, name}); loadLibs();
  };
  document.getElementById("notifytestlib").onclick = () =>
    mut("notifications.testLibrary", {library_id: lib})
      .then(renderSettings);
  document.getElementById("dismissall").onclick = () =>
    mut("notifications.dismissAll").then(renderSettings);
  document.querySelectorAll(".ndismiss").forEach(b => b.onclick = () =>
    mut("notifications.dismiss",
        {library_id: b.dataset.nlib, id: +b.dataset.nid})
      .then(renderSettings));
  if (syncSubLib !== lib) {
    syncSubLib = lib;
    sub("sync.newMessage", {library_id: lib}, () => {
      const el = document.getElementById("synclive");
      if (el) el.textContent = " · live ops arriving";
    });
  }

  const keysEl = document.getElementById("keys");
  if (!keysSetup) {
    keysEl.innerHTML = `<button id="ksetup">set up key manager</button>`;
    document.getElementById("ksetup").onclick = async () => {
      const pw = prompt("master password"); if (!pw) return;
      await mut("keys.setup", {library_id: lib, password: pw});
      renderSettings();
    };
  } else {
    const unlocked = await q("keys.isUnlocked", {library_id: lib});
    if (!unlocked) {
      keysEl.innerHTML = `<button id="kunlock">unlock</button>`;
      document.getElementById("kunlock").onclick = async () => {
        const pw = prompt("master password"); if (!pw) return;
        try {
          await mut("keys.unlock", {library_id: lib, password: pw});
          renderSettings();
        } catch (e) { toast(e.message); }
      };
    } else {
      const keys = await q("keys.list", {library_id: lib});
      keysEl.innerHTML = keys.map(k => {
        const u = esc(k.uuid || k.id);
        return `<div class="kv">${u} ${k.mounted ? "(mounted)" : ""}
          <button class="ghost kmnt" data-ku="${u}"
            data-m="${k.mounted ? 1 : 0}">
            ${k.mounted ? "unmount" : "mount"}</button>
          <button class="danger kdel" data-ku="${u}">×</button>
        </div>`;
      }).join("") +
        `<button id="kadd" class="ghost">add key</button>
         <button id="klock" class="ghost">lock</button>`;
      keysEl.querySelectorAll(".kmnt").forEach(b => b.onclick =
        async () => {
          await mut(+b.dataset.m ? "keys.unmount" : "keys.mount",
                    {uuid: b.dataset.ku});
          renderSettings();
        });
      keysEl.querySelectorAll(".kdel").forEach(b => b.onclick =
        async () => {
          if (!confirm("delete this key?")) return;
          await mut("keys.delete", {uuid: b.dataset.ku});
          renderSettings();
        });
      document.getElementById("kadd").onclick = async () => {
        const pw = prompt("new key password"); if (!pw) return;
        await mut("keys.add", {key: pw});
        renderSettings();
      };
      document.getElementById("klock").onclick = async () => {
        await mut("keys.lock", {library_id: lib}); renderSettings();
      };
    }
  }
  document.getElementById("dobackup").onclick = async () => {
    await mut("backups.backup", {library_id: lib});
    toast("backup written"); renderSettings();
  };
  document.querySelectorAll(".brestore").forEach(b => b.onclick =
    async () => {
      if (!confirm("restore this backup over the current library?"))
        return;
      await mut("backups.restore", {backup_id: b.dataset.bid});
      toast("backup restored"); loadAll();
    });
  document.querySelectorAll(".bdelete").forEach(b => b.onclick =
    async () => {
      await mut("backups.delete", {backup_id: b.dataset.bid});
      renderSettings();
    });
  document.getElementById("setpref").onclick = async () => {
    const k = prompt("preference key"); if (!k) return;
    const v = prompt("value");
    await mut("preferences.update", {library_id: lib, values: {[k]: v}});
    renderSettings();
  };
  document.getElementById("notifytest").onclick = () =>
    mut("notifications.test");
}

// ---- chrome wiring ---------------------------------------------------
document.getElementById("newlib").onclick = async () => {
  const name = prompt("library name"); if (!name) return;
  await mut("library.create", {name}); lib = null; loadLibs();
};
document.getElementById("newloc").onclick = async () => {
  const path = prompt("absolute path to index"); if (!path || !lib) return;
  await mut("locations.create", {library_id: lib, path});
  loadLocs();
};
document.getElementById("newtag").onclick = async () => {
  const name = prompt("tag name"); if (!name || !lib) return;
  const color = prompt("color (css, optional)") || null;
  await mut("tags.create", {library_id: lib, name, color});
  loadTags();
};
document.getElementById("newalbum").onclick = async () => {
  const name = prompt("album name"); if (!name || !lib) return;
  await mut("albums.create", {library_id: lib, name});
  loadGroupings();
};
document.getElementById("newspace").onclick = async () => {
  const name = prompt("space name"); if (!name || !lib) return;
  const description = prompt("description (optional)") || null;
  await mut("spaces.create", {library_id: lib, name, description});
  loadGroupings();
};
document.getElementById("search").oninput = (() => {
  let h; return () => { clearTimeout(h); h = setTimeout(() => {
    if (view !== "explorer") { view = "explorer"; renderTabs(); }
    browse();
  }, 250); };
})();
document.getElementById("favbtn").onclick = () => {
  favOnly = !favOnly;
  document.getElementById("favbtn").className = favOnly ? "" : "ghost";
  if (view === "explorer") browse();
};
function setViewMode(m) {
  viewMode = m;
  for (const [id, mm] of [["vgrid","grid"],["vlist","list"],
                          ["vmedia","media"]])
    document.getElementById(id).className =
      "viewbtn" + (viewMode === mm ? " on" : "");
  if (view === "explorer") browse();
}
document.getElementById("vgrid").onclick = () => setViewMode("grid");
document.getElementById("vlist").onclick = () => setViewMode("list");
document.getElementById("vmedia").onclick = () => setViewMode("media");
document.getElementById("pastebtn").onclick = doPaste;
document.getElementById("newfolder").onclick = async () => {
  if (view !== "explorer") { toast("open the explorer first"); return; }
  if (loc == null) { toast("select a location"); return; }
  const name = prompt("folder name"); if (!name) return;
  await mut("files.createFolder", {library_id: lib, location_id: loc,
    sub_path: curPath, name});
  setTimeout(() => { if (view === "explorer") browse(); }, 300);
};
setViewMode("grid");

sub("jobs.progress", null, (e) => {
  const el = document.getElementById("joblist");
  let row = document.getElementById("job-" + e.id);
  if (!row) {
    row = document.createElement("div"); row.className = "job";
    row.id = "job-" + e.id;
    row.innerHTML = `<span></span><div class="bar"><div></div></div>`;
    el.prepend(row);
  }
  row.querySelector("span").textContent =
    `${e.name || "job"} — ${e.message || ""}`;
  const pct = e.task_count ? (100 * (e.completed_task_count || 0) /
                              e.task_count) : 0;
  row.querySelector(".bar > div").style.width = pct + "%";
  if (e.task_count && e.completed_task_count >= e.task_count)
    setTimeout(() => row.remove(), 4000);
});
sub("invalidation.listen", null, (e) => {
  if (e.key === "search.paths" && view === "explorer") browse();
  if (e.key === "library.list") loadLibs();
  if (e.key === "tags.list") loadTags();
  if (e.key === "albums.list" || e.key === "spaces.list")
    loadGroupings();
  if (e.key === "jobs.reports" && view === "jobs") renderJobs();
});
sub("notifications.listen", null, (e) => {
  toast(`🔔 ${e.title || ""} ${e.content || e.message || ""}`);
});
sub("jobs.newThumbnail", null, (e) => {
  // live-patch just the matching cell's image — a directory of
  // hundreds of thumbnails must not trigger a refetch per event
  if (view !== "explorer" || !e.cas_id) return;
  const r = lastRows.find(x => x && x.cas_id === e.cas_id);
  if (!r) return;
  const el = document.querySelector(`[data-fpid="${r.id}"] .thumb`);
  if (!el || el.querySelector("img")) return;
  el.textContent = "";
  const img = document.createElement("img");
  img.src = `/spacedrive/thumbnail/${e.cas_id}.webp`;
  img.onerror = () => { img.remove(); el.textContent = "🗎"; };
  el.appendChild(img);
});
sub("p2p.events", null, async (e) => {
  if (e.type === "SpacedropProgress") {
    const el = document.getElementById("joblist");
    let row = document.getElementById("drop-" + e.id);
    if (!row) {
      row = document.createElement("div"); row.className = "job";
      row.id = "drop-" + e.id;
      row.innerHTML = `<span></span>
        <button class="ghost" style="float:right;font-size:10px">cancel
        </button><div class="bar"><div></div></div>`;
      row.querySelector("button").onclick = () =>
        mut("p2p.cancelSpacedrop", {id: e.id}).then(() => row.remove());
      el.prepend(row);
    }
    const pct = e.total ? Math.round(100 * e.bytes / e.total) : 0;
    row.querySelector("span").textContent =
      `spacedrop ${e.direction || ""} ${pct}%`;
    row.querySelector(".bar > div").style.width = pct + "%";
    if (e.bytes >= e.total) setTimeout(() => row.remove(), 3000);
    return;
  }
  if (e.type === "SpacedropRequest") {
    // The peer-supplied name is untrusted: suggest only its basename,
    // never a path ("../../etc/x" must not prefill the save prompt).
    const safe = (e.name || "spacedrop.bin")
      .split(/[\\/]/).pop().replace(/^\.+/, "") || "spacedrop.bin";
    const ok = confirm(
      `Spacedrop: accept "${safe}" (${e.size} bytes) from ${e.peer}?`);
    // Cancelling/clearing the prompt falls back to the safe name in the
    // current directory — an accepted drop is never silently rejected.
    const path = ok ? (prompt("save as", safe) || safe) : null;
    await mut("p2p.acceptSpacedrop", {id: e.id, path});
  }
});
renderTabs();
loadLibs();
