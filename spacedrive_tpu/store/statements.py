"""Central SQL statement contract registry — the store's machine-checked seam.

Every SQL statement the engine executes is DECLARED here — name, exact
SQL text, verb (read|write|ddl|pragma), touched tables, transaction
requirement, and result cardinality — and executed through
`Database.run(name, params)` / `run_many` / `run_tx` (store/db.py) or
the typed helpers. The reference gets this discipline from its
generated Prisma client (every query is a typed method); scattered
`execute("...")` literals gave us none of it: no inventory of reads vs
writes, no machine check that a write is tx-scoped, no seam to split
when ROADMAP item 4 moves writes onto a single-writer actor and reads
onto a connection pool. This registry IS that seam: `--sql-table`
renders it, sdlint's sql-discipline/tx-shape/schema-parity passes check
it statically, and store/sqlaudit.py enforces it at runtime.

Two declaration forms:

- `declare_stmt(name, sql, ...)` — an exact statement. The SQL is the
  single source of truth; call sites hold only the name.
- `declare_shape(name, skeleton, ...)` — a TEMPLATE for the small set
  of legitimately dynamic sites: the typed helpers (column lists vary
  per row dict), the sync engine's registry-generic apply code
  (table/column names come from store/models.py, guarded by
  `model.field()` before reaching SQL), and composable search filters.
  `{i}` slots match one SQL identifier which must exist in the model
  registry (tables ∪ columns — validated at runtime by the auditor);
  `{w}` slots match an arbitrary clause (dynamic WHERE/placeholder
  lists). sdlint matches f-string call sites against skeletons
  statically, the auditor matches the rendered SQL against the
  compiled pattern at runtime.

Write discipline: every write-verb declaration is tx_required — there
is no autocommit write path. `Database.run` demands the open `tx()`
connection for them; `run_tx` is the single-statement-transaction
sugar. The tier-1 registry test asserts this invariant holds for the
whole inventory (the acceptance gate for the item-4 actor split).

Design constraints (same as flags.py/models.py): stdlib + models only,
importable from every layer without cycles.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from . import models

__all__ = [
    "Stmt", "STATEMENTS", "SHAPES", "declare_stmt", "declare_shape",
    "get", "lookup_sql", "normalize_sql", "skeleton_of",
    "sql_table_markdown", "SqlContractError", "LARGE_TABLES",
    "VERBS", "CARDINALITIES",
]

VERBS = ("read", "write", "ddl", "pragma")
# read → what run() fetches; write/ddl/pragma carry "none" (cursor out).
CARDINALITIES = ("one", "many", "scalar", "none")

# Same dotted-name discipline as the timeout/channel registries.
NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)+$")

# Tables whose scans hurt at production scale: the EXPLAIN-sampling
# auditor mode (SDTPU_SQL_EXPLAIN) flags full-table scans on these into
# sd_sql_scan_total, and schema-parity warns on filters over their
# unindexed columns.
LARGE_TABLES = frozenset({
    "file_path", "object", "shared_operation", "shared_op_blob",
    "relation_operation", "media_data", "near_dup_pair", "job_scratch",
})

# Tables that exist without a model registration (SQLite internals).
_EXTERNAL_TABLES = frozenset({"sqlite_master"})

_WS_RE = re.compile(r"\s+")


def normalize_sql(sql: str) -> str:
    """Whitespace-collapsed statement text — the audit-match identity.
    SQL literals wrap across source lines freely; semantics don't."""
    return _WS_RE.sub(" ", sql).strip().rstrip(";").strip()


_VERB_KEYWORDS = {
    "SELECT": "read", "WITH": "read",
    "INSERT": "write", "UPDATE": "write", "DELETE": "write",
    "REPLACE": "write",
    "CREATE": "ddl", "DROP": "ddl", "ALTER": "ddl",
    "PRAGMA": "pragma",
}


def sql_verb_keyword(sql: str) -> Optional[str]:
    """The verb a statement's leading keyword implies, or None."""
    head = normalize_sql(sql).split(" ", 1)[0].upper()
    return _VERB_KEYWORDS.get(head)


class SqlContractError(RuntimeError):
    """A statement-contract violation at declare or dispatch time."""


@dataclass(frozen=True)
class Stmt:
    name: str
    sql: str                   # exact SQL, or the skeleton for shapes
    verb: str                  # read | write | ddl | pragma
    tables: Tuple[str, ...]
    tx_required: bool
    cardinality: str           # one | many | scalar | none
    coverage: str              # "tier1" | "tools"
    doc: str = ""
    shape: bool = False        # declared via declare_shape

    @property
    def large(self) -> bool:
        return bool(set(self.tables) & LARGE_TABLES)


STATEMENTS: Dict[str, Stmt] = {}  # sdlint: ok[unbounded-growth] import-time contract registry
SHAPES: Dict[str, Stmt] = {}  # sdlint: ok[unbounded-growth] import-time contract registry
_BY_SQL: Dict[str, str] = {}  # sdlint: ok[unbounded-growth] one entry per declared statement
# skeleton (normalized, slots erased to {}) → shape name, for the
# static pass's f-string matching; compiled regexes for the auditor.
_SHAPE_SKELETONS: Dict[str, str] = {}  # sdlint: ok[unbounded-growth] import-time contract registry
_SHAPE_PATTERNS: List[Tuple[re.Pattern, str]] = []  # sdlint: ok[unbounded-growth] import-time contract registry

_IDENT_RE = r"[A-Za-z_][A-Za-z0-9_]*"


def _registry_identifiers() -> frozenset:
    """Every table and column name the model registry knows — the set
    dynamic `{i}` slots are allowed to interpolate."""
    out = set(models.MODELS) | set(_EXTERNAL_TABLES)
    for m in models.MODELS.values():
        out.update(f.name for f in m.fields)
    return frozenset(out)


_REGISTRY_IDENTS = _registry_identifiers()


def _validate_common(name: str, verb: str, tables, tx_required: bool,
                     cardinality: Optional[str], coverage: str) -> str:
    if not NAME_RE.match(name):
        raise SqlContractError(
            f"statement name {name!r} must be dotted lower_snake "
            "(layer.what), like the timeout/channel registries")
    if name in STATEMENTS or name in SHAPES:
        raise SqlContractError(f"statement {name!r} declared twice")
    if verb not in VERBS:
        raise SqlContractError(f"{name}: verb {verb!r} not in {VERBS}")
    if coverage not in ("tier1", "tools"):
        raise SqlContractError(
            f"{name}: coverage {coverage!r} must be tier1|tools")
    for t in tables:
        if t not in models.MODELS and t not in _EXTERNAL_TABLES:
            raise SqlContractError(
                f"{name}: table {t!r} is not in the model registry")
    if verb == "read":
        if cardinality not in ("one", "many", "scalar"):
            raise SqlContractError(
                f"{name}: read statements need cardinality one|many|"
                f"scalar, got {cardinality!r}")
    else:
        if cardinality not in (None, "none"):
            raise SqlContractError(
                f"{name}: {verb} statements carry no cardinality")
        cardinality = "none"
    if verb == "write" and not tx_required:
        # THE invariant: no autocommit write path exists. Item 4's
        # group-commit actor splits along exactly this property.
        raise SqlContractError(
            f"{name}: write statements must declare tx_required=True")
    return cardinality


def declare_stmt(name: str, sql: str, *, verb: str,
                 tables: Tuple[str, ...] = (),
                 tx_required: bool = False,
                 cardinality: Optional[str] = None,
                 coverage: str = "tier1",
                 doc: str = "") -> str:
    """Declare one exact statement; returns the name (import-friendly).

    Validated here, once, at import: name discipline, verb/leading-
    keyword agreement, registry-known tables, write⇒tx_required,
    read⇒cardinality. The sdlint schema-parity pass re-checks
    tables/columns against store/models.py from the AST side."""
    cardinality = _validate_common(
        name, verb, tables, tx_required, cardinality, coverage)
    norm = normalize_sql(sql)
    kw_verb = sql_verb_keyword(norm)
    if kw_verb is not None and kw_verb != verb:
        raise SqlContractError(
            f"{name}: SQL leads with a {kw_verb} keyword but declares "
            f"verb={verb}")
    if norm in _BY_SQL:
        raise SqlContractError(
            f"{name}: SQL text already declared as {_BY_SQL[norm]!r} — "
            "reuse that name (audit matching must be unambiguous)")
    st = Stmt(name, norm, verb, tuple(tables), tx_required,
              cardinality, coverage, doc)
    STATEMENTS[name] = st
    _BY_SQL[norm] = name
    return name


def skeleton_of(skeleton: str) -> str:
    """Normalized skeleton with `{i}`/`{w}` slots erased to bare `{}` —
    what an f-string call site reduces to in the static pass."""
    return normalize_sql(skeleton).replace("{i}", "{}").replace(
        "{w}", "{}")


def declare_shape(name: str, skeleton: str, *, verb: str,
                  tables: Tuple[str, ...] = (),
                  tx_required: bool = False,
                  cardinality: Optional[str] = None,
                  coverage: str = "tier1",
                  doc: str = "") -> str:
    """Declare a statement TEMPLATE for a legitimately dynamic site.

    `{i}` = one identifier that must be a registry table/column name
    (checked per match at runtime); `{w}` = an arbitrary clause. The
    constant parts are exact. A shape is deliberately coarser than an
    exact statement — keep them few, and keep tables declared where
    they are fixed."""
    cardinality = _validate_common(
        name, verb, tables, tx_required, cardinality, coverage)
    norm = normalize_sql(skeleton)
    skel = skeleton_of(skeleton)
    if skel in _SHAPE_SKELETONS:
        raise SqlContractError(
            f"{name}: skeleton already declared as "
            f"{_SHAPE_SKELETONS[skel]!r}")
    parts: List[str] = []
    for tok in re.split(r"(\{i\}|\{w\})", norm):
        if tok == "{i}":
            parts.append(f"({_IDENT_RE})")
        elif tok == "{w}":
            parts.append(r"(?:.*?)")
        else:
            parts.append(re.escape(tok))
    pattern = re.compile("^" + "".join(parts) + "$", re.DOTALL)
    st = Stmt(name, norm, verb, tuple(tables), tx_required,
              cardinality, coverage, doc, shape=True)
    SHAPES[name] = st
    _SHAPE_SKELETONS[skel] = name
    _SHAPE_PATTERNS.append((pattern, name))
    return name


def get(name: str) -> Stmt:
    st = STATEMENTS.get(name)
    if st is None:
        raise SqlContractError(
            f"undeclared statement {name!r} (declare it in "
            "spacedrive_tpu/store/statements.py)")
    return st


# Shape matching memo: rendered dynamic SQL repeats heavily (one shape
# per table/column combination), so match once per distinct text.
# Capped — pathological param-churn trades match work for memory.
_MATCH_CAP = 4096
# capped by the len() guard in lookup_sql — never grows past _MATCH_CAP
_match_memo: Dict[str, Optional[str]] = {}  # sdlint: ok[unbounded-growth]


def lookup_sql(sql: str) -> Optional[Stmt]:
    """Contract for an executed statement's text: exact declarations
    first, then shape templates (with `{i}` captures validated against
    the model registry). None = undeclared."""
    norm = normalize_sql(sql)
    name = _BY_SQL.get(norm)
    if name is not None:
        return STATEMENTS[name]
    if norm in _match_memo:
        hit = _match_memo[norm]
        return SHAPES[hit] if hit is not None else None
    hit = None
    for pattern, shape_name in _SHAPE_PATTERNS:
        m = pattern.match(norm)
        if m is None:
            continue
        if all(g in _REGISTRY_IDENTS for g in m.groups()):
            hit = shape_name
            break
    if len(_match_memo) < _MATCH_CAP:
        _match_memo[norm] = hit
    return SHAPES[hit] if hit is not None else None


def shape_for_skeleton(skel: str) -> Optional[str]:
    """Shape name whose skeleton equals `skel` (already slot-erased,
    normalized) — the static pass's f-string lookup."""
    return _SHAPE_SKELETONS.get(skel)


def all_statements() -> List[Stmt]:
    """Exact statements then shapes, name-ordered — the inventory."""
    return ([STATEMENTS[n] for n in sorted(STATEMENTS)]
            + [SHAPES[n] for n in sorted(SHAPES)])


def sql_table_markdown() -> str:
    """README's generated statement table (`--sql-table`): the
    complete read/write seam, one row per declared statement/shape."""
    out = ["| Statement | Verb | Tables | Tx | Cardinality | Coverage |",
           "| --- | --- | --- | --- | --- | --- |"]
    for st in all_statements():
        name = f"`{st.name}`" + (" (shape)" if st.shape else "")
        tables = ", ".join(st.tables) if st.tables else "—"
        tx = "tx" if st.tx_required else "—"
        out.append(
            f"| {name} | {st.verb} | {tables} | {tx} | "
            f"{st.cardinality} | {st.coverage} |")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# THE statement namespace. Grouped by layer; every entry is enforced by
# the sdlint sql-discipline pass (undeclared literals fail the build)
# and by the runtime auditor (store/sqlaudit.py) in tier-1.
# ---------------------------------------------------------------------------

# -- store: Database internals (store/db.py) --------------------------------

declare_stmt(
    "store.init.instance_count",
    "SELECT COUNT(*) FROM instance",
    verb="read", tables=("instance",), cardinality="scalar",
    doc="Library-open probe: ≤1 instance row = never synced, so the "
        "lazy op-log indexes may drop (db.py __init__).")

# -- store: typed-helper shapes (store/db.py insert/update/...) -------------
# The helpers build SQL from the caller's row dict; the SHAPE is fixed,
# the column list varies. All writes, all tx-scoped (the helpers open
# tx() themselves or ride the caller's conn).

declare_shape(
    "store.helper.insert",
    "INSERT INTO {i} ({w}) VALUES ({w})",
    verb="write", tx_required=True,
    doc="Database.insert / insert_many (no-conflict form).")

declare_shape(
    "store.helper.insert_ignore",
    "INSERT OR IGNORE INTO {i} ({w}) VALUES ({w})",
    verb="write", tx_required=True,
    doc="Database.insert_many(ignore_conflicts=True) and the sync "
        "apply engine's seed-row inserts.")

declare_shape(
    "store.helper.update",
    "UPDATE {i} SET {w} WHERE {i} = ?",
    verb="write", tx_required=True,
    doc="Database.update (SET list from the values dict) and the "
        "sync apply engine's registry-derived single-column writes "
        "(field apply, FK-subselect resolution, cascade detach).")

declare_shape(
    "store.helper.upsert",
    "INSERT INTO {i} ({w}) VALUES ({w}) ON CONFLICT ({w}) "
    "DO UPDATE SET {w}",
    verb="write", tx_required=True,
    doc="Database.upsert.")

declare_shape(
    "store.helper.delete",
    "DELETE FROM {i} WHERE {i} = ?",
    verb="write", tx_required=True,
    doc="Database.delete and registry-derived single-key deletes "
        "(sync cascade, blob explode, quarantine drain).")

# -- sync: op factory / write path (sync/manager.py) ------------------------

declare_stmt(
    "sync.instances.all",
    "SELECT id, pub_id, timestamp FROM instance",
    verb="read", tables=("instance",), cardinality="many",
    doc="Instance-cache load at SyncManager init (ids, watermarks).")

declare_stmt(
    "sync.instances.id_by_pub",
    "SELECT id FROM instance WHERE pub_id = ?",
    verb="read", tables=("instance",), cardinality="one",
    doc="pub_id → local row id (cached in _instance_ids after one "
        "miss).")

declare_stmt(
    "sync.instances.set_watermark",
    "UPDATE instance SET timestamp = ? WHERE pub_id = ?",
    verb="write", tables=("instance",), tx_required=True,
    doc="Advance one instance's CRDT watermark, in the ingest tx.")

declare_stmt(
    "sync.oplog.insert_shared",
    "INSERT INTO shared_operation "
    "(timestamp, model, record_id, kind, data, instance_id) "
    "VALUES (?, ?, ?, ?, ?, ?)",
    verb="write", tables=("shared_operation",), tx_required=True,
    doc="Append shared-model op rows (single + executemany bulk; "
        "also the blob-explode target).")

declare_stmt(
    "sync.oplog.insert_relation",
    "INSERT INTO relation_operation "
    "(timestamp, relation, item_id, group_id, kind, data, instance_id) "
    "VALUES (?, ?, ?, ?, ?, ?, ?)",
    verb="write", tables=("relation_operation",), tx_required=True,
    doc="Append relation op rows.")

declare_stmt(
    "sync.blob.insert",
    "INSERT INTO shared_op_blob "
    "(model, min_ts, max_ts, n_ops, data, instance_id) "
    "VALUES (?, ?, ?, ?, ?, ?)",
    verb="write", tables=("shared_op_blob",), tx_required=True,
    doc="One page-level op blob per solo bulk chunk "
        "(bulk_shared_ops fast path).")

declare_stmt(
    "sync.oplog.max_ts_shared",
    "SELECT MAX(timestamp) AS t FROM shared_operation",
    verb="read", tables=("shared_operation",), cardinality="one",
    doc="Lazy _op_log_state init: highest logged shared-op stamp.")

declare_stmt(
    "sync.oplog.max_ts_relation",
    "SELECT MAX(timestamp) AS t FROM relation_operation",
    verb="read", tables=("relation_operation",), cardinality="one",
    doc="Lazy _op_log_state init: highest logged relation-op stamp.")

declare_stmt(
    "sync.oplog.max_ts_blob",
    "SELECT MAX(max_ts) AS t FROM shared_op_blob",
    verb="read", tables=("shared_op_blob",), cardinality="one",
    doc="Lazy _op_log_state init: highest blob-page stamp.")

declare_stmt(  # sdlint: ok[schema-parity] one-shot lazy probe, LIMIT 1, cached in _op_log_state
    "sync.oplog.has_tombstones",
    "SELECT 1 FROM shared_operation WHERE kind = 'd' LIMIT 1",
    verb="read", tables=("shared_operation",), cardinality="one",
    doc="Clone fast-path eligibility probe: any shared delete logged?")

# -- sync: read path / clone serving ----------------------------------------

declare_shape(
    "sync.oplog.page",
    "SELECT o.*, i.pub_id AS instance_pub_id FROM {i} o "
    "JOIN instance i ON i.id = o.instance_id WHERE {w} "
    "ORDER BY o.timestamp ASC LIMIT ?",
    verb="read", tables=("instance",), cardinality="many",
    doc="get_ops page over shared_operation/relation_operation with "
        "the per-instance watermark disjunction.")

declare_shape(
    "sync.oplog.window",
    "SELECT o.*, ? AS instance_pub_id FROM {i} o "
    "WHERE o.instance_id = ? AND o.timestamp > ? AND o.timestamp < ? "
    "ORDER BY o.timestamp LIMIT ?",
    verb="read", cardinality="many",
    doc="Clone-stream row-op window for one authoring instance "
        "(ops interleaved ahead of each verbatim blob page).")

declare_shape(
    "sync.blob.metas_watermarked",
    "SELECT b.id, b.model, b.min_ts, i.pub_id AS pub "
    "FROM shared_op_blob b JOIN instance i ON i.id = b.instance_id "
    "WHERE {w} ORDER BY b.min_ts",
    verb="read", tables=("shared_op_blob", "instance"),
    cardinality="many",
    doc="get_ops blob metadata filtered by the watermark disjunction.")

declare_stmt(
    "sync.blob.data_by_id",
    "SELECT data FROM shared_op_blob WHERE id = ?",
    verb="read", tables=("shared_op_blob",), cardinality="one",
    doc="Lazy per-page blob fetch (get_ops decode, clone stream).")

declare_stmt(
    "sync.clone.blob_metas",
    "SELECT b.id, b.model, b.min_ts, b.max_ts, b.n_ops, b.instance_id, "
    "i.pub_id AS pub FROM shared_op_blob b "
    "JOIN instance i ON i.id = b.instance_id ORDER BY b.min_ts",
    verb="read", tables=("shared_op_blob", "instance"),
    cardinality="many",
    doc="Clone-stream originator: every stored page in min_ts order.")

declare_stmt(
    "sync.blob.metas_batch",
    "SELECT id, model, instance_id, data FROM shared_op_blob "
    "ORDER BY min_ts LIMIT 16",
    verb="read", tables=("shared_op_blob",), cardinality="many",
    doc="_ensure_row_oplog explode batches (small txs, bounded lock "
        "hold).")

declare_stmt(
    "sync.blob.metas_sweep",
    "SELECT id, model, instance_id, data FROM shared_op_blob "
    "ORDER BY min_ts",
    verb="read", tables=("shared_op_blob",), cardinality="many",
    doc="Ingest straggler sweep under the write lock (late solo-era "
        "blob landing between explode and the ingest tx).")

declare_stmt(
    "sync.blob.delete",
    "DELETE FROM shared_op_blob WHERE id = ?",
    verb="write", tables=("shared_op_blob",), tx_required=True,
    doc="Blob-row delete after its ops explode to rows (atomic with "
        "the inserts).")

# -- sync: ingest / LWW compare ---------------------------------------------

declare_stmt(
    "sync.quarantine.insert",
    "INSERT OR IGNORE INTO quarantined_op (op_id, timestamp, data) "
    "VALUES (?, ?, ?)",
    verb="write", tables=("quarantined_op",), tx_required=True,
    doc="Park a permanently-inapplicable op (version skew) instead of "
        "freezing the watermark.")

declare_stmt(
    "sync.quarantine.all",
    "SELECT id, data FROM quarantined_op ORDER BY timestamp",
    verb="read", tables=("quarantined_op",), cardinality="many",
    doc="drain_quarantined_ops re-ingest scan at manager init.")

declare_stmt(
    "sync.quarantine.delete",
    "DELETE FROM quarantined_op WHERE id = ?",
    verb="write", tables=("quarantined_op",), tx_required=True,
    doc="Drop a quarantined op once it finally applied.")

declare_stmt(
    "sync.lww.shared_tombstone",
    "SELECT 1 FROM shared_operation WHERE model = ? "
    "AND record_id = ? AND kind = 'd' LIMIT 1",
    verb="read", tables=("shared_operation",), cardinality="one",
    doc="Remove-wins probe: is this record tombstoned?")

declare_stmt(
    "sync.lww.shared_update_coverage",
    "SELECT DISTINCT kind FROM shared_operation "
    "WHERE model = ? AND record_id = ? AND timestamp >= ? "
    "AND kind LIKE 'u:%'",
    verb="read", tables=("shared_operation",), cardinality="many",
    doc="Field-coverage LWW for update kinds (same-or-newer).")

declare_stmt(
    "sync.lww.superseding_updates",
    "SELECT DISTINCT kind FROM shared_operation WHERE model = ? "
    "AND record_id = ? AND timestamp > ? AND kind LIKE 'u:%'",
    verb="read", tables=("shared_operation",), cardinality="many",
    doc="Create-op apply: strictly-newer per-field updates the "
        "batched values must not clobber.")

declare_stmt(
    "sync.lww.shared_same_kind",
    "SELECT timestamp FROM shared_operation WHERE timestamp >= ? "
    "AND model = ? AND record_id = ? AND kind = ? "
    "ORDER BY timestamp DESC LIMIT 1",
    verb="read", tables=("shared_operation",), cardinality="one",
    doc="Exact-kind LWW compare (creates/deletes).")

declare_stmt(
    "sync.lww.relation_delete_check",
    "SELECT 1 FROM relation_operation WHERE relation = ? "
    "AND item_id = ? AND group_id = ? AND "
    "((kind = 'd' AND timestamp >= ?) OR "
    " (kind = 'c' AND timestamp > ?)) LIMIT 1",
    verb="read", tables=("relation_operation",), cardinality="one",
    doc="Relation delete staleness (newer delete, or reviving "
        "create).")

declare_stmt(
    "sync.lww.relation_nondelete_check",
    "SELECT 1 FROM relation_operation WHERE relation = ? "
    "AND item_id = ? AND group_id = ? AND timestamp >= ? "
    "AND kind IN (?, 'd') LIMIT 1",
    verb="read", tables=("relation_operation",), cardinality="one",
    doc="Relation create/update staleness (same-kind or delete).")

declare_stmt(
    "sync.lww.relation_superseding",
    "SELECT 1 FROM relation_operation WHERE relation = ? AND "
    "item_id = ? AND group_id = ? AND kind = ? AND timestamp > ? "
    "LIMIT 1",
    verb="read", tables=("relation_operation",), cardinality="one",
    doc="Relation-create field supersession probe.")

declare_stmt(
    "sync.pending.park",
    "INSERT INTO pending_relation_op "
    "(op_id, timestamp, data, item_model, item_key, "
    "group_model, group_key) "
    "SELECT ?, ?, ?, ?, ?, ?, ? WHERE NOT EXISTS "
    "(SELECT 1 FROM pending_relation_op WHERE op_id = ?)",
    verb="write", tables=("pending_relation_op",), tx_required=True,
    doc="Park an early relation op, op_id-deduped against "
        "redelivery.")

declare_stmt(
    "sync.pending.any",
    "SELECT 1 FROM pending_relation_op LIMIT 1",
    verb="read", tables=("pending_relation_op",), cardinality="one",
    doc="Fast-apply parity probe: any parked ops to drain after "
        "creates?")

declare_stmt(
    "sync.pending.all",
    "SELECT id, data FROM pending_relation_op ORDER BY timestamp",
    verb="read", tables=("pending_relation_op",), cardinality="many",
    doc="Drain scan of parked relation ops.")

declare_stmt(
    "sync.pending.delete",
    "DELETE FROM pending_relation_op WHERE id = ?",
    verb="write", tables=("pending_relation_op",), tx_required=True,
    doc="Unpark one relation op (applied, dead, or malformed).")

declare_stmt(
    "sync.pending.purge_refs",
    "DELETE FROM pending_relation_op WHERE "
    "(item_model = ? AND item_key = ?) OR "
    "(group_model = ? AND group_key = ?)",
    verb="write", tables=("pending_relation_op",), tx_required=True,
    doc="Shared delete purges parked ops referencing the dead record "
        "(indexed via the denormalized ref columns).")

# -- sync: registry-generic apply shapes ------------------------------------
# The apply engine is generic over store/models.py: table and column
# names come from the registry (model.field() guards every wire-
# controlled name before it reaches SQL), so these are shapes, not
# exact statements. `{i}` slots are runtime-checked against the
# registry's identifier set.

declare_shape(
    "sync.fk.resolve",
    "SELECT id FROM {i} WHERE pub_id = ?",
    verb="read", cardinality="one",
    doc="Sync-id (pub_id) → local row id, any shared table.")

declare_shape(
    "sync.apply.backfill_owner",
    "UPDATE {i} SET instance_id = ? WHERE {i} = ? "
    "AND instance_id IS NULL",
    verb="write", tx_required=True,
    doc="Create-op owner attribution backfill (apply + clone fast "
        "path).")

declare_shape(
    "sync.apply.relation_delete",
    "DELETE FROM {i} WHERE {i} = ? AND {i} = ?",
    verb="write", tx_required=True,
    doc="Relation-op link delete.")

declare_shape(
    "sync.apply.relation_set_field",
    "UPDATE {i} SET {i} = ? WHERE {i} = ? AND {i} = ?",
    verb="write", tx_required=True,
    doc="Relation-op extra-column write (e.g. date_created).")


# -- locations (locations/*.py + api location routes) -----------------------

declare_stmt(
    "location.all",
    "SELECT * FROM location",
    verb="read", tables=("location",), cardinality="many",
    doc="Location listing (api locations.list / nodes.listLocations).")

declare_stmt(
    "location.by_id",
    "SELECT * FROM location WHERE id = ?",
    verb="read", tables=("location",), cardinality="one",
    doc="Full location row (api routes, fs jobs, file serving).")

declare_stmt(
    "location.path_by_id",
    "SELECT path FROM location WHERE id = ?",
    verb="read", tables=("location",), cardinality="one",
    doc="Root path only (watcher, thumbnails, directory ops).")

declare_stmt(
    "location.pub_by_id",
    "SELECT pub_id FROM location WHERE id = ?",
    verb="read", tables=("location",), cardinality="one",
    doc="Sync id lookup for location delete/relink op emission.")

declare_stmt(
    "location.id_paths",
    "SELECT id, path FROM location",
    verb="read", tables=("location",), cardinality="many",
    doc="Online-check and watcher enumeration.")

declare_stmt(
    "location.paths",
    "SELECT path FROM location",
    verb="read", tables=("location",), cardinality="many",
    doc="Overlap check at location create.")

declare_stmt(
    "location.rules_for",
    "SELECT ir.* FROM indexer_rule ir "
    "JOIN indexer_rule_in_location irl "
    "ON irl.indexer_rule_id = ir.id WHERE irl.location_id = ?",
    verb="read", tables=("indexer_rule", "indexer_rule_in_location"),
    cardinality="many",
    doc="Rules attached to one location (indexer + api).")

declare_stmt(
    "location.rule.all",
    "SELECT * FROM indexer_rule",
    verb="read", tables=("indexer_rule",), cardinality="many",
    doc="Indexer-rule listing.")

declare_stmt(
    "location.rule.by_id",
    "SELECT * FROM indexer_rule WHERE id = ?",
    verb="read", tables=("indexer_rule",), cardinality="one",
    doc="One indexer rule.")

declare_stmt(
    "location.rule.default_flag",
    "SELECT default_rule FROM indexer_rule WHERE id = ?",
    verb="read", tables=("indexer_rule",), cardinality="one",
    doc="System-rule guard before delete.")

declare_stmt(
    "location.detach_rules",
    "DELETE FROM indexer_rule_in_location WHERE location_id = ?",
    verb="write", tables=("indexer_rule_in_location",),
    tx_required=True,
    doc="Rule re-attachment: clear before re-adding.")

declare_stmt(
    "location.attach_rule",
    "INSERT OR IGNORE INTO indexer_rule_in_location "
    "(location_id, indexer_rule_id) VALUES (?, ?)",
    verb="write", tables=("indexer_rule_in_location",),
    tx_required=True,
    doc="Attach one rule to a location.")

declare_shape(
    "location.shallow.page",
    "SELECT * FROM file_path WHERE {w} ORDER BY id LIMIT ?",
    verb="read", tables=("file_path",), cardinality="many",
    doc="Shallow-rescan identify page (location + optional sub-path "
        "filter).")

# -- identifier (objects/identifier.py) -------------------------------------

declare_stmt(
    "store.object_count",
    "SELECT COUNT(*) AS n FROM object",
    verb="read", tables=("object",), cardinality="one",
    doc="Object census (identifier cas-preload gate, library stats).")

declare_stmt(
    "store.last_rowid",
    "SELECT last_insert_rowid()",
    verb="read", cardinality="scalar",
    doc="Consecutive-rowid probe after a batched insert (identifier).")

declare_shape(
    "identifier.cas_links",
    "SELECT fp.cas_id AS cas_id, o.id AS oid, o.pub_id AS opub "
    "FROM file_path fp JOIN object o ON o.id = fp.object_id "
    "WHERE fp.cas_id IN ({w})",
    verb="read", tables=("file_path", "object"), cardinality="many",
    doc="Per-chunk existing-object probe by cas_id IN-list.")

declare_stmt(
    "identifier.cas_map",
    "SELECT fp.cas_id AS c, o.id AS oid, o.pub_id AS opub "
    "FROM file_path fp JOIN object o ON o.id = fp.object_id "
    "WHERE fp.cas_id IS NOT NULL",
    verb="read", tables=("file_path", "object"), cardinality="many",
    doc="Whole-library cas_id → object preload (bulk identify).")

declare_stmt(
    "identifier.object_insert",
    "INSERT INTO object (pub_id, kind, date_created) VALUES (?, ?, ?)",
    verb="write", tables=("object",), tx_required=True,
    doc="Object creates for unmatched cas_ids (executemany).")

declare_stmt(
    "identifier.object_by_pub",
    "SELECT id FROM object WHERE pub_id = ?",
    verb="read", tables=("object",), cardinality="one",
    doc="Consecutive-rowid assumption probe.")

declare_shape(
    "identifier.objects_by_pubs",
    "SELECT id, pub_id FROM object WHERE pub_id IN ({w})",
    verb="read", tables=("object",), cardinality="many",
    doc="Slow-path id lookup when the rowid probe fails.")

declare_stmt(
    "identifier.link_paths",
    "UPDATE file_path SET cas_id = ?, object_id = ? WHERE id = ?",
    verb="write", tables=("file_path",), tx_required=True,
    doc="ONE file_path update pass per chunk (executemany).")

declare_shape(
    "identifier.orphan_count",
    "SELECT COUNT(*) AS n FROM file_path WHERE {w}",
    verb="read", tables=("file_path",), cardinality="one",
    doc="Orphan census under the job's location/sub-path filters "
        "(identifier + validator reuse the filter builder).")

declare_shape(
    "identifier.orphan_page",
    "SELECT * FROM file_path WHERE {w} ORDER BY id ASC LIMIT ?",
    verb="read", tables=("file_path",), cardinality="many",
    doc="Keyset-paged orphan fetch per hash chunk.")

# -- indexer (locations/indexer_job.py, shallow.py) -------------------------

declare_stmt(
    "indexer.path_by_key",
    "SELECT * FROM file_path WHERE location_id = ? AND "
    "materialized_path = ? AND name = ? AND extension = ?",
    verb="read", tables=("file_path",), cardinality="one",
    doc="Existing row by the (location, path, name, ext) unique key "
        "(also fs_ops target probe).")

declare_stmt(
    "indexer.children",
    "SELECT pub_id, cas_id, is_dir, materialized_path, name, "
    "extension FROM file_path "
    "WHERE location_id = ? AND materialized_path = ?",
    verb="read", tables=("file_path",), cardinality="many",
    doc="Direct children of one directory (shallow diff).")

declare_shape(
    "indexer.paths_by_inodes",
    "SELECT inode, pub_id, materialized_path, name, extension "
    "FROM file_path WHERE location_id = ? AND inode IN ({w})",
    verb="read", tables=("file_path",), cardinality="many",
    doc="Move detection: existing rows by inode IN-list.")

declare_stmt(
    "indexer.path_current",
    "SELECT materialized_path, name FROM file_path WHERE pub_id = ?",
    verb="read", tables=("file_path",), cardinality="one",
    doc="Removal guard: row still at the recorded path?")

declare_shape(
    "indexer.desc_pubs",
    "SELECT pub_id FROM file_path WHERE {w}",
    verb="read", tables=("file_path",), cardinality="many",
    doc="Descendant pub_ids of a removed directory (op emission "
        "before the prefix delete).")

declare_shape(
    "indexer.desc_delete",
    "DELETE FROM file_path WHERE {w}",
    verb="write", tables=("file_path",), tx_required=True,
    doc="Prefix delete of a removed directory's descendants "
        "(materialized_like filter).")

declare_stmt(
    "indexer.path_delete_by_pub",
    "DELETE FROM file_path WHERE pub_id = ?",
    verb="write", tables=("file_path",), tx_required=True,
    doc="Single removed row delete (op emitted in the same tx).")

declare_stmt(
    "indexer.set_dir_size",
    "UPDATE file_path SET size_in_bytes_bytes = ? WHERE id = ?",
    verb="write", tables=("file_path",), tx_required=True,
    doc="Finalize dir-size rollup (ops via bulk_shared_ops in-tx).")

declare_stmt(
    "jobs.scratch.insert",
    "INSERT INTO job_scratch (job_id, data) VALUES (?, ?)",
    verb="write", tables=("job_scratch",), tx_required=True,
    doc="Spool one batch-job step payload.")

declare_stmt(
    "jobs.scratch.delete",
    "DELETE FROM job_scratch WHERE id = ?",
    verb="write", tables=("job_scratch",), tx_required=True,
    doc="Consume a spooled step atomically with its domain tx.")

declare_stmt(
    "jobs.scratch.delete_for_job",
    "DELETE FROM job_scratch WHERE job_id = ?",
    verb="write", tables=("job_scratch",), tx_required=True,
    doc="Sweep a finished/shed job's leftover scratch rows.")

# -- validator / dedup (objects/validator.py, objects/dedup.py) -------------

declare_shape(
    "validator.page",
    "SELECT id, pub_id, materialized_path, name, extension, "
    "integrity_checksum, size_in_bytes_bytes "
    "FROM file_path WHERE {w} AND id >= ? ORDER BY id LIMIT ?",
    verb="read", tables=("file_path",), cardinality="many",
    doc="Keyset-paged checksum fetch under the job filters.")

declare_stmt(
    "validator.fill_checksum",
    "UPDATE file_path SET integrity_checksum = ? "
    "WHERE id = ? AND integrity_checksum IS NULL",
    verb="write", tables=("file_path",), tx_required=True,
    doc="Fill-mode checksum write (never clobbers, executemany).")

declare_shape(
    "dedup.exact_groups",
    "SELECT fp.cas_id AS cas_id, COUNT(*) AS n, "
    "o.pub_id AS object_pub_id "
    "FROM file_path fp JOIN object o ON o.id = fp.object_id "
    "WHERE {w} GROUP BY fp.cas_id HAVING n > 1 "
    "ORDER BY n DESC LIMIT ?",
    verb="read", tables=("file_path", "object"), cardinality="many",
    doc="Exact-duplicate groups by cas_id (optional location "
        "filter).")

declare_stmt(
    "dedup.paths_by_cas",
    "SELECT materialized_path, name, extension, location_id, "
    "size_in_bytes_bytes FROM file_path WHERE cas_id = ?",
    verb="read", tables=("file_path",), cardinality="many",
    doc="Paths of one duplicate group.")

declare_shape(
    "dedup.image_rows",
    "SELECT fp.id, fp.object_id, fp.materialized_path, fp.name, "
    "fp.extension, md.phash AS phash "
    "FROM file_path fp "
    "LEFT JOIN media_data md ON md.object_id = fp.object_id "
    "WHERE {w} ORDER BY fp.id", verb="read",
    tables=("file_path", "media_data"), cardinality="many",
    doc="Images to perceptual-hash (extension + location filters).")

declare_stmt(
    "dedup.set_phash",
    "UPDATE media_data SET phash = ? WHERE object_id = ?",
    verb="write", tables=("media_data",), tx_required=True,
    doc="Store a computed phash on existing media_data.")

declare_stmt(
    "dedup.insert_phash_row",
    "INSERT OR IGNORE INTO media_data (object_id, phash) "
    "VALUES (?, ?)",
    verb="write", tables=("media_data",), tx_required=True,
    doc="Seed media_data when the EXIF pass never ran for this "
        "object.")

declare_stmt(
    "dedup.phashes_for_location",
    "SELECT DISTINCT md.object_id AS object_id, md.phash AS phash "
    "FROM media_data md "
    "JOIN file_path fp ON fp.object_id = md.object_id "
    "WHERE md.phash IS NOT NULL AND fp.location_id = ?",
    verb="read", tables=("media_data", "file_path"),
    cardinality="many",
    doc="Device near-dup sweep input codes.")

declare_stmt(
    "dedup.upsert_pair",
    "INSERT INTO near_dup_pair "
    "(object_a_id, object_b_id, distance, date_detected) "
    "VALUES (?, ?, ?, ?) "
    "ON CONFLICT (object_a_id, object_b_id) "
    "DO UPDATE SET distance = excluded.distance",
    verb="write", tables=("near_dup_pair",), tx_required=True,
    doc="Record one near-dup pair (re-detect refreshes distance).")

declare_stmt(
    "dedup.pairs_within",
    "SELECT * FROM near_dup_pair WHERE distance <= ? "
    "ORDER BY distance ASC LIMIT ?",
    verb="read", tables=("near_dup_pair",), cardinality="many",
    doc="Stored near-dup pairs for the search surface.")

declare_stmt(
    "dedup.paths_for_object",
    "SELECT materialized_path, name, extension "
    "FROM file_path WHERE object_id = ?",
    verb="read", tables=("file_path",), cardinality="many",
    doc="Display paths for one near-dup object.")

# -- media (media/processor.py, media/actor.py) -----------------------------

declare_shape(
    "media.file_rows",
    "SELECT id, pub_id, object_id, cas_id, materialized_path, "
    "name, extension FROM file_path WHERE {w} ORDER BY id",
    verb="read", tables=("file_path",), cardinality="many",
    doc="Media-processor scan rows (extension-set filter).")

declare_stmt(
    "media.data_exists",
    "SELECT id FROM media_data WHERE object_id = ?",
    verb="read", tables=("media_data",), cardinality="one",
    doc="Skip objects that already carry media_data.")

declare_stmt(
    "media.known_cas",
    "SELECT DISTINCT cas_id FROM file_path WHERE cas_id IS NOT NULL",
    verb="read", tables=("file_path",), cardinality="many",
    doc="Thumbnail cleanup: cas_ids still referenced by any library.")

# -- library / node (library.py statistics, node.py orphan remover) ---------

declare_stmt(
    "library.stats.path_count",
    "SELECT COUNT(*) AS n FROM file_path",
    verb="read", tables=("file_path",), cardinality="one",
    doc="Statistics: total file_path rows.")

declare_stmt(  # sdlint: ok[schema-parity] statistics IS a whole-table aggregate (u64 BE blobs defeat SQL SUM)
    "library.stats.file_sizes",
    "SELECT size_in_bytes_bytes FROM file_path WHERE is_dir = 0",
    verb="read", tables=("file_path",), cardinality="many",
    doc="Statistics: per-file sizes (summed host-side — the u64 BE "
        "blob encoding defeats SQL SUM).")

declare_stmt(
    "library.stats.unique_sizes",
    "SELECT MIN(size_in_bytes_bytes) AS s FROM file_path "
    "WHERE is_dir = 0 AND object_id IS NOT NULL GROUP BY object_id",
    verb="read", tables=("file_path",), cardinality="many",
    doc="Statistics: one size per object (dedup-aware bytes).")

declare_stmt(
    "library.stats.clear",
    "DELETE FROM statistics",
    verb="write", tables=("statistics",), tx_required=True,
    doc="Statistics snapshot is a single row, replaced in place.")

declare_stmt(
    "library.stats.insert",
    "INSERT INTO statistics (total_object_count, library_db_size, "
    "total_unique_bytes, total_bytes_used) VALUES (?, ?, ?, ?)",
    verb="write", tables=("statistics",), tx_required=True,
    doc="Persist the latest statistics snapshot.")

declare_stmt(
    "node.orphan_objects",
    "SELECT o.id, o.pub_id FROM object o "
    "LEFT JOIN file_path fp ON fp.object_id = o.id "
    "WHERE fp.id IS NULL LIMIT 512",
    verb="read", tables=("object", "file_path"), cardinality="many",
    doc="Orphan-object remover batch (no file_path references "
        "left).")

declare_stmt(
    "node.object_delete",
    "DELETE FROM object WHERE id = ?",
    verb="write", tables=("object",), tx_required=True,
    doc="Orphan-object delete (FK cascade handled in-tx).")

declare_stmt(
    "node.instance_pub_by_row",
    "SELECT pub_id FROM instance WHERE id = ?",
    verb="read", tables=("instance",), cardinality="one",
    doc="Locality check: which instance owns a location row "
        "(api file serving).")

declare_stmt(
    "sync.instances.rows",
    "SELECT * FROM instance",
    verb="read", tables=("instance",), cardinality="many",
    doc="Paired-peer identity re-arm at sync_net attach.")

# -- api: tags / labels (api/procedures.py) ---------------------------------

declare_stmt(
    "api.tag.all", "SELECT * FROM tag",
    verb="read", tables=("tag",), cardinality="many",
    doc="tags.list / tags.getWithObjects.")

declare_stmt(
    "api.tag.by_id", "SELECT * FROM tag WHERE id = ?",
    verb="read", tables=("tag",), cardinality="one",
    doc="Tag CRUD lookups.")

declare_stmt(
    "api.tag.for_object",
    "SELECT t.* FROM tag t JOIN tag_on_object to2 "
    "ON to2.tag_id = t.id WHERE to2.object_id = ?",
    verb="read", tables=("tag", "tag_on_object"), cardinality="many",
    doc="tags.getForObject.")

declare_stmt(
    "api.tag.object_ids",
    "SELECT object_id FROM tag_on_object WHERE tag_id = ?",
    verb="read", tables=("tag_on_object",), cardinality="many",
    doc="tags.getWithObjects member ids.")

declare_stmt(
    "api.tag.assigned_objects",
    "SELECT o.pub_id AS opub FROM tag_on_object tob "
    "JOIN object o ON o.id = tob.object_id WHERE tob.tag_id = ?",
    verb="read", tables=("tag_on_object", "object"),
    cardinality="many",
    doc="tags.delete: assignment pub_ids for FK-safe op order.")

declare_stmt(
    "api.tag.clear_assignments",
    "DELETE FROM tag_on_object WHERE tag_id = ?",
    verb="write", tables=("tag_on_object",), tx_required=True,
    doc="tags.delete: local assignment sweep (ops emitted in-tx).")

declare_stmt(
    "api.tag.unassign",
    "DELETE FROM tag_on_object WHERE tag_id = ? AND object_id = ?",
    verb="write", tables=("tag_on_object",), tx_required=True,
    doc="tags.assign(unassign=True).")

declare_stmt(
    "api.tag.assign",
    "INSERT OR IGNORE INTO tag_on_object (tag_id, object_id) "
    "VALUES (?, ?)",
    verb="write", tables=("tag_on_object",), tx_required=True,
    doc="tags.assign.")

declare_stmt(
    "api.label.list_with_counts",
    "SELECT l.*, COUNT(lo.label_id) AS object_count "
    "FROM label l LEFT JOIN label_on_object lo "
    "ON lo.label_id = l.id GROUP BY l.id",
    verb="read", tables=("label", "label_on_object"),
    cardinality="many",
    doc="labels.list.")

declare_stmt(
    "api.label.by_id", "SELECT * FROM label WHERE id = ?",
    verb="read", tables=("label",), cardinality="one",
    doc="Label CRUD lookups.")

declare_stmt(
    "api.label.for_object",
    "SELECT l.* FROM label l JOIN label_on_object lo "
    "ON lo.label_id = l.id WHERE lo.object_id = ?",
    verb="read", tables=("label", "label_on_object"),
    cardinality="many",
    doc="labels.getForObject.")

declare_stmt(
    "api.label.assigned_objects",
    "SELECT o.pub_id AS opub FROM label_on_object lo "
    "JOIN object o ON o.id = lo.object_id WHERE lo.label_id = ?",
    verb="read", tables=("label_on_object", "object"),
    cardinality="many",
    doc="labels.delete: assignment pub_ids for FK-safe op order.")

declare_stmt(
    "api.label.clear_assignments",
    "DELETE FROM label_on_object WHERE label_id = ?",
    verb="write", tables=("label_on_object",), tx_required=True,
    doc="labels.delete: local assignment sweep.")

declare_stmt(
    "api.label.unassign",
    "DELETE FROM label_on_object WHERE label_id = ? "
    "AND object_id = ?",
    verb="write", tables=("label_on_object",), tx_required=True,
    doc="labels.assign(unassign=True).")

declare_stmt(
    "api.label.assign",
    "INSERT OR IGNORE INTO label_on_object "
    "(label_id, object_id, date_created) VALUES (?, ?, ?)",
    verb="write", tables=("label_on_object",), tx_required=True,
    doc="labels.assign.")

# -- api: objects / files ---------------------------------------------------

declare_stmt(
    "api.object.by_id", "SELECT * FROM object WHERE id = ?",
    verb="read", tables=("object",), cardinality="one",
    doc="Object lookups across files.* and tag/label assignment.")

declare_stmt(
    "api.object.exists", "SELECT 1 FROM object WHERE id = ?",
    verb="read", tables=("object",), cardinality="one",
    doc="Stale-id guard before grouping membership inserts.")

declare_shape(
    "api.object.pubs_by_ids",
    "SELECT id, pub_id FROM object WHERE id IN ({w})",
    verb="read", tables=("object",), cardinality="many",
    doc="Multi-select access-time update: op targets by id list.")

declare_stmt(
    "api.object.set_access_time",
    "UPDATE object SET date_accessed = ? WHERE id = ?",
    verb="write", tables=("object",), tx_required=True,
    doc="files.updateAccessTime batch (ops in the same tx).")

declare_stmt(
    "api.object.kind_counts",
    "SELECT kind, COUNT(*) AS n FROM object GROUP BY kind",
    verb="read", tables=("object",), cardinality="many",
    doc="categories.list.")

declare_stmt(
    "api.file_path.by_id", "SELECT * FROM file_path WHERE id = ?",
    verb="read", tables=("file_path",), cardinality="one",
    doc="file_path row for files.* routes and fs jobs.")

declare_stmt(
    "api.file_path.for_object",
    "SELECT * FROM file_path WHERE object_id = ?",
    verb="read", tables=("file_path",), cardinality="many",
    doc="files.get attachments.")

declare_stmt(
    "api.media_data.for_object",
    "SELECT * FROM media_data WHERE object_id = ?",
    verb="read", tables=("media_data",), cardinality="one",
    doc="files.get / files.getMediaData.")

declare_stmt(
    "api.file_path.rename_descendants",
    "UPDATE file_path SET materialized_path = "
    "REPLACE(materialized_path, ?, ?) WHERE location_id = ? "
    "AND materialized_path LIKE ? ESCAPE '\\'",
    verb="write", tables=("file_path",), tx_required=True,
    doc="Directory rename: re-prefix every descendant's "
        "materialized_path.")

# -- api: grouping shapes (spaces/albums share one factory) -----------------

declare_shape(
    "api.grouping.list",
    "SELECT g.*, COUNT(r.{i}) AS object_count "
    "FROM {i} g LEFT JOIN {i} r ON r.{i} = g.id GROUP BY g.id",
    verb="read", cardinality="many",
    doc="spaces.list / albums.list with member counts.")

declare_shape(
    "api.grouping.get",
    "SELECT * FROM {i} WHERE id = ?",
    verb="read", cardinality="one",
    doc="Generic by-id fetch for the grouping factory.")

declare_shape(
    "api.grouping.exists",
    "SELECT 1 FROM {i} WHERE id = ?",
    verb="read", cardinality="one",
    doc="Existence probe for the grouping factory.")

declare_shape(
    "api.grouping.object_ids",
    "SELECT object_id FROM {i} WHERE {i} = ?",
    verb="read", cardinality="many",
    doc="Membership ids of one space/album.")

# -- api: jobs / search / preferences / notifications -----------------------

declare_stmt(
    "api.job.reports",
    "SELECT id, name, action, status, task_count, "
    "completed_task_count, errors_text, metadata, parent_id, "
    "date_created, date_started, date_completed, "
    "date_estimated_completion FROM job "
    "ORDER BY date_created DESC LIMIT 100",
    verb="read", tables=("job",), cardinality="many",
    doc="jobs.reports listing.")

declare_stmt(
    "api.job.clear",
    "DELETE FROM job WHERE id = ? AND status NOT IN (?, ?, ?)",
    verb="write", tables=("job",), tx_required=True,
    doc="jobs.clear (never a live job).")

declare_stmt(
    "api.job.clear_all",
    "DELETE FROM job WHERE status NOT IN (?, ?, ?)",
    verb="write", tables=("job",), tx_required=True,
    doc="jobs.clearAll (never live jobs).")

declare_shape(
    "api.search.paths_window",
    "SELECT fp.* FROM file_path fp WHERE {w} "
    "ORDER BY {w} {w}, fp.id LIMIT ? OFFSET ?",
    verb="read", tables=("file_path",), cardinality="many",
    doc="search.paths absolute-skip window (virtualized explorer).")

declare_shape(
    "api.search.paths_cursor",
    "SELECT fp.* FROM file_path fp WHERE {w} AND fp.id > ? "
    "ORDER BY fp.id LIMIT ?",
    verb="read", tables=("file_path",), cardinality="many",
    doc="search.paths keyset page.")

declare_shape(
    "api.search.paths_count",
    "SELECT COUNT(*) AS n FROM file_path fp WHERE {w}",
    verb="read", tables=("file_path",), cardinality="one",
    doc="search.pathsCount.")

declare_shape(
    "api.search.objects_window",
    "SELECT o.* FROM object o WHERE {w} "
    "ORDER BY {w} {w}, o.id LIMIT ? OFFSET ?",
    verb="read", tables=("object",), cardinality="many",
    doc="search.objects absolute-skip window.")

declare_shape(
    "api.search.objects_cursor",
    "SELECT o.* FROM object o WHERE {w} AND o.id > ? "
    "ORDER BY o.id LIMIT ?",
    verb="read", tables=("object",), cardinality="many",
    doc="search.objects keyset page.")

declare_shape(
    "api.search.objects_count",
    "SELECT COUNT(*) AS n FROM object o WHERE {w}",
    verb="read", tables=("object",), cardinality="one",
    doc="search.objectsCount.")

declare_shape(
    "api.search.paths_for_objects",
    "SELECT * FROM file_path WHERE object_id IN ({w})",
    verb="read", tables=("file_path",), cardinality="many",
    doc="One attachment query per search.objects page.")

declare_stmt(
    "api.preference.all", "SELECT * FROM preference",
    verb="read", tables=("preference",), cardinality="many",
    doc="preferences.get KV dump.")

declare_stmt(
    "api.preference.delete",
    "DELETE FROM preference WHERE key = ?",
    verb="write", tables=("preference",), tx_required=True,
    doc="preferences.update(None) key removal.")

declare_stmt(
    "api.notification.recent",
    "SELECT * FROM notification ORDER BY id DESC LIMIT 50",
    verb="read", tables=("notification",), cardinality="many",
    doc="notifications.get per library.")

declare_stmt(
    "api.notification.dismiss",
    "UPDATE notification SET read = 1 WHERE id = ?",
    verb="write", tables=("notification",), tx_required=True,
    doc="notifications.dismiss.")

declare_stmt(
    "api.notification.dismiss_all",
    "UPDATE notification SET read = 1",
    verb="write", tables=("notification",), tx_required=True,
    doc="notifications.dismissAll per library.")

# -- bench corpus writers (tools/; not on any tier-1 product path) ----------

declare_stmt(
    "bench.tag_insert",
    "INSERT INTO tag (pub_id, name) VALUES (?, ?)",
    verb="write", tables=("tag",), tx_required=True,
    coverage="tools",
    doc="sync_bench tag corpus (write_ops tx).")

# (sync_bench's corpus objects reuse identifier.object_insert — the
# bench deliberately mimics the identify write shape byte-for-byte.)

declare_stmt(
    "bench.op_count",
    "SELECT COUNT(*) FROM shared_operation",
    verb="read", tables=("shared_operation",), cardinality="scalar",
    coverage="tools",
    doc="load_bench clone-convergence census: ground-truth ops held "
        "by a simulated peer after its clone drains.")

declare_stmt(
    "bench.object_insert",
    "INSERT INTO object (pub_id, kind, note) VALUES (?, ?, ?)",
    verb="write", tables=("object",), tx_required=True,
    coverage="tools",
    doc="load_bench seed corpus: one blob wave's domain rows per tx "
        "(the wave's op-log page rides the same transaction).")

declare_stmt(
    "bench.file_path_insert",
    "INSERT INTO file_path (pub_id, name) VALUES (?, ?)",
    verb="write", tables=("file_path",), tx_required=True,
    coverage="tools",
    doc="sync_bench identify-shaped corpus paths.")

declare_stmt(
    "bench.file_path_link",
    "UPDATE file_path SET cas_id = ?, object_id = "
    "(SELECT id FROM object WHERE pub_id = ?) WHERE pub_id = ?",
    verb="write", tables=("file_path", "object"), tx_required=True,
    coverage="tools",
    doc="sync_bench identify-shaped corpus linking.")

# -- bench diagnostic reads (tools/) ----------------------------------------

declare_stmt(
    "jobs.report.by_id",
    "SELECT * FROM job WHERE id = ?",
    verb="read", tables=("job",), cardinality="one",
    coverage="tools",
    doc="perf_smoke per-stage report fetch.")

declare_stmt(  # sdlint: ok[schema-parity] bench diagnostic census, off the serving path
    "bench.file_count",
    "SELECT COUNT(*) AS n FROM file_path WHERE is_dir = 0",
    verb="read", tables=("file_path",), cardinality="one",
    coverage="tools",
    doc="perf_smoke per-stage file census.")

declare_stmt(
    "bench.identified_count",
    "SELECT COUNT(*) AS n FROM file_path WHERE is_dir = 0 "
    "AND cas_id IS NOT NULL",
    verb="read", tables=("file_path",), cardinality="one",
    coverage="tools",
    doc="perf_smoke summary: identified paths.")

declare_stmt(  # sdlint: ok[schema-parity] bench diagnostic census, off the serving path
    "bench.phash_count",
    "SELECT COUNT(*) AS n FROM media_data WHERE phash IS NOT NULL",
    verb="read", tables=("media_data",), cardinality="one",
    coverage="tools",
    doc="perf_smoke near-dup stage.")

declare_stmt(
    "bench.pair_count",
    "SELECT COUNT(*) AS n FROM near_dup_pair WHERE distance <= 10",
    verb="read", tables=("near_dup_pair",), cardinality="one",
    coverage="tools",
    doc="perf_smoke near-dup stage.")

declare_stmt(  # sdlint: ok[schema-parity] bench diagnostic census, off the serving path
    "bench.checksum_count",
    "SELECT COUNT(*) AS n FROM file_path "
    "WHERE integrity_checksum IS NOT NULL",
    verb="read", tables=("file_path",), cardinality="one",
    coverage="tools",
    doc="validator_device_bench progress census.")

declare_stmt(
    "bench.oplog_row_count",
    "SELECT COUNT(*) AS n FROM shared_operation",
    verb="read", tables=("shared_operation",), cardinality="one",
    coverage="tools",
    doc="sync_bench ingest-drain convergence poll.")

declare_stmt(
    "bench.oplog_total",
    "SELECT (SELECT COUNT(*) FROM shared_operation) + "
    "(SELECT COUNT(*) FROM relation_operation) AS n",
    verb="read", tables=("shared_operation", "relation_operation"),
    cardinality="one", coverage="tools",
    doc="sync_bench full-clone convergence poll.")

declare_stmt(
    "bench.tag_count",
    "SELECT COUNT(*) AS n FROM tag",
    verb="read", tables=("tag",), cardinality="one",
    coverage="tools",
    doc="sync_bench applied-tag census.")

declare_stmt(
    "bench.objects_digest",
    "SELECT pub_id, kind, date_created, note FROM object",
    verb="read", tables=("object",), cardinality="many",
    coverage="tools",
    doc="sync_bench byte-identity domain digest.")

declare_stmt(
    "bench.paths_digest",
    "SELECT fp.pub_id, fp.cas_id, o.pub_id AS opub "
    "FROM file_path fp LEFT JOIN object o ON o.id = fp.object_id",
    verb="read", tables=("file_path", "object"), cardinality="many",
    coverage="tools",
    doc="sync_bench byte-identity domain digest.")

declare_stmt(
    "bench.tags_digest",
    "SELECT pub_id, name FROM tag",
    verb="read", tables=("tag",), cardinality="many",
    coverage="tools",
    doc="sync_bench byte-identity domain digest.")

declare_stmt(
    "indexer.id_pub_by_key",
    "SELECT id, pub_id FROM file_path WHERE location_id = ? AND "
    "materialized_path = ? AND name = ? AND extension = ?",
    verb="read", tables=("file_path",), cardinality="one",
    doc="Finalize dir-size rollup: resolve each directory row by its "
        "unique key inside the rollup tx.")

declare_stmt(
    "jobs.scratch.data",
    "SELECT data FROM job_scratch WHERE id = ?",
    verb="read", tables=("job_scratch",), cardinality="one",
    doc="Unspool one batch-job step payload (missing row = the step "
        "already committed).")
