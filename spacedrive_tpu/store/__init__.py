from .db import Database, now_ts, rows_to_dicts, uuid_bytes
from .models import MODELS, Model, SyncMode

__all__ = [
    "Database", "MODELS", "Model", "SyncMode",
    "now_ts", "rows_to_dicts", "uuid_bytes",
]
