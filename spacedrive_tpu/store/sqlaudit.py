"""Runtime SQL auditor — the dynamic twin of sdlint's store passes.

Armed by `sanitize.install()` (flag `SDTPU_SQL_AUDIT`, default follows
SDTPU_SANITIZE): every Database connection is constructed from
`connection_class()`, a sqlite3.Connection subclass whose execute/
executemany match each statement's text against the contract registry
(store/statements.py) before it runs:

- **Declared** statements count into `sd_sql_statements_total{name}` /
  `sd_sql_rows_total{name}`; a write-verb statement executing outside
  an open `tx()` is a `sql_autocommit_write` violation (raised in
  tier-1, counted in production) — the single-writer discipline has no
  autocommit write path.
- **Undeclared** statements count `sd_sql_undeclared_total` and are a
  `sql_undeclared` violation. Exception: a READ on a thread inside the
  `adhoc()` allowance counts under the `_adhoc` label instead (never
  into the undeclared gate metric) — `Database.query`/`query_one`
  apply that allowance as the sanctioned ad-hoc DIAGNOSTIC read
  surface (tests, debugging) that the static sql-discipline pass
  keeps product code off.
- **DDL / PRAGMA / transaction-control / EXPLAIN** text passes through:
  schema bootstrap and the WAL machinery are store/db.py's whitelisted
  engine room (the static pass scopes them the same way).

Per-transaction statement counts land in the `sd_sql_tx_statements`
histogram at COMMIT (tx() brackets via tx_begin/tx_end) — the N+1 /
commit-per-item shapes the tx-shape pass hunts statically show up here
as a left-shifted histogram.

Opt-in EXPLAIN sampling (`SDTPU_SQL_EXPLAIN=N`, 0=off): every Nth
execution of a declared read over a registered large table runs
`EXPLAIN QUERY PLAN`; a full-table SCAN of a large table counts into
`sd_sql_scan_total{name}` — index regressions surface without tracing.

Disabled cost: `connection_class()` returns the plain
sqlite3.Connection and every hook is one `if not _armed` check.
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Any, Callable, Dict, Optional

from .. import flags
from ..telemetry import (
    SQL_ROWS,
    SQL_SCAN,
    SQL_STATEMENTS,
    SQL_TX_STATEMENTS,
    SQL_UNDECLARED,
)
from . import statements

__all__ = [
    "arm", "disarm", "armed", "connection_class", "adhoc",
    "tx_begin", "tx_end", "note_rows", "executed_names",
]

_armed = False
_record: Optional[Callable[[str, str, bool], None]] = None
_explain_every = 0
_tls = threading.local()

# Names observed executing since process start — the static↔runtime
# drift surfaces read it. Bounded by the declared-statement namespace
# (only registry names are ever inserted).
_executed: Dict[str, int] = {}  # sdlint: ok[unbounded-growth]
_executed_lock = threading.Lock()

# Leading keywords that bypass contract matching entirely: transaction
# control (tx() itself), schema/DDL bootstrap, PRAGMAs, and the
# auditor's own EXPLAIN probes.
_PASS_HEADS = frozenset({
    "BEGIN", "COMMIT", "ROLLBACK", "SAVEPOINT", "RELEASE",
    "CREATE", "DROP", "ALTER", "ANALYZE", "VACUUM", "REINDEX",
    "ATTACH", "DETACH", "PRAGMA", "EXPLAIN",
})

_WRITE_HEADS = frozenset({"INSERT", "UPDATE", "DELETE", "REPLACE"})


def armed() -> bool:
    return _armed


def arm(mode: str, record: Callable[[str, str, bool], None]) -> None:
    """Called by sanitize.install(). `record(kind, detail, may_raise)`
    is the sanitizer's violation hook — the raise/count split lives
    there. SDTPU_SQL_AUDIT=off skips arming (zero overhead); `auto`
    follows the sanitizer. Read once, at install."""
    global _armed, _record, _explain_every
    del mode  # raise/count is the record callback's concern
    level = flags.get("SDTPU_SQL_AUDIT")
    if level == "off":
        return
    _record = record
    _explain_every = max(0, int(flags.get("SDTPU_SQL_EXPLAIN")))
    _armed = True


def disarm() -> None:
    global _armed, _record
    _armed = False
    _record = None


def executed_names() -> Dict[str, int]:
    """Declared-statement execution counts since process start."""
    with _executed_lock:
        return dict(_executed)


class adhoc:
    """Thread-local allowance for ad-hoc diagnostic READS (Database.
    query/query_one, tests poking at a library). Writes are never
    excused — there is no ad-hoc write path."""

    def __enter__(self):
        _tls.adhoc = getattr(_tls, "adhoc", 0) + 1
        return self

    def __exit__(self, *exc):
        _tls.adhoc -= 1
        return False


def _in_adhoc() -> bool:
    return getattr(_tls, "adhoc", 0) > 0


def tx_begin(conn: sqlite3.Connection) -> None:
    """Bracket from Database.tx() right after BEGIN IMMEDIATE."""
    if not _armed:
        return
    try:
        conn._sd_in_tx = True
        conn._sd_tx_stmts = 0
    except AttributeError:  # plain sqlite3.Connection (pre-arm conn)
        pass


def tx_end(conn: sqlite3.Connection, committed: bool) -> None:
    if not _armed:
        return
    n = getattr(conn, "_sd_tx_stmts", None)
    try:
        conn._sd_in_tx = False
        conn._sd_tx_stmts = 0
    except AttributeError:
        return
    if committed and n:
        SQL_TX_STATEMENTS.observe(n)


def note_rows(name: str, n: int) -> None:
    """Fetched-row accounting for the read path (cursor rowcount is -1
    for SELECTs; Database.run counts what it actually fetched)."""
    if _armed and n:
        SQL_ROWS.labels(name=name).inc(n)


def _note_executed(name: str) -> None:
    with _executed_lock:
        _executed[name] = _executed.get(name, 0) + 1


def _violation(kind: str, detail: str) -> None:
    rec = _record
    if rec is not None:
        rec(kind, detail, True)


def _maybe_explain(conn: "AuditedConnection", st, sql: str,
                   params) -> None:
    count = _executed.get(st.name, 0)
    if count % _explain_every != 1 and _explain_every != 1:
        return
    try:
        plan = sqlite3.Connection.execute(
            conn, "EXPLAIN QUERY PLAN " + sql, params).fetchall()
    except sqlite3.Error:
        return
    for row in plan:
        detail = row["detail"] if "detail" in row.keys() else str(row)
        if not detail.startswith("SCAN"):
            continue
        if "USING" in detail:  # covering/index scan — fine
            continue
        # "SCAN file_path" (3.36+) / "SCAN TABLE file_path" (older)
        parts = [p for p in detail.split() if p != "TABLE"]
        table = parts[1] if len(parts) > 1 else ""
        if table in statements.LARGE_TABLES:
            SQL_SCAN.labels(name=st.name).inc()


def _observe(conn: "AuditedConnection", sql: str, params: Any,
             many: bool) -> Optional[Any]:
    """Pre-execute contract check; returns the matched Stmt (or None
    for pass-through text) so the caller can post rowcounts."""
    head = sql.lstrip().split(" ", 1)[0].split("\n", 1)[0].upper()
    if head in _PASS_HEADS:
        return None
    st = statements.lookup_sql(sql)
    in_tx = getattr(conn, "_sd_in_tx", False)
    if in_tx:
        conn._sd_tx_stmts = getattr(conn, "_sd_tx_stmts", 0) + 1
    if st is None:
        if _in_adhoc() and head not in _WRITE_HEADS:
            # sanctioned diagnostic read — counted under _adhoc, never
            # into the undeclared gate metric
            SQL_STATEMENTS.labels(name="_adhoc").inc()
            return None
        SQL_UNDECLARED.inc()
        _violation(
            "sql_undeclared",
            f"undeclared SQL reached the store: "
            f"{statements.normalize_sql(sql)[:200]!r} — declare it in "
            "spacedrive_tpu/store/statements.py (or use the typed "
            "helpers; ad-hoc diagnostic reads go through db.query)")
        return None
    SQL_STATEMENTS.labels(name=st.name).inc()
    _note_executed(st.name)
    if st.verb == "write" and not in_tx:
        _violation(
            "sql_autocommit_write",
            f"write statement {st.name!r} executed outside an open "
            "tx() — every write must ride a write transaction "
            "(db.run(..., conn=) from tx(), or db.run_tx)")
    if (_explain_every and not many and st.verb == "read" and st.large
            and isinstance(params, (tuple, list))):
        _maybe_explain(conn, st, sql, params)
    return st


class AuditedConnection(sqlite3.Connection):
    """sqlite3.Connection with the contract check on every execute.
    cursor()/fetch behavior is untouched; executescript is DDL-only in
    this codebase and passes through head-classification anyway."""

    def execute(self, sql: str, params=()):  # type: ignore[override]
        st = None
        if _armed:
            st = _observe(self, sql, params, many=False)
        cur = super().execute(sql, params)
        if st is not None and st.verb == "write" and cur.rowcount > 0:
            SQL_ROWS.labels(name=st.name).inc(cur.rowcount)
        return cur

    def executemany(self, sql: str, seq):  # type: ignore[override]
        st = None
        if _armed:
            st = _observe(self, sql, seq, many=True)
        cur = super().executemany(sql, seq)
        if st is not None and st.verb == "write" and cur.rowcount > 0:
            SQL_ROWS.labels(name=st.name).inc(cur.rowcount)
        return cur


def connection_class() -> type:
    """The sqlite3 factory Database._conn uses: audited when armed,
    the plain connection otherwise (zero overhead)."""
    return AuditedConnection if _armed else sqlite3.Connection


def stage_summary(top: int = 10) -> Dict[str, Any]:
    """The benches' `sql` artifact stage: top statements by count and
    by rows plus the per-tx statement histogram — an N+1 regression
    reads as a new hot single-row statement and a left-shifted
    histogram, gated in BENCH artifacts instead of found in prod."""
    from .. import telemetry

    snap = telemetry.snapshot()

    def _children(family: str) -> Dict[str, float]:
        fam = snap.get(family) or {}
        return {c["labels"]["name"]: c["value"]
                for c in fam.get("labeled", [])}

    counts = _children("sd_sql_statements_total")
    rows = _children("sd_sql_rows_total")
    hist = snap.get("sd_sql_tx_statements") or {}
    return {
        "top_by_count": sorted(counts.items(),
                               key=lambda kv: -kv[1])[:top],
        "top_by_rows": sorted(rows.items(),
                              key=lambda kv: -kv[1])[:top],
        "undeclared_total": (snap.get("sd_sql_undeclared_total")
                             or {}).get("value", 0),
        "tx_statements": {k: hist.get(k) for k in
                          ("count", "sum", "buckets") if k in hist},
    }
