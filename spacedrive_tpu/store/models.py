"""Declarative data model registry — schema + sync metadata in one place.

The reference defines its data model in Prisma schema doc-comments
(`/root/reference/core/prisma/schema.prisma`, 532 lines) and generates both
the DB client and per-model CRDT sync types from annotations (`@local`,
`@shared(id: …)`, `@relation(item, group)`) via
`/root/reference/crates/sync-generator/src/lib.rs:24-80`. Here the same
single-source-of-truth idea is a Python registry: each `Model` declares its
fields, indexes, and sync mode, and from it we derive (a) SQLite DDL
(store/db.py) and (b) CRDT apply/emit logic (sync/engine.py) — no codegen
step needed.

Sync modes (docs/developers/architecture/sync.mdx:22-47 semantics):
- LOCAL    — never synced (volumes, jobs, statistics).
- SHARED   — field-level last-write-wins CRDT keyed by a stable sync id.
- RELATION — CRDT over an (item, group) pair (tag_on_object).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field as dc_field
from typing import Dict, List, Optional, Sequence, Tuple


class SyncMode(enum.Enum):
    LOCAL = "local"
    SHARED = "shared"
    RELATION = "relation"


@dataclass(frozen=True)
class Field:
    name: str
    type: str  # SQLite affinity: INTEGER | TEXT | REAL | BLOB
    nullable: bool = True
    primary_key: bool = False
    autoincrement: bool = False
    unique: bool = False
    default: Optional[str] = None  # raw SQL default
    references: Optional[str] = None  # "table(column)"
    on_delete: Optional[str] = None  # CASCADE | SET NULL | ...
    local_only: bool = False  # excluded from sync even on SHARED models


@dataclass(frozen=True)
class Model:
    name: str  # table name, snake_case
    fields: Tuple[Field, ...]
    sync: SyncMode = SyncMode.LOCAL
    # SHARED: field names forming the stable sync id (usually pub_id).
    sync_id: Tuple[str, ...] = ()
    # RELATION: (item_field, group_field) — each a FK whose sync id is the
    # referenced model's sync id.
    relation: Optional[Tuple[str, str]] = None
    uniques: Tuple[Tuple[str, ...], ...] = ()
    indexes: Tuple[Tuple[str, ...], ...] = ()
    # Indexes that only serve a subsystem's READ paths (e.g. the op
    # log's sync-side lookups) and would tax every bulk local write:
    # excluded from bootstrap DDL, built on first use via
    # Database.ensure_lazy_indexes(table).
    lazy_indexes: Tuple[Tuple[str, ...], ...] = ()

    def field(self, name: str) -> Field:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(f"{self.name}.{name}")

    @property
    def synced_fields(self) -> List[Field]:
        return [
            f
            for f in self.fields
            if not f.primary_key
            and not f.local_only
            and f.name not in self.sync_id
        ]


def _id() -> Field:
    return Field("id", "INTEGER", nullable=False, primary_key=True, autoincrement=True)


def _pub_id() -> Field:
    return Field("pub_id", "BLOB", nullable=False, unique=True)


MODELS: Dict[str, Model] = {}  # sdlint: ok[unbounded-growth] import-time schema registry: one entry per declared model


def register(model: Model) -> Model:
    assert model.name not in MODELS, model.name
    MODELS[model.name] = model
    return model


# --- CRDT op logs (schema.prisma:21-55). Local by definition. -------------

register(Model(
    "shared_operation",
    (
        _id(),
        Field("timestamp", "INTEGER", nullable=False),  # HLC as u64 NTP64
        Field("model", "TEXT", nullable=False),
        Field("record_id", "BLOB", nullable=False),  # msgpack sync id
        Field("kind", "TEXT", nullable=False),  # c | u:<field> | u:a+b (multi) | d
        Field("data", "BLOB", nullable=False),  # msgpack payload
        Field("instance_id", "INTEGER", nullable=False,
              references="instance(id)"),
    ),
    # Both indexes serve only the sync read paths (get_ops watermark
    # scans, ingest LWW compare). Local bulk writers (identifier/
    # indexer/validator) append millions of op rows, and the random
    # (model, record_id) btree inserts were the measured superlinear
    # cost at 1M files — so the indexes build lazily on first sync use
    # (SyncManager._ensure_sync_indexes) instead of taxing every scan.
    lazy_indexes=(("timestamp",), ("model", "record_id")),
))

# Page-level op-log blobs: a bulk writer's whole chunk of shared ops
# (identifier/indexer, ~4-10k ops) lands as ONE row here instead of
# that many shared_operation rows — the op-log append was the measured
# wall of the 1M identify (README phase_ms: 16.7 s encode+insert vs
# 15.7 s of hashing). `data` is a msgpack array of per-op
# [timestamp, record_id(bin), kind, payload(bin)] entries where
# `payload` is byte-identical to what shared_operation.data would
# hold (sync/opblob.py; natively encoded by sdio.cpp sd_encode_ops).
# Blobs are written only while the library is SOLO (single instance);
# get_ops reads them directly, and the first remote ingest explodes
# them into indexed rows (SyncManager._ensure_row_oplog) because the
# per-record LWW compares need the (model, record_id) index.
register(Model(
    "shared_op_blob",
    (
        _id(),
        Field("model", "TEXT", nullable=False),
        Field("min_ts", "INTEGER", nullable=False),
        Field("max_ts", "INTEGER", nullable=False),
        Field("n_ops", "INTEGER", nullable=False),
        Field("data", "BLOB", nullable=False),
        Field("instance_id", "INTEGER", nullable=False,
              references="instance(id)"),
    ),
    # One cheap index: get_ops pages skip fully-served blobs by
    # watermark; bulk writers append a handful of rows per chunk, so
    # unlike the per-op tables this maintenance cost is negligible.
    indexes=(("max_ts",),),
))

# Relation ops that arrived before the rows they reference (cross-
# instance arrival order is not timestamp-ordered): parked here instead
# of the op log — logging them would make _compare_message reject the
# redelivery forever — and drained after shared creates land.
register(Model(
    "pending_relation_op",
    (
        _id(),
        # Redelivered pages re-park the same op (the watermark freeze
        # re-serves unapplied ops by design) — the ingest INSERT dedups
        # on op_id via WHERE NOT EXISTS, or drain would graduate N
        # duplicates into the op log. Deliberately a PLAIN NULLABLE
        # column (not UNIQUE): this table predates the column, and the
        # additive migration can only ALTER in plain nullable columns —
        # a UNIQUE constraint here would brick every pre-existing
        # library at open (SQLite can't ADD a UNIQUE column).
        Field("op_id", "BLOB"),
        Field("timestamp", "INTEGER", nullable=False),
        Field("data", "BLOB", nullable=False),  # packed CRDTOperation
        # Referenced (target model, packed sync id) pairs, denormalized
        # at park time so a shared delete purges dead parked ops with
        # one indexed DELETE instead of unpacking the whole table.
        # Nullable: rows parked by an older schema lack them and fall
        # back to the drain-time tombstone check.
        Field("item_model", "TEXT"),
        Field("item_key", "BLOB"),
        Field("group_model", "TEXT"),
        Field("group_key", "BLOB"),
    ),
    indexes=(("timestamp",), ("op_id",), ("item_model", "item_key"),
             ("group_model", "group_key")),
))

# Ops this node's schema cannot apply (unknown model — version skew
# with a newer peer): quarantined instead of dropped, because the
# watermark advances past them and get_ops would never re-serve them.
# SyncManager.drain_quarantined_ops re-ingests after a schema upgrade
# teaches the registry the model.
register(Model(
    "quarantined_op",
    (
        _id(),
        Field("op_id", "BLOB", nullable=False, unique=True),
        Field("timestamp", "INTEGER", nullable=False),
        Field("data", "BLOB", nullable=False),  # packed CRDTOperation
    ),
    indexes=(("timestamp",),),
))

register(Model(
    "relation_operation",
    (
        _id(),
        Field("timestamp", "INTEGER", nullable=False),
        Field("relation", "TEXT", nullable=False),
        Field("item_id", "BLOB", nullable=False),
        Field("group_id", "BLOB", nullable=False),
        Field("kind", "TEXT", nullable=False),
        Field("data", "BLOB", nullable=False),
        Field("instance_id", "INTEGER", nullable=False,
              references="instance(id)"),
    ),
    # Sync-side reads only, as above. (relation, item_id) narrows the
    # per-record LWW compares exactly like shared_operation's
    # (model, record_id) — surfaced by schema-parity's
    # unindexed-filter over the relation compare statements.
    lazy_indexes=(("timestamp",), ("relation", "item_id")),
))

# --- Instances (schema.prisma:70-97): one row per (device, library). ------

register(Model(
    "instance",
    (
        _id(),
        _pub_id(),
        Field("identity", "BLOB", nullable=False),  # ed25519 public key
        Field("node_id", "BLOB", nullable=False),
        Field("node_name", "TEXT", nullable=False),
        Field("node_platform", "INTEGER", nullable=False),
        Field("last_seen", "INTEGER", nullable=False),
        Field("date_created", "INTEGER", nullable=False),
        Field("timestamp", "INTEGER"),  # latest HLC seen from this instance
    ),
))

register(Model(
    "statistics",
    (
        _id(),
        Field("date_captured", "INTEGER", nullable=False,
              default="(strftime('%s','now'))"),
        Field("total_object_count", "INTEGER", nullable=False, default="0"),
        Field("library_db_size", "TEXT", nullable=False, default="'0'"),
        Field("total_bytes_used", "TEXT", nullable=False, default="'0'"),
        Field("total_bytes_capacity", "TEXT", nullable=False, default="'0'"),
        Field("total_unique_bytes", "TEXT", nullable=False, default="'0'"),
        Field("total_bytes_free", "TEXT", nullable=False, default="'0'"),
        Field("preview_media_bytes", "TEXT", nullable=False, default="'0'"),
    ),
))

# --- Volumes (@local, schema.prisma:114). ---------------------------------

register(Model(
    "volume",
    (
        _id(),
        Field("name", "TEXT", nullable=False),
        Field("mount_point", "TEXT", nullable=False),
        Field("total_bytes_capacity", "TEXT", nullable=False, default="'0'"),
        Field("total_bytes_available", "TEXT", nullable=False, default="'0'"),
        Field("disk_type", "TEXT"),
        Field("filesystem", "TEXT"),
        Field("is_system", "INTEGER", nullable=False, default="0"),
        Field("date_modified", "INTEGER", nullable=False,
              default="(strftime('%s','now'))"),
    ),
    uniques=(("mount_point", "name"),),
))

# --- Locations (@shared(id: pub_id), schema.prisma:130). ------------------

register(Model(
    "location",
    (
        _id(),
        _pub_id(),
        Field("name", "TEXT"),
        Field("path", "TEXT"),
        Field("total_capacity", "INTEGER"),
        Field("available_capacity", "INTEGER"),
        Field("is_archived", "INTEGER"),
        Field("generate_preview_media", "INTEGER"),
        Field("sync_preview_media", "INTEGER"),
        Field("hidden", "INTEGER"),
        Field("date_created", "INTEGER"),
        Field("instance_id", "INTEGER", references="instance(id)",
              local_only=True),
    ),
    sync=SyncMode.SHARED,
    sync_id=("pub_id",),
))

# --- FilePath (@shared, schema.prisma:155-198). ---------------------------

register(Model(
    "file_path",
    (
        _id(),
        _pub_id(),
        Field("is_dir", "INTEGER"),
        Field("cas_id", "TEXT"),  # schema.prisma:162
        Field("integrity_checksum", "TEXT"),  # schema.prisma:164
        Field("location_id", "INTEGER", references="location(id)",
              on_delete="CASCADE"),
        Field("materialized_path", "TEXT"),  # schema.prisma:171
        Field("name", "TEXT"),
        Field("extension", "TEXT"),
        Field("size_in_bytes_bytes", "BLOB"),  # u64 BE bytes, like :178
        Field("inode", "BLOB"),  # schema.prisma:181
        Field("object_id", "INTEGER", references="object(id)"),
        Field("key_id", "INTEGER"),
        Field("date_created", "INTEGER"),
        Field("date_modified", "INTEGER"),
        Field("date_indexed", "INTEGER"),
    ),
    sync=SyncMode.SHARED,
    sync_id=("pub_id",),
    uniques=(
        ("location_id", "materialized_path", "name", "extension"),  # :197
        ("location_id", "inode"),  # :198
    ),
    indexes=(("location_id",), ("cas_id",), ("object_id",)),
))

# --- Object (@shared, schema.prisma:204). ---------------------------------

register(Model(
    "object",
    (
        _id(),
        _pub_id(),
        Field("kind", "INTEGER"),
        Field("key_id", "INTEGER"),
        Field("hidden", "INTEGER"),
        Field("favorite", "INTEGER"),
        Field("important", "INTEGER"),
        Field("note", "TEXT"),
        Field("date_created", "INTEGER"),
        Field("date_accessed", "INTEGER"),
    ),
    sync=SyncMode.SHARED,
    sync_id=("pub_id",),
))

# --- MediaData (schema.prisma:298). ---------------------------------------

register(Model(
    "media_data",
    (
        _id(),
        Field("object_id", "INTEGER", nullable=False, unique=True,
              references="object(id)", on_delete="CASCADE"),
        Field("resolution", "BLOB"),
        Field("media_date", "BLOB"),
        Field("media_location", "BLOB"),
        Field("camera_data", "BLOB"),
        Field("artist", "TEXT"),
        Field("description", "TEXT"),
        Field("copyright", "TEXT"),
        Field("exif_version", "TEXT"),
        Field("epoch_time", "INTEGER"),
        # Net-new vs the reference: 64-bit perceptual hash (big-endian
        # bytes) for device-side near-dup search (BASELINE.json config 4).
        Field("phash", "BLOB"),
        # Net-new: audio/video container metadata as JSON (the
        # reference's audio.rs/video.rs structs are stubs; here the
        # self-hosted parsers in media/audio.py fill them for real).
        Field("stream_data", "TEXT"),
    ),
))

# --- Near-dup pairs (net-new capability; no reference analog). ------------

register(Model(
    "near_dup_pair",
    (
        _id(),
        Field("object_a_id", "INTEGER", nullable=False,
              references="object(id)", on_delete="CASCADE"),
        Field("object_b_id", "INTEGER", nullable=False,
              references="object(id)", on_delete="CASCADE"),
        Field("distance", "INTEGER", nullable=False),
        Field("date_detected", "INTEGER"),
    ),
    uniques=(("object_a_id", "object_b_id"),),
    # distance serves the search.nearDuplicates threshold filter —
    # surfaced by sdlint's schema-parity unindexed-filter check.
    indexes=(("object_a_id",), ("object_b_id",), ("distance",)),
))

# --- Tags (@shared; TagOnObject @relation — schema.prisma:331,349). -------

register(Model(
    "tag",
    (
        _id(),
        _pub_id(),
        Field("name", "TEXT"),
        Field("color", "TEXT"),
        Field("redundancy_goal", "INTEGER"),
        Field("date_created", "INTEGER"),
        Field("date_modified", "INTEGER"),
    ),
    sync=SyncMode.SHARED,
    sync_id=("pub_id",),
))

register(Model(
    "tag_on_object",
    (
        Field("tag_id", "INTEGER", nullable=False, primary_key=True,
              references="tag(id)"),
        Field("object_id", "INTEGER", nullable=False, primary_key=True,
              references="object(id)"),
    ),
    sync=SyncMode.RELATION,
    relation=("object_id", "tag_id"),  # (item, group) like the reference
    # object_id is the composite PK's SECOND column — the apply-side
    # delete cascade's WHERE object_id = ? needs its own index.
    indexes=(("object_id",),),
))

register(Model(
    "label",
    (
        _id(),
        _pub_id(),
        Field("name", "TEXT"),
        Field("date_created", "INTEGER"),
        Field("date_modified", "INTEGER"),
    ),
    sync=SyncMode.SHARED,
    sync_id=("pub_id",),
))

register(Model(
    "label_on_object",
    (
        Field("label_id", "INTEGER", nullable=False, primary_key=True,
              references="label(id)"),
        Field("object_id", "INTEGER", nullable=False, primary_key=True,
              references="object(id)"),
        Field("date_created", "INTEGER"),
    ),
    sync=SyncMode.RELATION,
    relation=("object_id", "label_id"),
    indexes=(("object_id",),),
))

# --- Space / Album (schema.prisma:389-411, 448-477): object groupings.
# The reference leaves these sync-UNannotated (its generator emits no
# sync types for them), so they stay LOCAL here too.

register(Model(
    "space",
    (
        _id(),
        _pub_id(),
        Field("name", "TEXT"),
        Field("description", "TEXT"),
        Field("date_created", "INTEGER"),
        Field("date_modified", "INTEGER"),
    ),
    sync=SyncMode.LOCAL,
))

register(Model(
    "object_in_space",
    (
        Field("space_id", "INTEGER", nullable=False, primary_key=True,
              references="space(id)"),
        Field("object_id", "INTEGER", nullable=False, primary_key=True,
              references="object(id)"),
    ),
    sync=SyncMode.LOCAL,
    indexes=(("object_id",),),
))

register(Model(
    "album",
    (
        _id(),
        _pub_id(),
        Field("name", "TEXT"),
        Field("is_hidden", "INTEGER"),
        Field("date_created", "INTEGER"),
        Field("date_modified", "INTEGER"),
    ),
    sync=SyncMode.LOCAL,
))

register(Model(
    "object_in_album",
    (
        Field("album_id", "INTEGER", nullable=False, primary_key=True,
              references="album(id)"),
        Field("object_id", "INTEGER", nullable=False, primary_key=True,
              references="object(id)"),
        Field("date_created", "INTEGER"),
    ),
    sync=SyncMode.LOCAL,
    indexes=(("object_id",),),
))

# --- Jobs (@local, schema.prisma:415-441; self-relation for chains). ------

register(Model(
    "job",
    (
        Field("id", "BLOB", nullable=False, primary_key=True),  # uuid bytes
        Field("name", "TEXT"),
        Field("action", "TEXT"),
        Field("status", "INTEGER"),
        Field("errors_text", "TEXT"),
        Field("data", "BLOB"),  # serialized resumable JobState
        Field("metadata", "BLOB"),
        Field("parent_id", "BLOB", references="job(id)",
              on_delete="CASCADE"),  # schema.prisma:440-441
        Field("task_count", "INTEGER"),
        Field("completed_task_count", "INTEGER"),
        Field("date_estimated_completion", "INTEGER"),
        Field("date_created", "INTEGER"),
        Field("date_started", "INTEGER"),
        Field("date_completed", "INTEGER"),
    ),
))

# Spooled step payloads for batch jobs (net-new vs the reference, which
# rmp-serializes every remaining step into job.data, job/mod.rs:896):
# steps carry a scratch row id instead of inline row lists, so the
# periodic crash checkpoint serializes kilobytes of descriptors rather
# than the whole remaining workload (measured ~200 MB / ~23 s per
# 3-second checkpoint for a 1M-file index before this). Rows delete as
# steps complete; finalize/cleanup and the job-row FK cascade sweep
# leftovers.

register(Model(
    "job_scratch",
    (
        _id(),
        Field("job_id", "BLOB", nullable=False,
              references="job(id)", on_delete="CASCADE"),
        Field("data", "BLOB", nullable=False),
    ),
    indexes=(("job_id",),),
))

# --- IndexerRule (@local here; schema.prisma:490). ------------------------

register(Model(
    "indexer_rule",
    (
        _id(),
        _pub_id(),
        Field("name", "TEXT", unique=True),
        Field("default_rule", "INTEGER"),
        Field("rules_per_kind", "BLOB"),  # msgpack [(kind, params), ...]
        Field("date_created", "INTEGER"),
        Field("date_modified", "INTEGER"),
    ),
))

register(Model(
    "indexer_rule_in_location",
    (
        Field("location_id", "INTEGER", nullable=False, primary_key=True,
              references="location(id)", on_delete="CASCADE"),
        Field("indexer_rule_id", "INTEGER", nullable=False, primary_key=True,
              references="indexer_rule(id)", on_delete="CASCADE"),
    ),
))

# --- Preferences / notifications (schema.prisma:517,524). -----------------

register(Model(
    "preference",
    (
        Field("key", "TEXT", nullable=False, primary_key=True),
        Field("value", "BLOB"),
    ),
))

register(Model(
    "notification",
    (
        _id(),
        Field("read", "INTEGER", nullable=False, default="0"),
        Field("data", "BLOB", nullable=False),
        Field("expires_at", "INTEGER"),
        Field("date_created", "INTEGER", nullable=False,
              default="(strftime('%s','now'))"),
    ),
))


# --- DDL generation -------------------------------------------------------


def ddl_for(model: Model) -> List[str]:
    cols = []
    pk_fields = [f for f in model.fields if f.primary_key]
    composite_pk = len(pk_fields) > 1
    for f in model.fields:
        col = f"{f.name} {f.type}"
        if f.primary_key and not composite_pk:
            col += " PRIMARY KEY"
            if f.autoincrement:
                col += " AUTOINCREMENT"
            elif not f.nullable:
                # SQLite's legacy quirk: non-INTEGER single-column PRIMARY
                # KEYs accept NULL unless NOT NULL is spelled out.
                col += " NOT NULL"
        elif not f.nullable:
            col += " NOT NULL"
        if f.unique and not f.primary_key:
            col += " UNIQUE"
        if f.default is not None:
            col += f" DEFAULT {f.default}"
        if f.references:
            col += f" REFERENCES {f.references}"
            if f.on_delete:
                col += f" ON DELETE {f.on_delete}"
        cols.append(col)
    if composite_pk:
        cols.append(
            "PRIMARY KEY (" + ", ".join(f.name for f in pk_fields) + ")"
        )
    for uq in model.uniques:
        cols.append("UNIQUE (" + ", ".join(uq) + ")")
    stmts = [
        f"CREATE TABLE IF NOT EXISTS {model.name} (\n  "
        + ",\n  ".join(cols)
        + "\n)"
    ]
    for idx in model.indexes:
        iname = f"idx_{model.name}_" + "_".join(idx)
        stmts.append(
            f"CREATE INDEX IF NOT EXISTS {iname} ON {model.name} "
            "(" + ", ".join(idx) + ")"
        )
    return stmts


def lazy_index_ddl(table: str) -> List[str]:
    """CREATE INDEX statements for a table's lazily-built indexes."""
    model = MODELS[table]
    return [
        f"CREATE INDEX IF NOT EXISTS idx_{model.name}_" + "_".join(idx)
        + f" ON {model.name} (" + ", ".join(idx) + ")"
        for idx in model.lazy_indexes
    ]


def all_ddl() -> List[str]:
    out: List[str] = []
    for model in MODELS.values():
        out.extend(ddl_for(model))
    return out
