"""Single-writer group-commit actor: the store's product write path.

BENCH_r08's ingest storm pinned saturation on `store.db.write_lock`:
every concurrent job funnels its writes through one serialized
connection, committing per item or per small chunk, so N writers pay
N fsync-priced COMMITs for work that could ride one. The reference
codebase batches exactly one writer this way (the identifier's commit
groups); this actor generalizes that to EVERY writer.

One `WriteActor` per `Database` (= per library — that IS the write
shard: a hot library's storm queues on its own actor and cannot starve
another library's). Product code enters through `Database.write_tx()`,
which enqueues a ticket on the declared bounded channel
(`store.actor.queue`) and blocks for its turn. The supervised writer
thread drains tickets and coalesces them into one fat transaction:

    BEGIN IMMEDIATE                       -- the actor, via db.tx()
      SAVEPOINT sdtpu_wtx                 -- ticket 1's bracket
        ... caller's batch body ...       -- runs on the CALLER's thread
      RELEASE sdtpu_wtx
      SAVEPOINT sdtpu_wtx                 -- ticket 2, 3, ... likewise
      ...
    COMMIT                                -- one fsync for the group

The connection is handed to exactly one caller at a time (grant/done
events), so SQLite never sees cross-thread interleaving. A batch body
that raises rolls back to ITS savepoint and re-raises to its caller —
the group goes on; the other tickets lose nothing. COMMIT failure (or
an injected `store.group_commit` error fault) fails every coalesced
ticket, exactly like a raw tx() commit failure. Group size is bounded
by SDTPU_STORE_GROUP_MAX; once the backlog drains, a group that
already coalesced work waits at most SDTPU_STORE_GROUP_LATENCY_S for
stragglers — a lone sequential writer never pays the wait (its group
of one commits immediately, the raw-tx latency).

Crash contract: the group is one SQLite transaction. kill -9 anywhere
inside it — including the injected pre-COMMIT delay window — either
lands the whole group or none of it; WAL recovery on restart converges
byte-identically with an unkilled control (tests/test_group_crash.py
storms this). Shutdown drains loudly: tickets still queued when the
actor stops fail with WriteActorClosed and count into
`sd_store_group_shutdown_drains_total` — never a silently dropped
write, never a future that resolves twice.

Closure batches (`submit`) ride the same queue for callers that do not
want to block a thread: the actor runs the closure on its own thread
inside a ticket savepoint and resolves the returned future after the
group commits — delivered onto the caller's event loop via
`threadctx.call_threadsafe` when one is supplied.
"""

from __future__ import annotations

import sqlite3
import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from typing import Any, Callable, Deque, List, Optional

from .. import channels, chaos, flags, threadctx, timeouts
from ..telemetry import (
    CHAN_PUT_BLOCK_SECONDS,
    STORE_GROUP_COMMITS,
    STORE_GROUP_SHUTDOWN_DRAINS,
    STORE_GROUP_SIZE,
    STORE_GROUP_WAIT_SECONDS,
    TIMEOUTS_FIRED,
)

__all__ = ["WriteActor", "WriteActorClosed", "WriteTxStalled"]


class WriteActorClosed(RuntimeError):
    """The library's write actor has shut down (db.close / node stop);
    the queued batch was NOT written."""


class WriteTxStalled(RuntimeError):
    """A store.actor.* budget expired: the writer thread (or a batch
    body holding the grant) is wedged, not slow — surfacing beats
    parking every producer forever."""


class _Ticket:
    """One queued write batch. Fields are written cross-thread, but
    each has exactly one writer per handshake phase (enqueue → grant →
    body → commit), with the events as the ordering edges — there is
    no concurrent write to any field.

    Slot tickets (fn is None) hand the group connection to the
    enqueueing thread, which runs its `write_tx` body between
    `grant_evt` and `done_evt`. Closure tickets carry `fn`, run on the
    actor thread, and resolve `future` after the group commits.
    """

    __slots__ = (
        "fn", "loop", "future", "enq_t",
        "grant_evt", "done_evt", "commit_evt",
        "conn", "grant_exc", "commit_exc",
        "body_ok", "body_fatal", "result", "resolved",
    )

    def __init__(self, fn: Optional[Callable] = None,
                 loop: Any = None,
                 future: Optional[Future] = None):
        self.fn = fn
        self.loop = loop
        self.future = future
        self.enq_t = time.perf_counter()
        self.grant_evt = threading.Event()
        self.done_evt = threading.Event()
        self.commit_evt = threading.Event()
        self.conn: Optional[sqlite3.Connection] = None
        self.grant_exc: Optional[BaseException] = None
        self.commit_exc: Optional[BaseException] = None
        self.body_ok = False
        # Set when the body's savepoint bracket itself broke (ROLLBACK
        # TO failed): the connection's transaction state is unknown, so
        # the whole group must fail rather than commit around it.
        self.body_fatal = False
        self.result: Any = None
        self.resolved = False


class WriteActor:
    """Per-library single-writer group-commit actor (see module doc).

    Constructed eagerly by Database.__init__ (so the threadctx race
    recorder sees every guarded write under the same lock); the writer
    thread itself starts lazily on first enqueue — libraries that never
    write never carry a thread.
    """

    def __init__(self, db: Any):
        self._db = db
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # The declared channel is the CONTRACT and metering shell: its
        # declared capacity bounds admission and its depth/high-water
        # meters feed sd_chan_* and the health observatory. The
        # Channel's deque core itself is loop-affine (its nowait
        # surface wakes asyncio waiter futures), so the actual queue
        # is this actor's own cv-guarded deque — every producer and
        # the writer thread touch it only under _lock.
        self._chan = channels.channel("store.actor.queue")
        # Bounded by the declared capacity above — enqueue() blocks
        # while len(_q) >= _chan.capacity, so this deque never exceeds
        # the store.actor.queue contract it implements.
        # sdlint: ok[queue-discipline]
        self._q: Deque[_Ticket] = deque()
        with self._lock:
            self._stopping = False
            self._thread: Optional[threading.Thread] = None
        # Shard-local tallies for the bench's balance table (the
        # sd_store_group_* families are process-global; per-library
        # attribution needs per-actor numbers). Actor thread only.
        self.groups = 0
        self.batches = 0

    # -- producer side -----------------------------------------------------

    def enqueue(self, t: _Ticket) -> None:
        """Queue one ticket, blocking for space under the declared
        store.actor.put budget. Raises WriteActorClosed after stop()
        and WriteTxStalled when the budget expires (the admission
        edge: a wedged writer thread frees its producers here)."""
        budget_s = timeouts.budget("store.actor.put")
        deadline = time.monotonic() + budget_s
        t0 = time.perf_counter()
        waited = False
        with self._lock:
            if self._stopping or getattr(self._db, "_closed", False):
                raise WriteActorClosed(
                    f"write actor for {self._db.path!r} is stopped")
            if self._thread is None:
                th = threading.Thread(
                    target=self._run, daemon=True,
                    name=f"sd-store-writer:{self._db.path}")
                self._thread = th
                th.start()
            while len(self._q) >= self._chan.capacity:
                waited = True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    TIMEOUTS_FIRED.labels(name="store.actor.put").inc()
                    raise WriteTxStalled(
                        f"store.actor.queue stayed full for "
                        f"{budget_s:.1f}s (store.actor.put budget): "
                        "the writer thread is not draining")
                self._cv.wait(remaining)
                if self._stopping:
                    raise WriteActorClosed(
                        f"write actor for {self._db.path!r} stopped "
                        "while waiting for queue space")
            self._q.append(t)
            self._chan._note_depth(len(self._q))
            self._cv.notify_all()
        if waited:
            CHAN_PUT_BLOCK_SECONDS.labels(
                name="store.actor.queue").observe(
                    time.perf_counter() - t0)

    def submit(self, fn: Callable[[sqlite3.Connection], Any],
               loop: Any = None) -> Future:
        """Queue a closure batch: `fn(conn)` runs on the actor thread
        inside its own savepoint, and the returned future resolves
        with fn's result after the group COMMITs (or with the body's /
        the group's exception). With `loop`, resolution is delivered
        onto that event loop via threadctx.call_threadsafe; without,
        the concurrent.futures.Future is resolved from the actor
        thread directly (result() blocks a plain thread safely)."""
        t = _Ticket(fn=fn, loop=loop, future=Future())
        self.enqueue(t)
        return t.future

    # -- lifecycle ---------------------------------------------------------

    def stop(self) -> None:
        """Stop the writer thread and fail anything still queued.
        Called by Database.close() BEFORE it takes the write lock —
        the actor may be holding it mid-group."""
        with self._lock:
            self._stopping = True
            self._cv.notify_all()
            th = self._thread
        if th is not None and th is not threading.current_thread():
            th.join(timeout=timeouts.budget("store.actor.write"))
        # The thread drains on exit; this sweep covers tickets that
        # raced in before the flag landed (and the never-started case).
        self._drain()

    def _drain(self) -> None:
        while True:
            with self._lock:
                if not self._q:
                    return
                t = self._q.popleft()
                self._chan._note_depth(len(self._q))
                self._cv.notify_all()
            STORE_GROUP_SHUTDOWN_DRAINS.inc()
            self._resolve(t, WriteActorClosed(
                f"write actor for {self._db.path!r} shut down with "
                "this batch still queued — it was NOT written"))

    # -- actor thread ------------------------------------------------------

    def _next(self, timeout: Optional[float]) -> Optional[_Ticket]:
        """Dequeue one ticket. None on stop, or when `timeout` (which
        may be 0 for a pure backlog poll) expires; timeout=None waits
        indefinitely for work."""
        with self._lock:
            while True:
                if self._stopping:
                    return None
                if self._q:
                    t = self._q.popleft()
                    self._chan._note_depth(len(self._q))
                    self._cv.notify_all()
                    return t
                if timeout is None:
                    self._cv.wait()
                    continue
                if timeout <= 0:
                    return None
                t0 = time.monotonic()
                self._cv.wait(timeout)
                timeout -= time.monotonic() - t0

    def _run(self) -> None:
        while True:
            t = self._next(None)
            if t is None:
                self._drain()
                return
            # The actor IS the tx-per-group loop — this is the one
            # place a transaction per iteration is the design.
            # sdlint: ok[tx-shape]
            self._run_group(t)

    def _run_group(self, first: _Ticket) -> None:
        group_max = max(1, int(flags.get("SDTPU_STORE_GROUP_MAX")))
        latency_s = float(flags.get("SDTPU_STORE_GROUP_LATENCY_S"))
        group: List[_Ticket] = []
        commit_exc: Optional[BaseException] = None
        try:
            with self._db.tx() as conn:
                self._serve(first, conn, group)
                budget_left = latency_s
                while len(group) < group_max:
                    nxt = self._next(0.0)  # drain the backlog first
                    if nxt is None:
                        # Empty queue: a group that already coalesced
                        # concurrent work waits briefly for stragglers
                        # (they tend to arrive in bursts); a group of
                        # one commits NOW — a lone sequential writer
                        # must not pay the latency bound per write.
                        if len(group) < 2 or budget_left <= 0:
                            break
                        t0 = time.monotonic()
                        nxt = self._next(budget_left)
                        budget_left -= time.monotonic() - t0
                        if nxt is None:
                            break
                    self._serve(nxt, conn, group)
                f = chaos.hit("store.group_commit",
                              only=("delay", "error"))
                if f is not None:
                    # delay: the kill -9 durability window — the group
                    # is fully written but uncommitted. error: the
                    # group fails to every waiter (ChaosError).
                    chaos.apply_sync(f)
        except BaseException as e:  # noqa: BLE001 — fanned out below
            commit_exc = e
        if commit_exc is None and group:
            STORE_GROUP_COMMITS.inc()
            STORE_GROUP_SIZE.observe(len(group))
            self.groups += 1
            self.batches += len(group)
        now = time.perf_counter()
        for t in group:
            STORE_GROUP_WAIT_SECONDS.observe(now - t.enq_t)
            self._resolve(t, commit_exc)

    def _serve(self, t: _Ticket, conn: sqlite3.Connection,
               group: List[_Ticket]) -> None:
        """Run one ticket's batch body inside the open group
        transaction. Appends to `group` when the body's writes are
        pending in the transaction (and the ticket therefore awaits
        the group's fate)."""
        if t.fn is None:
            # Slot ticket: hand the connection to the enqueueing
            # thread; write_tx runs the body under its savepoint and
            # returns the connection via done_evt.
            t.conn = conn
            t.grant_evt.set()
            if not t.done_evt.wait(timeouts.budget("store.actor.write")):
                TIMEOUTS_FIRED.labels(name="store.actor.write").inc()
                raise WriteTxStalled(
                    "a write_tx body held the group connection past "
                    "the store.actor.write budget — failing the group "
                    "rather than committing around a wedged writer")
            if t.body_fatal:
                raise sqlite3.OperationalError(
                    "write_tx body failed AND its savepoint rollback "
                    "failed — transaction state unknown, failing the "
                    "group")
            if t.body_ok:
                group.append(t)
            # body raised: the caller already has its exception and
            # its savepoint is rolled back — the group moves on.
            return
        # Closure ticket: the body runs here, on the actor thread.
        # Savepoint-bracket failures raise (fail the whole group —
        # transaction state is unknown past them); body failures
        # resolve THIS ticket with its exception and the group moves
        # on, its savepoint rolled back.
        conn.execute("SAVEPOINT sdtpu_wtx")
        try:
            t.result = t.fn(conn)
        except Exception as body_exc:
            conn.execute("ROLLBACK TO sdtpu_wtx")
            conn.execute("RELEASE sdtpu_wtx")
            self._resolve(t, body_exc)
            return
        conn.execute("RELEASE sdtpu_wtx")
        group.append(t)

    # -- completion --------------------------------------------------------

    def _resolve(self, t: _Ticket, exc: Optional[BaseException]) -> None:
        """Deliver a ticket's outcome exactly once. Slot tickets wake
        their parked write_tx caller (pre-grant failures via
        grant_evt, post-body outcomes via commit_evt); closure tickets
        resolve their future, on the caller's loop when given."""
        if t.resolved:
            return
        t.resolved = True
        if t.fn is None:
            if t.conn is None:  # never granted (shutdown drain)
                t.grant_exc = exc if exc is not None else \
                    WriteActorClosed("write actor stopped")
                t.grant_evt.set()
            else:
                t.commit_exc = exc
                t.commit_evt.set()
            return
        fut = t.future

        def _settle() -> None:
            try:
                if exc is None:
                    fut.set_result(t.result)
                else:
                    fut.set_exception(exc)
            except InvalidStateError:
                pass  # caller cancelled the future — outcome dropped

        if t.loop is not None and threadctx.call_threadsafe(
                t.loop, _settle):
            return
        _settle()
