"""SQLite store: one database file per library, single-writer discipline.

The reference talks to SQLite through a generated Prisma client and leans
on batched writes because "db is single threaded, nerd"
(/root/reference/core/src/job/manager.rs:31). Here the equivalent is a
thin typed wrapper over the stdlib sqlite3 driver in WAL mode with one
process-wide write lock per database; all workload writes go through
`tx()` batches exactly like the reference's `_batch` calls.

Rows come back as sqlite3.Row (dict-style access). The DDL comes from the
model registry (store/models.py), mirroring core/prisma/schema.prisma.
"""

from __future__ import annotations

import logging
import os
import sqlite3
import threading
import time
import uuid
from contextlib import contextmanager
from typing import Any, Dict, Iterable, List, Optional, Sequence

from . import actor as actor_mod
from . import models, sqlaudit, statements
from .. import chaos, flags, sanitize, telemetry, timeouts
from ..telemetry import (
    STORE_BUSY_RETRIES,
    STORE_COMMIT_SECONDS,
    STORE_INIT_WARNINGS,
    STORE_TX,
    STORE_WRITE_LOCK_WAIT_SECONDS,
    TIMEOUTS_FIRED,
)

log = logging.getLogger("spacedrive_tpu.store")


def uuid_bytes(u: Optional[uuid.UUID] = None) -> bytes:
    """Stable 16-byte id, like sd_utils::uuid_to_bytes. Fresh ids are
    time-ordered (sync/crdt.uuid4_bytes, v7 layout) so bulk inserts
    into UNIQUE pub_id B-trees append instead of churning random
    leaves; explicit UUIDs pass through unchanged."""
    if u is not None:
        return u.bytes
    from ..sync.crdt import uuid4_bytes

    return uuid4_bytes()


def now_ts() -> int:
    return int(time.time())


class Database:
    """A single SQLite database with serialized writes.

    Connections are per-thread (sqlite3 objects cannot cross threads);
    writes additionally serialize on one lock so batched transactions
    from concurrent jobs never deadlock on SQLITE_BUSY.
    """

    def __init__(self, path: str | os.PathLike):
        self.path = str(path)
        # Both locks come from the sanitizer so SDTPU_SANITIZE=1 runs
        # record lock order and held-across-await; with the sanitizer
        # off these ARE plain threading locks.
        self._write_lock = sanitize.tracked_rlock("db._write_lock")
        # Connection REGISTRATION serializes on its own lock, never on
        # the write lock: a reader thread opening its first connection
        # while a writer holds a long transaction (the identifier's
        # multi-chunk commit groups, which WAIT on reader-thread
        # prefetch results) must not block — with registration under
        # the write lock that wait was a deadlock.
        self._conns_lock = sanitize.tracked_lock("db._conns_lock")
        self._local = threading.local()
        self._all_conns: list[sqlite3.Connection] = []
        # Idle read-only connections (PRAGMA query_only) kept warm for
        # threads that have no dedicated conn: the to_thread worker
        # pool's reads borrow instead of minting a 256 MiB-cache
        # writer-shaped connection per thread. LIFO under _conns_lock.
        self._read_pool: list[sqlite3.Connection] = []
        self._closed = False
        # The per-library single-writer group-commit actor — THE
        # product write path (write_tx / submit_write). Constructed
        # eagerly so ownership of its guarded state is established
        # before any writer races in; its thread starts on first use.
        self._actor = actor_mod.WriteActor(self)
        conn = self._conn()
        with self._write_lock:
            ddl = models.all_ddl()
            tables = [d for d in ddl if not d.lstrip().upper()
                      .startswith(("CREATE INDEX", "CREATE UNIQUE INDEX"))]
            indexes = [d for d in ddl if d not in tables]
            for stmt in tables:
                conn.execute(stmt)
            # Additive schema evolution: CREATE TABLE IF NOT EXISTS
            # leaves pre-existing libraries without newly-registered
            # columns, so diff each table against the registry and
            # ALTER in what is missing — BEFORE index DDL, which may
            # reference a just-added column. Only plain nullable
            # columns are supported (constraints/FKs can't be ALTERed
            # in and would silently diverge from fresh schemas).
            for table, model in models.MODELS.items():
                have = {row[1] for row in conn.execute(
                    f"PRAGMA table_info({table})")}
                for field in model.fields:
                    if field.name in have:
                        continue
                    if not (field.nullable and not field.unique
                            and field.default is None
                            and field.references is None):
                        raise RuntimeError(
                            f"{table}.{field.name}: additive migration "
                            "only supports plain nullable columns")
                    conn.execute(
                        f"ALTER TABLE {table} ADD COLUMN "
                        f"{field.name} {field.type}")
            for stmt in indexes:
                conn.execute(stmt)
            # Upgrade path for the lazy-index change: libraries created
            # when the op-log indexes were bootstrap DDL still carry
            # them, paying per-row maintenance on every bulk write. An
            # UNPAIRED library (≤1 instance row) has never synced, so
            # the indexes are dropped — they rebuild on first sync use.
            # Paired libraries keep them (a 5M-row rebuild at next sync
            # would cost more than the maintenance saves).
            try:
                n_inst = conn.execute(
                    statements.get("store.init.instance_count").sql
                ).fetchone()[0]
                if n_inst <= 1:
                    for table, model in models.MODELS.items():
                        for idx in model.lazy_indexes:
                            conn.execute(
                                f"DROP INDEX IF EXISTS "
                                f"idx_{table}_{'_'.join(idx)}")
            except sqlite3.Error as e:
                # Non-fatal by design (the indexes only cost bulk-write
                # maintenance) but never silent: a corrupt library
                # failing this probe must be visible in health.
                log.debug("lazy-index drop skipped for %s: %s",
                          self.path, e)
                STORE_INIT_WARNINGS.inc()
            conn.commit()

    def _conn(self) -> sqlite3.Connection:
        if self._closed:
            raise sqlite3.ProgrammingError("database is closed")
        conn = getattr(self._local, "conn", None)
        if conn is None:
            # check_same_thread=False so close() can tear down every
            # thread's connection (backup restore swaps the file under
            # us); normal use keeps one conn per thread regardless.
            # The factory is the SQL auditor's seam: armed processes
            # get contract-checked connections (store/sqlaudit.py).
            conn = sqlite3.connect(self.path, timeout=30.0,
                                   check_same_thread=False,
                                   factory=sqlaudit.connection_class())
            conn.row_factory = sqlite3.Row
            conn.execute("PRAGMA journal_mode=WAL")
            conn.execute("PRAGMA foreign_keys=ON")
            conn.execute("PRAGMA synchronous=NORMAL")
            # Bulk scans update several indexes per row across millions
            # of rows; the 2 MiB default page cache thrashes once the
            # btrees outgrow it (measured superlinear db time at 1M
            # files). 256 MiB cache + mmap reads keep index pages hot.
            conn.execute("PRAGMA cache_size=-262144")
            conn.execute("PRAGMA mmap_size=1073741824")
            conn.execute("PRAGMA temp_store=MEMORY")
            # Auto-checkpoint moved ~10 MB of WAL back into the main
            # file on nearly every bulk-chunk commit (~0.2 s each at
            # 1M files). Bulk jobs instead checkpoint explicitly when
            # they finish (jobs/worker.py) and backups/close still
            # truncate; the WAL may grow to GBs mid-scan, which WAL
            # readers handle fine.
            conn.execute("PRAGMA wal_autocheckpoint=0")
            # Bound the WAL file's on-disk footprint after the explicit
            # end-of-bulk checkpoints: without a limit SQLite keeps the
            # multi-GB bulk-scan WAL allocated forever, and the next
            # scan's commits rewrite cold pages inside it. Matches the
            # passive-checkpoint budget in tx().
            conn.execute(f"PRAGMA journal_size_limit={self._WAL_BUDGET_BYTES}")
            with self._conns_lock:
                # Re-check under the lock: close() may have won the race
                # after the unlocked check above (restore swaps the file).
                if self._closed:
                    conn.close()
                    raise sqlite3.ProgrammingError("database is closed")
                self._all_conns.append(conn)
            self._local.conn = conn
        return conn

    def close(self) -> None:
        """Close EVERY thread's connection. Any later use of this
        Database object raises — restore swaps in a new instance."""
        # Stop the write actor BEFORE taking the write lock: the actor
        # may be holding it mid-group, and it fails anything still
        # queued loudly (WriteActorClosed, never a silent drop).
        self._actor.stop()
        with self._write_lock:  # no transaction in flight past here
            with self._conns_lock:
                self._closed = True
                for conn in self._all_conns:
                    try:
                        conn.close()
                    except sqlite3.Error:
                        pass
                self._all_conns.clear()
                self._read_pool.clear()
                self._local = threading.local()

    # -- reads ------------------------------------------------------------
    # query/query_one are the AD-HOC diagnostic read surface: raw SQL,
    # no contract, no write lock. Tests and debugging use them freely
    # (the runtime auditor's adhoc() allowance); PRODUCT code does not —
    # sdlint's sql-discipline pass fails the build on raw SQL literals
    # outside the registry, so engine reads go through run().

    def query(self, sql: str, params: Sequence = ()) -> List[sqlite3.Row]:
        with sqlaudit.adhoc():
            with self._read_conn() as c:
                return self._execute_read(c, sql, params).fetchall()

    def query_one(self, sql: str, params: Sequence = ()) -> Optional[sqlite3.Row]:
        with sqlaudit.adhoc():
            with self._read_conn() as c:
                return self._execute_read(c, sql, params).fetchone()

    # -- read-only connection pool ----------------------------------------

    @contextmanager
    def _read_conn(self):
        """Route a conn=None read. Precedence:

        1. a transaction open ON THIS THREAD (a write_tx grant or a
           raw tx()) — the read must see its own uncommitted writes;
        2. the thread's dedicated connection when it already minted
           one (bootstrap thread, the actor's writer thread);
        3. a pooled read-only connection, returned on exit.
        """
        c = getattr(self._local, "tx_conn", None)
        if c is not None:
            yield c
            return
        c = getattr(self._local, "conn", None)
        if c is not None:
            yield c
            return
        c = self._borrow_read()
        try:
            yield c
        finally:
            self._release_read(c)

    def _borrow_read(self) -> sqlite3.Connection:
        with self._conns_lock:
            if self._closed:
                raise sqlite3.ProgrammingError("database is closed")
            if self._read_pool:
                return self._read_pool.pop()
        conn = sqlite3.connect(self.path, timeout=30.0,
                               check_same_thread=False,
                               factory=sqlaudit.connection_class())
        conn.row_factory = sqlite3.Row
        # Reader-sized pragmas: the shared mmap does the heavy lifting;
        # duplicating the writer's 256 MiB page cache per pooled conn
        # would multiply memory for no read win. query_only goes last
        # so the setup itself writes nothing and misuse fails loudly.
        conn.execute("PRAGMA cache_size=-32768")
        conn.execute("PRAGMA mmap_size=1073741824")
        conn.execute("PRAGMA temp_store=MEMORY")
        conn.execute("PRAGMA query_only=ON")
        with self._conns_lock:
            if self._closed:
                conn.close()
                raise sqlite3.ProgrammingError("database is closed")
            self._all_conns.append(conn)
        return conn

    def _release_read(self, conn: sqlite3.Connection) -> None:
        keep = max(0, int(flags.get("SDTPU_STORE_READ_POOL")))
        with self._conns_lock:
            if not self._closed and len(self._read_pool) < keep:
                self._read_pool.append(conn)
                return
            # transient borrow beyond the cap (or closing): drop it
            try:
                self._all_conns.remove(conn)
            except ValueError:
                pass
        try:
            conn.close()
        except sqlite3.Error:
            pass

    def _execute_read(self, conn: sqlite3.Connection, sql: str,
                      params: Sequence) -> sqlite3.Cursor:
        """Execute a read under the declared `store.busy` backoff:
        WAL readers rarely see BUSY, but a pooled reader racing a
        checkpoint (or an injected fault) must degrade to bounded
        retry latency counted into sd_store_busy_retries_total — the
        same attribution the commit path has — not fail the read."""
        b: Optional[timeouts.Backoff] = None
        while True:
            try:
                return conn.execute(sql, params)
            except sqlite3.OperationalError as e:
                msg = str(e).lower()
                if "locked" not in msg and "busy" not in msg:
                    raise
                if b is None:
                    b = timeouts.Backoff("store.busy")
                d = b.next_delay()
                if d is None:
                    raise
                STORE_BUSY_RETRIES.inc()
                time.sleep(d)

    # -- declared-statement dispatch ---------------------------------------

    def run(self, name: str, params: Sequence = (), conn=None):
        """Execute the declared statement `name` (store/statements.py).

        Reads run on the calling thread's connection with NO write
        lock (WAL readers never block) and return what the declared
        cardinality promises: `one` → Row|None, `many` → list[Row],
        `scalar` → first column of the first row (or None). Pass
        `conn=` (from an open tx()) when the read must see the
        transaction's own uncommitted writes.

        Writes REQUIRE the open tx() connection (`conn=`) — every
        write-verb contract is tx_required, run_tx() is the
        single-statement sugar — and return the cursor (lastrowid /
        rowcount). ddl/pragma statements serialize on the write lock.
        """
        st = statements.get(name)
        if st.verb == "read":
            if conn is not None:
                return self._fetch_read(st, name,
                                        conn.execute(st.sql, params))
            with self._read_conn() as c:
                return self._fetch_read(
                    st, name, self._execute_read(c, st.sql, params))
        if st.verb == "write":
            if conn is None:
                raise statements.SqlContractError(
                    f"{name}: write statements execute on the open "
                    "tx() connection — pass conn= (or use run_tx)")
            return conn.execute(st.sql, params)
        # ddl / pragma: serialized like the other schema operations.
        # NEVER call from inside a write_tx body — the actor holds the
        # write lock for the whole group (deadlock by reentrancy is
        # only saved by the RLock when tx() runs on THIS thread).
        with self._write_lock:
            return self._conn().execute(st.sql, params)

    @staticmethod
    def _fetch_read(st, name: str, cur: sqlite3.Cursor):
        if st.cardinality == "one":
            row = cur.fetchone()
            sqlaudit.note_rows(name, 1 if row is not None else 0)
            return row
        if st.cardinality == "scalar":
            row = cur.fetchone()
            sqlaudit.note_rows(name, 1 if row is not None else 0)
            return row[0] if row is not None else None
        rows = cur.fetchall()
        sqlaudit.note_rows(name, len(rows))
        return rows

    def run_many(self, name: str, seq: Iterable[Sequence],
                 conn=None) -> sqlite3.Cursor:
        """executemany over a declared write statement, on the open
        tx() connection."""
        st = statements.get(name)
        if st.verb != "write":
            raise statements.SqlContractError(
                f"{name}: run_many is for write statements")
        if conn is None:
            raise statements.SqlContractError(
                f"{name}: write statements execute on the open tx() "
                "connection — pass conn= (or use run_tx)")
        return conn.executemany(st.sql, seq)

    def run_tx(self, name: str, params: Sequence = ()) -> sqlite3.Cursor:
        """One declared write statement as its own write batch — the
        single-statement sugar over `with write_tx() as c: run(...,
        conn=c)`; it group-commits through the actor like every other
        product write. Per-item loops should batch under ONE write_tx
        instead (the tx-shape pass flags run_tx inside loops)."""
        with self.write_tx() as c:
            return self.run(name, params, conn=c)

    # -- writes -----------------------------------------------------------

    # With wal_autocheckpoint off, something must still bound the WAL
    # for write paths that never finish a job (watcher churn, API
    # mutations, sync ingest on a long-lived node): every N commits the
    # WAL size is checked and folded back passively past this budget.
    _WAL_CHECK_EVERY = 128
    _WAL_BUDGET_BYTES = 256 << 20

    @contextmanager
    def tx(self):
        """RAW serialized write transaction; the unit of atomic
        batching — and, since the group-commit actor, the ENGINE-ROOM
        primitive: the actor brackets each coalesced group with one
        tx(), and bootstrap/migration/tool paths use it directly.
        Product code uses write_tx() instead (sdlint's tx-shape
        `actor-bypass` code enforces that statically).

        Telemetry: write-lock wait and COMMIT latency are observed only
        while telemetry is enabled — the disabled path adds one module
        flag check, no clock reads."""
        conn = self._conn()
        tm = telemetry.enabled()
        t_wait = time.perf_counter() if tm else 0.0
        with self._write_lock:
            if tm:
                STORE_WRITE_LOCK_WAIT_SECONDS.observe(
                    time.perf_counter() - t_wait)
            # Mark the open transaction on this thread so conn=None
            # reads (and nested write_tx calls) land on it — reads
            # inside a transaction must see its uncommitted writes.
            prev = getattr(self._local, "tx_conn", None)
            self._local.tx_conn = conn
            try:
                conn.execute("BEGIN IMMEDIATE")
                sqlaudit.tx_begin(conn)
                yield conn
                t_commit = time.perf_counter() if tm else 0.0
                self._commit_with_retry(conn)
                sqlaudit.tx_end(conn, committed=True)
                if tm:
                    STORE_COMMIT_SECONDS.observe(
                        time.perf_counter() - t_commit)
                    STORE_TX.inc()
            except BaseException:
                conn.rollback()
                sqlaudit.tx_end(conn, committed=False)
                raise
            finally:
                self._local.tx_conn = prev
            self._commits = getattr(self, "_commits", 0) + 1
            if self._commits % self._WAL_CHECK_EVERY == 0:
                try:
                    if (os.path.getsize(self.path + "-wal")
                            > self._WAL_BUDGET_BYTES):
                        conn.execute("PRAGMA wal_checkpoint(PASSIVE)")
                except (OSError, sqlite3.Error):
                    pass

    def _commit_with_retry(self, conn: sqlite3.Connection) -> None:
        """COMMIT under the declared `store.busy` backoff: sqlite BUSY
        (an external process holding the file lock — WAL writers from
        a backup tool, another node sharing the library file, or an
        injected `store.commit` chaos fault) degrades to bounded
        jittered latency (sd_store_busy_retries_total) instead of
        failing the whole job's transaction. The ladder is short
        (~2 s worst case) because the write lock is held throughout;
        exhaustion re-raises the BUSY to the tx() caller."""
        b: Optional[timeouts.Backoff] = None
        while True:
            f = chaos.hit("store.commit", only=("delay", "error"))
            try:
                if f is not None:
                    if f.kind == "error":
                        raise sqlite3.OperationalError(
                            "database is locked")
                    chaos.apply_sync(f)  # delay: fsync weather
                conn.commit()
                return
            except sqlite3.OperationalError as e:
                msg = str(e).lower()
                if "locked" not in msg and "busy" not in msg:
                    raise
                if b is None:
                    b = timeouts.Backoff("store.busy")
                d = b.next_delay()
                if d is None:
                    raise
                STORE_BUSY_RETRIES.inc()
                time.sleep(d)

    # -- the product write path: group-committed batches -------------------

    @contextmanager
    def _savepoint(self, conn: sqlite3.Connection):
        """One write batch's bracket inside an enclosing transaction.
        Same-name savepoints stack (ROLLBACK TO targets the most
        recent), so nested write_tx calls compose."""
        conn.execute("SAVEPOINT sdtpu_wtx")
        try:
            yield
        except BaseException:
            conn.execute("ROLLBACK TO sdtpu_wtx")
            conn.execute("RELEASE sdtpu_wtx")
            raise
        conn.execute("RELEASE sdtpu_wtx")

    @contextmanager
    def write_tx(self):
        """Group-committed write transaction — THE product write path.

        Same shape as tx() at the call site (`with db.write_tx() as
        conn:`) but the COMMIT is the group's: this batch body runs
        inside its own savepoint of the write actor's fat transaction,
        coalesced with concurrent writers' batches (store/actor.py),
        and the context exits only once that transaction is durable.
        A body exception rolls back only THIS batch's savepoint and
        re-raises here — the group (and the other writers) go on; a
        group COMMIT failure raises here exactly like a raw tx()
        commit failure would.

        Nested calls — and calls inside an open raw tx() on this
        thread — ride the enclosing transaction under a fresh
        savepoint: no actor round-trip, no self-deadlock. The body
        must NOT call the write-lock-taking surfaces (run on a
        ddl/pragma statement, checkpoint*, ensure_lazy_indexes): the
        actor holds the write lock for the whole group and those would
        deadlock against it from a foreign thread.

        SDTPU_STORE_ACTOR=off degrades to a raw tx() per batch — one
        commit per caller, no coalescing (the bench's before/after
        lever).
        """
        outer = getattr(self._local, "tx_conn", None)
        if outer is not None:
            with self._savepoint(outer):
                yield outer
            return
        if not flags.get("SDTPU_STORE_ACTOR"):
            with self.tx() as conn:
                yield conn
            return
        t = actor_mod._Ticket()
        self._actor.enqueue(t)
        budget_s = timeouts.budget("store.actor.write")
        if not t.grant_evt.wait(budget_s):
            TIMEOUTS_FIRED.labels(name="store.actor.write").inc()
            raise actor_mod.WriteTxStalled(
                f"write_tx waited {budget_s:.0f}s (store.actor.write "
                "budget) for the group connection — the writer thread "
                "is wedged")
        if t.grant_exc is not None:
            raise t.grant_exc
        conn = t.conn
        try:
            self._local.tx_conn = conn
            try:
                conn.execute("SAVEPOINT sdtpu_wtx")
            except BaseException:
                t.body_fatal = True
                raise
            try:
                yield conn
            except BaseException:
                try:
                    conn.execute("ROLLBACK TO sdtpu_wtx")
                    conn.execute("RELEASE sdtpu_wtx")
                except sqlite3.Error:
                    t.body_fatal = True
                raise
            try:
                conn.execute("RELEASE sdtpu_wtx")
            except BaseException:
                t.body_fatal = True
                raise
            t.body_ok = True
        finally:
            # Return the connection to the actor no matter what — a
            # body that kept it would wedge every coalesced writer.
            self._local.tx_conn = None
            t.done_evt.set()
        if not t.commit_evt.wait(budget_s):
            TIMEOUTS_FIRED.labels(name="store.actor.write").inc()
            raise actor_mod.WriteTxStalled(
                "write_tx batch was coalesced but the group COMMIT "
                "never resolved within the store.actor.write budget")
        if t.commit_exc is not None:
            raise t.commit_exc

    def submit_write(self, fn, loop=None):
        """Closure form of write_tx for callers that must not park a
        thread: `fn(conn)` runs ON THE ACTOR THREAD inside its own
        savepoint, and the returned concurrent.futures.Future resolves
        with fn's result once the group commits (delivered via
        threadctx.call_threadsafe onto `loop` when given). With
        SDTPU_STORE_ACTOR=off the closure runs inline under a raw
        tx() and the future comes back already resolved."""
        if not flags.get("SDTPU_STORE_ACTOR"):
            from concurrent.futures import Future

            fut: "Future" = Future()
            try:
                with self.tx() as conn:
                    res = fn(conn)
            except BaseException as e:  # noqa: BLE001 — future carries it
                fut.set_exception(e)
            else:
                fut.set_result(res)
            return fut
        return self._actor.submit(fn, loop=loop)

    # NOTE: the old `execute(sql, params)` wrapper is gone. It wrapped
    # EVERY statement — reads included — in a write transaction (write
    # lock + BEGIN IMMEDIATE, committing nothing for a SELECT), so
    # read-verb callers serialized behind bulk writers for no reason.
    # Reads go through run()/query() (no lock); writes go through
    # run(conn=)/run_tx()/the typed helpers (all tx-scoped).

    def checkpoint(self) -> None:
        """Flush the WAL into the main DB file (for backups). Must NOT run
        inside a transaction — wal_checkpoint fails under BEGIN."""
        with self._write_lock:
            self._conn().execute("PRAGMA wal_checkpoint(TRUNCATE)")

    def checkpoint_passive(self) -> None:
        """Best-effort WAL flush that never blocks other writers — the
        end-of-bulk-job companion to wal_autocheckpoint=0."""
        try:
            with self._write_lock:
                self._conn().execute("PRAGMA wal_checkpoint(PASSIVE)")
        except sqlite3.Error:
            pass

    def ensure_lazy_indexes(self, table: str) -> None:
        """Build a table's lazily-declared indexes (models.lazy_indexes).

        Idempotent and cheap once built; the first call on a large
        op log pays one O(N log N) index build — the price of entering
        sync after a bulk-optimized local life."""
        for stmt in models.lazy_index_ddl(table):
            with self._write_lock:
                self._conn().execute(stmt)

    # -- typed helpers over the model registry ----------------------------

    def insert(self, table: str, row: Dict[str, Any],
               conn: Optional[sqlite3.Connection] = None) -> int:
        cols = ", ".join(row)
        ph = ", ".join("?" for _ in row)
        sql = f"INSERT INTO {table} ({cols}) VALUES ({ph})"
        if conn is not None:
            return conn.execute(sql, list(row.values())).lastrowid
        with self.write_tx() as c:
            return c.execute(sql, list(row.values())).lastrowid

    def insert_many(self, table: str, rows: List[Dict[str, Any]],
                    conn: Optional[sqlite3.Connection] = None,
                    ignore_conflicts: bool = False) -> int:
        """Batched create_many; returns number of rows inserted."""
        if not rows:
            return 0
        # Union of keys across all rows (heterogeneous batches are natural:
        # dirs lack extension, some paths lack cas_id); missing keys → NULL.
        cols = list(dict.fromkeys(k for r in rows for k in r))
        ph = ", ".join("?" for _ in cols)
        conflict = " OR IGNORE" if ignore_conflicts else ""
        sql = (
            f"INSERT{conflict} INTO {table} ({', '.join(cols)}) "
            f"VALUES ({ph})"
        )
        vals = [[r.get(c) for c in cols] for r in rows]
        if conn is not None:
            cur = conn.executemany(sql, vals)
            return cur.rowcount
        with self.write_tx() as c:
            cur = c.executemany(sql, vals)
            return cur.rowcount

    def update(self, table: str, row_id: Any, values: Dict[str, Any],
               conn: Optional[sqlite3.Connection] = None,
               id_col: str = "id") -> None:
        if not values:
            return
        sets = ", ".join(f"{k} = ?" for k in values)
        sql = f"UPDATE {table} SET {sets} WHERE {id_col} = ?"
        params = list(values.values()) + [row_id]
        if conn is not None:
            conn.execute(sql, params)
        else:
            with self.write_tx() as c:
                c.execute(sql, params)

    def upsert(self, table: str, key: Dict[str, Any], values: Dict[str, Any],
               conn: Optional[sqlite3.Connection] = None) -> None:
        cols = list(key) + list(values)
        ph = ", ".join("?" for _ in cols)
        sets = ", ".join(f"{k} = excluded.{k}" for k in values) or \
            f"{list(key)[0]} = excluded.{list(key)[0]}"
        sql = (
            f"INSERT INTO {table} ({', '.join(cols)}) VALUES ({ph}) "
            f"ON CONFLICT ({', '.join(key)}) DO UPDATE SET {sets}"
        )
        params = list(key.values()) + list(values.values())
        if conn is not None:
            conn.execute(sql, params)
        else:
            with self.write_tx() as c:
                c.execute(sql, params)

    def delete(self, table: str, row_id: Any,
               conn: Optional[sqlite3.Connection] = None,
               id_col: str = "id") -> None:
        sql = f"DELETE FROM {table} WHERE {id_col} = ?"
        if conn is not None:
            conn.execute(sql, (row_id,))
        else:
            with self.write_tx() as c:
                c.execute(sql, (row_id,))


def rows_to_dicts(rows: Iterable[sqlite3.Row]) -> List[Dict[str, Any]]:
    return [dict(r) for r in rows]
