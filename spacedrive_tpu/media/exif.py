"""EXIF media-data extraction.

Covers the behavior of the reference's media-data extractor
(/root/reference/core/src/object/media/media_data_extractor.rs:50-90 and
crates/media-metadata image path): pull resolution, capture date, GPS
location, and camera data from image files into `media_data` rows.
PIL's Exif reader replaces the Rust `kamadak-exif` stack.
"""

from __future__ import annotations

import os

import msgpack
from typing import Any, Dict, Optional

# Extensions eligible for media-data extraction
# (media_data_extractor.rs:50-56); HEIF family needs a codec PIL lacks
# here, but extraction failures are non-fatal per-file errors anyway.
MEDIA_DATA_EXTENSIONS = {
    "tiff", "dng", "jpeg", "jpg", "heif", "heifs", "heic", "avif",
    "avcs", "avci", "hif", "png", "webp",
}

_TAG = {
    "DateTimeOriginal": 0x9003,
    "Make": 0x010F,
    "Model": 0x0110,
    "Software": 0x0131,
    "Orientation": 0x0112,
    "FNumber": 0x829D,
    "ExposureTime": 0x829A,
    "ISOSpeedRatings": 0x8827,
    "FocalLength": 0x920A,
    "LensMake": 0xA433,
    "LensModel": 0xA434,
}


def _ratio(v) -> Optional[float]:
    try:
        return float(v)
    except (TypeError, ValueError, ZeroDivisionError):
        return None


def _gps_to_degrees(values, ref: str) -> Optional[float]:
    try:
        d, m, s = (float(x) for x in values)
        deg = d + m / 60 + s / 3600
        return -deg if ref in ("S", "W") else deg
    except Exception:
        return None


def _heif_exif_fallback(path: str):
    """(width, height, PIL Exif) for HEIF containers PIL cannot open —
    the EXIF item + `ispe` size are readable without an HEVC decoder
    (media/isobmff.py; the reference extracts HEIF EXIF via kamadak-exif
    in sd-media-metadata)."""
    from PIL import Image

    ext = path.rsplit(".", 1)[-1].lower()
    from .images import HEIF_EXTENSIONS

    if ext not in HEIF_EXTENSIONS:
        return None
    try:
        from .images import MAXIMUM_FILE_SIZE
        from .isobmff import heif_dimensions, heif_exif

        if os.path.getsize(path) > MAXIMUM_FILE_SIZE:
            return None  # same 192 MiB budget format_image enforces
        with open(path, "rb") as f:
            data = f.read()
        dims = heif_dimensions(data) or (0, 0)
        tiff = heif_exif(data)
        exif = Image.Exif()
        if tiff is not None:
            exif.load(b"Exif\x00\x00" + tiff)
        return dims[0], dims[1], exif
    except Exception:
        return None


def extract_media_data(path: str) -> Optional[Dict[str, Any]]:
    """Returns a media_data row dict (without object_id), or None when the
    file has no readable EXIF."""
    from PIL import Image
    try:
        with Image.open(path) as im:
            width, height = im.size
            exif = im.getexif()
    except Exception:
        heif = _heif_exif_fallback(path)
        if heif is None:
            return None
        width, height, exif = heif

    row: Dict[str, Any] = {
        "resolution": msgpack.packb({"width": width, "height": height}),
    }
    if not exif:
        return row

    ifd = {}
    try:
        ifd = dict(exif.get_ifd(0x8769))  # Exif sub-IFD
    except Exception:
        pass
    merged = {**dict(exif), **ifd}

    date = merged.get(_TAG["DateTimeOriginal"])
    if date:
        row["media_date"] = msgpack.packb(str(date))
    camera = {
        k: str(merged[t]) for k, t in (
            ("make", _TAG["Make"]), ("model", _TAG["Model"]),
            ("software", _TAG["Software"]),
            ("lens_make", _TAG["LensMake"]),
            ("lens_model", _TAG["LensModel"]),
        ) if merged.get(t)
    }
    for k, t in (("f_number", _TAG["FNumber"]),
                 ("exposure_time", _TAG["ExposureTime"]),
                 ("focal_length", _TAG["FocalLength"])):
        v = _ratio(merged.get(t))
        if v is not None:
            camera[k] = v
    iso = merged.get(_TAG["ISOSpeedRatings"])
    if iso is not None:
        try:
            camera["iso"] = int(iso if not isinstance(iso, tuple) else iso[0])
        except (TypeError, ValueError):
            pass
    orient = merged.get(_TAG["Orientation"])
    if orient is not None:
        try:
            camera["orientation"] = int(orient)
        except (TypeError, ValueError):
            pass
    if camera:
        row["camera_data"] = msgpack.packb(camera)

    try:
        gps = exif.get_ifd(0x8825)  # GPS IFD
        if gps:
            lat = _gps_to_degrees(gps.get(2), str(gps.get(1, "N")))
            lon = _gps_to_degrees(gps.get(4), str(gps.get(3, "E")))
            if lat is not None and lon is not None:
                from .pluscodes import encode as encode_pluscode

                row["media_location"] = msgpack.packb({
                    "latitude": lat, "longitude": lon,
                    # Human-shareable plus code, as the reference derives
                    # (media-metadata pluscodes.rs).
                    "pluscode": encode_pluscode(lat, lon),
                })
    except Exception:
        pass
    return row
