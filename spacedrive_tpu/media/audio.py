"""Self-hosted audio/video stream metadata parsers.

The reference's sd-media-metadata ships typed audio/video structs that
are empty stubs awaiting an ffmpeg binding
(/root/reference/crates/media-metadata/src/{audio.rs,video.rs}); its
media pipeline never fills them. This module goes further than the
reference: container headers are parsed directly, no codec library
needed, for the formats whose metadata lives in plain sight —

- WAV   (RIFF fmt/data chunks: codec tag, channels, rate, duration)
- FLAC  (STREAMINFO block: rate, channels, bits, total samples)
- MP3   (first MPEG frame header; ID3v2 skipped; CBR duration estimate,
         Xing/Info frame count used when present)
- OGG   (Vorbis identification header + terminal page granule)
- Opus  (OpusHead in an Ogg stream, 48 kHz granule clock)
- AVI   (avih main header: dimensions, fps, frame count → duration;
         the same RIFF walker that powers MJPEG thumbnails)
- MP4/MOV/M4A/3GP (media/mp4meta.py: moov walk — duration, codec
         fourccs, dimensions, rotation, fps, audio rate/channels)
- MKV/WebM (media/mkv.py: EBML walk — the same fields)

Each parser returns a plain dict of present fields; `parse_stream_info`
dispatches by extension with a magic-byte check. Callers merge this into
`StreamMetadata` (media/avmetadata.py), which still prefers ffprobe when
an ffmpeg install is available.
"""

from __future__ import annotations

import os
import struct
from typing import Dict, Optional

AUDIO_EXTENSIONS = {"wav", "flac", "mp3", "ogg", "opus", "m4a", "aac",
                    "wma", "aiff"}

# Layer III bitrate tables (kbps) by bitrate index; MPEG2 and MPEG2.5
# share one table, distinct from MPEG1's.
_MP3_BITRATES_V1 = {
    1: 32, 2: 40, 3: 48, 4: 56, 5: 64, 6: 80, 7: 96, 8: 112,
    9: 128, 10: 160, 11: 192, 12: 224, 13: 256, 14: 320,
}
_MP3_BITRATES_V2 = {
    1: 8, 2: 16, 3: 24, 4: 32, 5: 40, 6: 48, 7: 56, 8: 64,
    9: 80, 10: 96, 11: 112, 12: 128, 13: 144, 14: 160,
}
# Sample rates by version bits (3=MPEG1, 2=MPEG2, 0=MPEG2.5; 1 reserved).
_MP3_RATES = {
    3: {0: 44100, 1: 48000, 2: 32000},
    2: {0: 22050, 1: 24000, 2: 16000},
    0: {0: 11025, 1: 12000, 2: 8000},
}


def parse_wav(path: str) -> Optional[Dict]:
    with open(path, "rb") as f:
        head = f.read(12)
        if len(head) < 12 or head[:4] != b"RIFF" or head[8:12] != b"WAVE":
            return None
        out: Dict = {"format_name": "wav"}
        byte_rate = data_size = None
        while True:
            hdr = f.read(8)
            if len(hdr) < 8:
                break
            cc, size = hdr[:4], struct.unpack("<I", hdr[4:8])[0]
            if cc == b"fmt " and size >= 16:
                fmt = f.read(size + (size & 1))
                tag, ch, rate, brate, _align, bits = struct.unpack(
                    "<HHIIHH", fmt[:16])
                out["audio_codec"] = {1: "pcm_s16le", 3: "pcm_float",
                                      6: "pcm_alaw", 7: "pcm_mulaw",
                                      85: "mp3"}.get(tag, f"wav_0x{tag:x}")
                out["channels"] = ch
                out["sample_rate"] = rate
                out["bitrate"] = brate * 8
                byte_rate = brate
            elif cc == b"data":
                data_size = size
                f.seek(size + (size & 1), os.SEEK_CUR)
            else:
                f.seek(size + (size & 1), os.SEEK_CUR)
        if byte_rate and data_size:
            out["duration_seconds"] = round(data_size / byte_rate, 3)
        return out if "sample_rate" in out else None


def parse_flac(path: str) -> Optional[Dict]:
    with open(path, "rb") as f:
        if f.read(4) != b"fLaC":
            return None
        while True:
            hdr = f.read(4)
            if len(hdr) < 4:
                return None
            last = bool(hdr[0] & 0x80)
            btype = hdr[0] & 0x7F
            size = int.from_bytes(hdr[1:4], "big")
            if btype != 0:  # only STREAMINFO is read; skip PICTURE etc.
                f.seek(size, os.SEEK_CUR)
                if last:
                    return None
                continue
            block = f.read(size)
            if btype == 0 and size >= 34:  # STREAMINFO
                bits = int.from_bytes(block[10:18], "big")
                rate = (bits >> 44) & 0xFFFFF
                channels = ((bits >> 41) & 0x7) + 1
                depth = ((bits >> 36) & 0x1F) + 1
                total = bits & ((1 << 36) - 1)
                out = {"format_name": "flac", "audio_codec": "flac",
                       "sample_rate": rate, "channels": channels,
                       "bits_per_sample": depth}
                if rate and total:
                    out["duration_seconds"] = round(total / rate, 3)
                return out
            if last:
                return None


def parse_mp3(path: str) -> Optional[Dict]:
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        data = f.read(256 * 1024)
        start = 0
        if data[:3] == b"ID3" and len(data) > 10:
            syn = data[6:10]
            start = 10 + ((syn[0] & 0x7F) << 21 | (syn[1] & 0x7F) << 14
                          | (syn[2] & 0x7F) << 7 | (syn[3] & 0x7F))
            if start >= len(data):
                # Oversized ID3 tag (cover art): window past it.
                f.seek(start)
                data = f.read(256 * 1024)
                base, start = start, 0
            else:
                base = 0
        else:
            base = 0
    pos = start
    while pos + 4 <= len(data):
        b = data[pos:pos + 4]
        if b[0] == 0xFF and (b[1] & 0xE0) == 0xE0:
            version = (b[1] >> 3) & 0x3   # 3=MPEG1 2=MPEG2 0=MPEG2.5
            layer = (b[1] >> 1) & 0x3     # 1=III
            br_idx = (b[2] >> 4) & 0xF
            sr_idx = (b[2] >> 2) & 0x3
            bitrates = (_MP3_BITRATES_V1 if version == 3
                        else _MP3_BITRATES_V2)
            if (layer == 1 and version != 1 and br_idx in bitrates
                    and sr_idx < 3):
                rate = _MP3_RATES[version][sr_idx]
                kbps = bitrates[br_idx]
                out = {"format_name": "mp3", "audio_codec": "mp3",
                       "sample_rate": rate,
                       "channels": 1 if ((b[3] >> 6) & 0x3) == 3 else 2,
                       "bitrate": kbps * 1000}
                # Xing/Info header carries the true frame count (VBR).
                spf = 1152 if version == 3 else 576
                window = data[pos:pos + 200]
                for tag in (b"Xing", b"Info"):
                    at = window.find(tag)
                    if at >= 0 and len(window) >= at + 12:
                        flags = struct.unpack(
                            ">I", window[at + 4:at + 8])[0]
                        if flags & 1:
                            frames = struct.unpack(
                                ">I", window[at + 8:at + 12])[0]
                            out["duration_seconds"] = round(
                                frames * spf / rate, 3)
                            return out
                out["duration_seconds"] = round(
                    (size - base - pos) * 8 / (kbps * 1000),
                    3)  # CBR estimate
                return out
        pos += 1
    return None


def _last_ogg_granule(data: bytes) -> Optional[int]:
    """Granule of the last structurally-plausible page: 'OggS' capture
    + version 0 + sane header-type bits + granule ≥ 0 (a -1 granule or
    a chance 'OggS' inside packet data is skipped)."""
    at = len(data)
    while True:
        at = data.rfind(b"OggS", 0, at)
        if at < 0:
            return None
        if (len(data) >= at + 27 and data[at + 4] == 0
                and data[at + 5] <= 0x07):
            granule = struct.unpack("<q", data[at + 6:at + 14])[0]
            if granule >= 0:
                return granule
        if at == 0:
            return None


def parse_ogg(path: str) -> Optional[Dict]:
    with open(path, "rb") as f:
        head = f.read(4096)
        if head[:4] != b"OggS":
            return None
        f.seek(max(0, os.path.getsize(path) - 65536))
        tail = f.read()
    granule = _last_ogg_granule(tail)
    at = head.find(b"\x01vorbis")
    if at >= 0 and len(head) >= at + 16:
        channels = head[at + 11]
        rate = struct.unpack("<I", head[at + 12:at + 16])[0]
        out = {"format_name": "ogg", "audio_codec": "vorbis",
               "channels": channels, "sample_rate": rate}
        if granule and rate:
            out["duration_seconds"] = round(granule / rate, 3)
        return out
    at = head.find(b"OpusHead")
    if at >= 0 and len(head) >= at + 10:
        channels = head[at + 9]
        out = {"format_name": "ogg", "audio_codec": "opus",
               "channels": channels, "sample_rate": 48000}
        if granule:
            out["duration_seconds"] = round(granule / 48000, 3)
        return out
    return None


def parse_avi(path: str) -> Optional[Dict]:
    """AVI main header → video dimensions/fps/duration; codec fourcc
    from the first stream header."""
    from .mjpeg import _walk_chunks

    out: Dict = {"format_name": "avi"}
    with open(path, "rb") as f:
        head = f.read(12)
        if len(head) < 12 or head[:4] != b"RIFF" or head[8:12] != b"AVI ":
            return None
        f.seek(0, os.SEEK_END)
        end = f.tell()
        for cc, p, size in list(_walk_chunks(f, 12, end)):
            if cc != b"LIST":
                continue
            f.seek(p)
            if f.read(4) != b"hdrl":
                continue
            for c2, p2, s2 in list(_walk_chunks(f, p + 4, p + size)):
                if c2 == b"avih" and s2 >= 40:
                    f.seek(p2)
                    v = struct.unpack("<10I", f.read(40))
                    us_per_frame, _, _, _, frames = v[:5]
                    out["width"], out["height"] = v[8], v[9]
                    if us_per_frame:
                        out["fps"] = round(1e6 / us_per_frame, 3)
                        out["duration_seconds"] = round(
                            frames * us_per_frame / 1e6, 3)
                elif c2 == b"LIST":
                    f.seek(p2)
                    if f.read(4) == b"strl":
                        for c3, p3, s3 in list(_walk_chunks(
                                f, p2 + 4, p2 + s2)):
                            if c3 == b"strh" and s3 >= 8:
                                f.seek(p3)
                                kind = f.read(4)
                                codec = f.read(4)
                                if kind == b"vids":
                                    out["video_codec"] = codec.decode(
                                        "ascii", "replace").strip()
                            break
    return out if len(out) > 1 else None


def _parse_mp4(path: str) -> Optional[Dict]:
    from .mp4meta import parse_mp4

    return parse_mp4(path)


def _parse_mkv(path: str) -> Optional[Dict]:
    from .mkv import parse_mkv

    return parse_mkv(path)


_PARSERS = {
    "wav": parse_wav, "wave": parse_wav,
    "flac": parse_flac,
    "mp3": parse_mp3,
    "ogg": parse_ogg, "oga": parse_ogg, "opus": parse_ogg,
    "avi": parse_avi,
    # ISO-BMFF family (media/mp4meta.py) + Matroska (media/mkv.py):
    # the formats that actually hold most of the world's video.
    "mp4": _parse_mp4, "m4v": _parse_mp4, "mov": _parse_mp4,
    "m4a": _parse_mp4, "3gp": _parse_mp4,
    "mkv": _parse_mkv, "webm": _parse_mkv,
}


def parse_stream_info(path: str) -> Optional[Dict]:
    """Self-hosted container probe by extension — WAV/FLAC/MP3/OGG/
    Opus/AVI here, MP4/MOV/M4A/3GP via media/mp4meta.py, MKV/WebM via
    media/mkv.py; None when the container is unreadable."""
    ext = os.path.splitext(path)[1].lstrip(".").lower()
    parser = _PARSERS.get(ext)
    if parser is None:
        return None
    try:
        return parser(path)
    except (OSError, struct.error, ValueError, IndexError):
        # IndexError: corrupt containers with truncated boxes/elements
        return None
