"""Image decode/convert dispatch (the sd-images crate surface).

Mirrors /root/reference/crates/images: `format_image` (decode to a
canonical RGB(A) image) and `convert_image` (decode + re-encode) route
by extension through per-format handlers, behind a 192 MiB size guard
(consts.rs:9). Handler availability is runtime-gated the way the
reference feature-gates heif/pdfium: the generic raster path is PIL;
HEIF decodes when a PIL HEIF plugin is importable; SVG rasterizes with
the self-hosted pure-Python renderer (media/svg.py — the reference uses
resvg, crates/images/src/svg.rs); PDF renders when pypdfium2 exists.
Unavailable handlers raise `UnsupportedFormat` with the reason, so
callers degrade per-file exactly like the reference's error path.
"""

from __future__ import annotations

import os
from typing import List

MIB = 1_048_576
MAXIMUM_FILE_SIZE = 192 * MIB     # consts.rs:9
SVG_TARGET_PX = 262_144.0         # consts.rs:31
PDF_RENDER_WIDTH = 992            # consts.rs:37

GENERIC_EXTENSIONS = {
    "apng", "bmp", "dib", "ff", "gif", "ico", "jpg", "jpeg", "png",
    "pnm", "qoi", "tga", "icb", "vda", "vst", "tiff", "tif", "webp",
}
SVG_EXTENSIONS = {"svg", "svgz"}
PDF_EXTENSIONS = {"pdf"}
HEIF_EXTENSIONS = {"heif", "heifs", "heic", "heics", "avif", "avci",
                   "avcs"}


class ImageHandlerError(Exception):
    pass


class UnsupportedFormat(ImageHandlerError):
    pass


def _check_size(path: str) -> None:
    if os.path.getsize(path) > MAXIMUM_FILE_SIZE:
        raise ImageHandlerError(
            f"{path}: exceeds maximum image size (192 MiB)")


def _heif_available() -> bool:
    try:
        import pillow_heif  # noqa: F401

        return True
    except ImportError:
        return False


def _pdf_available() -> bool:
    try:
        import pypdfium2  # noqa: F401

        return True
    except ImportError:
        return False


def _svg_available() -> bool:
    return True  # self-hosted rasterizer (media/svg.py)


def supported_extensions() -> List[str]:
    """Extensions `format_image` can decode in this runtime.

    HEIF and PDF are always listed: with no native decoder present the
    extraction paths (embedded JPEG / image-stream recovery) still
    produce thumbnails for the common cases, and files outside that
    envelope degrade per-file via UnsupportedFormat."""
    return (sorted(GENERIC_EXTENSIONS) + sorted(HEIF_EXTENSIONS)
            + sorted(SVG_EXTENSIONS) + sorted(PDF_EXTENSIONS))


def format_image(path: str):
    """Decode any supported image to a PIL Image (handler.rs:18)."""
    _check_size(path)
    ext = os.path.splitext(path)[1].lstrip(".").lower()
    if ext in GENERIC_EXTENSIONS:
        from PIL import Image

        im = Image.open(path)
        im.load()
        return im
    if ext in HEIF_EXTENSIONS:
        if _heif_available():
            import pillow_heif
            from PIL import Image

            pillow_heif.register_heif_opener()
            im = Image.open(path)
            im.load()
            return im
        # Decoder-free path: extract the container's embedded JPEG
        # (JPEG-coded item or EXIF IFD1 thumbnail) — media/isobmff.py.
        import io

        from PIL import Image

        from .isobmff import BoxError, heif_embedded_jpeg

        with open(path, "rb") as f:
            data = f.read()
        try:
            jpeg = heif_embedded_jpeg(data)
        except BoxError as e:
            raise UnsupportedFormat(f"{ext}: {e}") from e
        if jpeg is None:
            raise UnsupportedFormat(
                f"{ext}: no embedded JPEG item or EXIF thumbnail "
                "(full HEVC decode unavailable in this runtime)")
        im = Image.open(io.BytesIO(jpeg))
        im.load()
        return im
    if ext in SVG_EXTENSIONS:
        from .svg import render_svg

        return render_svg(path, target_px=SVG_TARGET_PX)
    if ext in PDF_EXTENSIONS:
        if _pdf_available():
            import pypdfium2

            pdf = pypdfium2.PdfDocument(path)
            page = pdf[0]
            scale = PDF_RENDER_WIDTH / page.get_size()[0]
            return page.render(scale=scale).to_pil()
        # Renderer-free path: recover the page's image stream directly
        # (DCTDecode = embedded JPEG, FlateDecode = raw samples).
        from .pdf import PdfImageError, pdf_first_image

        try:
            return pdf_first_image(path)
        except PdfImageError as e:
            raise UnsupportedFormat(str(e)) from e
    raise UnsupportedFormat(f"unsupported image extension: {ext!r}")


def convert_image(path: str, desired_ext: str):
    """Decode + convert for re-encoding under `desired_ext`
    (handler.rs:23). Returns a PIL Image ready to `.save()`."""
    desired = desired_ext.lstrip(".").lower()
    if desired not in GENERIC_EXTENSIONS:
        raise UnsupportedFormat(
            f"cannot encode to {desired_ext!r}")
    im = format_image(path)
    if desired in ("jpg", "jpeg") and im.mode != "RGB":
        im = im.convert("RGB")
    return im
