"""RAW camera file previews — decoder-free, via the TIFF structure.

The reference filters raw extensions into its media pipeline
(/root/reference/core/src/media_processor/ raw handling) and leans on
image crates to render them. TIFF-based RAW formats (DNG, CR2, NEF,
ARW, PEF, ORF) don't need a RAW demosaicer for thumbnails: every one
of them embeds at least one JPEG preview — IFD0/IFD1 thumbnails
(JPEGInterchangeFormat), SubIFD previews (NEF/DNG), or CR2's IFD0
full-size JPEG strip. This module walks the TIFF IFD tree (both
endians, SubIFDs included), collects every plausible JPEG blob, and
returns the largest one that actually parses as a JPEG.

Pure structure walking — bounded reads, no pixel decoding."""

from __future__ import annotations

import os
import struct
from typing import List, Optional, Tuple

RAW_TIFF_EXTENSIONS = {"dng", "cr2", "nef", "arw", "pef", "orf"}

_MAX_IFDS = 32          # cycle/fuzz guard
_MAX_PREVIEW = 64 << 20  # a preview larger than this is not a preview

# TIFF tags
_STRIP_OFFSETS = 0x0111
_STRIP_BYTE_COUNTS = 0x0117
_COMPRESSION = 0x0103
_JPEG_IF = 0x0201        # JPEGInterchangeFormat (offset)
_JPEG_IF_LEN = 0x0202    # JPEGInterchangeFormatLength
_SUB_IFDS = 0x014A


def _u(fmt: str, data: bytes, off: int) -> int:
    return struct.unpack_from(fmt, data, off)[0]


def _read_ifd(data: bytes, off: int, e: str) -> Tuple[dict, int]:
    """One IFD → ({tag: (type, count, value_or_offset_raw)}, next_off)."""
    if off + 2 > len(data):
        return {}, 0
    n = _u(e + "H", data, off)
    entries = {}
    p = off + 2
    for _ in range(n):
        if p + 12 > len(data):
            break
        tag = _u(e + "H", data, p)
        typ = _u(e + "H", data, p + 2)
        count = _u(e + "I", data, p + 4)
        entries[tag] = (typ, count, p + 8)
        p += 12
    nxt = _u(e + "I", data, p) if p + 4 <= len(data) else 0
    return entries, nxt


def _value(data: bytes, e: str, entry, index: int = 0) -> Optional[int]:
    """Integer value of a SHORT/LONG entry (inline or offset array)."""
    typ, count, vpos = entry
    size = {3: 2, 4: 4}.get(typ)
    if size is None or index >= count:
        return None
    fmt = e + ("H" if typ == 3 else "I")
    if count * size <= 4:
        return _u(fmt, data, vpos + index * size)
    arr_off = _u(e + "I", data, vpos)
    p = arr_off + index * size
    if p + size > len(data):
        return None
    return _u(fmt, data, p)


def _is_jpeg(blob: bytes) -> bool:
    return len(blob) > 4 and blob[:2] == b"\xff\xd8"


def extract_preview(path: str) -> Optional[bytes]:
    """Largest embedded JPEG preview of a TIFF-structured RAW file, or
    None when the file isn't TIFF-shaped / carries no parseable JPEG."""
    size = os.path.getsize(path)
    if size < 16 or size > (2 << 30):
        return None
    with open(path, "rb") as f:
        # Bounded: the IFD structures live at the head of every format
        # this module accepts; preview BLOBS may sit anywhere and are
        # seek+read individually below, never the whole file.
        data = f.read(min(size, 16 << 20))
    if data[:2] == b"II":
        e = "<"
    elif data[:2] == b"MM":
        e = ">"
    else:
        return None
    magic = _u(e + "H", data, 2)
    # 42 = TIFF/DNG/NEF/ARW/PEF; 0x4F52/0x5352 = ORF ("RO"/"RS")
    if magic not in (42, 0x4F52, 0x5352):
        return None
    first_ifd = _u(e + "I", data, 4)

    candidates: List[Tuple[int, int]] = []  # (offset, length)
    seen = set()
    queue = [first_ifd]
    hops = 0
    while queue and hops < _MAX_IFDS:
        off = queue.pop(0)
        if not off or off in seen or off + 2 > len(data):
            continue
        seen.add(off)
        hops += 1
        entries, nxt = _read_ifd(data, off, e)
        if nxt:
            queue.append(nxt)
        if _SUB_IFDS in entries:
            typ, count, _v = entries[_SUB_IFDS]
            for i in range(min(count, 8)):
                sub = _value(data, e, entries[_SUB_IFDS], i)
                if sub:
                    queue.append(sub)
        # IFD0/IFD1-style thumbnail pair
        if _JPEG_IF in entries and _JPEG_IF_LEN in entries:
            o = _value(data, e, entries[_JPEG_IF])
            ln = _value(data, e, entries[_JPEG_IF_LEN])
            if o and ln:
                candidates.append((o, ln))
        # strip-based previews (CR2 IFD0 carries a full-size JPEG this
        # way, compression 6 = old-style JPEG)
        comp = (_value(data, e, entries[_COMPRESSION])
                if _COMPRESSION in entries else None)
        if comp in (6, 7) and _STRIP_OFFSETS in entries \
                and _STRIP_BYTE_COUNTS in entries:
            o = _value(data, e, entries[_STRIP_OFFSETS])
            ln = _value(data, e, entries[_STRIP_BYTE_COUNTS])
            if o and ln:
                candidates.append((o, ln))

    best: Optional[bytes] = None
    fh = None
    try:
        for o, ln in candidates:
            if ln <= 0 or ln > _MAX_PREVIEW or o + ln > size:
                continue
            if o + ln <= len(data):
                blob = data[o:o + ln]
            else:  # preview beyond the structure window: targeted read
                if fh is None:
                    fh = open(path, "rb")
                fh.seek(o)
                blob = fh.read(ln)
            if _is_jpeg(blob) and (best is None or len(blob) > len(best)):
                best = blob
    finally:
        if fh is not None:
            fh.close()
    return best
