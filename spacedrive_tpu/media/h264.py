"""Self-hosted H.264 baseline I-frame decoder (CAVLC, 4:2:0).

The reference thumbnails any video by handing the whole problem to
ffmpeg's FFI (/root/reference/crates/ffmpeg/src/movie_decoder.rs:32,
thumbnailer.rs:11-161: seek 10%, decode one frame, scale, webp). This
image has no ffmpeg, so the dominant real-world codec gets a from-spec
decoder for exactly the slice of the standard a thumbnail needs:

- baseline profile I/IDR pictures: I_4x4, I_16x16 and I_PCM macroblocks
  with all intra prediction modes (ITU-T H.264 §8.3),
- CAVLC entropy decoding (§9.2) with the full coeff_token /
  total_zeros / run_before tables,
- dequantisation + the 4x4 integer inverse transform, the 4x4 luma-DC
  Hadamard and the 2x2 chroma-DC transform (§8.5),
- multi-slice pictures (first_mb_in_slice resumes the raster walk).

Out of scope, by design: P/B slices, CABAC, high-profile 8x8 transforms,
MBAFF/fields, and the in-loop deblocking filter (§8.7) — skipping
deblock changes pixels slightly vs a full decoder but is visually
irrelevant at thumbnail scale; tests therefore ground-truth against
fixtures encoded with deblocking disabled, where decode is bit-exact.

Decoding is deterministic, so correctness is asserted by byte equality
against an independent decoder (OpenCV/FFmpeg) on committed fixtures —
see tools/h264_fixture.py and tests/test_h264.py.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

import numpy as np


class H264Error(ValueError):
    pass


class Unsupported(H264Error):
    """Stream uses features outside the baseline-I subset (CABAC,
    P-slices, 4:2:2...). Callers fall back to cover art."""


# ---------------------------------------------------------------------------
# bit reading
# ---------------------------------------------------------------------------

def unescape(nal: bytes) -> bytes:
    """NAL → RBSP: strip emulation_prevention_three_bytes (§7.4.1)."""
    if b"\x00\x00\x03" not in nal:
        return nal
    out = bytearray()
    i, n = 0, len(nal)
    while i < n:
        if i + 2 < n and nal[i] == 0 and nal[i + 1] == 0 and nal[i + 2] == 3:
            out += b"\x00\x00"
            i += 3
        else:
            out.append(nal[i])
            i += 1
    return bytes(out)


class BitReader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0  # bit position
        self.n = len(data) * 8

    def u(self, bits: int) -> int:
        p, v = self.pos, 0
        if p + bits > self.n:
            raise H264Error("bitstream overrun")
        d = self.data
        for _ in range(bits):
            v = (v << 1) | ((d[p >> 3] >> (7 - (p & 7))) & 1)
            p += 1
        self.pos = p
        return v

    def flag(self) -> int:
        p = self.pos
        if p >= self.n:
            raise H264Error("bitstream overrun")
        self.pos = p + 1
        return (self.data[p >> 3] >> (7 - (p & 7))) & 1

    def ue(self) -> int:
        zeros = 0
        while not self.flag():
            zeros += 1
            if zeros > 32:
                raise H264Error("bad exp-golomb")
        return (1 << zeros) - 1 + (self.u(zeros) if zeros else 0)

    def se(self) -> int:
        k = self.ue()
        return (k + 1) >> 1 if k & 1 else -(k >> 1)

    def byte_align(self) -> None:
        self.pos = (self.pos + 7) & ~7

    def more_rbsp_data(self) -> bool:
        """§7.2: data remains iff bits exist past the rbsp_stop_bit."""
        if self.pos >= self.n:
            return False
        # find last set bit in stream (the stop bit)
        last = self.n - 1
        d = self.data
        while last >= 0 and not (d[last >> 3] >> (7 - (last & 7))) & 1:
            last -= 1
        return self.pos < last


def split_annexb(stream: bytes) -> List[bytes]:
    """Split an Annex-B byte stream into NAL units (no start codes)."""
    nals, i, n = [], 0, len(stream)
    starts = []
    while i + 3 <= n:
        if stream[i] == 0 and stream[i + 1] == 0:
            if stream[i + 2] == 1:
                starts.append((i, i + 3))
                i += 3
                continue
            if i + 4 <= n and stream[i + 2] == 0 and stream[i + 3] == 1:
                starts.append((i, i + 4))
                i += 4
                continue
        i += 1
    for k, (s, body) in enumerate(starts):
        end = starts[k + 1][0] if k + 1 < len(starts) else n
        if body < end:
            nals.append(stream[body:end])
    return nals


# ---------------------------------------------------------------------------
# parameter sets (§7.3.2)
# ---------------------------------------------------------------------------

def parse_sps(rbsp: bytes) -> Dict:
    r = BitReader(rbsp)
    sps: Dict = {}
    sps["profile_idc"] = r.u(8)
    r.u(8)  # constraint flags + reserved
    sps["level_idc"] = r.u(8)
    sps["id"] = r.ue()
    if sps["profile_idc"] in (100, 110, 122, 244, 44, 83, 86, 118, 128):
        chroma = r.ue()
        sps["chroma_format_idc"] = chroma
        if chroma == 3:
            r.flag()
        r.ue()  # bit_depth_luma_minus8
        r.ue()  # bit_depth_chroma_minus8
        r.flag()  # qpprime_y_zero_transform_bypass
        if r.flag():  # seq_scaling_matrix_present
            raise Unsupported("scaling matrices")
        if chroma != 1:
            raise Unsupported(f"chroma_format_idc {chroma}")
    else:
        sps["chroma_format_idc"] = 1
    sps["log2_max_frame_num"] = r.ue() + 4
    poc = r.ue()
    sps["pic_order_cnt_type"] = poc
    if poc == 0:
        sps["log2_max_poc_lsb"] = r.ue() + 4
    elif poc == 1:
        r.flag()
        r.se()
        r.se()
        for _ in range(r.ue()):
            r.se()
    sps["max_num_ref_frames"] = r.ue()
    r.flag()  # gaps_in_frame_num_value_allowed
    sps["pic_width_in_mbs"] = r.ue() + 1
    sps["pic_height_in_map_units"] = r.ue() + 1
    sps["frame_mbs_only"] = r.flag()
    if not sps["frame_mbs_only"]:
        raise Unsupported("interlaced (fields/MBAFF)")
    r.flag()  # direct_8x8_inference
    sps["crop"] = (0, 0, 0, 0)
    if r.flag():  # frame_cropping
        sps["crop"] = (r.ue(), r.ue(), r.ue(), r.ue())  # l, r, t, b
    return sps


def parse_pps(rbsp: bytes) -> Dict:
    r = BitReader(rbsp)
    pps: Dict = {}
    pps["id"] = r.ue()
    pps["sps_id"] = r.ue()
    if r.flag():  # entropy_coding_mode
        raise Unsupported("CABAC")
    pps["bottom_field_pic_order"] = r.flag()
    if r.ue() != 0:  # num_slice_groups_minus1
        raise Unsupported("slice groups (FMO)")
    pps["num_ref_idx_l0"] = r.ue() + 1
    pps["num_ref_idx_l1"] = r.ue() + 1
    r.flag()  # weighted_pred
    r.u(2)  # weighted_bipred_idc
    pps["pic_init_qp"] = r.se() + 26
    r.se()  # pic_init_qs
    pps["chroma_qp_index_offset"] = r.se()
    pps["deblocking_filter_control_present"] = r.flag()
    pps["constrained_intra_pred"] = r.flag()
    pps["redundant_pic_cnt_present"] = r.flag()
    return pps


# ---------------------------------------------------------------------------
# CAVLC tables (§9.2). Each VLC is {bitstring: value}; bitstrings are
# matched incrementally, MSB first.
# ---------------------------------------------------------------------------

def _vlc(entries) -> Dict[str, Tuple[int, int]]:
    return {code: val for code, val in entries}


# coeff_token → (TotalCoeff, TrailingOnes), Table 9-5, by nC class.
_COEFF_TOKEN_0 = _vlc([  # 0 <= nC < 2
    ("1", (0, 0)),
    ("000101", (1, 0)), ("01", (1, 1)),
    ("00000111", (2, 0)), ("000100", (2, 1)), ("001", (2, 2)),
    ("000000111", (3, 0)), ("00000110", (3, 1)), ("0000101", (3, 2)),
    ("00011", (3, 3)),
    ("0000000111", (4, 0)), ("000000110", (4, 1)), ("00000101", (4, 2)),
    ("000011", (4, 3)),
    ("00000000111", (5, 0)), ("0000000110", (5, 1)), ("000000101", (5, 2)),
    ("0000100", (5, 3)),
    ("0000000001111", (6, 0)), ("00000000110", (6, 1)),
    ("0000000101", (6, 2)), ("00000100", (6, 3)),
    ("0000000001011", (7, 0)), ("0000000001110", (7, 1)),
    ("00000000101", (7, 2)), ("000000100", (7, 3)),
    ("0000000001000", (8, 0)), ("0000000001010", (8, 1)),
    ("0000000001101", (8, 2)), ("0000000100", (8, 3)),
    ("00000000001111", (9, 0)), ("00000000001110", (9, 1)),
    ("0000000001001", (9, 2)), ("00000000100", (9, 3)),
    ("00000000001011", (10, 0)), ("00000000001010", (10, 1)),
    ("00000000001101", (10, 2)), ("0000000001100", (10, 3)),
    ("000000000001111", (11, 0)), ("000000000001110", (11, 1)),
    ("00000000001001", (11, 2)), ("00000000001100", (11, 3)),
    ("000000000001011", (12, 0)), ("000000000001010", (12, 1)),
    ("000000000001101", (12, 2)), ("00000000001000", (12, 3)),
    ("0000000000001111", (13, 0)), ("000000000000001", (13, 1)),
    ("000000000001001", (13, 2)), ("000000000001100", (13, 3)),
    ("0000000000001011", (14, 0)), ("0000000000001110", (14, 1)),
    ("0000000000001101", (14, 2)), ("000000000001000", (14, 3)),
    ("0000000000000111", (15, 0)), ("0000000000001010", (15, 1)),
    ("0000000000001001", (15, 2)), ("0000000000001100", (15, 3)),
    ("0000000000000100", (16, 0)), ("0000000000000110", (16, 1)),
    ("0000000000000101", (16, 2)), ("0000000000001000", (16, 3)),
])

_COEFF_TOKEN_2 = _vlc([  # 2 <= nC < 4
    ("11", (0, 0)),
    ("001011", (1, 0)), ("10", (1, 1)),
    ("000111", (2, 0)), ("00111", (2, 1)), ("011", (2, 2)),
    ("0000111", (3, 0)), ("001010", (3, 1)), ("001001", (3, 2)),
    ("0101", (3, 3)),
    ("00000111", (4, 0)), ("000110", (4, 1)), ("000101", (4, 2)),
    ("0100", (4, 3)),
    ("00000100", (5, 0)), ("0000110", (5, 1)), ("0000101", (5, 2)),
    ("00110", (5, 3)),
    ("000000111", (6, 0)), ("00000110", (6, 1)), ("00000101", (6, 2)),
    ("001000", (6, 3)),
    ("00000001111", (7, 0)), ("000000110", (7, 1)), ("000000101", (7, 2)),
    ("000100", (7, 3)),
    ("00000001011", (8, 0)), ("00000001110", (8, 1)),
    ("00000001101", (8, 2)), ("0000100", (8, 3)),
    ("000000001111", (9, 0)), ("00000001010", (9, 1)),
    ("00000001001", (9, 2)), ("000000100", (9, 3)),
    ("000000001011", (10, 0)), ("000000001110", (10, 1)),
    ("000000001101", (10, 2)), ("00000001100", (10, 3)),
    ("000000001000", (11, 0)), ("000000001010", (11, 1)),
    ("000000001001", (11, 2)), ("00000001000", (11, 3)),
    ("0000000001111", (12, 0)), ("0000000001110", (12, 1)),
    ("0000000001101", (12, 2)), ("000000001100", (12, 3)),
    ("0000000001011", (13, 0)), ("0000000001010", (13, 1)),
    ("0000000001001", (13, 2)), ("0000000001100", (13, 3)),
    ("0000000000111", (14, 0)), ("00000000001011", (14, 1)),
    ("0000000000110", (14, 2)), ("0000000001000", (14, 3)),
    ("00000000001001", (15, 0)), ("00000000001000", (15, 1)),
    ("00000000001010", (15, 2)), ("0000000000001", (15, 3)),
    ("00000000000111", (16, 0)), ("00000000000110", (16, 1)),
    ("00000000000101", (16, 2)), ("00000000000100", (16, 3)),
])

_COEFF_TOKEN_4 = _vlc([  # 4 <= nC < 8
    ("1111", (0, 0)),
    ("001111", (1, 0)), ("1110", (1, 1)),
    ("001011", (2, 0)), ("01111", (2, 1)), ("1101", (2, 2)),
    ("001000", (3, 0)), ("01100", (3, 1)), ("01110", (3, 2)),
    ("1100", (3, 3)),
    ("0001111", (4, 0)), ("01010", (4, 1)), ("01011", (4, 2)),
    ("1011", (4, 3)),
    ("0001011", (5, 0)), ("01000", (5, 1)), ("01001", (5, 2)),
    ("1010", (5, 3)),
    ("0001001", (6, 0)), ("001110", (6, 1)), ("001101", (6, 2)),
    ("1001", (6, 3)),
    ("0001000", (7, 0)), ("001010", (7, 1)), ("001001", (7, 2)),
    ("1000", (7, 3)),
    ("00001111", (8, 0)), ("0001110", (8, 1)), ("0001101", (8, 2)),
    ("01101", (8, 3)),
    ("00001011", (9, 0)), ("00001110", (9, 1)), ("0001010", (9, 2)),
    ("001100", (9, 3)),
    ("000001111", (10, 0)), ("00001010", (10, 1)), ("00001101", (10, 2)),
    ("0001100", (10, 3)),
    ("000001011", (11, 0)), ("000001110", (11, 1)), ("00001001", (11, 2)),
    ("00001100", (11, 3)),
    ("000001000", (12, 0)), ("000001010", (12, 1)), ("000001101", (12, 2)),
    ("00001000", (12, 3)),
    ("0000001101", (13, 0)), ("000000111", (13, 1)), ("000001001", (13, 2)),
    ("000001100", (13, 3)),
    ("0000001001", (14, 0)), ("0000001100", (14, 1)), ("0000001011", (14, 2)),
    ("0000001010", (14, 3)),
    ("0000000101", (15, 0)), ("0000001000", (15, 1)), ("0000000111", (15, 2)),
    ("0000000110", (15, 3)),
    ("0000000001", (16, 0)), ("0000000100", (16, 1)), ("0000000011", (16, 2)),
    ("0000000010", (16, 3)),
])

_COEFF_TOKEN_CHROMA_DC = _vlc([  # nC == -1 (4:2:0 chroma DC)
    ("01", (0, 0)),
    ("000111", (1, 0)), ("1", (1, 1)),
    ("000100", (2, 0)), ("000110", (2, 1)), ("001", (2, 2)),
    ("000011", (3, 0)), ("0000011", (3, 1)), ("0000010", (3, 2)),
    ("000101", (3, 3)),
    ("000010", (4, 0)), ("00000011", (4, 1)), ("00000010", (4, 2)),
    ("0000000", (4, 3)),
])

# total_zeros, Table 9-7/9-8 (4x4 blocks), indexed by TotalCoeff 1..15.
_TOTAL_ZEROS_4x4 = {
    1: _vlc([("1", 0), ("011", 1), ("010", 2), ("0011", 3), ("0010", 4),
             ("00011", 5), ("00010", 6), ("000011", 7), ("000010", 8),
             ("0000011", 9), ("0000010", 10), ("00000011", 11),
             ("00000010", 12), ("000000011", 13), ("000000010", 14),
             ("000000001", 15)]),
    2: _vlc([("111", 0), ("110", 1), ("101", 2), ("100", 3), ("011", 4),
             ("0101", 5), ("0100", 6), ("0011", 7), ("0010", 8),
             ("00011", 9), ("00010", 10), ("000011", 11), ("000010", 12),
             ("000001", 13), ("000000", 14)]),
    3: _vlc([("0101", 0), ("111", 1), ("110", 2), ("101", 3), ("0100", 4),
             ("0011", 5), ("100", 6), ("011", 7), ("0010", 8),
             ("00011", 9), ("00010", 10), ("000001", 11), ("00001", 12),
             ("000000", 13)]),
    4: _vlc([("00011", 0), ("111", 1), ("0101", 2), ("0100", 3),
             ("110", 4), ("101", 5), ("100", 6), ("0011", 7), ("011", 8),
             ("0010", 9), ("00010", 10), ("00001", 11), ("00000", 12)]),
    5: _vlc([("0101", 0), ("0100", 1), ("0011", 2), ("111", 3),
             ("110", 4), ("101", 5), ("100", 6), ("011", 7), ("0010", 8),
             ("00001", 9), ("0001", 10), ("00000", 11)]),
    6: _vlc([("000001", 0), ("00001", 1), ("111", 2), ("110", 3),
             ("101", 4), ("100", 5), ("011", 6), ("010", 7), ("0001", 8),
             ("001", 9), ("000000", 10)]),
    7: _vlc([("000001", 0), ("00001", 1), ("101", 2), ("100", 3),
             ("011", 4), ("11", 5), ("010", 6), ("0001", 7), ("001", 8),
             ("000000", 9)]),
    8: _vlc([("000001", 0), ("0001", 1), ("00001", 2), ("011", 3),
             ("11", 4), ("10", 5), ("010", 6), ("001", 7), ("000000", 8)]),
    9: _vlc([("000001", 0), ("000000", 1), ("0001", 2), ("11", 3),
             ("10", 4), ("001", 5), ("01", 6), ("00001", 7)]),
    10: _vlc([("00001", 0), ("00000", 1), ("001", 2), ("11", 3),
              ("10", 4), ("01", 5), ("0001", 6)]),
    11: _vlc([("0000", 0), ("0001", 1), ("001", 2), ("010", 3), ("1", 4),
              ("011", 5)]),
    12: _vlc([("0000", 0), ("0001", 1), ("01", 2), ("1", 3), ("001", 4)]),
    13: _vlc([("000", 0), ("001", 1), ("1", 2), ("01", 3)]),
    14: _vlc([("00", 0), ("01", 1), ("1", 2)]),
    15: _vlc([("0", 0), ("1", 1)]),
}

# total_zeros for chroma DC (4:2:0), Table 9-9(a), TotalCoeff 1..3.
_TOTAL_ZEROS_CHROMA_DC = {
    1: _vlc([("1", 0), ("01", 1), ("001", 2), ("000", 3)]),
    2: _vlc([("1", 0), ("01", 1), ("00", 2)]),
    3: _vlc([("1", 0), ("0", 1)]),
}

# run_before, Table 9-10, indexed by min(zerosLeft, 7).
_RUN_BEFORE = {
    1: _vlc([("1", 0), ("0", 1)]),
    2: _vlc([("1", 0), ("01", 1), ("00", 2)]),
    3: _vlc([("11", 0), ("10", 1), ("01", 2), ("00", 3)]),
    4: _vlc([("11", 0), ("10", 1), ("01", 2), ("001", 3), ("000", 4)]),
    5: _vlc([("11", 0), ("10", 1), ("011", 2), ("010", 3), ("001", 4),
             ("000", 5)]),
    6: _vlc([("11", 0), ("000", 1), ("001", 2), ("011", 3), ("010", 4),
             ("101", 5), ("100", 6)]),
    7: _vlc([("111", 0), ("110", 1), ("101", 2), ("100", 3), ("011", 4),
             ("010", 5), ("001", 6), ("0001", 7), ("00001", 8),
             ("000001", 9), ("0000001", 10), ("00000001", 11),
             ("000000001", 12), ("0000000001", 13), ("00000000001", 14)]),
}


def _read_vlc(r: BitReader, table: Dict[str, object], what: str):
    code = ""
    for _ in range(20):
        code += "1" if r.flag() else "0"
        if code in table:
            return table[code]
    raise H264Error(f"bad {what} VLC: {code}")


# zig-zag scan for 4x4 blocks (Table 8-13), position → (row, col)
_ZIGZAG = [(0, 0), (0, 1), (1, 0), (2, 0), (1, 1), (0, 2), (0, 3), (1, 2),
           (2, 1), (3, 0), (3, 1), (2, 2), (1, 3), (2, 3), (3, 2), (3, 3)]

# dequant scale V (Table: normAdjust4x4 per qp%6 at the 3 position classes)
_DEQUANT_V = [
    (10, 16, 13), (11, 18, 14), (13, 20, 16),
    (14, 23, 18), (16, 25, 20), (18, 29, 23),
]
# position class per (row, col): 0 for (even,even), 1 for (odd,odd), 2 mixed
_POS_CLASS = [[(0 if (i % 2 == 0 and j % 2 == 0) else
               1 if (i % 2 == 1 and j % 2 == 1) else 2)
               for j in range(4)] for i in range(4)]

_CHROMA_QP_MAP = [0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16,
                  17, 18, 19, 20, 21, 22, 23, 24, 25, 26, 27, 28, 29, 29, 30,
                  31, 32, 32, 33, 34, 34, 35, 35, 36, 36, 37, 37, 37, 38, 38,
                  38, 39, 39, 39, 39]

# coded_block_pattern mapping for Intra_4x4 (Table 9-4, codeNum → cbp)
_CBP_INTRA = [47, 31, 15, 0, 23, 27, 29, 30, 7, 11, 13, 14, 39, 43, 45, 46,
              16, 3, 5, 10, 12, 19, 21, 26, 28, 35, 37, 42, 44, 1, 2, 4, 8,
              17, 18, 20, 24, 6, 9, 22, 25, 32, 33, 34, 36, 40, 38, 41]


def residual_block_cavlc(r: BitReader, nC: int, max_coeffs: int
                         ) -> Tuple[List[int], int]:
    """§9.2: one CAVLC residual block → (coefficient levels in scan
    order, TotalCoeff)."""
    if nC == -1:
        table = _COEFF_TOKEN_CHROMA_DC
    elif nC < 2:
        table = _COEFF_TOKEN_0
    elif nC < 4:
        table = _COEFF_TOKEN_2
    elif nC < 8:
        table = _COEFF_TOKEN_4
    else:
        # nC >= 8: 6-bit FLC; 000011 means (0,0)
        v = r.u(6)
        total_coeff, trailing_ones = (0, 0) if v == 3 else \
            ((v >> 2) + 1, v & 3)
        return _cavlc_levels(r, total_coeff, trailing_ones, nC, max_coeffs)
    total_coeff, trailing_ones = _read_vlc(r, table, "coeff_token")
    return _cavlc_levels(r, total_coeff, trailing_ones, nC, max_coeffs)


def _cavlc_levels(r: BitReader, total_coeff: int, trailing_ones: int,
                  nC: int, max_coeffs: int) -> Tuple[List[int], int]:
    if total_coeff == 0:
        return [0] * max_coeffs, 0
    levels: List[int] = []
    for i in range(trailing_ones):
        levels.append(-1 if r.flag() else 1)
    suffix_len = 1 if (total_coeff > 10 and trailing_ones < 3) else 0
    for i in range(trailing_ones, total_coeff):
        # level_prefix: count of zeros before the 1
        prefix = 0
        while not r.flag():
            prefix += 1
            if prefix > 47:
                raise H264Error("bad level_prefix")
        if prefix == 14 and suffix_len == 0:
            suffix_size = 4
        elif prefix >= 15:
            suffix_size = prefix - 3
        else:
            suffix_size = suffix_len
        # levelCode per §9.2.2.1
        level_code = min(15, prefix) << suffix_len
        if prefix >= 15 and suffix_len == 0:
            level_code += 15
        if prefix >= 16:
            level_code += (1 << (prefix - 3)) - 4096
        if suffix_size:
            level_code += r.u(suffix_size)
        if i == trailing_ones and trailing_ones < 3:
            level_code += 2
        level = (level_code + 2) >> 1 if level_code % 2 == 0 else \
            -((level_code + 1) >> 1)
        levels.append(level)
        if suffix_len == 0:
            suffix_len = 1
        if abs(level) > (3 << (suffix_len - 1)) and suffix_len < 6:
            suffix_len += 1
    # total_zeros
    if total_coeff < max_coeffs:
        if nC == -1:
            tz_table = _TOTAL_ZEROS_CHROMA_DC[total_coeff]
        else:
            tz_table = _TOTAL_ZEROS_4x4[total_coeff]
        total_zeros = _read_vlc(r, tz_table, "total_zeros")
    else:
        total_zeros = 0
    # runs
    runs = []
    zeros_left = total_zeros
    for i in range(total_coeff - 1):
        if zeros_left > 0:
            run = _read_vlc(r, _RUN_BEFORE[min(zeros_left, 7)], "run_before")
        else:
            run = 0
        runs.append(run)
        zeros_left -= run
    runs.append(zeros_left)
    # place into scan order (levels are highest-freq first)
    out = [0] * max_coeffs
    pos = -1
    for i in range(total_coeff - 1, -1, -1):
        pos += runs[i] + 1
        if pos >= max_coeffs:
            raise H264Error("coefficient run overflow")
        out[pos] = levels[i]
    return out, total_coeff


# ---------------------------------------------------------------------------
# transforms (§8.5)
# ---------------------------------------------------------------------------

def idct4x4_add(pred: np.ndarray, coeffs: np.ndarray) -> np.ndarray:
    res = _idct_core(coeffs)
    return np.clip(pred.astype(np.int64) + ((res + 32) >> 6), 0, 255)


def _idct_core(c: np.ndarray) -> np.ndarray:
    """§8.5.12.2 order: each row horizontally, then each column."""
    d = c.astype(np.int64)
    e = np.empty((4, 4), np.int64)
    e[:, 0] = d[:, 0] + d[:, 2]
    e[:, 1] = d[:, 0] - d[:, 2]
    e[:, 2] = (d[:, 1] >> 1) - d[:, 3]
    e[:, 3] = d[:, 1] + (d[:, 3] >> 1)
    f = np.empty((4, 4), np.int64)
    f[:, 0] = e[:, 0] + e[:, 3]
    f[:, 1] = e[:, 1] + e[:, 2]
    f[:, 2] = e[:, 1] - e[:, 2]
    f[:, 3] = e[:, 0] - e[:, 3]
    g = np.empty((4, 4), np.int64)
    g[0, :] = f[0, :] + f[2, :]
    g[1, :] = f[0, :] - f[2, :]
    g[2, :] = (f[1, :] >> 1) - f[3, :]
    g[3, :] = f[1, :] + (f[3, :] >> 1)
    h = np.empty((4, 4), np.int64)
    h[0, :] = g[0, :] + g[3, :]
    h[1, :] = g[1, :] + g[2, :]
    h[2, :] = g[1, :] - g[2, :]
    h[3, :] = g[0, :] - g[3, :]
    return h


def dequant4x4(coeffs: List[int], qp: int, skip_dc: bool = False
               ) -> np.ndarray:
    """Scale AC (and optionally DC) levels per §8.5.12.1."""
    out = np.zeros((4, 4), np.int64)
    v = _DEQUANT_V[qp % 6]
    shift = qp // 6
    for idx, (i, j) in enumerate(_ZIGZAG):
        if skip_dc and idx == 0:
            continue
        lvl = coeffs[idx]
        if lvl:
            out[i, j] = (lvl * v[_POS_CLASS[i][j]]) << shift
    return out


def luma_dc_dequant(dc: np.ndarray, qp: int) -> np.ndarray:
    """4x4 luma DC: inverse Hadamard then scale (§8.5.10). LevelScale
    here is weightScale(16, flat default) × normAdjust."""
    h = np.array([[1, 1, 1, 1], [1, 1, -1, -1],
                  [1, -1, -1, 1], [1, -1, 1, -1]], np.int64)
    f = h @ dc.astype(np.int64) @ h
    ls = _DEQUANT_V[qp % 6][0] * 16
    if qp >= 36:
        return (f * ls) << (qp // 6 - 6)
    return (f * ls + (1 << (5 - qp // 6))) >> (6 - qp // 6)


def chroma_dc_dequant(dc: np.ndarray, qp: int) -> np.ndarray:
    """2x2 chroma DC transform + scale (§8.5.11), LevelScale = 16 ×
    normAdjust as above."""
    f = np.array([[dc[0, 0] + dc[0, 1] + dc[1, 0] + dc[1, 1],
                   dc[0, 0] - dc[0, 1] + dc[1, 0] - dc[1, 1]],
                  [dc[0, 0] + dc[0, 1] - dc[1, 0] - dc[1, 1],
                   dc[0, 0] - dc[0, 1] - dc[1, 0] + dc[1, 1]]], np.int64)
    ls = _DEQUANT_V[qp % 6][0] * 16
    return ((f * ls) << (qp // 6)) >> 5


# ---------------------------------------------------------------------------
# intra prediction (§8.3)
# ---------------------------------------------------------------------------

def _pred4x4(mode: int, top: Optional[np.ndarray], left: Optional[np.ndarray],
             topleft: Optional[int], topright: Optional[np.ndarray]
             ) -> np.ndarray:
    """9 intra 4x4 modes. top/topright are length-4 arrays (int64),
    left length-4, topleft scalar; None = unavailable."""
    p = np.empty((4, 4), np.int64)
    if mode == 0:  # vertical
        if top is None:
            raise H264Error("pred4x4 V without top")
        p[:] = top
        return p
    if mode == 1:  # horizontal
        if left is None:
            raise H264Error("pred4x4 H without left")
        p[:] = left[:, None]
        return p
    if mode == 2:  # DC
        if top is not None and left is not None:
            dc = (int(top.sum() + left.sum()) + 4) >> 3
        elif top is not None:
            dc = (int(top.sum()) + 2) >> 2
        elif left is not None:
            dc = (int(left.sum()) + 2) >> 2
        else:
            dc = 128
        p[:] = dc
        return p
    # diagonal modes need the 8-sample top row (top + topright)
    if mode in (3, 7):
        if top is None:
            raise H264Error("pred4x4 diag without top")
        if topright is None:
            tr = np.full(4, top[3], np.int64)
        else:
            tr = topright
        t = np.concatenate([top, tr])
    if mode == 3:  # diagonal down-left
        for y in range(4):
            for x in range(4):
                if x == 3 and y == 3:
                    p[y, x] = (t[6] + 3 * t[7] + 2) >> 2
                else:
                    p[y, x] = (t[x + y] + 2 * t[x + y + 1]
                               + t[x + y + 2] + 2) >> 2
        return p
    if mode == 7:  # vertical-left
        for y in range(4):
            for x in range(4):
                i = x + (y >> 1)
                if y % 2 == 0:
                    p[y, x] = (t[i] + t[i + 1] + 1) >> 1
                else:
                    p[y, x] = (t[i] + 2 * t[i + 1] + t[i + 2] + 2) >> 2
        return p
    if mode == 8:  # horizontal-up: left samples only
        if left is None:
            raise H264Error("pred4x4 HU without left")
        la = left
        for y in range(4):
            for x in range(4):
                z = x + 2 * y
                if z < 5:
                    i = y + (x >> 1)
                    if z % 2 == 0:
                        p[y, x] = (la[i] + la[i + 1] + 1) >> 1
                    else:
                        p[y, x] = (la[i] + 2 * la[i + 1] + la[i + 2] + 2) >> 2
                elif z == 5:
                    p[y, x] = (la[2] + 3 * la[3] + 2) >> 2
                else:
                    p[y, x] = la[3]
        return p
    # remaining modes (4, 5, 6) need top+left+topleft
    if top is None or left is None or topleft is None:
        raise H264Error("pred4x4 mode needs full neighborhood")
    tl = int(topleft)
    if mode == 4:  # diagonal down-right
        # ref[] = the 9 border samples left-bottom → topleft → top-right
        # (ref[0]=left[3] .. ref[3]=left[0], ref[4]=topleft, ref[5..8]=top)
        ref = np.empty(9, np.int64)
        ref[0:4] = left[::-1]      # ref[0]=left[3] ... ref[3]=left[0]
        ref[4] = tl
        ref[5:9] = top
        for y in range(4):
            for x in range(4):
                k = 4 + x - y
                p[y, x] = (ref[k - 1] + 2 * ref[k] + ref[k + 1] + 2) >> 2
        return p
    if mode == 5:  # vertical-right
        ref = np.empty(9, np.int64)
        ref[0:4] = left[::-1]
        ref[4] = tl
        ref[5:9] = top
        for y in range(4):
            for x in range(4):
                z = 2 * x - y
                k = 4 + x - (y >> 1)
                if z >= 0 and z % 2 == 0:
                    p[y, x] = (ref[k] + ref[k + 1] + 1) >> 1
                elif z >= 0:
                    p[y, x] = (ref[k - 1] + 2 * ref[k] + ref[k + 1] + 2) >> 2
                elif z == -1:
                    p[y, x] = (ref[3] + 2 * ref[4] + ref[5] + 2) >> 2
                else:  # z <= -2: down the left column (x=0, y=2..3)
                    p[y, x] = (left[y - 1] + 2 * left[y - 2] +
                               (left[y - 3] if y >= 3 else tl) + 2) >> 2
        return p
    if mode == 6:  # horizontal-down
        ref = np.empty(9, np.int64)
        ref[0:4] = left[::-1]
        ref[4] = tl
        ref[5:9] = top
        for y in range(4):
            for x in range(4):
                z = 2 * y - x
                k = 4 - y + (x >> 1)
                if z >= 0 and z % 2 == 0:
                    p[y, x] = (ref[k] + ref[k - 1] + 1) >> 1
                elif z >= 0:
                    p[y, x] = (ref[k + 1] + 2 * ref[k] + ref[k - 1] + 2) >> 2
                elif z == -1:
                    p[y, x] = (ref[3] + 2 * ref[4] + ref[5] + 2) >> 2
                else:  # z <= -2: along the top row (y=0, x=2..3)
                    p[y, x] = (top[x - 1] + 2 * top[x - 2] +
                               (top[x - 3] if x >= 3 else tl) + 2) >> 2
        return p
    raise H264Error(f"intra4x4 mode {mode}")


def _pred16x16(mode: int, top: Optional[np.ndarray],
               left: Optional[np.ndarray], topleft: Optional[int]
               ) -> np.ndarray:
    p = np.empty((16, 16), np.int64)
    if mode == 0:  # vertical
        if top is None:
            raise H264Error("pred16 V without top")
        p[:] = top
    elif mode == 1:  # horizontal
        if left is None:
            raise H264Error("pred16 H without left")
        p[:] = left[:, None]
    elif mode == 2:  # DC
        if top is not None and left is not None:
            dc = (int(top.sum() + left.sum()) + 16) >> 5
        elif top is not None:
            dc = (int(top.sum()) + 8) >> 4
        elif left is not None:
            dc = (int(left.sum()) + 8) >> 4
        else:
            dc = 128
        p[:] = dc
    elif mode == 3:  # plane
        if top is None or left is None or topleft is None:
            raise H264Error("pred16 plane needs full neighborhood")
        tl = int(topleft)
        h = sum((x + 1) * (int(top[8 + x]) -
                           (int(top[6 - x]) if 6 - x >= 0 else tl))
                for x in range(8))
        v = sum((y + 1) * (int(left[8 + y]) -
                           (int(left[6 - y]) if 6 - y >= 0 else tl))
                for y in range(8))
        b = (5 * h + 32) >> 6
        c = (5 * v + 32) >> 6
        a = 16 * (int(left[15]) + int(top[15]))
        for y in range(16):
            for x in range(16):
                p[y, x] = np.clip((a + b * (x - 7) + c * (y - 7) + 16) >> 5,
                                  0, 255)
    else:
        raise H264Error(f"intra16x16 mode {mode}")
    return p


def _pred_chroma(mode: int, top: Optional[np.ndarray],
                 left: Optional[np.ndarray], topleft: Optional[int]
                 ) -> np.ndarray:
    p = np.empty((8, 8), np.int64)
    if mode == 0:  # DC, per 4x4 quadrant (§8.3.4.1)
        for qy in (0, 4):
            for qx in (0, 4):
                t = top[qx:qx + 4] if top is not None else None
                l = left[qy:qy + 4] if left is not None else None
                # corner quadrants prefer the adjacent edge
                if qx == 0 and qy == 0 or qx == 4 and qy == 4:
                    if t is not None and l is not None:
                        dc = (int(t.sum() + l.sum()) + 4) >> 3
                    elif t is not None:
                        dc = (int(t.sum()) + 2) >> 2
                    elif l is not None:
                        dc = (int(l.sum()) + 2) >> 2
                    else:
                        dc = 128
                elif qx == 4 and qy == 0:
                    if t is not None:
                        dc = (int(t.sum()) + 2) >> 2
                    elif l is not None:
                        dc = (int(l.sum()) + 2) >> 2
                    else:
                        dc = 128
                else:  # qx == 0, qy == 4
                    if l is not None:
                        dc = (int(l.sum()) + 2) >> 2
                    elif t is not None:
                        dc = (int(t.sum()) + 2) >> 2
                    else:
                        dc = 128
                p[qy:qy + 4, qx:qx + 4] = dc
    elif mode == 1:  # horizontal
        if left is None:
            raise H264Error("chroma H without left")
        p[:] = left[:, None]
    elif mode == 2:  # vertical
        if top is None:
            raise H264Error("chroma V without top")
        p[:] = top
    elif mode == 3:  # plane
        if top is None or left is None or topleft is None:
            raise H264Error("chroma plane needs full neighborhood")
        tl = int(topleft)
        h = sum((x + 1) * (int(top[4 + x]) -
                           (int(top[2 - x]) if 2 - x >= 0 else tl))
                for x in range(4))
        v = sum((y + 1) * (int(left[4 + y]) -
                           (int(left[2 - y]) if 2 - y >= 0 else tl))
                for y in range(4))
        b = (17 * h + 16) >> 5
        c = (17 * v + 16) >> 5
        a = 16 * (int(left[7]) + int(top[7]))
        for y in range(8):
            for x in range(8):
                p[y, x] = np.clip((a + b * (x - 3) + c * (y - 3) + 16) >> 5,
                                  0, 255)
    else:
        raise H264Error(f"chroma mode {mode}")
    return p


# I_16x16 mb_type decomposition (Table 7-11): mb_type 1..24
def _i16_info(mb_type: int) -> Tuple[int, int, int]:
    """→ (pred_mode, cbp_chroma, cbp_luma) for I_16x16 mb_type."""
    m = mb_type - 1
    pred = m % 4
    m //= 4
    cbp_chroma = m % 3
    cbp_luma = 15 if m >= 3 else 0
    return pred, cbp_chroma, cbp_luma


# raster order of the 16 4x4 luma blocks within an MB (§6.4.3 inverse
# 4x4 scan: the standard "zig" ordering of blocks)
_BLK4_ORDER = [(0, 0), (0, 1), (1, 0), (1, 1), (0, 2), (0, 3), (1, 2), (1, 3),
               (2, 0), (2, 1), (3, 0), (3, 1), (2, 2), (2, 3), (3, 2), (3, 3)]


class _Frame:
    """Decode state: planes plus per-4x4-block CAVLC nC bookkeeping."""

    def __init__(self, w_mbs: int, h_mbs: int):
        self.w_mbs, self.h_mbs = w_mbs, h_mbs
        self.Y = np.zeros((h_mbs * 16, w_mbs * 16), np.int64)
        self.Cb = np.zeros((h_mbs * 8, w_mbs * 8), np.int64)
        self.Cr = np.zeros((h_mbs * 8, w_mbs * 8), np.int64)
        # total_coeff per 4x4 block, -1 = not yet decoded
        self.nzY = np.full((h_mbs * 4, w_mbs * 4), -1, np.int16)
        self.nzCb = np.full((h_mbs * 2, w_mbs * 2), -1, np.int16)
        self.nzCr = np.full((h_mbs * 2, w_mbs * 2), -1, np.int16)
        # intra4x4 pred mode per 4x4 block (-1 = unavailable/not intra4x4)
        self.i4mode = np.full((h_mbs * 4, w_mbs * 4), -1, np.int16)
        self.decoded = np.zeros((h_mbs, w_mbs), bool)
        # slice index per MB: neighbors in a DIFFERENT slice are
        # unavailable for intra prediction and CAVLC nC (§6.4.8)
        self.slice_id = np.full((h_mbs, w_mbs), -1, np.int32)

    def same_slice(self, mby: int, mbx: int, sid: int) -> bool:
        return (0 <= mby < self.h_mbs and 0 <= mbx < self.w_mbs
                and self.slice_id[mby, mbx] == sid)


def _nC(nz: np.ndarray, by: int, bx: int, frame: _Frame, sid: int,
        mb_shift: int) -> int:
    """CAVLC nC from left (A) and top (B) block totals (§9.2.1);
    neighbors outside the current slice are unavailable. `mb_shift`
    maps block coords to MB coords (2 for luma 4x4s, 1 for chroma)."""
    nA = nB = None
    if bx > 0 and nz[by, bx - 1] >= 0 and \
            frame.same_slice(by >> mb_shift, (bx - 1) >> mb_shift, sid):
        nA = int(nz[by, bx - 1])
    if by > 0 and nz[by - 1, bx] >= 0 and \
            frame.same_slice((by - 1) >> mb_shift, bx >> mb_shift, sid):
        nB = int(nz[by - 1, bx])
    if nA is not None and nB is not None:
        return (nA + nB + 1) >> 1
    if nA is not None:
        return nA
    if nB is not None:
        return nB
    return 0


def decode_picture(sps: Dict, pps: Dict, slices: List[bytes]
                   ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Decode one I/IDR picture from its slice NALs → (Y, Cb, Cr)
    uint8 planes, cropped per SPS."""
    w_mbs = sps["pic_width_in_mbs"]
    h_mbs = sps["pic_height_in_map_units"]
    frame = _Frame(w_mbs, h_mbs)
    for sid, nal in enumerate(slices):
        _decode_slice(sps, pps, unescape(nal[1:]), nal[0] & 0x1F, frame, sid)
    if not frame.decoded.all():
        raise H264Error("picture incomplete: missing macroblocks")
    Y = frame.Y.astype(np.uint8)
    Cb = frame.Cb.astype(np.uint8)
    Cr = frame.Cr.astype(np.uint8)
    cl, cr, ct, cb = sps["crop"]
    H, W = Y.shape
    Y = Y[2 * ct:H - 2 * cb or None, 2 * cl:W - 2 * cr or None]
    Cb = Cb[ct:(H // 2) - cb or None, cl:(W // 2) - cr or None]
    Cr = Cr[ct:(H // 2) - cb or None, cl:(W // 2) - cr or None]
    return Y, Cb, Cr


def _decode_slice(sps: Dict, pps: Dict, rbsp: bytes, nal_type: int,
                  frame: _Frame, sid: int = 0) -> None:
    r = BitReader(rbsp)
    first_mb = r.ue()
    slice_type = r.ue()
    if slice_type % 5 != 2:  # 2/7 = I
        raise Unsupported(f"slice_type {slice_type} (only I)")
    r.ue()  # pps id (single-PPS streams assumed; caller matched them)
    r.u(sps["log2_max_frame_num"])  # frame_num
    if nal_type == 5:
        r.ue()  # idr_pic_id
    if sps["pic_order_cnt_type"] == 0:
        r.u(sps["log2_max_poc_lsb"])
        if pps["bottom_field_pic_order"]:
            r.se()
    elif sps["pic_order_cnt_type"] == 1:
        raise Unsupported("poc type 1 slice fields")
    if pps["redundant_pic_cnt_present"]:
        r.ue()
    if nal_type == 5:
        r.flag()  # no_output_of_prior_pics
        r.flag()  # long_term_reference
    # I slice: no ref lists, no pred weights
    qp = pps["pic_init_qp"] + r.se()
    disable_deblock = 0
    if pps["deblocking_filter_control_present"]:
        disable_deblock = r.ue()
        if disable_deblock != 1:
            r.se()
            r.se()
    # macroblock_layer loop
    addr = first_mb
    total = frame.w_mbs * frame.h_mbs
    while True:
        if addr >= total:
            raise H264Error("mb address past picture end")
        qp = _decode_mb(r, sps, pps, frame, addr, qp, sid)
        addr += 1
        if not r.more_rbsp_data():
            break


def _decode_mb(r: BitReader, sps: Dict, pps: Dict, frame: _Frame,
               addr: int, qp: int, sid: int) -> int:
    mby, mbx = divmod(addr, frame.w_mbs)
    y0, x0 = mby * 16, mbx * 16
    cy0, cx0 = mby * 8, mbx * 8
    mb_type = r.ue()
    if mb_type > 25:
        raise H264Error(f"mb_type {mb_type} in I slice")

    up = frame.same_slice(mby - 1, mbx, sid)
    left_av = frame.same_slice(mby, mbx - 1, sid)
    upleft = frame.same_slice(mby - 1, mbx - 1, sid)
    upright = frame.same_slice(mby - 1, mbx + 1, sid)
    frame.slice_id[mby, mbx] = sid

    if mb_type == 25:  # I_PCM
        r.byte_align()
        for i in range(16):
            for j in range(16):
                frame.Y[y0 + i, x0 + j] = r.u(8)
        for plane in (frame.Cb, frame.Cr):
            for i in range(8):
                for j in range(8):
                    plane[cy0 + i, cx0 + j] = r.u(8)
        frame.nzY[mby * 4:mby * 4 + 4, mbx * 4:mbx * 4 + 4] = 16
        frame.nzCb[mby * 2:mby * 2 + 2, mbx * 2:mbx * 2 + 2] = 16
        frame.nzCr[mby * 2:mby * 2 + 2, mbx * 2:mbx * 2 + 2] = 16
        frame.i4mode[mby * 4:mby * 4 + 4, mbx * 4:mbx * 4 + 4] = 2  # DC
        frame.decoded[mby, mbx] = True
        return qp

    if mb_type == 0:  # I_4x4 (I_NxN)
        modes = _read_i4_modes(r, frame, mby, mbx, sid)
        chroma_mode = r.ue()
        cbp_code = r.ue()
        if cbp_code >= len(_CBP_INTRA):
            raise H264Error("bad coded_block_pattern")
        cbp = _CBP_INTRA[cbp_code]
        cbp_luma, cbp_chroma = cbp & 15, cbp >> 4
        if cbp_luma or cbp_chroma:
            qp = (qp + r.se() + 52) % 52
        _decode_i4x4_luma(r, frame, mby, mbx, modes, cbp_luma, qp,
                          up, left_av, upleft, upright, sid)
    else:  # I_16x16
        pred_mode, cbp_chroma, cbp_luma = _i16_info(mb_type)
        modes = None
        chroma_mode = r.ue()
        qp = (qp + r.se() + 52) % 52
        _decode_i16x16_luma(r, frame, mby, mbx, pred_mode, cbp_luma, qp,
                            up, left_av, upleft, sid)

    if chroma_mode > 3:
        raise H264Error("bad intra_chroma_pred_mode")
    _decode_chroma(r, pps, frame, mby, mbx, chroma_mode, cbp_chroma, qp,
                   up, left_av, upleft, sid)
    frame.decoded[mby, mbx] = True
    return qp


def _read_i4_modes(r: BitReader, frame: _Frame, mby: int, mbx: int,
                   sid: int) -> List[int]:
    """prev_intra4x4_pred_mode_flag / rem for the 16 blocks (§8.3.1.1),
    in coded block order, returning modes indexed by raster 4x4 pos."""
    modes = [-1] * 16
    b4y0, b4x0 = mby * 4, mbx * 4
    for k in range(16):
        br, bc = _BLK4_ORDER[k]
        gy, gx = b4y0 + br, b4x0 + bc
        # predicted mode = min(left, top) where available, else 2 (DC);
        # neighbors in another slice are unavailable (§8.3.1.1)
        lm = frame.i4mode[gy, gx - 1] if gx > 0 and \
            frame.same_slice(gy >> 2, (gx - 1) >> 2, sid) else -1
        tm = frame.i4mode[gy - 1, gx] if gy > 0 and \
            frame.same_slice((gy - 1) >> 2, gx >> 2, sid) else -1
        pred = 2 if lm < 0 or tm < 0 else min(int(lm), int(tm))
        if r.flag():
            mode = pred
        else:
            rem = r.u(3)
            mode = rem if rem < pred else rem + 1
        modes[br * 4 + bc] = mode
        frame.i4mode[gy, gx] = mode
    return modes


def _luma_neighbors(frame: _Frame, y: int, x: int, up: bool, left: bool,
                    upleft: bool, upright_limit: int):
    """Neighbor samples for a 4x4 at plane coords (y, x); availability
    is sample-precise: inside the MB everything above/left is decoded."""
    Y = frame.Y
    H, W = Y.shape
    top = Y[y - 1, x:x + 4].copy() if y > 0 and up else None
    lf = Y[y:y + 4, x - 1].copy() if x > 0 and left else None
    tl = int(Y[y - 1, x - 1]) if (y > 0 and x > 0 and upleft) else None
    tr = None
    if y > 0 and x + 8 <= upright_limit:
        tr = Y[y - 1, x + 4:x + 8].copy()
    return top, lf, tl, tr


def _decode_i4x4_luma(r: BitReader, frame: _Frame, mby: int, mbx: int,
                      modes: List[int], cbp_luma: int, qp: int,
                      up: bool, left_av: bool, upleft: bool, upright: bool,
                      sid: int = 0) -> None:
    y0, x0 = mby * 16, mbx * 16
    nz = frame.nzY
    for k in range(16):
        br, bc = _BLK4_ORDER[k]
        by, bx = y0 + br * 4, x0 + bc * 4
        gby, gbx = mby * 4 + br, mbx * 4 + bc
        # sample availability for this 4x4
        t_ok = (br > 0) or up
        l_ok = (bc > 0) or left_av
        tl_ok = (br > 0 and bc > 0) or (br > 0 and left_av) or \
            (bc > 0 and up) or upleft
        # top-right availability: within the MB rows, blocks on the top
        # row can see the above MB / above-right MB; interior blocks see
        # decoded-block coverage only when the block above-right in the
        # coded order is already reconstructed.
        tr_ok = False
        if br == 0:
            tr_ok = upright if bc == 3 else up
        elif bc == 3:
            tr_ok = False
        else:
            # above-right 4x4 inside this MB must already be decoded:
            # true iff its coded index precedes k
            nb = _BLK4_ORDER.index((br - 1, bc + 1))
            tr_ok = nb < k
        top, lf, tl, tr = _sample_neigh(frame.Y, by, bx, t_ok, l_ok,
                                        tl_ok, tr_ok)
        mode = modes[br * 4 + bc]
        pred = _pred4x4(mode, top, lf, tl, tr)
        blk8 = (br // 2) * 2 + (bc // 2)
        if cbp_luma & (1 << blk8):
            nc = _nC(nz, gby, gbx, frame, sid, 2)
            coeffs, tc = residual_block_cavlc(r, nc, 16)
            nz[gby, gbx] = tc
            d = dequant4x4(coeffs, qp)
            frame.Y[by:by + 4, bx:bx + 4] = idct4x4_add(pred, d)
        else:
            nz[gby, gbx] = 0
            frame.Y[by:by + 4, bx:bx + 4] = np.clip(pred, 0, 255)


def _sample_neigh(plane: np.ndarray, y: int, x: int, t_ok: bool, l_ok: bool,
                  tl_ok: bool, tr_ok: bool):
    top = plane[y - 1, x:x + 4].copy() if t_ok and y > 0 else None
    lf = plane[y:y + 4, x - 1].copy() if l_ok and x > 0 else None
    tl = int(plane[y - 1, x - 1]) if tl_ok and y > 0 and x > 0 else None
    tr = None
    if tr_ok and y > 0 and x + 8 <= plane.shape[1]:
        tr = plane[y - 1, x + 4:x + 8].copy()
    elif tr_ok and y > 0:
        tr = None  # off right edge: substitution handled in _pred4x4
    return top, lf, tl, tr


def _decode_i16x16_luma(r: BitReader, frame: _Frame, mby: int, mbx: int,
                        pred_mode: int, cbp_luma: int, qp: int,
                        up: bool, left_av: bool, upleft: bool,
                        sid: int = 0) -> None:
    y0, x0 = mby * 16, mbx * 16
    Y = frame.Y
    top = Y[y0 - 1, x0:x0 + 16].copy() if up else None
    lf = Y[y0:y0 + 16, x0 - 1].copy() if left_av else None
    tl = int(Y[y0 - 1, x0 - 1]) if upleft else None
    pred = _pred16x16(pred_mode, top, lf, tl)
    nz = frame.nzY
    # luma DC block: nC from neighboring 4x4 block 0's totals
    nc = _nC(nz, mby * 4, mbx * 4, frame, sid, 2)
    dc_coeffs, _dc_tc = residual_block_cavlc(r, nc, 16)
    dc = np.zeros((4, 4), np.int64)
    for idx, (i, j) in enumerate(_ZIGZAG):
        dc[i, j] = dc_coeffs[idx]
    dc = luma_dc_dequant(dc, qp)
    for k in range(16):
        br, bc = _BLK4_ORDER[k]
        by, bx = y0 + br * 4, x0 + bc * 4
        gby, gbx = mby * 4 + br, mbx * 4 + bc
        if cbp_luma:
            nc = _nC(nz, gby, gbx, frame, sid, 2)
            coeffs, tc = residual_block_cavlc(r, nc, 15)
            nz[gby, gbx] = tc
            d = dequant4x4([0] + coeffs, qp, skip_dc=False)
            # AC levels occupy scan positions 1..15
            d2 = np.zeros((4, 4), np.int64)
            v = _DEQUANT_V[qp % 6]
            for idx in range(1, 16):
                lvl = coeffs[idx - 1]
                if lvl:
                    i, j = _ZIGZAG[idx]
                    d2[i, j] = (lvl * v[_POS_CLASS[i][j]]) << (qp // 6)
            d = d2
        else:
            nz[gby, gbx] = 0
            d = np.zeros((4, 4), np.int64)
        d[0, 0] = dc[br, bc]
        frame.Y[by:by + 4, bx:bx + 4] = idct4x4_add(
            pred[br * 4:br * 4 + 4, bc * 4:bc * 4 + 4], d)


def _decode_chroma(r: BitReader, pps: Dict, frame: _Frame, mby: int,
                   mbx: int, chroma_mode: int, cbp_chroma: int, qp: int,
                   up: bool, left_av: bool, upleft: bool,
                   sid: int = 0) -> None:
    qpc_i = int(np.clip(qp + pps["chroma_qp_index_offset"], 0, 51))
    qpc = _CHROMA_QP_MAP[qpc_i]
    cy0, cx0 = mby * 8, mbx * 8
    for plane, nz in ((frame.Cb, frame.nzCb), (frame.Cr, frame.nzCr)):
        top = plane[cy0 - 1, cx0:cx0 + 8].copy() if up else None
        lf = plane[cy0:cy0 + 8, cx0 - 1].copy() if left_av else None
        tl = int(plane[cy0 - 1, cx0 - 1]) if upleft else None
        pred = _pred_chroma(chroma_mode, top, lf, tl)
        plane[cy0:cy0 + 8, cx0:cx0 + 8] = np.clip(pred, 0, 255)
    # residuals: DC blocks for both planes, then AC
    dcs = []
    for plane_i in range(2):
        if cbp_chroma:
            coeffs, _tc = residual_block_cavlc(r, -1, 4)
            dc = np.array([[coeffs[0], coeffs[1]],
                           [coeffs[2], coeffs[3]]], np.int64)
            dcs.append(chroma_dc_dequant(dc, qpc))
        else:
            dcs.append(np.zeros((2, 2), np.int64))
    for plane_i, (plane, nz) in enumerate(
            ((frame.Cb, frame.nzCb), (frame.Cr, frame.nzCr))):
        for br in range(2):
            for bc in range(2):
                by, bx = cy0 + br * 4, cx0 + bc * 4
                gby, gbx = mby * 2 + br, mbx * 2 + bc
                pred = plane[by:by + 4, bx:bx + 4].copy()
                if cbp_chroma == 2:
                    nc = _nC(nz, gby, gbx, frame, sid, 1)
                    coeffs, tc = residual_block_cavlc(r, nc, 15)
                    nz[gby, gbx] = tc
                    d = np.zeros((4, 4), np.int64)
                    v = _DEQUANT_V[qpc % 6]
                    for idx in range(1, 16):
                        lvl = coeffs[idx - 1]
                        if lvl:
                            i, j = _ZIGZAG[idx]
                            d[i, j] = (lvl * v[_POS_CLASS[i][j]]) << (qpc // 6)
                else:
                    nz[gby, gbx] = 0
                    d = np.zeros((4, 4), np.int64)
                d[0, 0] = dcs[plane_i][br, bc]
                plane[by:by + 4, bx:bx + 4] = idct4x4_add(pred, d)


# ---------------------------------------------------------------------------
# top-level entry points
# ---------------------------------------------------------------------------

def iter_pictures(stream: bytes):
    """Yield (sps, pps, slice_nals) per coded picture of an Annex-B
    stream. Pictures are cut at slices whose first_mb_in_slice restarts
    at 0 (types 1 AND 5 — non-IDR I slices exist in open-GOP streams),
    so multi-access-unit windows (TS captures) never mix pictures."""
    sps = pps = None
    slices: List[bytes] = []
    for nal in split_annexb(stream):
        if not nal:
            continue
        t = nal[0] & 0x1F
        if t == 7:
            if sps is None:
                sps = parse_sps(unescape(nal[1:]))
        elif t == 8:
            if pps is None:
                pps = parse_pps(unescape(nal[1:]))
        elif t in (1, 5):
            if sps is None or pps is None:
                continue  # mid-stream window before parameter sets
            first_mb = BitReader(unescape(nal[1:5])).ue()
            if first_mb == 0 and slices:
                yield sps, pps, slices
                slices = []
            slices.append(nal)
    if slices:
        yield sps, pps, slices


def decode_annexb_iframe(stream: bytes
                         ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Decode the first decodable I/IDR picture of an Annex-B stream →
    (Y, Cb, Cr). Later pictures are tried (bounded) when the first is
    a P/B slice the intra decoder rejects."""
    err: Optional[H264Error] = None
    for k, (sps, pps, slices) in enumerate(iter_pictures(stream)):
        if sps is None or pps is None:
            raise H264Error("slice before parameter sets")
        try:
            return decode_picture(sps, pps, slices)
        except Unsupported as e:
            err = e  # e.g. a P picture; try the next one
            if k >= 8:
                break
    raise err or H264Error("no decodable I/IDR picture")


def keyframe_from_mp4(path: str, fraction: float = 0.10
                      ) -> Optional[Tuple[np.ndarray, np.ndarray,
                                          np.ndarray]]:
    """Decode the sync sample nearest `fraction` into an H.264 MP4 →
    (Y, Cb, Cr), or None when the file isn't H.264-in-MP4 / uses
    features outside the baseline-I subset.

    The reference's thumbnailer contract (seek 10%, decode one frame —
    /root/reference/crates/ffmpeg/src/movie_decoder.rs:32) realized
    against the container's own sample tables: stsd→avcC for SPS/PPS,
    stss for sync samples, stts for times, stsz/stsc/stco for bytes —
    no demuxer library, O(moov) + one sample read.
    """
    import os as _os

    from .mp4meta import _file_top_boxes
    from .isobmff import iter_boxes

    try:
        with open(path, "rb") as f:
            f.seek(0, _os.SEEK_END)
            end = f.tell()
            f.seek(0)
            if f.read(12)[4:8] != b"ftyp":
                return None
            moov = None
            for typ, ps, pe in _file_top_boxes(f, end):
                if typ == b"moov":
                    if pe - ps > (64 << 20):
                        return None
                    f.seek(ps)
                    moov = f.read(pe - ps)
                    break
            if moov is None:
                return None
            tables = _h264_track_tables(moov)
            if tables is None:
                return None
            sample_i = _pick_sync_sample(tables, fraction)
            if sample_i is None:
                return None
            off, size = _sample_location(tables, sample_i)
            f.seek(off)
            sample = f.read(size)
            if len(sample) != size:
                return None
        nal_len = tables["nal_length_size"]
        slices = []
        pos = 0
        while pos + nal_len <= len(sample):
            ln = int.from_bytes(sample[pos:pos + nal_len], "big")
            pos += nal_len
            nal = sample[pos:pos + ln]
            pos += ln
            if nal and (nal[0] & 0x1F) in (1, 5):
                slices.append(nal)
        if not slices:
            return None
        return decode_picture(tables["sps"], tables["pps"], slices)
    except Unsupported:
        return None
    except (H264Error, struct.error, ValueError, OSError):
        return None


def _h264_track_tables(moov: bytes) -> Optional[Dict]:
    """Sample tables of the first avc1 video track."""
    from .isobmff import iter_boxes

    for typ, ps, pe in iter_boxes(moov):
        if typ != b"trak":
            continue
        out: Dict = {}

        def walk(bs, be):
            for t, s, e in iter_boxes(moov, bs, be):
                if t == b"hdlr":
                    out["handler"] = moov[s + 8:s + 12]
                elif t == b"stsd":
                    n = struct.unpack_from(">I", moov, s + 4)[0]
                    if n >= 1:
                        esz, fourcc = struct.unpack_from(">I4s", moov, s + 8)
                        out["fourcc"] = fourcc
                        out["entry"] = (s + 8, min(s + 8 + esz, e))
                elif t in (b"stts", b"stss", b"stsz", b"stsc", b"stco",
                           b"co64"):
                    out[t.decode()] = (s, e)
                elif t in (b"mdia", b"minf", b"stbl"):
                    walk(s, e)

        walk(ps, pe)
        if out.get("handler") != b"vide" or out.get("fourcc") not in (
                b"avc1", b"avc3"):
            continue
        # avcC inside the VisualSampleEntry (8 + 70 fixed bytes in)
        es, ee = out["entry"]
        avcc = None
        p = es + 8 + 78
        while p + 8 <= ee:
            bsz, btyp = struct.unpack_from(">I4s", moov, p)
            if bsz < 8 or p + bsz > ee:
                break
            if btyp == b"avcC":
                avcc = moov[p + 8:p + bsz]
                break
            p += bsz
        if avcc is None or len(avcc) < 7:
            continue
        nal_len = (avcc[4] & 3) + 1
        n_sps = avcc[5] & 0x1F
        q = 6
        sps = pps = None
        for _ in range(n_sps):
            ln = struct.unpack_from(">H", avcc, q)[0]
            q += 2
            if sps is None:
                sps = parse_sps(unescape(avcc[q + 1:q + ln]))
            q += ln
        n_pps = avcc[q]
        q += 1
        for _ in range(n_pps):
            ln = struct.unpack_from(">H", avcc, q)[0]
            q += 2
            if pps is None:
                pps = parse_pps(unescape(avcc[q + 1:q + ln]))
            q += ln
        if sps is None or pps is None:
            continue
        out["sps"], out["pps"] = sps, pps
        out["nal_length_size"] = nal_len
        out["moov"] = moov
        return out
    return None


def _table_entries(moov: bytes, span, fmt: str, count_off: int = 4):
    s, e = span
    n = struct.unpack_from(">I", moov, s + count_off)[0]
    sz = struct.calcsize(fmt)
    n = min(n, (e - s - count_off - 4) // sz + 1)  # clamp to box bytes
    return n, s + count_off + 4 - 4  # caller offsets per-format


def _pick_sync_sample(t: Dict, fraction: float) -> Optional[int]:
    """1-based sample number of the sync sample nearest `fraction` of
    the track duration (at-or-before; first sync after as fallback)."""
    moov = t["moov"]
    if "stts" not in t or "stsz" not in t:
        return None
    # total samples + the sample index at the target time
    s, e = t["stts"]
    n = struct.unpack_from(">I", moov, s + 4)[0]
    total_samples = 0
    total_time = 0
    runs = []
    p = s + 8
    for _ in range(n):
        if p + 8 > e:
            return None
        cnt, delta = struct.unpack_from(">II", moov, p)
        runs.append((cnt, delta))
        total_samples += cnt
        total_time += cnt * delta
        p += 8
    if total_samples == 0:
        return None
    target_t = total_time * fraction
    acc_t, acc_s = 0, 0
    target_sample = total_samples
    for cnt, delta in runs:
        if delta and acc_t + cnt * delta >= target_t:
            target_sample = acc_s + int((target_t - acc_t) / max(delta, 1)) + 1
            break
        acc_t += cnt * delta
        acc_s += cnt
    target_sample = max(1, min(total_samples, target_sample))
    if "stss" not in t:
        return target_sample  # every sample is sync
    s, e = t["stss"]
    n = struct.unpack_from(">I", moov, s + 4)[0]
    best_before = None
    first_after = None
    p = s + 8
    for _ in range(n):
        if p + 4 > e:
            break
        sync = struct.unpack_from(">I", moov, p)[0]
        if sync <= target_sample:
            best_before = sync
        elif first_after is None:
            first_after = sync
        p += 4
    return best_before or first_after


def _sample_location(t: Dict, sample_i: int) -> Tuple[int, int]:
    """Byte (offset, size) of 1-based sample_i via stsz + stsc + stco."""
    moov = t["moov"]
    # sizes
    s, e = t["stsz"]
    uniform, count = struct.unpack_from(">II", moov, s + 4)

    def size_of(k: int) -> int:  # 1-based
        if uniform:
            return uniform
        return struct.unpack_from(">I", moov, s + 12 + 4 * (k - 1))[0]

    # chunk mapping
    s2, e2 = t["stsc"]
    n2 = struct.unpack_from(">I", moov, s2 + 4)[0]
    entries = []
    p = s2 + 8
    for _ in range(n2):
        first_chunk, per_chunk, _desc = struct.unpack_from(">III", moov, p)
        entries.append((first_chunk, per_chunk))
        p += 12
    # chunk offsets
    if "stco" in t:
        s3, e3 = t["stco"]
        n3 = struct.unpack_from(">I", moov, s3 + 4)[0]

        def chunk_off(c: int) -> int:  # 1-based
            return struct.unpack_from(">I", moov, s3 + 8 + 4 * (c - 1))[0]
    else:
        s3, e3 = t["co64"]
        n3 = struct.unpack_from(">I", moov, s3 + 4)[0]

        def chunk_off(c: int) -> int:
            return struct.unpack_from(">Q", moov, s3 + 8 + 8 * (c - 1))[0]

    # walk chunks to find the one holding sample_i
    remaining = sample_i - 1
    chunk = 1
    for idx, (first_chunk, per_chunk) in enumerate(entries):
        last_chunk = (entries[idx + 1][0] - 1) if idx + 1 < len(entries) \
            else n3
        span_chunks = last_chunk - first_chunk + 1
        span_samples = span_chunks * per_chunk
        if remaining < span_samples:
            chunk = first_chunk + remaining // per_chunk
            index_in_chunk = remaining % per_chunk
            first_sample_of_chunk = sample_i - index_in_chunk
            off = chunk_off(chunk)
            for k in range(first_sample_of_chunk, sample_i):
                off += size_of(k)
            return off, size_of(sample_i)
        remaining -= span_samples
    raise H264Error("sample not covered by stsc")


def yuv420_to_rgb(Y: np.ndarray, Cb: np.ndarray, Cr: np.ndarray
                  ) -> np.ndarray:
    """BT.601 full-swing-ish conversion good enough for thumbnails."""
    H, W = Y.shape
    cb = np.repeat(np.repeat(Cb, 2, 0), 2, 1)[:H, :W].astype(np.float64) - 128
    cr = np.repeat(np.repeat(Cr, 2, 0), 2, 1)[:H, :W].astype(np.float64) - 128
    y = (Y.astype(np.float64) - 16) * (255.0 / 219.0)
    r = y + 1.596 * cr
    g = y - 0.392 * cb - 0.813 * cr
    b = y + 2.017 * cb
    return np.clip(np.stack([r, g, b], -1), 0, 255).astype(np.uint8)
