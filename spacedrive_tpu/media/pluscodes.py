"""Open Location Code ("plus code") encoding for photo GPS metadata.

The reference converts EXIF GPS coordinates into plus codes
(/root/reference/crates/media-metadata/src/image/geographic/pluscodes.rs)
so locations render human-shareably. This is the standard OLC encoding
algorithm (full codes, default 10-digit precision + optional refinement
grid digit pairs), implemented from the public spec.
"""

from __future__ import annotations

ALPHABET = "23456789CFGHJMPQRVWX"
SEPARATOR = "+"
SEPARATOR_POSITION = 8
PADDING = "0"
LAT_MAX = 90.0
LON_MAX = 180.0
PAIR_CODE_LENGTH = 10
GRID_ROWS = 5
GRID_COLS = 4


MAX_CODE_LENGTH = 15
GRID_CODE_LENGTH = MAX_CODE_LENGTH - PAIR_CODE_LENGTH
# Integer precision of the least-significant digit (OLC spec): pairs
# resolve to 1/8000°, each grid digit refines by 5 (lat) / 4 (lon).
FINAL_LAT_PRECISION = 8000 * GRID_ROWS ** GRID_CODE_LENGTH
FINAL_LON_PRECISION = 8000 * GRID_COLS ** GRID_CODE_LENGTH


def encode(lat: float, lon: float, code_length: int = PAIR_CODE_LENGTH
           ) -> str:
    """Encode a latitude/longitude into a full plus code."""
    if code_length < 2 or (code_length < PAIR_CODE_LENGTH
                           and code_length % 2 == 1):
        raise ValueError(f"invalid code length {code_length}")
    code_length = min(code_length, MAX_CODE_LENGTH)
    lat = min(max(lat, -LAT_MAX), LAT_MAX)
    lon = ((lon + LON_MAX) % (2 * LON_MAX)) - LON_MAX
    if lat == LAT_MAX:  # north pole: shift into the topmost cell
        lat -= _lat_precision(code_length)

    lat_val = int((lat + LAT_MAX) * FINAL_LAT_PRECISION)
    lon_val = int((lon + LON_MAX) * FINAL_LON_PRECISION)

    # Build least-significant first, then reverse.
    digits = []
    for _ in range(GRID_CODE_LENGTH):
        digits.append(ALPHABET[(lat_val % GRID_ROWS) * GRID_COLS
                               + lon_val % GRID_COLS])
        lat_val //= GRID_ROWS
        lon_val //= GRID_COLS
    for _ in range(PAIR_CODE_LENGTH // 2):
        digits.append(ALPHABET[lon_val % 20])
        digits.append(ALPHABET[lat_val % 20])
        lat_val //= 20
        lon_val //= 20
    out = "".join(reversed(digits))[:code_length]
    if code_length < SEPARATOR_POSITION:
        out = out + PADDING * (SEPARATOR_POSITION - code_length)
        return out + SEPARATOR
    return out[:SEPARATOR_POSITION] + SEPARATOR + out[SEPARATOR_POSITION:]


def _lat_precision(code_length: int) -> float:
    if code_length <= PAIR_CODE_LENGTH:
        return 20.0 ** (2 - code_length // 2)
    return (20.0 ** -3) / (GRID_ROWS ** (code_length - PAIR_CODE_LENGTH))
