"""Thumbnail generation: webp thumbnails in a 256-way sharded cache.

Mirrors the reference's thumbnailer output contract
(/root/reference/core/src/object/media/thumbnail/mod.rs:47-56,113,117 and
shard.rs:4): thumbnails live at
`<data_dir>/thumbnails/<cas_id[0:2]>/<cas_id>.webp`, scaled so
width*height ≈ TARGET_PX = 262,144 px², encoded webp at quality 30.
Decode/encode is PIL (the reference uses the sd-images Rust crate +
webp encoder); batch resize can move on-device later — decode stays CPU.
"""

from __future__ import annotations

import io
import math
import os
from typing import Optional, Tuple

from .. import persist

TARGET_PX = 262144.0    # thumbnail/mod.rs:113
TARGET_QUALITY = 30     # thumbnail/mod.rs:117
WEBP_EXTENSION = "webp"
VERSION_FILE = "version.txt"
THUMBNAIL_CACHE_VERSION = 1

# Extensions the media dispatch can always thumbnail here: the PIL
# raster set, SVG via the self-hosted rasterizer (media/svg.py), and
# MJPEG `.avi` via the self-hosted container parser (media/mjpeg.py);
# HEIF/PDF run decoder-free via embedded-payload extraction
# (media/isobmff.py, media/pdf.py); files outside that envelope degrade
# per-file. Other video containers join via `thumbnailable_extensions()`
# when ffmpeg is on PATH.
THUMBNAILABLE_EXTENSIONS = {
    "jpg", "jpeg", "png", "gif", "bmp", "tiff", "webp", "ico", "apng",
    "svg", "svgz", "avi",
    "heic", "heif", "heifs", "heics", "avif", "avci", "avcs", "pdf",
}


def thumbnailable_extensions() -> set:
    """Extensions the thumbnail dispatch can handle in THIS runtime:
    the static set, every video container when ffmpeg is present, and
    the cover-art containers always (embedded covr/attachment images
    thumbnail without any decoder; files without one degrade to None)."""
    from .rawpreview import RAW_TIFF_EXTENSIONS
    from .video import (_COVER_EXTENSIONS, _H264_TS_EXTENSIONS,
                        VIDEO_EXTENSIONS, available, cv2_available)

    exts = (set(THUMBNAILABLE_EXTENSIONS) | set(_COVER_EXTENSIONS)
            | RAW_TIFF_EXTENSIONS | set(_H264_TS_EXTENSIONS))
    if available() or cv2_available():
        exts |= VIDEO_EXTENSIONS
    return exts


def shard_hex(cas_id: str) -> str:
    """Two-char shard dir (shard.rs:4)."""
    return cas_id[:2]


def thumbnail_path(data_dir: str, cas_id: str) -> str:
    return os.path.join(
        data_dir, "thumbnails", shard_hex(cas_id),
        f"{cas_id}.{WEBP_EXTENSION}")


def ensure_thumbnail_dir(data_dir: str) -> str:
    root = os.path.join(data_dir, "thumbnails")
    os.makedirs(root, exist_ok=True)
    version_file = os.path.join(root, VERSION_FILE)
    if not os.path.exists(version_file):
        persist.atomic_write("media.thumbs_version", version_file,
                             str(THUMBNAIL_CACHE_VERSION))
    return root


def scale_dimensions(w: float, h: float,
                     target_px: float = TARGET_PX) -> Tuple[int, int]:
    """Scale preserving aspect ratio to ~target_px total pixels
    (thumbnail/mod.rs:142)."""
    ratio = math.sqrt(target_px / (w * h)) if w * h > 0 else 1.0
    ratio = min(ratio, 1.0)  # never upscale
    return max(1, round(w * ratio)), max(1, round(h * ratio))


def encode_webp(im, out_path: str,
                target_px: float = TARGET_PX) -> str:
    """RGB(A)-composite → scale → atomic webp write (the shared tail of
    every thumbnail path: images, SVG, video frames)."""
    from PIL import Image

    if im.mode == "RGBA":
        # Composite transparency onto white like a file manager.
        bg = Image.new("RGB", im.size, (255, 255, 255))
        bg.paste(im, mask=im.split()[3])
        im = bg
    else:
        im = im.convert("RGB")
    w, h = scale_dimensions(im.width, im.height, target_px)
    im = im.resize((w, h), Image.LANCZOS)
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    # Encode to memory, commit through the declared seam: readers
    # (api thumb serving) never see torn webp bytes.
    buf = io.BytesIO()
    im.save(buf, "WEBP", quality=TARGET_QUALITY)
    persist.atomic_write("media.thumbnail", out_path, buf.getvalue())
    return out_path


def generate_thumbnail(input_path: str, data_dir: str,
                       cas_id: str) -> Optional[str]:
    """Decode → scale → webp encode → sharded cache. Returns the output
    path, or None if the format is unsupported. Skips work if the
    thumbnail already exists (actor.rs skip semantics)."""
    out = thumbnail_path(data_dir, cas_id)
    if os.path.exists(out):
        return out
    from .video import VIDEO_EXTENSIONS

    ext = os.path.splitext(input_path)[1].lstrip(".").lower()
    if ext in VIDEO_EXTENSIONS:
        # generate_video_thumbnail picks ffmpeg / MJPEG / None itself.
        from .video import generate_video_thumbnail

        return generate_video_thumbnail(input_path, out)
    from .rawpreview import RAW_TIFF_EXTENSIONS

    if ext in RAW_TIFF_EXTENSIONS:
        # TIFF-structured RAW: largest embedded JPEG preview, no
        # demosaicer (media/rawpreview.py).
        import io

        from PIL import Image

        from .rawpreview import extract_preview

        try:
            blob = extract_preview(input_path)
            if blob is None:
                return None
            with Image.open(io.BytesIO(blob)) as im:
                im.load()
                return encode_webp(im, out)
        except Exception:
            return None
    try:
        # Route through the sd-images dispatch so SVG (self-hosted
        # rasterizer) and gated codecs work, not just PIL formats.
        from .images import format_image

        im = format_image(input_path)
        try:
            return encode_webp(im, out)
        finally:
            im.close() if hasattr(im, "close") else None
    except Exception:
        return None


def remove_thumbnails_by_cas_ids(data_dir: str, cas_ids) -> int:
    """Thumbnailer::remove_cas_ids (actor API)."""
    n = 0
    for cas_id in cas_ids:
        p = thumbnail_path(data_dir, cas_id)
        if os.path.exists(p):
            os.remove(p)
            n += 1
    return n
