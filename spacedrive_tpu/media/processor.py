"""MediaProcessorJob: unified media-data extraction + thumbnail pass.

Mirrors the reference job
(/root/reference/core/src/object/media/media_processor/job.rs:34-67 and
media_processor/mod.rs:75-103): one pass over the location's image paths
in batches of BATCH_SIZE = 10, extracting EXIF into `media_data` rows and
generating webp thumbnails keyed by cas_id.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, List, Optional

from ..jobs.job import EarlyFinish, JobContext, StatefulJob, StepOutcome, register_job
from ..locations.paths import IsolatedPath
from .exif import MEDIA_DATA_EXTENSIONS, extract_media_data
from .thumbnail import (
    thumbnailable_extensions,
    ensure_thumbnail_dir,
    generate_thumbnail,
)

BATCH_SIZE = 10  # media_processor/job.rs:34


@register_job
class MediaProcessorJob(StatefulJob):
    NAME = "media_processor"
    IS_BATCHED = True

    def __init__(self, *, location_id: int, sub_path: Optional[str] = None):
        super().__init__(location_id=location_id, sub_path=sub_path)
        self.location_id = location_id
        self.sub_path = sub_path

    def _init_sync(self, ctx: JobContext):
        db = ctx.db
        from ..locations.file_path_helper import job_prologue
        from .avmetadata import probeable_extensions

        exts = sorted(MEDIA_DATA_EXTENSIONS | thumbnailable_extensions()
                      | probeable_extensions())
        ph = ",".join("?" for _ in exts)
        loc, where, params = job_prologue(
            db, self.location_id, self.sub_path,
            f"location_id = ? AND is_dir = 0 AND object_id IS NOT NULL "
            f"AND LOWER(extension) IN ({ph})",
            [self.location_id, *exts])
        # binds the declared media.file_rows shape
        rows = db.query(
            f"SELECT id, pub_id, object_id, cas_id, materialized_path, "
            f"name, extension FROM file_path WHERE {where} ORDER BY id",
            params)
        if not rows:
            raise EarlyFinish("no media files")
        steps = []
        for i in range(0, len(rows), BATCH_SIZE):
            steps.append({"rows": [dict(r) for r in rows[i:i + BATCH_SIZE]]})
        data = {"location_path": loc["path"], "extracted": 0, "thumbs": 0}
        ctx.progress(task_count=len(steps))
        return data, steps

    async def execute_step(self, ctx, data, step, step_number):
        outcome = await asyncio.to_thread(self._exif_step, ctx, data, step)
        await self._thumbs_step(ctx, data, step)
        ctx.progress(message=(
            f"media: {data['extracted']} exif, {data['thumbs']} thumbs"))
        outcome.metadata = {
            "media_data_extracted": data["extracted"],
            "thumbnails_generated": data["thumbs"],
        }
        return outcome

    def _exif_step(self, ctx: JobContext, data, step) -> StepOutcome:
        import json as _json

        from .avmetadata import probe_media, probeable_extensions

        av_exts = probeable_extensions()
        db = ctx.db
        errors: List[str] = []
        # Extraction runs outside any tx (file IO per row); the batch
        # lands as ONE insert_many transaction — the tx-shape pass
        # flagged the old per-row db.insert as commit-per-item.
        # OR IGNORE keeps the old unique-race semantics (another path
        # of the same object winning the object_id slot is benign).
        mds: List[dict] = []
        for r in step["rows"]:
            ext = (r["extension"] or "").lower()
            is_av = ext in av_exts
            if ext not in MEDIA_DATA_EXTENSIONS and not is_av:
                continue
            full = self._full_path(data, r)
            existing = db.run("media.data_exists", (r["object_id"],))
            if existing is not None:
                continue
            try:
                if is_av:
                    info = probe_media(full)
                    if info is None:
                        continue
                    md = {"object_id": r["object_id"],
                          "stream_data": _json.dumps(info.to_dict())}
                else:
                    md = extract_media_data(full)
                    if md is None:
                        continue
                    md["object_id"] = r["object_id"]
                mds.append(md)
            except Exception as e:
                errors.append(f"media_data {full}: {e}")
        if mds:
            try:
                data["extracted"] += db.insert_many(
                    "media_data", mds, ignore_conflicts=True)
            except Exception as e:
                # OR IGNORE does not cover FK violations (an object
                # deleted between scan and insert): fall back to
                # per-row inserts so one dead reference costs one
                # error string, not the whole batch
                del e
                with db.write_tx() as conn:
                    for md in mds:
                        try:
                            db.insert("media_data", md, conn=conn)
                            data["extracted"] += 1
                        except Exception as row_e:  # noqa: BLE001
                            errors.append(
                                f"media_data object "
                                f"{md.get('object_id')}: {row_e}")
        return StepOutcome(errors=errors)

    async def _thumbs_step(self, ctx: JobContext, data, step) -> None:
        """Dispatch the batch to the thumbnailer actor (job.rs dispatches
        to the actor, actor.rs:487); inline fallback when the job runs
        without a node (unit harnesses)."""
        data_dir = ctx.services.get("data_dir")
        if not data_dir:
            return
        entries = []
        for r in step["rows"]:
            ext = (r["extension"] or "").lower()
            if r["cas_id"] and ext in thumbnailable_extensions():
                entries.append((r["cas_id"], self._full_path(data, r)))
        if not entries:
            return
        node = ctx.services.get("node")
        actor = getattr(node, "thumbnailer", None) if node else None
        if actor is not None and actor.is_running():
            batch = await actor.new_batch(
                entries, library_id=getattr(ctx.library, "id", None))
            await batch.done.wait()
            data["thumbs"] += batch.generated
        else:
            await asyncio.to_thread(ensure_thumbnail_dir, data_dir)
            for cas_id, full in entries:
                if await asyncio.to_thread(
                        generate_thumbnail, full, data_dir, cas_id):
                    data["thumbs"] += 1

    def _full_path(self, data, r) -> str:
        iso = IsolatedPath.from_db_row(
            self.location_id, False, r["materialized_path"],
            r["name"] or "", r["extension"] or "")
        return iso.join_on(data["location_path"])

    async def finalize(self, ctx, data, metadata):
        return metadata
