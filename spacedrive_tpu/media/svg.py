"""Pure-Python SVG rasterizer — the sd-images SVG handler, self-hosted.

The reference renders SVG thumbnails through resvg
(/root/reference/crates/images/src/svg.rs); this runtime has no native
SVG library, so this module rasterizes a practical subset directly onto
a PIL canvas — enough for the thumbnail pipeline's real-world inputs
(icons, logos, diagrams):

- structure: <svg> width/height/viewBox, nested <g>, <defs> ignored,
  `svgz` (gzip) streams;
- shapes: rect (incl. rx ellipse-corner approximation by rounded
  supersampling), circle, ellipse, line, polyline, polygon, path with
  M/m L/l H/h V/v C/c S/s Q/q T/t A/a Z/z (curves and arcs flattened to
  polylines);
- paint: fill/stroke presentation attributes + inline `style=`,
  any CSS color PIL's ImageColor parses (named/hex/rgb()/hsl()),
  fill-opacity/stroke-opacity/opacity, stroke-width, `none`;
  url(#gradient) references degrade to the gradient's first stop color;
- transforms: translate/scale/rotate/matrix, composed down the tree and
  applied to flattened geometry (rotation of circles works because all
  geometry is polygonized before transforming).

Rendering is 4× supersampled then box-downsampled, which stands in for
anti-aliasing. Out of scope (rendered as their fallback or skipped):
text, filters, masks, clip paths, real gradients, CSS stylesheets.
"""

from __future__ import annotations

import gzip
import math
import re
import xml.etree.ElementTree as ET
from typing import List, Optional, Tuple

SS = 4  # supersampling factor
# Decompressed .svgz ceiling — same budget as images.MAXIMUM_FILE_SIZE.
_MAX_DECOMPRESSED = 192 * (1 << 20)

_FLOAT = r"[-+]?(?:\d+\.?\d*|\.\d+)(?:[eE][-+]?\d+)?"
_NUM_RE = re.compile(_FLOAT)
_PATH_RE = re.compile(rf"([MmLlHhVvCcSsQqTtAaZz])|({_FLOAT})")


def _strip_ns(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


def _floats(s: str) -> List[float]:
    return [float(m) for m in _NUM_RE.findall(s or "")]


def _parse_length(s, default: float = 0.0) -> float:
    if s is None:
        return default
    m = _NUM_RE.search(str(s))
    return float(m.group(0)) if m else default


Matrix = Tuple[float, float, float, float, float, float]  # a b c d e f
_IDENTITY: Matrix = (1, 0, 0, 1, 0, 0)


def _mat_mul(m1: Matrix, m2: Matrix) -> Matrix:
    a1, b1, c1, d1, e1, f1 = m1
    a2, b2, c2, d2, e2, f2 = m2
    return (a1 * a2 + c1 * b2, b1 * a2 + d1 * b2,
            a1 * c2 + c1 * d2, b1 * c2 + d1 * d2,
            a1 * e2 + c1 * f2 + e1, b1 * e2 + d1 * f2 + f1)


def _mat_apply(m: Matrix, x: float, y: float) -> Tuple[float, float]:
    a, b, c, d, e, f = m
    return a * x + c * y + e, b * x + d * y + f


def _parse_transform(s: str) -> Matrix:
    m = _IDENTITY
    for name, args in re.findall(r"(\w+)\s*\(([^)]*)\)", s or ""):
        v = _floats(args)
        if name == "translate":
            t = (1, 0, 0, 1, v[0], v[1] if len(v) > 1 else 0)
        elif name == "scale":
            t = (v[0], 0, 0, v[1] if len(v) > 1 else v[0], 0, 0)
        elif name == "rotate":
            th = math.radians(v[0])
            cos, sin = math.cos(th), math.sin(th)
            t = (cos, sin, -sin, cos, 0, 0)
            if len(v) == 3:
                cx, cy = v[1], v[2]
                t = _mat_mul(_mat_mul((1, 0, 0, 1, cx, cy), t),
                             (1, 0, 0, 1, -cx, -cy))
        elif name == "matrix" and len(v) == 6:
            t = tuple(v)  # type: ignore[assignment]
        elif name == "skewX":
            t = (1, 0, math.tan(math.radians(v[0])), 1, 0, 0)
        elif name == "skewY":
            t = (1, math.tan(math.radians(v[0])), 0, 1, 0, 0)
        else:
            continue
        m = _mat_mul(m, t)
    return m


class _Style:
    __slots__ = ("fill", "stroke", "stroke_width", "opacity",
                 "fill_opacity", "stroke_opacity")

    def __init__(self):
        self.fill: Optional[str] = "black"   # SVG initial value
        self.stroke: Optional[str] = None
        self.stroke_width = 1.0
        self.opacity = 1.0
        self.fill_opacity = 1.0
        self.stroke_opacity = 1.0

    def child(self, el, gradients) -> "_Style":
        s = _Style()
        s.fill, s.stroke = self.fill, self.stroke
        s.stroke_width = self.stroke_width
        s.opacity, s.fill_opacity = self.opacity, self.fill_opacity
        s.stroke_opacity = self.stroke_opacity
        props = dict(el.attrib)
        for decl in (el.get("style") or "").split(";"):
            if ":" in decl:
                k, v = decl.split(":", 1)
                props[k.strip()] = v.strip()
        if "fill" in props:
            s.fill = _resolve_paint(props["fill"], gradients)
        if "stroke" in props:
            s.stroke = _resolve_paint(props["stroke"], gradients)
        if "stroke-width" in props:
            s.stroke_width = _parse_length(props["stroke-width"], 1.0)
        if "opacity" in props:
            s.opacity *= _parse_length(props["opacity"], 1.0)
        if "fill-opacity" in props:
            s.fill_opacity = _parse_length(props["fill-opacity"], 1.0)
        if "stroke-opacity" in props:
            s.stroke_opacity = _parse_length(props["stroke-opacity"], 1.0)
        return s


def _resolve_paint(value: str, gradients) -> Optional[str]:
    value = (value or "").strip()
    if value in ("none", ""):
        return None
    m = re.match(r"url\(#([^)]+)\)", value)
    if m:
        # Gradients degrade to their first stop color.
        return gradients.get(m.group(1), "gray")
    if value == "currentColor":
        return "black"
    return value


def _color(value: Optional[str], opacity: float):
    if value is None or opacity <= 0:
        return None
    from PIL import ImageColor

    try:
        rgb = ImageColor.getrgb(value)
    except ValueError:
        return None
    a = int(round(255 * max(0.0, min(1.0, opacity))))
    return (rgb[0], rgb[1], rgb[2],
            a if len(rgb) < 4 else int(rgb[3] * opacity))


def _flatten_cubic(p0, p1, p2, p3, steps: int = 16):
    pts = []
    for k in range(1, steps + 1):
        t = k / steps
        u = 1 - t
        x = (u**3 * p0[0] + 3 * u * u * t * p1[0]
             + 3 * u * t * t * p2[0] + t**3 * p3[0])
        y = (u**3 * p0[1] + 3 * u * u * t * p1[1]
             + 3 * u * t * t * p2[1] + t**3 * p3[1])
        pts.append((x, y))
    return pts


def _flatten_quad(p0, p1, p2, steps: int = 12):
    pts = []
    for k in range(1, steps + 1):
        t = k / steps
        u = 1 - t
        x = u * u * p0[0] + 2 * u * t * p1[0] + t * t * p2[0]
        y = u * u * p0[1] + 2 * u * t * p1[1] + t * t * p2[1]
        pts.append((x, y))
    return pts


def _flatten_arc(p0, rx, ry, rot, large, sweep, p1, steps: int = 24):
    """Endpoint-parameterized elliptical arc → polyline (F.6.5)."""
    if rx == 0 or ry == 0 or p0 == p1:
        return [p1]
    rx, ry = abs(rx), abs(ry)
    phi = math.radians(rot)
    cp, sp = math.cos(phi), math.sin(phi)
    dx, dy = (p0[0] - p1[0]) / 2, (p0[1] - p1[1]) / 2
    x1 = cp * dx + sp * dy
    y1 = -sp * dx + cp * dy
    lam = (x1 / rx) ** 2 + (y1 / ry) ** 2
    if lam > 1:
        s = math.sqrt(lam)
        rx, ry = rx * s, ry * s
    num = rx**2 * ry**2 - rx**2 * y1**2 - ry**2 * x1**2
    den = rx**2 * y1**2 + ry**2 * x1**2
    co = math.sqrt(max(0.0, num / den)) if den else 0.0
    if large == sweep:
        co = -co
    cxp = co * rx * y1 / ry
    cyp = -co * ry * x1 / rx
    cx = cp * cxp - sp * cyp + (p0[0] + p1[0]) / 2
    cy = sp * cxp + cp * cyp + (p0[1] + p1[1]) / 2

    def angle(ux, uy, vx, vy):
        dot = ux * vx + uy * vy
        ln = math.hypot(ux, uy) * math.hypot(vx, vy)
        a = math.acos(max(-1, min(1, dot / ln))) if ln else 0.0
        return -a if ux * vy - uy * vx < 0 else a

    th1 = angle(1, 0, (x1 - cxp) / rx, (y1 - cyp) / ry)
    dth = angle((x1 - cxp) / rx, (y1 - cyp) / ry,
                (-x1 - cxp) / rx, (-y1 - cyp) / ry)
    if not sweep and dth > 0:
        dth -= 2 * math.pi
    elif sweep and dth < 0:
        dth += 2 * math.pi
    pts = []
    for k in range(1, steps + 1):
        th = th1 + dth * k / steps
        x = cx + rx * math.cos(th) * cp - ry * math.sin(th) * sp
        y = cy + rx * math.cos(th) * sp + ry * math.sin(th) * cp
        pts.append((x, y))
    return pts


def _parse_path(d: str) -> List[List[Tuple[float, float]]]:
    """Path data → list of subpath polylines (closed subpaths repeat
    their first point at the end)."""
    tokens = [(m.group(1), m.group(2)) for m in _PATH_RE.finditer(d or "")]
    i = 0
    nums: List[float] = []
    subpaths: List[List[Tuple[float, float]]] = []
    cur: List[Tuple[float, float]] = []
    pos = (0.0, 0.0)
    start = (0.0, 0.0)
    last_ctrl: Optional[Tuple[float, float]] = None
    last_cmd = ""

    def flush():
        nonlocal cur
        if len(cur) > 1:
            subpaths.append(cur)
        cur = []

    def take(n) -> List[float]:
        nonlocal i
        out = []
        while len(out) < n and i < len(tokens) and tokens[i][1] is not None:
            out.append(float(tokens[i][1]))
            i += 1
        return out if len(out) == n else []

    while i < len(tokens):
        cmd_tok, num_tok = tokens[i]
        if cmd_tok:
            cmd = cmd_tok
            i += 1
        elif last_cmd:
            # Implicit command repetition; M/m repeats as L/l.
            cmd = {"M": "L", "m": "l"}.get(last_cmd, last_cmd)
        else:
            i += 1
            continue
        rel = cmd.islower()
        C = cmd.upper()
        if C == "Z":
            if cur:
                cur.append(start)
            flush()
            pos = start
            last_cmd, last_ctrl = cmd, None
            continue
        if C == "M":
            v = take(2)
            if not v:
                break
            flush()
            pos = (pos[0] + v[0], pos[1] + v[1]) if rel else (v[0], v[1])
            start = pos
            cur = [pos]
            last_ctrl = None
        elif C == "L":
            v = take(2)
            if not v:
                break
            pos = (pos[0] + v[0], pos[1] + v[1]) if rel else (v[0], v[1])
            cur.append(pos)
            last_ctrl = None
        elif C == "H":
            v = take(1)
            if not v:
                break
            pos = (pos[0] + v[0] if rel else v[0], pos[1])
            cur.append(pos)
            last_ctrl = None
        elif C == "V":
            v = take(1)
            if not v:
                break
            pos = (pos[0], pos[1] + v[0] if rel else v[0])
            cur.append(pos)
            last_ctrl = None
        elif C in ("C", "S"):
            n = 6 if C == "C" else 4
            v = take(n)
            if not v:
                break
            if rel:
                v = [v[k] + pos[k % 2] for k in range(n)]
            if C == "C":
                c1, c2, end = (v[0], v[1]), (v[2], v[3]), (v[4], v[5])
            else:
                c1 = ((2 * pos[0] - last_ctrl[0], 2 * pos[1] - last_ctrl[1])
                      if last_cmd.upper() in ("C", "S") and last_ctrl
                      else pos)
                c2, end = (v[0], v[1]), (v[2], v[3])
            cur.extend(_flatten_cubic(pos, c1, c2, end))
            last_ctrl = c2
            pos = end
        elif C in ("Q", "T"):
            n = 4 if C == "Q" else 2
            v = take(n)
            if not v:
                break
            if rel:
                v = [v[k] + pos[k % 2] for k in range(n)]
            if C == "Q":
                c1, end = (v[0], v[1]), (v[2], v[3])
            else:
                c1 = ((2 * pos[0] - last_ctrl[0], 2 * pos[1] - last_ctrl[1])
                      if last_cmd.upper() in ("Q", "T") and last_ctrl
                      else pos)
                end = (v[0], v[1])
            cur.extend(_flatten_quad(pos, c1, end))
            last_ctrl = c1
            pos = end
        elif C == "A":
            v = take(7)
            if not v:
                break
            end = ((pos[0] + v[5], pos[1] + v[6]) if rel
                   else (v[5], v[6]))
            cur.extend(_flatten_arc(pos, v[0], v[1], v[2],
                                    bool(v[3]), bool(v[4]), end))
            pos = end
            last_ctrl = None
        last_cmd = cmd
    flush()
    return subpaths


def _collect_gradients(root) -> dict:
    """gradient id → first stop color (the degrade-to-solid fallback)."""
    out = {}
    for el in root.iter():
        if _strip_ns(el.tag) in ("linearGradient", "radialGradient"):
            gid = el.get("id")
            for stop in el:
                if _strip_ns(stop.tag) == "stop":
                    color = stop.get("stop-color")
                    if not color:
                        m = re.search(r"stop-color\s*:\s*([^;]+)",
                                      stop.get("style") or "")
                        color = m.group(1).strip() if m else None
                    if gid and color:
                        out[gid] = color
                    break
    return out


def _ellipse_points(cx, cy, rx, ry, steps: int = 48):
    return [(cx + rx * math.cos(2 * math.pi * k / steps),
             cy + ry * math.sin(2 * math.pi * k / steps))
            for k in range(steps)]


def render_svg(path: str, target_px: float = 262_144.0):
    """Rasterize an SVG file to an RGBA PIL image of ~target_px area.

    svg.rs renders to the same target pixel budget (consts.rs:31).
    """
    from PIL import Image, ImageDraw

    with open(path, "rb") as f:
        head = f.read(2)
        f.seek(0)
        if head == b"\x1f\x8b":
            # Chunked decompress with a hard output ceiling: a tiny
            # crafted .svgz must not expand past the same 192 MiB budget
            # that bounds on-disk inputs (images._check_size only guards
            # the compressed size).
            chunks, total = [], 0
            with gzip.open(f) as gz:
                while True:
                    chunk = gz.read(1 << 20)
                    if not chunk:
                        break
                    total += len(chunk)
                    if total > _MAX_DECOMPRESSED:
                        raise ValueError(
                            f"{path}: decompressed SVG exceeds "
                            f"{_MAX_DECOMPRESSED >> 20} MiB")
                    chunks.append(chunk)
            data = b"".join(chunks)
        else:
            data = f.read()
    # Reject entity declarations before parsing: xml.etree expands
    # internal entities, so a billion-laughs/quadratic-blowup document
    # reached by the automatic thumbnail job could exhaust node memory.
    # A bare external DOCTYPE (the legacy W3C header every old
    # Illustrator/Inkscape file carries) is harmless — expat never
    # fetches external DTDs — so only an internal subset (the "[...]"
    # block that could hold ENTITY declarations) is refused.
    if b"<!ENTITY" in data:
        raise ValueError(f"{path}: SVG with entity declarations "
                         "is not supported")
    doc = data.find(b"<!DOCTYPE")
    if doc != -1:
        gt = data.find(b">", doc)
        if gt == -1 or b"[" in data[doc:gt]:
            raise ValueError(f"{path}: SVG DOCTYPE with internal "
                             "subset is not supported")
    root = ET.fromstring(data)
    if _strip_ns(root.tag) != "svg":
        raise ValueError(f"{path}: not an SVG document")

    vb = _floats(root.get("viewBox") or "")
    if len(vb) == 4:
        min_x, min_y, vw, vh = vb
    else:
        min_x = min_y = 0.0
        vw = _parse_length(root.get("width"), 0) or 300.0
        vh = _parse_length(root.get("height"), 0) or 150.0
    if vw <= 0 or vh <= 0:
        raise ValueError(f"{path}: empty SVG viewport")

    scale = math.sqrt(target_px / (vw * vh))
    out_w = max(1, int(round(vw * scale)))
    out_h = max(1, int(round(vh * scale)))
    s = scale * SS
    # viewport transform: user coords → supersampled pixel coords
    view = (s, 0, 0, s, -min_x * s, -min_y * s)

    img = Image.new("RGBA", (out_w * SS, out_h * SS), (0, 0, 0, 0))
    draw = ImageDraw.Draw(img, "RGBA")
    gradients = _collect_gradients(root)

    def emit(points, style: _Style, ctm: Matrix, closed: bool):
        pts = [_mat_apply(ctm, x, y) for x, y in points]
        if len(pts) < 2:
            return
        fill = _color(style.fill, style.fill_opacity * style.opacity) \
            if closed else None
        stroke = _color(style.stroke,
                        style.stroke_opacity * style.opacity)
        # stroke width scales with the CTM's mean scale factor
        a, b, c, d, _, _ = ctm
        sw = style.stroke_width * math.sqrt(abs(a * d - b * c) or 1.0)
        if fill and len(pts) >= 3:
            draw.polygon(pts, fill=fill)
        if stroke:
            draw.line(pts + ([pts[0]] if closed else []),
                      fill=stroke, width=max(1, int(round(sw))),
                      joint="curve")

    def walk(el, style: _Style, ctm: Matrix):
        tag = _strip_ns(el.tag)
        if tag in ("defs", "symbol", "clipPath", "mask", "marker",
                   "linearGradient", "radialGradient", "style", "metadata",
                   "title", "desc"):
            return
        st = style.child(el, gradients)
        m = ctm
        if el.get("transform"):
            m = _mat_mul(ctm, _parse_transform(el.get("transform")))
        if tag in ("svg", "g", "a", "switch"):
            for ch in el:
                walk(ch, st, m)
            return
        if tag == "rect":
            x = _parse_length(el.get("x"))
            y = _parse_length(el.get("y"))
            w = _parse_length(el.get("width"))
            h = _parse_length(el.get("height"))
            if w > 0 and h > 0:
                emit([(x, y), (x + w, y), (x + w, y + h), (x, y + h)],
                     st, m, closed=True)
        elif tag == "circle":
            r = _parse_length(el.get("r"))
            if r > 0:
                emit(_ellipse_points(_parse_length(el.get("cx")),
                                     _parse_length(el.get("cy")), r, r),
                     st, m, closed=True)
        elif tag == "ellipse":
            rx = _parse_length(el.get("rx"))
            ry = _parse_length(el.get("ry"))
            if rx > 0 and ry > 0:
                emit(_ellipse_points(_parse_length(el.get("cx")),
                                     _parse_length(el.get("cy")), rx, ry),
                     st, m, closed=True)
        elif tag == "line":
            p = [(_parse_length(el.get("x1")), _parse_length(el.get("y1"))),
                 (_parse_length(el.get("x2")), _parse_length(el.get("y2")))]
            st2 = st
            emit(p, st2, m, closed=False)
        elif tag in ("polyline", "polygon"):
            v = _floats(el.get("points") or "")
            pts = list(zip(v[0::2], v[1::2]))
            if pts:
                emit(pts, st, m, closed=(tag == "polygon"))
        elif tag == "path":
            for sub in _parse_path(el.get("d") or ""):
                closed = len(sub) > 2 and sub[0] == sub[-1]
                emit(sub, st, m, closed=closed or st.fill is not None)

    walk(root, _Style(), view)
    return img.resize((out_w, out_h), Image.LANCZOS)
