"""Minimal MJPEG-AVI container support, pure Python.

The reference's video thumbnailer decodes any codec through ffmpeg FFI
(/root/reference/crates/ffmpeg/src/{thumbnailer.rs,movie_decoder.rs});
this runtime ships no ffmpeg, so the video path would otherwise never
execute. Motion-JPEG needs no codec — every frame is a complete JPEG —
so parsing the RIFF/AVI container is enough to hand PIL a decodable
frame. That makes MJPEG `.avi` the self-hosted video format: the
thumbnailer really runs for it (seek-10% frame semantics preserved),
and everything else still degrades through the ffmpeg gate.

The writer emits a minimal-but-valid AVI (hdrl with avih + one video
strl, movi with 00dc chunks, idx1 index) so tests and the corpus
generator can synthesize real files; ffprobe-compatible in structure.
"""

from __future__ import annotations

import io
import os
import struct
from typing import List, Optional, Tuple

JPEG_SOI = b"\xff\xd8"


def _walk_chunks(f, start: int, end: int):
    """Yield (fourcc, payload_start, payload_size) reading only the
    8-byte headers — payloads are seeked over, never loaded, so a
    multi-GB camera AVI indexes in O(frame count) memory."""
    pos = start
    while pos + 8 <= end:
        f.seek(pos)
        header = f.read(8)
        if len(header) < 8:
            return
        fourcc = header[:4]
        (size,) = struct.unpack("<I", header[4:8])
        yield fourcc, pos + 8, size
        pos += 8 + size + (size & 1)  # chunks are word-aligned


def index_frames(path: str) -> List[Tuple[int, int]]:
    """(offset, size) of every video frame chunk in stream order.

    Walks RIFF → LIST 'movi' → '..dc'/'..db' chunk headers.
    """
    frames: List[Tuple[int, int]] = []
    with open(path, "rb") as f:
        head = f.read(12)
        if len(head) < 12 or head[0:4] != b"RIFF" or head[8:12] != b"AVI ":
            raise ValueError(f"{path}: not a RIFF/AVI file")
        f.seek(0, os.SEEK_END)
        file_end = f.tell()
        for fourcc, p, size in list(_walk_chunks(f, 12, file_end)):
            if fourcc != b"LIST":
                continue
            f.seek(p)
            if f.read(4) != b"movi":
                continue
            for cc, fp, fsize in _walk_chunks(f, p + 4,
                                              min(p + size, file_end)):
                if cc[2:4] in (b"dc", b"db") and fsize > 0:
                    frames.append((fp, fsize))
    return frames


def frame_at_fraction(path: str, fraction: float = 0.10
                      ) -> Optional[bytes]:
    """The JPEG bytes of the frame nearest `fraction` through the stream
    (thumbnailer.rs seeks 10%), or None when the file holds no JPEG
    frames (non-MJPEG AVIs)."""
    frames = index_frames(path)
    if not frames:
        return None
    off, size = frames[min(int(len(frames) * fraction),
                           len(frames) - 1)]
    with open(path, "rb") as f:
        f.seek(off)
        payload = f.read(size)
    return payload if payload.startswith(JPEG_SOI) else None


def write_mjpeg_avi(path: str, frames: List, fps: int = 10,
                    quality: int = 85) -> str:
    """Write PIL images (or raw JPEG bytes) as an MJPEG AVI."""
    jpegs: List[bytes] = []
    width = height = 0
    for fr in frames:
        if isinstance(fr, bytes):
            jpegs.append(fr)
        else:
            if not width:
                width, height = fr.size
            bio = io.BytesIO()
            fr.convert("RGB").save(bio, "JPEG", quality=quality)
            jpegs.append(bio.getvalue())
    if not jpegs:
        raise ValueError("no frames")
    if not width:
        from PIL import Image

        with Image.open(io.BytesIO(jpegs[0])) as im:
            width, height = im.size

    def chunk(fourcc: bytes, payload: bytes) -> bytes:
        pad = b"\x00" if len(payload) & 1 else b""
        return fourcc + struct.pack("<I", len(payload)) + payload + pad

    def lst(four: bytes, payload: bytes) -> bytes:
        return chunk(b"LIST", four + payload)

    us_per_frame = 1_000_000 // fps
    max_bytes = max(len(j) for j in jpegs)
    avih = struct.pack(
        "<14I", us_per_frame, max_bytes * fps, 0, 0x10,  # HASINDEX
        len(jpegs), 0, 1, max_bytes, width, height, 0, 0, 0, 0)
    # AVISTREAMHEADER: flags, prio/lang, initialFrames, scale, rate,
    # start, length, bufSize, quality, sampleSize, then rcFrame (56 B).
    strh = (b"vids" + b"MJPG" + struct.pack(
        "<IHHIIIIIIII", 0, 0, 0, 0, 1, fps, 0, len(jpegs),
        max_bytes, 0xFFFFFFFF, 0) + struct.pack("<4H", 0, 0,
                                                width, height))
    strf = struct.pack("<IiiHH4sIiiII", 40, width, height, 1, 24,
                       b"MJPG", width * height * 3, 0, 0, 0, 0)
    hdrl = lst(b"hdrl", chunk(b"avih", avih)
               + lst(b"strl", chunk(b"strh", strh) + chunk(b"strf", strf)))

    movi_body = b"movi"
    index_entries = []
    for j in jpegs:
        # idx1 ckOffset: the chunk header's offset from the 'movi' fourcc
        index_entries.append((len(movi_body), len(j)))
        movi_body += chunk(b"00dc", j)
    movi = chunk(b"LIST", movi_body)
    idx1 = b"".join(
        b"00dc" + struct.pack("<III", 0x10, off, size)
        for off, size in index_entries)
    body = b"AVI " + hdrl + movi + chunk(b"idx1", idx1)
    # Synthesized sample media for tests/benches at a caller-chosen
    # path — corpus content, not durable node state.
    # sdlint: ok[io-durability]
    with open(path, "wb") as f:
        f.write(b"RIFF" + struct.pack("<I", len(body)) + body)
    return os.path.abspath(path)
