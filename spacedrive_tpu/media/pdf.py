"""PDF first-page image extraction without a PDF renderer.

The reference renders PDFs with pdfium behind a feature gate
(/root/reference/crates/images/src/pdf.rs); no pdfium exists in this
runtime, but most PDF pages that *contain* an image carry it as an
image XObject whose stream is directly recoverable:

- /Filter /DCTDecode  → the stream IS a JPEG;
- /Filter /FlateDecode → zlib-compressed raw samples, reconstructable
  from /Width /Height /ColorSpace /BitsPerComponent (+ optional PNG
  predictors from /DecodeParms).

Scope: unencrypted PDFs with image XObjects in plain object streams
(not /ObjStm-packed); the first (largest) image in document order
stands in for "first page". Outside that envelope the caller gets
UnsupportedFormat and degrades per-file like every other handler.
"""

from __future__ import annotations

import re
import zlib
from typing import List, Optional, Tuple

_OBJ_RE = re.compile(
    rb"(\d+)\s+(\d+)\s+obj(.*?)(?:endobj|\Z)", re.DOTALL)
_STREAM_RE = re.compile(rb"stream\r?\n(.*?)(?:\r?\n)?endstream", re.DOTALL)
_INT_RE = {
    "Width": re.compile(rb"/Width\s+(\d+)"),
    "Height": re.compile(rb"/Height\s+(\d+)"),
    "BitsPerComponent": re.compile(rb"/BitsPerComponent\s+(\d+)"),
    "Predictor": re.compile(rb"/Predictor\s+(\d+)"),
    "Colors": re.compile(rb"/Colors\s+(\d+)"),
    "Columns": re.compile(rb"/Columns\s+(\d+)"),
}


class PdfImageError(ValueError):
    pass


def _int(dict_src: bytes, key: str, default: int = 0) -> int:
    m = _INT_RE[key].search(dict_src)
    return int(m.group(1)) if m else default


def _png_unpredict(raw: bytes, columns: int, colors: int) -> bytes:
    """Reverse PNG row filters (predictor 10-15): each row is one filter
    byte + columns*colors bytes."""
    stride = columns * colors
    out = bytearray()
    prev = bytes(stride)
    pos = 0
    while pos + 1 + stride <= len(raw) + stride:  # allow short last row
        ft = raw[pos]
        row = bytearray(raw[pos + 1:pos + 1 + stride])
        pos += 1 + stride
        if ft == 1:    # Sub
            for i in range(colors, len(row)):
                row[i] = (row[i] + row[i - colors]) & 0xFF
        elif ft == 2:  # Up
            for i in range(len(row)):
                row[i] = (row[i] + prev[i]) & 0xFF
        elif ft == 3:  # Average
            for i in range(len(row)):
                left = row[i - colors] if i >= colors else 0
                row[i] = (row[i] + ((left + prev[i]) >> 1)) & 0xFF
        elif ft == 4:  # Paeth
            for i in range(len(row)):
                a = row[i - colors] if i >= colors else 0
                b = prev[i]
                c = prev[i - colors] if i >= colors else 0
                p = a + b - c
                pa, pb, pc = abs(p - a), abs(p - b), abs(p - c)
                pred = a if pa <= pb and pa <= pc else (
                    b if pb <= pc else c)
                row[i] = (row[i] + pred) & 0xFF
        elif ft != 0:
            raise PdfImageError(f"unknown PNG filter {ft}")
        out += row
        prev = bytes(row)
        if pos >= len(raw):
            break
    return bytes(out)


def _candidates(data: bytes) -> List[Tuple[int, bytes, bytes]]:
    """(pixel_area, dict_src, stream_bytes) for every image XObject."""
    out = []
    for m in _OBJ_RE.finditer(data):
        body = m.group(3)
        if b"/Subtype" not in body or b"/Image" not in body:
            continue
        sm = _STREAM_RE.search(body)
        if not sm:
            continue
        dict_src = body[:sm.start()]
        w, h = _int(dict_src, "Width"), _int(dict_src, "Height")
        if w <= 0 or h <= 0:
            continue
        out.append((w * h, dict_src, sm.group(1)))
    out.sort(key=lambda t: -t[0])
    return out


def pdf_first_image(path: str):
    """Decode the largest image XObject in the PDF to a PIL image."""
    import io

    from PIL import Image

    with open(path, "rb") as f:
        data = f.read()
    if not data.startswith(b"%PDF"):
        raise PdfImageError(f"{path}: not a PDF")
    errors = []
    for _area, dict_src, stream in _candidates(data):
        try:
            if b"/DCTDecode" in dict_src:
                im = Image.open(io.BytesIO(stream))
                im.load()
                return im
            if b"/FlateDecode" in dict_src:
                raw = zlib.decompress(stream)
                w = _int(dict_src, "Width")
                h = _int(dict_src, "Height")
                bpc = _int(dict_src, "BitsPerComponent", 8)
                if bpc != 8:
                    raise PdfImageError(f"unsupported {bpc}-bit samples")
                if b"/DeviceRGB" in dict_src:
                    mode, colors = "RGB", 3
                elif b"/DeviceGray" in dict_src:
                    mode, colors = "L", 1
                else:
                    raise PdfImageError("unsupported color space")
                pred = _int(dict_src, "Predictor", 1)
                if pred >= 10:
                    raw = _png_unpredict(
                        raw, _int(dict_src, "Columns", w),
                        _int(dict_src, "Colors", colors))
                elif pred != 1:
                    raise PdfImageError(f"unsupported predictor {pred}")
                need = w * h * colors
                if len(raw) < need:
                    raise PdfImageError("short image stream")
                return Image.frombytes(mode, (w, h), raw[:need])
            raise PdfImageError("no supported filter")
        except Exception as e:  # try the next candidate
            errors.append(str(e))
    raise PdfImageError(
        f"{path}: no extractable image stream"
        + (f" ({'; '.join(errors[:3])})" if errors else ""))
