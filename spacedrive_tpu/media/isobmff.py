"""ISO-BMFF (HEIF/HEIC/AVIF) box parser: embedded JPEG + EXIF extraction.

The reference decodes HEIF through libheif behind a feature gate
(/root/reference/crates/images/src/heif.rs); no HEVC decoder exists in
this runtime, but HEIF containers carry extractable payloads that cover
the thumbnail/metadata use cases without decoding HEVC at all:

- items whose coding is already JPEG (`infe` item_type "jpeg", or
  "mime" with an image/jpeg content type) — extract the bytes, decode
  with the generic raster path;
- the EXIF metadata item ("Exif"), whose TIFF IFD1 conventionally
  embeds a ready-made JPEG thumbnail (JPEGInterchangeFormat tags).

Box-structure references (publicly documented): ISO/IEC 14496-12
(box/fullbox framing, `meta`/`iloc`/`iinf`/`iref`/`pitm`) and ISO/IEC
23008-12 (HEIF item types). Only the subset needed for extraction is
implemented; everything else is skipped structurally.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

# Boxes whose payload is a sequence of child boxes.
_CONTAINERS = {b"moov", b"trak", b"mdia", b"minf", b"stbl", b"dinf",
               b"iprp", b"ipco"}


class BoxError(ValueError):
    pass


def iter_boxes(data: bytes, start: int = 0,
               end: Optional[int] = None) -> Iterator[Tuple[bytes, int, int]]:
    """Yield (type, payload_start, payload_end) for each box in a span."""
    pos = start
    end = len(data) if end is None else end
    while pos + 8 <= end:
        size, typ = struct.unpack_from(">I4s", data, pos)
        header = 8
        if size == 1:
            if pos + 16 > end:
                raise BoxError("truncated largesize box")
            size = struct.unpack_from(">Q", data, pos + 8)[0]
            header = 16
        elif size == 0:
            size = end - pos  # box extends to end of file
        if size < header or pos + size > end:
            raise BoxError(f"bad box size {size} at {pos}")
        yield typ, pos + header, pos + size
        pos += size


def find_box(data: bytes, path: List[bytes], start: int = 0,
             end: Optional[int] = None) -> Optional[Tuple[int, int]]:
    """Payload span of the first box matching a type path (e.g.
    [b"meta", b"iinf"]); `meta` is a FullBox (4-byte version/flags)."""
    span = (start, len(data) if end is None else end)
    for depth, want in enumerate(path):
        found = None
        for typ, ps, pe in iter_boxes(data, span[0], span[1]):
            if typ == want:
                if typ in (b"meta",):  # FullBox: skip version/flags
                    ps += 4
                found = (ps, pe)
                break
        if found is None:
            return None
        span = found
    return span


@dataclass
class HeifItem:
    item_id: int
    item_type: bytes
    content_type: str = ""
    extents: List[Tuple[int, int]] = field(default_factory=list)  # (off, len)
    construction_method: int = 0
    base_offset: int = 0


@dataclass
class HeifMeta:
    primary: Optional[int] = None
    items: Dict[int, HeifItem] = field(default_factory=dict)
    # references: (ref_type, from_item) -> [to_items]
    refs: Dict[Tuple[bytes, int], List[int]] = field(default_factory=dict)
    idat: bytes = b""


def _parse_iinf(data: bytes, ps: int, pe: int, meta: HeifMeta) -> None:
    version = data[ps]
    count_size = 2 if version == 0 else 4
    pos = ps + 4
    pos += count_size  # entry_count
    for typ, ips, ipe in iter_boxes(data, pos, pe):
        if typ != b"infe":
            continue
        v = data[ips]
        p = ips + 4
        if v >= 2:
            if v == 2:
                item_id = struct.unpack_from(">H", data, p)[0]
                p += 2
            else:
                item_id = struct.unpack_from(">I", data, p)[0]
                p += 4
            p += 2  # protection index
            item_type = data[p:p + 4]
            p += 4
            item = meta.items.setdefault(item_id, HeifItem(item_id, b""))
            item.item_type = item_type
            if item_type == b"mime":
                # null-terminated item_name, then content_type
                name_end = data.index(b"\x00", p, ipe)
                ct_end = data.index(b"\x00", name_end + 1, ipe)
                item.content_type = data[name_end + 1:ct_end].decode(
                    "ascii", "replace")


def _parse_iloc(data: bytes, ps: int, pe: int, meta: HeifMeta) -> None:
    version = data[ps]
    p = ps + 4
    sizes = struct.unpack_from(">H", data, p)[0]
    p += 2
    offset_size = (sizes >> 12) & 0xF
    length_size = (sizes >> 8) & 0xF
    base_offset_size = (sizes >> 4) & 0xF
    index_size = sizes & 0xF if version in (1, 2) else 0
    if version < 2:
        item_count = struct.unpack_from(">H", data, p)[0]
        p += 2
    else:
        item_count = struct.unpack_from(">I", data, p)[0]
        p += 4

    def read_int(pos: int, size: int) -> Tuple[int, int]:
        if size == 0:
            return 0, pos
        raw = data[pos:pos + size]
        return int.from_bytes(raw, "big"), pos + size

    for _ in range(item_count):
        if version < 2:
            item_id = struct.unpack_from(">H", data, p)[0]
            p += 2
        else:
            item_id = struct.unpack_from(">I", data, p)[0]
            p += 4
        cm = 0
        if version in (1, 2):
            cm = struct.unpack_from(">H", data, p)[0] & 0xF
            p += 2
        p += 2  # data_reference_index
        base, p = read_int(p, base_offset_size)
        extent_count = struct.unpack_from(">H", data, p)[0]
        p += 2
        item = meta.items.setdefault(item_id, HeifItem(item_id, b""))
        item.construction_method = cm
        item.base_offset = base
        for _ in range(extent_count):
            _, p = read_int(p, index_size)
            off, p = read_int(p, offset_size)
            length, p = read_int(p, length_size)
            item.extents.append((off, length))


def _parse_iref(data: bytes, ps: int, pe: int, meta: HeifMeta) -> None:
    version = data[ps]
    id_fmt = ">H" if version == 0 else ">I"
    id_sz = 2 if version == 0 else 4
    for typ, rps, rpe in iter_boxes(data, ps + 4, pe):
        p = rps
        from_id = struct.unpack_from(id_fmt, data, p)[0]
        p += id_sz
        count = struct.unpack_from(">H", data, p)[0]
        p += 2
        to_ids = []
        for _ in range(count):
            to_ids.append(struct.unpack_from(id_fmt, data, p)[0])
            p += id_sz
        meta.refs[(typ, from_id)] = to_ids


def parse_heif(data: bytes) -> HeifMeta:
    meta_span = find_box(data, [b"meta"])
    if meta_span is None:
        raise BoxError("no meta box (not a HEIF container)")
    meta = HeifMeta()
    for typ, ps, pe in iter_boxes(data, meta_span[0], meta_span[1]):
        if typ == b"pitm":
            v = data[ps]
            meta.primary = (struct.unpack_from(">H", data, ps + 4)[0]
                            if v == 0 else
                            struct.unpack_from(">I", data, ps + 4)[0])
        elif typ == b"iinf":
            _parse_iinf(data, ps, pe, meta)
        elif typ == b"iloc":
            _parse_iloc(data, ps, pe, meta)
        elif typ == b"iref":
            _parse_iref(data, ps, pe, meta)
        elif typ == b"idat":
            meta.idat = data[ps:pe]
    return meta


def item_bytes(data: bytes, meta: HeifMeta, item: HeifItem) -> bytes:
    """Concatenate an item's extents (construction 0 = file offsets,
    1 = offsets into the meta idat box)."""
    src = meta.idat if item.construction_method == 1 else data
    out = bytearray()
    for off, length in item.extents:
        s = item.base_offset + off
        if length == 0:
            length = len(src) - s
        if s + length > len(src):
            raise BoxError(f"item {item.item_id} extent out of range")
        out += src[s:s + length]
    return bytes(out)


def heif_dimensions(data: bytes) -> Optional[Tuple[int, int]]:
    """Largest declared image size (`ispe` property in meta/iprp/ipco) —
    readable without any decode."""
    span = find_box(data, [b"meta", b"iprp", b"ipco"])
    if span is None:
        return None
    best = None
    for typ, ps, pe in iter_boxes(data, span[0], span[1]):
        if typ == b"ispe" and pe - ps >= 12:
            w, h = struct.unpack_from(">II", data, ps + 4)
            if best is None or w * h > best[0] * best[1]:
                best = (w, h)
    return best


# -- extraction helpers ----------------------------------------------------


def heif_exif(data: bytes, meta: Optional[HeifMeta] = None) -> Optional[bytes]:
    """The EXIF payload (TIFF stream) of a HEIF file, or None."""
    meta = meta or parse_heif(data)
    for item in meta.items.values():
        if item.item_type == b"Exif" and item.extents:
            raw = item_bytes(data, meta, item)
            if len(raw) < 8:
                return None
            # ExifDataBlock: u32 offset to the TIFF header within payload
            off = struct.unpack_from(">I", raw, 0)[0] + 4
            if raw[4:10] == b"Exif\x00\x00":
                off = 10
            if off > len(raw) - 8:
                return None
            return raw[off:]
    return None


def _tiff_thumbnail(tiff: bytes) -> Optional[bytes]:
    """JPEG thumbnail from TIFF IFD1 (JPEGInterchangeFormat/Length) —
    the classic EXIF-embedded thumbnail every camera writes."""
    if len(tiff) < 8:
        return None
    if tiff[:2] == b"II":
        u16, u32 = "<H", "<I"
    elif tiff[:2] == b"MM":
        u16, u32 = ">H", ">I"
    else:
        return None

    def read_ifd(off: int) -> Tuple[Dict[int, Tuple[int, int, int]], int]:
        """{tag: (type, count, value_or_offset)}, next_ifd_offset."""
        out: Dict[int, Tuple[int, int, int]] = {}
        if off + 2 > len(tiff):
            return out, 0
        n = struct.unpack_from(u16, tiff, off)[0]
        p = off + 2
        for _ in range(n):
            if p + 12 > len(tiff):
                return out, 0
            tag = struct.unpack_from(u16, tiff, p)[0]
            ftype = struct.unpack_from(u16, tiff, p + 2)[0]
            count = struct.unpack_from(u32, tiff, p + 4)[0]
            value = struct.unpack_from(u32, tiff, p + 8)[0]
            out[tag] = (ftype, count, value)
            p += 12
        nxt = (struct.unpack_from(u32, tiff, p)[0]
               if p + 4 <= len(tiff) else 0)
        return out, nxt

    ifd0_off = struct.unpack_from(u32, tiff, 4)[0]
    _, ifd1_off = read_ifd(ifd0_off)
    if not ifd1_off:
        return None
    ifd1, _ = read_ifd(ifd1_off)
    if 0x0201 not in ifd1 or 0x0202 not in ifd1:
        return None
    start = ifd1[0x0201][2]
    length = ifd1[0x0202][2]
    if start + length > len(tiff):
        return None
    jpeg = tiff[start:start + length]
    return jpeg if jpeg[:2] == b"\xff\xd8" else None


def heif_embedded_jpeg(data: bytes) -> Optional[bytes]:
    """Best extractable JPEG from a HEIF container, decoder-free.

    Preference order: a JPEG-coded thumbnail item referencing the
    primary (`thmb` iref), any JPEG-coded item, then the EXIF IFD1
    thumbnail. Returns raw JPEG bytes or None.
    """
    meta = parse_heif(data)

    def is_jpeg(it: HeifItem) -> bool:
        return (it.item_type == b"jpeg"
                or (it.item_type == b"mime"
                    and it.content_type.lower() == "image/jpeg"))

    jpeg_items = [it for it in meta.items.values()
                  if is_jpeg(it) and it.extents]
    # thumbnails first (smallest payload that still previews correctly)
    thumbs = [it for it in jpeg_items
              if meta.primary in meta.refs.get((b"thmb", it.item_id), [])]
    for it in thumbs + jpeg_items:
        raw = item_bytes(data, meta, it)
        if raw[:2] == b"\xff\xd8":
            return raw
    exif = heif_exif(data, meta)
    if exif is not None:
        return _tiff_thumbnail(exif)
    return None
