"""Audio/video stream metadata (sd-media-metadata's audio/video side).

The reference ships typed audio/video metadata structs that are mostly
stubs awaiting an ffmpeg binding (/root/reference/crates/media-metadata/
src/{audio.rs,video.rs}). Here the same typed rows fill from `ffprobe`
when it exists (media/video.py gates), and otherwise from the
self-hosted container parsers (media/audio.py: WAV/FLAC/MP3/OGG/Opus/
AVI; media/mp4meta.py: MP4/MOV/M4A/3GP; media/mkv.py: MKV/WebM) — so
the audio/video metadata plane actually runs in this image, beyond the
reference's stubs.
"""

from __future__ import annotations

import json
import subprocess
from dataclasses import asdict, dataclass
from typing import Optional

from .video import available as ffmpeg_available


@dataclass
class StreamMetadata:
    duration_seconds: Optional[float] = None
    bitrate: Optional[int] = None
    format_name: Optional[str] = None
    brand: Optional[str] = None          # ISO-BMFF major brand
    # video stream
    width: Optional[int] = None
    height: Optional[int] = None
    fps: Optional[float] = None
    video_codec: Optional[str] = None
    rotation: Optional[int] = None       # display rotation, degrees CW
    # audio stream
    audio_codec: Optional[str] = None
    sample_rate: Optional[int] = None
    channels: Optional[int] = None
    bits_per_sample: Optional[int] = None

    def to_dict(self) -> dict:
        return {k: v for k, v in asdict(self).items() if v is not None}


def probeable_extensions() -> set:
    """Audio/video extensions probe_media can actually read in THIS
    runtime: everything when ffprobe exists, all video plus the
    self-hosted audio formats when cv2's bundled libavcodec exists,
    else just the self-hosted parsers' formats — keeps the media job
    from re-probing thousands of deterministically-unreadable files on
    every run."""
    from .audio import AUDIO_EXTENSIONS, _PARSERS
    from .video import VIDEO_EXTENSIONS, cv2_available

    if ffmpeg_available():
        return set(AUDIO_EXTENSIONS) | set(VIDEO_EXTENSIONS)
    if cv2_available():
        return set(_PARSERS) | set(VIDEO_EXTENSIONS)
    return set(_PARSERS)


def _cv2_stream_metadata(path: str) -> Optional[StreamMetadata]:
    """Video-stream facts via cv2's bundled libavcodec (duration, fps,
    dimensions) for containers the self-hosted parsers can't read —
    the metadata twin of the cv2 thumbnail backend."""
    from .video import VIDEO_EXTENSIONS, cv2_probe

    import os

    ext = os.path.splitext(path)[1].lstrip(".").lower()
    if ext not in VIDEO_EXTENSIONS:
        return None
    info = cv2_probe(path)
    if not info:
        return None
    md = StreamMetadata()
    md.duration_seconds = info.get("duration_seconds")
    md.width = info.get("width")
    md.height = info.get("height")
    md.fps = info.get("fps")
    return md


def probe_media(path: str) -> Optional[StreamMetadata]:
    """ffprobe (when installed), else the self-hosted parsers with a
    cv2 fallback for video containers they can't read → StreamMetadata;
    None when nothing can read the container."""
    if not ffmpeg_available():
        from .audio import parse_stream_info

        info = parse_stream_info(path)
        if info is None:
            return _cv2_stream_metadata(path)
        md = StreamMetadata()
        for k, v in info.items():
            # Parser keys are the dataclass fields; a mismatch is a bug,
            # not something to silently drop.
            setattr(md, k, v)
        if md.width is None and md.duration_seconds is None:
            # Parser read the container but got no stream facts (e.g. a
            # codec it can't inspect) — decode-probe with cv2 and MERGE:
            # the parser's container facts (format_name, brand, codecs)
            # must survive alongside cv2's dimensions/duration/fps.
            cv = _cv2_stream_metadata(path)
            if cv is not None:
                for name in ("duration_seconds", "width", "height", "fps"):
                    if getattr(md, name) is None:
                        setattr(md, name, getattr(cv, name))
        return md
    try:
        out = subprocess.run(
            ["ffprobe", "-v", "quiet", "-print_format", "json",
             "-show_format", "-show_streams", path],
            capture_output=True, timeout=30, check=True)
        raw = json.loads(out.stdout)
    except Exception:
        return None
    md = StreamMetadata()
    fmt = raw.get("format", {})
    md.format_name = fmt.get("format_name")
    try:
        md.duration_seconds = float(fmt["duration"])
    except (KeyError, ValueError):
        pass
    try:
        md.bitrate = int(fmt["bit_rate"])
    except (KeyError, ValueError):
        pass
    for stream in raw.get("streams", []):
        if stream.get("codec_type") == "video" and md.width is None:
            md.width = stream.get("width")
            md.height = stream.get("height")
            md.video_codec = stream.get("codec_name")
            rate = stream.get("avg_frame_rate", "0/1")
            try:
                num, _, den = rate.partition("/")
                md.fps = float(num) / float(den or 1)
            except (ValueError, ZeroDivisionError):
                pass
            # display rotation: modern ffprobe puts it in side_data,
            # older ones in tags.rotate — keep parity with the
            # self-hosted mp4 parser's matrix-derived field
            try:
                rot = None
                for sd in stream.get("side_data_list", []):
                    if "rotation" in sd:
                        # side_data reports CCW (a portrait iPhone clip
                        # is -90); our field is degrees CW like the
                        # tkhd matrix and legacy tags.rotate
                        rot = -int(sd["rotation"])
                if rot is None and "rotate" in stream.get("tags", {}):
                    rot = int(stream["tags"]["rotate"])
                if rot is not None:
                    md.rotation = rot % 360 or None
            except (TypeError, ValueError):
                pass
        elif stream.get("codec_type") == "audio" and md.audio_codec is None:
            md.audio_codec = stream.get("codec_name")
            try:
                md.sample_rate = int(stream.get("sample_rate", 0)) or None
            except ValueError:
                pass
            md.channels = stream.get("channels")
    return md
