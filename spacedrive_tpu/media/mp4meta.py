"""MP4/MOV/M4A stream metadata from the container, no demuxer needed.

The reference's sd-media-metadata video structs are empty stubs awaiting
an ffmpeg binding (/root/reference/crates/media-metadata/src/video.rs);
here the `moov` box tree is read directly (ISO/IEC 14496-12, the same
box framing media/isobmff.py parses for HEIF): movie duration from
`mvhd`, per-track dimensions/rotation from `tkhd`, codec fourcc +
sample-entry details from `stsd`, audio rate/channels from the
AudioSampleEntry, fps estimated from `stts`/`mdhd`.

Only box headers are walked at file level (a video file is GBs but its
`moov` is typically well under 10 MB), so probing is O(moov), not
O(file). The common camera/phone brands — isom/mp42/qt/3gp — all use
this layout.
"""

from __future__ import annotations

import math
import os
import struct
from typing import Dict, Optional

from .isobmff import iter_boxes

_MOOV_CAP = 64 << 20  # a moov larger than this is not metadata


def _file_top_boxes(f, end: int):
    """Yield (type, payload_off, payload_end) of top-level boxes by
    seeking over payloads — never reads media data."""
    pos = 0
    while pos + 8 <= end:
        f.seek(pos)
        head = f.read(16)
        if len(head) < 8:
            return
        size, typ = struct.unpack_from(">I4s", head, 0)
        hdr = 8
        if size == 1:
            if len(head) < 16:
                return
            size = struct.unpack_from(">Q", head, 8)[0]
            hdr = 16
        elif size == 0:
            size = end - pos
        if size < hdr or pos + size > end:
            return
        yield typ, pos + hdr, pos + size
        pos += size


def _rotation_from_matrix(m: bytes) -> Optional[int]:
    """Track display rotation (degrees CW) from the 3x3 16.16/2.30
    fixed-point matrix — how phones record portrait video."""
    a, b, _u, c, d = struct.unpack_from(">5i", m, 0)[:5]
    a /= 65536.0; b /= 65536.0; c /= 65536.0; d /= 65536.0
    deg = round(math.degrees(math.atan2(b, a))) % 360
    return deg if deg in (0, 90, 180, 270) else None


def parse_mp4(path: str) -> Optional[Dict]:
    with open(path, "rb") as f:
        f.seek(0, os.SEEK_END)
        end = f.tell()
        f.seek(0)
        head = f.read(12)
        if len(head) < 12 or head[4:8] != b"ftyp":
            return None
        brand = head[8:12].decode("ascii", "replace")
        moov = None
        for typ, ps, pe in _file_top_boxes(f, end):
            if typ == b"moov":
                if pe - ps > _MOOV_CAP:
                    return None
                f.seek(ps)
                moov = f.read(pe - ps)
                break
        if moov is None:
            return None

    out: Dict = {"format_name": "mov" if brand.startswith("qt")
                 else "mp4", "brand": brand.strip()}

    def full(ps: int):
        version = moov[ps]
        return version, ps + 4

    for typ, ps, pe in iter_boxes(moov):
        if typ == b"mvhd":
            v, p = full(ps)
            if v == 1:
                p += 16
                timescale = struct.unpack_from(">I", moov, p)[0]
                duration = struct.unpack_from(">Q", moov, p + 4)[0]
            else:
                p += 8
                timescale = struct.unpack_from(">I", moov, p)[0]
                duration = struct.unpack_from(">I", moov, p + 4)[0]
            if timescale:
                out["duration_seconds"] = round(duration / timescale, 3)
        elif typ == b"trak":
            _parse_trak(moov, ps, pe, out)
    # format_name + brand alone mean nothing parsed — treat as unreadable
    return out if len(out) > 2 else None


def _parse_trak(moov: bytes, ps: int, pe: int, out: Dict) -> None:
    handler = None
    tkhd_dims = None
    rotation = None
    mdhd_ts = sample_count = None
    mdhd_dur = None
    stsd_entry = None

    def walk(ps, pe, depth=0):
        nonlocal handler, tkhd_dims, rotation, mdhd_ts, mdhd_dur
        nonlocal sample_count, stsd_entry
        for typ, bs, be in iter_boxes(moov, ps, pe):
            if typ == b"tkhd":
                v = moov[bs]
                p = bs + 4 + (32 if v == 1 else 20)
                p += 8 + 2 + 2 + 2 + 2   # reserved, layer, group, vol, rsvd
                mat = moov[p:p + 36]
                if len(mat) == 36:
                    rotation = _rotation_from_matrix(mat)
                p += 36
                if be - p >= 8:
                    w, h = struct.unpack_from(">II", moov, p)
                    tkhd_dims = (w >> 16, h >> 16)
            elif typ == b"hdlr":
                handler = moov[bs + 8:bs + 12]
            elif typ == b"mdhd":
                v = moov[bs]
                p = bs + 4 + (16 if v == 1 else 8)
                mdhd_ts = struct.unpack_from(">I", moov, p)[0]
                mdhd_dur = (struct.unpack_from(">Q", moov, p + 4)[0]
                            if v == 1 else
                            struct.unpack_from(">I", moov, p + 4)[0])
            elif typ == b"stsd":
                n = struct.unpack_from(">I", moov, bs + 4)[0]
                if n >= 1:
                    esz, fourcc = struct.unpack_from(">I4s", moov, bs + 8)
                    stsd_entry = (fourcc, bs + 8, min(bs + 8 + esz, be))
            elif typ == b"stts":
                n = struct.unpack_from(">I", moov, bs + 4)[0]
                # clamp to what the box actually holds (corrupt counts
                # must not read sibling bytes) and to a sane VFR bound;
                # a clamped count would yield a WRONG fps, so omit it
                capped = min(n, (be - bs - 8) // 8, 65536)
                if capped == n:
                    total = 0
                    for k in range(capped):
                        cnt = struct.unpack_from(
                            ">I", moov, bs + 8 + 8 * k)[0]
                        total += cnt
                    sample_count = total
            elif typ in (b"mdia", b"minf", b"stbl"):
                walk(bs, be, depth + 1)

    walk(ps, pe)
    if stsd_entry is None:
        return
    fourcc, es, ee = stsd_entry
    codec = fourcc.decode("ascii", "replace").strip()
    if handler == b"vide":
        if "video_codec" in out:
            return  # first video track wins (matches the ffprobe branch)
        out["video_codec"] = codec
        # VisualSampleEntry: 8 hdr + 6 reserved + 2 dref + 16 predefined
        p = es + 8 + 6 + 2 + 16
        if ee - p >= 4:
            w, h = struct.unpack_from(">HH", moov, p)
            if w and h:
                out["width"], out["height"] = w, h
        if tkhd_dims and not out.get("width"):
            out["width"], out["height"] = tkhd_dims
        if rotation:
            out["rotation"] = rotation
        if mdhd_ts and mdhd_dur and sample_count:
            secs = mdhd_dur / mdhd_ts
            if secs > 0:
                out["fps"] = round(sample_count / secs, 3)
    elif handler == b"soun":
        if "audio_codec" in out:
            return
        out["audio_codec"] = codec
        # AudioSampleEntry: 8 hdr + 6 reserved + 2 dref + 8 version/rsvd,
        # then channelcount(2) samplesize(2) predefined(2) reserved(2)
        # samplerate(16.16)
        p = es + 8 + 6 + 2 + 8
        if ee - p >= 12:
            channels, _bits = struct.unpack_from(">HH", moov, p)
            rate = struct.unpack_from(">I", moov, p + 8)[0] >> 16
            if channels:
                out["channels"] = channels
            if rate:
                out["sample_rate"] = rate


def mp4_cover_art(path: str) -> Optional[bytes]:
    """Embedded cover image (iTunes-style `covr` in moov/udta/meta/ilst)
    — JPEG/PNG bytes, or None. Lets MP4/M4V/MOV files carry a real
    thumbnail with no video decoder (movies/TV rips and anything tagged
    by iTunes/ffmpeg `-disposition:v attached_pic` muxing carry one)."""
    with open(path, "rb") as f:
        f.seek(0, os.SEEK_END)
        end = f.tell()
        f.seek(0)
        if f.read(12)[4:8] != b"ftyp":
            return None
        moov = None
        for typ, ps, pe in _file_top_boxes(f, end):
            if typ == b"moov":
                if pe - ps > _MOOV_CAP:
                    return None
                f.seek(ps)
                moov = f.read(pe - ps)
                break
    if moov is None:
        return None
    span = (0, len(moov))
    for name in (b"udta", b"meta", b"ilst", b"covr", b"data"):
        found = None
        for typ, ps, pe in iter_boxes(moov, span[0], span[1]):
            if typ == name:
                if name == b"meta":
                    ps += 4  # FullBox version/flags
                found = (ps, pe)
                break
        if found is None:
            return None
        span = found
    # data box: u32 type (13=jpeg, 14=png), u32 locale, then payload
    ps, pe = span
    if pe - ps < 8:
        return None
    payload = moov[ps + 8:pe]
    if payload[:2] == b"\xff\xd8" or payload[:8] == b"\x89PNG\r\n\x1a\n":
        return payload
    return None
