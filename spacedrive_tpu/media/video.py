"""Video thumbnailing: any-codec decode via ffmpeg CLI or OpenCV's
bundled libavcodec, self-hosted parsers as the library-free floor.

The reference's sd-ffmpeg crate drives raw ffmpeg FFI: seek to 10% of
the stream, decode one frame, scale, encode webp
(/root/reference/crates/ffmpeg/src/thumbnailer.rs:11-161,
movie_decoder.rs:32). Here the same contract runs through a chain of
decode backends, best available first:

1. `ffmpeg`/`ffprobe` CLIs when installed;
2. `cv2.VideoCapture` — OpenCV wheels bundle libavcodec, so this is
   the moral equivalent of the reference linking ffmpeg: CABAC
   Main/High H.264, HEVC, VP9, and everything else its ffmpeg build
   decodes (committed fixtures in tests/fixtures/video exercise it);
3. the self-hosted from-spec decoders — MJPEG-AVI (media/mjpeg.py)
   and baseline-CAVLC H.264 in MP4/TS (media/h264.py, mpegts.py) —
   which keep the path alive with no media library at all;
4. embedded cover art (MP4 `covr`, Matroska attachments).

A codec nothing in the chain handles degrades to None, exactly like
the reference degrades on MovieDecoder errors.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
from functools import lru_cache
from typing import Optional

from .. import persist
from .thumbnail import TARGET_QUALITY, scale_dimensions

SEEK_PERCENTAGE = 0.10  # thumbnailer.rs seek to 10%
# Containers the self-hosted MJPEG parser handles without ffmpeg.
MJPEG_EXTENSIONS = {"avi"}
VIDEO_EXTENSIONS = {
    "mp4", "mkv", "mov", "avi", "webm", "m4v", "mpg", "mpeg", "wmv",
    "flv", "3gp", "ts", "mts", "m2ts", "ogv",
}


@lru_cache(maxsize=1)
def available() -> bool:
    return (shutil.which("ffmpeg") is not None
            and shutil.which("ffprobe") is not None)


@lru_cache(maxsize=1)
def cv2_available() -> bool:
    """OpenCV with its bundled ffmpeg videoio — the default any-codec
    decode backend when no ffmpeg CLI is installed."""
    try:
        import cv2  # noqa: F401

        return True
    except Exception:
        return False


def cv2_probe(path: str) -> Optional[dict]:
    """Container probe via cv2: duration / fps / dimensions / frames.
    Returns None when cv2 is absent or cannot open the file."""
    if not cv2_available():
        return None
    import cv2

    cap = cv2.VideoCapture(path)
    try:
        if not cap.isOpened():
            return None
        fps = cap.get(cv2.CAP_PROP_FPS) or 0.0
        frames = cap.get(cv2.CAP_PROP_FRAME_COUNT) or 0.0
        out = {
            "width": int(cap.get(cv2.CAP_PROP_FRAME_WIDTH)) or None,
            "height": int(cap.get(cv2.CAP_PROP_FRAME_HEIGHT)) or None,
            "fps": round(fps, 3) or None,
            "frame_count": int(frames) or None,
            "duration_seconds": (round(frames / fps, 3)
                                 if fps > 0 and frames > 0 else None),
        }
        return out if any(v for v in out.values()) else None
    finally:
        cap.release()


def probe_duration(path: str) -> Optional[float]:
    """Container duration in seconds, or None."""
    if available():
        try:
            out = subprocess.run(
                ["ffprobe", "-v", "quiet", "-print_format", "json",
                 "-show_format", path],
                capture_output=True, timeout=30, check=True)
            return float(json.loads(out.stdout)["format"]["duration"])
        except Exception:
            return None
    info = cv2_probe(path)
    return info.get("duration_seconds") if info else None


def _mjpeg_thumbnail(input_path: str, out_path: str,
                     target_px: float) -> Optional[str]:
    """ffmpeg-free path: extract the 10% frame of an MJPEG AVI and webp
    it (media/mjpeg.py). Returns None for non-MJPEG containers."""
    import io

    from PIL import Image

    from .mjpeg import frame_at_fraction
    from .thumbnail import encode_webp

    try:
        jpeg = frame_at_fraction(input_path, SEEK_PERCENTAGE)
        if jpeg is None:
            return None
        with Image.open(io.BytesIO(jpeg)) as im:
            return encode_webp(im, out_path, target_px)
    except Exception:
        return None


def _is_mjpeg_candidate(path: str) -> bool:
    return (os.path.splitext(path)[1].lstrip(".").lower()
            in MJPEG_EXTENSIONS)


_COVER_EXTENSIONS = {"mp4", "m4v", "mov", "m4a", "3gp", "mkv", "webm"}
_H264_MP4_EXTENSIONS = {"mp4", "m4v", "mov", "3gp"}
_H264_TS_EXTENSIONS = {"ts", "mts", "m2ts"}


def _h264_thumbnail(input_path: str, out_path: str,
                    target_px: float) -> Optional[str]:
    """Self-hosted H.264 path: decode the IDR nearest 10% with the
    from-spec baseline-I decoder (media/h264.py) and webp it — MP4
    family via the sample tables, transport streams via the TS demux
    (media/mpegts.py). Returns None for non-H.264 files or streams
    outside the baseline-I subset (CABAC, high profile) — the caller
    then tries cover art."""
    from PIL import Image

    from .h264 import keyframe_from_mp4, yuv420_to_rgb
    from .thumbnail import encode_webp

    ext = os.path.splitext(input_path)[1].lstrip(".").lower()
    if ext in _H264_MP4_EXTENSIONS:
        grab = keyframe_from_mp4
    elif ext in _H264_TS_EXTENSIONS:
        from .mpegts import keyframe_from_ts as grab
    else:
        return None
    try:
        planes = grab(input_path, SEEK_PERCENTAGE)
        if planes is None:
            return None
        rgb = yuv420_to_rgb(*planes)
        return encode_webp(Image.fromarray(rgb), out_path, target_px)
    except Exception:
        return None


def _cv2_thumbnail(input_path: str, out_path: str,
                   target_px: float) -> Optional[str]:
    """Decode the frame at 10% with cv2's bundled libavcodec and webp
    it — the any-codec backend (CABAC H.264, HEVC, VP9, ...) mirroring
    the reference's ffmpeg link (movie_decoder.rs:32). Seeks by frame
    index when the container reports a frame count (cheap on the tiny
    GOPs real files have), else falls back to reading the first frame.
    Returns None when cv2 is absent or its ffmpeg can't decode the
    stream — the caller continues down the self-hosted chain."""
    if not cv2_available():
        return None
    import cv2
    from PIL import Image

    from .thumbnail import encode_webp

    cap = cv2.VideoCapture(input_path)
    try:
        if not cap.isOpened():
            return None
        frames = cap.get(cv2.CAP_PROP_FRAME_COUNT) or 0.0
        if frames > 0:
            cap.set(cv2.CAP_PROP_POS_FRAMES,
                    int(frames * SEEK_PERCENTAGE))
        ok, frame = cap.read()
        if not ok and frames > 0:
            # Seek landed outside the decodable range (some containers
            # report wrong counts) — retry from the start.
            cap.set(cv2.CAP_PROP_POS_FRAMES, 0)
            ok, frame = cap.read()
        if not ok or frame is None or frame.size == 0:
            return None
        rgb = cv2.cvtColor(frame, cv2.COLOR_BGR2RGB)
        return encode_webp(Image.fromarray(rgb), out_path, target_px)
    except Exception:
        return None
    finally:
        cap.release()


def _cover_art_thumbnail(input_path: str, out_path: str,
                         target_px: float) -> Optional[str]:
    """Decoder-free fallback for H.264/HEVC containers: embedded cover
    art (iTunes `covr` in MP4, image attachments in Matroska — the
    cover.jpg convention of movie files). Returns None when absent."""
    import io

    from PIL import Image

    from .thumbnail import encode_webp

    ext = os.path.splitext(input_path)[1].lstrip(".").lower()
    if ext not in _COVER_EXTENSIONS:
        return None
    try:
        if ext in ("mkv", "webm"):
            from .mkv import mkv_attachment_image

            blob = mkv_attachment_image(input_path)
        else:
            from .mp4meta import mp4_cover_art

            blob = mp4_cover_art(input_path)
        if not blob:
            return None
        with Image.open(io.BytesIO(blob)) as im:
            im.load()
            return encode_webp(im, out_path, target_px)
    except Exception:
        return None


def _fallback_chain(input_path: str, out_path: str,
                    target_px: float) -> Optional[str]:
    """The ffmpeg-CLI-less backend chain, best decoder first (module
    docstring): cv2's libavcodec → self-hosted MJPEG-AVI → self-hosted
    CAVLC H.264 (MP4/TS) → embedded cover art."""
    return (_cv2_thumbnail(input_path, out_path, target_px)
            or (_mjpeg_thumbnail(input_path, out_path, target_px)
                if _is_mjpeg_candidate(input_path) else None)
            or _h264_thumbnail(input_path, out_path, target_px)
            or _cover_art_thumbnail(input_path, out_path, target_px))


def generate_video_thumbnail(input_path: str, out_path: str,
                             target_px: float = 262144.0
                             ) -> Optional[str]:
    """Seek 10%, grab one frame, scale to ~target_px, encode webp.

    Returns out_path on success, None when no decoder in the backend
    chain (module docstring) applies — the caller records no thumbnail,
    as the reference does on MovieDecoder errors."""
    if not available():
        return _fallback_chain(input_path, out_path, target_px)
    duration = probe_duration(input_path) or 0.0
    seek = duration * SEEK_PERCENTAGE
    # ~512×512-equivalent area; ffmpeg keeps aspect via -2.
    w, _ = scale_dimensions(1024, 1024, target_px)
    tmp = out_path + ".tmp"
    try:
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        # -f webp: the muxer cannot be inferred from the ".tmp" name.
        subprocess.run(
            ["ffmpeg", "-v", "quiet", "-ss", f"{seek:.3f}",
             "-i", input_path, "-frames:v", "1",
             "-vf", f"scale='min({w},iw)':-2",
             "-quality", str(TARGET_QUALITY), "-f", "webp", "-y", tmp],
            capture_output=True, timeout=60, check=True)
        if not os.path.getsize(tmp):
            raise ValueError("empty frame")
        # ffmpeg streamed the frame into the tmp; seal applies the
        # declared atomic-replace tail so readers never see torn webp.
        persist.seal("media.thumbnail", tmp, out_path)
        return out_path
    except Exception:
        try:
            os.remove(tmp)
        except OSError:
            pass
        return _fallback_chain(input_path, out_path, target_px)
