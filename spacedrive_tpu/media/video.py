"""Video thumbnailing: ffmpeg CLI when present, self-hosted MJPEG-AVI
always.

The reference's sd-ffmpeg crate drives raw ffmpeg FFI: seek to 10% of
the stream, decode one frame, scale, encode webp
(/root/reference/crates/ffmpeg/src/thumbnailer.rs:11-161,
movie_decoder.rs:32). This runtime image ships no ffmpeg binary or
libraries, so the same contract is implemented over the `ffmpeg`/
`ffprobe` CLIs when present — and for Motion-JPEG `.avi` files the
container is parsed directly (media/mjpeg.py) so the video-thumbnail
path actually executes here: seek to the frame at 10%, decode the JPEG
with PIL, scale, encode webp. Other codecs degrade to None without
ffmpeg, exactly like the reference degrades on MovieDecoder errors.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
from functools import lru_cache
from typing import Optional

from .thumbnail import TARGET_QUALITY, scale_dimensions

SEEK_PERCENTAGE = 0.10  # thumbnailer.rs seek to 10%
# Containers the self-hosted MJPEG parser handles without ffmpeg.
MJPEG_EXTENSIONS = {"avi"}
VIDEO_EXTENSIONS = {
    "mp4", "mkv", "mov", "avi", "webm", "m4v", "mpg", "mpeg", "wmv",
    "flv", "3gp", "ts", "mts", "m2ts", "ogv",
}


@lru_cache(maxsize=1)
def available() -> bool:
    return (shutil.which("ffmpeg") is not None
            and shutil.which("ffprobe") is not None)


def probe_duration(path: str) -> Optional[float]:
    """Container duration in seconds, or None."""
    if not available():
        return None
    try:
        out = subprocess.run(
            ["ffprobe", "-v", "quiet", "-print_format", "json",
             "-show_format", path],
            capture_output=True, timeout=30, check=True)
        return float(json.loads(out.stdout)["format"]["duration"])
    except Exception:
        return None


def _mjpeg_thumbnail(input_path: str, out_path: str,
                     target_px: float) -> Optional[str]:
    """ffmpeg-free path: extract the 10% frame of an MJPEG AVI and webp
    it (media/mjpeg.py). Returns None for non-MJPEG containers."""
    import io

    from PIL import Image

    from .mjpeg import frame_at_fraction
    from .thumbnail import encode_webp

    try:
        jpeg = frame_at_fraction(input_path, SEEK_PERCENTAGE)
        if jpeg is None:
            return None
        with Image.open(io.BytesIO(jpeg)) as im:
            return encode_webp(im, out_path, target_px)
    except Exception:
        return None


def _is_mjpeg_candidate(path: str) -> bool:
    return (os.path.splitext(path)[1].lstrip(".").lower()
            in MJPEG_EXTENSIONS)


_COVER_EXTENSIONS = {"mp4", "m4v", "mov", "m4a", "3gp", "mkv", "webm"}
_H264_MP4_EXTENSIONS = {"mp4", "m4v", "mov", "3gp"}
_H264_TS_EXTENSIONS = {"ts", "mts", "m2ts"}


def _h264_thumbnail(input_path: str, out_path: str,
                    target_px: float) -> Optional[str]:
    """Self-hosted H.264 path: decode the IDR nearest 10% with the
    from-spec baseline-I decoder (media/h264.py) and webp it — MP4
    family via the sample tables, transport streams via the TS demux
    (media/mpegts.py). Returns None for non-H.264 files or streams
    outside the baseline-I subset (CABAC, high profile) — the caller
    then tries cover art."""
    from PIL import Image

    from .h264 import keyframe_from_mp4, yuv420_to_rgb
    from .thumbnail import encode_webp

    ext = os.path.splitext(input_path)[1].lstrip(".").lower()
    if ext in _H264_MP4_EXTENSIONS:
        grab = keyframe_from_mp4
    elif ext in _H264_TS_EXTENSIONS:
        from .mpegts import keyframe_from_ts as grab
    else:
        return None
    try:
        planes = grab(input_path, SEEK_PERCENTAGE)
        if planes is None:
            return None
        rgb = yuv420_to_rgb(*planes)
        return encode_webp(Image.fromarray(rgb), out_path, target_px)
    except Exception:
        return None


def _cover_art_thumbnail(input_path: str, out_path: str,
                         target_px: float) -> Optional[str]:
    """Decoder-free fallback for H.264/HEVC containers: embedded cover
    art (iTunes `covr` in MP4, image attachments in Matroska — the
    cover.jpg convention of movie files). Returns None when absent."""
    import io

    from PIL import Image

    from .thumbnail import encode_webp

    ext = os.path.splitext(input_path)[1].lstrip(".").lower()
    if ext not in _COVER_EXTENSIONS:
        return None
    try:
        if ext in ("mkv", "webm"):
            from .mkv import mkv_attachment_image

            blob = mkv_attachment_image(input_path)
        else:
            from .mp4meta import mp4_cover_art

            blob = mp4_cover_art(input_path)
        if not blob:
            return None
        with Image.open(io.BytesIO(blob)) as im:
            im.load()
            return encode_webp(im, out_path, target_px)
    except Exception:
        return None


def generate_video_thumbnail(input_path: str, out_path: str,
                             target_px: float = 262144.0
                             ) -> Optional[str]:
    """Seek 10%, grab one frame, scale to ~target_px, encode webp.

    Returns out_path on success, None when no decoder applies or the
    decode fails (the caller records no thumbnail, as the reference does
    on MovieDecoder errors). MJPEG `.avi` decodes without ffmpeg — and
    is also the fallback when an installed ffmpeg fails on one."""
    if not available():
        if _is_mjpeg_candidate(input_path):
            return _mjpeg_thumbnail(input_path, out_path, target_px)
        return (_h264_thumbnail(input_path, out_path, target_px)
                or _cover_art_thumbnail(input_path, out_path, target_px))
    duration = probe_duration(input_path) or 0.0
    seek = duration * SEEK_PERCENTAGE
    # ~512×512-equivalent area; ffmpeg keeps aspect via -2.
    w, _ = scale_dimensions(1024, 1024, target_px)
    tmp = out_path + ".tmp"
    try:
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        # -f webp: the muxer cannot be inferred from the ".tmp" name.
        subprocess.run(
            ["ffmpeg", "-v", "quiet", "-ss", f"{seek:.3f}",
             "-i", input_path, "-frames:v", "1",
             "-vf", f"scale='min({w},iw)':-2",
             "-quality", str(TARGET_QUALITY), "-f", "webp", "-y", tmp],
            capture_output=True, timeout=60, check=True)
        if not os.path.getsize(tmp):
            raise ValueError("empty frame")
        os.replace(tmp, out_path)
        return out_path
    except Exception:
        try:
            os.remove(tmp)
        except OSError:
            pass
        if _is_mjpeg_candidate(input_path):
            return _mjpeg_thumbnail(input_path, out_path, target_px)
        return (_h264_thumbnail(input_path, out_path, target_px)
                or _cover_art_thumbnail(input_path, out_path, target_px))
