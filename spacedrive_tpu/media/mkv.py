"""Matroska/WebM stream metadata: a minimal EBML walker.

Same rationale as media/mp4meta.py — the reference's video metadata
structs are stubs awaiting ffmpeg
(/root/reference/crates/media-metadata/src/video.rs); MKV keeps its
metadata in plain EBML elements near the head of the file, so a tiny
varint walker recovers duration, codecs, dimensions and audio params
without any demuxer. Element IDs from the public Matroska spec
(Segment → Info{TimestampScale, Duration}, Tracks → TrackEntry
{TrackType, CodecID, Video{PixelWidth, PixelHeight}, Audio
{SamplingFrequency, Channels}}).

Only the first `_SCAN_CAP` bytes are examined: Info/Tracks precede the
clusters in every muxer that exists (streamed files use unknown-size
Segments, handled below).
"""

from __future__ import annotations

import struct
from typing import Dict, Optional, Tuple

_SCAN_CAP = 16 << 20

_EBML = 0x1A45DFA3
_SEGMENT = 0x18538067
_INFO = 0x1549A966
_TS_SCALE = 0x2AD7B1
_DURATION = 0x4489
_TRACKS = 0x1654AE6B
_TRACK_ENTRY = 0xAE
_TRACK_TYPE = 0x83
_CODEC_ID = 0x86
_VIDEO = 0xE0
_PIXEL_W = 0xB0
_PIXEL_H = 0xBA
_AUDIO = 0xE1
_SAMPLING = 0xB5
_CHANNELS = 0x9F
_DOCTYPE = 0x4282


def _read_vint(data: bytes, pos: int,
               keep_marker: bool) -> Optional[Tuple[int, int, int]]:
    """(value, next_pos, vint_length). EBML ids keep the length marker
    bit; sizes strip it. Returns None at end of data."""
    if pos >= len(data):
        return None
    first = data[pos]
    if first == 0:
        return None
    length = 8 - first.bit_length() + 1
    if pos + length > len(data):
        return None
    val = first if keep_marker else first & (0xFF >> length)
    for k in range(1, length):
        val = (val << 8) | data[pos + k]
    return val, pos + length, length


def _walk(data: bytes, pos: int, end: int):
    """Yield (element_id, payload_start, payload_end)."""
    while pos < end:
        r = _read_vint(data, pos, keep_marker=True)
        if r is None:
            return
        eid, pos, _ = r
        r = _read_vint(data, pos, keep_marker=False)
        if r is None:
            return
        size, pos, slen = r
        # Unknown size = ALL data bits set FOR THIS VINT LENGTH (a
        # legit size of 127 in a non-minimal 2-byte vint is not it).
        if size == (1 << (7 * slen)) - 1:
            # unknown-size master element (streamed Segment): its
            # children run to the end of the scanned span
            yield eid, pos, end
            return
        pe = min(pos + size, end)
        yield eid, pos, pe
        pos += size


def _uint(data: bytes, ps: int, pe: int) -> int:
    v = 0
    for b in data[ps:pe]:
        v = (v << 8) | b
    return v


def _float(data: bytes, ps: int, pe: int) -> Optional[float]:
    n = pe - ps
    if n == 4:
        return struct.unpack(">f", data[ps:pe])[0]
    if n == 8:
        return struct.unpack(">d", data[ps:pe])[0]
    return None


def _scan(path: str):
    """Progressive read: Info/Tracks live in the head of every real
    muxer's output, so start at 256 KB and grow only while the tracks
    haven't been seen (a library sweep must not read 16 MB per file)."""
    size = 256 << 10
    with open(path, "rb") as f:
        while True:
            f.seek(0)
            data = f.read(size)
            if (b"\x16\x54\xae\x6b" in data  # Tracks id present
                    or len(data) < size or size >= _SCAN_CAP):
                return data
            size *= 4


def parse_mkv(path: str) -> Optional[Dict]:
    data = _scan(path)
    if len(data) < 8:
        return None
    out: Dict = {}
    segments = []
    for eid, ps, pe in _walk(data, 0, len(data)):
        if eid == _EBML:
            for cid, cs, ce in _walk(data, ps, pe):
                if cid == _DOCTYPE:
                    out["format_name"] = data[cs:ce].decode(
                        "ascii", "replace").strip("\x00")
        elif eid == _SEGMENT:
            segments.append((ps, pe))
    if "format_name" not in out:
        return None
    ts_scale = 1_000_000  # ns per timestamp tick (spec default)
    duration_ticks: Optional[float] = None
    for ps, pe in segments:
        for eid, bs, be in _walk(data, ps, pe):
            if eid == _INFO:
                for cid, cs, ce in _walk(data, bs, be):
                    if cid == _TS_SCALE:
                        ts_scale = _uint(data, cs, ce) or ts_scale
                    elif cid == _DURATION:
                        duration_ticks = _float(data, cs, ce)
            elif eid == _TRACKS:
                for cid, cs, ce in _walk(data, bs, be):
                    if cid != _TRACK_ENTRY:
                        continue
                    ttype, codec = None, None
                    video, audio = None, None
                    for tid, ts, te in _walk(data, cs, ce):
                        if tid == _TRACK_TYPE:
                            ttype = _uint(data, ts, te)
                        elif tid == _CODEC_ID:
                            codec = data[ts:te].decode("ascii", "replace")
                        elif tid == _VIDEO:
                            video = (ts, te)
                        elif tid == _AUDIO:
                            audio = (ts, te)
                    # first track of each type wins, matching mp4meta
                    # and the ffprobe branch
                    if ttype == 1 and video and "video_codec" not in out:
                        if codec:
                            out["video_codec"] = codec
                        for vid, vs, ve in _walk(data, *video):
                            if vid == _PIXEL_W:
                                out["width"] = _uint(data, vs, ve)
                            elif vid == _PIXEL_H:
                                out["height"] = _uint(data, vs, ve)
                    elif ttype == 2 and audio and "audio_codec" not in out:
                        if codec:
                            out["audio_codec"] = codec
                        for aid, as_, ae in _walk(data, *audio):
                            if aid == _SAMPLING:
                                r = _float(data, as_, ae)
                                if r:
                                    out["sample_rate"] = int(r)
                            elif aid == _CHANNELS:
                                out["channels"] = _uint(data, as_, ae)
    if duration_ticks is not None:
        out["duration_seconds"] = round(
            duration_ticks * ts_scale / 1e9, 3)
    return out if len(out) > 1 else None


_ATTACHMENTS = 0x1941A469
_ATTACHED_FILE = 0x61A7
_FILE_NAME = 0x466E
_FILE_MIME = 0x4660
_FILE_DATA = 0x465C


def mkv_attachment_image(path: str) -> Optional[bytes]:
    """First image attachment (cover.jpg convention) from a Matroska
    file — movie rips routinely attach cover art; no video decode
    needed. Returns JPEG/PNG bytes or None."""
    data = _scan(path)
    if len(data) < 8:
        return None
    # attachments usually precede clusters; extend the scan if the
    # Attachments id is beyond the tracks-bounded head read
    if _ATTACHMENTS.to_bytes(4, "big") not in data:
        with open(path, "rb") as f:
            data = f.read(_SCAN_CAP)
        if _ATTACHMENTS.to_bytes(4, "big") not in data:
            return None
    for eid, ps, pe in _walk(data, 0, len(data)):
        if eid != 0x18538067:  # Segment
            continue
        for sid, bs, be in _walk(data, ps, pe):
            if sid != _ATTACHMENTS:
                continue
            for aid, as_, ae in _walk(data, bs, be):
                if aid != _ATTACHED_FILE:
                    continue
                mime, blob = "", None
                for fid, fs, fe in _walk(data, as_, ae):
                    if fid == _FILE_MIME:
                        mime = data[fs:fe].decode("ascii", "replace")
                    elif fid == _FILE_DATA:
                        blob = data[fs:fe]
                if blob and (mime.startswith("image/")
                             or blob[:2] == b"\xff\xd8"
                             or blob[:8] == b"\x89PNG\r\n\x1a\n"):
                    return blob
    return None
