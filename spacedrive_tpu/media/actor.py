"""Thumbnailer actor: the long-lived batch-thumbnailing service.

Behavioral equivalent of the reference's standalone thumbnailer actor
(/root/reference/core/src/object/media/thumbnail/actor.rs:64-586), which
is deliberately NOT a job: it outlives jobs, owns the 256-way sharded
webp cache, and serves two queues — indexed batches (cas_id + source
path, dispatched by the media processor) and ephemeral batches (paths
browsed outside any library, non_indexed.rs). Completed thumbnails emit
`NewThumbnail` core events; a periodic clean-up pass removes cache
entries whose cas_ids appear in no loaded library; a version file
invalidates the whole cache across format changes
(thumbnail/directory.rs).
"""

from __future__ import annotations

import asyncio
import os
import shutil
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .. import channels, tasks
from .thumbnail import (
    THUMBNAIL_CACHE_VERSION,
    thumbnailable_extensions,
    VERSION_FILE,
    ensure_thumbnail_dir,
    generate_thumbnail,
    remove_thumbnails_by_cas_ids,
    thumbnail_path,
)

BATCH_CONCURRENCY = 4        # actor.rs processing fan-out per batch
CLEANUP_TICK_S = 1800.0      # periodic clean-up vs library DBs


@dataclass
class ThumbBatch:
    """One unit of queued work: (cas_id, source path) pairs."""

    entries: List[tuple]     # [(cas_id, full_path), ...]
    library_id: Optional[object] = None
    ephemeral: bool = False
    done: asyncio.Event = field(default_factory=asyncio.Event)
    generated: int = 0
    # Completion shares: one for this batch's own processing (if it
    # kept any entries) plus one per delegate batch that absorbed
    # coalesced entries. `done` fires only when every share lands, so
    # awaiting a batch always means every requested path was
    # processed or shed — never silently skipped.
    _outstanding: int = 0
    _dependents: List["ThumbBatch"] = field(default_factory=list)


class Thumbnailer:
    """Actor facade: queue batches, await them, let the loop work."""

    def __init__(self, node):
        self.node = node
        self.data_dir = node.data_dir
        # Bounded batch queue (channels.py registry, policy
        # shed_oldest): during a full-library scan a slow thumbnailer
        # used to absorb the whole index into this queue — now the
        # oldest batch is shed (thumbnails are regenerable; its
        # awaiters are released via done) and depth stays capped.
        self.queue = channels.channel("media.thumbs",
                                      on_evict=self._shed_batch)
        # (cas_id, path) → the pending/processing batch that will
        # generate it: duplicate requests coalesce into that batch
        # instead of queueing the same thumbnail twice (a rescan
        # mid-generation re-dispatches the same paths).
        self._queued: Dict[Tuple[str, str], ThumbBatch] = {}
        self._owner = f"{getattr(node, 'task_owner', 'proc')}/media"
        self._task: Optional[asyncio.Task] = None
        self._cleanup_task: Optional[asyncio.Task] = None
        self._migrate_version()

    # -- cache versioning (thumbnail/directory.rs) -------------------------

    def _migrate_version(self) -> None:
        root = os.path.join(self.data_dir, "thumbnails")
        vf = os.path.join(root, VERSION_FILE)
        if os.path.isdir(root):
            try:
                with open(vf) as f:
                    on_disk = int(f.read().strip() or 0)
            except (OSError, ValueError):
                on_disk = 0
            if on_disk != THUMBNAIL_CACHE_VERSION:
                # Format change: the whole cache is regenerable state.
                shutil.rmtree(root, ignore_errors=True)
        ensure_thumbnail_dir(self.data_dir)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = tasks.spawn(
                "thumbnailer", self._run(), owner=self._owner)
        if self._cleanup_task is None or self._cleanup_task.done():
            self._cleanup_task = tasks.spawn(
                "thumbnailer-cleanup", self._cleanup_loop(),
                owner=self._owner)

    async def stop(self) -> None:
        await tasks.cancel_and_gather(self._task, self._cleanup_task)
        self._task = self._cleanup_task = None

    # -- queueing API (actor.rs new_batch / new_ephemeral_batch) -----------

    async def new_batch(self, entries: List[tuple],
                        library_id=None) -> ThumbBatch:
        batch = ThumbBatch(entries=list(entries), library_id=library_id)
        return await self._enqueue(batch)

    async def new_ephemeral_batch(self, entries: List[tuple]) -> ThumbBatch:
        batch = ThumbBatch(entries=list(entries), ephemeral=True)
        return await self._enqueue(batch)

    async def _enqueue(self, batch: ThumbBatch) -> ThumbBatch:
        """Per-path coalescing + bounded put. Entries already pending
        in another batch are dropped from this one — that batch will
        generate them — but this batch's `done` then also waits for
        those delegates (processed or shed), so a caller's await
        never returns while its thumbnails are still someone else's
        pending work."""
        fresh: List[tuple] = []
        delegate_ids: set = set()
        delegates: List[ThumbBatch] = []
        for entry in batch.entries:
            owner = self._queued.get(entry)
            if owner is None:
                fresh.append(entry)
            elif id(owner) not in delegate_ids:
                delegate_ids.add(id(owner))
                delegates.append(owner)
        batch.entries = fresh
        batch._outstanding = 1 if fresh else 0
        for d in delegates:
            if not d.done.is_set():
                batch._outstanding += 1
                # waiter registration, not a buffer: one entry per
                # live caller-owned batch, drained when the delegate
                # completes — the same shape as a channel's parked
                # getter futures
                d._dependents.append(batch)  # sdlint: ok[backpressure]
        if not fresh:
            if batch._outstanding == 0:
                batch.done.set()
            return batch
        for key in fresh:
            self._queued[key] = batch
        # shed_oldest policy: put never blocks; under overflow the
        # OLDEST batch is evicted through _shed_batch below.
        await self.queue.put(batch)
        return batch

    def _part_done(self, batch: ThumbBatch) -> None:
        """One completion share landed (own processing, a shed, or a
        delegate finishing). The last share fires `done` and cascades
        to dependents. Dependency edges only point at OLDER batches,
        so the cascade is acyclic and cannot hang."""
        batch._outstanding -= 1
        if batch._outstanding > 0 or batch.done.is_set():
            return
        batch.done.set()
        deps, batch._dependents = batch._dependents, []
        for dep in deps:
            self._part_done(dep)

    def _shed_batch(self, batch: ThumbBatch) -> None:
        # Overflow eviction (counted in sd_chan_shed_total
        # {media.thumbs}): release the batch's awaiters and forget its
        # paths so a later rescan can re-request them. Thumbnails are
        # regenerable state — shedding loses work, never correctness.
        self._forget(batch)
        self._part_done(batch)

    def _forget(self, batch: ThumbBatch) -> None:
        for key in batch.entries:
            if self._queued.get(key) is batch:
                del self._queued[key]

    def remove_cas_ids(self, cas_ids) -> int:
        return remove_thumbnails_by_cas_ids(self.data_dir, cas_ids)

    # -- the actor loop ----------------------------------------------------

    def is_running(self) -> bool:
        return self._task is not None and not self._task.done()

    async def _run(self) -> None:
        while True:
            batch: ThumbBatch = await self.queue.get()
            try:
                await self._process(batch)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # One poisoned batch must not kill the actor: jobs
                # await batch.done with no timeout.
                self.node.events.emit({
                    "type": "ThumbnailerError", "error": str(e)})
            finally:
                self._forget(batch)
                self._part_done(batch)

    async def _process(self, batch: ThumbBatch) -> None:
        sem = asyncio.Semaphore(BATCH_CONCURRENCY)

        async def one(cas_id: str, path: str) -> None:
            ext = os.path.splitext(path)[1].lstrip(".").lower()
            if ext not in thumbnailable_extensions():
                return
            async with sem:
                out = await asyncio.to_thread(
                    generate_thumbnail, path, self.data_dir, cas_id)
            if out:
                batch.generated += 1
                self.node.events.emit({
                    "type": "NewThumbnail", "cas_id": cas_id,
                    "ephemeral": batch.ephemeral})

        await asyncio.gather(
            *(one(cas_id, path) for cas_id, path in batch.entries))

    # -- clean-up (actor.rs periodic pass vs all library DBs) --------------

    async def _cleanup_loop(self) -> None:
        while True:
            await asyncio.sleep(CLEANUP_TICK_S)
            try:
                await asyncio.to_thread(self.clean_up)
            except Exception:
                pass  # best-effort janitor; never kill the actor

    def clean_up(self) -> int:
        """Remove cached thumbnails whose cas_id is referenced by no
        loaded library. Returns the number removed."""
        known = set()
        for lib in self.node.libraries.list():
            for row in lib.db.run("media.known_cas"):
                known.add(row["cas_id"])
        removed = 0
        root = os.path.join(self.data_dir, "thumbnails")
        if not os.path.isdir(root):
            return 0
        for shard in os.listdir(root):
            shard_dir = os.path.join(root, shard)
            if not os.path.isdir(shard_dir) or len(shard) != 2:
                continue
            for name in os.listdir(shard_dir):
                if not name.endswith(".webp"):
                    continue
                cas_id = name[:-5]
                if cas_id not in known:
                    try:
                        os.remove(os.path.join(shard_dir, name))
                        removed += 1
                    except OSError:
                        pass
            try:
                os.rmdir(shard_dir)  # only succeeds when empty
            except OSError:
                pass
        return removed

    def exists(self, cas_id: str) -> bool:
        return os.path.exists(thumbnail_path(self.data_dir, cas_id))
