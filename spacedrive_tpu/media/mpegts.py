"""MPEG-TS demux for video thumbnails — feeds the H.264 decoder.

Camcorders/broadcast rips ship H.264 in MPEG transport streams
(.ts/.mts/.m2ts). The reference handles them through ffmpeg's demuxer
(/root/reference/crates/ffmpeg/src/movie_decoder.rs); here the
container is walked directly: 188-byte packets (192 with the
BDAV/M2TS 4-byte timestamp prefix), PAT → PMT → the AVC elementary
stream (stream_type 0x1B), PES payloads re-assembled into Annex-B and
handed to media/h264.py. Seek-to-fraction = start scanning packets at
that byte offset (TS is designed for mid-stream joins: SPS/PPS repeat
before every IDR) and decode the first complete IDR picture found.

Structure-only parsing, bounded reads (SCAN_CAP per attempt)."""

from __future__ import annotations

import os
import struct
from typing import Dict, List, Optional, Tuple

TS_PACKET = 188
SCAN_CAP = 48 << 20       # max bytes examined per scan attempt
_H264_STREAM_TYPE = 0x1B


def _packet_size(head: bytes) -> Optional[int]:
    """188 (plain) or 192 (M2TS: 4-byte TP_extra before sync)."""
    if len(head) < 384:
        return None
    if head[0] == 0x47 and head[TS_PACKET] == 0x47:
        return TS_PACKET
    if len(head) >= 2 * 192 and head[4] == 0x47 and head[196] == 0x47:
        return 192
    return None


def _iter_packets(data: bytes, psize: int, start: int = 0):
    """Yield (pid, payload_unit_start, payload_bytes)."""
    skew = psize - TS_PACKET  # 4 for m2ts
    pos = start
    n = len(data)
    while pos + psize <= n:
        p = pos + skew
        if data[p] != 0x47:  # resync
            pos += 1
            continue
        b1, b2, b3 = data[p + 1], data[p + 2], data[p + 3]
        pid = ((b1 & 0x1F) << 8) | b2
        pusi = bool(b1 & 0x40)
        afc = (b3 >> 4) & 3
        off = p + 4
        if afc in (2, 3):  # adaptation field
            af_len = data[off]
            off += 1 + af_len
        if afc in (1, 3) and off < p + TS_PACKET + 0:
            yield pid, pusi, data[off:p + 4 + TS_PACKET - 4]
        pos += psize


def _parse_psi(payload: bytes) -> Optional[bytes]:
    """Pointer-field-skipped PSI section body, or None."""
    if not payload:
        return None
    ptr = payload[0]
    body = payload[1 + ptr:]
    return body if len(body) > 8 else None


def _find_h264_pid(data: bytes, psize: int) -> Optional[int]:
    """PAT (PID 0) → first program's PMT → first 0x1B stream PID."""
    pmt_pids: List[int] = []
    for pid, pusi, payload in _iter_packets(data, psize):
        if pid == 0 and pusi:
            body = _parse_psi(payload)
            if body is None or body[0] != 0x00:  # PAT table_id
                continue
            sec_len = ((body[1] & 0x0F) << 8) | body[2]
            p = 8
            end = min(3 + sec_len - 4, len(body))
            while p + 4 <= end:
                prog = (body[p] << 8) | body[p + 1]
                entry_pid = ((body[p + 2] & 0x1F) << 8) | body[p + 3]
                if prog != 0:
                    pmt_pids.append(entry_pid)
                p += 4
            break
    for pid, pusi, payload in _iter_packets(data, psize):
        if pid in pmt_pids and pusi:
            body = _parse_psi(payload)
            if body is None or body[0] != 0x02 or len(body) < 12:
                continue
            sec_len = ((body[1] & 0x0F) << 8) | body[2]
            pinfo_len = ((body[10] & 0x0F) << 8) | body[11]
            p = 12 + pinfo_len
            end = min(3 + sec_len - 4, len(body))
            while p + 5 <= end:
                stype = body[p]
                spid = ((body[p + 1] & 0x1F) << 8) | body[p + 2]
                es_len = ((body[p + 3] & 0x0F) << 8) | body[p + 4]
                if stype == _H264_STREAM_TYPE:
                    return spid
                p += 5 + es_len
            break
    return None


def _strip_pes_header(payload: bytes) -> Optional[bytes]:
    if len(payload) < 9 or payload[:3] != b"\x00\x00\x01":
        return None
    hdr_len = payload[8]
    return payload[9 + hdr_len:]


def extract_annexb(path: str, fraction: float = 0.10
                   ) -> Optional[bytes]:
    """Annex-B byte stream around `fraction` of the file: the video
    PID's PES payloads from the first unit-start after the seek point,
    capped at SCAN_CAP. Returns None for non-TS / non-H.264 files."""
    size = os.path.getsize(path)
    if size < 2 * TS_PACKET:
        return None
    with open(path, "rb") as f:
        head = f.read(512)
        psize = _packet_size(head)
        if psize is None:
            return None
        # PAT/PMT from the head of the file
        f.seek(0)
        lead = f.read(min(size, 4 << 20))
        try:
            vpid = _find_h264_pid(lead, psize)
        except (IndexError, struct.error):
            return None  # 0x47-looking garbage; honor the None contract
        if vpid is None:
            return None
        start = int(size * fraction)
        start -= start % psize
        f.seek(start)
        data = f.read(min(size - start, SCAN_CAP))
    out: List[bytes] = []
    started = False
    units_started = 0
    for pid, pusi, payload in _iter_packets(data, psize):
        if pid != vpid:
            continue
        if pusi:
            units_started += 1
            # collect a handful of access units: SPS/PPS repeat ahead
            # of the IDR, and a couple of extra units guarantee the
            # IDR's slices are complete before we stop
            if units_started > 12 and started:
                break
            body = _strip_pes_header(payload)
            if body is None:
                continue
            started = True
            out.append(body)
        elif started:
            out.append(payload)
    return b"".join(out) if out else None


def keyframe_from_ts(path: str, fraction: float = 0.10):
    """Decode the IDR picture nearest `fraction` → (Y, Cb, Cr) or None.

    Retries from the file head when the mid-stream window lacked an
    IDR (short clips)."""
    from . import h264 as D

    for frac in (fraction, 0.0):
        stream = extract_annexb(path, frac)
        if stream is None:
            continue
        try:
            return D.decode_annexb_iframe(stream)
        except D.H264Error:
            continue
    return None
