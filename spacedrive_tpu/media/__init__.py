from .thumbnail import generate_thumbnail, thumbnail_path
from .processor import MediaProcessorJob

__all__ = ["generate_thumbnail", "thumbnail_path", "MediaProcessorJob"]
