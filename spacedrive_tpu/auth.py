"""OAuth device-flow auth plane.

The reference implements RFC 8628 device authorization against its
hosted API (/root/reference/core/src/auth.rs,
core/src/api/auth.rs:35-174): `loginSession` POSTs /login/device/code,
streams Start{user_code, verification urls}, polls
/login/oauth/access_token with the device-code grant until the user
approves in a browser, persists the OAuthToken into the node config,
and `me` exchanges the stored token for {id, email}; `logout` clears
the token.

This runtime has no hosted issuer (zero egress), so the SAME state
machine runs against an in-process issuer implementing the three
endpoint behaviors — device-code minting, the authorization_pending /
access_denied / expired_token poll protocol, bearer-token user lookup.
A deployment with a reachable issuer swaps `Node.auth_issuer` for an
HTTP adapter with the same three methods; every caller (procedures,
tests, UI) is already written against that surface.
"""

from __future__ import annotations

import secrets
import time
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

DEVICE_CODE_URN = "urn:ietf:params:oauth:grant-type:device_code"


@dataclass
class OAuthToken:
    """auth.rs:4-15."""

    access_token: str
    refresh_token: str
    token_type: str = "Bearer"
    expires_in: int = 3600

    def to_header(self) -> str:
        return f"{self.token_type} {self.access_token}"

    def to_raw(self) -> dict:
        return {"access_token": self.access_token,
                "refresh_token": self.refresh_token,
                "token_type": self.token_type,
                "expires_in": self.expires_in}

    @classmethod
    def from_raw(cls, raw: dict) -> "OAuthToken":
        return cls(raw["access_token"], raw["refresh_token"],
                   raw.get("token_type", "Bearer"),
                   int(raw.get("expires_in", 3600)))


def _user_code() -> str:
    alphabet = "BCDFGHJKLMNPQRSTVWXZ"  # no vowels: no accidental words
    return ("".join(secrets.choice(alphabet) for _ in range(4)) + "-"
            + "".join(secrets.choice(alphabet) for _ in range(4)))


class DeviceFlowIssuer:
    """In-process issuer: the serverside of RFC 8628 the reference's
    hosted API provides. Sessions expire after `ttl` seconds."""

    def __init__(self, verification_url: str = "https://auth.invalid/activate",
                 ttl: float = 600.0):
        self.verification_url = verification_url
        self.ttl = ttl
        # device_code → session dict
        self._sessions: Dict[str, dict] = {}
        # access_token → {"id", "email"}
        self._tokens: Dict[str, dict] = {}

    # -- POST /login/device/code -------------------------------------------

    def device_code(self, client_id: str) -> dict:
        device_code = secrets.token_urlsafe(24)
        user_code = _user_code()
        self._sessions[device_code] = {
            "client_id": client_id, "user_code": user_code,
            "state": "pending", "user": None,
            "expires_at": time.monotonic() + self.ttl,
        }
        return {
            "device_code": device_code,
            "user_code": user_code,
            "verification_url": self.verification_url,
            "verification_uri_complete":
                f"{self.verification_url}?user_code={user_code}",
        }

    # -- the user's browser step -------------------------------------------

    def approve(self, user_code: str, user_id: str, email: str) -> bool:
        s = self._by_user_code(user_code)
        if s is None or s["state"] != "pending":
            return False
        s["state"] = "approved"
        s["user"] = {"id": user_id, "email": email}
        return True

    def deny(self, user_code: str) -> bool:
        s = self._by_user_code(user_code)
        if s is None or s["state"] != "pending":
            return False
        s["state"] = "denied"
        return True

    def _by_user_code(self, user_code: str) -> Optional[dict]:
        for s in self._sessions.values():
            if s["user_code"] == user_code:
                return s
        return None

    # -- POST /login/oauth/access_token ------------------------------------

    def access_token(self, grant_type: str, device_code: str,
                     client_id: str) -> Tuple[int, dict]:
        """(status, body) mirroring the endpoint the reference polls
        (api/auth.rs:80-128): 200 + token JSON on approval, 400 +
        {"error": ...} for the pending/denied/expired protocol."""
        if grant_type != DEVICE_CODE_URN:
            return 400, {"error": "unsupported_grant_type"}
        s = self._sessions.get(device_code)
        if s is None or s["client_id"] != client_id:
            return 400, {"error": "invalid_grant"}
        if time.monotonic() > s["expires_at"]:
            self._sessions.pop(device_code, None)
            return 400, {"error": "expired_token"}
        if s["state"] == "pending":
            return 400, {"error": "authorization_pending"}
        if s["state"] == "denied":
            self._sessions.pop(device_code, None)
            return 400, {"error": "access_denied"}
        token = OAuthToken(access_token=secrets.token_urlsafe(24),
                           refresh_token=secrets.token_urlsafe(24))
        self._tokens[token.access_token] = s["user"]
        self._sessions.pop(device_code, None)
        return 200, token.to_raw()

    # -- GET /api/v1/user/me -----------------------------------------------

    def me(self, authorization_header: Optional[str]) -> Optional[dict]:
        if not authorization_header:
            return None
        parts = authorization_header.split(" ", 1)
        if len(parts) != 2 or parts[0] != "Bearer":
            return None
        return self._tokens.get(parts[1])

    def revoke(self, access_token: str) -> None:
        self._tokens.pop(access_token, None)


def issuer_for(node) -> DeviceFlowIssuer:
    """The node's issuer endpoint surface (lazily built; tests and
    future HTTP adapters may assign `node.auth_issuer` directly)."""
    issuer = getattr(node, "auth_issuer", None)
    if issuer is None:
        issuer = DeviceFlowIssuer()
        node.auth_issuer = issuer
    return issuer


def stored_token(node) -> Optional[OAuthToken]:
    raw = node.config.raw.get("auth_token")
    return OAuthToken.from_raw(raw) if raw else None


def store_token(node, token: Optional[OAuthToken]) -> None:
    node.config.raw["auth_token"] = token.to_raw() if token else None
    node.config.save()
