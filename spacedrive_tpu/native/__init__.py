"""ctypes bindings to the native I/O plane (native/sdio.cpp → libsdio.so).

The native library supplies the batched file-staging and CPU-hash plane
that the reference implements in Rust (tokio::fs + the blake3 crate,
/root/reference/core/src/object/cas.rs, validation/hash.rs). Every entry
point degrades gracefully: if the shared library is missing and no C++
toolchain is available, `available()` is False and callers fall back to
the pure-Python paths (ops/cas.py, ops/staging.py).

pybind11 is not in this image, so the ABI is plain C over ctypes with
numpy arrays as buffers.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

# Status codes — must match `enum Status` in native/sdio.cpp.
OK = 0
ERR_OPEN = -1
ERR_SHORT_READ = -2
ERR_GREW = -3
ERR_EMPTY = -4
ERR_IO = -5

STATUS_MESSAGES = {
    ERR_OPEN: "cannot open file",
    ERR_SHORT_READ: "short read",
    ERR_GREW: "file grew past its declared size class",
    ERR_EMPTY: "empty file",
    ERR_IO: "I/O error",
}

# Mirrors of the constants baked into native/sdio.cpp; sourced from the
# oracle module so a change there fails loudly here instead of silently
# diverging from the compiled library.
from ..ops.cas import LARGE_PAYLOAD_SIZE as LARGE_PAYLOAD  # noqa: E402
from ..ops.cas import MINIMUM_FILE_SIZE as SMALL_CAP  # noqa: E402

assert LARGE_PAYLOAD == 57344 and SMALL_CAP == 102400, (
    "ops.cas sampling constants diverged from native/sdio.cpp — rebuild "
    "and update the C++ constants together")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _native_dir() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "native")


def _lib_path() -> str:
    env = os.environ.get("SD_NATIVE_LIB")
    if env:
        return env
    return os.path.join(_native_dir(), "build", "libsdio.so")


def _declare(lib: ctypes.CDLL) -> ctypes.CDLL:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    charpp = ctypes.POINTER(ctypes.c_char_p)

    lib.sd_blake3.argtypes = [u8p, ctypes.c_uint64, u8p]
    lib.sd_blake3.restype = None
    lib.sd_blake3_many.argtypes = [
        ctypes.c_int64, u8p, ctypes.c_int64, i32p, u64p, u8p, ctypes.c_int]
    lib.sd_blake3_many.restype = None
    lib.sd_stage_large.argtypes = [
        ctypes.c_int64, charpp, u64p, u8p, i32p, ctypes.c_int]
    lib.sd_stage_large.restype = None
    lib.sd_stage_small.argtypes = [
        ctypes.c_int64, charpp, ctypes.c_uint64, u8p, i32p, i32p,
        ctypes.c_int]
    lib.sd_stage_small.restype = None
    lib.sd_stage_batch.argtypes = [
        ctypes.c_int64, charpp, u64p, u8p, ctypes.c_int64,
        ctypes.c_uint64, i32p, i32p, ctypes.c_int]
    lib.sd_stage_batch.restype = None
    lib.sd_cas_digests.argtypes = [
        ctypes.c_int64, charpp, u64p, u8p, i32p, ctypes.c_int]
    lib.sd_cas_digests.restype = None
    lib.sd_checksum_files.argtypes = [
        ctypes.c_int64, charpp, u64p, u8p, i32p, ctypes.c_int]
    lib.sd_checksum_files.restype = None
    lib.sd_secure_erase.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.sd_secure_erase.restype = ctypes.c_int32
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.sd_encode_ops.argtypes = [
        ctypes.c_int64, u64p, u8p, ctypes.c_char_p, u8p, u8p, i64p,
        u8p, ctypes.c_int64]
    lib.sd_encode_ops.restype = ctypes.c_int64
    lib.sd_decode_ops.argtypes = [
        u8p, ctypes.c_int64, ctypes.c_int64, u64p, i64p, i32p, i64p,
        i32p, i64p, i64p, i64p, i64p, i64p, u8p]
    lib.sd_decode_ops.restype = ctypes.c_int64
    return lib


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        path = _lib_path()
        if "SD_NATIVE_LIB" not in os.environ:
            # Always run make: its dependency tracking is a ~no-op when
            # the .so is fresh and rebuilds it when sdio.cpp changed
            # (loading a stale binary would silently diverge from the
            # wrapper). Callers that must never block on a cold build
            # warm this up at bootstrap (Node.__init__).
            try:
                subprocess.run(
                    ["make", "-C", _native_dir()], check=True,
                    capture_output=True, timeout=120)
            except Exception:
                if not os.path.exists(path):
                    return None
        try:
            _lib = _declare(ctypes.CDLL(path))
        except OSError:
            _lib = None
        return _lib


def available() -> bool:
    return _load() is not None


def _u8(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _i32(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _u64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


def _paths_array(paths: Sequence[str]):
    arr = (ctypes.c_char_p * len(paths))()
    arr[:] = [os.fsencode(p) for p in paths]
    return arr


def blake3_digest(data: bytes) -> bytes:
    lib = _load()
    assert lib is not None
    out = np.zeros(32, dtype=np.uint8)
    buf = np.frombuffer(data, dtype=np.uint8) if data else \
        np.zeros(0, dtype=np.uint8)
    lib.sd_blake3(_u8(buf), len(data), _u8(out))
    return out.tobytes()


def blake3_many(payloads: np.ndarray, lens: np.ndarray,
                prefix_sizes: Optional[np.ndarray] = None,
                n_threads: int = 0) -> np.ndarray:
    """Hash each row of a dense [n, stride] uint8 array → [n, 32] digests.

    With `prefix_sizes`, row i hashes le64(prefix_sizes[i]) ‖ row bytes —
    the CAS-ID preimage (cas.rs:33).
    """
    lib = _load()
    assert lib is not None
    payloads = np.ascontiguousarray(payloads, dtype=np.uint8)
    n, stride = payloads.shape
    lens = np.ascontiguousarray(lens, dtype=np.int32)
    out = np.zeros((n, 32), dtype=np.uint8)
    pre = None
    if prefix_sizes is not None:
        pre = np.ascontiguousarray(prefix_sizes, dtype=np.uint64)
    lib.sd_blake3_many(
        n, _u8(payloads), stride, _i32(lens),
        _u64(pre) if pre is not None else None, _u8(out), n_threads)
    return out


def stage_large(paths: Sequence[str], sizes: np.ndarray,
                n_threads: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Sampled reads → ([n, 57344] uint8 payloads, [n] int32 status)."""
    lib = _load()
    assert lib is not None
    n = len(paths)
    sizes = np.ascontiguousarray(sizes, dtype=np.uint64)
    out = np.zeros((n, LARGE_PAYLOAD), dtype=np.uint8)
    status = np.zeros(n, dtype=np.int32)
    if n:
        lib.sd_stage_large(n, _paths_array(paths), _u64(sizes), _u8(out),
                           _i32(status), n_threads)
    return out, status


def stage_small(paths: Sequence[str], cap: int = SMALL_CAP,
                n_threads: int = 0
                ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Whole-file reads → ([n, cap+1] payloads, [n] lens, [n] status).

    The extra column lets the native side detect files that grew past the
    size class (ERR_GREW); callers slice [:, :cap].
    """
    lib = _load()
    assert lib is not None
    n = len(paths)
    out = np.zeros((n, cap + 1), dtype=np.uint8)
    lens = np.zeros(n, dtype=np.int32)
    status = np.zeros(n, dtype=np.int32)
    if n:
        lib.sd_stage_small(n, _paths_array(paths), cap, _u8(out),
                           _i32(lens), _i32(status), n_threads)
    return out, lens, status


def stage_batch(paths: Sequence[str], sizes: np.ndarray,
                out: np.ndarray, payload_cap: int,
                n_threads: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Packed batched staging into a CALLER-OWNED [n, stride] uint8
    buffer (a pooled, page-aligned ring page): row i becomes
    le64(size) ‖ payload ‖ zeros — build_cas_messages' exact layout,
    written by the C plane with no intermediate Python bytes objects.

    `out` must be C-contiguous with stride a 1024 multiple covering
    8 + payload_cap (plus the +1 grew-detection byte for small rows —
    the chunk grid always leaves >= 1016 bytes of padding, so any
    conforming grid qualifies). Returns ([n] int32 msg_lens — the
    kernel's `lengths` operand — and [n] int32 status); non-OK rows
    are scrubbed to their 8-byte prefix for per-file fallback at the
    staging seam."""
    lib = _load()
    assert lib is not None
    n = len(paths)
    if out.ndim != 2 or out.dtype != np.uint8 or out.shape[0] < n or \
            not out.flags.c_contiguous:
        # A real exception, not an assert: a mis-shaped buffer would
        # let the C writer scribble past the pooled page.
        raise ValueError(
            f"stage_batch: out must be C-contiguous uint8 [>= {n}, "
            f"stride], got {out.dtype} {out.shape}")
    stride = int(out.shape[1])
    if stride < 8 + int(payload_cap) + 1 or stride % 1024:
        raise ValueError(
            f"stage_batch: stride {stride} cannot hold the {payload_cap}"
            "-byte payload class (+ prefix and grew byte) on the chunk "
            "grid")
    sizes = np.ascontiguousarray(sizes, dtype=np.uint64)
    msg_lens = np.zeros(n, dtype=np.int32)
    status = np.zeros(n, dtype=np.int32)
    if n:
        lib.sd_stage_batch(n, _paths_array(paths), _u64(sizes), _u8(out),
                           stride, payload_cap, _i32(msg_lens),
                           _i32(status), n_threads)
    return msg_lens, status


def cas_digests(paths: Sequence[str], sizes: np.ndarray,
                n_threads: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Fused stage+hash: ([n, 32] digests, [n] status). ERR_EMPTY marks
    empty files (no CAS ID)."""
    lib = _load()
    assert lib is not None
    n = len(paths)
    sizes = np.ascontiguousarray(sizes, dtype=np.uint64)
    digests = np.zeros((n, 32), dtype=np.uint8)
    status = np.zeros(n, dtype=np.int32)
    if n:
        lib.sd_cas_digests(n, _paths_array(paths), _u64(sizes),
                           _u8(digests), _i32(status), n_threads)
    return digests, status


def checksum_files(paths: Sequence[str],
                   n_threads: int = 0,
                   sizes_hint: Optional[np.ndarray] = None,
                   ) -> Tuple[List[Optional[str]], np.ndarray]:
    """Full-file BLAKE3 checksums → ([n] hex-or-None, [n] status).

    `sizes_hint` (DB-known sizes) routes small files to the batched
    cross-file SIMD path without a stat sweep; it only partitions —
    stale hints re-route at read time, digests never depend on it."""
    lib = _load()
    assert lib is not None
    n = len(paths)
    digests = np.zeros((n, 32), dtype=np.uint8)
    status = np.zeros(n, dtype=np.int32)
    if n:
        hint = None
        if sizes_hint is not None:
            hint = _u64(np.ascontiguousarray(sizes_hint, dtype=np.uint64))
        lib.sd_checksum_files(n, _paths_array(paths), hint, _u8(digests),
                              _i32(status), n_threads)
    hexes: List[Optional[str]] = [
        digests[i].tobytes().hex() if status[i] == OK else None
        for i in range(n)
    ]
    return hexes, status


def encode_ops(timestamps, record_ids, kind: str, op_ids,
               values_packed) -> bytes:
    """Batched op-log blob encoding (sync/opblob.py format): n ops of
    one uniform `kind`, 16-byte record/op ids, values pre-packed per
    op. Returns the msgpack blob bytes — byte-identical to the Python
    fragment encoder (opblob.encode_uniform_py)."""
    lib = _load()
    assert lib is not None
    n = len(op_ids)
    if n == 0:
        return b"\x90"  # empty msgpack array
    ts = np.fromiter(timestamps, dtype=np.uint64, count=n)
    rids = np.frombuffer(b"".join(record_ids), dtype=np.uint8)
    oids = np.frombuffer(b"".join(op_ids), dtype=np.uint8)
    if rids.size != 16 * n or oids.size != 16 * n:
        # Same hardening as the cap check below: under `python -O` an
        # assert would vanish and the C encoder would read shifted
        # bytes, minting a structurally valid blob with WRONG record
        # ids — silent op-log corruption.
        raise ValueError(
            f"encode_ops: record/op ids must be 16 bytes each "
            f"(got {rids.size}/{oids.size} bytes for n={n})")
    vbuf = b"".join(values_packed)
    offs = np.zeros(n + 1, dtype=np.int64)
    np.cumsum([len(v) for v in values_packed], out=offs[1:])
    vals = (np.frombuffer(vbuf, dtype=np.uint8) if vbuf
            else np.zeros(1, dtype=np.uint8))
    kindb = kind.encode("utf-8")
    cap = 64 + n * (48 + len(kindb) + 70) + len(vbuf)
    out = np.zeros(cap, dtype=np.uint8)
    written = lib.sd_encode_ops(
        n, _u64(ts), _u8(rids), kindb, _u8(oids), _u8(vals),
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), _u8(out),
        cap)
    if written <= 0:
        # A real exception, not an assert: under `python -O` an assert
        # would vanish and a truncated garbage blob would land in the
        # op log — permanent sync corruption, not a crash.
        raise RuntimeError(
            f"sd_encode_ops: output buffer undersized (cap={cap}, n={n})")
    return out[:written].tobytes()


def _blob_entry_count(data: bytes) -> int:
    """Entry count from the blob's msgpack array header (the encoders
    emit exactly fixarray / array16 / array32)."""
    if not data:
        raise ValueError("decode_ops: empty blob")
    t = data[0]
    if t & 0xF0 == 0x90:
        return t & 0x0F
    if t == 0xDC:
        return int.from_bytes(data[1:3], "big")
    if t == 0xDD:
        return int.from_bytes(data[1:5], "big")
    raise ValueError(f"decode_ops: not an op blob (leading byte {t:#x})")


def decode_ops(data: bytes):
    """Batched blob decode (sync/opblob.py format): one C call parses a
    whole shared_op_blob page into dense offset arrays over `data` —
    no per-op msgpack objects. Returns
    (n, ts, rid_off, rid_len, kind_off, kind_len, payload_off,
    payload_len, opid_off, values_off, values_len, flags) where flags
    bit0 marks a uniform bulk payload (opid/values offsets valid) and
    bit1 the update shape. Raises ValueError on malformed input —
    callers (opblob.decode_entries) fall back to the Python decoder."""
    lib = _load()
    assert lib is not None
    n = _blob_entry_count(data)
    if 7 * n > len(data):
        # The header's count is WIRE-CONTROLLED (a blob_page frame from
        # a paired peer): allocating the offset arrays before this
        # check would let a 5-byte b"\xdd\xff\xff\xff\xff" frame force
        # tens of GB of np.zeros. Every real entry costs ≥7 bytes
        # (fixarray4 + ts + empty bin rid + empty fixstr + empty bin).
        raise ValueError(
            f"decode_ops: header claims {n} entries in {len(data)} bytes")
    buf = (np.frombuffer(data, dtype=np.uint8) if data
           else np.zeros(1, dtype=np.uint8))
    ts = np.zeros(max(n, 1), dtype=np.uint64)
    rid_off = np.zeros(max(n, 1), dtype=np.int64)
    rid_len = np.zeros(max(n, 1), dtype=np.int32)
    kind_off = np.zeros(max(n, 1), dtype=np.int64)
    kind_len = np.zeros(max(n, 1), dtype=np.int32)
    payload_off = np.zeros(max(n, 1), dtype=np.int64)
    payload_len = np.zeros(max(n, 1), dtype=np.int64)
    opid_off = np.zeros(max(n, 1), dtype=np.int64)
    values_off = np.zeros(max(n, 1), dtype=np.int64)
    values_len = np.zeros(max(n, 1), dtype=np.int64)
    flags = np.zeros(max(n, 1), dtype=np.uint8)
    i64 = ctypes.POINTER(ctypes.c_int64)
    got = lib.sd_decode_ops(
        _u8(buf), len(data), n, _u64(ts),
        rid_off.ctypes.data_as(i64), _i32(rid_len),
        kind_off.ctypes.data_as(i64), _i32(kind_len),
        payload_off.ctypes.data_as(i64),
        payload_len.ctypes.data_as(i64),
        opid_off.ctypes.data_as(i64), values_off.ctypes.data_as(i64),
        values_len.ctypes.data_as(i64), _u8(flags))
    if got != n:
        # A real exception (never an assert — see encode_ops): a
        # malformed page must route to the tolerant Python decoder,
        # not yield a truncated op stream.
        raise ValueError(f"sd_decode_ops: malformed blob (rc={got})")
    return (n, ts, rid_off, rid_len, kind_off, kind_len, payload_off,
            payload_len, opid_off, values_off, values_len, flags)


def secure_erase(path: str, passes: int = 1) -> None:
    lib = _load()
    assert lib is not None
    rc = lib.sd_secure_erase(os.fsencode(path), passes)
    if rc != OK:
        raise OSError(
            f"secure_erase({path!r}): "
            f"{STATUS_MESSAGES.get(rc, f'status {rc}')}")
