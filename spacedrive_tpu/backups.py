"""Library backup/restore.

Mirrors the reference's backups API
(/root/reference/core/src/api/backups.rs:127-350): synchronous archive of
the library DB + config into `<data_dir>/backups/<backup_id>`, with a
header identifying (backup_id, timestamp, library_id, library_name). The
reference writes a custom binary header + zstd stream; here it is a zip
with a manifest.json — same information, stdlib container.
"""

from __future__ import annotations

import json
import os
import time
import uuid as uuidlib
import zipfile
from typing import Dict, List

from . import persist


def backups_dir(data_dir: str) -> str:
    d = os.path.join(data_dir, "backups")
    os.makedirs(d, exist_ok=True)
    return d


def do_backup(node, library) -> str:
    """Create a backup; returns backup_id."""
    backup_id = str(uuidlib.uuid4())
    path = os.path.join(backups_dir(node.data_dir), f"{backup_id}.bak")
    # Checkpoint WAL so the main DB file is complete.
    library.db.checkpoint()
    manifest = {
        "id": backup_id,
        "timestamp": int(time.time()),
        "library_id": str(library.id),
        "library_name": library.config.name,
    }
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("manifest.json", json.dumps(manifest))
        z.write(library.db.path, "library.db")
        z.write(library.config_path, "library.sdlibrary")
    return backup_id


def list_backups(node) -> List[Dict]:
    out = []
    d = backups_dir(node.data_dir)
    for name in sorted(os.listdir(d)):
        if not name.endswith(".bak"):
            continue
        p = os.path.join(d, name)
        try:
            with zipfile.ZipFile(p) as z:
                manifest = json.loads(z.read("manifest.json"))
        except (OSError, zipfile.BadZipFile, KeyError, ValueError):
            continue
        manifest["path"] = p
        out.append(manifest)
    return out


def delete_backup(node, backup_id: str) -> bool:
    p = os.path.join(backups_dir(node.data_dir), f"{backup_id}.bak")
    if os.path.exists(p):
        os.remove(p)
        return True
    return False


def restore_backup(node, backup_id: str) -> str:
    """Restore a backup into the libraries dir (overwrites the library's
    DB + config); returns the library id. The library is reloaded."""
    p = os.path.join(backups_dir(node.data_dir), f"{backup_id}.bak")
    with zipfile.ZipFile(p) as z:
        manifest = json.loads(z.read("manifest.json"))
        lib_id = uuidlib.UUID(manifest["library_id"])
        lib = node.libraries.get(lib_id)
        if lib is not None:
            lib.db.close()
            node.libraries.libraries.pop(lib_id, None)
        base = node.libraries.dir
        db_path = os.path.join(base, f"{lib_id}.db")
        for suffix in ("-wal", "-shm"):
            stale = db_path + suffix
            if os.path.exists(stale):
                os.remove(stale)
        # Two durable artifacts land here; restore is idempotent from
        # the zip and ordered db-before-config, so a crash between the
        # two never leaves a config pointing at an absent/old db that
        # a re-run can't fix.
        # sdlint: ok[crash-atomicity]
        persist.atomic_write("library.db_image", db_path,
                             z.read("library.db"))
        persist.atomic_write(
            "library.config",
            os.path.join(base, f"{lib_id}.sdlibrary"),
            z.read("library.sdlibrary"))
    node.libraries._load(lib_id)
    return str(lib_id)
