"""Volume detection: mounted filesystem enumeration.

Mirrors `get_volumes` (/root/reference/core/src/volume/mod.rs:101,241 —
sysinfo-based): enumerate mount points with capacity/availability,
filtering pseudo-filesystems. Linux implementation reads /proc/mounts +
statvfs (no sysinfo crate here).
"""

from __future__ import annotations

import os
from typing import Dict, List

_PSEUDO_FS = {
    "proc", "sysfs", "devtmpfs", "devpts", "tmpfs", "cgroup", "cgroup2",
    "pstore", "securityfs", "debugfs", "tracefs", "overlay", "squashfs",
    "fusectl", "configfs", "mqueue", "hugetlbfs", "bpf", "autofs",
    "binfmt_misc", "rpc_pipefs", "nsfs", "efivarfs", "ramfs",
}


def get_volumes() -> List[Dict]:
    """Enumerate real mounted volumes with capacity info."""
    volumes = []
    seen_mounts = set()
    try:
        with open("/proc/mounts") as f:
            lines = f.readlines()
    except OSError:
        lines = []
    for line in lines:
        parts = line.split()
        if len(parts) < 3:
            continue
        device, mount_point, fstype = parts[0], parts[1], parts[2]
        if fstype in _PSEUDO_FS or mount_point in seen_mounts:
            continue
        mount_point = mount_point.encode().decode("unicode_escape")
        try:
            st = os.statvfs(mount_point)
        except OSError:
            continue
        total = st.f_blocks * st.f_frsize
        if total == 0:
            continue
        seen_mounts.add(mount_point)
        volumes.append({
            "name": os.path.basename(device) or device,
            "mount_point": mount_point,
            "filesystem": fstype,
            "total_bytes_capacity": str(total),
            "total_bytes_available": str(st.f_bavail * st.f_frsize),
            "is_system": mount_point == "/",
            "disk_type": None,
        })
    return volumes


def save_volumes(db) -> int:
    """Upsert detected volumes into the @local volume table — one tx
    for the whole detection sweep (tx-shape: no tx per volume)."""
    vols = get_volumes()
    with db.write_tx() as conn:
        for v in vols:
            db.upsert(
                "volume",
                {"mount_point": v["mount_point"], "name": v["name"]},
                {
                    "filesystem": v["filesystem"],
                    "total_bytes_capacity": v["total_bytes_capacity"],
                    "total_bytes_available": v["total_bytes_available"],
                    "is_system": int(v["is_system"]),
                }, conn=conn)
    return len(vols)
