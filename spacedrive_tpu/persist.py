"""Declared persistence plane: every durable on-disk artifact, by name.

The engine's whole value is that its state survives: the library
database, the incident store, node/library configs, key material,
caches, and BENCH artifacts must recover after ANY crash. Before this
module each site hand-rolled (or skipped) the tmp → fsync → atomic-
replace idiom; now durability is a CONTRACT, the same registry shape
as timeouts.py / channels.py / chaos.py:

- `declare_artifact(name, path_pattern, kind, fsync, recovery)` at the
  bottom of this module declares every durable artifact: its dotted
  name, where it lives, its write discipline (`atomic` replace | `wal`
  promote-or-discard | `append` DB-rows | `scratch` always-removed),
  its fsync policy, and a one-line recovery story. README renders the
  inventory via `python -m tools.sdlint --artifact-table`.
- Product code writes BY NAME through `atomic_write()` /
  `wal_writer()` / `scratch()` / `seal()` / `db_write()`. sdlint's
  io-durability pass flags bare open-for-write, rename-without-tmp,
  replace-without-fsync, and undeclared/dynamic artifact names — the
  timeout-registry name rules pointed at the filesystem seam.
- Runtime twin (`arm()`, called by sanitize.install() unless
  `SDTPU_FS_AUDIT=off`): interposes `os.replace`/`os.fsync`, checks
  fsync-file → rename → fsync-dir ordering per declared policy, counts
  `sd_persist_writes_total{name}` / `sd_persist_fsync_seconds` /
  `sd_persist_violations_total{kind}`, and raises
  `persist_undeclared_write` / `persist_unfsynced_rename` in tier-1.
- Crash grid (`tools/crash_grid.py`): `crashpoint(name, edge)` fires
  between every two steps of a write; a child started with
  `SDTPU_PERSIST_CRASHPOINT=<name>:<edge>` SIGKILLs itself there, and
  the grid asserts every artifact recovers valid-or-absent at EVERY
  declared edge — systematically, not sampled. The same seam draws the
  declared `persist.crashpoint` chaos fault so SDTPU_CHAOS can widen
  any window with a delay.

Write path (atomic/wal), with its crashpoint edges:

    open  <path>.tmp            -- edge tmp-open      (empty tmp)
    write first half, flush     -- edge tmp-partial   (torn tmp)
    write rest, flush           -- edge tmp-full      (complete tmp)
    fsync(tmp)     [policy]     -- edge fsync-file
    os.replace(tmp, path)       -- edge renamed
    fsync(dir)     [always]     -- durable

Recovery: `recover(name, dir)` — `wal` promotes a complete, validated
tmp (fsyncing before the promote rename) and discards torn ones;
`atomic` discards all tmp residue. Every outcome is valid-or-absent;
a reader never sees a torn final file.
"""

from __future__ import annotations

import os
import shutil
import signal
import sys
import tempfile
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from . import chaos, flags
from .telemetry import (
    PERSIST_FSYNC_SECONDS,
    PERSIST_VIOLATIONS,
    PERSIST_WRITES,
)

__all__ = [
    "Artifact", "ARTIFACTS", "declare_artifact", "artifact",
    "atomic_write", "wal_writer", "scratch", "seal", "db_write",
    "recover", "edges_for", "crashpoint",
    "arm", "disarm", "armed", "artifact_table_markdown",
]

KINDS = ("atomic", "wal", "append", "scratch")
FSYNC_POLICIES = ("always", "file-only", "none", "delegated")

# The SIGKILL edges of one atomic/wal write, in firing order. Policy
# `none`/`file-only` writes skip the edges their policy skips —
# edges_for() is the authoritative per-artifact list the crash grid
# iterates.
_EDGES_FSYNC = ("tmp-open", "tmp-partial", "tmp-full", "fsync-file",
                "renamed")
_EDGES_NOSYNC = ("tmp-open", "tmp-partial", "tmp-full", "renamed")


@dataclass(frozen=True)
class Artifact:
    name: str          # dotted id: "<layer>.<what>"
    path_pattern: str  # where it lives (docs/table; not a glob)
    kind: str          # atomic | wal | append | scratch
    fsync: str         # always | file-only | none | delegated
    recovery: str      # one-line crash-recovery story


# Import-time declaration registry (same contract as TIMEOUTS /
# CHANNELS / FAULTS): bounded by the declarations at the bottom of
# this module, never by runtime traffic.
ARTIFACTS: Dict[str, Artifact] = {}  # sdlint: ok[unbounded-growth]


def declare_artifact(name: str, path_pattern: str, kind: str,
                     fsync: str, recovery: str) -> Artifact:
    if name in ARTIFACTS:
        raise ValueError(f"artifact {name!r} declared twice")
    if "." not in name or not all(
            p.replace("_", "a").isalnum() and p == p.lower()
            for p in name.split(".")):
        raise ValueError(f"artifact name {name!r}: want "
                         "dotted lower_snake segments")
    if kind not in KINDS:
        raise ValueError(f"artifact {name!r}: unknown kind {kind!r}")
    if fsync not in FSYNC_POLICIES:
        raise ValueError(f"artifact {name!r}: unknown fsync "
                         f"policy {fsync!r}")
    if (fsync == "delegated") != (kind == "append"):
        raise ValueError(f"artifact {name!r}: `delegated` fsync is "
                         "for (and only for) DB-backed `append` "
                         "artifacts — SQLite owns their durability")
    if not recovery.strip():
        raise ValueError(f"artifact {name!r}: empty recovery story")
    a = Artifact(name, path_pattern, kind, fsync, recovery)
    ARTIFACTS[name] = a
    return a


def artifact(name: str) -> Artifact:
    a = ARTIFACTS.get(name)
    if a is None:
        raise KeyError(f"undeclared artifact {name!r} (declare it in "
                       "spacedrive_tpu/persist.py)")
    return a


def edges_for(name: str) -> Tuple[str, ...]:
    """The crashpoint edges one write of `name` passes, in order —
    what tools/crash_grid.py SIGKILLs at, one child per edge."""
    a = artifact(name)
    if a.kind in ("append", "scratch"):
        return ()  # DB rows (SQLite WAL) / always-removed scratch
    if a.fsync in ("always", "file-only"):
        return _EDGES_FSYNC
    return _EDGES_NOSYNC


# -- crashpoint seam ---------------------------------------------------------

def crashpoint(name: str, edge: str) -> None:
    """One declared durability edge: draws the `persist.crashpoint`
    chaos fault (a delay widens the window for racing killers), then
    SIGKILLs this process when `SDTPU_PERSIST_CRASHPOINT` names this
    exact `<artifact>:<edge>` — how crash-grid children die at every
    edge systematically. No-ops in normal operation."""
    fault = chaos.hit("persist.crashpoint", only=("delay",))
    if fault is not None:
        chaos.apply_sync(fault)
    spec = flags.get("SDTPU_PERSIST_CRASHPOINT")
    if spec and spec == f"{name}:{edge}":
        os.kill(os.getpid(), signal.SIGKILL)


# -- write-context bookkeeping (the auditor's TLS seam) ----------------------

_tls = threading.local()


def _write_stack() -> List[Artifact]:
    stack = getattr(_tls, "writes", None)
    if stack is None:
        stack = []
        _tls.writes = stack
    return stack


@contextmanager
def _writing(a: Artifact) -> Iterator[None]:
    stack = _write_stack()
    stack.append(a)
    try:
        yield
    finally:
        stack.pop()


def _current_write() -> Optional[Artifact]:
    stack = _write_stack()
    return stack[-1] if stack else None


def _timed_fsync(fd: int) -> None:
    t0 = time.perf_counter()
    os.fsync(fd)
    PERSIST_FSYNC_SECONDS.observe(time.perf_counter() - t0)


def _fsync_dir(path: str) -> None:
    """Directory-entry durability for a just-renamed artifact: without
    this the rename itself can vanish at power loss even though the
    file's bytes were fsynced."""
    try:
        fd = os.open(path or ".", os.O_RDONLY)
    except OSError:
        return  # platforms/filesystems without dir-open semantics
    try:
        _timed_fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


# -- writers -----------------------------------------------------------------

def _write_bytes(a: Artifact, path: str, data: bytes,
                 chaos_point: Optional[Callable[[str], None]]) -> None:
    tmp = path + ".tmp"
    half = len(data) // 2
    # The seam itself is the one sanctioned bare writer.
    with open(tmp, "wb") as f:  # sdlint: ok[io-durability]
        crashpoint(a.name, "tmp-open")
        f.write(data[:half])
        f.flush()
        if chaos_point is not None:
            chaos_point("tmp-partial")   # the caller's torn-tmp window
        crashpoint(a.name, "tmp-partial")
        f.write(data[half:])
        f.flush()
        crashpoint(a.name, "tmp-full")
        if a.fsync in ("always", "file-only"):
            _timed_fsync(f.fileno())
            crashpoint(a.name, "fsync-file")
    if chaos_point is not None:
        chaos_point("pre-rename")        # the complete-tmp window
    os.replace(tmp, path)
    crashpoint(a.name, "renamed")
    if a.fsync == "always":
        _fsync_dir(os.path.dirname(path))
    PERSIST_WRITES.labels(name=a.name).inc()


def atomic_write(name: str, path: str, data,
                 chaos_point: Optional[Callable[[str], None]] = None
                 ) -> str:
    """Write `data` (bytes or str) durably to `path` under artifact
    `name`'s declared policy: same-dir tmp → flush → fsync(file) →
    atomic replace → fsync(dir). `chaos_point(edge)` is the caller's
    hook into the torn-tmp (`tmp-partial`) and complete-tmp
    (`pre-rename`) windows — how incidents.py keeps its declared
    `incidents.write` delay seam inside the shared writer."""
    a = artifact(name)
    if a.kind not in ("atomic", "wal"):
        raise ValueError(f"artifact {name!r} is kind={a.kind!r}; "
                         "atomic_write serves atomic|wal artifacts")
    if isinstance(data, str):
        data = data.encode("utf-8")
    with _writing(a):
        _write_bytes(a, path, data, chaos_point)
    return path


@contextmanager
def wal_writer(name: str) -> Iterator[Callable[..., str]]:
    """Record writer for a `wal` artifact: yields
    `write(path, data, chaos_point=None)` with the same tmp → fsync →
    rename discipline as atomic_write, under the WAL recovery contract
    (a complete tmp left by a crash is PROMOTED by recover(), a torn
    one discarded)."""
    a = artifact(name)
    if a.kind != "wal":
        raise ValueError(f"artifact {name!r} is kind={a.kind!r}; "
                         "wal_writer serves wal artifacts")

    def write(path: str, data,
              chaos_point: Optional[Callable[[str], None]] = None
              ) -> str:
        if isinstance(data, str):
            data = data.encode("utf-8")
        with _writing(a):
            _write_bytes(a, path, data, chaos_point)
        return path

    yield write


@contextmanager
def scratch(name: str, dir: Optional[str] = None,
            keep: Optional[str] = None) -> Iterator[str]:
    """A declared scratch tree: yields a fresh private directory and
    ALWAYS removes it on exit — success, failure, or sanitizer raise —
    the tmp-hygiene contract as an API instead of a per-tool finally.
    `keep` short-circuits to a caller-owned path that survives (bench
    --keep flows)."""
    a = artifact(name)
    if a.kind != "scratch":
        raise ValueError(f"artifact {name!r} is kind={a.kind!r}; "
                         "scratch serves scratch artifacts")
    if keep:
        os.makedirs(keep, exist_ok=True)
        PERSIST_WRITES.labels(name=name).inc()
        yield keep
        return
    path = tempfile.mkdtemp(prefix=name.replace(".", "-") + "-",
                            dir=dir)
    PERSIST_WRITES.labels(name=name).inc()
    try:
        yield path
    finally:
        shutil.rmtree(path, ignore_errors=True)


def seal(name: str, tmp_path: str, final_path: str) -> str:
    """Seal a STREAMED body: the caller wrote `tmp_path` incrementally
    (multi-GB encrypt/transcode outputs that cannot buffer in memory);
    this applies the declared tail — fsync(file) per policy → atomic
    replace → fsync(dir) — so a crash never leaves a truncated file
    that passes for a valid artifact."""
    a = artifact(name)
    if a.kind != "atomic":
        raise ValueError(f"artifact {name!r} is kind={a.kind!r}; "
                         "seal serves atomic artifacts")
    with _writing(a):
        if a.fsync in ("always", "file-only"):
            fd = os.open(tmp_path, os.O_RDONLY)
            try:
                _timed_fsync(fd)
            finally:
                os.close(fd)
            crashpoint(a.name, "fsync-file")
        os.replace(tmp_path, final_path)
        crashpoint(a.name, "renamed")
        if a.fsync == "always":
            _fsync_dir(os.path.dirname(final_path))
    PERSIST_WRITES.labels(name=a.name).inc()
    return final_path


def db_write(name: str, rows: int = 1) -> None:
    """Record a commit of a DB-backed `append` artifact (job-scratch
    spool rows and kin). Durability is DELEGATED to SQLite's WAL (the
    group-commit actor's kill -9 storm proves it); this seam gives the
    artifact a declared name, a row in the table, and write counts."""
    a = artifact(name)
    if a.kind != "append":
        raise ValueError(f"artifact {name!r} is kind={a.kind!r}; "
                         "db_write serves append artifacts")
    PERSIST_WRITES.labels(name=a.name).inc(max(1, rows))


def recover(name: str, directory: str,
            validate: Optional[Callable[[bytes], bool]] = None
            ) -> List[Tuple[str, str]]:
    """Next-boot sweep of `directory` for artifact `name`'s tmp
    residue. Returns [(path, outcome)]: `wal` artifacts promote a
    complete tmp whose bytes pass `validate` (fsyncing BEFORE the
    promote rename — the promoted content must be durable too) and
    discard the rest; `atomic` artifacts discard all residue (the
    final file is already old-or-new, never torn). Promoted paths are
    the final (renamed) names."""
    a = artifact(name)
    out: List[Tuple[str, str]] = []
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    with _writing(a):
        for fn in names:
            if not fn.endswith(".tmp"):
                continue
            path = os.path.join(directory, fn)
            final = path[:-len(".tmp")]
            promoted = False
            if a.kind == "wal" and validate is not None:
                try:
                    with open(path, "rb") as f:
                        raw = f.read()
                    if validate(raw):
                        fd = os.open(path, os.O_RDONLY)
                        try:
                            _timed_fsync(fd)
                        finally:
                            os.close(fd)
                        os.replace(path, final)
                        _fsync_dir(directory)
                        promoted = True
                except (OSError, ValueError):
                    promoted = False
            if promoted:
                out.append((final, "promoted"))
            else:
                try:
                    os.unlink(path)
                except OSError:
                    pass
                out.append((path, "discarded"))
    return out


# -- runtime twin: the fs auditor -------------------------------------------

_armed = False
_mode = "count"
_recorder: Optional[Callable[[str, str, bool], None]] = None
_orig_replace: Optional[Callable[..., Any]] = None
_orig_fsync: Optional[Callable[..., Any]] = None

# (st_dev, st_ino) of recently-fsynced files, insertion-ordered.
# Bounded: the auditor's memory of "this inode was fsynced" only has
# to outlive the fsync → rename gap of in-flight writes.
_FSYNCED_CAP = 512
_fsynced: Dict[Tuple[int, int], bool] = {}
_fsynced_lock = threading.Lock()

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_SELF_FILE = os.path.abspath(__file__)


def armed() -> bool:
    return _armed


def _report(kind: str, detail: str) -> None:
    PERSIST_VIOLATIONS.labels(kind=kind).inc()
    rec = _recorder
    if rec is not None:
        rec(kind, detail, True)  # raise-mode surfaces at the call site


def _note_fsynced(fd: int) -> None:
    try:
        st = os.fstat(fd)
    except OSError:
        return
    with _fsynced_lock:
        _fsynced[(st.st_dev, st.st_ino)] = True
        while len(_fsynced) > _FSYNCED_CAP:
            del _fsynced[next(iter(_fsynced))]


def _was_fsynced(path: str) -> bool:
    try:
        st = os.stat(path)
    except OSError:
        return True  # already renamed/raced away: nothing to judge
    with _fsynced_lock:
        return (st.st_dev, st.st_ino) in _fsynced


def _audited_fsync(fd):
    _note_fsynced(fd)
    return _orig_fsync(fd)


def _audited_replace(src, dst, *, src_dir_fd=None, dst_dir_fd=None):
    ctx = _current_write()
    if ctx is not None:
        # Inside the persist seam: verify the declared fsync-before-
        # rename ordering actually happened (belt and braces over the
        # writer's own code path — a policy regression fails tier-1).
        if ctx.fsync in ("always", "file-only") and \
                not _was_fsynced(os.fspath(src)):
            _report(
                "persist_unfsynced_rename",
                f"artifact {ctx.name!r}: rename of {src!r} with no "
                f"preceding fsync (declared policy {ctx.fsync!r})")
    else:
        # Raw os.replace from a product module is an undeclared
        # durable write — route it through the persist registry.
        caller = sys._getframe(1).f_code.co_filename
        try:
            caller = os.path.abspath(caller)
        except (OSError, ValueError):
            caller = ""
        if caller.startswith(_PKG_DIR + os.sep) and \
                caller != _SELF_FILE:
            rel = os.path.relpath(caller, os.path.dirname(_PKG_DIR))
            _report(
                "persist_undeclared_write",
                f"raw os.replace({os.fspath(src)!r} -> "
                f"{os.fspath(dst)!r}) from {rel} outside the persist "
                "seam — declare the artifact and write it by name")
    return _orig_replace(src, dst, src_dir_fd=src_dir_fd,
                         dst_dir_fd=dst_dir_fd)


def arm(mode: str, record: Callable[[str, str, bool], None]) -> None:
    """Interpose os.replace/os.fsync (sanitize.install() calls this
    unless SDTPU_FS_AUDIT=off). Violations flow through `record` into
    the sanitizer's shared list/counter and raise in raise mode."""
    global _armed, _mode, _recorder, _orig_replace, _orig_fsync
    if _armed:
        return
    if flags.get("SDTPU_FS_AUDIT") == "off":
        return
    _mode = mode
    _recorder = record
    _orig_replace = os.replace
    _orig_fsync = os.fsync
    os.replace = _audited_replace
    os.fsync = _audited_fsync
    _armed = True


def disarm() -> None:
    global _armed, _recorder, _orig_replace, _orig_fsync
    if not _armed:
        return
    if _orig_replace is not None:
        os.replace = _orig_replace
        _orig_replace = None
    if _orig_fsync is not None:
        os.fsync = _orig_fsync
        _orig_fsync = None
    _recorder = None
    with _fsynced_lock:
        _fsynced.clear()
    _armed = False


# -- docs --------------------------------------------------------------------

def artifact_table_markdown() -> str:
    """README's generated durable-artifact inventory (the flag/
    timeout/channel/statement table idiom): one row per declared
    artifact, straight from the registry."""
    lines = [
        "| artifact | path | kind | fsync | recovery |",
        "|---|---|---|---|---|",
    ]
    for name in sorted(ARTIFACTS):
        a = ARTIFACTS[name]
        lines.append(
            f"| `{a.name}` | `{a.path_pattern}` | {a.kind} | "
            f"{a.fsync} | {a.recovery} |")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# The artifact inventory — THE durable-state namespace. Every durable
# write anywhere in the engine names one of these; sdlint's
# io-durability pass fails on undeclared or dynamic names, and
# tests/test_persist.py's drift check fails on a declared artifact
# nothing writes (or a write site naming an undeclared artifact).
# tools/crash_grid.py SIGKILLs a child at every edges_for() edge of
# every atomic/wal row and asserts valid-or-absent recovery.
# ---------------------------------------------------------------------------

declare_artifact(
    "incidents.bundle", "incidents/<id>.json", "wal", "always",
    "Complete `.json.tmp` promoted at next boot (schema-validated), "
    "torn tmp discarded; a reader never sees a torn final bundle "
    "(incidents.py _recover).")

declare_artifact(
    "incidents.marker", "incidents/.running", "atomic", "none",
    "Presence after a crash IS the signal (becomes the `crash` "
    "bundle); removed by orderly close(). Torn/absent marker reads "
    "as a clean exit — advisory, so no fsync cost per boot.")

declare_artifact(
    "library.config", "libraries/<uuid>.sdlibrary", "atomic",
    "always",
    "Old-or-new after any crash (atomic replace); load parses the "
    "surviving JSON, tmp residue is ignored by the `*.sdlibrary` "
    "load filter and swept by recover().")

declare_artifact(
    "library.db_image", "libraries/<uuid>.db (backup restore)",
    "atomic", "always",
    "Backup restore is re-runnable from the zip: a crashed restore "
    "leaves old-or-new db bytes, never torn; restore order (db "
    "before config) means a config never points at an absent db.")

declare_artifact(
    "node.config", "node_state.sdconfig", "atomic", "always",
    "Old-or-new after any crash; Node boot re-reads the surviving "
    "JSON and regenerates defaults when absent.")

declare_artifact(
    "crypto.keyring", "keys.json", "atomic", "always",
    "Old-or-new after any crash — key material must never tear; a "
    "lost most-recent write re-enrolls the key, a torn file would "
    "lose the whole ring.")

declare_artifact(
    "media.thumbnail", "thumbnails/<shard>/<cas_id>.webp", "atomic",
    "none",
    "Regenerable cache: absent → re-encoded on demand; atomic "
    "replace keeps readers off torn webp bytes; no fsync (a power "
    "loss costs a re-encode, not correctness).")

declare_artifact(
    "media.thumbs_version", "thumbnails/version.txt", "atomic",
    "none",
    "Cache-format version stamp; absent → rewritten at next "
    "ensure_thumbnail_dir, mismatched → cache regenerated.")

declare_artifact(
    "object.sealed", "<target>.part -> <target> (.sdtpu seal)",
    "atomic", "always",
    "Streamed encrypt output sealed by fsync + rename: a crash "
    "leaves the `.part` (removed by the job's error path / re-run), "
    "never a truncated file that passes for a valid .sdtpu.")

declare_artifact(
    "stage.h2d_cache", "<cache_dir>/h2d_probe.json", "atomic",
    "none",
    "Link-probe cache: stale/torn/absent → re-probe (~ms); key "
    "mismatch is already a re-probe, so crash loss is free.")

declare_artifact(
    "flight.trace", "<--trace out>.json (chrome trace)", "atomic",
    "none",
    "Bench artifact: re-run the bench; atomic replace means "
    "chrome://tracing and trace_export never read torn JSON.")

declare_artifact(
    "bench.artifact", "<--json out> (BENCH result doc)", "atomic",
    "none",
    "Bench artifact: re-run the bench; atomic replace means "
    "bench_trend.py never chokes on a torn half-JSON from a crashed "
    "run.")

declare_artifact(
    "bench.workdir", "$TMPDIR/bench-workdir-* (scratch tree)",
    "scratch", "none",
    "Always removed on exit (success OR failure) by scratch(); a "
    "surviving tree is a tmp-hygiene violation, not state.")

declare_artifact(
    "job.scratch", "libraries/<uuid>.db `job_scratch` rows",
    "append", "delegated",
    "SQLite WAL owns durability (group-commit kill -9 storm proves "
    "it): spooled rows land all-or-nothing per tx; resume consumes "
    "surviving rows, unspool deletes them.")
