"""Flight recorder: pipeline timeline capture + Chrome-trace export.

The observability gap this closes (ROADMAP item 1's evidence problem):
when an identify run misses its computed bound, the aggregate
`sd_pipeline_*` counters say THAT time was lost, never WHERE — which
batch, which device stream, which stage. The recorder keeps a bounded
per-batch timeline of the depth-N pipeline (ops/overlap.py) and the
host hashing planes (ops/staging.py): one event per
stage/H2D/kernel/retire phase with begin-end wall timestamps, device
and stream labels, and the owning trace id — plus one `window` event
per retired batch carrying **bound attribution**: which of
max(t_stage, t_h2d, t_kernel) was binding for that batch and by how
much.

Storage is a declared registry channel (`ops.pipeline.timeline`,
shed_oldest — history ages out, memory never grows with uptime),
written from the per-device dispatch executor threads and the pipeline
coroutines under the recorder's lock; the ownership contract is
declared in threadctx.py (`flight.FlightRecorder`) so the race
recorder audits every write in tier-1.

`chrome_trace()` turns the span ring (tracing.py) plus this timeline
into a Chrome-trace/Perfetto `traceEvents` JSON document —
per-device stage/H2D/kernel/retire lanes, span lanes grouped by trace
id, `M` metadata naming every pid/tid — and `validate_chrome_trace()`
is the schema gate: `tools/trace_export.py --json` self-checks through
it in tier-1, the `node.trace.export` rspc route serves it from a live
node, and `overlap_bench --trace` / `perf_smoke --trace` ship it next
to their BENCH artifacts. `fleet_chrome_trace()` is the multi-node
composition (fleet.py distributed trace assembly): N nodes' captures
as per-node pid-lane pairs on one skew-aligned axis, offsets recorded
in the document metadata, behind `fleet.trace.export` and
`tools/trace_export.py --fleet`.

Design constraints: stdlib + channels/telemetry/tracing only — every
layer (ops executors, benches, the API host) can import it without
cycles and without jax.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Dict, List, Optional, Tuple

from . import channels, tracing
from .telemetry import TRACE_TIMELINE_EVENTS

__all__ = [
    "FlightRecorder", "RECORDER", "LANES", "chrome_trace",
    "fleet_chrome_trace", "validate_chrome_trace",
]

# The pipeline phases one batch moves through, in order. `window` is
# the synthetic fifth lane: emitted when a batch's `retire` lands,
# carrying the batch's bound attribution.
LANES = ("stage", "h2d", "kernel", "retire")

# The three components the steady-state bound maximizes over
# (PipelineStats.bound_files_per_sec) — per-batch attribution names
# the binding one.
_BOUND_COMPONENTS = ("stage", "h2d", "kernel")

# Open-window safety cap: a run that dies mid-batch (or a caller that
# records phases but never a retire) must not leak entries — past the
# cap the oldest open window is dropped, not the recorder's memory
# contract. Bounded well above any real in-flight depth (ring depth
# caps at MAX_PIPELINE_DEPTH = 8 per run).
_OPEN_CAP = 64

# Run tokens disambiguate concurrent/successive pipeline runs whose
# batch NUMBERING overlaps (two identifier jobs both dispatch a
# "batch 3"; a trace id is not enough — one job's trace covers every
# run it starts). new_run_token() is what run_overlapped threads
# through its records.
_RUN_SEQ = itertools.count(1)


def new_run_token() -> int:
    """Fresh per-run id for record(..., run=token): keeps one run's
    open batch windows from colliding with another's."""
    return next(_RUN_SEQ)


class FlightRecorder:
    """Bounded per-batch pipeline timeline.

    Writers are the per-device dispatch executor threads, the retire
    executor thread, and the pipeline's private-loop coroutines —
    every mutation runs under `_lock` (contract declared in
    threadctx.py). Events are JSON-safe dicts; the ring is the
    declared `ops.pipeline.timeline` channel, so capacity scales with
    SDTPU_CHAN_SCALE and shed counts surface as
    sd_chan_shed_total{ops.pipeline.timeline}.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.ring = channels.channel("ops.pipeline.timeline")
        # (scope, run, batch) -> lane -> (t0_perf, t1_perf): the open
        # batch windows awaiting their retire event. Entries leave at
        # retire, and a window is only OPENED when the caller passes a
        # run token (the pipeline loop, which always retires) — scopes
        # that never emit a retire (identify host-plane chunks) are
        # pure lane events, so they cannot accumulate here. Capped at
        # _OPEN_CAP as the crashed-run backstop.
        self._open: Dict[Tuple[str, int, int],
                         Dict[str, Tuple[float, float]]] = {}

    def record(self, lane: str, batch: int, t0: float, t1: float,
               device: str = "", stream: int = 0,
               trace: Optional[str] = None, scope: str = "pipeline",
               run: Optional[int] = None, **fields: Any) -> None:
        """One phase of one batch: [t0, t1) perf_counter readings from
        the thread that ran the phase. With a `run` token
        (new_run_token(); the pipeline loop passes one), phases
        accumulate into the (scope, run, batch) window and `retire`
        closes it, emitting the bound-attribution event; without one
        the event is a bare lane entry."""
        ev = {
            "lane": lane, "batch": int(batch), "scope": scope,
            "device": str(device), "stream": int(stream),
            "ts_us": tracing.perf_to_us(t0),
            "dur_us": max(0, int((t1 - t0) * 1e6)),
        }
        if trace:
            ev["trace"] = trace
        ev.update(fields)
        TRACE_TIMELINE_EVENTS.inc()
        with self._lock:
            self.ring.put_nowait(ev)
            if run is None:
                return
            key = (scope, int(run), int(batch))
            if lane in _BOUND_COMPONENTS:
                entry = self._open.setdefault(key, {})
                entry[lane] = (t0, t1)
                if device:
                    # The batch's device stream (its h2d/kernel phases
                    # carry it; stage/retire run off-device): the
                    # window event inherits it so bound attribution
                    # names WHICH stream was bound, per device lane.
                    entry["_dev"] = (str(device), int(stream))
                while len(self._open) > _OPEN_CAP:
                    # Crashed-run backstop: drop the OLDEST open
                    # window (dict preserves insertion order) rather
                    # than grow with abandoned batches.
                    self._open.pop(next(iter(self._open)))
            elif lane == "retire":
                phases = self._open.pop(key, {})
                phases["retire"] = (t0, t1)
                win = self._window_event(ev, phases)
                if win is not None:
                    TRACE_TIMELINE_EVENTS.inc()
                    self.ring.put_nowait(win)

    @staticmethod
    def _window_event(retire_ev: Dict[str, Any],
                      phases: Dict[str, Tuple[float, float]]
                      ) -> Optional[Dict[str, Any]]:
        """Bound attribution for one retired batch: which of
        max(t_stage, t_h2d, t_kernel) bound it, and by how much over
        the runner-up (the margin a perfect pipeline of this shape
        cannot hide)."""
        dev, stream = phases.pop("_dev", (retire_ev["device"],
                                          retire_ev["stream"]))
        durs = {lane: t1 - t0 for lane, (t0, t1) in phases.items()}
        comps = [(durs.get(lane, 0.0), lane)
                 for lane in _BOUND_COMPONENTS]
        comps.sort(reverse=True)
        (best, binding), (second, _) = comps[0], comps[1]
        if best <= 0.0:
            return None  # phases never recorded (partial run)
        t0 = min(t0 for t0, _ in phases.values())
        t1 = max(t1 for _, t1 in phases.values())
        win = {
            "lane": "window", "batch": retire_ev["batch"],
            "scope": retire_ev["scope"], "device": dev,
            "stream": stream,
            "ts_us": tracing.perf_to_us(t0),
            "dur_us": max(0, int((t1 - t0) * 1e6)),
            "binding": binding,
            "margin_us": max(0, int((best - second) * 1e6)),
            "phases_us": {lane: int(d * 1e6)
                          for lane, d in sorted(durs.items())},
        }
        if "trace" in retire_ev:
            win["trace"] = retire_ev["trace"]
        return win

    def snapshot(self) -> List[Dict[str, Any]]:
        """Oldest-first copy of the ring (JSON-safe; what
        node.trace.export and the benches export)."""
        with self._lock:
            return [dict(ev) for ev in self.ring]

    def clear(self) -> None:
        """Test/bench hook: empty the ring and drop open windows."""
        with self._lock:
            while True:
                try:
                    self.ring.get_nowait()
                except Exception:
                    break
            self._open.clear()


# THE process-wide recorder (the pipeline writes here; multiple
# concurrent runs interleave by design — events carry their trace id).
RECORDER = FlightRecorder()


# -- Chrome-trace export ----------------------------------------------------
#
# Event shapes emitted (the trace-event format's stable core):
#   {"ph": "M", "name": "process_name"|"thread_name", "pid", ["tid"],
#    "args": {"name": ...}}                       — lane naming
#   {"ph": "X", "name", "ts", "dur", "pid", "tid", "args": {...}}
#                                                  — complete events
# ts/dur are microseconds; events are sorted by ts (metadata first) so
# validate_chrome_trace can assert monotonicity, which chrome://tracing
# and Perfetto both accept directly.

PID_SPANS = 1
PID_TIMELINE = 2


def _timeline_tid_name(ev: Dict[str, Any]) -> str:
    """Lane naming: per-device h2d/kernel streams, per-worker stage
    lanes, one retire lane, one window (bound-attribution) lane — the
    'per-device stage/H2D/kernel/retire lanes' the export promises."""
    lane = ev.get("lane", "?")
    dev = ev.get("device", "")
    scope = ev.get("scope", "pipeline")
    prefix = "" if scope == "pipeline" else f"{scope} "
    if dev:
        # Pipeline devices are jax device ids ("0"); identify-scope
        # events carry the backend name instead.
        dev_label = f"dev{dev}" if scope == "pipeline" else dev
        return f"{prefix}{dev_label} {lane}"
    if lane == "stage":
        return f"{prefix}stage/w{ev.get('stream', 0)}"
    return f"{prefix}{lane}"


def _node_trace_events(spans: List[Dict[str, Any]],
                       timeline: List[Dict[str, Any]],
                       node_name: str, pid_spans: int, pid_timeline: int,
                       shift_us: int = 0
                       ) -> Tuple[List[Dict[str, Any]],
                                  List[Dict[str, Any]]]:
    """One node's (meta, events) pair: span lanes under `pid_spans`,
    timeline lanes under `pid_timeline`, every timestamp shifted by
    `shift_us` (how the fleet merger aligns a remote node's wall clock
    onto the assembling node's axis; 0 for the local export)."""
    events: List[Dict[str, Any]] = []
    meta: List[Dict[str, Any]] = [
        {"ph": "M", "name": "process_name", "pid": pid_spans, "ts": 0,
         "args": {"name": f"{node_name}: spans"}},
        {"ph": "M", "name": "process_name", "pid": pid_timeline, "ts": 0,
         "args": {"name": f"{node_name}: pipeline timeline"}},
    ]

    # Span lanes: one tid per trace id, in order of first appearance —
    # a cross-node trace's local spans line up in one lane.
    trace_tids: Dict[str, int] = {}
    for rec in spans:
        if "ts_us" not in rec:
            continue  # pre-upgrade record shape (no start timestamp)
        trace = str(rec.get("trace", "?"))
        tid = trace_tids.get(trace)
        if tid is None:
            tid = len(trace_tids) + 1
            trace_tids[trace] = tid
            meta.append({"ph": "M", "name": "thread_name",
                         "pid": pid_spans, "tid": tid, "ts": 0,
                         "args": {"name": f"trace {trace}"}})
        args = {k: v for k, v in rec.items() if k not in ("span", "ms")}
        events.append({
            "ph": "X", "name": str(rec.get("span", "?")),
            "ts": max(0, int(rec["ts_us"]) + shift_us),
            "dur": max(0, int(float(rec.get("ms", 0.0)) * 1000)),
            "pid": pid_spans, "tid": tid, "args": args,
        })

    # Timeline lanes.
    lane_tids: Dict[str, int] = {}
    for ev in timeline:
        if "ts_us" not in ev:
            continue
        lane_name = _timeline_tid_name(ev)
        tid = lane_tids.get(lane_name)
        if tid is None:
            tid = len(lane_tids) + 1
            lane_tids[lane_name] = tid
            meta.append({"ph": "M", "name": "thread_name",
                         "pid": pid_timeline, "tid": tid, "ts": 0,
                         "args": {"name": lane_name}})
        if ev.get("lane") == "window":
            name = f"bound:{ev.get('binding', '?')}"
        else:
            name = f"{ev.get('lane', '?')} b{ev.get('batch', '?')}"
        args = {k: v for k, v in ev.items() if k != "ts_us"}
        events.append({
            "ph": "X", "name": name,
            "ts": max(0, int(ev["ts_us"]) + shift_us),
            "dur": max(0, int(ev.get("dur_us", 0))),
            "pid": pid_timeline, "tid": tid, "args": args,
        })
    return meta, events


def chrome_trace(spans: Optional[List[Dict[str, Any]]] = None,
                 timeline: Optional[List[Dict[str, Any]]] = None,
                 node_name: str = "node") -> Dict[str, Any]:
    """Span ring + pipeline timeline → one Chrome-trace JSON document.

    Defaults pull from the live process (the whole tracing ring, the
    process recorder); callers with their own captures — the CLI
    validating a fetched artifact, tests with synthetic events — pass
    them explicitly.
    """
    if spans is None:
        spans = tracing.recent_spans(limit=tracing.span_ring_capacity())
    if timeline is None:
        timeline = RECORDER.snapshot()

    meta, events = _node_trace_events(
        spans, timeline, node_name, PID_SPANS, PID_TIMELINE)
    events.sort(key=lambda e: (e["ts"], -e["dur"]))
    return {
        "displayTimeUnit": "ms",
        "otherData": {
            "node": node_name,
            "spans": len([r for r in spans if "ts_us" in r]),
            "timeline_events": len(timeline),
            "generator": "spacedrive_tpu flight recorder",
        },
        "traceEvents": meta + events,
    }


def fleet_chrome_trace(rows: List[Dict[str, Any]],
                       trace: Optional[str] = None,
                       fleet_name: str = "fleet") -> Dict[str, Any]:
    """N nodes' span/timeline captures → ONE Chrome-trace document
    with per-node pid lanes (node i gets pids 2i+1 / 2i+2, named
    after the node), every remote timestamp shifted onto the
    assembling node's clock by that node's estimated skew.

    `rows` are dicts: {"node": name, "spans": [...], "timeline":
    [...], "skew_s": float} — skew_s is "how far ahead of the local
    wall clock this node's clock runs" (fleet.py estimates it from
    obs-poll RTT midpoints), so local_ts = remote_ts - skew. The
    per-node offsets are recorded in otherData.clock_skew_s so the
    correction is auditable, not silent."""
    meta: List[Dict[str, Any]] = []
    events: List[Dict[str, Any]] = []
    skews: Dict[str, float] = {}
    names: List[str] = []
    for i, row in enumerate(rows):
        name = str(row.get("node") or f"node{i}")
        skew_s = float(row.get("skew_s") or 0.0)
        names.append(name)
        skews[name] = round(skew_s, 6)
        m, e = _node_trace_events(
            row.get("spans") or [], row.get("timeline") or [],
            name, 2 * i + 1, 2 * i + 2,
            shift_us=-int(skew_s * 1e6))
        meta.extend(m)
        events.extend(e)
    events.sort(key=lambda e: (e["ts"], -e["dur"]))
    other: Dict[str, Any] = {
        "node": fleet_name,
        "nodes": names,
        "clock_skew_s": skews,
        "spans": sum(1 for e in events if e["pid"] % 2 == 1),
        "timeline_events": sum(1 for e in events if e["pid"] % 2 == 0),
        "generator": "spacedrive_tpu fleet observatory",
    }
    if trace:
        other["trace"] = str(trace)
    return {
        "displayTimeUnit": "ms",
        "otherData": other,
        "traceEvents": meta + events,
    }


def write_trace_artifact(path: str, node_name: str) -> List[str]:
    """The benches' shared --trace export: build the live process's
    trace, validate, and write it ONLY when schema-clean. Returns the
    problem list (empty = written) — the caller decides how to fail.
    One implementation so the export/validate/write sequence cannot
    drift between overlap_bench, perf_smoke, and future tools."""
    import json

    from . import persist

    doc = chrome_trace(node_name=node_name)
    problems = validate_chrome_trace(doc)
    if problems:
        return problems
    persist.atomic_write("flight.trace", path,
                         json.dumps(doc, indent=1))
    return []


def validate_chrome_trace(doc: Any) -> List[str]:
    """Schema gate for an exported trace. Returns problem strings
    (empty = valid): required keys per event kind, numeric µs
    timestamps, monotone ts over the complete events, and a named
    process/thread for every pid/tid an event lands in — the contract
    the golden-file test and `tools/trace_export.py --json` pin."""
    problems: List[str] = []
    if not isinstance(doc, dict) or \
            not isinstance(doc.get("traceEvents"), list):
        return ["top level must be a dict with a traceEvents list"]
    named_pids = set()
    named_tids = set()
    last_ts: Optional[int] = None
    for i, ev in enumerate(doc["traceEvents"]):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            problems.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if ph == "M":
            if ev.get("name") not in ("process_name", "thread_name"):
                problems.append(
                    f"{where}: unknown metadata {ev.get('name')!r}")
                continue
            if not isinstance(ev.get("pid"), int):
                problems.append(f"{where}: metadata needs an int pid")
                continue
            if not isinstance(ev.get("args"), dict) or \
                    "name" not in ev["args"]:
                problems.append(f"{where}: metadata needs args.name")
                continue
            if ev["name"] == "process_name":
                named_pids.add(ev["pid"])
            else:
                if not isinstance(ev.get("tid"), int):
                    problems.append(
                        f"{where}: thread_name needs an int tid")
                    continue
                named_tids.add((ev["pid"], ev["tid"]))
        elif ph == "X":
            missing = [k for k in ("name", "ts", "dur", "pid", "tid")
                       if k not in ev]
            if missing:
                problems.append(f"{where}: missing keys {missing}")
                continue
            if not isinstance(ev["ts"], (int, float)) or ev["ts"] < 0 \
                    or not isinstance(ev["dur"], (int, float)) \
                    or ev["dur"] < 0:
                problems.append(
                    f"{where}: ts/dur must be non-negative numbers")
                continue
            if not isinstance(ev["pid"], int) \
                    or not isinstance(ev["tid"], int):
                problems.append(f"{where}: pid/tid must be ints")
                continue
            if last_ts is not None and ev["ts"] < last_ts:
                problems.append(
                    f"{where}: ts {ev['ts']} < previous {last_ts} — "
                    "complete events must be sorted")
            last_ts = int(ev["ts"])
            if ev["pid"] not in named_pids:
                problems.append(
                    f"{where}: pid {ev['pid']} has no process_name "
                    "metadata")
            if (ev["pid"], ev["tid"]) not in named_tids:
                problems.append(
                    f"{where}: pid/tid {ev['pid']}/{ev['tid']} has no "
                    "thread_name metadata")
        else:
            problems.append(f"{where}: unknown ph {ph!r}")
    if "displayTimeUnit" in doc and \
            doc["displayTimeUnit"] not in ("ms", "ns"):
        problems.append(
            f"displayTimeUnit {doc['displayTimeUnit']!r} not ms/ns")
    return problems
