"""light_scan_location: inline shallow index + identify of one directory.

The reference's shallow variants (indexer/shallow.rs:26,
file_identifier/shallow.rs:26, location/mod.rs:489) run inline rather
than as jobs — they service watcher events and Explorer navigation where
job-queue latency would be felt. Here the walker's shallow mode feeds the
same save/update/remove writes the IndexerJob uses, then the identifier's
chunk kernel runs over the new orphans in that one directory.
"""

from __future__ import annotations

from typing import Optional

from ..objects.identifier import CHUNK_SIZE, identify_chunk, orphan_filters
from .file_path_helper import load_location
from .indexer_job import (
    _entry_to_row,
    make_db_fetchers,
    remove_file_path_rows,
    save_file_path_rows,
    update_file_path_rows,
)
from .paths import IsolatedPath
from .rules import load_rules_for_location
from .walker import Walker


def light_scan_location(library, location_id: int,
                        sub_path: Optional[str] = None,
                        backend: str = "auto") -> dict:
    """Shallow rescan of one directory: index changes + identify orphans.

    Returns {"saved", "updated", "removed", "linked", "created", "errors"}.
    """
    db, sync = library.db, library.sync
    loc = load_location(db, location_id)
    location_path = loc["path"]
    target = location_path
    sub_iso = None
    if sub_path:
        sub_iso = IsolatedPath.from_relative(
            location_id, sub_path.strip("/") + "/")
        target = sub_iso.join_on(location_path)

    rules = load_rules_for_location(db, location_id)
    existing, to_remove = make_db_fetchers(db, location_id)
    walker = Walker(location_id, location_path, rules=rules,
                    existing_paths_fetcher=existing,
                    to_remove_fetcher=to_remove)
    res = walker.walk_single_dir(target, add_root=bool(sub_path))
    errors = list(res.errors)

    # Saves FIRST: a renamed file is (new path in walked) + (old path in
    # to_remove) with the SAME inode — the save re-paths the existing row
    # in place (keeping its object link), and the path-conditional
    # removal then recognizes the re-pathed row and leaves it alone.
    rows = [_entry_to_row(e, location_id) for e in res.walked]
    save_file_path_rows(library, loc["pub_id"], rows)
    upd = [_entry_to_row(e, location_id) for e in res.to_update]
    update_file_path_rows(library, upd)
    removed = remove_file_path_rows(library, location_id,
                                    list(res.to_remove))

    # identify new orphans in this directory only
    sub_mat = sub_iso.materialized_path_for_children() if sub_iso else "/"
    linked = created = 0
    cursor = 0  # advances past unreadable rows so they can't loop forever
    while True:
        where, params = orphan_filters(location_id, cursor, None)
        where += " AND materialized_path = ?"
        params.append(sub_mat)
        # binds the declared location.shallow.page shape
        chunk = [dict(r) for r in db.query(
            f"SELECT * FROM file_path WHERE {where} ORDER BY id LIMIT ?",
            params + [CHUNK_SIZE])]
        if not chunk:
            break
        # Deliberate per-chunk commit: the cursor pages over COMMITTED
        # rows (a crash resumes where the last chunk landed), and each
        # chunk is one group-committed write_tx.
        # sdlint: ok[tx-shape]
        lk, cr, errs = identify_chunk(
            library, location_id, location_path, chunk, backend)
        linked += lk
        created += cr
        errors.extend(errs)
        cursor = chunk[-1]["id"] + 1
        if len(chunk) < CHUNK_SIZE:
            break

    return {"saved": len(rows), "updated": len(upd), "removed": removed,
            "linked": linked, "created": created, "errors": errors}
