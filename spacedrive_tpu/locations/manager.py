"""Location CRUD + scan orchestration.

Mirrors /root/reference/core/src/location/mod.rs: creating a location
writes the row through sync and attaches indexer rules; `scan_location`
chains IndexerJob → FileIdentifierJob (→ MediaProcessorJob when present)
via the job builder (mod.rs:417-445); `light_scan_location` runs the
shallow variants inline for watcher-triggered rescans (mod.rs:489).
"""

from __future__ import annotations

import os
import time
import uuid as uuidlib
from typing import List, Optional, Sequence

from ..jobs.manager import JobBuilder, JobManager
from ..objects.identifier import FileIdentifierJob
from ..store import uuid_bytes
from .indexer_job import IndexerJob


class LocationError(Exception):
    pass


def create_location(library, path: str,
                    indexer_rule_ids: Sequence[int] = (),
                    name: Optional[str] = None) -> int:
    """Create a location row (+sync ops) for a directory on this node
    (location/mod.rs create semantics: path must exist, be a dir, and not
    be nested inside an existing location)."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise LocationError(f"{path} is not a directory")
    for row in library.db.run("location.paths"):
        other = row["path"] or ""
        if other and (path == other
                      or path.startswith(other.rstrip("/") + "/")
                      or other.startswith(path.rstrip("/") + "/")):
            raise LocationError(
                f"{path} overlaps existing location {other}")
    pub_id = uuid_bytes()
    name = name or os.path.basename(path) or path
    sync = library.sync
    ops = sync.shared_create("location", pub_id, {
        "name": name, "path": path, "date_created": int(time.time()),
    })
    with sync.write_ops(ops) as conn:
        loc_id = library.db.insert("location", {
            "pub_id": pub_id, "name": name, "path": path,
            "date_created": int(time.time()),
            "instance_id": sync._instance_row_id(sync.instance, conn),
        }, conn=conn)
        for rid in indexer_rule_ids:
            library.db.insert("indexer_rule_in_location", {
                "location_id": loc_id, "indexer_rule_id": rid,
            }, conn=conn)
    return loc_id


def delete_location(library, location_id: int) -> None:
    row = library.db.run("location.pub_by_id", (location_id,))
    if row is None:
        raise LocationError("no such location")
    with library.sync.write_ops(
            [library.sync.shared_delete("location", row["pub_id"])]) as conn:
        library.db.delete("location", location_id, conn=conn)


async def scan_location(jobs: JobManager, library, location_id: int,
                        backend: str = "auto",
                        with_media: bool = True) -> bytes:
    """Full rescan: indexer → identifier (→ media processor) chain
    (location/mod.rs:417-445)."""
    builder = JobBuilder(IndexerJob(location_id=location_id)) \
        .queue_next(FileIdentifierJob(location_id=location_id,
                                      backend=backend))
    if with_media:
        from ..media.processor import MediaProcessorJob
        builder.queue_next(MediaProcessorJob(location_id=location_id))
    return await builder.spawn(jobs, library)


async def scan_location_sub_path(jobs: JobManager, library,
                                 location_id: int, sub_path: str,
                                 backend: str = "auto") -> bytes:
    builder = JobBuilder(
        IndexerJob(location_id=location_id, sub_path=sub_path)) \
        .queue_next(FileIdentifierJob(location_id=location_id,
                                      sub_path=sub_path, backend=backend))
    return await builder.spawn(jobs, library)


def relink_location(library, location_id: int, new_path: str) -> None:
    """Point a location at a moved directory (location/mod.rs relink)."""
    new_path = os.path.abspath(new_path)
    if not os.path.isdir(new_path):
        raise LocationError(f"{new_path} is not a directory")
    row = library.db.run("location.pub_by_id", (location_id,))
    if row is None:
        raise LocationError("no such location")
    with library.sync.write_ops([
        library.sync.shared_update("location", row["pub_id"], "path",
                                   new_path)
    ]) as conn:
        library.db.update("location", location_id, {"path": new_path},
                          conn=conn)
