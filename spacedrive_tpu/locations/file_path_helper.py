"""Shared file_path query helpers for workload jobs.

The reference keeps per-workload projections and sub-path guards in
core/src/location/file_path_helper/mod.rs (ensure_sub_path_is_in_location,
ensure_sub_path_is_directory, per-job `select!`s); here the shared pieces
are the location-row prologue every job runs and the escaped LIKE filter
for sub-path scoping.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

from ..jobs.job import EarlyFinish
from .paths import IsolatedPath


def load_location(db, location_id: int):
    """Location row, or EarlyFinish when it vanished mid-chain (the
    reference jobs treat a missing location as clean completion)."""
    loc = db.run("location.by_id", (location_id,))
    if loc is None or not loc["path"]:
        raise EarlyFinish(f"location {location_id} gone")
    return loc


def sub_path_children_mat(location_id: int,
                          sub_path: Optional[str]) -> Optional[str]:
    """materialized_path prefix covering everything under sub_path."""
    if not sub_path:
        return None
    iso = IsolatedPath.from_relative(
        location_id, sub_path.strip("/") + "/")
    return iso.materialized_path_for_children()


def materialized_like(where: str, params: List[Any],
                      children_mat: Optional[str]) -> str:
    """Append an escaped `materialized_path LIKE prefix%` filter.

    SQLite LIKE has no default escape character, and `_`/`%` in real
    directory names would otherwise widen or break the match — both are
    escaped and an explicit ESCAPE clause added.
    """
    if children_mat is None:
        return where
    escaped = (children_mat.replace("\\", "\\\\")
               .replace("%", r"\%").replace("_", r"\_"))
    params.append(escaped + "%")
    return where + r" AND materialized_path LIKE ? ESCAPE '\'"


def job_prologue(db, location_id: int, sub_path: Optional[str],
                 base_where: str, base_params: List[Any],
                 ) -> Tuple[Any, str, List[Any]]:
    """The shared job-init prologue: (location row, WHERE, params) with
    sub-path scoping applied."""
    loc = load_location(db, location_id)
    where = materialized_like(
        base_where, base_params,
        sub_path_children_mat(location_id, sub_path))
    return loc, where, base_params
