"""Indexer rules: glob accept/reject + child-directory presence checks.

Behavioral equivalent of the reference's rule system
(/root/reference/core/src/location/indexer/rules/mod.rs:152-614): four rule
kinds, msgpack-serialized parameters persisted per rule row, and the same
seeded system rules (/root/reference/core/src/location/indexer/rules/seed.rs
— Linux subset, since this framework targets Linux/TPU hosts).

Application semantics (walk.rs:476-600, encoded in walker.py):
- RejectFilesByGlob: any match rejects the entry.
- AcceptFilesByGlob: if any accept-glob rule exists, at least one must
  match or the entry is skipped (dirs are still descended into).
- Accept/RejectIfChildrenDirectoriesArePresent: applied to directories by
  listing their children's names.
"""

from __future__ import annotations

import enum
import os
from dataclasses import dataclass, field
from typing import Dict, List, Sequence, Set, Tuple

import msgpack

from .glob import GlobSet


class RuleKind(enum.IntEnum):
    ACCEPT_FILES_BY_GLOB = 0
    REJECT_FILES_BY_GLOB = 1
    ACCEPT_IF_CHILDREN_DIRECTORIES_ARE_PRESENT = 2
    REJECT_IF_CHILDREN_DIRECTORIES_ARE_PRESENT = 3


@dataclass
class RulePerKind:
    kind: RuleKind
    # Glob patterns for the *_FILES_BY_GLOB kinds, child dir names otherwise.
    params: Tuple[str, ...]
    _glob_set: GlobSet = field(init=False, repr=False)

    def __post_init__(self):
        if self.kind in (RuleKind.ACCEPT_FILES_BY_GLOB,
                         RuleKind.REJECT_FILES_BY_GLOB):
            self._glob_set = GlobSet(self.params)
        else:
            self._glob_set = GlobSet(())

    def apply(self, source: str | os.PathLike) -> Tuple[RuleKind, bool]:
        """Returns (kind, passed). `passed=False` on a reject kind means the
        entry was rejected (rules/mod.rs:431-453 returns the same polarity:
        reject rules yield `!matched`)."""
        src = os.fspath(source)
        if self.kind == RuleKind.ACCEPT_FILES_BY_GLOB:
            return (self.kind, self._glob_set.is_match(src))
        if self.kind == RuleKind.REJECT_FILES_BY_GLOB:
            return (self.kind, not self._glob_set.is_match(src))
        if self.kind == RuleKind.ACCEPT_IF_CHILDREN_DIRECTORIES_ARE_PRESENT:
            return (self.kind, self._check_children(src, accept=True))
        return (self.kind, self._check_children(src, accept=False))

    def _check_children(self, src: str, accept: bool) -> bool:
        """accept_dir_for_its_children / reject_dir_for_its_children
        (rules/mod.rs:526-614): scan child dir names against params."""
        children: Set[str] = set(self.params)
        try:
            if not os.path.isdir(src):
                return False if accept else True
            with os.scandir(src) as it:
                for entry in it:
                    if entry.is_dir(follow_symlinks=False) and \
                            entry.name in children:
                        return accept
        except OSError:
            return False if accept else True
        return not accept


@dataclass
class IndexerRule:
    name: str
    rules: List[RulePerKind]
    default: bool = False
    pub_id: bytes = b""

    def apply(self, source: str | os.PathLike) -> List[Tuple[RuleKind, bool]]:
        return [r.apply(source) for r in self.rules]

    # -- persistence (msgpack blob in indexer_rule.rules_per_kind) ---------

    def serialize_rules(self) -> bytes:
        return msgpack.packb(
            [[int(r.kind), list(r.params)] for r in self.rules],
            use_bin_type=True,
        )

    @classmethod
    def from_row(cls, row) -> "IndexerRule":
        raw = msgpack.unpackb(row["rules_per_kind"], raw=False)
        return cls(
            name=row["name"],
            rules=[RulePerKind(RuleKind(k), tuple(params)) for k, params in raw],
            default=bool(row["default_rule"]),
            pub_id=row["pub_id"],
        )


def apply_all(
    rules: Sequence[IndexerRule], source: str | os.PathLike
) -> Dict[RuleKind, List[bool]]:
    """IndexerRule::apply_all (rules/mod.rs:476-494): kind → result list."""
    out: Dict[RuleKind, List[bool]] = {}
    for rule in rules:
        for kind, passed in rule.apply(source):
            out.setdefault(kind, []).append(passed)
    return out


# -- seeded system rules (seed.rs:72-220, Linux/unix subset) ---------------

def no_os_protected() -> IndexerRule:
    return IndexerRule(
        name="No OS protected",
        default=True,
        rules=[RulePerKind(RuleKind.REJECT_FILES_BY_GLOB, (
            "**/.spacedrive",
            # Linux (seed.rs:142-154)
            "**/*~",
            "**/.fuse_hidden*",
            "**/.directory",
            "**/.Trash-*",
            "**/.nfs*",
            # unix (seed.rs:160-170)
            "/{dev,sys,proc}",
            "/{run,var,boot}",
            "**/lost+found",
        ))],
    )


def no_hidden() -> IndexerRule:
    return IndexerRule(
        name="No Hidden",
        rules=[RulePerKind(RuleKind.REJECT_FILES_BY_GLOB, ("**/.*",))],
    )


def no_git() -> IndexerRule:
    return IndexerRule(
        name="No Git",
        rules=[RulePerKind(RuleKind.REJECT_FILES_BY_GLOB, (
            "**/{.git,.gitignore,.gitattributes,.gitkeep,.gitconfig,.gitmodules}",
        ))],
    )


def only_images() -> IndexerRule:
    return IndexerRule(
        name="Only Images",
        rules=[RulePerKind(RuleKind.ACCEPT_FILES_BY_GLOB, (
            "*.{avif,bmp,gif,ico,jpeg,jpg,png,svg,tif,tiff,webp}",
        ))],
    )


SYSTEM_RULES = (no_os_protected, no_hidden, no_git, only_images)


def seed_system_rules(db) -> None:
    """Upsert the system rules with stable pub_ids derived from their seed
    index (seed.rs:38-69: uuid_from_u128(i)). DO NOT REORDER."""
    import time
    now = int(time.time())
    with db.write_tx() as conn:  # one tx for the whole seed set
        for i, factory in enumerate(SYSTEM_RULES):
            rule = factory()
            pub_id = i.to_bytes(16, "big")
            db.upsert(
                "indexer_rule",
                {"pub_id": pub_id},
                {
                    "name": rule.name,
                    "default_rule": int(rule.default),
                    "rules_per_kind": rule.serialize_rules(),
                    "date_created": now,
                    "date_modified": now,
                },
                conn=conn,
            )


def load_rules_for_location(db, location_id: int) -> List[IndexerRule]:
    rows = db.run("location.rules_for", (location_id,))
    return [IndexerRule.from_row(r) for r in rows]
