"""IndexerJob: walk a location and persist file_path rows in batches.

Behavioral equivalent of the reference's indexer job
(/root/reference/core/src/location/indexer/indexer_job.rs:140-621):
init walks up to INIT_WALK_LIMIT entries and emits Save steps (chunks of
BATCH_SIZE=1000 creates), Update steps, and one Walk step per deferred
directory; Walk steps call keep_walking and append more steps. Stale rows
found by the walker are deleted. Dir sizes accumulate across steps and are
written in finalize.

All writes go through the sync manager (create/update/delete ops), unlike
the reference which TODOs sync for removals (indexer_job.rs:232).
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Dict, List, Optional

from .. import persist
from ..jobs.job import EarlyFinish, JobContext, StatefulJob, StepOutcome, register_job
from ..store import statements, uuid_bytes
from .paths import IsolatedPath
from .rules import load_rules_for_location
from .walker import ToWalkEntry, WalkedEntry, Walker, WalkResult

BATCH_SIZE = 1000       # indexer_job.rs:48
INIT_WALK_LIMIT = 50_000  # indexer_job.rs:205


def _entry_to_row(e: WalkedEntry, location_id: int) -> Dict[str, Any]:
    m = e.metadata
    return {
        "pub_id": e.pub_id,
        "location_id": location_id,
        "is_dir": int(e.iso.is_dir),
        "materialized_path": e.iso.materialized_path,
        "name": e.iso.name,
        "extension": e.iso.extension,
        "inode": int(m.inode).to_bytes(8, "big"),
        "size_in_bytes_bytes": int(m.size_in_bytes).to_bytes(8, "big"),
        "date_created": m.created_at,
        "date_modified": m.modified_at,
        "date_indexed": time.time(),
    }


def _row_sync_values(row: Dict[str, Any]) -> Dict[str, Any]:
    """Synced field subset (location_id handled as the location pub_id by
    callers; local ids never go on the wire)."""
    return {k: row[k] for k in (
        "is_dir", "materialized_path", "name", "extension",
        "size_in_bytes_bytes", "date_created", "date_modified",
        "date_indexed",
    )}


def make_db_fetchers(db, location_id: int):
    """The walker's injected DB seams, backed by the real store
    (file_paths_db_fetcher_fn!/to_remove_db_fetcher_fn!,
    indexer/mod.rs macros)."""

    def existing(paths):
        out = []
        for p in paths:
            row = db.run("indexer.path_by_key", p.db_key())
            if row is not None:
                out.append(dict(row))
        return out

    def to_remove(parent_iso, iso_paths):
        """Rows directly under parent_iso that the walker did not see."""
        children_mat = parent_iso.materialized_path_for_children()
        if children_mat is None:
            return []
        rows = db.run("indexer.children", (location_id, children_mat))
        seen = {(p.materialized_path, p.name, p.extension)
                for p in iso_paths}
        return [dict(r) for r in rows
                if (r["materialized_path"], r["name"], r["extension"] or "")
                not in seen]

    return existing, to_remove


# -- shared write choreography (used by the job steps AND shallow.py) ------

SYNCED_UPDATE_FIELDS = ("inode", "size_in_bytes_bytes", "date_modified",
                        "date_indexed", "is_dir")


def _consume_scratch(conn, scratch_id: Optional[int]) -> None:
    """Drop a processed step's spooled payload inside the step's own
    domain transaction — commit and consume are atomic, so a crash can
    never land between them (no reliance on idempotent replay)."""
    if scratch_id is not None:
        conn.execute(statements.get("jobs.scratch.delete").sql,
                     (scratch_id,))


def save_file_path_rows(library, location_pub_id: bytes,
                        rows: List[Dict[str, Any]],
                        consume_scratch: Optional[int] = None) -> int:
    """Batched create through sync; replayed steps' unique collisions are
    ignored (IS_BATCHED idempotency).

    A new path whose inode ALREADY has a row is a move the walker saw
    from the destination side (cross-directory renames land in different
    walk steps, so remove-before-save ordering can't cover them): the
    existing row is re-pathed in place — keeping its object link and
    cas_id — instead of colliding with the (location_id, inode) unique
    constraint and being silently dropped."""
    if not rows:
        if consume_scratch is not None:
            with library.db.write_tx() as conn:
                _consume_scratch(conn, consume_scratch)
        return 0
    db, sync = library.db, library.sync

    # ONE batched lookup for the whole chunk's inodes (a per-row query
    # costs ~10 µs × 1M rows on big scans). Keys are the 8-byte big-
    # endian inode blobs as stored (FilePathMetadata.from_stat).
    from ..objects.identifier import _in_chunks

    inodes = sorted({r["inode"] for r in rows if r.get("inode")})
    existing_by_inode: Dict[bytes, Any] = {}
    for chunk in _in_chunks(inodes):
        ph = ",".join("?" for _ in chunk)
        # binds the declared indexer.paths_by_inodes shape
        for e in db.query(
            f"SELECT inode, pub_id, materialized_path, name, extension "
            f"FROM file_path WHERE location_id = ? AND inode IN ({ph})",
                [rows[0]["location_id"], *chunk]):
            existing_by_inode[e["inode"]] = e

    moved: List[Dict[str, Any]] = []
    fresh: List[Dict[str, Any]] = []
    for row in rows:
        existing = existing_by_inode.get(row.get("inode"))
        if existing is None:
            fresh.append(row)
        elif (existing["materialized_path"] != row["materialized_path"]
              or existing["name"] != row["name"]
              or (existing["extension"] or "") != (row["extension"] or "")):
            moved.append({**row, "pub_id": existing["pub_id"]})
        # else: identical path replay — the insert below IGNOREs it

    if moved:
        _repath_rows(library, moved)
    if not fresh:
        if consume_scratch is not None:
            with db.write_tx() as conn:
                _consume_scratch(conn, consume_scratch)
        return len(moved)
    specs = []
    for row in fresh:
        values = _row_sync_values(row)
        values["location_id"] = location_pub_id  # FK syncs as pub_id
        specs.append((row["pub_id"], "c", None, None, values))
    with db.write_tx() as conn:
        n = db.insert_many(
            "file_path", fresh, conn=conn, ignore_conflicts=True)
        n_ops = sync.bulk_shared_ops(conn, "file_path", specs)
        _consume_scratch(conn, consume_scratch)
    if n_ops:
        sync._notify_created()
    return len(moved) + n


def _repath_rows(library, rows: List[Dict[str, Any]]) -> int:
    """Move detected by inode: update the existing row's path identity
    (+ freshness fields) in place, preserving object link and cas_id."""
    db, sync = library.db, library.sync
    fields = ("materialized_path", "name", "extension",
              *SYNCED_UPDATE_FIELDS)
    ops = []
    with db.write_tx() as conn:
        for row in rows:
            values = {k: row[k] for k in fields}
            db.update("file_path", row["pub_id"], values, conn=conn,
                      id_col="pub_id")
            for k, v in values.items():
                ops.append(sync.shared_update(
                    "file_path", row["pub_id"], k, v))
        sync._insert_op_rows(conn, ops)
    if ops:
        sync._notify_created()
    return len(rows)


def update_file_path_rows(library, rows: List[Dict[str, Any]],
                          consume_scratch: Optional[int] = None) -> int:
    """A row lands here when the walker saw its content change
    (size/mtime drift): besides refreshing those fields, the derived
    identity — cas_id, integrity_checksum, object link — is INVALIDATED
    so the identifier re-identifies and the validator re-fills. Without
    this, stale checksums would read as corruption forever (and stale
    cas_ids as wrong dedup identity)."""
    if not rows:
        if consume_scratch is not None:
            with library.db.write_tx() as conn:
                _consume_scratch(conn, consume_scratch)
        return 0
    db, sync = library.db, library.sync
    ops = []
    with db.write_tx() as conn:
        for row in rows:
            values = {k: row[k] for k in SYNCED_UPDATE_FIELDS}
            if not row.get("is_dir"):
                values.update(cas_id=None, integrity_checksum=None,
                              object_id=None)
            db.update("file_path", row["pub_id"], values, conn=conn,
                      id_col="pub_id")
            for k, v in values.items():
                ops.append(sync.shared_update(
                    "file_path", row["pub_id"], k, v))
        sync._insert_op_rows(conn, ops)
        _consume_scratch(conn, consume_scratch)
    if ops:
        sync._notify_created()
    return len(rows)


def remove_file_path_rows(library, location_id: int,
                          removed: List[Dict[str, Any]],
                          consume_scratch: Optional[int] = None) -> int:
    """Delete stale rows; a removed DIRECTORY also deletes every
    descendant row by materialized_path prefix (the walker only reports
    the dir itself — without this, rm -rf'd subtrees leave ghost rows).

    Path-conditional: a row whose (materialized_path, name) no longer
    matches what the walker observed was MOVED and re-pathed by a save
    step since — deleting it by pub_id would destroy the moved file's
    row and object link. Such rows are skipped."""
    if not removed:
        if consume_scratch is not None:
            with library.db.write_tx() as conn:
                _consume_scratch(conn, consume_scratch)
        return 0
    db, sync = library.db, library.sync
    from .file_path_helper import materialized_like
    ops = []
    n = 0
    with db.write_tx() as conn:
        for r in removed:
            if r.get("materialized_path") is not None:
                cur_row = db.run("indexer.path_current",
                                 (r["pub_id"],), conn=conn)
                if cur_row is None:
                    continue  # already gone (replayed step)
                if (cur_row["materialized_path"] != r["materialized_path"]
                        or cur_row["name"] != r.get("name")):
                    continue  # re-pathed by a move — keep it
            if r.get("is_dir") and r.get("materialized_path") is not None:
                children_mat = (f"{r['materialized_path']}{r['name']}/")
                where, params = "location_id = ?", [location_id]
                where = materialized_like(where, params, children_mat)
                # binds the declared indexer.desc_pubs shape
                desc = conn.execute(
                    f"SELECT pub_id FROM file_path WHERE {where}",
                    params).fetchall()
                for d in desc:
                    ops.append(sync.shared_delete("file_path", d["pub_id"]))
                # binds the declared indexer.desc_delete shape
                cur = conn.execute(
                    f"DELETE FROM file_path WHERE {where}", params)
                n += cur.rowcount
            ops.append(sync.shared_delete("file_path", r["pub_id"]))
            db.run("indexer.path_delete_by_pub", (r["pub_id"],),
                   conn=conn)
            n += 1
        sync._insert_op_rows(conn, ops)
        _consume_scratch(conn, consume_scratch)
    if ops:
        sync._notify_created()
    return n


@register_job
class IndexerJob(StatefulJob):
    NAME = "indexer"
    IS_BATCHED = True

    def __init__(self, *, location_id: int, sub_path: Optional[str] = None):
        super().__init__(location_id=location_id, sub_path=sub_path)
        self.location_id = location_id
        self.sub_path = sub_path

    # -- helpers -----------------------------------------------------------

    def _walker(self, ctx: JobContext, location_path: str) -> Walker:
        # One Walker per run: rules can't change mid-job, and per-step
        # reconstruction would re-query the rule tables for every
        # deferred directory.
        cached = getattr(self, "_walker_cache", None)
        if cached is not None and cached.location_path == location_path:
            return cached
        db = ctx.db
        rules = load_rules_for_location(db, self.location_id)
        existing, to_remove = make_db_fetchers(db, self.location_id)
        self._walker_cache = Walker(
            self.location_id, location_path, rules=rules,
            existing_paths_fetcher=existing, to_remove_fetcher=to_remove,
        )
        return self._walker_cache

    def _spool(self, ctx: JobContext,
               batches: List[List[Dict[str, Any]]]) -> List[int]:
        """Write step row-payloads to job_scratch and return their ids.

        Steps then carry a scratch reference instead of inline rows, so
        the worker's 3-second crash checkpoint serializes step
        DESCRIPTORS (bytes) rather than the whole remaining workload —
        inline rows measured ~200 MB / ~23 s per checkpoint at 1M files.
        The scratch rows live in the library DB, so cold_resume finds
        them after a crash exactly like the step list itself."""
        if not batches:
            return []
        import msgpack
        sids: List[int] = []
        with ctx.db.write_tx() as conn:
            for b in batches:
                # per-row lastrowid feeds the step descriptors —
                # executemany has no rowid surface; one tx regardless
                cur = ctx.db.run(  # sdlint: ok[tx-shape]
                    "jobs.scratch.insert",
                    (ctx.job_id, msgpack.packb(b, use_bin_type=True)),
                    conn=conn)
                sids.append(cur.lastrowid)
        # Declared DB-backed artifact: SQLite's WAL owns durability,
        # this records the commit under the job.scratch name.
        persist.db_write("job.scratch", rows=len(sids))
        return sids

    @staticmethod
    def _unspool(ctx: JobContext, step) -> List[Dict[str, Any]]:
        """Rows of a spooled step; [] when the scratch row is already
        consumed (replay of a completed step — consume commits atomically
        with the step's domain writes, so a missing row PROVES the work
        landed). Inline "rows" kept for states persisted pre-spooling."""
        if "rows" in step:
            return step["rows"]
        row = ctx.db.run("jobs.scratch.data", (step["scratch"],))
        if row is None:
            return []
        import msgpack
        return msgpack.unpackb(row["data"], raw=False)

    def _result_to_steps(self, ctx: JobContext, res: WalkResult,
                         data: Dict[str, Any]) -> List[Any]:
        steps: List[Any] = []
        # Removals are DEFERRED to the end of the job (finalize): a moved
        # file appears as (new path in some dir's walked) + (old path in
        # another dir's to_remove), and only after every save step has
        # had the chance to re-path it by inode can a removal safely
        # judge — path-conditionally — that a row is truly stale.
        # Deferred payloads SPOOL to job_scratch like save/update rows
        # (`data` only carries the scratch ids): inline removal dicts
        # were serialized into every 3-second crash checkpoint, so a
        # mass-removal rescan (rm -rf of a big subtree) regrew the
        # checkpoint blob toward the very problem spooling solved.
        if res.to_remove:
            removals = [
                {k: r.get(k) for k in (
                    "pub_id", "is_dir", "materialized_path", "name")}
                for r in res.to_remove]
            data.setdefault("removal_scratch", []).extend(self._spool(
                ctx, [removals[i:i + BATCH_SIZE]
                      for i in range(0, len(removals), BATCH_SIZE)]))
        save_rows = [_entry_to_row(e, self.location_id) for e in res.walked]
        save_batches = [save_rows[i:i + BATCH_SIZE]
                        for i in range(0, len(save_rows), BATCH_SIZE)]
        upd_rows = [_entry_to_row(e, self.location_id) for e in res.to_update]
        upd_batches = [upd_rows[i:i + BATCH_SIZE]
                       for i in range(0, len(upd_rows), BATCH_SIZE)]
        sids = self._spool(ctx, save_batches + upd_batches)
        steps.extend({"kind": "save", "scratch": sid}
                     for sid in sids[:len(save_batches)])
        steps.extend({"kind": "update", "scratch": sid}
                     for sid in sids[len(save_batches):])
        for w in res.to_walk:
            steps.append({"kind": "walk", "path": w.path,
                          "accepted": w.parent_dir_accepted_by_its_children,
                          "parent": w.maybe_parent})
        for p, s in res.paths_and_sizes.items():
            data["dir_sizes"][p] = data["dir_sizes"].get(p, 0) + s
        return steps

    # -- lifecycle ---------------------------------------------------------

    async def init(self, ctx: JobContext):
        db = ctx.db
        loc = db.run("location.by_id", (self.location_id,))
        if loc is None or not loc["path"]:
            raise EarlyFinish(f"location {self.location_id} gone")
        location_path = loc["path"]
        to_walk_path = location_path
        if self.sub_path:
            iso = IsolatedPath.new(
                self.location_id, location_path,
                f"{location_path.rstrip('/')}/{self.sub_path.strip('/')}",
                True)
            to_walk_path = iso.join_on(location_path)
        data: Dict[str, Any] = {
            "location_path": location_path,
            "location_pub_id": loc["pub_id"],
            "dir_sizes": {},
            # Scratch-row ids of spooled removal batches; the legacy
            # inline "pending_removals" key is still consumed in
            # finalize for checkpoints persisted before spooling.
            "removal_scratch": [],
            "pending_removals": [],
            "total_saved": 0, "total_updated": 0, "total_removed": 0,
        }
        walker = self._walker(ctx, location_path)
        res = await asyncio.to_thread(
            walker.walk, to_walk_path, INIT_WALK_LIMIT)
        # Step building spools row batches into job_scratch (db writes)
        # and was measured stalling the loop ~1.5s on big removal sets
        # (the sanitizer's loop_stall detector caught it) — off-loop.
        steps = await asyncio.to_thread(
            self._result_to_steps, ctx, res, data)
        # A pure-removal rescan (rm -rf'd subtree, nothing new) emits
        # zero steps but must still reach finalize, where the spooled
        # removals are applied — EarlyFinish here would both strand the
        # stale rows and leak the scratch payloads.
        if not steps and not data["removal_scratch"]:
            raise EarlyFinish("nothing to index")
        return data, steps

    async def execute_step(self, ctx: JobContext, data, step, step_number):
        kind = step["kind"]
        if kind == "save":
            return await asyncio.to_thread(self._save, ctx, data, step)
        if kind == "update":
            return await asyncio.to_thread(self._update, ctx, data, step)
        if kind == "remove":
            return await asyncio.to_thread(self._remove, ctx, data, step)
        # walk step: descend one deferred directory.
        walker = self._walker(ctx, data["location_path"])
        res = await asyncio.to_thread(
            walker.keep_walking,
            ToWalkEntry(step["path"], step.get("accepted"), step.get("parent")),
        )
        more = await asyncio.to_thread(
            self._result_to_steps, ctx, res, data)
        return StepOutcome(more_steps=more, errors=list(res.errors))

    def _save(self, ctx: JobContext, data, step) -> StepOutcome:
        n = save_file_path_rows(
            ctx.library, data["location_pub_id"], self._unspool(ctx, step),
            consume_scratch=step.get("scratch"))
        data["total_saved"] += n
        ctx.progress(message=f"saved {data['total_saved']} paths")
        return StepOutcome(metadata={"indexed_count": data["total_saved"]})

    def _update(self, ctx: JobContext, data, step) -> StepOutcome:
        data["total_updated"] += update_file_path_rows(
            ctx.library, self._unspool(ctx, step),
            consume_scratch=step.get("scratch"))
        return StepOutcome(metadata={"updated_count": data["total_updated"]})

    def _remove(self, ctx: JobContext, data, step) -> StepOutcome:
        data["total_removed"] += remove_file_path_rows(
            ctx.library, self.location_id, self._unspool(ctx, step),
            consume_scratch=step.get("scratch"))
        return StepOutcome(metadata={"removed_count": data["total_removed"]})

    async def cleanup(self, ctx: JobContext, data):
        """Cancel/failure path (finalize never runs): sweep this job's
        spooled step payloads. Resume-after-pause does NOT come through
        here — paused jobs keep their scratch rows alive alongside the
        persisted step list that references them."""
        if ctx.job_id:
            await asyncio.to_thread(
                ctx.db.run_tx, "jobs.scratch.delete_for_job",
                (ctx.job_id,))

    def _write_dir_sizes(self, ctx: JobContext, data) -> int:
        """Deferred dir-size writes + their sync ops in ONE tx.

        size_in_bytes_bytes is a SYNCED field (store/models.py), so the
        sizes an index run computes must reach peers — the bare UPDATE
        this used to do diverged replicas silently (sdlint crdt-parity
        finding). Returns ops emitted; the caller fires the created
        notification outside the tx."""
        db = ctx.db
        sync = ctx.library.sync
        loc_path = data["location_path"]
        with db.write_tx() as conn:
            specs = []
            for path, size in data["dir_sizes"].items():
                try:
                    iso = IsolatedPath.new(
                        self.location_id, loc_path, path, True)
                except ValueError:
                    continue
                row = ctx.db.run(
                    "indexer.id_pub_by_key",
                    (iso.location_id, iso.materialized_path, iso.name,
                     iso.extension), conn=conn)
                if row is None:
                    continue
                blob = int(size).to_bytes(8, "big")
                # interleaved with the per-row key resolution above;
                # the whole rollup is already ONE tx
                ctx.db.run(  # sdlint: ok[tx-shape]
                    "indexer.set_dir_size", (blob, row["id"]),
                    conn=conn)
                specs.append((row["pub_id"], "u:size_in_bytes_bytes",
                              "size_in_bytes_bytes", blob, None))
            return sync.bulk_shared_ops(conn, "file_path", specs)

    async def finalize(self, ctx: JobContext, data, metadata):
        """Execute deferred removals (every save has had its chance to
        re-path moved inodes by now), then write accumulated dir sizes
        onto their file_path rows (indexer_job.rs finalize semantics)."""
        if data.get("pending_removals"):  # pre-spooling checkpoints
            data["total_removed"] += await asyncio.to_thread(
                remove_file_path_rows, ctx.library, self.location_id,
                data["pending_removals"])
            data["pending_removals"] = []
        for sid in data.get("removal_scratch") or []:
            # Unspool each deferred-removal batch; a consumed/missing
            # row proves a replayed finalize already removed it.
            rows = await asyncio.to_thread(
                self._unspool, ctx, {"scratch": sid})
            data["total_removed"] += await asyncio.to_thread(
                remove_file_path_rows, ctx.library, self.location_id,
                rows, sid)
        data["removal_scratch"] = []
        db = ctx.db
        if await asyncio.to_thread(self._write_dir_sizes, ctx, data):
            ctx.library.sync._notify_created()
        if ctx.job_id:  # sweep any unconsumed scratch (replays, errors)
            await asyncio.to_thread(
                db.run_tx, "jobs.scratch.delete_for_job", (ctx.job_id,))
        metadata.setdefault("indexed_count", data["total_saved"])
        metadata.setdefault("updated_count", data["total_updated"])
        metadata.setdefault("removed_count", data["total_removed"])
        return metadata
