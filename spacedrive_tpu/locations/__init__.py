from .paths import IsolatedPath, accept_file_name, materialized_path_str

__all__ = ["IsolatedPath", "accept_file_name", "materialized_path_str"]
