"""Rule-filtered filesystem walker with injected DB fetchers.

Behavioral equivalent of the reference's walker
(/root/reference/core/src/location/indexer/walk.rs:116-690): iterative BFS
with per-entry rule application, dedup against the DB via *injected fetcher
closures* (the reference's main testing seam — walk.rs:695-1071 passes stub
closures so the walker runs without a database), deferred directory queue,
per-directory size accounting, and change detection (inode/mtime) to split
results into to_create / to_update / to_remove.

Synchronous by design: jobs run it via asyncio.to_thread, keeping the
event loop responsive (the reference uses tokio's async fs instead).
"""

from __future__ import annotations

import os
from ..sync.crdt import uuid4_bytes
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

from .paths import IsolatedPath
from .rules import IndexerRule, RuleKind, apply_all

# Mtime comparisons tolerate 1 ms like the reference (walk.rs:378-380:
# DB datetimes lose precision).
MTIME_DELTA_S = 0.001


@dataclass(frozen=True)
class FilePathMetadata:
    """Subset of stat() persisted on every file_path row
    (file_path_helper/mod.rs:123-129)."""

    inode: int
    size_in_bytes: int
    created_at: float
    modified_at: float
    hidden: bool

    @classmethod
    def from_stat(cls, path: str, st: os.stat_result) -> "FilePathMetadata":
        name = os.path.basename(path)
        return cls(
            inode=st.st_ino,
            size_in_bytes=st.st_size,
            created_at=getattr(st, "st_birthtime", st.st_ctime),
            modified_at=st.st_mtime,
            hidden=name.startswith("."),  # unix semantics (mod.rs:131-144)
        )


@dataclass
class WalkedEntry:
    pub_id: bytes
    iso: IsolatedPath
    metadata: FilePathMetadata


@dataclass
class ToWalkEntry:
    path: str
    parent_dir_accepted_by_its_children: Optional[bool] = None
    maybe_parent: Optional[str] = None


@dataclass
class WalkResult:
    walked: List[WalkedEntry]           # new entries to create
    to_update: List[WalkedEntry]        # existing rows whose fs state changed
    to_walk: Deque[ToWalkEntry]         # deferred directories (batched jobs)
    to_remove: List[dict]               # stale rows {pub_id, cas_id, ...}
    errors: List[str]
    paths_and_sizes: Dict[str, int]     # dir path → accumulated size


# Injected seams (walk.rs:121-129). Both receive IsolatedPath keys:
# - existing_paths_fetcher(iso_paths) -> rows with at least
#   {pub_id, inode, date_modified, size_in_bytes_bytes, is_dir,
#    materialized_path, name, extension}
# - to_remove_fetcher(parent_iso, iso_paths) -> rows for paths under
#   parent_iso that are in the DB but NOT in iso_paths.
ExistingFetcher = Callable[[Sequence[IsolatedPath]], List[dict]]
ToRemoveFetcher = Callable[[IsolatedPath, Sequence[IsolatedPath]], List[dict]]


def _noop_existing(_paths: Sequence[IsolatedPath]) -> List[dict]:
    return []


def _noop_to_remove(_parent: IsolatedPath,
                    _paths: Sequence[IsolatedPath]) -> List[dict]:
    return []


class Walker:
    def __init__(
        self,
        location_id: int,
        location_path: str,
        rules: Sequence[IndexerRule] = (),
        existing_paths_fetcher: ExistingFetcher = _noop_existing,
        to_remove_fetcher: ToRemoveFetcher = _noop_to_remove,
        update_notifier: Optional[Callable[[str, int], None]] = None,
    ):
        self.location_id = location_id
        self.location_path = os.path.normpath(os.fspath(location_path))
        self.rules = list(rules)
        self.existing_paths_fetcher = existing_paths_fetcher
        self.to_remove_fetcher = to_remove_fetcher
        self.update_notifier = update_notifier or (lambda path, count: None)

    def _iso(self, path: str, is_dir: bool) -> IsolatedPath:
        return IsolatedPath.new(self.location_id, self.location_path, path, is_dir)

    # -- public entry points (walk / keep_walking / walk_single_dir) -------

    def walk(self, root: Optional[str] = None, limit: int = 2**63) -> WalkResult:
        """Full BFS from `root` (default: the location root), stopping once
        `limit` paths are collected (remaining dirs stay in to_walk —
        walk.rs:178-182 semantics for batched indexer steps)."""
        root = os.path.normpath(root or self.location_path)
        to_walk: Deque[ToWalkEntry] = deque([ToWalkEntry(root)])
        indexed: Dict[IsolatedPath, WalkedEntry] = {}
        errors: List[str] = []
        to_remove: List[dict] = []
        paths_and_sizes: Dict[str, int] = {}

        while to_walk:
            entry = to_walk.popleft()
            size = self._walk_one(entry, indexed, to_walk, to_remove, errors,
                                  root=root)
            paths_and_sizes[entry.path] = \
                paths_and_sizes.get(entry.path, 0) + size
            if entry.maybe_parent is not None:
                paths_and_sizes[entry.maybe_parent] = \
                    paths_and_sizes.get(entry.maybe_parent, 0) + size
            if len(indexed) >= limit:
                break

        walked, to_update = self._filter_existing(indexed)
        return WalkResult(walked, to_update, to_walk, to_remove, errors,
                          paths_and_sizes)

    def keep_walking(self, entry: ToWalkEntry) -> WalkResult:
        """Process ONE deferred directory, returning newly deferred child
        dirs (keep_walking, walk.rs:199-262) — the indexer's Walk step."""
        to_walk: Deque[ToWalkEntry] = deque()
        indexed: Dict[IsolatedPath, WalkedEntry] = {}
        errors: List[str] = []
        to_remove: List[dict] = []
        size = self._walk_one(entry, indexed, to_walk, to_remove, errors,
                              root=entry.path)
        walked, to_update = self._filter_existing(indexed)
        sizes = {entry.path: size}
        if entry.maybe_parent is not None:
            sizes[entry.maybe_parent] = size
        return WalkResult(walked, to_update, to_walk, to_remove, errors, sizes)

    def walk_single_dir(self, root: Optional[str] = None,
                        add_root: bool = False) -> WalkResult:
        """Shallow, non-recursive walk of one directory (walk.rs:262-330),
        used by light_scan/shallow variants."""
        root = os.path.normpath(root or self.location_path)
        indexed: Dict[IsolatedPath, WalkedEntry] = {}
        errors: List[str] = []
        to_remove: List[dict] = []
        if add_root:
            try:
                st = os.stat(root)
                iso = self._iso(root, True)
                indexed[iso] = WalkedEntry(
                    uuid4_bytes(), iso,
                    FilePathMetadata.from_stat(root, st),
                )
            except OSError as e:
                errors.append(f"{root}: {e}")
        size = self._walk_one(ToWalkEntry(root), indexed, None, to_remove,
                              errors, root=root)
        walked, to_update = self._filter_existing(indexed)
        return WalkResult(walked, to_update, deque(), to_remove, errors,
                          {root: size})

    # -- core per-directory pass (inner_walk_single_dir, walk.rs:430-690) --

    def _walk_one(
        self,
        entry: ToWalkEntry,
        indexed: Dict[IsolatedPath, WalkedEntry],
        to_walk: Optional[Deque[ToWalkEntry]],
        to_remove: List[dict],
        errors: List[str],
        root: str,
    ) -> int:
        path = entry.path
        try:
            parent_iso = self._iso(path, True)
        except ValueError as e:
            errors.append(str(e))
            return 0
        try:
            entries = list(os.scandir(path))
        except OSError as e:
            errors.append(f"{path}: {e}")
            return 0

        buffer: Dict[IsolatedPath, WalkedEntry] = {}
        for dirent in entries:
            accept_by_children_dir = entry.parent_dir_accepted_by_its_children
            current = dirent.path
            self.update_notifier(current, len(indexed) + len(buffer))

            per_kind = apply_all(self.rules, current)
            rejects = per_kind.get(RuleKind.REJECT_FILES_BY_GLOB)
            if rejects and not all(rejects):
                continue

            try:
                if dirent.is_symlink():  # hard-ignored (walk.rs:529-532)
                    continue
                st = dirent.stat()
                is_dir = dirent.is_dir()
            except OSError as e:
                errors.append(f"{current}: {e}")
                continue

            if is_dir:
                cr = per_kind.get(
                    RuleKind.REJECT_IF_CHILDREN_DIRECTORIES_ARE_PRESENT)
                if cr and not all(cr):
                    continue
                ca = per_kind.get(
                    RuleKind.ACCEPT_IF_CHILDREN_DIRECTORIES_ARE_PRESENT)
                if ca is not None:
                    if any(ca):
                        accept_by_children_dir = True
                    elif accept_by_children_dir is None:
                        accept_by_children_dir = False
                # Dirs are queued for descent even when accept-globs skip
                # them as entries (walk.rs:575-583 runs before the
                # accept-glob check).
                if to_walk is not None:
                    to_walk.append(ToWalkEntry(
                        current, accept_by_children_dir, path))

            accepts = per_kind.get(RuleKind.ACCEPT_FILES_BY_GLOB)
            if accepts is not None and not any(accepts):
                continue

            if accept_by_children_dir is False:
                continue

            # derive the child iso from the parent's fields — no
            # normpath / prefix-check round trip per dirent
            iso = parent_iso.child(dirent.name, is_dir)
            buffer[iso] = WalkedEntry(
                uuid4_bytes(), iso,
                FilePathMetadata.from_stat(current, st),
            )

            # Index any not-yet-seen ancestors up to (not incl.) the walk
            # root (walk.rs:617-660) — accept-globs can make a file appear
            # before its parent dir was accepted as an entry.
            ancestor = os.path.dirname(current)
            while ancestor != root and len(ancestor) > len(root):
                try:
                    aiso = self._iso(ancestor, True)
                except ValueError:
                    break
                if aiso in indexed or aiso in buffer:
                    break
                try:
                    ast = os.stat(ancestor)
                except OSError as e:
                    errors.append(f"{ancestor}: {e}")
                    ancestor = os.path.dirname(ancestor)
                    continue
                buffer[aiso] = WalkedEntry(
                    uuid4_bytes(), aiso,
                    FilePathMetadata.from_stat(ancestor, ast),
                )
                ancestor = os.path.dirname(ancestor)

        try:
            to_remove.extend(
                self.to_remove_fetcher(parent_iso, list(buffer)))
        except Exception as e:  # soft failure (walk.rs:663-672)
            errors.append(f"to_remove fetch {path}: {e}")

        total = sum(w.metadata.size_in_bytes for w in buffer.values())
        indexed.update(buffer)
        return total

    # -- DB dedup (filter_existing_paths, walk.rs:332-424) -----------------

    def _filter_existing(
        self, indexed: Dict[IsolatedPath, WalkedEntry]
    ) -> Tuple[List[WalkedEntry], List[WalkedEntry]]:
        if not indexed:
            return [], []
        rows = self.existing_paths_fetcher(list(indexed))
        by_key = {}
        for row in rows:
            iso = IsolatedPath.from_db_row(
                self.location_id, bool(row["is_dir"]),
                row["materialized_path"], row["name"], row["extension"] or "",
            )
            by_key[iso] = row
        to_create: List[WalkedEntry] = []
        to_update: List[WalkedEntry] = []
        for iso, entry in indexed.items():
            row = by_key.get(iso)
            if row is None:
                to_create.append(entry)
                continue
            db_inode = row.get("inode")
            db_inode = int.from_bytes(db_inode[:8], "big") if db_inode else None
            db_mtime = row.get("date_modified") or 0
            db_size = row.get("size_in_bytes_bytes")
            db_size = int.from_bytes(db_size, "big") if db_size else 0
            # Dir sizes are computed aggregates, not fs stat sizes, so size
            # never participates in change detection for dirs. (The
            # reference instead vetoes the whole update when a dir's stat
            # size differs from the stored aggregate — walk.rs:371-404 —
            # which suppresses nearly every dir update; deliberately not
            # mirrored.)
            changed = (
                db_inode != entry.metadata.inode
                or entry.metadata.modified_at - db_mtime > MTIME_DELTA_S
            )
            if changed:
                to_update.append(WalkedEntry(
                    row["pub_id"], iso, entry.metadata))
        return to_create, to_update
