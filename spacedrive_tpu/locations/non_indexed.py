"""Ephemeral (non-indexed) directory browsing.

Mirrors `walk` in /root/reference/core/src/location/non_indexed.rs:91:
list an arbitrary directory not belonging to any location, returning
typed entries (kind, size, dates) without touching the library DB, plus
thumbnail keys for images so the Explorer can show previews of
un-indexed folders.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from ..files import ObjectKind, kind_for_extension
from ..ops.cas import generate_cas_id


def walk_ephemeral(path: str, with_hidden_files: bool = False,
                   compute_cas_ids: bool = False) -> List[Dict]:
    """List one directory as ephemeral entries.

    compute_cas_ids also derives CAS IDs for image files (used for the
    ephemeral thumbnail queue — thumbnails are keyed by cas_id).
    """
    entries: List[Dict] = []
    with os.scandir(path) as it:
        for dirent in sorted(it, key=lambda e: e.name):
            if not with_hidden_files and dirent.name.startswith("."):
                continue
            try:
                if dirent.is_symlink():
                    continue
                st = dirent.stat()
                is_dir = dirent.is_dir()
            except OSError:
                continue
            name = dirent.name
            ext = ""
            if not is_dir:
                dot = name.rfind(".")
                if dot > 0:
                    ext = name[dot + 1:]
            kind = ObjectKind.FOLDER if is_dir else kind_for_extension(ext)
            entry = {
                "name": name if is_dir else
                (name[:name.rfind(".")] if "." in name[1:] else name),
                "extension": ext,
                "path": dirent.path,
                "is_dir": is_dir,
                "kind": int(kind),
                "size_in_bytes": st.st_size,
                "date_created": getattr(st, "st_birthtime", st.st_ctime),
                "date_modified": st.st_mtime,
                "hidden": name.startswith("."),
                "cas_id": None,
            }
            if (compute_cas_ids and not is_dir and st.st_size > 0
                    and kind == ObjectKind.IMAGE):
                try:
                    entry["cas_id"] = generate_cas_id(
                        dirent.path, st.st_size)
                except OSError:
                    pass
            entries.append(entry)
    return entries
