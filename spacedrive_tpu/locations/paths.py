"""Path algebra: the identity of every file row.

The (location_id, materialized_path, name, extension, is_dir) tuple ↔
filesystem path mapping, mirroring the semantics of the reference's
`IsolatedFilePathData`
(/root/reference/core/src/location/file_path_helper/isolated_file_path_data.rs:27-556):

- `materialized_path` is the parent directory relative to the location
  root, always "/"-separated, always starting and ending with "/"
  ("/" for the root itself).
- `name` excludes the extension for files, includes everything for dirs.
- `extension` is everything after the last dot (empty for dirs, dotfiles,
  and extension-less files; a leading dot means hidden file, not
  extension).
- the unique key in the DB is (location_id, materialized_path, name,
  extension) — see store/models.py file_path uniques.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Optional, Tuple


_FORBIDDEN_NAME = re.compile(r"/|\x00")  # POSIX rules (isolated_file_path_data.rs:181-200)


def accept_file_name(name: str) -> bool:
    return not _FORBIDDEN_NAME.search(name)


def _split_name_ext(stem: str) -> Tuple[str, str]:
    """Name/extension split: last dot wins, a dot at index 0 is a hidden
    file not an extension (isolated_file_path_data.rs:158-176)."""
    last_dot = stem.rfind(".")
    if last_dot <= 0:
        return stem, ""
    return stem[:last_dot], stem[last_dot + 1:]


def _name_ext(base: str, is_dir: bool):
    """The one name/extension split rule for every constructor —
    desynchronizing walk-time and parse-time DB keys is the failure
    this helper prevents."""
    return (base, "") if is_dir else _split_name_ext(base)


def _relative_to_location(location_path: str, full_path: str) -> str:
    loc = os.path.normpath(os.fspath(location_path))
    full = os.path.normpath(os.fspath(full_path))
    if full == loc:
        return ""
    prefix = loc.rstrip(os.sep) + os.sep
    if not full.startswith(prefix):
        raise ValueError(
            f"path {full!r} is not inside location {loc!r}"
        )
    return full[len(prefix):].replace(os.sep, "/")


def materialized_path_str(location_path: str, full_path: str) -> str:
    """Parent dir of full_path relative to the location root, normalized
    (extract_normalized_materialized_path_str, isolated_file_path_data.rs:485-513)."""
    rel = _relative_to_location(location_path, full_path)
    if not rel:
        return "/"
    parent = rel.rsplit("/", 1)[0] if "/" in rel else ""
    return f"/{parent}/" if parent else "/"


@dataclass(frozen=True)
class IsolatedPath:
    location_id: int
    materialized_path: str
    is_dir: bool
    name: str
    extension: str
    relative_path: str = field(default="", compare=False)

    # -- constructors ------------------------------------------------------

    @classmethod
    def new(cls, location_id: int, location_path: str | os.PathLike,
            full_path: str | os.PathLike, is_dir: bool) -> "IsolatedPath":
        rel = _relative_to_location(os.fspath(location_path), os.fspath(full_path))
        if not rel:  # the location root itself
            return cls(location_id, "/", True, "", "", "")
        mat = materialized_path_str(os.fspath(location_path), os.fspath(full_path))
        base = rel.rsplit("/", 1)[-1]
        name, ext = _name_ext(base, is_dir)
        return cls(location_id, mat, is_dir, name, ext, rel)

    def child(self, base: str, is_dir: bool) -> "IsolatedPath":
        """Child entry of this DIRECTORY, derived without touching the
        filesystem path algebra — the walker's per-dirent fast path
        (profiling showed normpath+prefix checks in `new()` were ~40%
        of pure walk time at 60k files; the parent's fields already
        hold everything the child needs)."""
        # mat comes from the IDENTITY fields (the same value
        # materialized_path_for_children computes), never from the
        # compare=False relative_path cache
        mat = self.materialized_path_for_children()
        rel = (f"{self.relative_path}/{base}" if self.relative_path
               else base)
        name, ext = _name_ext(base, is_dir)
        return IsolatedPath(self.location_id, mat, is_dir, name, ext, rel)

    @classmethod
    def from_relative(cls, location_id: int, relative: str) -> "IsolatedPath":
        """Parse "dir/dir2/file.txt" or "dir/sub/" (trailing slash = dir);
        from_relative_str semantics (isolated_file_path_data.rs:120-137)."""
        is_dir = relative.endswith("/")
        if relative in ("", "/"):
            return cls(location_id, "/", True, "", "", "")
        body = relative[:-1] if is_dir else relative
        body = body.lstrip("/")
        if "/" in body:
            parent, base = body.rsplit("/", 1)
            mat = f"/{parent}/"
        else:
            mat, base = "/", body
        name, ext = _name_ext(base, is_dir)
        return cls(location_id, mat, is_dir, name, ext, body)

    @classmethod
    def from_db_row(cls, location_id: int, is_dir: bool, materialized_path: str,
                    name: str, extension: str) -> "IsolatedPath":
        if not is_dir and extension:
            rel = f"{materialized_path[1:]}{name}.{extension}"
        else:
            rel = f"{materialized_path[1:]}{name}"
        return cls(location_id, materialized_path, is_dir, name, extension, rel)

    # -- algebra -----------------------------------------------------------

    @property
    def is_root(self) -> bool:
        return self.is_dir and self.materialized_path == "/" and not self.name

    def parent(self) -> "IsolatedPath":
        if self.materialized_path == "/":
            return IsolatedPath(self.location_id, "/", True, "", "", "")
        trimmed = self.materialized_path[:-1]  # drop trailing slash
        last_slash = trimmed.rfind("/")
        parent_mat = self.materialized_path[:last_slash + 1]
        parent_name = trimmed[last_slash + 1:]
        rel = self.materialized_path[1:-1]
        return IsolatedPath(self.location_id, parent_mat, True, parent_name, "", rel)

    def full_name(self) -> str:
        if self.extension:
            return f"{self.name}.{self.extension}"
        return self.name

    def materialized_path_for_children(self) -> Optional[str]:
        """What children of this dir store as their materialized_path."""
        if self.is_root:
            return "/"
        if not self.is_dir:
            return None
        return f"{self.materialized_path}{self.name}/"

    def join_on(self, location_path: str | os.PathLike) -> str:
        """Absolute filesystem path of this entry under location_path."""
        return os.path.join(
            os.fspath(location_path),
            self.relative_path.replace("/", os.sep),
        )

    def db_key(self) -> Tuple[int, str, str, str]:
        """(location_id, materialized_path, name, extension) — the DB
        unique key (schema.prisma:197 semantics)."""
        return (self.location_id, self.materialized_path, self.name, self.extension)

    def __str__(self) -> str:
        return self.relative_path
