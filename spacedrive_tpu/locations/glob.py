"""Globset-compatible glob matching.

The reference filters indexer entries with Rust's `globset` crate using
default settings (/root/reference/core/src/location/indexer/rules/mod.rs:188-210
via `Glob::parse`), whose semantics differ from Python's fnmatch:

- `*` and `?` match across `/` (default `literal_separator = false`);
- `**` must form its own path component and matches any number of
  components (including zero when written `**/`);
- `{a,b,c}` alternation, possibly nested;
- `[...]` character classes with `!` negation;
- matches are anchored: the glob must cover the whole path string.

Implemented as a translator to Python regex.
"""

from __future__ import annotations

import re
from typing import Iterable, List, Sequence


class GlobError(ValueError):
    pass


def _translate(glob: str) -> str:
    out: List[str] = []
    i, n = 0, len(glob)
    while i < n:
        c = glob[i]
        if c == "*":
            if glob.startswith("**", i):
                # `**` must be a complete component (globset InvalidRecursive).
                prev_ok = i == 0 or glob[i - 1] in "/{,"
                nxt = i + 2
                next_ok = nxt >= n or glob[nxt] in "/},"
                if not (prev_ok and next_ok):
                    raise GlobError(
                        f"recursive wildcard must form a single component: {glob!r}"
                    )
                if nxt < n and glob[nxt] == "/":
                    # `**/` — zero or more whole components.
                    out.append(r"(?s:.*/)?")
                    i = nxt + 1
                else:
                    out.append(r"(?s:.*)")
                    i = nxt
            else:
                out.append(r"(?s:.*)")
                i += 1
        elif c == "?":
            out.append(r"(?s:.)")
            i += 1
        elif c == "[":
            j = i + 1
            if j < n and glob[j] in "!^":
                j += 1
            if j < n and glob[j] == "]":
                j += 1
            while j < n and glob[j] != "]":
                j += 1
            if j >= n:
                raise GlobError(f"unclosed character class: {glob!r}")
            inner = glob[i + 1:j]
            if inner.startswith("!"):
                inner = "^" + inner[1:]
            inner = inner.replace("\\", "\\\\")
            out.append(f"[{inner}]")
            i = j + 1
        elif c == "{":
            # Find the matching close brace (nesting allowed).
            depth, j = 1, i + 1
            while j < n and depth:
                if glob[j] == "{":
                    depth += 1
                elif glob[j] == "}":
                    depth -= 1
                j += 1
            if depth:
                raise GlobError(f"unclosed alternation: {glob!r}")
            body = glob[i + 1:j - 1]
            # Split on top-level commas only.
            parts, buf, d = [], [], 0
            for ch in body:
                if ch == "{":
                    d += 1
                elif ch == "}":
                    d -= 1
                if ch == "," and d == 0:
                    parts.append("".join(buf))
                    buf = []
                else:
                    buf.append(ch)
            parts.append("".join(buf))
            out.append("(?:" + "|".join(_translate(p) for p in parts) + ")")
            i = j
        else:
            out.append(re.escape(c))
            i += 1
    return "".join(out)


class Glob:
    def __init__(self, pattern: str):
        self.pattern = pattern
        self._re = re.compile(r"(?s:\A" + _translate(pattern) + r")\Z")

    def is_match(self, path: str) -> bool:
        return self._re.match(path) is not None

    def __repr__(self) -> str:
        return f"Glob({self.pattern!r})"


class GlobSet:
    """Any-match set over several globs (globset::GlobSet::is_match)."""

    def __init__(self, patterns: Iterable[str]):
        self.globs: Sequence[Glob] = [Glob(p) for p in patterns]

    def is_match(self, path: str) -> bool:
        return any(g.is_match(path) for g in self.globs)

    @property
    def patterns(self) -> List[str]:
        return [g.pattern for g in self.globs]
