"""Filesystem watcher: inotify-backed location monitoring.

Covers the behavior of the reference's watcher subsystem
(/root/reference/core/src/location/manager/{mod,watcher/mod,watcher/linux}.rs):
a per-location recursive watcher whose normalized events — create, modify,
rename (cookie-paired MOVED_FROM/MOVED_TO), delete — are debounced and
dispatched as `light_scan_location` calls on the affected directories.

The reference uses the `notify` crate; this image has no watchdog wheel,
so inotify is driven directly through ctypes (Linux-only, with a polling
fallback for other platforms/tests).
"""

from __future__ import annotations

import asyncio
import ctypes
import ctypes.util
import os
import struct
from typing import Callable, Dict, Optional, Set

from .. import flags, tasks

# inotify event masks (linux/inotify.h)
IN_CREATE = 0x00000100
IN_DELETE = 0x00000200
IN_MODIFY = 0x00000002
IN_CLOSE_WRITE = 0x00000008
IN_MOVED_FROM = 0x00000040
IN_MOVED_TO = 0x00000080
IN_DELETE_SELF = 0x00000400
IN_MOVE_SELF = 0x00000800
IN_ISDIR = 0x40000000
IN_Q_OVERFLOW = 0x00004000
IN_IGNORED = 0x00008000
IN_NONBLOCK = 0x00000800
IN_CLOEXEC = 0x00080000

WATCH_MASK = (IN_CREATE | IN_DELETE | IN_CLOSE_WRITE | IN_MOVED_FROM |
              IN_MOVED_TO | IN_DELETE_SELF | IN_MOVE_SELF)

_EVENT_HDR = struct.Struct("iIII")  # wd, mask, cookie, len

DEBOUNCE_S = 0.1  # the reference debounces per-OS around 100ms


class _Inotify:
    """Thin ctypes wrapper over the three inotify syscalls."""

    def __init__(self):
        libc_name = ctypes.util.find_library("c") or "libc.so.6"
        self._libc = ctypes.CDLL(libc_name, use_errno=True)
        self.fd = self._libc.inotify_init1(IN_NONBLOCK | IN_CLOEXEC)
        if self.fd < 0:
            raise OSError(ctypes.get_errno(), "inotify_init1 failed")

    def add_watch(self, path: str, mask: int = WATCH_MASK) -> int:
        wd = self._libc.inotify_add_watch(
            self.fd, os.fsencode(path), mask)
        if wd < 0:
            raise OSError(ctypes.get_errno(), f"inotify_add_watch {path}")
        return wd

    def rm_watch(self, wd: int) -> None:
        self._libc.inotify_rm_watch(self.fd, wd)

    def read_events(self):
        """Drain pending events → [(wd, mask, cookie, name)]."""
        try:
            buf = os.read(self.fd, 65536)
        except BlockingIOError:
            return []
        events = []
        offset = 0
        while offset + _EVENT_HDR.size <= len(buf):
            wd, mask, cookie, length = _EVENT_HDR.unpack_from(buf, offset)
            offset += _EVENT_HDR.size
            name = buf[offset:offset + length].split(b"\x00", 1)[0].decode(
                "utf-8", "surrogateescape")
            offset += length
            events.append((wd, mask, cookie, name))
        return events

    def close(self) -> None:
        os.close(self.fd)


class PollingWatcher:
    """mtime/entry-signature polling fallback with the LocationWatcher
    contract — the path platforms without inotify take (the reference's
    notify crate falls back to polling the same way,
    manager/watcher/mod.rs). Selected by make_watcher when inotify is
    unavailable, or forced with SDTPU_WATCHER=poll (how Linux CI tests
    the fallback it would otherwise never execute).

    Each poll walks the tree and compares a per-directory signature
    (entry names, kinds, sizes, mtimes); changed still-present dirs
    emit on_dirty(relpath) — vanished ones are covered by their
    parent's changed signature, mirroring IN_DELETE_SELF handling. O(tree) per tick — the price of portability;
    the interval keeps it cheap for the location sizes that lack
    inotify in practice."""

    INTERVAL_S = 1.0
    SYNC_SEED_DIRS = 2000

    def __init__(self, location_id: int, root: str,
                 on_dirty: Callable[[str], None],
                 owner: str = "locations"):
        self.location_id = location_id
        self.root = os.path.normpath(root)
        self.on_dirty = on_dirty
        # Baseline semantics vs loop latency: a synchronous walk here
        # gives an exact watch-time baseline (nothing created after
        # watch() can hide in it) but blocks the event loop on large
        # trees. Hybrid: walk synchronously up to SYNC_SEED_DIRS dirs
        # (tests and typical locations), else seed on the first tick in
        # a thread — big locations always pair watch() with a full
        # scan chain, which covers the seeding window.
        self._sigs: Optional[Dict[str, tuple]] = self._snapshot(
            limit=self.SYNC_SEED_DIRS)
        self._task = tasks.spawn(
            f"watcher-poll/{location_id}", self._poll_loop(), owner=owner)

    def _dir_sig(self, path: str) -> Optional[tuple]:
        try:
            with os.scandir(path) as it:
                ents = []
                for e in it:
                    try:
                        st = e.stat(follow_symlinks=False)
                        ents.append((e.name, e.is_dir(
                            follow_symlinks=False), st.st_size,
                            st.st_mtime_ns))
                    except OSError:
                        continue
            return tuple(sorted(ents))
        except OSError:
            return None

    def _snapshot(self, limit: Optional[int] = None
                  ) -> Optional[Dict[str, tuple]]:
        """Signature map of the whole tree; with `limit`, None when the
        tree exceeds that many directories (caller falls back to
        thread-seeded baseline)."""
        sigs: Dict[str, tuple] = {}
        stack = [self.root]
        while stack:
            d = stack.pop()
            sig = self._dir_sig(d)
            if sig is None:
                continue
            sigs[d] = sig
            if limit is not None and len(sigs) > limit:
                return None
            stack.extend(os.path.join(d, name)
                         for name, is_dir, _, _ in sig if is_dir)
        return sigs

    async def _poll_loop(self) -> None:
        if self._sigs is None:  # big tree: seed off the event loop
            self._sigs = await asyncio.to_thread(self._snapshot)
        while True:
            await asyncio.sleep(self.INTERVAL_S)
            try:
                new = await asyncio.to_thread(self._snapshot)
                old = self._sigs
                self._sigs = new
                # Vanished dirs are NOT emitted (the inotify path's
                # IN_DELETE_SELF rule: scanning a deleted dir only
                # errors; the parent's changed signature covers the
                # cleanup) — EXCEPT the root, which has no watched
                # parent: a vanished root rescans "" to surface the
                # missing-path state, like IN_DELETE_SELF on root.
                dirty = {d for d in set(old) | set(new)
                         if old.get(d) != new.get(d) and d in new}
                if self.root in old and self.root not in new:
                    dirty.add(self.root)
                for d in sorted(dirty):
                    rel = os.path.relpath(d, self.root)
                    # forward slashes: the materialized-path convention
                    # on every platform (the fallback is for non-Linux)
                    self.on_dirty("" if rel == "."
                                  else rel.replace(os.sep, "/"))
            except asyncio.CancelledError:
                raise
            except Exception:
                # a throwing on_dirty must not silently kill the
                # watcher — the inotify path survives the equivalent
                continue

    def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None


def inotify_available() -> bool:
    try:
        _Inotify().close()
        return True
    except (OSError, AttributeError):
        return False


def make_watcher(location_id: int, root: str,
                 on_dirty: Callable[[str], None],
                 owner: str = "locations"):
    """inotify watcher when the platform has it, polling otherwise
    (or when SDTPU_WATCHER=poll forces the fallback under test).
    Must be called on the running event loop the watcher will live on.
    `owner` is the supervisor ownership path the watcher's background
    tasks register under (tasks.py)."""
    if flags.get("SDTPU_WATCHER") != "poll" and inotify_available():
        return LocationWatcher(location_id, root, on_dirty)
    return PollingWatcher(location_id, root, on_dirty, owner=owner)


class LocationWatcher:
    """Recursive watcher for one location; emits debounced dir rescans.

    `on_dirty(sub_path: str)` is called (on the event loop) for each
    directory (location-relative, '' = root) with changes after the
    debounce window — the Locations actor maps this to
    light_scan_location (manager/mod.rs → watcher dispatch).
    """

    def __init__(self, location_id: int, root: str,
                 on_dirty: Callable[[str], None]):
        self.location_id = location_id
        self.root = os.path.normpath(root)
        self.on_dirty = on_dirty
        self.loop = asyncio.get_running_loop()
        self._ino = _Inotify()
        self._wd_to_path: Dict[int, str] = {}
        self._path_to_wd: Dict[str, int] = {}
        self._dirty: Set[str] = set()
        self._flush_handle: Optional[asyncio.TimerHandle] = None
        self._watch_tree(self.root)
        self.loop.add_reader(self._ino.fd, self._on_readable)

    # -- watch management --------------------------------------------------

    def _watch_tree(self, path: str) -> None:
        try:
            wd = self._ino.add_watch(path)
        except OSError:
            return
        self._wd_to_path[wd] = path
        self._path_to_wd[path] = wd
        try:
            with os.scandir(path) as it:
                for e in it:
                    if e.is_dir(follow_symlinks=False):
                        self._watch_tree(e.path)
        except OSError:
            pass

    def _unwatch(self, path: str) -> None:
        for p in [p for p in self._path_to_wd
                  if p == path or p.startswith(path + os.sep)]:
            wd = self._path_to_wd.pop(p)
            self._wd_to_path.pop(wd, None)
            self._ino.rm_watch(wd)

    # -- event pump --------------------------------------------------------

    def _on_readable(self) -> None:
        for wd, mask, cookie, name in self._ino.read_events():
            if mask & IN_Q_OVERFLOW:
                # Events were lost kernel-side; every watched dir may be
                # stale, and the per-dir scan is shallow — dirty them all.
                for p in list(self._path_to_wd):
                    self._mark_dirty(p)
                continue
            if mask & IN_IGNORED:
                # Kernel dropped this watch (dir deleted/unmounted):
                # purge it from the maps, else a reused wd number could
                # later be rm_watch'd out from under a live watch.
                stale = self._wd_to_path.pop(wd, None)
                if stale is not None:
                    self._path_to_wd.pop(stale, None)
                continue
            parent = self._wd_to_path.get(wd)
            if parent is None:
                continue
            full = os.path.join(parent, name) if name else parent
            if mask & IN_ISDIR:
                if mask & (IN_CREATE | IN_MOVED_TO):
                    self._watch_tree(full)
                    self._mark_dirty(full)
                elif mask & (IN_DELETE | IN_MOVED_FROM):
                    self._unwatch(full)
            if mask & (IN_DELETE_SELF | IN_MOVE_SELF):
                # The dir itself is gone — scanning it would only error.
                # Its PARENT's listing changed; dirty that (root included:
                # a deleted location root rescans as root, surfacing the
                # missing-path state).
                if parent == self.root:
                    self._mark_dirty(self.root)
                else:
                    self._mark_dirty(os.path.dirname(parent))
                continue
            self._mark_dirty(parent)

    def _mark_dirty(self, dir_path: str) -> None:
        self._dirty.add(dir_path)
        if self._flush_handle is None:
            self._flush_handle = self.loop.call_later(
                DEBOUNCE_S, self._flush)

    def _flush(self) -> None:
        self._flush_handle = None
        dirty, self._dirty = self._dirty, set()
        for d in dirty:
            rel = os.path.relpath(d, self.root)
            self.on_dirty("" if rel == "." else rel.replace(os.sep, "/"))

    def close(self) -> None:
        if self._flush_handle is not None:
            self._flush_handle.cancel()
        self.loop.remove_reader(self._ino.fd)
        self._ino.close()


class Locations:
    """The locations actor: online-location set + per-location watchers
    (manager/mod.rs:44-681). Watch events run light_scan_location on a
    worker thread, keeping the loop responsive."""

    def __init__(self, node, backend: str = "auto"):
        self.node = node
        self.backend = backend
        self.watchers: Dict[tuple, LocationWatcher] = {}
        self._scanning: Set[tuple] = set()
        self._pending: Dict[tuple, Set[str]] = {}
        # Supervisor subtree for watcher poll loops + dirty scans:
        # Node.shutdown's reap sweeps it even though the Locations
        # actor itself has no stop hook on the node.
        self._owner = f"{node.task_owner}/locations"

    def watch_location(self, library, location_id: int) -> bool:
        loc = library.db.run("location.path_by_id", (location_id,))
        if loc is None or not loc["path"] or not os.path.isdir(loc["path"]):
            return False
        key = (library.id, location_id)
        if key in self.watchers:
            return True

        def on_dirty(sub_path: str, _key=key, _lib=library,
                     _loc=location_id):
            # Coalesce: events landing while a scan runs are queued and
            # drained afterwards, never dropped.
            pending = self._pending.setdefault(_key, set())
            pending.add(sub_path)
            if _key in self._scanning:
                return
            self._scanning.add(_key)

            async def scan():
                from .shallow import light_scan_location
                try:
                    while self._pending.get(_key):
                        batch = self._pending.pop(_key)
                        self._pending[_key] = set()
                        for sub in batch:
                            try:
                                await asyncio.to_thread(
                                    light_scan_location, _lib, _loc,
                                    sub or None, self.backend)
                            except Exception as e:
                                self.node.events.emit({
                                    "type": "WatcherError",
                                    "location_id": _loc, "error": str(e)})
                    self.node.events.invalidate_query(
                        _lib.id, "search.paths")
                finally:
                    self._pending.pop(_key, None)
                    self._scanning.discard(_key)
            # Supervised spawn: the registry's strong reference is the
            # fix for the dropped-task bug this function shipped with —
            # `asyncio.get_event_loop().create_task(scan())` held NO
            # reference, so a gc.collect() mid-scan could destroy (and
            # cancel) the scan task (tests/test_tasks.py pins survival).
            tasks.spawn(f"watcher-scan/{_loc}", scan(), owner=self._owner)

        self.watchers[key] = make_watcher(
            location_id, loc["path"], on_dirty, owner=self._owner)
        return True

    def unwatch_location(self, library, location_id: int) -> None:
        w = self.watchers.pop((library.id, location_id), None)
        if w is not None:
            w.close()

    def close(self) -> None:
        for w in self.watchers.values():
            w.close()
        self.watchers.clear()
