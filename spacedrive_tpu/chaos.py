"""Chaos plane: declared fault injection for the robustness rails.

PRs 3-13 built every robustness surface this engine has — timeout
budgets, bounded channels with shed/coalesce policies, jobs admission
refusal, the supervisor ownership tree, the race recorder, and the
health/fleet observatories — but nothing ever injected a fault against
them, so the declared capacities and budgets were untested guesses and
the recovery paths were bare counters. This module is the registry the
whole repo's pattern demands for that gap: every injection site is a
DECLARED fault point (name, site, allowed kinds, doc) at the bottom of
THIS module, armed per-run through the `SDTPU_CHAOS` spec flag, drawn
from a SEEDED deterministic RNG so a failing storm replays exactly.

Spec grammar (`SDTPU_CHAOS`)::

    <point>=<fault>[,<fault>...][;<point>=...]
    fault := delay:<dur>[:<prob>]          # 50ms | 0.2s | bare seconds
           | error|drop|disconnect|wedge|corrupt[:<prob>]

e.g. ``p2p.tunnel.frame=drop:0.01,delay:50ms;store.commit=error:0.05``.
Undeclared point names and kinds outside a point's declared set are
REFUSED at parse (`ChaosSpecError`) — a typo'd storm must fail loudly,
not silently run fault-free.

Fault kinds (what a firing injection does at the seam):

- ``delay``      — sleep the parsed duration (latency weather);
- ``error``      — raise ``ChaosError`` (a ConnectionError subclass:
  recovery paths treat it exactly like a failed peer/resource);
- ``drop``       — the call site discards the operation (a lost frame,
  a swallowed page) and flow control must recover;
- ``disconnect`` — raise ``ChaosDisconnect`` (torn transport);
- ``wedge``      — park the seam (`WEDGE_S` sleep) so the call site's
  declared timeout budget is what frees it — the direct test of the
  timeouts.py table;
- ``corrupt``    — the call site tampers the payload bytes (AEAD tag
  failure on the peer, schema rejection upstream).

Every firing counts into ``sd_chaos_injected_total{name,kind}`` BEFORE
the effect lands, so an artifact can always reconcile observed
degradation against injected cause. Determinism: each armed fault
point draws from its own ``random.Random`` seeded from
(`SDTPU_CHAOS_SEED`, point name), so one site's draw sequence does not
depend on how other sites interleave.

Disarmed cost is the telemetry contract: `hit()` is one module-global
load + None check (<5 µs, budget-tested like telemetry's disabled
path). Sites pass `only=` to restrict a draw to the kinds that seam
can express (a recv path cannot drop an AEAD frame without desyncing
the counter nonce; it can delay, wedge, or disconnect).

Design constraints (same as timeouts.py/channels.py): stdlib +
flags/telemetry only, importable from every layer without cycles.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from . import flags
from .telemetry import CHAOS_INJECTED

__all__ = [
    "FaultPoint", "Fault", "FAULTS", "KINDS", "declare_fault",
    "ChaosError", "ChaosDisconnect", "ChaosSpecError",
    "arm", "disarm", "rearm_from_env", "armed", "armed_spec",
    "hit", "apply_async", "apply_sync", "fault_table_markdown",
    "WEDGE_S",
]

KINDS = ("delay", "error", "drop", "disconnect", "wedge", "corrupt")

# A wedged seam parks this long; the call site's declared budget (or
# the harness teardown cancelling the task) is what frees it — wedge
# exists precisely to prove those budgets fire.
WEDGE_S = 3600.0


class ChaosError(ConnectionError):
    """An injected `error` fault. ConnectionError subclass on purpose:
    every recovery path that tolerates a failed peer/resource already
    catches it — chaos must exercise those paths, not invent new
    exception plumbing."""


class ChaosDisconnect(ChaosError):
    """An injected `disconnect` fault (torn transport mid-operation)."""


class ChaosSpecError(ValueError):
    """A malformed/undeclared SDTPU_CHAOS spec entry (refused at
    parse — armed runs fail loudly, never silently fault-free)."""


@dataclass(frozen=True)
class FaultPoint:
    name: str                 # dotted id: "<layer>.<seam>"
    site: str                 # "module.py function" (docs/table)
    kinds: Tuple[str, ...]    # subset of KINDS this seam can express
    doc: str


@dataclass(frozen=True)
class Fault:
    """One firing: what `hit()` hands the call site."""
    name: str
    kind: str
    delay_s: float = 0.0      # parsed duration (delay kind only)


# Import-time declaration registry (same contract as TIMEOUTS /
# CHANNELS / BACKOFFS): bounded by the declarations at the bottom of
# this module, never by runtime traffic.
FAULTS: Dict[str, FaultPoint] = {}  # sdlint: ok[unbounded-growth]


def declare_fault(name: str, site: str, kinds: Sequence[str],
                  doc: str) -> FaultPoint:
    if name in FAULTS:
        raise ValueError(f"fault point {name!r} declared twice")
    if not kinds:
        raise ValueError(f"fault point {name!r}: no kinds")
    for k in kinds:
        if k not in KINDS:
            raise ValueError(f"fault point {name!r}: unknown kind {k!r}")
    p = FaultPoint(name, site, tuple(kinds), doc)
    FAULTS[name] = p
    return p


# -- spec parsing ------------------------------------------------------------

def _parse_duration(s: str) -> float:
    s = s.strip().lower()
    try:
        if s.endswith("ms"):
            d = float(s[:-2]) / 1000.0
        elif s.endswith("s"):
            d = float(s[:-1])
        else:
            d = float(s)
    except ValueError:
        raise ChaosSpecError(f"bad duration {s!r} (want 50ms/0.2s/0.2)")
    # Range-checked AT PARSE like everything else in the grammar: a
    # negative delay would crash sync seams with time.sleep's
    # ValueError (and silently no-op async ones), inf/nan would be an
    # undeclared permanent wedge — `wedge` is the declared spelling.
    if not (0.0 <= d <= WEDGE_S):
        raise ChaosSpecError(
            f"bad duration {s!r}: must be within [0, {WEDGE_S:g}s] "
            "(use the `wedge` kind for park-forever)")
    return d


def _parse_prob(s: str, where: str) -> float:
    try:
        p = float(s)
    except ValueError:
        raise ChaosSpecError(f"{where}: bad probability {s!r}")
    if not 0.0 <= p <= 1.0:
        raise ChaosSpecError(f"{where}: probability {p} outside [0, 1]")
    return p


@dataclass(frozen=True)
class _ArmedFault:
    kind: str
    prob: float
    delay_s: float = 0.0


def parse_spec(spec: str) -> Dict[str, List[_ArmedFault]]:
    """`SDTPU_CHAOS` grammar → {point name: armed faults}. Refuses
    undeclared names and kinds a point did not declare."""
    out: Dict[str, List[_ArmedFault]] = {}
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        name, sep, faults = entry.partition("=")
        name = name.strip()
        if not sep or not faults.strip():
            raise ChaosSpecError(
                f"chaos spec entry {entry!r}: want <point>=<fault>[,...]")
        point = FAULTS.get(name)
        if point is None:
            raise ChaosSpecError(
                f"chaos spec names undeclared fault point {name!r} "
                "(declare it in spacedrive_tpu/chaos.py)")
        armed: List[_ArmedFault] = []
        for f in faults.split(","):
            parts = [p.strip() for p in f.strip().split(":")]
            kind = parts[0]
            if kind not in KINDS:
                raise ChaosSpecError(
                    f"{name}: unknown fault kind {kind!r}")
            if kind not in point.kinds:
                raise ChaosSpecError(
                    f"{name}: kind {kind!r} not declared for this "
                    f"point (declared: {', '.join(point.kinds)})")
            if kind == "delay":
                if len(parts) < 2:
                    raise ChaosSpecError(
                        f"{name}: delay needs a duration "
                        "(delay:<dur>[:<prob>])")
                if len(parts) > 3:
                    raise ChaosSpecError(
                        f"{name}: delay takes at most a duration and "
                        "a probability (delay:<dur>[:<prob>])")
                dur = _parse_duration(parts[1])
                prob = _parse_prob(parts[2], name) \
                    if len(parts) > 2 else 1.0
                armed.append(_ArmedFault("delay", prob, dur))
            else:
                if len(parts) > 2:
                    raise ChaosSpecError(
                        f"{name}: {kind} takes at most a probability")
                prob = _parse_prob(parts[1], name) \
                    if len(parts) > 1 else 1.0
                armed.append(_ArmedFault(kind, prob))
        out.setdefault(name, []).extend(armed)
    return out


# -- arming ------------------------------------------------------------------
# _ARMED is the hot-path switch: None = disarmed, and hit() pays ONE
# module-global load to find out (the telemetry disabled-path shape).
# Faults and their per-point RNGs live in ONE structure rebound
# atomically by arm()/disarm(), so a worker thread mid-hit() during a
# concurrent rearm always sees a consistent snapshot (never a spec
# whose RNG table was cleared under it). RNGs are seeded (seed, name)
# so each site's draw sequence is deterministic regardless of
# cross-site interleaving.

_ARMED: Optional[
    Dict[str, Tuple[List[_ArmedFault], random.Random]]] = None
_spec_str: str = ""
_seed: int = 0


def arm(spec: str, seed: Optional[int] = None) -> None:
    """Parse and install a chaos spec (refusing bad entries). An empty
    spec disarms."""
    global _ARMED, _spec_str, _seed
    parsed = parse_spec(spec) if spec else {}
    _seed = int(seed if seed is not None
                else flags.get("SDTPU_CHAOS_SEED"))
    armed = {name: (faults, random.Random(f"{_seed}:{name}"))
             for name, faults in parsed.items()}
    _spec_str = spec if parsed else ""
    _ARMED = armed or None


def disarm() -> None:
    global _ARMED, _spec_str
    _ARMED = None
    _spec_str = ""


def rearm_from_env() -> None:
    """Re-read SDTPU_CHAOS / SDTPU_CHAOS_SEED (process bootstrap and
    tests; import does the same once at the bottom of this module)."""
    arm(str(flags.get("SDTPU_CHAOS") or ""))


def armed() -> bool:
    return _ARMED is not None


def armed_spec() -> str:
    """The spec string currently armed ('' when disarmed) — what the
    load harness records into its artifact."""
    return _spec_str


def armed_point(name: str) -> bool:
    """True when the armed spec names this fault point. A zero-draw
    pre-check for sites that would otherwise loop hit() across large
    batches (the staging seam draws per ROW so a 0.1-probability EIO
    storm speckles a batch instead of all-or-nothing) — skipping the
    loop when disarmed keeps the hot path at one dict probe."""
    spec = _ARMED
    return spec is not None and name in spec


def hit(name: str, only: Optional[Sequence[str]] = None
        ) -> Optional[Fault]:
    """One draw at a fault point. Returns the Fault to apply, or None
    (disarmed, point not in the spec, or no probability fired).

    `only` restricts the draw to kinds this call site can express —
    an armed kind outside it is skipped WITHOUT consuming a random
    draw, so the same seed fires identically whichever sites filter.
    Every returned fault is already counted into
    sd_chaos_injected_total{name,kind}."""
    spec = _ARMED
    if spec is None:
        return None
    entry = spec.get(name)
    if entry is None:
        return None
    armed_faults, rng = entry
    for f in armed_faults:
        if only is not None and f.kind not in only:
            continue
        if f.prob < 1.0 and rng.random() >= f.prob:
            continue
        CHAOS_INJECTED.labels(name=name, kind=f.kind).inc()
        return Fault(name, f.kind, f.delay_s)
    return None


async def apply_async(f: Fault) -> bool:
    """Generic async effect for a drawn fault. Returns True when the
    call site must DROP the operation; `corrupt` also returns False —
    tampering is site-specific (the site knows its payload bytes)."""
    if f.kind == "delay":
        await asyncio.sleep(f.delay_s)
        return False
    if f.kind == "wedge":
        await asyncio.sleep(WEDGE_S)
        return False
    if f.kind == "drop":
        return True
    if f.kind == "disconnect":
        raise ChaosDisconnect(f"chaos: injected disconnect at {f.name}")
    if f.kind == "error":
        raise ChaosError(f"chaos: injected error at {f.name}")
    return False  # corrupt: the site tampers its own bytes


def apply_sync(f: Fault) -> bool:
    """`apply_async` for synchronous seams (store commit, off-loop
    ingest): delay/wedge block the calling thread — which is the
    injected symptom, never the event loop (the only callers are
    already off-loop by the blocking-async discipline)."""
    if f.kind == "delay":
        time.sleep(f.delay_s)
        return False
    if f.kind == "wedge":
        time.sleep(WEDGE_S)
        return False
    if f.kind == "drop":
        return True
    if f.kind == "disconnect":
        raise ChaosDisconnect(f"chaos: injected disconnect at {f.name}")
    if f.kind == "error":
        raise ChaosError(f"chaos: injected error at {f.name}")
    return False


def fault_table_markdown() -> str:
    """Generated fault-point table (docs/architecture.md §Chaos)."""
    out = ["| Fault point | Site | Kinds | Covers |",
           "| --- | --- | --- | --- |"]
    for name in sorted(FAULTS):
        p = FAULTS[name]
        doc = " ".join(p.doc.split())
        out.append(f"| `{name}` | {p.site} | {', '.join(p.kinds)} "
                   f"| {doc} |")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# THE fault-point namespace. Keep alphabetical; every entry must be
# referenced by a chaos.hit("<name>") literal at ≥1 injection site —
# tests/test_chaos.py's static↔runtime drift check fails on a declared
# point nothing injects (and on an injection site naming an undeclared
# point).
# ---------------------------------------------------------------------------

declare_fault(
    "api.http.dispatch", "api/server.py _rspc_http",
    ("delay", "error"),
    "rspc HTTP dispatch on the API host, before the procedure runs: "
    "delay = a slow backend, error = a failing one. Fires inside the "
    "admission-controlled region, so storms drive the api.http."
    "inflight shed path.")

declare_fault(
    "api.ws.send", "api/server.py WsSubscriptionPump._drain",
    ("delay", "drop", "wedge"),
    "One websocket frame leaving a subscription pump: delay = a slow "
    "consumer, wedge = a dead one that never reads (the channel must "
    "shed, the pump must never wedge the node), drop = a lost frame.")

declare_fault(
    "fleet.poll", "fleet.py FleetMonitor._poll_peer",
    ("delay", "error", "wedge"),
    "A fleet-observatory obs.health fetch from one peer: wedge parks "
    "the fetch until the declared fleet.poll budget fires and the "
    "peer's row goes stale-degraded; disarming must let the row "
    "recover.")

declare_fault(
    "incidents.write", "incidents.py IncidentObservatory._write",
    ("delay",),
    "The WAL-style bundle write, drawn twice: once mid-body (a delay "
    "there widens the torn-.json.tmp window) and once after the full "
    "body lands but before the atomic rename (the complete-tmp "
    "window). The kill -9 recovery test parks the writer in each "
    "window and asserts restart recovers a valid bundle or none — "
    "never a torn final file.")

declare_fault(
    "p2p.tunnel.frame", "p2p/proto.py Tunnel.send/recv",
    ("delay", "drop", "disconnect", "wedge", "corrupt"),
    "One sealed frame crossing a tunnel. Send side can drop (lost "
    "frame — flow control recovers) or corrupt (AEAD tag failure on "
    "the peer); recv side delays/wedges/disconnects only (dropping a "
    "received AEAD frame would desync the counter nonce, which is a "
    "different bug than the one being injected).")

declare_fault(
    "p2p.tunnel.open", "p2p/manager.py P2PManager.open_stream",
    ("delay", "error", "wedge"),
    "Outbound dial + handshake: error = unreachable peer (the "
    "announce loop's declared backoff path), wedge = a half-open "
    "socket the p2p.connect deadline must free.")

declare_fault(
    "persist.crashpoint", "persist.py crashpoint (every durability edge)",
    ("delay",),
    "One declared durability edge inside the persist write seam "
    "(tmp-open / tmp-partial / tmp-full / fsync-file / renamed), "
    "drawn between every two steps of every atomic/WAL artifact "
    "write: a delay widens that window for racing killers, and "
    "SDTPU_PERSIST_CRASHPOINT=<artifact>:<edge> turns the same edge "
    "into a SIGKILL — how tools/crash_grid.py proves valid-or-absent "
    "recovery at every edge systematically.")

declare_fault(
    "stage.native.read", "ops/staging.py stage_batch_native",
    ("delay", "error", "corrupt"),
    "The native packed-staging seam, per ROW of a staged batch: error "
    "= EIO from a flaky disk, corrupt = a torn/short read (both flip "
    "the row's status so it degrades to the per-file Python reader — "
    "identify throughput drops, digests stay bit-identical, the ring "
    "never wedges); delay = once per batch, slow-disk weather on the "
    "stage lane.")

declare_fault(
    "store.commit", "store/db.py Database.tx",
    ("delay", "error"),
    "Write-transaction commit: error = sqlite BUSY (an external "
    "writer holding the file lock), absorbed by the declared "
    "store.busy backoff so injected BUSY degrades to latency instead "
    "of job failure; delay = slow fsync weather under the write lock.")

declare_fault(
    "store.group_commit", "store/actor.py WriteActor._run_group",
    ("delay", "error"),
    "A coalesced group on the single-writer actor, after every batch "
    "body ran and before COMMIT: delay parks the whole group with the "
    "write lock held (the kill -9 durability storm's window — every "
    "batch in the group must either commit atomically or vanish "
    "atomically across a crash), error fails the group to all its "
    "waiters (each one sees its transaction roll back, exactly like a "
    "raw tx() commit failure).")

declare_fault(
    "sync.clone.ack", "sync/ingest.py pump_clone_stream",
    ("delay", "drop", "disconnect"),
    "A clone-stream watermark ack leaving the receiver: drop leaves "
    "the originator's window full until its sync.clone.ack budget "
    "fires; the stream dies and the per-op pull loop finishes the "
    "tail.")

declare_fault(
    "sync.clone.page", "sync/clone_serve.py serve_clone_stream",
    ("delay", "drop", "disconnect", "wedge"),
    "One blob page leaving the windowed clone originator: disconnect "
    "is the mid-clone torn stream (reconnect must converge byte-"
    "identically from the receiver's durable watermark), drop is a "
    "lost page the ack window starves on, wedge parks the stream "
    "against the drain/ack budgets.")

declare_fault(
    "sync.ingest.apply", "sync/manager.py receive_crdt_operations",
    ("delay", "error"),
    "Remote-op ingest on the receiving replica: error fails the page "
    "like a poisoned batch (the pull loop's frozen-watermark recovery "
    "re-serves it), delay is slow-apply weather under storm.")


# Import-time arming from the environment (the same shape as
# telemetry's _ENABLED): a process started with SDTPU_CHAOS set runs
# armed; rearm_from_env()/arm()/disarm() re-decide for tests and the
# load harness.
rearm_from_env()
