"""Central bounded-channel registry — the resource twin of
timeouts.py's budget table and tasks.py's supervisor.

Every producer/consumer channel in the engine (job run-queue, worker
command inbox, sync ingest inbox/outbox, thumbnailer batch queue, ws
subscription buffers, the tunnel's send_nowait frame window) is
DECLARED here — name, capacity, overflow policy, owner, and a
docstring — and constructed through `channel(name)` / `window(name)` /
`bounded_dict(name)`. Before this module the tree held a dozen
silently unbounded buffers (`asyncio.Queue()` with no maxsize, a bare
jobs deque, per-subscriber ws buffering limited only by RAM): ROADMAP
item 3's admission-control and shed-load work has nowhere to land
while any producer can absorb unbounded memory the moment its consumer
stalls. tools/sdlint's queue-discipline / backpressure /
unbounded-growth passes now fail the build on a bare cross-task queue,
an unbudgeted blocking put, or a grow-only collection in a long-lived
component; this registry is the sanctioned shape they all point at.

Overflow policies (what a full channel does with the next put):

- ``block``    — `await put()` waits for space under the contract's
  declared `put_budget` (a timeouts.py name: the wait is bounded and a
  fired budget counts into `sd_timeout_fired_total`). `put_nowait` on
  a full block channel is a programming error: it records a
  ``chan_overflow`` sanitizer violation and raises ChannelFull.
- ``shed_oldest`` — evict the head to admit the new item (regenerable
  work: thumbnail batches, stale worker commands).
- ``shed_new``  — drop the incoming item (admission control: the jobs
  run-queue refuses, it does not balloon).
- ``coalesce``  — `put(item, key=...)` replaces a pending item with
  the same key in place (telemetry snapshots, ingest notifications);
  on full with no key match it sheds the new item.

Every drop/replacement counts into `sd_chan_shed_total{name}`; depth
and high-water feed `sd_chan_depth`/`sd_chan_high_water{name}`, and
blocked producers observe into `sd_chan_put_block_seconds{name}`.
Effective capacity = declared capacity × `SDTPU_CHAN_SCALE` (flags.py),
read once at channel construction. `sanitize.install()` arms the
registry (`arm()`): a depth that would exceed the declared capacity —
only reachable through the external-buffer `Window` (a send_nowait
burst past the declared window) or a nowait put on a full block
channel — is a ``chan_overflow`` violation, raised in tier-1 and
counted in production.

README's channel table is generated from this registry
(`python -m tools.sdlint --chan-table`).

Design constraints (same as flags.py / timeouts.py): stdlib +
flags/telemetry/timeouts only, importable from every layer without
cycles. Channels are loop-thread-only like asyncio.Queue —
cross-thread producers go through `loop.call_soon_threadsafe`
(exactly how the ws emit path already crosses); the pure-sync surface
(`put_nowait`/`get_nowait`/`len`/`remove`) also works loop-less, which
is how the jobs run-queue serves synchronous construction paths.
"""

from __future__ import annotations

import asyncio
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Any, Callable, Deque, Dict, Iterator, List, Optional

from . import flags
from .telemetry import (
    CHAN_DEPTH,
    CHAN_HIGH_WATER,
    CHAN_PUT_BLOCK_SECONDS,
    CHAN_SHED,
)
from .timeouts import TIMEOUTS, with_timeout

__all__ = [
    "ChannelContract", "CHANNELS", "declare_channel", "capacity",
    "channel", "window", "bounded_dict", "Channel", "Window",
    "BoundedDict", "ChannelFull", "arm", "disarm",
    "chan_table_markdown",
]

POLICIES = ("block", "shed_oldest", "shed_new", "coalesce")
KINDS = ("queue", "window", "cache")


class ChannelFull(RuntimeError):
    """put_nowait on a full block-policy channel (producers must use
    the budgeted `await put()` there — the backpressure pass flags the
    call site statically; this is its runtime twin)."""


@dataclass(frozen=True)
class ChannelContract:
    name: str               # dotted id: "<layer>.<what>"
    capacity: int           # items before the overflow policy engages
    policy: str             # block | shed_oldest | shed_new | coalesce
    owner: str              # component that drains it (docs/table)
    doc: str
    put_budget: Optional[str] = None  # timeouts.py name (block queues)
    kind: str = "queue"     # queue | window (external buffer) | cache
    # True for HISTORY rings whose overflow policy is how they age by
    # design (flight-recorder timeline, health sample rings, the
    # latest-wins worker command inbox): the health engine reads their
    # shed rate as normal aging, not as saturation evidence.
    sheds_expected: bool = False


CHANNELS: Dict[str, ChannelContract] = {}

# Process-lifetime depth peak per channel NAME, backing the
# sd_chan_high_water gauge across instance churn. Keyed by declared
# names only, so it is bounded by the registry itself. The peak
# compare-and-set runs under _HW_LOCK: channels are loop-affine, but
# the pure-sync put_nowait surface is also driven from worker threads
# (jobs run-queue, the threaded stress test), and an unguarded
# read-compare-write could publish a LOWER peak over a higher one —
# the gauge is documented monotone. threadctx declares the contract
# (channels.Metered.high_water guarded_by _hw_lock).
_NAME_HIGH_WATER: Dict[str, int] = {}
_HW_LOCK = threading.Lock()

# Armed by sanitize.install(): (mode, record) — identical split to
# ops/jit_registry.arm. `record(kind, detail, may_raise)` is
# sanitize._record; raise/count is its decision.
_armed_record: Optional[Callable[[str, str, bool], None]] = None


def arm(mode: str, record: Callable[[str, str, bool], None]) -> None:
    """Arm overflow detection (called by sanitize.install). `mode` is
    carried by `record` itself; kept in the signature for parity with
    jit_registry.arm."""
    global _armed_record
    del mode  # the record callback owns the raise/count split
    _armed_record = record


def disarm() -> None:
    global _armed_record
    _armed_record = None


def _violation(detail: str) -> None:
    if _armed_record is not None:
        _armed_record("chan_overflow", detail, True)


def declare_channel(name: str, capacity: int, policy: str, owner: str,
                    doc: str, put_budget: Optional[str] = None,
                    kind: str = "queue",
                    sheds_expected: bool = False) -> ChannelContract:
    if name in CHANNELS:
        raise ValueError(f"channel {name!r} declared twice")
    if capacity <= 0:
        raise ValueError(f"channel {name!r}: capacity must be positive")
    if policy not in POLICIES:
        raise ValueError(f"channel {name!r}: unknown policy {policy!r}")
    if kind not in KINDS:
        raise ValueError(f"channel {name!r}: unknown kind {kind!r}")
    if policy == "block" and kind == "queue":
        if put_budget is None:
            raise ValueError(
                f"channel {name!r}: block policy requires a put_budget "
                "(a timeouts.py name) so producers can never wait "
                "unbounded")
        if put_budget not in TIMEOUTS:
            raise ValueError(
                f"channel {name!r}: put_budget {put_budget!r} is not "
                "declared in spacedrive_tpu/timeouts.py")
    c = ChannelContract(name, int(capacity), policy, owner, doc,
                        put_budget, kind, bool(sheds_expected))
    CHANNELS[name] = c
    return c


def _contract(name: str) -> ChannelContract:
    c = CHANNELS.get(name)
    if c is None:
        raise KeyError(f"undeclared channel {name!r} (declare it in "
                       "spacedrive_tpu/channels.py)")
    return c


def capacity(name: str) -> int:
    """Effective capacity for a declared channel: declared × the
    SDTPU_CHAN_SCALE flag, floored at 1."""
    c = _contract(name)
    try:
        scale = float(flags.get("SDTPU_CHAN_SCALE"))
    except (TypeError, ValueError):
        scale = 1.0
    return max(1, int(round(c.capacity * scale)))


class _Metered:
    """Depth/high-water/shed accounting shared by Channel and Window.
    Label children are cached at construction so the hot path is one
    method call per op."""

    def __init__(self, contract: ChannelContract):
        self.contract = contract
        self.name = contract.name
        self.capacity = capacity(contract.name)
        self.high_water = 0
        self._hw_lock = _HW_LOCK  # module-wide: peaks cross instances
        self._m_depth = CHAN_DEPTH.labels(name=self.name)
        self._m_high = CHAN_HIGH_WATER.labels(name=self.name)
        self._m_shed = CHAN_SHED.labels(name=self.name)

    def _note_depth(self, depth: int) -> None:
        self._m_depth.set(depth)
        if depth > self.high_water:
            with self._hw_lock:
                if depth > self.high_water:
                    self.high_water = depth
                # The gauge is per NAME and documented "since process
                # start"; instances come and go (one ws buffer per
                # subscription), so a fresh instance must not regress
                # it below an earlier instance's peak — and two racing
                # producers must not publish a lower peak over a
                # higher one (monotone under the stress test).
                if depth > _NAME_HIGH_WATER.get(self.name, 0):
                    _NAME_HIGH_WATER[self.name] = depth
                    self._m_high.set(depth)

    def _shed(self, n: int = 1) -> None:
        self._m_shed.inc(n)

    @property
    def shed_total(self) -> float:
        return self._m_shed.value


class Channel(_Metered):
    """A bounded producer/consumer channel bound to a declared
    contract. The deque-backed core needs no event loop; async
    `put`/`get` create their waiter futures lazily on the running
    loop, so synchronous construction paths (Node bootstrap, sync
    tests) work unchanged.

    `on_evict(item)` fires for every item the overflow policy drops
    (shed_oldest eviction, shed_new rejection, coalesce replacement)
    so adopters can settle promises the item carried (the thumbnailer
    marks a shed batch done — its awaiters must not hang)."""

    def __init__(self, name: str,
                 on_evict: Optional[Callable[[Any], None]] = None,
                 capacity_cap: Optional[int] = None):
        super().__init__(_contract(name))
        if self.contract.kind != "queue":
            raise ValueError(
                f"channel {name!r} is declared kind="
                f"{self.contract.kind!r}; use "
                f"{'window' if self.contract.kind == 'window' else 'bounded_dict'}()")
        if capacity_cap is not None:
            # Runtime narrowing BELOW the declared ceiling is allowed —
            # the contract is the upper bound the registry audits, not
            # an exact size (the depth-N overlap pipeline sizes its
            # hand-off channels to the configured depth, which must
            # never exceed the declared ops.pipeline.* capacity).
            self.capacity = max(1, min(self.capacity, int(capacity_cap)))
        self._on_evict = on_evict
        # Slots are [key, item] lists so a coalesce replacement mutates
        # in place, keeping the original queue position.
        self._slots: Deque[list] = deque()
        self._keys: Dict[Any, list] = {}
        self._getters: Deque[asyncio.Future] = deque()
        self._space: Deque[asyncio.Future] = deque()

    # -- introspection -----------------------------------------------------

    def __len__(self) -> int:
        return len(self._slots)

    def qsize(self) -> int:
        return len(self._slots)

    def __bool__(self) -> bool:
        return bool(self._slots)

    def empty(self) -> bool:
        return not self._slots

    def __iter__(self) -> Iterator[Any]:
        """Snapshot iteration over pending items (run-queue scans)."""
        return iter([slot[1] for slot in list(self._slots)])

    # -- waiter plumbing ---------------------------------------------------

    @staticmethod
    def _wake(waiters: Deque[asyncio.Future]) -> None:
        while waiters:
            fut = waiters.popleft()
            if not fut.done():
                fut.set_result(None)
                break

    def _evict(self, slot: list) -> None:
        if slot[0] is not None:
            self._keys.pop(slot[0], None)
        self._shed()
        if self._on_evict is not None:
            self._on_evict(slot[1])

    def _append(self, item: Any, key: Any) -> None:
        slot = [key, item]
        self._slots.append(slot)
        if key is not None:
            self._keys[key] = slot
        self._note_depth(len(self._slots))
        self._wake(self._getters)

    # -- producer side -----------------------------------------------------

    def put_nowait(self, item: Any, key: Any = None) -> bool:
        """Apply the contract's policy without awaiting. Returns True
        when the item is pending afterwards (directly or coalesced),
        False when it was shed."""
        if key is not None and key in self._keys:
            # Coalesce: replace the pending payload in place; the old
            # payload is the one shed.
            slot = self._keys[key]
            self._evict([None, slot[1]])
            slot[1] = item
            return True
        if len(self._slots) >= self.capacity:
            policy = self.contract.policy
            if policy == "block":
                _violation(
                    f"put_nowait on full block channel {self.name!r} "
                    f"(depth {len(self._slots)}/{self.capacity}): "
                    "producers must use the budgeted `await put()`")
                raise ChannelFull(
                    f"channel {self.name!r} full "
                    f"({len(self._slots)}/{self.capacity})")
            if policy == "shed_oldest":
                self._evict(self._slots.popleft())
                self._append(item, key)
                return True
            # shed_new, and coalesce with no pending key match
            self._evict([None, item])
            return False
        self._append(item, key)
        return True

    async def put(self, item: Any, key: Any = None) -> bool:
        """Policy-aware put. Non-block policies never wait (same as
        put_nowait); block policy waits for space under the contract's
        declared timeouts.py budget, observing the wait into
        sd_chan_put_block_seconds{name}."""
        if self.contract.policy != "block":
            return self.put_nowait(item, key)
        if key is not None and key in self._keys:
            # Same coalesce-in-place as put_nowait: without this, two
            # budgeted puts with one key would append two slots both
            # claiming the key, and the first consume would strip the
            # second slot's mapping — later puts then duplicate
            # instead of coalescing.
            slot = self._keys[key]
            self._evict([None, slot[1]])
            slot[1] = item
            return True
        if len(self._slots) >= self.capacity:
            loop = asyncio.get_running_loop()
            t0 = time.perf_counter()
            try:
                while len(self._slots) >= self.capacity:
                    fut = loop.create_future()
                    self._space.append(fut)
                    try:
                        await with_timeout(self.contract.put_budget, fut)
                    except BaseException:
                        # Budget fired or producer cancelled: remove
                        # the space waiter (wait_for already cancelled
                        # the future on timeout; an abandoned done
                        # future would otherwise sit in the deque until
                        # a get happens to sweep it) and hand any
                        # already-granted space to the next producer.
                        fut.cancel()
                        try:
                            self._space.remove(fut)
                        except ValueError:
                            pass
                        if len(self._slots) < self.capacity \
                                and not fut.cancelled():
                            self._wake(self._space)
                        raise
            finally:
                CHAN_PUT_BLOCK_SECONDS.labels(name=self.name).observe(
                    time.perf_counter() - t0)
        self._append(item, key)
        return True

    # -- consumer side -----------------------------------------------------

    def get_nowait(self) -> Any:
        if not self._slots:
            raise asyncio.QueueEmpty
        slot = self._slots.popleft()
        if slot[0] is not None and self._keys.get(slot[0]) is slot:
            del self._keys[slot[0]]
        self._note_depth(len(self._slots))
        self._wake(self._space)
        return slot[1]

    popleft = get_nowait  # run-queue spelling (jobs manager)

    async def get(self) -> Any:
        while True:
            try:
                return self.get_nowait()
            except asyncio.QueueEmpty:
                fut = asyncio.get_running_loop().create_future()
                self._getters.append(fut)
                try:
                    await fut
                except BaseException:
                    # Cancelled (or worse) while parked: drop the
                    # waiter instead of leaking it in the deque
                    # forever (the worker cancels a pending
                    # commands.get() every step), and if a put woke
                    # THIS future before the cancel landed, pass the
                    # wakeup on so the item isn't stranded — same
                    # contract as asyncio.Queue.get.
                    fut.cancel()
                    try:
                        self._getters.remove(fut)
                    except ValueError:
                        pass
                    if self._slots and not fut.cancelled():
                        self._wake(self._getters)
                    raise

    def remove(self, item: Any) -> None:
        """Remove a specific pending item (run-queue cancellation).
        Raises ValueError when absent, matching deque.remove."""
        for slot in self._slots:
            if slot[1] is item or slot[1] == item:
                self._slots.remove(slot)
                if slot[0] is not None:
                    self._keys.pop(slot[0], None)
                self._note_depth(len(self._slots))
                self._wake(self._space)
                return
        raise ValueError("Channel.remove(item): item not pending")


class Window(_Metered):
    """Depth tracker for a channel whose items live in an EXTERNAL
    buffer (proto.Tunnel's send_nowait frames sit in the transport's
    write buffer, not here). `note_put()` counts an item into the
    window; `note_drain()` empties it (the flush/ack point). A put
    past the declared capacity is the chan_overflow breach — the
    static backpressure pass bounds bursts at the AST, this bounds
    them at runtime.

    Depth mutations serialize on an internal guard so windows can be
    noted from executor threads (the staging buffer pool's stage and
    retire workers) as well as the event loop."""

    def __init__(self, name: str):
        super().__init__(_contract(name))
        if self.contract.kind != "window":
            raise ValueError(
                f"channel {name!r} is declared kind="
                f"{self.contract.kind!r}, not a window")
        self._depth_lock = threading.Lock()
        self._depth = 0

    def __len__(self) -> int:
        return self._depth

    def note_put(self) -> None:
        with self._depth_lock:
            self._depth += 1
            depth = self._depth
        self._note_depth(depth)
        if depth > self.capacity:
            self._shed()  # the frame is already queued; count + flag
            _violation(
                f"window {self.name!r} burst past its declared "
                f"capacity ({depth}/{self.capacity}) without a "
                "drain — a wedged peer now buffers unbounded memory")

    def note_drain(self) -> None:
        with self._depth_lock:
            self._depth = 0
        self._note_depth(0)

    def note_pop(self) -> None:
        """Retire ONE item from the window. For windows whose items
        return individually (the staging buffer pool's leases come
        back one per batch retirement) rather than draining at a
        single flush/ack point."""
        with self._depth_lock:
            if self._depth > 0:
                self._depth -= 1
            depth = self._depth
        self._note_depth(depth)


class BoundedDict(_Metered):
    """Registry-declared cache: an LRU dict capped at the contract's
    capacity, evictions counted into sd_chan_shed_total{name}. The
    unbounded-growth pass exempts attributes constructed through
    `bounded_dict()` — this is the sanctioned grow-forever shape."""

    def __init__(self, name: str):
        super().__init__(_contract(name))
        if self.contract.kind != "cache":
            raise ValueError(
                f"channel {name!r} is declared kind="
                f"{self.contract.kind!r}, not a cache")
        self._d: "OrderedDict[Any, Any]" = OrderedDict()

    def __setitem__(self, k: Any, v: Any) -> None:
        if k in self._d:
            self._d.move_to_end(k)
        self._d[k] = v
        while len(self._d) > self.capacity:
            self._d.popitem(last=False)
            self._shed()
        self._note_depth(len(self._d))

    def __getitem__(self, k: Any) -> Any:
        v = self._d[k]
        self._d.move_to_end(k)
        return v

    def get(self, k: Any, default: Any = None) -> Any:
        if k in self._d:
            return self[k]
        return default

    def pop(self, k: Any, *default: Any) -> Any:
        v = self._d.pop(k, *default)
        self._note_depth(len(self._d))
        return v

    def __contains__(self, k: Any) -> bool:
        return k in self._d

    def __iter__(self):
        # Without this, `for k in bd` falls back to the legacy
        # sequence protocol (bd[0], bd[1], ...) and dies with a
        # baffling KeyError(0). Iteration is a read, not a use: it
        # must not disturb LRU order.
        return iter(self._d)

    def __len__(self) -> int:
        return len(self._d)

    def __delitem__(self, k: Any) -> None:
        del self._d[k]
        self._note_depth(len(self._d))

    def keys(self):
        return self._d.keys()

    def items(self):
        return self._d.items()

    def values(self):
        return self._d.values()


def channel(name: str,
            on_evict: Optional[Callable[[Any], None]] = None,
            capacity_cap: Optional[int] = None) -> Channel:
    """A Channel bound to the declared contract `name`. Multiple
    instances per name are expected (one commands channel per worker,
    one ws buffer per subscription): the shed counter aggregates
    across them; depth gauges sample per instance. `capacity_cap`
    narrows this instance below the declared ceiling (never above)."""
    return Channel(name, on_evict=on_evict, capacity_cap=capacity_cap)


def window(name: str) -> Window:
    return Window(name)


def bounded_dict(name: str) -> BoundedDict:
    return BoundedDict(name)


def chan_table_markdown() -> str:
    """README's generated channel table (one row per declared
    channel)."""
    out = ["| Channel | Capacity | Policy | Owner | Covers |",
           "| --- | --- | --- | --- | --- |"]
    for name in sorted(CHANNELS):
        c = CHANNELS[name]
        doc = " ".join(c.doc.split())
        policy = c.policy if c.kind == "queue" else f"{c.policy} ({c.kind})"
        out.append(f"| `{name}` | {c.capacity} | {policy} | {c.owner} "
                   f"| {doc} |")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# THE channel namespace. Keep alphabetical; every entry is enforced by
# the sdlint queue-discipline pass (a bare cross-task queue, or a
# channel() call naming an undeclared contract, fails the build) and
# cross-checked against this registry by tests/test_sdlint.py's drift
# test (every declared channel must be constructed somewhere in the
# tree; every construction must name a declared channel).
# ---------------------------------------------------------------------------

declare_channel(
    "api.ws", 64, "coalesce", "api",
    "Per-subscription websocket event buffer (api/server.py "
    "WsSubscriptionPump): one drainer task per subscription sends "
    "frames under the api.ws.send budget; TelemetrySnapshot events "
    "coalesce to the newest snapshot; a stalled consumer sheds new "
    "events instead of buffering the node's event stream in RAM.")

declare_channel(
    "api.http.inflight", 256, "shed_new", "api",
    "rspc HTTP admission window (api/server.py _rspc_http): one token "
    "per in-flight dispatch. shed_new IS the API host's shed-load "
    "edge — a request past capacity is refused with 503 SHED "
    "immediately instead of queueing unbounded behind a saturated "
    "backend (the jobs run-queue's admission refusal, for the HTTP "
    "plane); sheds count into sd_chan_shed_total{api.http.inflight}, "
    "which is how the health observatory attributes an API storm by "
    "name.")

declare_channel(
    "bench.chan", 256, "block", "tools",
    "tools/chan_bench.py producer/consumer burst channel: the "
    "measured put-block path (budget bench.chan.put).",
    put_budget="bench.chan.put")

declare_channel(
    "bench.load.wire", 64, "block", "tools",
    "tools/load_bench.py stub-transport frame pipe: one instance per "
    "direction per simulated peer, carrying the same tunnel-shaped "
    "frames (clone pages, acks, pull pages) the TCP plane does — the "
    "in-process wire the fleet-scale harness storms the real node "
    "over.", put_budget="bench.load.wire.put")

declare_channel(
    "bench.shed", 256, "shed_new", "tools",
    "tools/chan_bench.py stalled-consumer channel: the measured "
    "shed path.")

declare_channel(
    "jobs.manager.queue", 1024, "shed_new", "jobs",
    "JobManager admission run-queue (FIFO behind the worker pool). "
    "shed_new IS the admission control: a job past capacity is "
    "refused loudly (report FAILED + JobError event), the queue "
    "never balloons.")

declare_channel(
    "fleet.peer.snapshots", 32, "shed_oldest", "fleet",
    "Per-peer ring of fetched obs.health snapshots (spacedrive_tpu/"
    "fleet.py): one instance per registered peer, each entry the "
    "peer's own HealthSnapshot plus receive metadata (rtt, estimated "
    "clock skew, received-at). Oldest snapshots age out — the fleet "
    "view only ever needs the freshest few — so a chatty peer cannot "
    "grow the poller's memory.", sheds_expected=True)

declare_channel(
    "fleet.snapshots", 32, "shed_oldest", "fleet",
    "Recent merged fleet-health views (spacedrive_tpu/fleet.py): "
    "fleet.health serves the newest entry; history ages out "
    "oldest-first, same shape as health.snapshots.",
    sheds_expected=True)

declare_channel(
    "health.series", 120, "shed_oldest", "health",
    "Per-series sample ring of the health observatory (spacedrive_"
    "tpu/health.py): one instance per metric series, each entry a "
    "(ts, windowed value) point from the sampler. Oldest samples age "
    "out — ~10 min of history at the default 5 s interval — so the "
    "observer itself is depth-disciplined like everything it "
    "observes.", sheds_expected=True)

declare_channel(
    "health.snapshots", 64, "shed_oldest", "health",
    "Recent computed HealthSnapshot ring (spacedrive_tpu/health.py): "
    "node.health serves the newest entry; history ages out "
    "oldest-first.", sheds_expected=True)

declare_channel(
    "incidents.store", 64, "shed_oldest", "incidents",
    "Incident-bundle header index of the incident observatory "
    "(spacedrive_tpu/incidents.py) — the count bound of the on-disk "
    "bundle store. Each entry is one frozen evidence bundle's header "
    "plus its file path; shedding the oldest entry DELETES its file "
    "(the eviction hook is the store's garbage collector), so the "
    "postmortem directory can never outgrow this declared bound. "
    "The byte cap (SDTPU_INCIDENT_STORE_MB) evicts through the same "
    "hook; both count sd_incident_dropped_total.",
    sheds_expected=True)

declare_channel(
    "jobs.worker.commands", 16, "shed_oldest", "jobs",
    "Per-worker command inbox (pause/resume/cancel/shutdown). The "
    "drain is latest-wins, so shedding the OLDEST command under a "
    "flood preserves semantics exactly.", sheds_expected=True)

declare_channel(
    "media.thumbs", 64, "shed_oldest", "media",
    "Thumbnailer batch queue with per-path coalescing (media/"
    "actor.py): a full-library scan against a slow thumbnailer sheds "
    "the oldest batch (thumbnails are regenerable; its awaiters are "
    "released) instead of absorbing the index into RAM.")

declare_channel(
    "ops.pipeline.inflight", 8, "block", "ops",
    "Depth-N identify pipeline dispatched-but-unretired window "
    "(ops/overlap.py): device digests (plus, on the undonated path, "
    "their pinned input buffers) waiting for the D2H retirer. "
    "Capacity is the SDTPU_PIPELINE_DEPTH ceiling; each run narrows "
    "its instance to the configured depth.",
    put_budget="ops.pipeline.inflight.put")

declare_channel(
    "ops.pipeline.staged", 8, "block", "ops",
    "Depth-N identify pipeline staged-batch hand-off (ops/overlap.py): "
    "host word/length arrays staged by the concurrent stagers, waiting "
    "for a per-device dispatcher. Capacity is the SDTPU_PIPELINE_DEPTH "
    "ceiling; each run narrows its instance to the configured depth.",
    put_budget="ops.pipeline.staged.put")

declare_channel(
    "ops.pipeline.timeline", 4096, "shed_oldest", "ops",
    "Flight-recorder timeline ring (spacedrive_tpu/flight.py): one "
    "event per pipeline batch phase (stage/H2D/kernel/retire, plus "
    "the per-batch bound-attribution window), written by the per-"
    "device dispatch executor threads under the recorder's lock. "
    "History ages out oldest-first — the export shows the recent "
    "window, memory never grows with uptime.", sheds_expected=True)

declare_channel(
    "ops.stage.pool", 12, "block", "ops",
    "Native staging buffer pool checkout window (ops/staging.py "
    "StagePool): each depth slot's packed H2D source page — a pooled, "
    "page-aligned anonymous mapping the C plane stages straight into "
    "and jax reads zero-copy — counts one item from acquire until its "
    "batch RETIRES. Capacity bounds total pooled pages "
    "(SDTPU_STAGE_POOL_BUFFERS narrows below it): the depth-8 ring + "
    "warmup/calibration leases + slack. An exhausted pool degrades "
    "the batch to the Python staging path — it never allocates past "
    "the bound — and a burst past capacity is a chan_overflow "
    "violation.", kind="window")

declare_channel(
    "p2p.route_cache", 512, "shed_oldest", "p2p",
    "Healthy-tunnel route cache (sync_net): LRU over identity → "
    "(addr, port), invalidated on send failure.", kind="cache")

declare_channel(
    "p2p.tunnel.frames", 4, "block", "p2p",
    "proto.Tunnel's send_nowait frame window: frames sealed but not "
    "yet drained to the socket. The capacity IS sync_net's "
    "CLONE_WINDOW; a burst past it without a drain is a "
    "chan_overflow violation, and the drain itself runs under the "
    "sync.clone.drain budget at the call site.", kind="window")

declare_channel(
    "store.actor.queue", 256, "block", "store",
    "Per-library write-batch queue of the single-writer group-commit "
    "actor (store/actor.py): every product write transaction — job "
    "chunks, sync ingest pages, api mutations — enters as one queued "
    "batch and is coalesced by the supervised writer thread into fat "
    "transactions (SDTPU_STORE_GROUP_MAX / _LATENCY_S bound the "
    "group). Producers block under the store.actor.put budget when "
    "the writer falls behind — the write path's admission edge.",
    put_budget="store.actor.put")

declare_channel(
    "sync.clone.serve", 2, "block", "sync",
    "Fair-share clone-serve page-fetch gate (sync/clone_serve.py): "
    "each concurrent clone stream's next off-loop page fetch takes "
    "one FIFO slot here, so N cloning peers round-robin the fetch "
    "executor instead of a hot stream (fast acks, warm cache) "
    "monopolizing it and starving slower peers — the load harness's "
    "per-peer fairness gate measures the result. Block-wait p99 vs "
    "the sync.clone.serve budget is the clone-overcommit signal the "
    "health observatory attributes by name.",
    put_budget="sync.clone.serve")

declare_channel(
    "sync.ingest.events", 64, "coalesce", "sync",
    "Ingester event inbox (notification/messages): notifications "
    "coalesce by kind (a poke storm collapses to one pending "
    "notification, the reference's wait! semantics); message pages "
    "are flow-controlled one-in-flight by the pull loop.")

declare_channel(
    "sync.ingest.requests", 32, "block", "sync",
    "Ingester → wire request outbox: the _pull consumer drains it "
    "between frames; the producer's put blocks under the "
    "sync.ingest.backlog budget when the consumer wedges.",
    put_budget="sync.ingest.backlog")

declare_channel(
    "tracing.logring", 512, "shed_oldest", "tracing",
    "Bounded in-memory log ring (tracing.LogRing, installed at Node "
    "bootstrap under SDTPU_LOG_RING): the newest trace/span-stamped "
    "log records, aged oldest-first, so incident bundles freeze a "
    "log tail without unbounded buffering — stderr is write-only, "
    "this ring is the recoverable copy.", sheds_expected=True)
