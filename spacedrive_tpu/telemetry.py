"""Node-wide telemetry: the process-global metrics registry.

The reference leans on the `tracing`/`tracing-subscriber` ecosystem for
in-process observability (SURVEY §5); the TPU-native equivalent is this
registry plus the hierarchical spans in `tracing.py`. Counters, gauges,
and fixed-bucket histograms live in ONE process-global namespace and are
served three ways: Prometheus text on `GET /metrics`, the `node.metrics`
rspc query, and periodic `TelemetrySnapshot` events on the node event
bus (node.py TelemetryReporter).

Design constraints, in order:

- **Cheap hot path.** Every increment starts with one module-global
  flag check; when telemetry is disabled (`SDTPU_TELEMETRY=off` or
  `set_enabled(False)`) that check is the WHOLE cost — the regression
  budget (tests/test_telemetry.py) holds it under 5 µs/call with
  typical cost ~0.1 µs. Enabled increments take one per-metric lock
  (leaf lock, never held around any other lock) so thread-pool workers
  never lose updates.
- **Central namespace.** Every metric family is defined at the bottom
  of THIS module and imported by the instrumented code;
  `tools/telemetry_lint.py` (run in tier-1) fails the build on
  families registered anywhere else or on name collisions (since
  round 9 the lint is sdlint's telemetry pass; the shim remains).
  Names follow `sd_<layer>_<what>[_total|_seconds|_bytes]` with
  layers jobs | identifier | sync | p2p | store | api | trace |
  sanitize | jit | task | timeout | chan | health | sql | chaos |
  backoff | wire.
- **Windowed reads without resets.** Counters and histograms expose
  `snapshot_delta(cursor)` — an exact delta view since a previous
  cursor — so the health observatory (health.py) can compute windowed
  rates and percentiles while the cumulative families `/metrics`
  serves keep their meaning forever (a delta reader NEVER resets the
  registry; consecutive deltas telescope exactly, even under
  concurrent increments).
- **No dependencies.** Pure stdlib plus the equally dependency-free
  flag registry (flags.py) — importable from every layer (store, p2p,
  ops) without cycles.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import flags

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "counter", "gauge", "histogram", "snapshot", "render_prometheus",
    "enabled", "set_enabled", "reset",
]

# Module-global hot-path switch: one LOAD_GLOBAL in every increment.
# Rebound (not mutated) by set_enabled so readers need no lock.
_ENABLED = flags.get("SDTPU_TELEMETRY")

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0, 60.0)


def enabled() -> bool:
    return _ENABLED


def set_enabled(flag: bool) -> None:
    """Toggle all hot-path recording (process-wide). Values already
    recorded stay; disabled increments are dropped, not buffered."""
    global _ENABLED
    _ENABLED = bool(flag)


def _fmt_num(v: float) -> str:
    """Prometheus sample formatting: integral values without the .0."""
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(float(v))


def _escape_label(v: Any) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _label_str(names: Sequence[str], values: Sequence[Any]) -> str:
    return ",".join(
        f'{n}="{_escape_label(v)}"' for n, v in zip(names, values))


class _Metric:
    """Shared shell: name/help/labels plumbing. A metric with
    `labelnames` is a parent that only vends children via `labels()`;
    a metric without is itself the single sample."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple, "_Metric"] = {}

    def labels(self, **kv: Any) -> "_Metric":
        """Child metric for one label-value combination (created on
        first use, cached forever — label cardinality is expected to be
        tiny: status names, backend names, phase names)."""
        if tuple(sorted(kv)) != tuple(sorted(self.labelnames)):
            raise ValueError(
                f"{self.name}: labels {sorted(kv)} != declared "
                f"{sorted(self.labelnames)}")
        key = tuple(kv[n] for n in self.labelnames)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    child = type(self)(self.name, self.help,
                                       **self._child_kwargs())
                    self._children[key] = child
        return child

    def _child_kwargs(self) -> Dict[str, Any]:
        return {}

    def samples(self) -> List[Tuple[Optional[Dict[str, Any]], "_Metric"]]:
        """The flat sample list: (labels, child) per label combination
        for a labeled parent, [(None, self)] for a bare metric — what
        the health sampler iterates to spool every series."""
        if self.labelnames:
            with self._lock:
                items = sorted(self._children.items())
            return [(dict(zip(self.labelnames, key)), child)
                    for key, child in items]
        return [(None, self)]

    # -- introspection ----------------------------------------------------

    def _sample(self) -> Dict[str, Any]:
        raise NotImplementedError

    def _zero(self) -> None:
        raise NotImplementedError

    def snapshot_value(self) -> Dict[str, Any]:
        if self.labelnames:
            return {
                "kind": self.kind,
                "labelnames": list(self.labelnames),
                "labeled": [
                    {"labels": dict(zip(self.labelnames, key)),
                     **child._sample()}
                    for key, child in sorted(self._children.items())
                ],
            }
        return {"kind": self.kind, **self._sample()}

    def reset(self) -> None:
        self._zero()
        for child in list(self._children.values()):
            child._zero()


class Counter(_Metric):
    """Monotonic float counter (Prometheus `counter`)."""

    kind = "counter"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        super().__init__(name, help, labelnames)
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if not _ENABLED:
            return
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def snapshot_delta(self, cursor: Optional[float] = None
                       ) -> Dict[str, Any]:
        """Windowed counter view: the value delta since `cursor` (a
        previous call's ``"cursor"``), plus the new cursor. Exact
        under concurrency — increments commit under the metric lock,
        so consecutive deltas telescope to the true total with
        nothing lost or double-counted, and the cumulative value is
        never touched (no reset). A value BELOW the cursor means the
        registry was reset mid-window (bench isolation); the delta
        then restarts from zero instead of going negative."""
        v = self._value
        prev = 0.0 if cursor is None else float(cursor)
        return {"value": v - prev if v >= prev else v, "cursor": v}

    def _sample(self) -> Dict[str, Any]:
        return {"value": self._value}

    def _zero(self) -> None:
        with self._lock:
            self._value = 0.0

    def _render(self, out: List[str], labels: str) -> None:
        suffix = f"{{{labels}}}" if labels else ""
        out.append(f"{self.name}{suffix} {_fmt_num(self._value)}")


class Gauge(Counter):
    """Set-to-current-value metric (Prometheus `gauge`)."""

    kind = "gauge"

    def set(self, v: float) -> None:
        if not _ENABLED:
            return
        with self._lock:
            self._value = float(v)

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)


class Histogram(_Metric):
    """Fixed-bucket histogram: cumulative bucket counts + sum + count.

    Buckets are upper bounds; +Inf is implicit. `observe` is one
    bisect + three adds under the metric lock."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError(f"{name}: histogram needs >= 1 bucket")
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0

    def _child_kwargs(self) -> Dict[str, Any]:
        return {"buckets": self.buckets}

    def observe(self, v: float) -> None:
        if not _ENABLED:
            return
        i = bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def state(self) -> Tuple[Tuple[int, ...], float, int]:
        """Atomic (counts, sum, count) copy under the metric lock —
        the cursor `snapshot_delta` consumes. counts are per-bucket
        (non-cumulative), +Inf last."""
        with self._lock:
            return (tuple(self._counts), self._sum, self._count)

    def snapshot_delta(self, cursor: Optional[Tuple] = None
                       ) -> Dict[str, Any]:
        """Windowed histogram view since `cursor` (a previous call's
        ``"cursor"``): per-bucket NON-cumulative delta counts aligned
        with `self.buckets` (+Inf last), delta sum/count, and the new
        cursor. The read is one locked state copy, so a window's
        totals are exact even while worker threads observe
        concurrently — and the cumulative registry is never reset
        (windowed percentiles come from bucket-delta interpolation in
        health.py, not from zeroing). A shrunken state means the
        registry was reset mid-window; the delta restarts from the
        fresh values instead of going negative."""
        counts, s, n = self.state()
        if cursor is None:
            d_counts, d_sum, d_count = list(counts), s, n
        else:
            pc, ps, pn = cursor
            d_counts = [c - p for c, p in zip(counts, pc)]
            d_sum, d_count = s - ps, n - pn
            if d_count < 0 or any(c < 0 for c in d_counts):
                d_counts, d_sum, d_count = list(counts), s, n
        return {"counts": d_counts, "sum": d_sum, "count": d_count,
                "cursor": (counts, s, n)}

    def _sample(self) -> Dict[str, Any]:
        cum, cums = 0, []
        for c in self._counts[:-1]:
            cum += c
            cums.append(cum)
        return {
            "count": self._count, "sum": round(self._sum, 6),
            "buckets": [[le, n] for le, n in zip(self.buckets, cums)]
            + [["+Inf", self._count]],
        }

    def _zero(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._sum = 0.0
            self._count = 0

    def _render(self, out: List[str], labels: str) -> None:
        prefix = labels + "," if labels else ""
        cum = 0
        for le, c in zip(self.buckets, self._counts):
            cum += c
            out.append(
                f'{self.name}_bucket{{{prefix}le="{_fmt_num(le)}"}} {cum}')
        out.append(f'{self.name}_bucket{{{prefix}le="+Inf"}} {self._count}')
        suffix = f"{{{labels}}}" if labels else ""
        out.append(f"{self.name}_sum{suffix} {_fmt_num(self._sum)}")
        out.append(f"{self.name}_count{suffix} {self._count}")


class MetricsRegistry:
    """Name → metric map with collision detection. One process-global
    instance (REGISTRY) is the node-wide namespace; tests construct
    private ones for golden-format checks."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str,
                       labelnames: Sequence[str], **kw) -> Any:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                # Re-registration with an identical spec returns the
                # existing family (module re-imports); anything else is
                # a namespace collision and fails loudly.
                want_buckets = (
                    tuple(sorted(float(b) for b in kw["buckets"]))
                    if "buckets" in kw else None)
                if (type(existing) is cls
                        and existing.labelnames == tuple(labelnames)
                        and (want_buckets is None
                             or want_buckets == existing.buckets)):
                    return existing
                raise ValueError(
                    f"metric name collision: {name} already registered "
                    f"as {existing.kind}")
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        return self._metrics.get(name)

    def families(self) -> Dict[str, _Metric]:
        """Shallow copy of the name → family map (the health sampler's
        iteration surface; a copy so registration during the walk —
        module imports from another thread — cannot break it)."""
        with self._lock:
            return dict(self._metrics)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-safe {name: sample} map — the TelemetrySnapshot event
        payload and the node.metrics query result."""
        return {name: m.snapshot_value()
                for name, m in sorted(self._metrics.items())}

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        out: List[str] = []
        for name, m in sorted(self._metrics.items()):
            if m.help:
                out.append(f"# HELP {name} {m.help}")
            out.append(f"# TYPE {name} {m.kind}")
            if m.labelnames:
                for key, child in sorted(m._children.items()):
                    child._render(out, _label_str(m.labelnames, key))
            else:
                m._render(out, "")
        return "\n".join(out) + "\n"

    def reset(self) -> None:
        """Zero every value (bench/test isolation). Metric objects stay
        registered — module-level references remain valid. Best-effort
        vs concurrent increments: a racing inc may land before or after
        its family is zeroed, never corrupt it."""
        for m in self._metrics.values():
            m.reset()


REGISTRY = MetricsRegistry()


def counter(name: str, help: str = "",
            labelnames: Sequence[str] = ()) -> Counter:
    return REGISTRY.counter(name, help, labelnames)


def gauge(name: str, help: str = "",
          labelnames: Sequence[str] = ()) -> Gauge:
    return REGISTRY.gauge(name, help, labelnames)


def histogram(name: str, help: str = "", labelnames: Sequence[str] = (),
              buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, help, labelnames, buckets=buckets)


def snapshot() -> Dict[str, Any]:
    return REGISTRY.snapshot()


def render_prometheus() -> str:
    return REGISTRY.render_prometheus()


def reset() -> None:
    REGISTRY.reset()


# ---------------------------------------------------------------------------
# Metric families — THE node-wide namespace. Every family used anywhere
# in the package is defined here and imported by the instrumented code;
# tools/telemetry_lint.py fails tier-1 on families registered elsewhere.
# ---------------------------------------------------------------------------

# -- jobs (jobs/manager.py, jobs/worker.py, jobs/report.py) -----------------
JOBS_INGESTED = counter(
    "sd_jobs_ingested_total", "Jobs accepted by JobManager.ingest")
JOBS_DUPLICATE_REJECTED = counter(
    "sd_jobs_duplicate_rejected_total",
    "Jobs rejected because an identical job was running/queued")
JOBS_RESUMED = counter(
    "sd_jobs_resumed_total", "Paused/interrupted jobs re-admitted "
    "(resume + cold_resume)")
JOBS_EARLY_FINISH = counter(
    "sd_jobs_early_finish_total",
    "Jobs that completed at init via EarlyFinish (nothing to do)")
JOBS_STEP_ERRORS = counter(
    "sd_jobs_step_errors_total",
    "Non-fatal step errors recorded into job reports")
JOBS_COMPLETED = counter(
    "sd_jobs_completed_total", "Jobs reaching a final status",
    labelnames=("status",))
JOBS_RUNNING = gauge(
    "sd_jobs_running", "Jobs currently running under the worker pool")
JOBS_QUEUED = gauge(
    "sd_jobs_queued", "Jobs waiting in the manager FIFO queue")
JOB_DURATION_SECONDS = histogram(
    "sd_job_duration_seconds", "Wall time of finished job runs",
    labelnames=("name",),
    buckets=(0.01, 0.05, 0.1, 0.5, 1, 5, 15, 60, 300, 1800))
JOB_STEP_SECONDS = histogram(
    "sd_job_step_seconds", "Wall time of individual job steps",
    labelnames=("name",))
JOBS_ITEMS_PROCESSED = counter(
    "sd_jobs_items_processed_total",
    "Completed task-count units (steps/chunks) by finished jobs",
    labelnames=("name",))
JOBS_ITEMS_PER_SEC = gauge(
    "sd_jobs_items_per_sec",
    "items/s of the most recently finished run of each job",
    labelnames=("name",))

# -- identifier (objects/identifier.py, ops/staging.py) ---------------------
IDENT_BATCHES = counter(
    "sd_identifier_batches_total",
    "CAS hashing batches dispatched, by resolved backend "
    "(jax = device pipeline; native/numpy/oracle = host planes)",
    labelnames=("backend",))
IDENT_BATCH_FILES = histogram(
    "sd_identifier_batch_files", "Files per CAS hashing batch",
    buckets=(1, 16, 64, 256, 1024, 4096, 16384, 65536))
IDENT_BYTES_HASHED = counter(
    "sd_identifier_bytes_hashed_total",
    "Payload bytes fed to the CAS hashers (sampled large-file rows "
    "count their 57344-byte payload, small files their real size)")
IDENT_DEVICE_FALLBACK = counter(
    "sd_identifier_device_fallback_total",
    "auto-backend batches that downgraded jax->host (link probe said "
    "the H2D link loses to the native plane)")
IDENT_READ_ERRORS = counter(
    "sd_identifier_read_errors_total",
    "Files dropped from CAS batches by read errors")
IDENT_FILES = counter(
    "sd_identifier_files_total",
    "Identifier outcomes per file", labelnames=("outcome",))
IDENT_PHASE_SECONDS = counter(
    "sd_identifier_phase_seconds_total",
    "Per-phase cost attribution of identifier steps (the phase_ms "
    "split, as live counters)", labelnames=("phase",))

# -- pipeline (ops/overlap.py depth-N identify pipeline) --------------------
PIPELINE_DEPTH_HIGH_WATER = gauge(
    "sd_pipeline_depth_high_water",
    "Most batches simultaneously in flight (stage→H2D→kernel→fetch) "
    "observed in the depth-N identify pipeline since process start "
    "(≤ SDTPU_PIPELINE_DEPTH by construction)")
PIPELINE_STAGE_STALL_SECONDS = counter(
    "sd_pipeline_stage_stall_seconds_total",
    "Dispatcher time spent waiting on the staged-batch channel — the "
    "un-hidden remainder when staging is the pipeline bottleneck")
PIPELINE_RETIRE_STALL_SECONDS = counter(
    "sd_pipeline_retire_stall_seconds_total",
    "Retirer time spent waiting on the in-flight window — pipeline "
    "starvation (H2D/kernel slower than the fetch side)")
PIPELINE_H2D_BYTES = counter(
    "sd_pipeline_h2d_bytes_total",
    "Host→device bytes transferred by the pipeline dispatchers "
    "(simulated-link runs count the simulated bytes too)")
PIPELINE_H2D_SECONDS = counter(
    "sd_pipeline_h2d_seconds_total",
    "Wall seconds the pipeline dispatchers spent in host→device "
    "transfer (including the SDTPU_SIM_LINK_GBPS injected delay)")
PIPELINE_DONATED_REUSE = counter(
    "sd_pipeline_donated_reuse_total",
    "Staged device buffers consumed by donated kernel dispatches "
    "(each is allocator space recycled for a later batch's H2D "
    "instead of pinned until digest retirement)")
PIPELINE_DEVICE_BATCHES = counter(
    "sd_pipeline_device_batches_total",
    "Batches dispatched per local device by the round-robin pipeline",
    labelnames=("device",))

# -- stage pool (ops/staging.py shared staging executor) --------------------
STAGE_POOL_WORKERS = gauge(
    "sd_stage_pool_workers",
    "Worker threads of the shared staging ThreadPoolExecutor "
    "(ops/staging.py) — 0 when the pool is shut down, so shutdown-"
    "leak tests can see its lifecycle")

# -- native packed staging (ops/staging.py stage_batch_native) --------------
STAGE_NATIVE_BYTES = counter(
    "sd_stage_native_bytes_total",
    "Message bytes (prefix + payload) staged by the native packed "
    "backend (sd_stage_batch) straight into pooled H2D source pages")
STAGE_BATCHES = counter(
    "sd_stage_batches_total",
    "Batches staged for the device CAS pipeline, by backend: `native` "
    "is the packed zero-copy path, `python` the stage_files + "
    "build_cas_messages host path (flag off, .so missing, or pool "
    "exhausted)",
    labelnames=("backend",))
STAGE_FALLBACK_FILES = counter(
    "sd_stage_fallback_files_total",
    "Files that degraded PER-FILE from the native packed reader to "
    "the Python reader (bad row status: vanished, permission, short "
    "read, chaos-injected EIO) inside an otherwise-native batch")
STAGE_POOL_BUFFERS = gauge(
    "sd_stage_pool_buffers",
    "Pooled staging pages currently checked out to in-flight batches "
    "(StagePool occupancy; the ops.stage.pool window meters the same "
    "edge with overflow detection)")
STAGE_POOL_HIGH_WATER = gauge(
    "sd_stage_pool_high_water",
    "Peak concurrent StagePool checkouts since process start — how "
    "close the ring came to the declared pool bound")

# -- sync (sync/manager.py, sync/ingest.py, sync/opblob.py) -----------------
SYNC_OPS_ENCODED = counter(
    "sd_sync_ops_encoded_total",
    "CRDT ops appended to the local op log, by storage format",
    labelnames=("format",))
SYNC_BLOB_PAGES_WRITTEN = counter(
    "sd_sync_blob_pages_written_total",
    "Page-level shared_op_blob rows written by bulk writers")
SYNC_OPS_SERVED = counter(
    "sd_sync_ops_served_total",
    "Ops served to pulling peers via get_ops (both storage formats)")
SYNC_OPS_INGESTED = counter(
    "sd_sync_ops_ingested_total",
    "Remote ops offered to receive_crdt_operations")
SYNC_OPS_APPLIED = counter(
    "sd_sync_ops_applied_total",
    "Remote ops that won LWW and mutated the replica")
SYNC_INGEST_ERRORS = counter(
    "sd_sync_ingest_errors_total",
    "Remote ops that failed ingest (savepoint rolled back)")
SYNC_INGEST_PAGES = counter(
    "sd_sync_ingest_pages_total",
    "Pull-loop pages drained through the ingest actor")
SYNC_BLOB_PAGES_APPLIED = counter(
    "sd_sync_blob_pages_applied_total",
    "Clone-stream blob pages applied, fast (batched, LWW-compare "
    "proven no-op) vs fallback (per-op)", labelnames=("path",))
SYNC_BLOBS_EXPLODED = counter(
    "sd_sync_blobs_exploded_total",
    "Blob pages exploded into indexed op rows (first remote ingest)")
SYNC_CLONE_WINDOW_STALLS = counter(
    "sd_sync_clone_window_stalls_total",
    "Times the clone-stream originator blocked on a watermark ack "
    "with CLONE_WINDOW pages in flight (receiver backpressure)")
SYNC_CLONE_PAGES_RELAYED = counter(
    "sd_sync_clone_pages_relayed_total",
    "Blob pages relayed verbatim to pulling peers (serving side)")

# -- p2p (p2p/proto.py, p2p/sync_net.py) ------------------------------------
P2P_TUNNEL_BYTES_SENT = counter(
    "sd_p2p_tunnel_bytes_sent_total",
    "Frame payload bytes written to p2p tunnels (post-encryption)")
P2P_TUNNEL_BYTES_RECV = counter(
    "sd_p2p_tunnel_bytes_recv_total",
    "Frame payload bytes read from p2p tunnels (pre-decryption)")
P2P_TUNNELS_OPENED = counter(
    "sd_p2p_tunnels_opened_total", "Authenticated tunnels established")
P2P_ROUTE_CACHE_HITS = counter(
    "sd_p2p_route_cache_hits_total",
    "Peer-route resolutions answered from the healthy-tunnel cache")
P2P_ROUTE_CACHE_MISSES = counter(
    "sd_p2p_route_cache_misses_total",
    "Peer-route resolutions that had to scan discovery")
P2P_RECONNECTS = counter(
    "sd_p2p_reconnects_total",
    "Announce rounds that lost a peer mid-stream (route invalidated; "
    "next round re-resolves)")

# -- store (store/db.py) ----------------------------------------------------
STORE_TX = counter(
    "sd_store_tx_total", "Write transactions committed through tx()")
STORE_COMMIT_SECONDS = histogram(
    "sd_store_commit_seconds", "COMMIT latency of write transactions",
    buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5))
STORE_WRITE_LOCK_WAIT_SECONDS = histogram(
    "sd_store_write_lock_wait_seconds",
    "Time spent waiting for the per-database write lock",
    buckets=(0.0001, 0.001, 0.01, 0.05, 0.1, 0.5, 1, 5, 30))
STORE_BUSY_RETRIES = counter(
    "sd_store_busy_retries_total",
    "Write-transaction commits retried under the declared store.busy "
    "backoff after sqlite BUSY (an external writer — or an injected "
    "store.commit chaos fault — holding the file lock): the retry "
    "degrades the fault to latency instead of failing the job")
STORE_GROUP_COMMITS = counter(
    "sd_store_group_commits_total",
    "Fat transactions committed by the single-writer group-commit "
    "actor (store/actor.py) — each one carries sd_store_group_size "
    "coalesced write batches")
STORE_GROUP_SIZE = histogram(
    "sd_store_group_size",
    "Write batches coalesced per group commit — 1 means the actor "
    "found no concurrency to exploit (the raw-tx shape), the "
    "SDTPU_STORE_GROUP_MAX ceiling means writers queue faster than "
    "COMMIT retires them",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128))
STORE_GROUP_WAIT_SECONDS = histogram(
    "sd_store_group_wait_seconds",
    "A write batch's whole trip through the actor: queue wait + "
    "batches coalesced ahead of it + the group COMMIT (the write "
    "path's end-to-end latency, vs the store.actor.write budget)",
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 30, 120))
STORE_GROUP_SHUTDOWN_DRAINS = counter(
    "sd_store_group_shutdown_drains_total",
    "Write batches failed loudly (never silently dropped) because "
    "the actor shut down with them still queued — each one's "
    "completion future resolves exactly once with the shutdown error")
STORE_INIT_WARNINGS = counter(
    "sd_store_init_warnings_total",
    "Non-fatal problems swallowed while opening a library database "
    "(e.g. the lazy-index drop failing on a corrupt library) — "
    "logged at debug, surfaced here so health can see a bad open")

# -- sql statement contracts (store/statements.py + store/sqlaudit.py) ------
SQL_STATEMENTS = counter(
    "sd_sql_statements_total",
    "Executions per declared statement/shape name (runtime SQL "
    "auditor; `_adhoc` = diagnostic reads through db.query)",
    labelnames=("name",))
SQL_ROWS = counter(
    "sd_sql_rows_total",
    "Rows flowing through each declared statement: fetched for reads "
    "(counted by Database.run), affected for writes (cursor rowcount)",
    labelnames=("name",))
SQL_UNDECLARED = counter(
    "sd_sql_undeclared_total",
    "Statements that matched no declared contract or shape — a "
    "sql_undeclared sanitizer violation outside the ad-hoc read "
    "allowance (raised in tier-1, counted in production)")
SQL_TX_STATEMENTS = histogram(
    "sd_sql_tx_statements",
    "Statements executed per committed write transaction — the "
    "commit-per-item anti-pattern reads as a spike at 1-2",
    buckets=(1, 2, 5, 10, 25, 100, 500, 1000, 5000, 20000))
SQL_SCAN = counter(
    "sd_sql_scan_total",
    "EXPLAIN-sampled executions whose query plan full-scans a "
    "registered large table (SDTPU_SQL_EXPLAIN sampling mode)",
    labelnames=("name",))

# -- api (api/server.py) ----------------------------------------------------
API_REQUESTS = counter(
    "sd_api_requests_total", "HTTP requests served, by route template",
    labelnames=("route",))

# -- tracing (tracing.py, flight.py) ----------------------------------------
TRACE_SPANS = counter(
    "sd_trace_spans_total", "Spans recorded into the ring buffer",
    labelnames=("ok",))
TRACE_TIMELINE_EVENTS = counter(
    "sd_trace_timeline_events_total",
    "Pipeline timeline events recorded by the flight recorder "
    "(flight.py): per-batch stage/H2D/kernel/retire phases plus the "
    "per-batch bound-attribution windows")

# -- sanitizer (sanitize.py) ------------------------------------------------
SANITIZE_VIOLATIONS = counter(
    "sd_sanitize_violations_total",
    "Runtime-sanitizer detections (SDTPU_SANITIZE=1), by kind: "
    "loop_stall | lock_across_await | lock_order_cycle | "
    "jit_retrace_budget | host_transfer | task_exception | "
    "task_orphaned | chan_overflow | data_race | sql_undeclared | "
    "sql_autocommit_write | persist_undeclared_write | "
    "persist_unfsynced_rename",
    labelnames=("kind",))
SANITIZE_LOOP_MAX_STALL = gauge(
    "sd_sanitize_loop_max_stall_seconds",
    "Longest single event-loop callback observed by the sanitizer "
    "since process start (0 while the sanitizer is off)")

# -- thread-safety (threadctx.py ownership registry) ------------------------
RACE_TRACKED_WRITES = counter(
    "sd_race_tracked_writes_total",
    "Attribute/container writes recorded by the armed threadctx write "
    "recorder (declared owner classes only; 0 while the race guard is "
    "off)")
RACE_CANDIDATES = counter(
    "sd_race_candidates_total",
    "Writes that broke their declared ownership contract — one attr "
    "written from >=2 threads with an empty lockset intersection, a "
    "second thread on a loop_only/single_thread attr, or a post-init "
    "write to an immutable one. Each is a data_race sanitizer "
    "violation (raised in tier-1, counted in production)",
    labelnames=("cls_attr",))
RACE_HANDOFF_CLOSED = counter(
    "sd_race_handoff_closed_total",
    "Cross-thread loop hand-offs (threadctx.call_threadsafe) dropped "
    "because the target event loop was already closed mid-shutdown — "
    "work that is moot by definition, counted instead of crashing the "
    "posting executor thread")

# -- jit contracts (ops/jit_registry.py) ------------------------------------
JIT_RETRACES = counter(
    "sd_jit_retraces_total",
    "New jit traces (cache growth) observed by the retrace guard, per "
    "registered contract name",
    labelnames=("fn",))
JIT_CACHE_SIZE = gauge(
    "sd_jit_cache_size",
    "Current process-wide trace count per registered jit contract "
    "(compared against the contract's max_traces budget)",
    labelnames=("fn",))
JIT_DECLARED_TRANSFERS = counter(
    "sd_jit_declared_transfers_total",
    "Entries into declared io() host-transfer scopes, per contract "
    "name (the sanctioned D2H points of the device pipelines)",
    labelnames=("fn",))

# -- task supervisor (tasks.py) ---------------------------------------------
TASK_SPAWNED = counter(
    "sd_task_spawned_total",
    "Tasks registered with the structured-concurrency supervisor, by "
    "ownership path (instance #seq stripped)",
    labelnames=("owner",))
TASK_ORPHANED = counter(
    "sd_task_orphaned_total",
    "Supervised tasks that survived a shutdown reap's grace period "
    "(SDTPU_TASK_REAP_S) — each is a task_orphaned sanitizer "
    "violation")
TASK_CANCEL_LATENCY = histogram(
    "sd_task_cancel_latency_seconds",
    "Seconds from a supervisor cancel() to the task actually "
    "finishing (shutdown responsiveness of the component tree)")

# -- channel contracts (channels.py) ----------------------------------------
CHAN_DEPTH = gauge(
    "sd_chan_depth",
    "Instantaneous item depth per registered channel (channels.py); "
    "multi-instance channels (per-tunnel windows, per-subscription ws "
    "buffers) sample the most recently updated instance",
    labelnames=("name",))
CHAN_HIGH_WATER = gauge(
    "sd_chan_high_water",
    "Deepest observed depth per registered channel since process "
    "start (monotonic across instance churn; the armed sanitizer "
    "raises when depth would exceed the declared capacity)",
    labelnames=("name",))
CHAN_SHED = counter(
    "sd_chan_shed_total",
    "Items dropped or coalesced away by a channel's overflow policy "
    "(shed_oldest eviction, shed_new rejection, coalesce replacement)",
    labelnames=("name",))
CHAN_PUT_BLOCK_SECONDS = histogram(
    "sd_chan_put_block_seconds",
    "Producer wait for space on block-policy channels (only waits are "
    "observed, not instant puts) — the backpressure actually exerted",
    labelnames=("name",),
    buckets=(0.0001, 0.001, 0.01, 0.05, 0.1, 0.5, 1, 5, 30, 120))

# -- fleet observatory (fleet.py, p2p/obs.py) -------------------------------
OBS_REQUESTS = counter(
    "sd_obs_requests_total",
    "Observability-protocol requests served to peers (p2p/obs.py "
    "serve_obs — the p2p obs.* handler and the rspc obs.* queries "
    "both dispatch through it), by request kind "
    "(metrics | health | trace | error)",
    labelnames=("what",))
FLEET_POLLS = counter(
    "sd_fleet_polls_total",
    "Fleet-observatory peer poll attempts (fleet.py), by outcome: "
    "ok | unreachable (connect/timeout failure, peer row goes "
    "stale-degraded) | malformed (snapshot rejected by the schema "
    "gate without touching the fleet view)",
    labelnames=("outcome",))
FLEET_PEERS = gauge(
    "sd_fleet_peers",
    "Peers currently registered with the fleet observatory's poller "
    "(paired p2p routes plus explicitly added clients)")
FLEET_PEERS_STALE = gauge(
    "sd_fleet_peers_stale",
    "Registered peers whose last good obs.health snapshot is older "
    "than 2x the poll interval (or who never answered) — each is a "
    "degraded row in the fleet view with last-seen evidence")

# -- health observatory (health.py) -----------------------------------------
HEALTH_STATE = gauge(
    "sd_health_state",
    "Per-subsystem saturation state computed by the health "
    "observatory's engine (health.py): 0 = ok, 1 = degraded, "
    "2 = saturated. The attribution behind each non-ok state is "
    "served by the node.health rspc query / subscription",
    labelnames=("subsystem",))
HEALTH_SAMPLES = counter(
    "sd_health_samples_total",
    "Sampler observations taken by the health observatory (each "
    "spools delta-snapshots of every registered family into the "
    "health.series rings and re-evaluates saturation)")

# -- timeout contracts (timeouts.py) ----------------------------------------
TIMEOUTS_FIRED = counter(
    "sd_timeout_fired_total",
    "Declared network-await budgets that fired, per contract name "
    "(timeouts.py registry) — which peers/paths are hanging",
    labelnames=("name",))

# -- backoff contracts (timeouts.py declare_backoff) -------------------------
BACKOFF_RETRIES = counter(
    "sd_backoff_retries_total",
    "Retries scheduled under a declared backoff policy (timeouts.py "
    "registry), per policy name — each is one jittered-exponential "
    "delay actually imposed on a failing operation",
    labelnames=("name",))
BACKOFF_GAVE_UP = counter(
    "sd_backoff_gave_up_total",
    "Backoff ladders exhausted (max_tries reached) per declared "
    "policy name — the operation stops retrying and degrades (the "
    "sync announcer hands the peer to the fleet observatory as "
    "stale; callers of with_backoff see the final exception)",
    labelnames=("name",))

# -- chaos plane (chaos.py) --------------------------------------------------
CHAOS_INJECTED = counter(
    "sd_chaos_injected_total",
    "Faults injected by the armed chaos plane (chaos.py, SDTPU_CHAOS "
    "spec), per declared fault point and kind — counted BEFORE the "
    "effect lands so artifacts reconcile observed degradation "
    "against injected cause. 0 forever while disarmed",
    labelnames=("name", "kind"))

# -- incident observatory (incidents.py) -------------------------------------
INCIDENTS_OPENED = counter(
    "sd_incident_opened_total",
    "Evidence bundles snapshot-frozen by the incident observatory "
    "(incidents.py), per declared trigger kind — each is one durable "
    "postmortem written to the incidents.store channel's on-disk "
    "bound",
    labelnames=("kind",))
INCIDENTS_DEDUPED = counter(
    "sd_incident_deduped_total",
    "Trigger firings collapsed into an existing fingerprint "
    "(subsystem + resource + kind) inside its "
    "SDTPU_INCIDENT_WINDOW_S rate-limit window — a storm shows up "
    "here, not as a store full of identical bundles")
INCIDENTS_DROPPED = counter(
    "sd_incident_dropped_total",
    "Bundles evicted from the bounded incidents.store (count cap via "
    "the declared channel's shed_oldest, byte cap via "
    "SDTPU_INCIDENT_STORE_MB) — evidence lost to the bound; the "
    "health observatory flags a non-zero delta under the incidents "
    "subsystem")
INCIDENTS_RECOVERED = counter(
    "sd_incident_recovered_total",
    "Partially-written bundles found at next-boot WAL recovery, by "
    "outcome: promoted (complete .json.tmp renamed into the store) | "
    "discarded (torn tmp unlinked — the crash landed mid-write)",
    labelnames=("outcome",))
INCIDENT_OPEN = gauge(
    "sd_incident_open",
    "Unacknowledged bundles currently in the incidents store — the "
    "untriaged postmortem backlog (incidents.ack drains it)")
INCIDENT_STORE_BYTES = gauge(
    "sd_incident_store_bytes",
    "Bytes of bundle JSON currently held by the on-disk incidents "
    "store, enforced below SDTPU_INCIDENT_STORE_MB by oldest-first "
    "eviction")

# -- persistence plane (persist.py) ------------------------------------------
PERSIST_WRITES = counter(
    "sd_persist_writes_total",
    "Durable writes committed through the declared persistence seam "
    "(persist.py registry), per artifact name — atomic/WAL file "
    "commits, sealed streams, scratch acquisitions, and DB-backed "
    "append commits all count here",
    labelnames=("name",))
PERSIST_FSYNC_SECONDS = histogram(
    "sd_persist_fsync_seconds",
    "Latency of fsync calls issued by the persist seam (file fsyncs "
    "before rename, directory fsyncs after) — slow-disk weather on "
    "the durability path shows up here before it shows up as job "
    "latency")
PERSIST_VIOLATIONS = counter(
    "sd_persist_violations_total",
    "Fs-auditor detections (persist.arm, with the sanitizer), by "
    "kind: persist_undeclared_write (raw os.replace from a product "
    "module outside the seam) | persist_unfsynced_rename (rename "
    "with no preceding file fsync against the artifact's declared "
    "policy) — raised in tier-1, counted in production",
    labelnames=("kind",))

# -- wire plane (p2p/wire.py) ------------------------------------------------
WIRE_FRAMES = counter(
    "sd_wire_frames_total",
    "Frames validated by the armed wire auditor at the pack/unpack "
    "seam, per declared message name and direction (`in` = decoded "
    "off a transport, `out` = encoded toward one) — the live census "
    "of which declared contracts actually carry traffic",
    labelnames=("name", "dir"))
WIRE_VIOLATIONS = counter(
    "sd_wire_violations_total",
    "Wire-auditor detections (wire.arm, with the sanitizer), by "
    "kind: undeclared (frame matching no declared contract) | "
    "schema (declared kind, payload drifted from its schema) | "
    "size_cap (frame over its declared cap) | proto_skew (version "
    "const mismatch) — raised in tier-1, counted in production",
    labelnames=("kind",))
WIRE_BYTES = counter(
    "sd_wire_bytes_total",
    "Payload bytes carried by audited frames, per declared message "
    "name (plaintext msgpack size at the tunnel seam — AEAD and "
    "length-header overhead excluded), so one chatty contract's "
    "share of the mesh is attributable by name",
    labelnames=("name",))
