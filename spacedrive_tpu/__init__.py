"""spacedrive_tpu — a TPU-native VDFS engine.

A brand-new framework with the capabilities of the reference VDFS core
(Spacedrive's `sd-core`): location indexing, content-addressed file
identification (sampled BLAKE3 CAS IDs), integrity validation, duplicate
detection, media processing, CRDT library sync — with the identification
hot path executed as a batched JAX pipeline on TPU behind a pausable/
resumable job-system boundary.

Layout:
    ops/       device + host kernels (BLAKE3, pHash, Hamming)
    models/    the flagship device pipelines (identifier, validator, neardup)
    parallel/  mesh construction and sharding helpers
    db/        SQLite data model + typed store
    jobs/      stateful job engine (pause/resume/checkpoint)
    location/  walker, indexer rules, path algebra
    objects/   file identification / validation / fs op jobs
    library/   library manager + node config
    sync/      CRDT sync engine (HLC, op log, ingest)
    p2p/       host-side mesh (discovery, pairing, transfer)
    utils/     event bus, migrator, misc
"""

__version__ = "0.1.0"
