"""Authenticated STREAM encryption in 1 MiB blocks.

The construction is the LE31 STREAM mode the reference uses via
`aead::stream::{EncryptorLE31, DecryptorLE31}`
(crates/crypto/src/crypto/stream.rs:8-14): each block is sealed with a
per-block nonce = base nonce ‖ le32(counter | last_block << 31), so
blocks cannot be reordered, truncated, or extended without detection.
Base-nonce lengths follow the reference (types.rs:22-24): 20 bytes for
XChaCha20-Poly1305 (24-byte AEAD nonce − 4), 8 for AES-256-GCM (12 − 4).

Sync (bytes in/bytes out) and streaming (file-like reader/writer) APIs;
the job system wraps the streaming form for encrypt/decrypt jobs.
"""

from __future__ import annotations

import enum
import os
import struct
from typing import BinaryIO

from cryptography.hazmat.primitives.ciphers.aead import AESGCM

from .primitives import AEAD_TAG_LEN, BLOCK_LEN, Protected
from .xchacha import XChaCha20Poly1305


class Algorithm(enum.Enum):
    XCHACHA20_POLY1305 = "XChaCha20Poly1305"
    AES_256_GCM = "Aes256Gcm"

    @property
    def nonce_len(self) -> int:
        return 20 if self is Algorithm.XCHACHA20_POLY1305 else 8

    def generate_nonce(self) -> bytes:
        return os.urandom(self.nonce_len)

    def _aead(self, key: bytes):
        if self is Algorithm.XCHACHA20_POLY1305:
            return XChaCha20Poly1305(key)
        return AESGCM(key)


# Step-local by construction: each Encryptor/Decryptor is created,
# driven, and dropped inside ONE to_thread job-step body, so the
# nonce counter never has two live writer threads — the class-level
# two-context union the pass sees is two DIFFERENT jobs' private
# instances, not shared state.
class _Stream:  # sdlint: ok[shared-mutation]
    def __init__(self, key: Protected, nonce: bytes, algorithm: Algorithm):
        if len(key) != 32:
            raise ValueError("stream key must be 32 bytes")
        if len(nonce) != algorithm.nonce_len:
            raise ValueError(
                f"nonce length mismatch: {len(nonce)} != "
                f"{algorithm.nonce_len} for {algorithm.value}")
        self._aead = algorithm._aead(key.expose())
        self._base = nonce
        self._counter = 0

    def _next_nonce(self, last: bool) -> bytes:
        if self._counter >= 1 << 31:
            raise OverflowError("STREAM counter exhausted")
        value = self._counter | (int(last) << 31)
        self._counter += 1
        return self._base + struct.pack("<I", value)


class Encryptor(_Stream):
    def encrypt_next(self, plaintext: bytes, aad: bytes = b"",
                     last: bool = False) -> bytes:
        return self._aead.encrypt(self._next_nonce(last), plaintext,
                                  aad or None)

    def encrypt_last(self, plaintext: bytes, aad: bytes = b"") -> bytes:
        return self.encrypt_next(plaintext, aad, last=True)

    @classmethod
    def encrypt_streams(cls, key: Protected, nonce: bytes,
                        algorithm: Algorithm, reader: BinaryIO,
                        writer: BinaryIO, aad: bytes = b"") -> int:
        """Seal reader → writer in BLOCK_LEN blocks; returns bytes read.

        The AAD (the serialized header in file encryption) binds only the
        first block, as in the reference (stream.rs encrypt_streams)."""
        enc = cls(key, nonce, algorithm)
        total = 0
        block = reader.read(BLOCK_LEN)
        first = True
        while True:
            nxt = reader.read(BLOCK_LEN)
            total += len(block)
            this_aad = aad if first else b""
            if nxt:
                writer.write(enc.encrypt_next(block, this_aad))
            else:
                writer.write(enc.encrypt_last(block, this_aad))
                break
            block, first = nxt, False
        return total

    @classmethod
    def encrypt_bytes(cls, key: Protected, nonce: bytes,
                      algorithm: Algorithm, data: bytes,
                      aad: bytes = b"") -> bytes:
        import io

        out = io.BytesIO()
        cls.encrypt_streams(key, nonce, algorithm, io.BytesIO(data), out,
                            aad)
        return out.getvalue()


class Decryptor(_Stream):
    def decrypt_next(self, ciphertext: bytes, aad: bytes = b"",
                     last: bool = False) -> bytes:
        return self._aead.decrypt(self._next_nonce(last), ciphertext,
                                  aad or None)

    def decrypt_last(self, ciphertext: bytes, aad: bytes = b"") -> bytes:
        return self.decrypt_next(ciphertext, aad, last=True)

    @classmethod
    def decrypt_streams(cls, key: Protected, nonce: bytes,
                        algorithm: Algorithm, reader: BinaryIO,
                        writer: BinaryIO, aad: bytes = b"") -> int:
        dec = cls(key, nonce, algorithm)
        sealed = BLOCK_LEN + AEAD_TAG_LEN
        total = 0
        block = reader.read(sealed)
        first = True
        while True:
            nxt = reader.read(sealed)
            this_aad = aad if first else b""
            if nxt:
                pt = dec.decrypt_next(block, this_aad)
            else:
                pt = dec.decrypt_last(block, this_aad)
            writer.write(pt)
            total += len(pt)
            if not nxt:
                break
            block, first = nxt, False
        return total

    @classmethod
    def decrypt_bytes(cls, key: Protected, nonce: bytes,
                      algorithm: Algorithm, data: bytes,
                      aad: bytes = b"") -> Protected:
        import io

        out = io.BytesIO()
        cls.decrypt_streams(key, nonce, algorithm, io.BytesIO(data), out,
                            aad)
        return Protected(bytearray(out.getbuffer()))


def encrypt_key(master_key: Protected, nonce: bytes, algorithm: Algorithm,
                wrapping_key: Protected, aad: bytes = b"") -> bytes:
    """Seal a 32-byte key (one STREAM block → 48 bytes)."""
    enc = Encryptor(wrapping_key, nonce, algorithm)
    return enc.encrypt_last(master_key.expose(), aad)


def decrypt_key(encrypted: bytes, nonce: bytes, algorithm: Algorithm,
                wrapping_key: Protected, aad: bytes = b"") -> Protected:
    dec = Decryptor(wrapping_key, nonce, algorithm)
    return Protected(bytearray(dec.decrypt_last(encrypted, aad)))


__all__ = [
    "Algorithm", "Encryptor", "Decryptor", "encrypt_key", "decrypt_key",
]
