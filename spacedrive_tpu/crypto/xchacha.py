"""XChaCha20-Poly1305 built from HChaCha20 + IETF ChaCha20-Poly1305.

The `cryptography` package ships only the 12-byte-nonce IETF AEAD; the
24-byte-nonce XChaCha variant (the reference's default algorithm,
crates/crypto/src/types.rs:22) derives a subkey with HChaCha20 from the
first 16 nonce bytes, then runs IETF ChaCha20-Poly1305 with nonce
``b"\\x00"*4 + nonce[16:24]`` (draft-irtf-cfrg-xchacha-03 §2). HChaCha20
is a single 20-round permutation — pure Python is fine at one call per
stream block.
"""

from __future__ import annotations

import struct

from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305

_MASK = 0xFFFFFFFF
_SIGMA = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)


def _quarter(s, a, b, c, d):
    s[a] = (s[a] + s[b]) & _MASK
    s[d] ^= s[a]
    s[d] = ((s[d] << 16) | (s[d] >> 16)) & _MASK
    s[c] = (s[c] + s[d]) & _MASK
    s[b] ^= s[c]
    s[b] = ((s[b] << 12) | (s[b] >> 20)) & _MASK
    s[a] = (s[a] + s[b]) & _MASK
    s[d] ^= s[a]
    s[d] = ((s[d] << 8) | (s[d] >> 24)) & _MASK
    s[c] = (s[c] + s[d]) & _MASK
    s[b] ^= s[c]
    s[b] = ((s[b] << 7) | (s[b] >> 25)) & _MASK


def hchacha20(key: bytes, nonce16: bytes) -> bytes:
    """HChaCha20 subkey derivation (draft-irtf-cfrg-xchacha-03 §2.2)."""
    if len(key) != 32 or len(nonce16) != 16:
        raise ValueError("hchacha20 needs a 32-byte key and 16-byte nonce")
    s = list(_SIGMA) + list(struct.unpack("<8I", key)) + list(
        struct.unpack("<4I", nonce16))
    for _ in range(10):
        _quarter(s, 0, 4, 8, 12)
        _quarter(s, 1, 5, 9, 13)
        _quarter(s, 2, 6, 10, 14)
        _quarter(s, 3, 7, 11, 15)
        _quarter(s, 0, 5, 10, 15)
        _quarter(s, 1, 6, 11, 12)
        _quarter(s, 2, 7, 8, 13)
        _quarter(s, 3, 4, 9, 14)
    return struct.pack("<8I", *(s[i] for i in (0, 1, 2, 3, 12, 13, 14, 15)))


class XChaCha20Poly1305:
    """AEAD with 24-byte nonces; API mirrors cryptography's AEAD classes."""

    NONCE_LEN = 24

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError("XChaCha20Poly1305 needs a 32-byte key")
        self._key = key

    def _inner(self, nonce: bytes) -> tuple:
        if len(nonce) != self.NONCE_LEN:
            raise ValueError("XChaCha20Poly1305 nonce must be 24 bytes")
        subkey = hchacha20(self._key, nonce[:16])
        return ChaCha20Poly1305(subkey), b"\x00" * 4 + nonce[16:]

    def encrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
        aead, n12 = self._inner(nonce)
        return aead.encrypt(n12, data, aad)

    def decrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
        aead, n12 = self._inner(nonce)
        return aead.decrypt(n12, data, aad)
