"""Key manager: a mounted-keys registry behind a master password.

Capability equivalent of the reference's key manager
(crates/crypto/src/keys/keymanager.rs): a root key sealed by the master
password (+ optional secret key), stored keys (each a user password
sealed under the root key) that can be mounted/unmounted at runtime, and
a keyring. The OS keychains the reference talks to (macOS Security
framework / Secret Service) aren't reachable from this runtime, so the
keyring is a file-backed store of sealed entries under the node data
dir — same interface, portable backend.
"""

from __future__ import annotations

import json
import os
import uuid as uuidlib
from dataclasses import dataclass
from typing import Dict, Optional

from .. import persist
from ..ops.blake3_ref import derive_key
from .hashing import HashingAlgorithm, Params, hash_password
from .primitives import (
    MASTER_PASSWORD_CONTEXT,
    ROOT_KEY_CONTEXT,
    Protected,
    generate_master_key,
    generate_salt,
)
from .stream import Algorithm, decrypt_key, encrypt_key


@dataclass
class StoredKey:
    """One sealed key entry (keymanager.rs StoredKey, simplified).

    Entries are sealed directly under the root key (no per-entry
    password hashing), so the only state is the AEAD triple + flags.
    """

    uuid: str
    version: int
    algorithm: Algorithm
    master_key_nonce: bytes
    encrypted_key: bytes  # the actual key material, sealed by root key
    memory_only: bool = False
    automount: bool = False

    def to_json(self) -> dict:
        return {
            "uuid": self.uuid,
            "version": self.version,
            "algorithm": self.algorithm.value,
            "master_key_nonce": self.master_key_nonce.hex(),
            "encrypted_key": self.encrypted_key.hex(),
            "automount": self.automount,
        }

    @classmethod
    def from_json(cls, d: dict) -> "StoredKey":
        return cls(
            uuid=d["uuid"], version=d["version"],
            algorithm=Algorithm(d["algorithm"]),
            master_key_nonce=bytes.fromhex(d["master_key_nonce"]),
            encrypted_key=bytes.fromhex(d["encrypted_key"]),
            automount=d.get("automount", False),
        )


class KeyManager:
    """Runtime key registry; locked until `unlock()` provides the master
    password that reveals the root key."""

    VERSION = 1

    def __init__(self, data_path: Optional[str] = None,
                 algorithm: Algorithm = Algorithm.XCHACHA20_POLY1305,
                 hashing_algorithm: HashingAlgorithm =
                 HashingAlgorithm.ARGON2ID,
                 params: Params = Params.STANDARD):
        self.algorithm = algorithm
        self.hashing_algorithm = hashing_algorithm
        self.params = params
        self._data_path = data_path
        self._root_key: Optional[Protected] = None
        self._stored: Dict[str, StoredKey] = {}
        self._mounted: Dict[str, Protected] = {}
        self._verification: Optional[dict] = None
        if data_path and os.path.exists(data_path):
            self._load()

    # -- persistence --------------------------------------------------------
    def _load(self) -> None:
        with open(self._data_path, "r") as f:
            state = json.load(f)
        self._verification = state.get("verification")
        for entry in state.get("keys", []):
            sk = StoredKey.from_json(entry)
            self._stored[sk.uuid] = sk

    def _save(self) -> None:
        if not self._data_path:
            return
        state = {
            "verification": self._verification,
            "keys": [k.to_json() for k in self._stored.values()
                     if not k.memory_only],
        }
        persist.atomic_write("crypto.keyring", self._data_path,
                             json.dumps(state))

    # -- onboarding / unlock -------------------------------------------------
    @property
    def is_unlocked(self) -> bool:
        return self._root_key is not None

    def initialize(self, master_password: Protected,
                   secret: Optional[Protected] = None) -> None:
        """First-run setup: derive the verification entry + root key."""
        if self._verification is not None:
            # Re-initializing would mint a new root key and orphan every
            # stored key sealed under the old one.
            raise ValueError(
                "key manager already initialized; unlock() instead")
        salt = generate_salt()
        costs = self.hashing_algorithm.costs(self.params)
        hashed = hash_password(self.hashing_algorithm, master_password,
                               salt, self.params, secret, costs=costs)
        wrapping = Protected(derive_key(MASTER_PASSWORD_CONTEXT,
                                        hashed.expose()))
        root = generate_master_key()
        nonce = self.algorithm.generate_nonce()
        self._verification = {
            "salt": salt.hex(),
            "nonce": nonce.hex(),
            "sealed_root": encrypt_key(root, nonce, self.algorithm,
                                       wrapping).hex(),
            "algorithm": self.algorithm.value,
            "hashing_algorithm": self.hashing_algorithm.value,
            "hashing_params": self.params.value,
            "kdf_costs": list(costs),
        }
        self._root_key = Protected(derive_key(ROOT_KEY_CONTEXT,
                                              root.expose()))
        self._save()

    def unlock(self, master_password: Protected,
               secret: Optional[Protected] = None) -> None:
        if self._verification is None:
            raise ValueError("key manager not initialized")
        v = self._verification
        hashed = hash_password(
            HashingAlgorithm(v["hashing_algorithm"]), master_password,
            bytes.fromhex(v["salt"]), Params(v["hashing_params"]), secret,
            costs=tuple(v["kdf_costs"]) if v.get("kdf_costs") else None)
        wrapping = Protected(derive_key(MASTER_PASSWORD_CONTEXT,
                                        hashed.expose()))
        # The verification record pins every parameter it was created
        # with — a manager constructed with different defaults must
        # still unlock an existing store.
        algorithm = Algorithm(v.get("algorithm", self.algorithm.value))
        try:
            root = decrypt_key(bytes.fromhex(v["sealed_root"]),
                               bytes.fromhex(v["nonce"]), algorithm,
                               wrapping)
        except Exception as e:
            raise ValueError("incorrect master password") from e
        self._root_key = Protected(derive_key(ROOT_KEY_CONTEXT,
                                              root.expose()))

    def lock(self) -> None:
        """Unmount everything and forget the root key (`set_unlocked(false)`
        + empty_keymount equivalent)."""
        for key in self._mounted.values():
            key.zeroize()
        self._mounted.clear()
        if self._root_key is not None:
            self._root_key.zeroize()
        self._root_key = None

    def _require_unlocked(self) -> Protected:
        if self._root_key is None:
            raise ValueError("key manager is locked")
        return self._root_key

    # -- stored keys ---------------------------------------------------------
    def add_key(self, key_material: Protected, *, automount: bool = False,
                memory_only: bool = False) -> str:
        root = self._require_unlocked()
        uid = str(uuidlib.uuid4())
        nonce = self.algorithm.generate_nonce()
        sealed = encrypt_key(key_material, nonce, self.algorithm, root,
                             aad=uid.encode())
        self._stored[uid] = StoredKey(
            uuid=uid, version=self.VERSION, algorithm=self.algorithm,
            master_key_nonce=nonce, encrypted_key=sealed,
            memory_only=memory_only, automount=automount)
        self._save()
        return uid

    def mount(self, uuid: str) -> None:
        root = self._require_unlocked()
        if uuid in self._mounted:
            return
        sk = self._stored[uuid]
        self._mounted[uuid] = decrypt_key(
            sk.encrypted_key, sk.master_key_nonce, sk.algorithm, root,
            aad=uuid.encode())

    def unmount(self, uuid: str) -> None:
        key = self._mounted.pop(uuid, None)
        if key is not None:
            key.zeroize()

    def mounted_key(self, uuid: str) -> Protected:
        return self._mounted[uuid]

    def automount(self) -> None:
        for uid, sk in self._stored.items():
            if sk.automount:
                self.mount(uid)

    def delete_key(self, uuid: str) -> None:
        self.unmount(uuid)
        self._stored.pop(uuid, None)
        self._save()

    def list_keys(self) -> list:
        return [
            {"uuid": k.uuid, "mounted": k.uuid in self._mounted,
             "automount": k.automount, "memory_only": k.memory_only}
            for k in self._stored.values()
        ]
