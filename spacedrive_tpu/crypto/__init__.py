"""Crypto subsystem: stream encryption, key hashing, headers, key manager.

Native-capability equivalent of the reference's `sd-crypto` crate
(/root/reference/crates/crypto): authenticated STREAM encryption
(XChaCha20-Poly1305, AES-256-GCM) in 1 MiB blocks, password hashing
(Argon2id, Balloon-BLAKE3), an encrypted-file header with up to two
keyslots, a BLAKE3 derive-key KDF with fixed context strings, an
in-memory key manager with a file-backed keyring, and secure erase.

The wire/header format is this framework's own versioned layout (the
reference's is tied to Rust aead crate internals); the cryptographic
constructions match: LE31 STREAM block chaining, 48-byte encrypted master
keys, 16-byte salts, hashed-password → master-key keyslots.
"""

from .primitives import (  # noqa: F401
    AEAD_TAG_LEN,
    BLOCK_LEN,
    ENCRYPTED_KEY_LEN,
    KEY_LEN,
    SALT_LEN,
    SECRET_KEY_LEN,
    Protected,
    generate_master_key,
    generate_salt,
)
from .stream import Algorithm, Decryptor, Encryptor  # noqa: F401
from .hashing import HashingAlgorithm, Params, hash_password  # noqa: F401
from .header import FileHeader, Keyslot  # noqa: F401
from .keymanager import KeyManager  # noqa: F401
from .erase import secure_erase  # noqa: F401
