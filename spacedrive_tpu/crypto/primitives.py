"""Crypto constants and small helpers.

Mirrors the capability constants of the reference's
/root/reference/crates/crypto/src/primitives.rs:19-68: key/salt/tag
sizes, the 1 MiB stream block, and the fixed derive-key contexts (ours
are this framework's own strings — context strings are domain
separators, so they must NOT be copied between applications).
"""

from __future__ import annotations

import os

KEY_LEN = 32
SALT_LEN = 16
SECRET_KEY_LEN = 18
AEAD_TAG_LEN = 16
# Encrypted master key: 32-byte key + 16-byte AEAD tag.
ENCRYPTED_KEY_LEN = KEY_LEN + AEAD_TAG_LEN
# STREAM block size — matches the reference's 1 MiB
# (crates/crypto/src/primitives.rs:27).
BLOCK_LEN = 1_048_576

APP_IDENTIFIER = "spacedrive-tpu"
SECRET_KEY_IDENTIFIER = "Secret key"

# Domain-separation contexts for the BLAKE3 derive-key KDF.
ROOT_KEY_CONTEXT = "spacedrive-tpu 2026-07-30 root key derivation"
MASTER_PASSWORD_CONTEXT = "spacedrive-tpu 2026-07-30 master password hash"
FILE_KEY_CONTEXT = "spacedrive-tpu 2026-07-30 file key derivation"


def generate_master_key() -> "Protected":
    return Protected(os.urandom(KEY_LEN))


def generate_salt() -> bytes:
    return os.urandom(SALT_LEN)


def generate_secret_key() -> "Protected":
    return Protected(os.urandom(SECRET_KEY_LEN))


class Protected:
    """Best-effort zeroizing secret container.

    Python equivalent of the reference's `Protected<Vec<u8>>` wrapper
    (crates/crypto/src/protected.rs): hides the value from repr/logs and
    overwrites the buffer on `zeroize()`/GC. CPython can't guarantee no
    copies exist (immutable bytes interning), so secrets are held in a
    mutable bytearray and exposed only via `.expose()`.
    """

    __slots__ = ("_buf",)

    def __init__(self, value: bytes | bytearray):
        self._buf = bytearray(value)
        if isinstance(value, bytearray):
            for i in range(len(value)):
                value[i] = 0

    def expose(self) -> bytes:
        return bytes(self._buf)

    def __len__(self) -> int:
        return len(self._buf)

    def zeroize(self) -> None:
        for i in range(len(self._buf)):
            self._buf[i] = 0

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.zeroize()
        except Exception:
            pass

    def __repr__(self) -> str:
        return "Protected(<redacted>)"

    def __eq__(self, other) -> bool:
        if isinstance(other, Protected):
            import hmac

            return hmac.compare_digest(bytes(self._buf), bytes(other._buf))
        return NotImplemented

    __hash__ = None  # secrets are not dict keys
