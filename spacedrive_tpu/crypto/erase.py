"""Secure erase: overwrite-then-unlink.

Same contract as the reference's sd-crypto erase
(crates/crypto/src/fs/erase.rs): overwrite the file's bytes with
`passes` rounds of random data, fsyncing between rounds, before the
caller unlinks it. The hot implementation is the native C++ plane
(native/sdio.cpp sd_secure_erase); this module adds the pure-Python
fallback so erase works before the native library is built.
"""

from __future__ import annotations

import os

_BLOCK = 1_048_576


def _erase_python(path: str, passes: int) -> None:
    size = os.path.getsize(path)
    # In-place overwrite is the POINT (secure erase destroys the
    # bytes where they live); atomicity would defeat it.
    # sdlint: ok[io-durability]
    with open(path, "r+b", buffering=0) as f:
        for _ in range(max(1, passes)):
            f.seek(0)
            remaining = size
            while remaining > 0:
                n = min(_BLOCK, remaining)
                f.write(os.urandom(n))
                remaining -= n
            f.flush()
            os.fsync(f.fileno())


def secure_erase(path: str, passes: int = 1, unlink: bool = False) -> None:
    from .. import native

    if native.available():
        native.secure_erase(path, passes)
    else:
        _erase_python(path, passes)
    if unlink:
        os.unlink(path)
