"""Password hashing: Argon2id and Balloon-BLAKE3.

The reference supports exactly these two algorithms, each at
Standard/Hardened/Paranoid cost levels
(crates/crypto/src/types.rs:51-54, keys/hashing.rs). Argon2id runs
through the installed `argon2` package; Balloon hashing (Boneh–Corrigan-
Gibbs–Schechter 2016) is implemented here over the framework's own
BLAKE3 (ops/blake3_ref), single-threaded with the standard delta=3
neighbor sampling. Both consume an optional 18-byte "secret key" as
additional keying material, as the reference does.

Cost levels are calibrated for this runtime rather than copied: Argon2id
uses the reference-class memory costs; Balloon's pure-Python space costs
are scaled down ~64× (it is a compatibility/portability path, not the
default). The actual cost tuple (`HashingAlgorithm.costs`) is persisted
in every keyslot / key-manager verification record and passed back in at
verify time, so existing hashes keep working if these tables are
retuned.
"""

from __future__ import annotations

import enum
import struct

from .primitives import KEY_LEN, SALT_LEN, Protected


class Params(enum.Enum):
    STANDARD = "Standard"
    HARDENED = "Hardened"
    PARANOID = "Paranoid"


# Argon2id: (memory KiB, iterations, parallelism)
_ARGON2_COSTS = {
    Params.STANDARD: (131072, 8, 4),
    Params.HARDENED: (262144, 8, 4),
    Params.PARANOID: (524288, 8, 4),
}

# Balloon-BLAKE3: (space_cost blocks of 64 B, time_cost rounds)
_BALLOON_COSTS = {
    Params.STANDARD: (2048, 2),
    Params.HARDENED: (4096, 2),
    Params.PARANOID: (8192, 2),
}


class HashingAlgorithm(enum.Enum):
    ARGON2ID = "Argon2id"
    BALLOON_BLAKE3 = "BalloonBlake3"

    def costs(self, params: Params) -> tuple:
        """Normalized 3-int cost tuple — what keyslots persist so hashes
        survive future retuning of the tables above: argon2
        (memory KiB, iterations, lanes); balloon (space, time, 0)."""
        if self is HashingAlgorithm.ARGON2ID:
            return tuple(_ARGON2_COSTS[params])
        space, time = _BALLOON_COSTS[params]
        return (space, time, 0)

    def hash(self, password: Protected, salt: bytes, params: Params,
             secret: Protected | None = None,
             costs: tuple | None = None) -> Protected:
        if len(salt) != SALT_LEN:
            raise ValueError("salt must be 16 bytes")
        pw = password.expose()
        if secret is not None:
            pw = pw + secret.expose()
        costs = tuple(costs) if costs else self.costs(params)
        if self is HashingAlgorithm.ARGON2ID:
            return _argon2id(pw, salt, costs)
        return _balloon_blake3(pw, salt, costs)


def _argon2id(password: bytes, salt: bytes, costs: tuple) -> Protected:
    from argon2.low_level import Type, hash_secret_raw

    memory, iters, lanes = costs
    raw = hash_secret_raw(
        secret=password, salt=salt, time_cost=iters, memory_cost=memory,
        parallelism=lanes, hash_len=KEY_LEN, type=Type.ID,
    )
    return Protected(bytearray(raw))


def _balloon_blake3(password: bytes, salt: bytes,
                    costs: tuple) -> Protected:
    """Balloon hashing with BLAKE3 as H; delta=3 (BCGS16 §3.2)."""
    from ..ops.blake3_ref import blake3_digest

    space, time = costs[0], costs[1]
    h = lambda *parts: blake3_digest(b"".join(parts), 64)  # noqa: E731
    cnt = 0

    def counter() -> bytes:
        nonlocal cnt
        cnt += 1
        return struct.pack("<Q", cnt - 1)

    buf = [h(counter(), password, salt)]
    for m in range(1, space):
        buf.append(h(counter(), buf[m - 1]))
    for t in range(time):
        for m in range(space):
            buf[m] = h(counter(), buf[(m - 1) % space], buf[m])
            for i in range(3):
                idx_block = h(counter(), salt,
                              struct.pack("<QQQ", t, m, i))
                other = int.from_bytes(idx_block[:8], "little") % space
                buf[m] = h(counter(), buf[m], buf[other])
    return Protected(bytearray(buf[space - 1][:KEY_LEN]))


def hash_password(algorithm: HashingAlgorithm, password: Protected,
                  salt: bytes, params: Params = Params.STANDARD,
                  secret: Protected | None = None,
                  costs: tuple | None = None) -> Protected:
    """Password (+ optional secret key) + salt → 32-byte wrapping key.

    `costs` (from a stored keyslot) overrides the live cost tables so
    old hashes keep verifying after retuning."""
    return algorithm.hash(password, salt, params, secret, costs)
