"""Encrypted-file header with keyslots, metadata, and preview media.

Capability equivalent of the reference's header module
(crates/crypto/src/header/{file,keyslot,metadata,preview_media}.rs):
magic bytes + version + algorithm + stream base nonce, up to two
keyslots (each: hashing algorithm + params, salt, content salt, and the
master key sealed under the hashed password), optional AEAD-encrypted
metadata and preview-media blobs, and the serialized header acting as
AAD for the first content block.

The byte layout is this framework's own: little-endian, length-prefixed,
msgpack-free, versioned via a u16. Magic is ``b"sdtpu\\xf5\\x01"`` (the
reference uses ``b"ballapp"``, file.rs:49 — a different app must use a
different magic).
"""

from __future__ import annotations

import io
import json
import struct
from dataclasses import dataclass, field
from typing import BinaryIO, List, Optional

from .hashing import HashingAlgorithm, Params, hash_password
from .primitives import Protected, generate_master_key, generate_salt
from .stream import Algorithm, Decryptor, Encryptor, decrypt_key, encrypt_key

MAGIC = b"sdtpu\xf5\x01"
HEADER_VERSION = 1
KEYSLOT_VERSION = 1


@dataclass
class Keyslot:
    """One password's grip on the master key.

    ``hashed(password, salt) → wrapping key``; the master key is sealed
    under the wrapping key with `nonce`. `content_salt` feeds any
    password-derived per-content keys (parity with keyslot.rs fields).
    """

    version: int
    algorithm: Algorithm
    hashing_algorithm: HashingAlgorithm
    hashing_params: Params
    # The concrete KDF cost tuple used at creation time (3 uint32s) —
    # persisted so retuning the live cost tables never breaks unlocking.
    kdf_costs: tuple
    salt: bytes
    content_salt: bytes
    master_key_nonce: bytes
    encrypted_master_key: bytes

    @classmethod
    def new(cls, algorithm: Algorithm,
            hashing_algorithm: HashingAlgorithm, params: Params,
            password: Protected, master_key: Protected,
            secret: Optional[Protected] = None) -> "Keyslot":
        salt = generate_salt()
        nonce = algorithm.generate_nonce()
        costs = hashing_algorithm.costs(params)
        wrapping = hash_password(hashing_algorithm, password, salt, params,
                                 secret, costs=costs)
        return cls(
            version=KEYSLOT_VERSION,
            algorithm=algorithm,
            hashing_algorithm=hashing_algorithm,
            hashing_params=params,
            kdf_costs=costs,
            salt=salt,
            content_salt=generate_salt(),
            master_key_nonce=nonce,
            encrypted_master_key=encrypt_key(master_key, nonce, algorithm,
                                             wrapping),
        )

    def unlock(self, password: Protected,
               secret: Optional[Protected] = None) -> Protected:
        wrapping = hash_password(self.hashing_algorithm, password,
                                 self.salt, self.hashing_params, secret,
                                 costs=self.kdf_costs)
        return decrypt_key(self.encrypted_master_key,
                           self.master_key_nonce, self.algorithm, wrapping)

    def _pack(self) -> bytes:
        return b"".join([
            struct.pack("<HBBB", self.version,
                        _ALG_CODE[self.algorithm],
                        _HASH_CODE[self.hashing_algorithm],
                        _PARAM_CODE[self.hashing_params]),
            struct.pack("<III", *self.kdf_costs),
            _pfx(self.salt), _pfx(self.content_salt),
            _pfx(self.master_key_nonce), _pfx(self.encrypted_master_key),
        ])

    @classmethod
    def _unpack(cls, r: io.BytesIO) -> "Keyslot":
        version, alg, hsh, par = struct.unpack("<HBBB", _read_exact(r, 5))
        costs = struct.unpack("<III", _read_exact(r, 12))
        try:
            return cls(
                version=version,
                algorithm=_ALG_BY_CODE[alg],
                hashing_algorithm=_HASH_BY_CODE[hsh],
                hashing_params=_PARAM_BY_CODE[par],
                kdf_costs=costs,
                salt=_read_pfx(r), content_salt=_read_pfx(r),
                master_key_nonce=_read_pfx(r),
                encrypted_master_key=_read_pfx(r),
            )
        except KeyError as e:
            raise ValueError(f"unknown keyslot field code {e}") from e


_ALG_CODE = {Algorithm.XCHACHA20_POLY1305: 0, Algorithm.AES_256_GCM: 1}
_ALG_BY_CODE = {v: k for k, v in _ALG_CODE.items()}
_HASH_CODE = {HashingAlgorithm.ARGON2ID: 0,
              HashingAlgorithm.BALLOON_BLAKE3: 1}
_HASH_BY_CODE = {v: k for k, v in _HASH_CODE.items()}
_PARAM_CODE = {Params.STANDARD: 0, Params.HARDENED: 1, Params.PARANOID: 2}
_PARAM_BY_CODE = {v: k for k, v in _PARAM_CODE.items()}


# A header (nonces, keyslots, JSON metadata, a preview thumbnail) never
# legitimately approaches this; anything larger is a corrupt or hostile
# length prefix, refused before allocation.
MAX_FIELD_LEN = 64 * 1024 * 1024


def _pfx(b: bytes) -> bytes:
    return struct.pack("<I", len(b)) + b


def _read_exact(r, n: int) -> bytes:
    out = r.read(n)
    if len(out) != n:
        raise ValueError("truncated header")
    return out


def _read_pfx(r) -> bytes:
    (n,) = struct.unpack("<I", _read_exact(r, 4))
    if n > MAX_FIELD_LEN:
        raise ValueError(f"header field length {n} exceeds limit")
    return _read_exact(r, n)


@dataclass
class FileHeader:
    """Everything needed to decrypt a file, safe to store in plaintext."""

    version: int
    algorithm: Algorithm
    nonce: bytes
    keyslots: List[Keyslot] = field(default_factory=list)
    metadata: Optional[bytes] = None       # sealed JSON
    metadata_nonce: Optional[bytes] = None
    preview_media: Optional[bytes] = None  # sealed bytes
    preview_media_nonce: Optional[bytes] = None

    MAX_KEYSLOTS = 2

    @classmethod
    def new(cls, algorithm: Algorithm = Algorithm.XCHACHA20_POLY1305,
            ) -> "FileHeader":
        return cls(version=HEADER_VERSION, algorithm=algorithm,
                   nonce=algorithm.generate_nonce())

    def add_keyslot(self, hashing_algorithm: HashingAlgorithm,
                    params: Params, password: Protected,
                    master_key: Protected,
                    secret: Optional[Protected] = None) -> None:
        if len(self.keyslots) >= self.MAX_KEYSLOTS:
            raise ValueError("header already has 2 keyslots")
        self.keyslots.append(Keyslot.new(
            self.algorithm, hashing_algorithm, params, password,
            master_key, secret))

    def decrypt_master_key(self, password: Protected,
                           secret: Optional[Protected] = None) -> Protected:
        for slot in self.keyslots:
            try:
                return slot.unlock(password, secret)
            except Exception:
                continue
        raise ValueError("no keyslot unlocked with the provided password")

    # -- sealed attachments -------------------------------------------------
    def add_metadata(self, master_key: Protected, obj) -> None:
        nonce = self.algorithm.generate_nonce()
        enc = Encryptor(master_key, nonce, self.algorithm)
        self.metadata = enc.encrypt_last(json.dumps(obj).encode())
        self.metadata_nonce = nonce

    def decrypt_metadata(self, master_key: Protected):
        if self.metadata is None:
            raise ValueError("header has no metadata")
        dec = Decryptor(master_key, self.metadata_nonce, self.algorithm)
        return json.loads(dec.decrypt_last(self.metadata))

    def add_preview_media(self, master_key: Protected, media: bytes) -> None:
        nonce = self.algorithm.generate_nonce()
        enc = Encryptor(master_key, nonce, self.algorithm)
        self.preview_media = enc.encrypt_last(media)
        self.preview_media_nonce = nonce

    def decrypt_preview_media(self, master_key: Protected) -> bytes:
        if self.preview_media is None:
            raise ValueError("header has no preview media")
        dec = Decryptor(master_key, self.preview_media_nonce, self.algorithm)
        return dec.decrypt_last(self.preview_media)

    # -- wire format --------------------------------------------------------
    def serialize(self) -> bytes:
        body = b"".join([
            struct.pack("<HB", self.version, _ALG_CODE[self.algorithm]),
            _pfx(self.nonce),
            struct.pack("<B", len(self.keyslots)),
            b"".join(s._pack() for s in self.keyslots),
            _pfx(self.metadata or b""), _pfx(self.metadata_nonce or b""),
            _pfx(self.preview_media or b""),
            _pfx(self.preview_media_nonce or b""),
        ])
        return MAGIC + _pfx(body)

    @classmethod
    def deserialize(cls, reader: BinaryIO) -> "FileHeader":
        """Read a header from the start of `reader`, leaving it
        positioned at the first content byte."""
        magic = reader.read(len(MAGIC))
        if magic != MAGIC:
            raise ValueError("not a spacedrive-tpu encrypted file")
        body = _read_pfx(reader)
        r = io.BytesIO(body)
        version, alg = struct.unpack("<HB", _read_exact(r, 3))
        if version != HEADER_VERSION:
            raise ValueError(f"unsupported header version {version}")
        if alg not in _ALG_BY_CODE:
            raise ValueError(f"unknown algorithm code {alg}")
        hdr = cls(version=version, algorithm=_ALG_BY_CODE[alg],
                  nonce=_read_pfx(r))
        (n_slots,) = struct.unpack("<B", _read_exact(r, 1))
        if n_slots > cls.MAX_KEYSLOTS:
            raise ValueError(f"too many keyslots ({n_slots})")
        for _ in range(n_slots):
            hdr.keyslots.append(Keyslot._unpack(r))
        hdr.metadata = _read_pfx(r) or None
        hdr.metadata_nonce = _read_pfx(r) or None
        hdr.preview_media = _read_pfx(r) or None
        hdr.preview_media_nonce = _read_pfx(r) or None
        return hdr

    def aad(self) -> bytes:
        """The header bytes that bind the first content block.

        Keyslots/metadata/preview can be edited after the fact (password
        change), so — like the reference (file.rs:97) — only the
        immutable prefix (magic, version, algorithm, nonce) is AAD.
        """
        return MAGIC + struct.pack("<HB", self.version,
                                   _ALG_CODE[self.algorithm]) + self.nonce


def encrypt_file(src: BinaryIO, dst: BinaryIO, password: Protected,
                 algorithm: Algorithm = Algorithm.XCHACHA20_POLY1305,
                 hashing_algorithm: HashingAlgorithm =
                 HashingAlgorithm.ARGON2ID,
                 params: Params = Params.STANDARD,
                 metadata=None, preview_media: bytes | None = None,
                 master_key: Protected | None = None) -> FileHeader:
    """Header + sealed stream → dst; returns the written header."""
    master_key = master_key or generate_master_key()
    header = FileHeader.new(algorithm)
    header.add_keyslot(hashing_algorithm, params, password, master_key)
    if metadata is not None:
        header.add_metadata(master_key, metadata)
    if preview_media is not None:
        header.add_preview_media(master_key, preview_media)
    dst.write(header.serialize())
    Encryptor.encrypt_streams(master_key, header.nonce, algorithm, src,
                              dst, aad=header.aad())
    return header


def decrypt_file(src: BinaryIO, dst: BinaryIO,
                 password: Protected) -> FileHeader:
    header = FileHeader.deserialize(src)
    master_key = header.decrypt_master_key(password)
    Decryptor.decrypt_streams(master_key, header.nonce, header.algorithm,
                              src, dst, aad=header.aad())
    return header
