"""Extension taxonomy + magic-byte disambiguation.

Covers the behavior of the reference's `sd-file-ext` crate
(/root/reference/crates/file-ext/src/extensions.rs:11-564,
/root/reference/crates/file-ext/src/magic.rs:12-236): map a file extension to
an ObjectKind category, and when extensions conflict across categories (or a
caller forces verification), check magic bytes read from the file header.

The Rust macro soup becomes one flat table: category → {ext: signatures},
where each signature is (offset, pattern, mask). A zero mask byte is a
wildcard (the reference's `_`). An empty signature list means "extension is
trusted as-is" (no magic bytes known).
"""

from __future__ import annotations

import os
from typing import BinaryIO, Dict, List, Optional, Sequence, Tuple

from .kinds import ObjectKind

# One signature: (offset, pattern bytes, mask bytes — 0x00 = wildcard).
Signature = Tuple[int, bytes, bytes]


def _sig(pattern: Sequence[Optional[int]], offset: int = 0) -> Signature:
    pat = bytes(0 if b is None else b for b in pattern)
    mask = bytes(0 if b is None else 0xFF for b in pattern)
    return (offset, pat, mask)


_ = None  # wildcard byte inside signatures, matching the reference notation

# category name → {extension → [signatures]} — extensions.rs:31-362.
EXTENSION_TABLE: Dict[str, Dict[str, List[Signature]]] = {
    "video": {
        "avi": [_sig([0x52, 0x49, 0x46, 0x46, _, _, _, _, 0x41, 0x56, 0x49, 0x20])],
        "qt": [_sig([0x71, 0x74, 0x20, 0x20])],
        "mov": [_sig([0x66, 0x74, 0x79, 0x70, 0x71, 0x74, 0x20, 0x20], 4)],
        "swf": [_sig([0x5A, 0x57, 0x53]), _sig([0x46, 0x57, 0x53])],
        "mjpeg": [],
        "ts": [_sig([0x47])],
        "mts": [_sig([0x47]), _sig([_, _, _, 0x47])],
        "mpeg": [_sig([0x47]), _sig([0x00, 0x00, 0x01, 0xBA]),
                 _sig([0x00, 0x00, 0x01, 0xB3])],
        "mxf": [_sig([0x06, 0x0E, 0x2B, 0x34, 0x02, 0x05, 0x01, 0x01,
                      0x0D, 0x01, 0x02, 0x01, 0x01, 0x02])],
        "m2v": [_sig([0x00, 0x00, 0x01, 0xBA])],
        "mpg": [],
        "mpe": [],
        "m2ts": [],
        "flv": [_sig([0x46, 0x4C, 0x56])],
        "wm": [],
        "3gp": [],
        "m4v": [_sig([0x66, 0x74, 0x79, 0x70, 0x4D, 0x34, 0x56], 4)],
        "wmv": [_sig([0x30, 0x26, 0xB2, 0x75, 0x8E, 0x66, 0xCF, 0x11,
                      0xA6, 0xD9, 0x00, 0xAA, 0x00, 0x62, 0xCE, 0x6C])],
        "asf": [_sig([0x30, 0x26, 0xB2, 0x75, 0x8E, 0x66, 0xCF, 0x11,
                      0xA6, 0xD9, 0x00, 0xAA, 0x00, 0x62, 0xCE, 0x6C])],
        "mp4": [],
        "webm": [_sig([0x1A, 0x45, 0xDF, 0xA3])],
        "mkv": [_sig([0x1A, 0x45, 0xDF, 0xA3])],
        "vob": [_sig([0x00, 0x00, 0x01, 0xBA])],
        "ogv": [_sig([0x4F, 0x67, 0x67, 0x53])],
        "wtv": [_sig([0xB7, 0xD8, 0x00])],
        "hevc": [],
        "f4v": [_sig([0x66, 0x74, 0x79, 0x70, 0x66, 0x72, 0x65, 0x65], 4)],
    },
    "image": {
        "jpg": [_sig([0xFF, 0xD8])],
        "jpeg": [_sig([0xFF, 0xD8])],
        "png": [_sig([0x89, 0x50, 0x4E, 0x47, 0x0D, 0x0A, 0x1A, 0x0A])],
        "apng": [_sig([0x89, 0x50, 0x4E, 0x47, 0x0D, 0x0A, 0x1A, 0x0A,
                       0x00, 0x00, 0x00, 0x0D, 0x49, 0x48, 0x44, 0x52])],
        "gif": [_sig([0x47, 0x49, 0x46, 0x38, _, 0x61])],
        "bmp": [_sig([0x42, 0x4D])],
        "tiff": [_sig([0x49, 0x49, 0x2A, 0x00])],
        "webp": [_sig([0x52, 0x49, 0x46, 0x46, _, _, _, _, 0x57, 0x45, 0x42, 0x50])],
        "svg": [_sig([0x3C, 0x73, 0x76, 0x67])],
        "ico": [_sig([0x00, 0x00, 0x01, 0x00])],
        "heic": [_sig([0x00, 0x00, 0x00, 0x18, 0x66, 0x74, 0x79, 0x70,
                       0x68, 0x65, 0x69, 0x63])],
        "heics": [_sig([0x00, 0x00, 0x00, 0x18, 0x66, 0x74, 0x79, 0x70,
                        0x68, 0x65, 0x69, 0x63])],
        "heif": [],
        "heifs": [],
        "hif": [],
        "avif": [],
        "avci": [],
        "avcs": [],
        "raw": [],
        "akw": [_sig([0x41, 0x4B, 0x57, 0x42])],
        "dng": [_sig([0x49, 0x49, 0x2A, 0x00, 0x08, 0x00, 0x00, 0x00,
                      0x44, 0x4E, 0x47, 0x00])],
        "cr2": [_sig([0x49, 0x49, 0x2A, 0x00, 0x10, 0x00, 0x00, 0x00,
                      0x43, 0x52, 0x02, 0x00])],
        "dcr": [_sig([0x49, 0x49, 0x2A, 0x00, 0x10, 0x00, 0x00, 0x00,
                      0x44, 0x43, 0x52, 0x00])],
        "nwr": [_sig([0x49, 0x49, 0x2A, 0x00, 0x10, 0x00, 0x00, 0x00,
                      0x4E, 0x57, 0x52, 0x00])],
        "nef": [_sig([0x49, 0x49, 0x2A, 0x00, 0x08, 0x00, 0x00, 0x00,
                      0x4E, 0x45, 0x46, 0x00])],
        "arw": [_sig([0x49, 0x49, 0x2A, 0x00, 0x08])],
        "rw2": [_sig([0x49, 0x49, 0x2A, 0x00, 0x18])],
    },
    "audio": {
        "mp3": [_sig([0x49, 0x44, 0x33])],
        "mp2": [_sig([0xFF, 0xFB]), _sig([0xFF, 0xFD])],
        "m4a": [_sig([0x66, 0x74, 0x79, 0x70, 0x4D, 0x34, 0x41, 0x20], 4)],
        "wav": [_sig([0x52, 0x49, 0x46, 0x46, _, _, _, _, 0x57, 0x41, 0x56, 0x45])],
        "aiff": [_sig([0x46, 0x4F, 0x52, 0x4D, _, _, _, _, 0x41, 0x49, 0x46, 0x46])],
        "aif": [_sig([0x46, 0x4F, 0x52, 0x4D, _, _, _, _, 0x41, 0x49, 0x46, 0x46])],
        "flac": [_sig([0x66, 0x4C, 0x61, 0x43])],
        "ogg": [_sig([0x4F, 0x67, 0x67, 0x53])],
        "oga": [_sig([0x4F, 0x67, 0x67, 0x53])],
        "opus": [_sig([0x4F, 0x70, 0x75, 0x73, 0x48, 0x65, 0x61, 0x64], 28)],
        "wma": [_sig([0x30, 0x26, 0xB2, 0x75, 0x8E, 0x66, 0xCF, 0x11,
                      0xA6, 0xD9, 0x00, 0xAA, 0x00, 0x62, 0xCE, 0x6C])],
        "amr": [_sig([0x23, 0x21, 0x41, 0x4D, 0x52])],
        "aac": [_sig([0xFF, 0xF1])],
        "wv": [_sig([0x77, 0x76, 0x70, 0x6B])],
        "voc": [_sig(list(b"Creative Voice File"))],
        "tta": [_sig([0x54, 0x54, 0x41])],
        "loas": [_sig([0x56, 0xE0])],
        "caf": [_sig([0x63, 0x61, 0x66, 0x66])],
        "aptx": [_sig([0x4B, 0xBF, 0x4B, 0xBF])],
        "adts": [_sig([0xFF, 0xF1])],
        "ast": [_sig([0x53, 0x54, 0x52, 0x4D])],
    },
    "archive": {
        "zip": [_sig([0x50, 0x4B, 0x03, 0x04])],
        "rar": [_sig([0x52, 0x61, 0x72, 0x21, 0x1A, 0x07, 0x00])],
        "tar": [_sig([0x75, 0x73, 0x74, 0x61, 0x72])],
        "gz": [_sig([0x1F, 0x8B, 0x08])],
        "bz2": [_sig([0x42, 0x5A, 0x68])],
        "7z": [_sig([0x37, 0x7A, 0xBC, 0xAF, 0x27, 0x1C])],
        "xz": [_sig([0xFD, 0x37, 0x7A, 0x58, 0x5A, 0x00])],
    },
    "executable": {
        "exe": [_sig([0x4D, 0x5A])],
        "app": [_sig([0x4D, 0x5A])],
        "apk": [_sig([0x50, 0x4B, 0x03, 0x04])],
        "deb": [_sig(list(b"!<arch>\ndebian-binary"))],
        "dmg": [_sig([0x78, 0x01, 0x73, 0x0D, 0x62, 0x62, 0x60])],
        "pkg": [_sig([0x4D, 0x5A])],
        "rpm": [_sig([0xED, 0xAB, 0xEE, 0xDB])],
        "msi": [_sig([0xD0, 0xCF, 0x11, 0xE0, 0xA1, 0xB1, 0x1A, 0xE1])],
        "jar": [_sig([0x50, 0x4B, 0x03, 0x04])],
        "bat": [],
    },
    "document": {
        "pdf": [_sig([0x25, 0x50, 0x44, 0x46, 0x2D])],
        "key": [_sig([0x50, 0x4B, 0x03, 0x04])],
        "pages": [_sig([0x50, 0x4B, 0x03, 0x04])],
        "numbers": [_sig([0x50, 0x4B, 0x03, 0x04])],
        "doc": [_sig([0xD0, 0xCF, 0x11, 0xE0, 0xA1, 0xB1, 0x1A, 0xE1])],
        "docx": [_sig([0x50, 0x4B, 0x03, 0x04])],
        "xls": [_sig([0xD0, 0xCF, 0x11, 0xE0, 0xA1, 0xB1, 0x1A, 0xE1])],
        "xlsx": [_sig([0x50, 0x4B, 0x03, 0x04])],
        "ppt": [_sig([0xD0, 0xCF, 0x11, 0xE0, 0xA1, 0xB1, 0x1A, 0xE1])],
        "pptx": [_sig([0x50, 0x4B, 0x03, 0x04])],
        "odt": [_sig([0x50, 0x4B, 0x03, 0x04])],
        "ods": [_sig([0x50, 0x4B, 0x03, 0x04])],
        "odp": [_sig([0x50, 0x4B, 0x03, 0x04])],
        "ics": [_sig(list(b"BEGIN:VCARD"))],
        "hwp": [_sig([0xD0, 0xCF, 0x11, 0xE0, 0xA1, 0xB1, 0x1A, 0xE1])],
    },
    "text": {ext: [] for ext in ("txt", "rtf", "md", "markdown")},
    "config": {ext: [] for ext in (
        "ini", "json", "yaml", "yml", "toml", "xml", "mathml", "rss",
        "csv", "cfg", "compose", "tsconfig",
    )},
    "encrypted": {
        "bytes": [_sig(list(b"ballapp"))],
        "container": [_sig(list(b"sdbox"))],
        "block": [_sig(list(b"sdblock"))],
    },
    "key": {ext: [] for ext in ("pgp", "pub", "pem", "p12", "p8", "keychain")},
    "font": {
        "ttf": [_sig([0x00, 0x01, 0x00, 0x00, 0x00])],
        "otf": [_sig([0x4F, 0x54, 0x54, 0x4F, 0x00])],
        "woff": [_sig([0x77, 0x4F, 0x46, 0x46])],
        "woff2": [_sig([0x77, 0x4F, 0x46, 0x32])],
    },
    "mesh": {
        "fbx": [_sig([0x46, 0x42, 0x58, 0x20])],
        "obj": [_sig([0x6F, 0x62, 0x6A])],
    },
    "code": {ext: [] for ext in (
        "scpt", "scptd", "applescript", "sh", "zsh", "fish", "bash",
        "c", "cpp", "h", "hpp", "rb", "js", "mjs", "jsx", "html", "css",
        "sass", "scss", "less", "cr", "cs", "csx", "d", "dart",
        "dockerfile", "go", "hs", "java", "kt", "kts", "lua", "make",
        "nim", "nims", "m", "mm", "ml", "mli", "mll", "mly", "pl", "php",
        "php1", "php2", "php3", "php4", "php5", "php6", "phps", "phpt",
        "phtml", "ps1", "psd1", "psm1", "py", "qml", "r", "rs", "sol",
        "sql", "swift", "ts", "tsx", "vala", "zig", "vue", "scala",
        "mdx", "astro", "mts",
    )},
    "database": {
        "sqlite": [_sig(list(b"SQLite format 3\x00"))],
        "db": [],
    },
    "book": {
        "azw": [_sig([0x52, 0x49, 0x46, 0x46])],
        "azw3": [_sig([0x52, 0x49, 0x46, 0x46])],
        "epub": [_sig([0x50, 0x4B, 0x03, 0x04])],
        "mobi": [_sig([0x4D, 0x4F, 0x42, 0x49])],
    },
}

CATEGORY_KIND: Dict[str, ObjectKind] = {
    "document": ObjectKind.DOCUMENT,
    "video": ObjectKind.VIDEO,
    "image": ObjectKind.IMAGE,
    "audio": ObjectKind.AUDIO,
    "archive": ObjectKind.ARCHIVE,
    "executable": ObjectKind.EXECUTABLE,
    "text": ObjectKind.TEXT,
    "encrypted": ObjectKind.ENCRYPTED,
    "key": ObjectKind.KEY,
    "font": ObjectKind.FONT,
    "mesh": ObjectKind.MESH,
    "code": ObjectKind.CODE,
    "database": ObjectKind.DATABASE,
    "book": ObjectKind.BOOK,
    "config": ObjectKind.CONFIG,
}

# Category priority for conflicts mirrors the declaration order of the
# reference's `Extension` enum (extensions.rs:12-28): the first listed
# category wins when from_str finds several and no magic check runs.
_CATEGORY_ORDER = (
    "document", "video", "image", "audio", "archive", "executable",
    "text", "encrypted", "key", "font", "mesh", "code", "database",
    "book", "config",
)


def extension_candidates(ext: str) -> List[str]:
    """Categories claiming this extension, in enum declaration order."""
    e = ext.lower()
    return [c for c in _CATEGORY_ORDER if e in EXTENSION_TABLE[c]]


def _match_sig(buf: bytes, sig: Signature) -> bool:
    offset, pat, mask = sig
    # The reference reads exactly len(pat) bytes at offset and fails the
    # check on short reads (magic.rs:161-175).
    window = buf[offset:offset + len(pat)]
    if len(window) != len(pat):
        return False
    return all((b & m) == (p & m) for b, p, m in zip(window, pat, mask))


# Longest (offset + length) over every signature — one header read suffices.
MAX_MAGIC_SPAN = max(
    (off + len(pat)
     for sigs in EXTENSION_TABLE.values()
     for siglist in sigs.values()
     for off, pat, _m in siglist),
    default=0,
)


def verify_magic(category: str, ext: str, header: bytes) -> bool:
    """True if `header` carries one of the extension's magic signatures."""
    sigs = EXTENSION_TABLE[category].get(ext.lower())
    if not sigs:
        return False
    return any(_match_sig(header, s) for s in sigs)


def _read_header(path: str | os.PathLike) -> Optional[bytes]:
    try:
        with open(path, "rb") as f:
            return f.read(MAX_MAGIC_SPAN)
    except OSError:
        return None


def kind_for_extension(ext: str) -> ObjectKind:
    """Extension-only kind resolution (no file I/O, no conflict checks)."""
    cands = extension_candidates(ext)
    if not cands:
        return ObjectKind.UNKNOWN
    return CATEGORY_KIND[cands[0]]


def resolve_kind(
    path: str | os.PathLike,
    ext: Optional[str] = None,
    header: Optional[bytes] = None,
) -> ObjectKind:
    """Resolve a file's ObjectKind the way `Extension::resolve_conflicting`
    does (magic.rs:178-236): unambiguous extensions are trusted without I/O;
    the known cross-category conflicts (`ts`, `mts`: video vs code) read the
    header and fall back to code when video magic is absent.

    `header` lets batch pipelines (which already staged the first bytes of
    every file) avoid a second read.
    """
    if ext is None:
        name = os.path.basename(os.fspath(path))
        dot = name.rfind(".")
        ext = name[dot + 1:] if dot > 0 else ""
    if not ext:
        return ObjectKind.UNKNOWN
    cands = extension_candidates(ext)
    if not cands:
        return ObjectKind.UNKNOWN
    if len(cands) == 1:
        return CATEGORY_KIND[cands[0]]
    # Conflict path. The reference only disambiguates ts/mts (video|code);
    # any other conflict resolves to None → Unknown (magic.rs:222-234).
    if ext.lower() in ("ts", "mts") and "video" in cands:
        if header is None:
            header = _read_header(path)
        if header is not None and verify_magic("video", ext, header):
            return ObjectKind.VIDEO
        return ObjectKind.CODE
    return ObjectKind.UNKNOWN
