from .kinds import ObjectKind
from .ext import (
    EXTENSION_TABLE,
    extension_candidates,
    kind_for_extension,
    resolve_kind,
    verify_magic,
)

__all__ = [
    "ObjectKind",
    "EXTENSION_TABLE",
    "extension_candidates",
    "kind_for_extension",
    "resolve_kind",
    "verify_magic",
]
