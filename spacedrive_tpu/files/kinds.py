"""Object kind taxonomy.

Mirrors the reference's 26-variant `ObjectKind` enum
(/root/reference/crates/file-ext/src/kind.rs:6-56). Discriminant values are
stable and stored in the `object.kind` column, so the order here must never
change (the reference carries the same warning for its TS bindings).
"""

from __future__ import annotations

import enum


class ObjectKind(enum.IntEnum):
    UNKNOWN = 0
    DOCUMENT = 1
    FOLDER = 2
    TEXT = 3
    PACKAGE = 4
    IMAGE = 5
    AUDIO = 6
    VIDEO = 7
    ARCHIVE = 8
    EXECUTABLE = 9
    ALIAS = 10
    ENCRYPTED = 11
    KEY = 12
    LINK = 13
    WEB_PAGE_ARCHIVE = 14
    WIDGET = 15
    ALBUM = 16
    COLLECTION = 17
    FONT = 18
    MESH = 19
    CODE = 20
    DATABASE = 21
    BOOK = 22
    CONFIG = 23
    DOTFILE = 24
    SCREENSHOT = 25
