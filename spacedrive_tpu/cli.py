"""Command-line host.

Covers the reference's `apps/cli` (header inspection,
/root/reference/apps/cli/src/main.rs:14-23 + print_crypto_details) and
adds the obvious node entry points the reference leaves to its server/
desktop hosts: `serve` (HTTP/websocket API host) and one-shot
`encrypt`/`decrypt` for files outside any library.

Usage:
    python -m spacedrive_tpu header  sealed.sdtpu
    python -m spacedrive_tpu serve   --data-dir ~/.spacedrive-tpu
    python -m spacedrive_tpu encrypt plain.bin   [-o out.sdtpu]
    python -m spacedrive_tpu decrypt out.sdtpu   [-o plain.bin]
"""

from __future__ import annotations

import argparse
import getpass
import sys


def _cmd_header(args) -> int:
    from .crypto.header import FileHeader

    try:
        with open(args.path, "rb") as f:
            header = FileHeader.deserialize(f)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(f"Header version: {header.version}")
    print(f"Encryption algorithm: {header.algorithm.value}")
    print(f"AAD (hex): {header.aad().hex()}")
    for i, slot in enumerate(header.keyslots):
        print(f"Keyslot {i}:")
        print(f"  Version: {slot.version}")
        print(f"  Hashing algorithm: {slot.hashing_algorithm.value}"
              f" ({slot.hashing_params.value})")
        print(f"  Salt (hex): {slot.salt.hex()}")
        print(f"  Master key (hex, encrypted): "
              f"{slot.encrypted_master_key.hex()}")
        print(f"  Master key nonce (hex): {slot.master_key_nonce.hex()}")
    print(f"Metadata: {'present' if header.metadata else 'none'}")
    print("Preview media: "
          f"{'present' if header.preview_media else 'none'}")
    return 0


def _password(args) -> "object":
    from .crypto.primitives import Protected

    pw = args.password or getpass.getpass("password: ")
    return Protected(pw.encode())


def _cmd_encrypt(args) -> int:
    from .crypto.header import encrypt_file

    import os

    out = args.output or args.path + ".sdtpu"
    if os.path.exists(out):
        print(f"error: output {out} already exists", file=sys.stderr)
        return 1
    password = _password(args)  # prompt before the output file exists
    try:
        # Streaming user output to a caller-chosen path (pre-checked
        # absent above; partial output removed in the except below) —
        # not durable node state.
        # sdlint: ok[io-durability]
        with open(args.path, "rb") as fin, open(out, "wb") as fout:
            encrypt_file(fin, fout, password, metadata={"name": args.path})
    except (OSError, ValueError) as e:
        try:
            os.remove(out)
        except OSError:
            pass
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(out)
    return 0


def _cmd_decrypt(args) -> int:
    import os

    from .crypto.header import decrypt_file

    out = args.output or (
        args.path[:-6] if args.path.endswith(".sdtpu")
        else args.path + ".decrypted")
    if os.path.exists(out):
        print(f"error: output {out} already exists", file=sys.stderr)
        return 1
    password = _password(args)
    try:
        # Same streaming-user-output shape as _cmd_encrypt: the
        # caller owns the target.
        # sdlint: ok[io-durability]
        with open(args.path, "rb") as fin, open(out, "wb") as fout:
            decrypt_file(fin, fout, password)
    except (OSError, ValueError) as e:
        try:
            os.remove(out)
        except OSError:
            pass
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(out)
    return 0


def _cmd_serve(args) -> int:
    import asyncio

    from .api.server import serve

    try:
        asyncio.run(serve(args.data_dir, args.host, args.port))
    except KeyboardInterrupt:
        pass
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="spacedrive_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("header", help="inspect an encrypted file's header")
    p.add_argument("path")
    p.set_defaults(fn=_cmd_header)

    p = sub.add_parser("encrypt", help="encrypt a file")
    p.add_argument("path")
    p.add_argument("-o", "--output")
    p.add_argument("-p", "--password")
    p.set_defaults(fn=_cmd_encrypt)

    p = sub.add_parser("decrypt", help="decrypt a file")
    p.add_argument("path")
    p.add_argument("-o", "--output")
    p.add_argument("-p", "--password")
    p.set_defaults(fn=_cmd_decrypt)

    p = sub.add_parser("serve", help="run the node + API server")
    p.add_argument("--data-dir", required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080)
    p.set_defaults(fn=_cmd_serve)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
