"""Sync manager: atomic domain+op-log writes, op retrieval, LWW apply.

Behavioral equivalent of `sd-core-sync`'s Manager
(/root/reference/core/crates/sync/src/manager.rs:62-199) plus the apply
half of the generated `ModelSyncData` logic
(/root/reference/crates/sync-generator/src/lib.rs:24-80): because our data
model lives in a Python registry (store/models.py), the CRDT emit/apply
code is generic over that registry instead of codegen'd per model.

Key contracts kept from the reference:
- `write_ops` batches domain queries and op-log inserts in ONE transaction
  (manager.rs:87) and broadcasts a created-message afterwards;
- `get_ops` merges the shared+relation op tables, filtered by per-instance
  HLC watermarks, ordered by (timestamp, instance) (manager.rs:130-199);
- FK fields on shared models sync as the referenced row's pub_id, resolved
  back to local row ids on apply (the sync-generator's `@relation`/FK
  handling).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from .. import chaos
from ..store import models as M
from ..store.db import Database
from ..telemetry import (
    SYNC_BLOB_PAGES_APPLIED,
    SYNC_BLOB_PAGES_WRITTEN,
    SYNC_BLOBS_EXPLODED,
    SYNC_INGEST_ERRORS,
    SYNC_OPS_APPLIED,
    SYNC_OPS_ENCODED,
    SYNC_OPS_INGESTED,
    SYNC_OPS_SERVED,
)
from . import opblob
from .crdt import (CRDTOperation, OpKind, RelationOp, SharedOp, op_payload,
                   pack_value, unpack_value, uuid4_bytes, uuid4_bytes_batch)
from .hlc import HLC

# Pre-encoded msgpack fragments of op_payload's canonical key order for
# the two field-is-None shapes bulk_shared_ops emits (create: 5-key map;
# multi-field update: 6-key map with trailing update=True). They live in
# sync/opblob.py now (the blob codec shares them); any change to
# op_payload's dict layout MUST change them AND the mirrored constants
# in native/sdio.cpp — the byte-equality tests between the bulk, blob,
# and dataclass op paths are the guard.
_BULK_HDR5 = opblob.BULK_HDR5
_BULK_HDR6 = opblob.BULK_HDR6
_BULK_OPID = opblob.BULK_OPID
_BULK_VALUES = opblob.BULK_VALUES
_BULK_UPDATE_T = opblob.BULK_UPDATE_T

# A bulk append at or above this many ops on a SOLO library (no other
# instance registered) lands as ONE shared_op_blob page instead of that
# many shared_operation rows. Below it, per-blob bookkeeping plus the
# get_ops decode overhead outweigh the saved row inserts.
BLOB_MIN_OPS = 256


@dataclass
class GetOpsArgs:
    """(instance pub_id → NTP64 watermark) + page size
    (manager.rs:24-28; OPS_PER_REQUEST=1000 at p2p/sync/mod.rs:403)."""

    clocks: List[Tuple[bytes, int]]
    count: int = 1000


def cascade_local_fks(conn, model: str, local_id: int) -> None:
    """Clear every FK reference to `model` row `local_id` that the DDL
    does not already handle (no declared ON DELETE): nullable columns
    are SET NULL, non-nullable referencing rows are deleted. Shared by
    the sync apply path (_apply_shared) and LOCAL delete sites like the
    orphan remover — a raw DELETE FROM object with foreign_keys=ON
    fails on tag/label/album/space membership rows otherwise (and one
    failure aborts the whole cleanup batch). Table/column names come
    from the model registry; the f-strings bind the declared
    store.helper.update / store.helper.delete shapes."""
    for rname, rmodel in M.MODELS.items():
        for f in rmodel.fields:
            if _fk_target(f) != model or f.on_delete:
                continue
            if f.nullable:
                conn.execute(
                    f"UPDATE {rname} SET {f.name} = NULL "
                    f"WHERE {f.name} = ?", (local_id,))
            else:
                conn.execute(
                    f"DELETE FROM {rname} WHERE {f.name} = ?",
                    (local_id,))


def _fk_target(f: M.Field) -> Optional[str]:
    """Referenced table name for FK fields (e.g. 'location')."""
    if not f.references:
        return None
    return f.references.split("(", 1)[0]


class SyncManager:
    def __init__(self, db: Database, instance_pub_id: bytes,
                 emit_messages: bool = True):
        self.db = db
        self.instance = instance_pub_id
        self.clock = HLC()
        self.emit_messages = emit_messages
        # One subscriber per watching component (sync_net), not per
        # event; a library lifetime registers O(1) of them.
        self._on_created: List[Callable[[], None]] = []  # sdlint: ok[unbounded-growth]
        # instance pub_id → local row id, and → last-seen NTP64. Both
        # are keyed by PAIRED INSTANCES — the sync topology, mirrored
        # from the instance table, not traffic — and the timestamps
        # map is the CRDT watermark vector: evicting an entry would
        # re-pull that instance's whole history, so "grow-only" is the
        # correctness contract here.
        self._instance_ids: Dict[bytes, int] = {}  # sdlint: ok[unbounded-growth]
        self.timestamps: Dict[bytes, int] = {}  # sdlint: ok[unbounded-growth]
        self._sync_indexes_ready = False
        # Solo = no other instance registered: bulk writers may append
        # page-level op blobs (get_ops decodes them; the first remote
        # ingest explodes them to rows). Flips False forever the moment
        # a peer instance appears (register_instance).
        self._solo = True
        # Clone fast-path bookkeeping: the batched blob apply
        # (receive_blob_pages) may skip per-op LWW compare only while
        # it can PROVE compare is a no-op — every incoming timestamp
        # newer than every logged op, and no shared delete tombstones
        # in the log. Both lazy-init from SQL on first use
        # (_op_log_state) and are then maintained in memory by every
        # op-insert site (_note_ops_logged).
        self._op_log_high: Optional[int] = None
        self._has_shared_tombstones: Optional[bool] = None
        # Leaf lock over the in-memory sync caches above: they are
        # mutated from to_thread job steps (write_ops),
        # loop-side ingest, AND pairing — the threadctx ownership
        # registry declares them guarded_by("_meta_lock"), the
        # shared-mutation pass checks it, and the armed race recorder
        # watches it at runtime. Always a leaf (taken after db locks,
        # never around them), so it can add no ordering cycle.
        self._meta_lock = threading.Lock()
        self._load_instances()
        # Re-ingest ops quarantined by an OLDER schema (one cheap
        # SELECT when the table is empty — the common case).
        self.drain_quarantined_ops()

    def _ensure_sync_indexes(self) -> None:
        """Build the op-log read indexes on first sync use — they are
        declared lazy (store/models.py) so bulk local writers never pay
        per-row index maintenance on tables only sync reads."""
        if self._sync_indexes_ready:
            return
        self.db.ensure_lazy_indexes("shared_operation")
        self.db.ensure_lazy_indexes("relation_operation")
        with self._meta_lock:
            self._sync_indexes_ready = True

    def _load_instances(self) -> None:
        rows = self.db.run("sync.instances.all")
        with self._meta_lock:
            for row in rows:
                self._instance_ids[row["pub_id"]] = row["id"]
                if row["timestamp"]:
                    self.timestamps[row["pub_id"]] = row["timestamp"]
                    self.clock.update_with_timestamp(row["timestamp"])
            self._solo = all(
                pub == self.instance for pub in self._instance_ids)

    def _instance_row_id(self, pub_id: bytes, conn=None) -> int:
        rid = self._instance_ids.get(pub_id)
        if rid is None:
            row = self.db.run("sync.instances.id_by_pub", (pub_id,),
                              conn=conn)
            if row is None:
                raise KeyError(f"unknown instance {pub_id.hex()}")
            rid = row["id"]
            with self._meta_lock:
                self._instance_ids[pub_id] = rid
        return rid

    def _op_log_state(self) -> Tuple[int, bool]:
        """(highest logged timestamp across every op-log format, any
        shared delete tombstones logged?) — the two facts the clone
        fast path's LWW-compare-is-a-no-op proof rests on. Lazy SQL
        init, then kept current by _note_ops_logged; blob pages never
        hold tombstones (bulk writers emit only the field-is-None
        create/multi-update shapes), so the tombstone probe only needs
        the row table."""
        if self._op_log_high is None:
            hi = 0
            for row in (self.db.run("sync.oplog.max_ts_shared"),
                        self.db.run("sync.oplog.max_ts_relation"),
                        self.db.run("sync.oplog.max_ts_blob")):
                if row is not None and row["t"] is not None:
                    hi = max(hi, row["t"])
            with self._meta_lock:
                if self._op_log_high is None:
                    self._op_log_high = hi
        if self._has_shared_tombstones is None:
            probed = self.db.run(
                "sync.oplog.has_tombstones") is not None
            with self._meta_lock:
                if self._has_shared_tombstones is None:
                    self._has_shared_tombstones = probed
        return self._op_log_high, self._has_shared_tombstones

    def _note_ops_logged(self, ts_high: int, any_shared_delete: bool
                         ) -> None:
        """Keep the lazily-computed _op_log_state facts current after
        an op-insert batch (no-op while still uninitialized)."""
        with self._meta_lock:
            if self._op_log_high is not None and \
                    ts_high > self._op_log_high:
                self._op_log_high = ts_high
            if any_shared_delete and \
                    self._has_shared_tombstones is not None:
                self._has_shared_tombstones = True

    def on_created(self, cb: Callable[[], None]) -> None:
        """Subscribe to SyncMessage::Created broadcasts (manager.rs:89)."""
        self._on_created.append(cb)

    def _notify_created(self) -> None:
        if not self.emit_messages:
            return
        for cb in list(self._on_created):
            cb()

    # -- op factory (crates/sync/src/factory.rs:22-120) --------------------

    def _new_op(self, typ) -> CRDTOperation:
        return CRDTOperation.new(self.instance, self.clock.new_timestamp(), typ)

    def shared_create(self, model: str, record_id: Any,
                      values: Optional[Dict[str, Any]] = None
                      ) -> List[CRDTOperation]:
        """Create = ONE "c" op carrying all initial values.

        The reference emits a bare create + one "u:<field>" op per field
        (factory.rs:34-50) and left the batched form unimplemented
        (crdt.rs:94); carrying the values in the create op is ~9× fewer
        op-log rows on bulk indexing — measured DB-bound at 1M files.
        Post-create edits remain per-field LWW updates."""
        return [self._new_op(
            SharedOp(model, record_id, values=dict(values or {})))]

    def shared_update(self, model: str, record_id: Any, field: str,
                      value: Any) -> CRDTOperation:
        return self._new_op(SharedOp(model, record_id, field=field, value=value))

    def shared_multi_update(self, model: str, record_id: Any,
                            values: Dict[str, Any]) -> CRDTOperation:
        """ONE update op carrying several columns (kind "u:a+b").

        Apply stays per-field LWW: each carried field is dropped on apply
        if a strictly newer op covers it (_apply_shared), and the whole
        op is stale only when every field is covered at >= its timestamp
        (_compare_message). Exists for bulk writers — the identifier's
        {cas_id, object_id} per file — where per-field ops made the op
        log out-cost the hash (round-3 phase_ms: ops 377 / hash 334)."""
        return self._new_op(SharedOp(
            model, record_id, values=dict(values), update=True))

    def shared_delete(self, model: str, record_id: Any) -> CRDTOperation:
        return self._new_op(SharedOp(model, record_id, delete=True))

    def relation_create(self, relation: str, item_id: Any, group_id: Any,
                        values: Optional[Dict[str, Any]] = None
                        ) -> List[CRDTOperation]:
        return [self._new_op(RelationOp(
            relation, item_id, group_id, values=dict(values or {})))]

    def relation_update(self, relation: str, item_id: Any, group_id: Any,
                        field: str, value: Any) -> CRDTOperation:
        return self._new_op(
            RelationOp(relation, item_id, group_id, field=field, value=value))

    def relation_delete(self, relation: str, item_id: Any,
                        group_id: Any) -> CRDTOperation:
        return self._new_op(RelationOp(relation, item_id, group_id, delete=True))

    # -- write path --------------------------------------------------------

    @contextmanager
    def write_ops(self, ops: Sequence[CRDTOperation]):
        """One atomic transaction for domain writes + op-log rows
        (manager.rs:62-99). Usage:

            with sync.write_ops(ops) as conn:
                db.insert_many("file_path", rows, conn=conn)
        """
        with self.db.write_tx() as conn:
            yield conn
            if self.emit_messages:
                self._insert_op_rows(conn, ops)
        if self.emit_messages and ops:
            self._notify_created()

    def _insert_op_rows(self, conn, ops: Iterable[CRDTOperation]) -> None:
        """Append local ops to the log — no-op when message emission is
        disabled (SyncEmitMessages feature flag, manager.rs:69), so every
        direct caller respects the flag without its own guard.

        Bulk path: the identifier emits 2-3 ops per file, so a 4096-file
        chunk lands ~10k op rows here — executemany keeps that out of the
        per-row Python/sqlite statement loop."""
        if not self.emit_messages:
            return
        my_id = self._instance_row_id(self.instance, conn)
        shared_rows: List[tuple] = []
        rel_rows: List[tuple] = []
        for op in ops:
            t = op.typ
            data = pack_value(op_payload(
                t.field, t.value, t.delete, op.id, t.values,
                getattr(t, "update", False)))
            if isinstance(t, SharedOp):
                shared_rows.append(
                    (op.timestamp, t.model, pack_value(t.record_id),
                     t.kind, data, my_id))
            else:
                rel_rows.append(
                    (op.timestamp, t.relation, pack_value(t.item_id),
                     pack_value(t.group_id), t.kind, data, my_id))
        if shared_rows:
            self.db.run_many("sync.oplog.insert_shared", shared_rows,
                             conn=conn)
        if rel_rows:
            self.db.run_many("sync.oplog.insert_relation", rel_rows,
                             conn=conn)
        if shared_rows or rel_rows:
            SYNC_OPS_ENCODED.labels(format="row").inc(
                len(shared_rows) + len(rel_rows))
            self._note_ops_logged(
                max(r[0] for r in shared_rows + rel_rows),
                any(r[3] == OpKind.DELETE for r in shared_rows))

    def bulk_shared_ops(
        self, conn, model: str,
        specs: Sequence[Tuple[Any, str, Optional[str], Any,
                              Optional[Dict[str, Any]]]],
    ) -> int:
        """Fast-path op-log append for bulk writers (identifier/indexer).

        Each spec is (record_id, kind, field, value, values) — kind "c"
        carries `values`, kind "u:<field>" carries field+value, and a
        multi-update kind ("u:a+b", field None) carries `values`. Emits
        byte-equivalent rows to _insert_op_rows over the corresponding
        CRDTOperation list, minting timestamps in one clock batch and
        skipping the per-op dataclass layer (~40 µs → ~8 µs per op).
        Returns the number of rows appended (0 when emission is off).
        """
        if not self.emit_messages or not specs:
            return 0
        my_id = self._instance_row_id(self.instance, conn)
        stamps = self.clock.new_timestamps(len(specs))
        op_ids = uuid4_bytes_batch(len(specs))

        # Blob fast path: a big uniform chunk on a SOLO library lands as
        # ONE shared_op_blob page (sync/opblob.py format, natively
        # encoded) instead of len(specs) op rows — the dominant host-
        # side cost of the 1M identify. get_ops decodes blobs; the
        # first remote ingest explodes them into indexed rows
        # (_ensure_row_oplog), so the CRDT contract is unchanged.
        if self._solo and len(specs) >= BLOB_MIN_OPS:
            kind0 = specs[0][1]
            # Only the create / multi-update shapes may land as blobs:
            # pack_bulk_payload would encode a 'd' spec as a
            # create-shaped payload with delete=False (silently
            # un-deleting on every replica) — deletes fall through to
            # the row path, whose tombstone bookkeeping handles them.
            uniform = (kind0 == "c" or kind0.startswith("u:")) and all(
                field is None and kind == kind0
                and type(rid) is bytes and len(rid) == 16
                for rid, kind, field, _v, _vs in specs)
            if uniform:
                blob = opblob.encode_uniform(
                    stamps, [s[0] for s in specs], kind0, op_ids,
                    [pack_value(s[4]) for s in specs])
                self.db.run(
                    "sync.blob.insert",
                    (model, stamps[0], stamps[-1], len(specs), blob,
                     my_id), conn=conn)
                self._note_ops_logged(stamps[-1], False)
                SYNC_OPS_ENCODED.labels(format="blob").inc(len(specs))
                SYNC_BLOB_PAGES_WRITTEN.inc()
                return len(specs)

        def _rid(rid) -> bytes:
            # record ids are almost always 16-byte pub_ids; msgpack
            # bin8(16) is b"\xc4\x10" + payload — one concat instead of
            # a packb call per row (byte-identical, ~0.8 s/1.9M rows)
            if type(rid) is bytes and len(rid) == 16:
                return b"\xc4\x10" + rid
            return pack_value(rid)

        def _data(kind: str, field, value, values, op_id) -> bytes:
            # field-is-None ops (creates and multi-field updates — the
            # ONLY shapes bulk writers emit) concatenate pre-encoded
            # msgpack fragments around one packb of `values`, skipping
            # the per-op payload-dict build (1.8 -> 0.7 µs/op at 380k
            # ops per 200k-file identify). Byte-equality with the
            # dataclass path is asserted by tests — _compare_message
            # dedup depends on it.
            if kind == OpKind.DELETE:
                # 'd' also has field None but must NOT take the
                # create-shaped fragment path (delete=False would
                # silently un-delete on every replica)
                return pack_value(op_payload(None, None, True, op_id,
                                             values))
            if field is None:
                if kind.startswith("u:"):
                    return (_BULK_HDR6 + _BULK_OPID + op_id
                            + _BULK_VALUES + pack_value(values)
                            + _BULK_UPDATE_T)
                return (_BULK_HDR5 + _BULK_OPID + op_id
                        + _BULK_VALUES + pack_value(values))
            return pack_value(op_payload(
                field, value, False, op_id, values))

        rows = [
            (ts, model, _rid(rid), kind,
             _data(kind, field, value, values, op_id), my_id)
            for (rid, kind, field, value, values), ts, op_id
            in zip(specs, stamps, op_ids)
        ]
        self.db.run_many("sync.oplog.insert_shared", rows, conn=conn)
        self._note_ops_logged(
            stamps[-1], any(s[1] == OpKind.DELETE for s in specs))
        SYNC_OPS_ENCODED.labels(format="row").inc(len(rows))
        return len(rows)

    def _insert_op_row(self, conn, op: CRDTOperation, instance_row_id: int) -> None:
        t = op.typ
        SYNC_OPS_ENCODED.labels(format="row").inc()
        self._note_ops_logged(
            op.timestamp, isinstance(t, SharedOp) and t.delete)
        data = pack_value(op_payload(
            t.field, t.value, t.delete, op.id, t.values,
            getattr(t, "update", False)))
        if isinstance(t, SharedOp):
            self.db.run(
                "sync.oplog.insert_shared",
                (op.timestamp, t.model, pack_value(t.record_id), t.kind,
                 data, instance_row_id), conn=conn)
        else:
            self.db.run(
                "sync.oplog.insert_relation",
                (op.timestamp, t.relation, pack_value(t.item_id),
                 pack_value(t.group_id), t.kind, data, instance_row_id),
                conn=conn)

    # -- read path (manager.rs:130-199) ------------------------------------

    def get_ops(self, args: GetOpsArgs) -> List[CRDTOperation]:
        """Ops newer than the given per-instance watermarks, plus all ops
        from instances absent from the watermark list, ordered by
        (timestamp, instance), limited to args.count. Reads BOTH op-log
        storage formats: per-op rows and page-level blobs (the solo
        bulk-writer format) — a fresh peer pulling from a library that
        never synced before sees one merged, identically-ordered
        stream."""
        self._ensure_sync_indexes()
        clock_ids = [pub for pub, _ in args.clocks]
        results: List[Tuple[int, bytes, CRDTOperation]] = \
            self._blob_op_tuples(args)
        for table, is_shared in (("shared_operation", True),
                                 ("relation_operation", False)):
            conds, params = [], []
            for pub, ts in args.clocks:
                conds.append(
                    "(i.pub_id = ? AND o.timestamp > ?)")
                params.extend([pub, ts])
            if clock_ids:
                ph = ",".join("?" for _ in clock_ids)
                conds.append(f"i.pub_id NOT IN ({ph})")
                params.extend(clock_ids)
            where = " OR ".join(conds) if conds else "1=1"
            # binds the declared sync.oplog.page shape (table from the
            # two-element literal tuple above, watermark disjunction)
            rows = self.db.query(
                f"SELECT o.*, i.pub_id AS instance_pub_id FROM {table} o "
                f"JOIN instance i ON i.id = o.instance_id "
                f"WHERE {where} ORDER BY o.timestamp ASC LIMIT ?",
                params + [args.count],
            )
            for row in rows:
                results.append(
                    (row["timestamp"], row["instance_pub_id"],
                     self._row_to_op(row, is_shared)))
        results.sort(key=lambda t: (t[0], t[1]))
        page = [op for _, _, op in results[:args.count]]
        SYNC_OPS_SERVED.inc(len(page))
        return page

    def _blob_op_tuples(self, args: GetOpsArgs
                        ) -> List[Tuple[int, bytes, CRDTOperation]]:
        """(timestamp, instance, op) tuples from page-level op blobs,
        filtered by the same per-instance watermarks as the row tables.

        Blobs decode lazily in min_ts order: fully-served pages are
        excluded in SQL by their max_ts, and decoding stops once
        args.count qualifying ops are collected and the next blob's
        whole range lies past the count-th smallest timestamp — a pull
        loop paging a million-op backlog touches one or two blobs per
        page, not the whole log."""
        conds, params = [], []
        for pub, ts in args.clocks:
            conds.append("(i.pub_id = ? AND b.max_ts > ?)")
            params.extend([pub, ts])
        if args.clocks:
            ph = ",".join("?" for _ in args.clocks)
            conds.append(f"i.pub_id NOT IN ({ph})")
            params.extend([pub for pub, _ in args.clocks])
        where = " OR ".join(conds) if conds else "1=1"
        # binds the declared sync.blob.metas_watermarked shape
        metas = self.db.query(
            f"SELECT b.id, b.model, b.min_ts, i.pub_id AS pub "
            f"FROM shared_op_blob b JOIN instance i "
            f"ON i.id = b.instance_id WHERE {where} ORDER BY b.min_ts",
            params)
        if not metas:
            return []
        wm = dict(args.clocks)
        out: List[Tuple[int, bytes, CRDTOperation]] = []
        for m in metas:
            if len(out) >= args.count:
                kth = sorted(t for t, _, _ in out)[args.count - 1]
                if m["min_ts"] > kth:
                    break
            row = self.db.run("sync.blob.data_by_id", (m["id"],))
            if row is None:
                # A concurrent first-ingest exploded this blob between
                # the metas SELECT and here (each statement reads its
                # own WAL snapshot): its ops are rows now, served by
                # the row-table queries that follow.
                continue
            floor = wm.get(m["pub"])
            kth = None  # lazy per-blob cutoff, see below
            for ts, rid, kind, payload in opblob.iter_entries(
                    row["data"]):
                if floor is not None and ts <= floor:
                    continue
                if len(out) >= args.count:
                    # Entries within a blob ascend (HLC batch mint), so
                    # once an entry exceeds the count-th smallest
                    # collected timestamp nothing later in this blob
                    # can make the final page — stop DECODING: the
                    # iterator is lazy (opblob.iter_entries), so a 2M-op
                    # backlog never pays msgpack work past the window a
                    # multi-page pull will re-request anyway.
                    if kth is None:
                        kth = sorted(t for t, _, _ in out)[args.count - 1]
                    if ts > kth:
                        break
                out.append((ts, m["pub"], self._entry_to_op(
                    m["model"], ts, rid, payload, m["pub"])))
        return out

    def _entry_to_op(self, model: str, ts: int, rid_packed: bytes,
                     payload: bytes, pub: bytes) -> CRDTOperation:
        """One decoded blob entry → CRDTOperation (the blob-format
        sibling of _row_to_op; payload bytes are identical to what the
        row format's `data` column would hold)."""
        data = unpack_value(payload)
        typ = SharedOp(
            model, unpack_value(rid_packed), data.get("field"),
            data.get("value"), bool(data.get("delete")),
            data.get("values"), bool(data.get("update")))
        return CRDTOperation(pub, ts, data.get("op_id", b""), typ)

    def _ensure_row_oplog(self) -> None:
        """Explode page-level op blobs into indexed shared_operation
        rows. Ingest needs this: _compare_message and the tombstone
        checks do per-(model, record_id) lookups the blob format cannot
        index — the price of entering sync after a bulk-optimized solo
        life, paid once (like the lazy op-log indexes). Batched in
        small transactions so a huge backlog never holds the write
        lock for seconds; crash-safe because each blob's rows insert
        and its blob row deletes atomically."""
        while True:
            metas = self.db.run("sync.blob.metas_batch")
            if not metas:
                return
            # one SMALL tx per 16-blob batch BY DESIGN: a multi-GB
            # backlog must never hold the write lock for seconds
            with self.db.write_tx() as conn:  # sdlint: ok[tx-shape]
                for m in metas:
                    self._explode_blob_conn(conn, m)

    def _explode_blob_conn(self, conn, m) -> None:
        """One blob page → its op rows + blob-row delete, atomically on
        the caller's transaction."""
        self.db.run_many(
            "sync.oplog.insert_shared",
            [(ts, m["model"], rid, kind, payload, m["instance_id"])
             for ts, rid, kind, payload
             in opblob.decode_entries(m["data"])], conn=conn)
        self.db.run("sync.blob.delete", (m["id"],), conn=conn)
        SYNC_BLOBS_EXPLODED.inc()

    def _row_to_op(self, row, is_shared: bool) -> CRDTOperation:
        data = unpack_value(row["data"])
        if is_shared:
            typ: Any = SharedOp(
                row["model"], unpack_value(row["record_id"]),
                data.get("field"), data.get("value"),
                bool(data.get("delete")), data.get("values"),
                bool(data.get("update")),
            )
        else:
            typ = RelationOp(
                row["relation"], unpack_value(row["item_id"]),
                unpack_value(row["group_id"]), data.get("field"),
                data.get("value"), bool(data.get("delete")),
                data.get("values"),
            )
        return CRDTOperation(
            row["instance_pub_id"], row["timestamp"],
            data.get("op_id", b""), typ)

    # -- clone fast path: serving side --------------------------------------

    def iter_clone_stream(self, clocks: Sequence[Tuple[bytes, int]],
                          ops_page: int = 1000):
        """Originator half of the full-library clone fast path: yield
        ``("page", page_dict)`` items carrying stored `shared_op_blob`
        pages VERBATIM (no explode, no per-op materialization, no
        re-encode) interleaved with ``("ops", [CRDTOperation, ...])``
        row-format chunks, for a peer that has NEVER diverged from the
        blob-authoring instances (its watermark for them is absent or
        zero — anything else means it already holds some of their
        history and the per-op get_ops path must arbitrate).

        Ordering invariant: a page's ack advances the puller's
        watermark for the authoring instance to the page's max_ts, so
        every ROW-format op from that instance with a smaller timestamp
        is yielded AHEAD of the page — otherwise the advanced watermark
        would skip it forever. Ops from other instances are untouched
        by the ack and flow through the normal pull loop afterwards.
        Pages are fetched lazily (one SELECT per yield) so a 2M-op
        backlog never materializes in memory."""
        self._ensure_sync_indexes()
        wm = dict(clocks)
        metas = self.db.run("sync.clone.blob_metas")
        floors: Dict[bytes, int] = {}
        for m in metas:
            pub = m["pub"]
            if wm.get(pub, 0) != 0:
                continue
            floor = floors.get(pub, 0)
            for ops in self._row_ops_between(
                    m["instance_id"], pub, floor, m["min_ts"], ops_page):
                yield ("ops", ops)
            row = self.db.run("sync.blob.data_by_id", (m["id"],))
            if row is None:
                # Concurrently exploded (a first remote ingest ran
                # between the metas SELECT and here): its ops are rows
                # now, picked up by the next page's row window or the
                # normal pull loop after the stream.
                continue
            yield ("page", {
                "model": m["model"], "instance": pub,
                "min_ts": m["min_ts"], "max_ts": m["max_ts"],
                "n_ops": m["n_ops"], "data": row["data"]})
            floors[pub] = m["max_ts"]

    def _row_ops_between(self, instance_row_id: int, pub: bytes,
                         lo: int, hi: int, ops_page: int):
        """Row-format ops authored by one instance with lo < ts < hi,
        in timestamp order, chunked to ops_page (bounded memory)."""
        while True:
            merged: List[Tuple[int, bool, Any]] = []
            for table, is_shared in (("shared_operation", True),
                                     ("relation_operation", False)):
                # binds the declared sync.oplog.window shape
                rows = self.db.query(
                    f"SELECT o.*, ? AS instance_pub_id FROM {table} o "
                    f"WHERE o.instance_id = ? AND o.timestamp > ? "
                    f"AND o.timestamp < ? ORDER BY o.timestamp LIMIT ?",
                    (pub, instance_row_id, lo, hi, ops_page))
                merged.extend((r["timestamp"], is_shared, r) for r in rows)
            if not merged:
                return
            merged.sort(key=lambda t: t[0])
            chunk = merged[:ops_page]
            yield [self._row_to_op(r, s) for _, s, r in chunk]
            if len(merged) < ops_page:
                return
            lo = chunk[-1][0]

    # -- ingest (core/crates/sync/src/ingest.rs:110-233) -------------------

    def register_instance(self, pub_id: bytes, **fields: Any) -> int:
        """Insert an instance row if unknown; returns local row id."""
        if pub_id != self.instance:
            with self._meta_lock:
                self._solo = False  # peers exist: row-format bulk ops
        row = self.db.run("sync.instances.id_by_pub", (pub_id,))
        if row is not None:
            with self._meta_lock:
                self._instance_ids[pub_id] = row["id"]
            return row["id"]
        import time
        defaults = {
            "pub_id": pub_id, "identity": fields.pop("identity", b""),
            "node_id": fields.pop("node_id", b""),
            "node_name": fields.pop("node_name", "?"),
            "node_platform": fields.pop("node_platform", 0),
            "last_seen": fields.pop("last_seen", int(time.time())),
            "date_created": fields.pop("date_created", int(time.time())),
        }
        defaults.update(fields)
        rid = self.db.insert("instance", defaults)
        with self._meta_lock:
            self._instance_ids[pub_id] = rid
        return rid

    def receive_crdt_operation(self, op: CRDTOperation) -> bool:
        """Ingest one remote op; returns True if applied, False if stale
        (receive_crdt_operation, ingest.rs:110-160). Thin wrapper over
        the batched path so the two can never diverge."""
        applied, errors = self.receive_crdt_operations([op])
        if errors:
            raise RuntimeError(errors[0])
        return applied == 1

    def receive_crdt_operations(self, ops: Sequence[CRDTOperation]
                                ) -> Tuple[int, List[str]]:
        """Batched ingest of one pull-loop page: ONE transaction for
        the whole page (a SAVEPOINT isolates each op so one malformed
        remote op rolls back alone, not the page), one watermark write
        per instance — measured ~6× the per-op-transaction drain rate.
        Returns (applied_count, per-op error strings).

        Ops can arrive RELAYED: in an A↔B↔C line, C receives A-authored
        ops from B's log without ever pairing with A. An unknown origin
        instance is auto-registered as a placeholder row (no identity/
        route — those only come from direct pairing), so multi-hop
        propagation works across any connected mesh."""
        if not ops:
            return 0, []
        # Chaos seam: error fails the page like a poisoned batch (the
        # pull loop's frozen-watermark recovery re-serves it); delay
        # is slow-apply weather — blocking THIS worker thread is the
        # injected symptom (every wire caller runs ingest off-loop).
        f = chaos.hit("sync.ingest.apply", only=("delay", "error"))
        if f is not None:
            chaos.apply_sync(f)
        # Row-format first, indexes second: ingest's LWW compares and
        # tombstone checks are per-(model, record_id) lookups, so any
        # solo-era blob pages explode to rows before the index build
        # covers them (explode before indexing also keeps the explode
        # itself index-maintenance-free on first contact).
        self._ensure_row_oplog()
        self._ensure_sync_indexes()
        for op in ops:
            if op.instance not in self._instance_ids:
                try:
                    self._instance_row_id(op.instance)
                except KeyError:
                    # bounded by distinct unknown relayed instances
                    # (≈0 per page) — not a per-item tx
                    self.register_instance(  # sdlint: ok[tx-shape]
                        op.instance, node_name="(relayed)")
        applied = 0
        errors: List[str] = []
        ts_max: Dict[bytes, int] = {}
        failed: set = set()
        with self.db.write_tx() as conn:
            # Straggler sweep under the write lock: a bulk writer that
            # checked _solo before this pull registered the peer can
            # land one last blob between the explode above and this
            # transaction — the LWW compares below must see those ops
            # as rows. Almost always an empty, one-query no-op.
            for m in self.db.run("sync.blob.metas_sweep", conn=conn):
                self._explode_blob_conn(conn, m)
            for op in ops:
                self.clock.update_with_timestamp(op.timestamp)
                # Poison-op triage BEFORE the try: an op this schema can
                # NEVER apply (unknown model — version skew with a newer
                # peer) must not freeze the watermark, or every future
                # pull from that instance re-serves the same poison page
                # and sync silently stops. But the watermark advancing
                # past it means get_ops will never re-serve it either —
                # so the op is QUARANTINED, not dropped: after a schema
                # upgrade, drain_quarantined_ops re-ingests it.
                reason = self._op_permanently_inapplicable(op)
                if reason is not None:
                    # poison ops are rare (version skew); executemany
                    # would buy nothing and lose the per-op triage
                    self.db.run(  # sdlint: ok[tx-shape]
                        "sync.quarantine.insert",
                        (op.id, op.timestamp, op.pack()), conn=conn)
                    errors.append(
                        f"ingest {op.typ!r}: quarantined: {reason}")
                    if op.instance not in failed:
                        ts_max[op.instance] = max(
                            self.timestamps.get(op.instance, op.timestamp),
                            ts_max.get(op.instance, 0), op.timestamp)
                    continue
                try:
                    if not self._compare_message(op):
                        conn.execute("SAVEPOINT ingest_op")
                        try:
                            self._apply_op_conn(conn, op)
                        except Exception:
                            conn.execute(
                                "ROLLBACK TO SAVEPOINT ingest_op")
                            raise
                        finally:
                            conn.execute("RELEASE SAVEPOINT ingest_op")
                        applied += 1
                except Exception as e:  # noqa: BLE001 — per-op guard
                    # FREEZE this instance's watermark at its last
                    # successfully processed timestamp: a later op from
                    # the same instance in this page would otherwise
                    # advance ts_max past the failure, and get_ops would
                    # never re-serve the failed op (silent divergence).
                    # The frozen watermark makes the next pull re-request
                    # from before the failure; already-applied later ops
                    # are stale on redelivery (_compare_message).
                    errors.append(f"ingest {op.typ!r}: {e}")
                    failed.add(op.instance)
                    continue
                if op.instance in failed:
                    continue
                # watermark moves only past applied-or-stale ops
                ts_max[op.instance] = max(
                    self.timestamps.get(op.instance, op.timestamp),
                    ts_max.get(op.instance, 0), op.timestamp)
            for pub, ts in ts_max.items():
                # one row per PAIRED INSTANCE (2-3), not per item
                self.db.run(  # sdlint: ok[tx-shape]
                    "sync.instances.set_watermark", (ts, pub),
                    conn=conn)
        with self._meta_lock:
            self.timestamps.update(ts_max)
        SYNC_OPS_INGESTED.inc(len(ops))
        SYNC_OPS_APPLIED.inc(applied)
        if errors:
            SYNC_INGEST_ERRORS.inc(len(errors))
        return applied, errors

    # -- clone fast path: receiving side ------------------------------------

    def receive_blob_pages(self, pages: Sequence[dict]
                           ) -> Tuple[int, List[str], int]:
        """Batched ingest of verbatim `shared_op_blob` pages (the clone
        fast path's receiving half). Each page applies in ONE
        transaction — executemany op-log inserts, executemany domain
        writes grouped by value-shape, and a deferred FK-resolution
        pass (FK pub_ids resolve via subselect AFTER all of the page's
        rows are seeded) — skipping per-op _compare_message entirely,
        because eligibility (_clone_fast_eligible) PROVES the LWW
        compare is a no-op: every incoming timestamp is newer than
        every logged op and no tombstones exist. The moment a page
        fails that proof (local writes during the clone, deletes in
        the log, non-uniform payloads, redelivery) it falls back to
        the per-op receive_crdt_operations path — identical final
        state, just slower. Returns (applied, errors, fast_pages)."""
        applied = 0
        errors: List[str] = []
        fast_pages = 0
        for page in pages:
            # one tx per PAGE is the protocol's ack/watermark unit
            a, errs, fast = self._receive_blob_page(page)  # sdlint: ok[tx-shape]
            applied += a
            errors.extend(errs)
            fast_pages += 1 if fast else 0
            SYNC_BLOB_PAGES_APPLIED.labels(
                path="fast" if fast else "fallback").inc()
        return applied, errors, fast_pages

    def _receive_blob_page(self, page: dict) -> Tuple[int, List[str], bool]:
        model = page["model"]
        pub = bytes(page["instance"])
        rows = opblob.decode_apply_rows(page["data"])
        if not rows:
            return 0, [], False
        if pub not in self._instance_ids:
            try:
                self._instance_row_id(pub)
            except KeyError:
                self.register_instance(pub, node_name="(relayed)")
        if self._clone_fast_eligible(model, rows):
            try:
                self._apply_page_fast(model, pub, rows)
                return len(rows), [], True
            except Exception as e:  # noqa: BLE001 — tx rolled back whole
                # The per-op path re-decides op by op (savepoints,
                # quarantine, watermark freeze) — never lose a page to
                # a fast-path surprise.
                errors = [f"clone fast apply {model}: {e}; "
                          f"falling back per-op"]
                applied, errs = self._receive_page_per_op(model, pub, rows)
                return applied, errors + errs, False
        applied, errs = self._receive_page_per_op(model, pub, rows)
        return applied, errs, False

    def _receive_page_per_op(self, model: str, pub: bytes,
                             rows: Sequence[tuple]
                             ) -> Tuple[int, List[str]]:
        ops = [self._entry_to_op(model, ts, rid, payload, pub)
               for ts, rid, _kind, payload, _vp, _u in rows]
        return self.receive_crdt_operations(ops)

    def _clone_fast_eligible(self, model: str,
                             rows: Sequence[tuple]) -> bool:
        """True when applying this page without per-op LWW compare is
        provably identical to the per-op path: known shared model, only
        uniform create/multi-update entries, strictly ascending
        timestamps all newer than every logged op, no shared delete
        tombstones, and no record touched twice (grouped executemany
        statements preserve order only within one group)."""
        mdef = M.MODELS.get(model)
        if mdef is None or mdef.sync != M.SyncMode.SHARED:
            return False  # per-op path quarantines version skew properly
        hi, tombstones = self._op_log_state()
        if tombstones:
            return False
        prev = hi
        seen = set()
        for ts, rid, kind, _payload, values_packed, _update in rows:
            if values_packed is None:
                return False  # not a uniform bulk payload
            if kind != OpKind.CREATE and not kind.startswith("u:"):
                return False
            if ts <= prev:
                return False
            prev = ts
            if rid in seen:
                return False
            seen.add(rid)
        return True

    @staticmethod
    def _rid_bytes(rid_packed: bytes) -> Any:
        """Unpack a blob entry's packed record id (bin8(16) fast path —
        the only shape bulk writers emit)."""
        if len(rid_packed) == 18 and rid_packed[:2] == b"\xc4\x10":
            return rid_packed[2:]
        return unpack_value(rid_packed)

    def _apply_page_fast(self, model: str, pub: bytes,
                         rows: Sequence[tuple]) -> None:
        """One page → one transaction of executemany writes. Mirrors
        _apply_shared's create/multi-update semantics exactly, minus
        the compare/supersede probes eligibility already proved moot."""
        mdef = M.MODELS[model]
        sync_col = mdef.sync_id[0]
        remote_id = self._instance_row_id(pub)
        max_ts = rows[-1][0]
        attributable = any(f.name == "instance_id" for f in mdef.fields)
        # (is_create, sorted value keys) → [(record_id, values)];
        # insertion-ordered, and no record repeats across groups
        # (eligibility), so cross-group execution order is free.
        groups: Dict[Tuple[bool, Tuple[str, ...]], List[Tuple[Any, dict]]] \
            = {}
        oplog_rows = []
        any_create = False
        for ts, rid_packed, kind, payload, values_packed, _update in rows:
            oplog_rows.append(
                (ts, model, rid_packed, kind, payload, remote_id))
            is_create = kind == OpKind.CREATE
            any_create = any_create or is_create
            values = unpack_value(values_packed) or {}
            key = (is_create, tuple(sorted(values)))
            groups.setdefault(key, []).append(
                (self._rid_bytes(rid_packed), values))
        with self.db.write_tx() as conn:
            self.db.run_many("sync.oplog.insert_shared", oplog_rows,
                             conn=conn)
            for (is_create, keys), recs in groups.items():
                self._apply_group_fast(conn, mdef, sync_col, remote_id,
                                       is_create and attributable,
                                       keys, recs)
            if any_create and self.db.run(
                    "sync.pending.any", conn=conn) is not None:
                # parity with _apply_op_conn: creates may materialize
                # rows parked relation ops were waiting for
                self._drain_pending_relations(conn)
            new_wm = max(self.timestamps.get(pub, 0), max_ts)
            self.db.run("sync.instances.set_watermark", (new_wm, pub),
                        conn=conn)
        with self._meta_lock:
            self.timestamps[pub] = new_wm
        self.clock.update_with_timestamp(max_ts)
        self._note_ops_logged(max_ts, False)

    def _apply_group_fast(self, conn, mdef, sync_col: str, remote_id: int,
                          attribute: bool, keys: Tuple[str, ...],
                          recs: List[Tuple[Any, dict]]) -> None:
        """Domain writes for one (kind-class, value-shape) group:
        executemany row seeding, then one executemany per field — FK
        fields resolve pub_id → local id via a scalar subselect (the
        deferred resolution pass; referenced rows seeded by earlier
        statements of this page resolve, absent ones write NULL exactly
        like _resolve_fk). The f-strings interpolate registry-derived
        identifiers only and bind the declared store.helper.* /
        sync.apply.* shapes."""
        table = mdef.name
        if attribute:
            conn.executemany(
                f"INSERT OR IGNORE INTO {table} ({sync_col}, instance_id) "
                f"VALUES (?, ?)", [(r, remote_id) for r, _ in recs])
            conn.executemany(
                f"UPDATE {table} SET instance_id = ? WHERE {sync_col} = ? "
                f"AND instance_id IS NULL",
                [(remote_id, r) for r, _ in recs])
        else:
            conn.executemany(
                f"INSERT OR IGNORE INTO {table} ({sync_col}) VALUES (?)",
                [(r,) for r, _ in recs])
        for name in keys:
            try:
                f = mdef.field(name)  # registry guard before SQL
            except KeyError:
                continue  # newer peer's field this schema lacks — skip
            target = _fk_target(f)
            if target is not None and \
                    M.MODELS[target].sync == M.SyncMode.SHARED:
                conn.executemany(
                    f"UPDATE {table} SET {name} = "
                    f"(SELECT id FROM {target} WHERE pub_id = ?) "
                    f"WHERE {sync_col} = ?",
                    [(vals[name], r) for r, vals in recs])
            else:
                conn.executemany(
                    f"UPDATE {table} SET {name} = ? WHERE {sync_col} = ?",
                    [(vals[name], r) for r, vals in recs])

    def drain_quarantined_ops(self) -> int:
        """Re-ingest ops a previous (older) schema quarantined as
        unknown-model. Called at manager init: after an upgrade the
        registry knows the model and the ops apply; still-unknown ones
        stay quarantined for the next upgrade. Returns drained count."""
        rows = self.db.run("sync.quarantine.all")
        drained = 0
        for row in rows:
            op = CRDTOperation.unpack(row["data"])
            if self._op_permanently_inapplicable(op) is not None:
                continue
            # init-time drain of an almost-always-empty table: each
            # op re-decides through the full ingest machinery
            _, errs = self.receive_crdt_operations([op])  # sdlint: ok[tx-shape]
            if not errs:
                self.db.run_tx(  # sdlint: ok[tx-shape]
                    "sync.quarantine.delete", (row["id"],))
                drained += 1
        return drained

    def _op_permanently_inapplicable(self, op: CRDTOperation
                                     ) -> Optional[str]:
        """Reason string when no retry can EVER apply this op here:
        the model/relation is absent from this node's registry or has
        the wrong sync mode (version skew with a newer peer). Unknown
        FIELDS on a known model are not poison — the apply paths skip
        them (additive-migration tolerance). Conservative: anything
        else returns None and failures stay transient (freeze+retry)."""
        t = op.typ
        if isinstance(t, SharedOp):
            model = M.MODELS.get(t.model)
            if model is None:
                return f"unknown model {t.model!r}"
            if model.sync != M.SyncMode.SHARED:
                return f"model {t.model!r} is not shared-synced"
        else:
            model = M.MODELS.get(t.relation)
            if model is None:
                return f"unknown relation {t.relation!r}"
            if model.sync != M.SyncMode.RELATION or not model.relation:
                return f"model {t.relation!r} is not relation-synced"
        return None

    def _compare_message(self, op: CRDTOperation) -> bool:
        """LWW check: is there an op in the log at or after this one for
        the same (model, record, kind)? (ingest.rs:188-233). Unlike the
        reference — which re-applies identical-timestamp ops idempotently —
        an exact-timestamp hit also counts as old, so redelivered pages
        don't duplicate op-log rows.

        Update kinds ("u:<field>" and multi "u:a+b") compare by FIELD
        COVERAGE, not exact kind: the op is old iff every field it
        carries is covered by same-or-newer update ops on the record —
        so a newer multi-update supersedes a stale single-field op and
        vice versa. The (model, record_id) lazy index narrows the scan
        to one record's ops.

        Deletes are REMOVE-WINS: a 'd' tombstone in the log makes every
        non-delete op on that record stale regardless of timestamps.
        Without this the outcome depended on ARRIVAL order — a node
        that applied delete-then-update resurrected the row (seed_row
        upsert) while one that applied update-then-delete kept it dead
        — permanent divergence, found by the 3-node fuzz harness.
        Remove-wins is safe because pub_ids are unique mints, never
        reused after a delete."""
        t = op.typ
        if isinstance(t, SharedOp):
            if not t.delete:
                row = self.db.run(
                    "sync.lww.shared_tombstone",
                    (t.model, pack_value(t.record_id)))
                if row is not None:
                    return True  # tombstoned — remove-wins
            kind = t.kind
            if kind.startswith("u:"):
                fields = set(OpKind.update_fields(kind))
                covered: set = set()
                for row in self.db.run(
                        "sync.lww.shared_update_coverage",
                        (t.model, pack_value(t.record_id), op.timestamp)):
                    covered.update(OpKind.update_fields(row["kind"]))
                return fields <= covered
            row = self.db.run(
                "sync.lww.shared_same_kind",
                (op.timestamp, t.model, pack_value(t.record_id), t.kind))
        else:
            # Unlike ingest.rs:209-224 (item-only), group_id participates:
            # ops on different groups of one item are independent records.
            # Existence of a link is LWW between 'c' and 'd' BY
            # TIMESTAMP, independent of arrival order (the shared-op
            # remove-wins fix, mirrored — but timestamp-aware, because
            # unlike pub_ids a relation pair IS legitimately
            # re-creatable by a later re-assign):
            #  - any op is stale under a same-or-newer delete;
            #  - a delete is also stale under a STRICTLY newer create
            #    (re-assign after delete revives the link);
            #  - same-kind same-or-newer ops dedup redelivery, as ever.
            key = (t.relation, pack_value(t.item_id),
                   pack_value(t.group_id))
            if t.delete:
                row = self.db.run(
                    "sync.lww.relation_delete_check",
                    key + (op.timestamp, op.timestamp))
            else:
                row = self.db.run(
                    "sync.lww.relation_nondelete_check",
                    key + (op.timestamp, t.kind))
        return row is not None

    # -- generic ModelSyncData apply ---------------------------------------

    def _resolve_fk(self, conn, table: str, pub_id: Any) -> Optional[int]:
        if pub_id is None:
            return None
        # binds the declared sync.fk.resolve shape (registry table)
        row = conn.execute(
            f"SELECT id FROM {table} WHERE pub_id = ?", (pub_id,)).fetchone()
        return row["id"] if row else None

    def _apply_op_conn(self, conn, op: CRDTOperation) -> None:
        """Apply a remote op to the domain tables + insert it into the
        op log, on the caller's open transaction (apply_op,
        ingest.rs:162-186; the batched ingest wraps a savepoint per op).

        A relation op whose referenced rows haven't arrived yet is parked
        in pending_relation_op (NOT the op log — a logged op would make
        _compare_message treat any redelivery as stale forever) and
        drained once a later shared create materializes the rows."""
        t = op.typ
        remote_id = self._instance_row_id(op.instance, conn)
        if isinstance(t, SharedOp):
            self._apply_shared(conn, t, remote_id, op.timestamp)
            self._insert_op_row(conn, op, remote_id)
            if t.field is None and not t.delete and not t.update:
                self._drain_pending_relations(conn)
        else:
            if self._apply_relation(conn, t, op.timestamp):
                self._insert_op_row(conn, op, remote_id)
            elif self._relation_target_tombstoned(conn, t):
                # The referenced record was DELETED (op-log tombstone)
                # and pub_ids are unique mints — the row can never
                # materialize, so parking would sit in
                # pending_relation_op forever (the arrival order the
                # delete-time purge cannot cover). Drop: the delete
                # already won LWW.
                pass
            else:
                rmodel = M.MODELS[t.relation]
                item_f, group_f = rmodel.relation
                # Dedup on op_id: the frozen watermark re-serves this
                # op on every retry pull until the page's failing op
                # clears — without dedup each redelivery would park
                # another copy and drain would log N duplicates.
                # WHERE NOT EXISTS, not a UNIQUE constraint: op_id was
                # ALTERed into pre-existing tables, where SQLite can't
                # add uniqueness.
                self.db.run(
                    "sync.pending.park",
                    (op.id, op.timestamp, op.pack(),
                     _fk_target(rmodel.field(item_f)),
                     pack_value(t.item_id),
                     _fk_target(rmodel.field(group_f)),
                     pack_value(t.group_id), op.id), conn=conn)

    def _drain_pending_relations(self, conn) -> None:
        """Retry parked relation ops; applied ones graduate to the op
        log (keeping LWW bookkeeping consistent)."""
        rows = self.db.run("sync.pending.all", conn=conn)
        for row in rows:
            op = CRDTOperation.unpack(row["data"])
            t = op.typ
            if not isinstance(t, RelationOp):
                self.db.run("sync.pending.delete", (row["id"],),
                            conn=conn)
                continue
            if self._apply_relation(conn, t, op.timestamp):
                remote_id = self._instance_row_id(op.instance, conn)
                self._insert_op_row(conn, op, remote_id)
                self.db.run("sync.pending.delete", (row["id"],),
                            conn=conn)
            elif self._relation_target_tombstoned(conn, t):
                self.db.run("sync.pending.delete", (row["id"],),
                            conn=conn)

    def _relation_target_tombstoned(self, conn, t: RelationOp) -> bool:
        """True when either record a relation op references has a
        delete ('d') tombstone in the shared op log — it can never be
        re-created (pub_ids are unique mints), so the op is dead."""
        model = M.MODELS[t.relation]
        item_f, group_f = model.relation
        for rid, tbl in ((t.item_id, _fk_target(model.field(item_f))),
                         (t.group_id, _fk_target(model.field(group_f)))):
            if tbl is None:
                continue
            row = self.db.run("sync.lww.shared_tombstone",
                              (tbl, pack_value(rid)), conn=conn)
            if row is not None:
                return True
        return False

    def _superseding_update_fields(self, conn, t: SharedOp,
                                   ts: Optional[int]) -> set:
        """Fields of this record with per-field updates NEWER than ts —
        the create op's batched values must not clobber them. ONE query
        per create (the in-order common case returns the empty set)."""
        if ts is None:
            return set()
        rows = self.db.run(
            "sync.lww.superseding_updates",
            (t.model, pack_value(t.record_id), ts), conn=conn)
        out: set = set()
        for row in rows:
            out.update(OpKind.update_fields(row["kind"]))
        return out

    def _apply_shared(self, conn, t: SharedOp,
                      origin_instance_row: Optional[int] = None,
                      ts: Optional[int] = None) -> None:
        model = M.MODELS[t.model]
        assert model.sync == M.SyncMode.SHARED, t.model
        sync_col = model.sync_id[0]
        if t.delete:
            # Cascade EVERY local FK referencing the doomed row FIRST:
            # the emitting peer only minted relation-delete ops for
            # assignments in ITS db (api tags.delete), so a
            # concurrently-created, not-yet-synced assignment on THIS
            # peer — or a purely local reference like file_path.object_id
            # or object_in_album — would fail the row delete on FK
            # violation, and the op would never succeed on any retry
            # (permanent divergence). Policy: nullable FK columns are
            # SET NULL, non-nullable referencing rows are deleted. The
            # row delete wins LWW over any concurrent assignment anyway,
            # so this is the converged state.
            local = self._resolve_fk(conn, t.model, t.record_id)
            if local is not None:
                # (FKs with a declared ON DELETE are skipped inside —
                # the DDL cascade fires on the row delete below, and a
                # manual SET NULL would DETACH rows the DDL cascade is
                # about to delete, e.g. file_path.location_id.)
                cascade_local_fks(conn, t.model, local)
            # Purge parked relation ops referencing the deleted record:
            # their referenced row can never materialize again (pub_ids
            # are unique mints), so they would sit in pending_relation_op
            # forever and tax every future drain scan. One indexed
            # DELETE via the denormalized ref columns; rows parked by an
            # older schema (NULL refs) are caught by the drain-time
            # tombstone check instead.
            key = pack_value(t.record_id)
            self.db.run("sync.pending.purge_refs",
                        (t.model, key, t.model, key), conn=conn)
            conn.execute(
                f"DELETE FROM {t.model} WHERE {sync_col} = ?", (t.record_id,))
            return

        # The f-strings below interpolate registry-guarded identifiers
        # only and bind the declared store.helper.* / sync.apply.*
        # shapes (runtime-matched by the SQL auditor).
        def write_field(name: str, raw_value: Any) -> None:
            try:
                f = model.field(name)  # registry guard before SQL
            except KeyError:
                return  # newer peer's field this schema lacks — skip
            value = raw_value
            target = _fk_target(f)
            if target is not None and \
                    M.MODELS[target].sync == M.SyncMode.SHARED:
                value = self._resolve_fk(conn, target, value)
            conn.execute(
                f"UPDATE {t.model} SET {name} = ? WHERE {sync_col} = ?",
                (value, t.record_id))
        def seed_row(attribute: bool) -> None:
            # Owner attribution: a remotely-CREATED row carries the
            # creating instance in its local-only instance_id (the
            # reference's instance ownership checks; files-over-p2p
            # locality decisions key off this). Updates may be written by
            # any peer, so the update-upsert path seeds unattributed and
            # the create op — whenever it arrives — backfills the NULL.
            attribute = attribute and origin_instance_row is not None and \
                any(f.name == "instance_id" for f in model.fields)
            if attribute:
                conn.execute(
                    f"INSERT OR IGNORE INTO {t.model} "
                    f"({sync_col}, instance_id) VALUES (?, ?)",
                    (t.record_id, origin_instance_row))
                conn.execute(
                    f"UPDATE {t.model} SET instance_id = ? "
                    f"WHERE {sync_col} = ? AND instance_id IS NULL",
                    (origin_instance_row, t.record_id))
            else:
                conn.execute(
                    f"INSERT OR IGNORE INTO {t.model} ({sync_col}) "
                    f"VALUES (?)", (t.record_id,))

        if t.update:  # multi-field update: per-field LWW on apply
            seed_row(attribute=False)
            superseded = self._superseding_update_fields(conn, t, ts)
            for name, raw in (t.values or {}).items():
                if name not in superseded:
                    write_field(name, raw)
            return
        if t.field is None:  # create (values batched in the one op)
            seed_row(attribute=True)
            superseded = (self._superseding_update_fields(conn, t, ts)
                          if t.values else set())
            for name, raw in (t.values or {}).items():
                if name not in superseded:
                    write_field(name, raw)
            return
        # per-field update: _compare_message already decided LWW vs the
        # op log for this exact kind
        seed_row(attribute=False)
        write_field(t.field, t.value)

    def _relation_field_superseded(self, conn, t: RelationOp, field: str,
                                   ts: Optional[int]) -> bool:
        """Mirror of _create_field_superseded for relation creates."""
        if ts is None:
            return False
        row = self.db.run(
            "sync.lww.relation_superseding",
            (t.relation, pack_value(t.item_id), pack_value(t.group_id),
             OpKind.update(field), ts), conn=conn)
        return row is not None

    def _apply_relation(self, conn, t: RelationOp,
                        ts: Optional[int] = None) -> bool:
        """Returns False when the referenced rows aren't here yet (the
        caller parks the op for later)."""
        model = M.MODELS[t.relation]
        assert model.sync == M.SyncMode.RELATION and model.relation
        item_field, group_field = model.relation
        item_table = _fk_target(model.field(item_field))
        group_table = _fk_target(model.field(group_field))
        item_local = self._resolve_fk(conn, item_table, t.item_id)
        group_local = self._resolve_fk(conn, group_table, t.group_id)
        if item_local is None or group_local is None:
            return False
        # Identifiers inline (not via a shared `where` variable) so each
        # f-string binds its declared sync.apply.relation_* shape.
        if t.delete:
            conn.execute(
                f"DELETE FROM {t.relation} WHERE {item_field} = ? "
                f"AND {group_field} = ?",
                (item_local, group_local))
            return True
        conn.execute(
            f"INSERT OR IGNORE INTO {t.relation} "
            f"({item_field}, {group_field}) VALUES (?, ?)",
            (item_local, group_local))

        def write_field(name: str, raw_value: Any) -> None:
            # Validate the wire-controlled field name against the registry
            # before it reaches SQL (same guard as _apply_shared).
            try:
                f = model.field(name)
            except KeyError:
                return  # newer peer's field this schema lacks — skip
            conn.execute(
                f"UPDATE {t.relation} SET {f.name} = ? "
                f"WHERE {item_field} = ? AND {group_field} = ?",
                (raw_value, item_local, group_local))

        if t.field is not None:
            write_field(t.field, t.value)
        else:
            for name, raw in (t.values or {}).items():
                if not self._relation_field_superseded(conn, t, name, ts):
                    write_field(name, raw)
        return True
