from .hlc import HLC, ntp64_now
from .crdt import (
    CRDTOperation,
    OpKind,
    RelationOp,
    SharedOp,
)
from .manager import GetOpsArgs, SyncManager

__all__ = [
    "HLC", "ntp64_now", "CRDTOperation", "OpKind", "SharedOp",
    "RelationOp", "SyncManager", "GetOpsArgs",
]
