"""Hybrid logical clock over NTP64 timestamps.

The reference uses the `uhlc` crate (HLCBuilder in
/root/reference/core/crates/sync/src/manager.rs:43): timestamps are NTP64
u64 values — upper 32 bits whole seconds since the UNIX epoch, lower 32
bits fractional seconds — made strictly monotonic across local events and
merged with remote timestamps on ingest
(/root/reference/core/crates/sync/src/ingest.rs:113-116).
"""

from __future__ import annotations

import threading
import time


def ntp64_now() -> int:
    """Physical time as NTP64 (seconds<<32 | fraction)."""
    t = time.time()
    secs = int(t)
    frac = int((t - secs) * (1 << 32))
    return (secs << 32) | frac


def ntp64_to_seconds(ts: int) -> float:
    return (ts >> 32) + (ts & 0xFFFFFFFF) / (1 << 32)


class HLC:
    """Strictly monotonic hybrid clock, thread-safe."""

    def __init__(self, last: int = 0):
        self._last = last
        self._lock = threading.Lock()

    def new_timestamp(self) -> int:
        with self._lock:
            now = ntp64_now()
            self._last = now if now > self._last else self._last + 1
            return self._last

    def new_timestamps(self, n: int) -> range:
        """n strictly increasing timestamps under ONE lock acquisition —
        the bulk-writer path (an identifier chunk mints 2-3 ops per file,
        so per-op locking is measurable at 1M files)."""
        with self._lock:
            now = ntp64_now()
            start = now if now > self._last else self._last + 1
            self._last = start + n - 1
            return range(start, start + n)

    def update_with_timestamp(self, remote_ts: int) -> None:
        """Merge a remote timestamp so local events happen-after it."""
        with self._lock:
            if remote_ts > self._last:
                self._last = remote_ts

    @property
    def last(self) -> int:
        with self._lock:
            return self._last
