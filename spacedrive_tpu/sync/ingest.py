"""Ingest actor: the receiving half of the sync plane.

State machine mirroring the reference's ingest Actor
(/root/reference/core/crates/sync/src/ingest.rs:30-108):

    WaitingForNotification → RetrievingMessages → Ingesting → (loop)

On a notification it emits `Request.Messages(timestamps)` upstream (the
p2p responder turns that into a wire GetOperations), waits for a
`MessagesEvent`, ingests each op through the manager's LWW path, and asks
for more pages while `has_more`. Transport is an interface: tests drive it
with plain asyncio queues (the blueprint of the reference's in-process
two-node test, core/crates/sync/tests/lib.rs:102-217).
"""

from __future__ import annotations

import asyncio
import enum
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

from .crdt import CRDTOperation
from .manager import SyncManager


class ReqKind(enum.Enum):
    MESSAGES = "messages"
    INGESTED = "ingested"
    FINISHED = "finished_ingesting"


@dataclass
class Request:
    kind: ReqKind
    timestamps: List[Tuple[bytes, int]] = field(default_factory=list)
    count: int = 1  # INGESTED: ops applied by the batch behind this


@dataclass
class MessagesEvent:
    instance: bytes
    messages: List[CRDTOperation]
    has_more: bool


class Ingester:
    """Owns the notification→retrieve→ingest loop for one library."""

    def __init__(self, sync: SyncManager):
        self.sync = sync
        self.events: asyncio.Queue = asyncio.Queue()
        self.requests: asyncio.Queue = asyncio.Queue()
        self.errors: List[str] = []
        self._task: Optional[asyncio.Task] = None

    # -- inputs ------------------------------------------------------------

    def notify(self) -> None:
        """Event::Notification — a peer has new ops."""
        self.events.put_nowait(("notification", None))

    def deliver(self, event: MessagesEvent) -> None:
        """Event::Messages — a page of ops arrived."""
        self.events.put_nowait(("messages", event))

    # -- actor loop --------------------------------------------------------

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        while True:
            # WaitingForNotification
            await self._wait("notification")
            # RetrievingMessages / Ingesting page loop
            has_more = True
            while has_more:
                # Clocks include OUR OWN instance at the current HLC
                # state: without it, a peer that ingested our backlog
                # would ship our entire log straight back (get_ops
                # returns everything from instances absent from the
                # clock list) just for us to discard it as stale.
                clocks = dict(self.sync.timestamps)
                clocks[self.sync.instance] = max(
                    self.sync.clock.last,
                    clocks.get(self.sync.instance, 0))
                await self.requests.put(Request(
                    ReqKind.MESSAGES, timestamps=list(clocks.items())))
                event = await self._wait("messages")
                # Whole page in ONE worker-thread call and ONE db
                # transaction (a savepoint isolates each op, so one
                # malformed remote op neither kills the actor nor
                # poisons its page) — ~6× the per-op drain rate.
                try:
                    applied, errors = await asyncio.to_thread(
                        self.sync.receive_crdt_operations, event.messages)
                except Exception as e:  # page-level guard
                    # A page-level failure (commit error, disk full)
                    # would repeat forever if we re-requested the same
                    # clocks — ABORT this pull; the next notification
                    # retries from the persisted watermarks.
                    self.errors.append(f"ingest page: {e}")
                    break
                self.errors.extend(errors)
                if applied:
                    await self.requests.put(
                        Request(ReqKind.INGESTED, count=applied))
                has_more = event.has_more
            await self.requests.put(Request(ReqKind.FINISHED))

    async def _wait(self, kind: str):
        """wait! macro semantics (ingest.rs:48,63): drop events of the
        wrong kind while waiting for the expected one."""
        while True:
            k, payload = await self.events.get()
            if k == kind:
                return payload
