"""Ingest actor: the receiving half of the sync plane.

State machine mirroring the reference's ingest Actor
(/root/reference/core/crates/sync/src/ingest.rs:30-108):

    WaitingForNotification → RetrievingMessages → Ingesting → (loop)

On a notification it emits `Request.Messages(timestamps)` upstream (the
p2p responder turns that into a wire GetOperations), waits for a
`MessagesEvent`, ingests each op through the manager's LWW path, and asks
for more pages while `has_more`. Transport is an interface: tests drive it
with plain asyncio queues (the blueprint of the reference's in-process
two-node test, core/crates/sync/tests/lib.rs:102-217).
"""

from __future__ import annotations

import asyncio
import enum
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from .. import channels, chaos, tasks
from ..p2p import wire
from ..telemetry import SYNC_INGEST_PAGES
from ..timeouts import with_timeout
from .crdt import CRDTOperation
from .manager import SyncManager


class ReqKind(enum.Enum):
    MESSAGES = "messages"
    INGESTED = "ingested"
    FINISHED = "finished_ingesting"


@dataclass
class Request:
    kind: ReqKind
    timestamps: List[Tuple[bytes, int]] = field(default_factory=list)
    count: int = 1  # INGESTED: ops applied by the batch behind this


@dataclass
class MessagesEvent:
    instance: bytes
    messages: List[CRDTOperation]
    has_more: bool


def _extend_capped(errors: List[str], errs: List[str]) -> None:
    """Append ingest errors, aging out the oldest past ERRORS_CAP.
    Every writer to an Ingester.errors list — the actor's own
    _note_errors AND the clone fast path, which is handed the raw
    list — must funnel through this, or a multi-million-op clone
    whose pages keep failing grows the failure history unbounded."""
    errors.extend(errs)
    if len(errors) > Ingester.ERRORS_CAP:
        del errors[: len(errors) - Ingester.ERRORS_CAP]


async def pump_clone_stream(sync: SyncManager, recv, send,
                            errors: List[str]) -> Tuple[int, int, int]:
    """Receiver half of the clone fast path's blob phase: drain
    `blob_page` / `clone_ops` frames until `blob_done`, acking each
    applied page with the advanced watermark so the originator's
    windowed sender (N pages in flight) can release the next page.

    `recv`/`send` are the tunnel's async frame callables — tests drive
    this with plain asyncio queues, exactly like the Ingester. Pages go
    through the manager's batched fresh-peer apply
    (receive_blob_pages, which falls back per-op the moment the page
    fails the LWW-no-op proof); interleaved `clone_ops` chunks — the
    row-format ops the originator must deliver BEFORE a page's ack can
    advance the watermark past them — go through the normal per-op
    ingest. Returns (ops_applied, fast_pages, fallback_pages)."""
    applied = 0
    fast_pages = 0
    fallback_pages = 0
    # Frozen-watermark guard: if an op from instance X fails ingest,
    # receive_crdt_operations freezes X's watermark BELOW it so the
    # next pull re-serves it (the per-op path's silent-divergence
    # invariant). This forward-only stream must then stop APPLYING
    # X's later frames entirely — even the per-op fallback would
    # advance the watermark past the failed op, orphaning it forever.
    # `expect` tracks the highest timestamp delivered per instance; a
    # watermark short of it means something froze → the instance goes
    # `dirty` and its remaining frames drain unapplied (acked with the
    # frozen watermark, pure flow control). The next pull re-serves
    # from the frozen point through the per-op loop. Quarantined
    # poison ops advance the watermark by design, so version skew
    # does NOT dirty the stream.
    dirty: set = set()
    expect: dict = {}

    def _frozen(pub: bytes) -> bool:
        return sync.timestamps.get(pub, 0) < expect.get(pub, 0)

    async def _send_ack(pub: bytes, fast: bool) -> None:
        # Chaos seam: a dropped/torn ack leaves the originator's
        # window full until its sync.clone.ack budget fires — the
        # stream dies and the per-op pull loop finishes the tail from
        # the durable watermark this ack would have carried.
        f = chaos.hit("sync.clone.ack",
                      only=("delay", "drop", "disconnect"))
        if f is not None and await chaos.apply_async(f):
            return  # dropped on the wire
        await with_timeout(
            "sync.clone.ack_send",
            send(wire.pack("clone.ack",
                           ts=sync.timestamps.get(pub, 0),
                           fast=bool(fast))))

    while True:
        frame = await with_timeout("sync.clone.frame", recv())
        kind = frame.get("kind") if isinstance(frame, dict) else None
        if kind == "blob_done":
            return applied, fast_pages, fallback_pages
        if kind == "clone_ops":
            frame = wire.unpack("clone.ops", frame)
            ops = [CRDTOperation.from_wire(raw)
                   for raw in frame.get("ops", [])]
            live = [op for op in ops if op.instance not in dirty]
            if live:
                n, errs = await asyncio.to_thread(
                    sync.receive_crdt_operations, live)
                applied += n
                _extend_capped(errors, errs)
                for op in live:
                    expect[op.instance] = max(
                        expect.get(op.instance, 0), op.timestamp)
                for pub in {op.instance for op in live}:
                    if _frozen(pub):
                        dirty.add(pub)
        elif kind == "blob_page":
            frame = wire.unpack("clone.page", frame)
            pub = bytes(frame["instance"])
            if pub in dirty or _frozen(pub):
                dirty.add(pub)
                await _send_ack(pub, False)
                fallback_pages += 1
                continue
            n, errs, fast = await asyncio.to_thread(
                sync.receive_blob_pages, [frame])
            applied += n
            _extend_capped(errors, errs)
            fast_pages += 1 if fast else 0
            fallback_pages += 0 if fast else 1
            expect[pub] = max(expect.get(pub, 0), int(frame["max_ts"]))
            if _frozen(pub):
                dirty.add(pub)
            # Ack AFTER the apply committed: the watermark the ack
            # carries is durable, so a crash mid-stream re-pulls from
            # exactly the right place.
            await _send_ack(pub, fast)
        else:
            # WireError IS a ValueError — pre-registry callers catching
            # the old bare ValueError still catch this.
            raise wire.WireError(
                f"unexpected clone-stream frame: {frame!r}")


class Ingester:
    """Owns the notification→retrieve→ingest loop for one library."""

    # Most recent ingest errors kept for callers (sync_net surfaces
    # them); older ones age out so a long churn stream cannot grow the
    # actor's memory with its failure history.
    ERRORS_CAP = 256

    def __init__(self, sync: SyncManager, owner: str = "sync-ingest"):
        self.sync = sync
        self._owner = owner
        # Bounded channels (channels.py registry): the event inbox
        # coalesces notification pokes by kind; the request outbox is
        # block-policy — its put waits under the sync.ingest.backlog
        # budget when the _pull consumer wedges.
        self.events = channels.channel("sync.ingest.events")
        self.requests = channels.channel("sync.ingest.requests")
        self.errors: List[str] = []
        self._task: Optional[asyncio.Task] = None

    # -- inputs ------------------------------------------------------------

    def notify(self) -> None:
        """Event::Notification — a peer has new ops. A poke storm
        coalesces to one pending notification (the reference's wait!
        drops redundant ones the same way)."""
        self.events.put_nowait(("notification", None), key="notification")

    def deliver(self, event: MessagesEvent) -> None:
        """Event::Messages — a page of ops arrived."""
        self.events.put_nowait(("messages", event))

    def _note_errors(self, errs: List[str]) -> None:
        _extend_capped(self.errors, errs)

    # -- actor loop --------------------------------------------------------

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = tasks.spawn("ingester", self._run(),
                                     owner=self._owner)

    async def stop(self) -> None:
        await tasks.cancel_and_gather(self._task)
        self._task = None

    async def _run(self) -> None:
        while True:
            # WaitingForNotification
            await self._wait("notification")
            # RetrievingMessages / Ingesting page loop
            has_more = True
            while has_more:
                # Clocks include OUR OWN instance at the current HLC
                # state: without it, a peer that ingested our backlog
                # would ship our entire log straight back (get_ops
                # returns everything from instances absent from the
                # clock list) just for us to discard it as stale.
                clocks = dict(self.sync.timestamps)
                clocks[self.sync.instance] = max(
                    self.sync.clock.last,
                    clocks.get(self.sync.instance, 0))
                await self.requests.put(Request(
                    ReqKind.MESSAGES, timestamps=list(clocks.items())))
                event = await self._wait("messages")
                # Whole page in ONE worker-thread call and ONE db
                # transaction (a savepoint isolates each op, so one
                # malformed remote op neither kills the actor nor
                # poisons its page) — ~6× the per-op drain rate.
                SYNC_INGEST_PAGES.inc()
                try:
                    applied, errors = await asyncio.to_thread(
                        self.sync.receive_crdt_operations, event.messages)
                except Exception as e:  # page-level guard
                    # A page-level failure (commit error, disk full)
                    # would repeat forever if we re-requested the same
                    # clocks — ABORT this pull; the next notification
                    # retries from the persisted watermarks.
                    self._note_errors([f"ingest page: {e}"])
                    break
                self._note_errors(errors)
                if applied:
                    await self.requests.put(
                        Request(ReqKind.INGESTED, count=applied))
                has_more = event.has_more
            await self.requests.put(Request(ReqKind.FINISHED))

    async def _wait(self, kind: str):
        """wait! macro semantics (ingest.rs:48,63): drop events of the
        wrong kind while waiting for the expected one."""
        while True:
            k, payload = await self.events.get()
            if k == kind:
                return payload
