"""Windowed clone-stream originator — the serving half of the
full-library clone fast path, extracted from p2p/sync_net.py.

Two reasons this lives crypto-free under sync/ instead of inside
NetworkedLibraries:

- **One protocol, every transport.** The receiver half
  (`sync/ingest.pump_clone_stream`) always was transport-agnostic
  (async `recv`/`send` callables); the originator half was welded to
  the tunnel stack, so crypto-less containers — tier-1, and the
  load harness's stub-transport fleets — could never drive the REAL
  windowed flow control (CLONE_WINDOW in flight, per-page watermark
  acks, drain deadlines). Now both halves speak through the same
  tunnel-shaped duck (`send`/`send_nowait`/`drain`/`recv`/`close`),
  and `tools/load_bench.py` storms it in-process.
- **Fair-share serving.** With many peers cloning concurrently, each
  stream used to requeue its next page fetch the instant an ack
  freed its window — a hot stream (fast acks, warm cache) could
  monopolize the executor and starve slower peers far below their
  fair share (the load harness's starvation gate measures exactly
  this). Page fetches now take a FIFO slot on the declared
  ``sync.clone.serve`` block channel (capacity = concurrent fetches,
  budget ``sync.clone.serve``): waiters are served strictly in
  arrival order, so N streams round-robin the fetch executor and the
  slowest peer's page rate stays a bounded fraction of the mean.

Chaos seam ``sync.clone.page``: every outgoing blob page consults the
armed chaos plane — `disconnect` is the mid-clone torn stream
(reconnect must converge byte-identically from the receiver's durable
watermark, pinned by tests/test_chaos.py), `drop` loses the frame so
the ack window starves against the `sync.clone.ack` budget, `wedge`
parks the stream against the drain/ack budgets, `delay` is link
weather.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from .. import channels, chaos
from ..p2p import wire
from ..telemetry import (
    SYNC_CLONE_PAGES_RELAYED,
    SYNC_CLONE_WINDOW_STALLS,
)
from ..timeouts import with_timeout

__all__ = ["CLONE_WINDOW", "serve_clone_stream", "serve_gate"]

# Clone fast path flow control: pages in flight on the tunnel before
# the originator waits for a watermark ack. The window IS the declared
# p2p.tunnel.frames channel capacity (channels.py; default 4, scaled
# by SDTPU_CHAN_SCALE, snapshotted at import): 4 at the bulk writers'
# 4-16k-op pages keeps a few MB in transport buffers — enough that the
# receiver's batched apply never starves on the wire, bounded enough
# that a slow receiver exerts backpressure instead of ballooning
# originator memory. Tunnel.send_nowait's runtime Window enforces the
# same cap, so a drift between this constant and the registry is a
# chan_overflow violation in tier-1, not silent memory growth.
CLONE_WINDOW = channels.capacity("p2p.tunnel.frames")


def serve_gate() -> channels.Channel:
    """One node's fair-share page-fetch gate (a declared block
    channel; construct once per serving component, share across its
    concurrent clone streams)."""
    return channels.channel("sync.clone.serve")


async def _next_item(stream, gate: Optional[channels.Channel]):
    """The stream's next (kind, item) — fetched off-loop under a FIFO
    slot of the fair-share gate, so concurrent clone streams
    round-robin the fetch executor instead of racing it."""
    if gate is None:
        return await asyncio.to_thread(next, stream, None)
    await gate.put(None)
    try:
        return await asyncio.to_thread(next, stream, None)
    finally:
        gate.get_nowait()


async def serve_clone_stream(sync, tunnel, clocks,
                             gate: Optional[channels.Channel] = None
                             ) -> bool:
    """Stream eligible blob pages (plus the interleaved row-format
    ops that must precede each page's watermark advance) to the
    pulling peer. Window invariant: at most CLONE_WINDOW unacked
    pages in flight; each ack carries the receiver's durably
    committed watermark, so a dropped stream resumes exactly where
    the receiver's instance row says. Returns False (nothing sent)
    when the peer is not a fresh clone target — the caller falls
    through to the per-op page.

    `sync` is the library's SyncManager; `tunnel` is anything
    tunnel-shaped (p2p Tunnel, the load harness's stub transport)."""
    # Generator construction is lazy — the SQL happens inside each
    # next(), which runs off-loop below.
    stream = sync.iter_clone_stream(clocks)  # sdlint: ok[blocking-async]
    started = False
    inflight = 0
    try:
        while True:
            nxt = await _next_item(stream, gate)
            if nxt is None:
                break
            kind, item = nxt
            if not started:
                await with_timeout(
                    "p2p.frame_send",
                    tunnel.send(wire.pack("clone.stream",
                                          window=CLONE_WINDOW)))
                started = True
            if kind == "ops":
                await with_timeout("p2p.frame_send", tunnel.send(
                    wire.pack("clone.ops",
                              ops=[op.to_wire() for op in item])))
                continue
            # Chaos seam: a dropped page starves the ack window (the
            # sync.clone.ack budget notices), a disconnect tears the
            # stream mid-clone, a wedge parks it against the drain
            # budget. The counters let artifacts reconcile the
            # receiver's observed stall with the injected cause.
            f = chaos.hit("sync.clone.page")
            dropped = f is not None and await chaos.apply_async(f)
            if not dropped:
                tunnel.send_nowait(wire.pack("clone.page", **item))
                SYNC_CLONE_PAGES_RELAYED.inc()
            inflight += 1
            if inflight >= CLONE_WINDOW:
                # One backpressure point per window instead of per
                # frame (the point of send_nowait): the window's
                # pages stream into the socket back-to-back, and a
                # slow receiver pauses us here, not mid-window.
                await with_timeout("sync.clone.drain", tunnel.drain())
            while inflight >= CLONE_WINDOW:
                SYNC_CLONE_WINDOW_STALLS.inc()
                # Budgeted per page: the receiver's batched apply
                # commits a whole page behind each ack.
                ack = await with_timeout("sync.clone.ack",
                                         tunnel.recv())
                try:
                    wire.unpack("clone.ack", ack)
                except wire.WireError:
                    raise ConnectionError(
                        f"clone stream: bad ack frame {ack!r}")
                inflight -= 1
        # flush the final partial window
        await with_timeout("sync.clone.drain", tunnel.drain())
        while inflight > 0:
            ack = await with_timeout("sync.clone.ack", tunnel.recv())
            try:
                wire.unpack("clone.ack", ack)
            except wire.WireError:
                raise ConnectionError(
                    f"clone stream: bad ack frame {ack!r}")
            inflight -= 1
    except BaseException:
        tunnel.close()  # mid-stream failure: no clean blob_done exists
        raise
    if started:
        await with_timeout("p2p.frame_send",
                           tunnel.send(wire.pack("clone.done")))
    return started
