"""CRDT operation vocabulary.

Mirrors the reference's `sd-sync` crate types
(/root/reference/crates/sync/src/crdt.rs:25-131): a `CRDTOperation` is
(instance uuid, NTP64 timestamp, op uuid, payload), where the payload is a
Shared op (model + record sync-id + create/update/delete) or a Relation op
(relation name + item/group sync-ids + create/update/delete). Kind strings
are "c", "u:<field>", "d" (crdt.rs:15-22) and index the op log for LWW
comparisons.

Wire/DB encoding is msgpack (the reference uses rmp_serde for DB blobs and
serde_json for record ids; we use msgpack for both — values must be
msgpack-serializable plain data).
"""

from __future__ import annotations

import enum
import os
import threading
import uuid as uuidlib
from dataclasses import dataclass
from typing import Any, Optional, Union

import msgpack


class OpKind:
    CREATE = "c"
    DELETE = "d"

    @staticmethod
    def update(field: str) -> str:
        return f"u:{field}"

    @staticmethod
    def multi_update(fields) -> str:
        """Kind string for a multi-field update op: "u:" + the sorted
        field names joined by "+" (field names cannot contain "+").

        The reference has no such op — its identifier emits one
        single-field update op per written column (three passes,
        /root/reference/core/src/object/file_identifier/mod.rs:144-331).
        Carrying {cas_id, object_id} in ONE op halves the file_path op
        volume on the flagship job while apply stays per-field LWW
        (manager._apply_shared filters each field against newer ops)."""
        return "u:" + "+".join(sorted(fields))

    @staticmethod
    def update_fields(kind: str) -> list:
        """Field names covered by an update kind ("u:a+b" → [a, b])."""
        return kind[2:].split("+") if kind.startswith("u:") else []


def uuid4_bytes() -> bytes:
    """Time-ordered 16-byte id (UUIDv7 layout), cheap single mint.

    Name kept for call-site stability; since round 4 ids are v7-style:
    48-bit ms timestamp + version/variant bits + a 16-bit in-batch
    counter + 58 random bits. Bulk writers insert MILLIONS of these
    into UNIQUE B-trees (file_path/object pub_id and the op ids) —
    v4's uniform randomness made every insert land on a random leaf
    (page churn measured as the dominant db_write cost at 1M files),
    while time-prefixed ids append into a hot right-edge page.
    Uniqueness (58 random bits per ms+counter slot) is what sync
    correctness needs; nothing requires v4.
    """
    return uuid4_bytes_batch(1)[0]


_uuid_state = [0, 0]  # [last_ms, next_counter] — shared across calls
_uuid_lock = threading.Lock()  # ids mint from job threads too


def uuid4_bytes_batch(n: int) -> list:
    """n time-ordered ids from ONE urandom syscall (see uuid4_bytes).

    A 16-bit counter spans b[6] nibble + b[7] + 4 bits of b[8], so
    batches stay STRICTLY ordered up to 65,536 ids — past the largest
    bulk batch (the identifier's 16,384 device step). The counter is
    MODULE state continuing across calls within one millisecond
    (resetting on ms change): two batches minted back-to-back in the
    same ms (object pub_ids then op ids in one identifier chunk) occupy
    disjoint, ordered counter slots instead of colliding at 0. Past
    65,536 ids/ms the counter wraps and uniqueness rests on the 58
    random bits — still 2^58 per slot."""
    if n <= 0:
        return []
    import time as _time

    blob = os.urandom(8 * n)
    with _uuid_lock:
        ms = _time.time_ns() // 1_000_000
        if ms != _uuid_state[0]:
            _uuid_state[0] = ms
            _uuid_state[1] = 0
        base = _uuid_state[1]
        _uuid_state[1] = (base + n) & 0xFFFF
    ts = ms.to_bytes(6, "big")
    if n < 64:  # numpy setup overhead loses on small mints
        out = []
        for i in range(n):
            k = 8 * i
            c = (base + i) & 0xFFFF
            b = bytearray(16)
            b[0:6] = ts
            b[6] = 0x70 | ((c >> 12) & 0x0F)   # version 7 + counter hi
            b[7] = (c >> 4) & 0xFF             # counter mid
            b[8] = 0x80 | ((c & 0x0F) << 2) | (blob[k] & 0x03)  # variant+lo
            b[9:16] = blob[k + 1:k + 8]
            out.append(bytes(b))
        return out
    # Bulk path (identifier/indexer chunks mint 4-16k ids at a time):
    # same byte layout, column-at-a-time. ~0.3 µs/id vs 1.6 scalar —
    # uuid minting was 0.9 s of a 200k identify before this.
    import numpy as np
    rnd = np.frombuffer(blob, dtype=np.uint8).reshape(n, 8)
    c = (base + np.arange(n, dtype=np.uint32)) & 0xFFFF
    b = np.empty((n, 16), dtype=np.uint8)
    b[:, 0:6] = np.frombuffer(ts, dtype=np.uint8)
    b[:, 6] = 0x70 | ((c >> 12) & 0x0F)
    b[:, 7] = (c >> 4) & 0xFF
    b[:, 8] = 0x80 | ((c & 0x0F) << 2) | (rnd[:, 0] & 0x03)
    b[:, 9:16] = rnd[:, 1:8]
    rows = b.tobytes()
    return [rows[i << 4:(i + 1) << 4] for i in range(n)]


def _pack(v: Any) -> bytes:
    return msgpack.packb(v, use_bin_type=True)


def _unpack(b: bytes) -> Any:
    return msgpack.unpackb(b, raw=False, strict_map_key=False)


@dataclass(frozen=True)
class SharedOp:
    model: str                  # model name in the registry
    record_id: Any              # sync id value (e.g. pub_id bytes)
    field: Optional[str] = None  # None+value None = create/delete
    value: Any = None
    delete: bool = False
    # Create ops carry ALL initial values in one op (the reference
    # anticipated this — crdt.rs:94 `Create(BTreeMap)` commented out —
    # but ships per-field updates instead; one op per row is ~9× fewer
    # op-log writes on bulk indexing). Subsequent edits remain per-field
    # LWW updates.
    values: Any = None
    # update=True + values = a MULTI-FIELD update op (kind "u:a+b"):
    # one op row carrying several columns, applied per-field LWW.
    update: bool = False

    @property
    def kind(self) -> str:
        if self.delete:
            return OpKind.DELETE
        if self.field is not None:
            return OpKind.update(self.field)
        if self.update:
            return OpKind.multi_update(self.values or {})
        return OpKind.CREATE


@dataclass(frozen=True)
class RelationOp:
    relation: str               # relation model name
    item_id: Any                # item sync id
    group_id: Any               # group sync id
    field: Optional[str] = None
    value: Any = None
    delete: bool = False
    values: Any = None          # create ops: all extra columns at once

    @property
    def kind(self) -> str:
        if self.delete:
            return OpKind.DELETE
        if self.field is not None:
            return OpKind.update(self.field)
        return OpKind.CREATE


@dataclass(frozen=True)
class CRDTOperation:
    instance: bytes             # instance pub_id (16 bytes)
    timestamp: int              # NTP64
    id: bytes                   # op uuid bytes
    typ: Union[SharedOp, RelationOp]

    @classmethod
    def new(cls, instance: bytes, timestamp: int,
            typ: Union[SharedOp, RelationOp]) -> "CRDTOperation":
        return cls(instance, timestamp, uuid4_bytes(), typ)

    # -- wire encoding -----------------------------------------------------

    def to_wire(self) -> dict:
        t = self.typ
        base = {
            "instance": self.instance,
            "timestamp": self.timestamp,
            "id": self.id,
        }
        if isinstance(t, SharedOp):
            base["shared"] = {
                "model": t.model, "record_id": t.record_id,
                "field": t.field, "value": t.value, "delete": t.delete,
                "values": t.values,
            }
            if t.update:  # key only present on multi-field updates
                base["shared"]["update"] = True
        else:
            base["relation"] = {
                "relation": t.relation, "item_id": t.item_id,
                "group_id": t.group_id, "field": t.field,
                "value": t.value, "delete": t.delete,
                "values": t.values,
            }
        return base

    @classmethod
    def from_wire(cls, raw: dict) -> "CRDTOperation":
        if "shared" in raw:
            s = raw["shared"]
            typ: Union[SharedOp, RelationOp] = SharedOp(
                s["model"], s["record_id"], s["field"], s["value"],
                s["delete"], s.get("values"), bool(s.get("update")),
            )
        else:
            r = raw["relation"]
            typ = RelationOp(
                r["relation"], r["item_id"], r["group_id"], r["field"],
                r["value"], r["delete"], r.get("values"),
            )
        return cls(raw["instance"], raw["timestamp"], raw["id"], typ)

    def pack(self) -> bytes:
        return _pack(self.to_wire())

    @classmethod
    def unpack(cls, blob: bytes) -> "CRDTOperation":
        return cls.from_wire(_unpack(blob))


def op_payload(field: Optional[str], value: Any, delete: bool,
               op_id: bytes, values: Any, update: bool = False) -> dict:
    """The op-log `data` blob's dict, in its one canonical key order.

    Every writer of shared/relation_operation.data MUST build the dict
    here — _compare_message dedup and backup replay rely on byte-equal
    packing between the dataclass path and the bulk fast path."""
    d = {"field": field, "value": value, "delete": delete,
         "op_id": op_id, "values": values}
    if update:  # key only present on multi-field update ops
        d["update"] = True
    return d


def pack_value(v: Any) -> bytes:
    return _pack(v)


def unpack_value(b: bytes) -> Any:
    return _unpack(b)
