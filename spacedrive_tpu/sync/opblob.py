"""Page-level op-log blob codec (the shared_op_blob `data` format).

A bulk writer's whole chunk of shared ops — the identifier's ~4k
"u:cas_id+object_id" links, the indexer's 1000-row create batches —
lands in ONE `shared_op_blob` row instead of one `shared_operation`
row per op. The 1M identify spent 16.7 s encoding + inserting ~1.9M op
rows against 15.7 s of hashing (README phase_ms); the blob format cuts
the SQLite side of that to a handful of inserts per chunk and hands
the msgpack side to the native C++ plane.

Format: `data` is a standard msgpack array of per-op entries

    [timestamp(uint), record_id(bin, msgpack-packed sync id),
     kind(str), payload(bin)]

where `payload` is BYTE-IDENTICAL to what the same op's
`shared_operation.data` column would hold (the canonical op_payload
dict packing, sync/crdt.py). That identity is the whole contract:
exploding a blob into rows (SyncManager._ensure_row_oplog) or serving
it through get_ops yields exactly the ops the row format would have
produced, so LWW compare, dedup, and backup replay never see a second
encoding. Plain msgpack framing keeps the blob readable by any
msgpack decoder; entry boundaries are self-delimiting, so per-op
"offsets" are implicit in the framing.

Two encoders produce the same bytes:
- `sd_encode_ops` in native/sdio.cpp — one C call for a whole chunk
  (timestamps/record ids/op ids as dense arrays, values as a packed
  buffer + offsets);
- the pure-Python fragment path below — the tested fallback when the
  native plane is absent (and the oracle the native output is
  byte-compared against in tests/test_sync_blob.py).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import msgpack

# Pre-encoded msgpack fragments of op_payload's canonical key order for
# the two field-is-None shapes bulk writers emit (create: 5-key map;
# multi-field update: 6-key map with trailing update=True). Any change
# to op_payload's dict layout MUST change these AND the mirrored C
# constants in native/sdio.cpp sd_encode_ops — the byte-equality tests
# between the bulk, blob, and dataclass op paths are the guard.
BULK_HDR5 = b"\x85\xa5field\xc0\xa5value\xc0\xa6delete\xc2"
BULK_HDR6 = b"\x86\xa5field\xc0\xa5value\xc0\xa6delete\xc2"
BULK_OPID = b"\xa5op_id\xc4\x10"
BULK_VALUES = b"\xa6values"
BULK_UPDATE_T = b"\xa6update\xc3"


def pack_bulk_payload(kind: str, op_id: bytes, values_packed: bytes) -> bytes:
    """One op's `data` payload from pre-packed values — the fragment
    fast path for the field-is-None shapes (byte-equal to
    pack_value(op_payload(...)))."""
    if kind.startswith("u:"):
        return (BULK_HDR6 + BULK_OPID + op_id
                + BULK_VALUES + values_packed + BULK_UPDATE_T)
    return BULK_HDR5 + BULK_OPID + op_id + BULK_VALUES + values_packed


def encode_entries(entries: Sequence[Sequence[Any]]) -> bytes:
    """Pack [[ts, record_id_packed, kind, payload], ...] into the blob
    bytes. Plain msgpack — the reference encoder the native path must
    byte-match."""
    return msgpack.packb(list(entries), use_bin_type=True)


def decode_entries(data: bytes) -> List[list]:
    """Blob bytes → [[ts, record_id_packed, kind, payload], ...].

    Dispatches to the native batched decoder (sd_decode_ops) when the
    C++ plane is loaded; the pure-Python path below is the fallback and
    the byte-parity oracle (tests/test_sync_blob.py), and also catches
    malformed pages the strict native parser refuses."""
    rows = _decode_native(data)
    if rows is not None:
        return rows
    return decode_entries_py(data)


def decode_entries_py(data: bytes) -> List[list]:
    """Pure-Python blob decode — the reference the native decoder must
    match entry-for-entry."""
    return msgpack.unpackb(data, raw=False, use_list=True)


def iter_entries(data: bytes):
    """Lazily yield [ts, record_id_packed, kind, payload] entries.

    The count-bounded get_ops read path uses this instead of
    decode_entries so serving a 1000-op page out of a 2M-op blob
    backlog never decodes (or materializes) entries past the requested
    window — the consumer just stops iterating."""
    u = msgpack.Unpacker(raw=False, use_list=True)
    u.feed(data)
    for _ in range(u.read_array_header()):
        yield u.unpack()


def _decode_native(data: bytes, with_values: bool = False
                   ) -> Optional[list]:
    """One shared materialization of sd_decode_ops' offset arrays:
    [ts, rid, kind, payload] entry lists (decode_entries form), or —
    with_values — the decode_apply_rows tuples carrying the located
    values slice + uniform-update flag. None when the plane is absent
    or refuses the bytes (callers fall back to the Python decoder)."""
    from .. import native

    if not native.available():
        return None
    try:
        (n, ts, rid_off, rid_len, kind_off, kind_len, payload_off,
         payload_len, _oo, values_off, values_len,
         flags) = native.decode_ops(data)
    except ValueError:
        return None
    out: list = []
    kinds: dict = {}  # pages are uniform-kind: decode each kind once
    for i in range(n):
        kb = data[int(kind_off[i]):int(kind_off[i]) + int(kind_len[i])]
        kind = kinds.get(kb)
        if kind is None:
            kind = kinds[kb] = kb.decode("utf-8")
        ro, po = int(rid_off[i]), int(payload_off[i])
        e_ts = int(ts[i])
        rid = data[ro:ro + int(rid_len[i])]
        payload = data[po:po + int(payload_len[i])]
        if with_values:
            f = int(flags[i])
            vo, vl = int(values_off[i]), int(values_len[i])
            out.append((e_ts, rid, kind, payload,
                        data[vo:vo + vl] if f & 1 else None,
                        bool(f & 2)))
        else:
            out.append([e_ts, rid, kind, payload])
    return out


def decode_apply_rows(data: bytes) -> List[tuple]:
    """Blob bytes → (ts, rid_packed, kind, payload, values_packed,
    update) rows for the batched fresh-peer apply.

    `values_packed` is the payload's packed `values` map located WITHOUT
    decoding the payload's outer dict — via the native decoder's offset
    arrays, or the same fragment arithmetic in Python (the payloads were
    built by concatenating those very fragments). Entries whose payload
    is not a uniform bulk shape get values_packed=None, which routes the
    caller to its per-op fallback."""
    rows = _decode_native(data, with_values=True)
    if rows is not None:
        return rows
    return [_apply_row_py(e) for e in decode_entries_py(data)]


_OPID_AT = len(BULK_HDR5)
_RID_AT = _OPID_AT + len(BULK_OPID)
_VALUES_AT = _RID_AT + 16
_VALUES_END = _VALUES_AT + len(BULK_VALUES)


def _apply_row_py(entry) -> tuple:
    """One decoded entry → decode_apply_rows tuple (Python fallback;
    the same fragment checks as the native uniform-shape probe, and
    the same outputs: the update flag is set only when the FULL
    uniform probe succeeds, matching the native flags bit1)."""
    ts, rid, kind, payload = entry
    hdr6 = payload.startswith(BULK_HDR6)
    values: Optional[bytes] = None
    if (hdr6 or payload.startswith(BULK_HDR5)) and \
            payload[_OPID_AT:_RID_AT] == BULK_OPID and \
            payload[_VALUES_AT:_VALUES_END] == BULK_VALUES:
        if hdr6:
            if payload.endswith(BULK_UPDATE_T):
                values = payload[_VALUES_END:-len(BULK_UPDATE_T)] or None
        else:
            values = payload[_VALUES_END:] or None
    return (ts, rid, kind, payload, values,
            hdr6 and values is not None)


def encode_uniform(timestamps: Sequence[int], record_ids: Sequence[bytes],
                   kind: str, op_ids: Sequence[bytes],
                   values_packed: Sequence[bytes]) -> bytes:
    """Encode a uniform-kind chunk (every record id a 16-byte pub id,
    every op a field-is-None create or multi-update) — the shape both
    bulk writers emit. Dispatches to the native C++ encoder when the
    plane is loaded; the Python fragment path is the fallback and the
    byte-parity oracle."""
    blob = _encode_uniform_native(
        timestamps, record_ids, kind, op_ids, values_packed)
    if blob is not None:
        return blob
    return encode_uniform_py(timestamps, record_ids, kind, op_ids,
                             values_packed)


def encode_uniform_py(timestamps: Sequence[int],
                      record_ids: Sequence[bytes], kind: str,
                      op_ids: Sequence[bytes],
                      values_packed: Sequence[bytes]) -> bytes:
    """Pure-Python encoder for the uniform chunk shape (see
    encode_uniform). record_ids are RAW 16-byte pub ids — packed here
    with the bin8(16) fragment, exactly like the bulk row path."""
    entries = [
        [ts, b"\xc4\x10" + rid, kind, pack_bulk_payload(kind, oid, vp)]
        for ts, rid, oid, vp in zip(timestamps, record_ids, op_ids,
                                    values_packed)
    ]
    return encode_entries(entries)


def _encode_uniform_native(timestamps, record_ids, kind, op_ids,
                           values_packed) -> Optional[bytes]:
    from .. import native

    if not native.available():
        return None
    return native.encode_ops(timestamps, record_ids, kind, op_ids,
                             values_packed)
